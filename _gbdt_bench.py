"""Higgs-1M-shaped GBDT training throughput on the TPU (BASELINE.md config:
LightGBM Higgs-1M, 100 iterations, binary)."""
import time, json
import numpy as np

def main():
    import jax
    from synapseml_tpu.gbdt.booster import train_booster
    print("platform:", jax.devices()[0].platform, flush=True)
    rng = np.random.default_rng(0)
    N, F = 1_000_000, 28
    X = rng.normal(size=(N, F)).astype(np.float32)
    w = rng.normal(size=F); w[F//2:] = 0
    logits = X @ w * 0.5 + rng.normal(size=N) * 0.5
    y = (logits > 0).astype(np.float32)
    t0 = time.perf_counter()
    booster = train_booster(X, y, objective="binary", num_iterations=100,
                            learning_rate=0.1, num_leaves=31, max_bin=255)
    train_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    p = booster.predict(X[:100_000])
    pred_s = time.perf_counter() - t0
    auc_y, auc_p = y[:100_000], np.asarray(p).ravel()
    order = np.argsort(auc_p)
    ranks = np.empty(len(order)); ranks[order] = np.arange(1, len(order)+1)
    n1 = auc_y.sum(); n0 = len(auc_y) - n1
    auc = (ranks[auc_y == 1].sum() - n1*(n1+1)/2) / (n1*n0)
    print(json.dumps({"train_s": round(train_s, 2), "pred_100k_s": round(pred_s, 3),
                      "auc": round(float(auc), 4),
                      "rows_per_sec": round(N*100/train_s)}))
main()
