# %% [markdown]
# # Time-series anomaly detection services
# The Anomaly Detector family (reference: `services/anomaly/
# AnomalyDetection.scala`): `DetectLastAnomaly` scores the newest point
# given its history, `DetectAnomalies` scores a whole series, and
# `SimpleDetectAnomalies` does the same from FLAT rows — it groups by
# `group_col`, assembles each group's series, calls the service once per
# group, and scatters the flags back onto the rows. Mocked endpoints keep
# the real request/response shapes.

# %%
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class Mock(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def _json(self, payload):
        body = json.dumps(payload).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(n))
        p = self.path.split("?")[0]
        series = body["series"]
        vals = [pt["value"] for pt in series]
        if p.endswith("/timeseries/last/detect"):
            spike = vals[-1] > 3 * (sum(vals[:-1]) / max(len(vals) - 1, 1))
            return self._json({"isAnomaly": bool(spike),
                               "expectedValue": float(np.median(vals))})
        if p.endswith("/timeseries/entire/detect"):
            med = float(np.median(vals))
            return self._json({"isAnomaly": [v > 3 * max(med, 1e-9)
                                             for v in vals]})
        self.send_error(404)


import numpy as np

srv = ThreadingHTTPServer(("127.0.0.1", 0), Mock)
threading.Thread(target=srv.serve_forever, daemon=True).start()
URL = f"http://127.0.0.1:{srv.server_address[1]}"

# %% [markdown]
# ## Score a series column

# %%
import synapseml_tpu as st
from synapseml_tpu.services import (DetectAnomalies, DetectLastAnomaly,
                                    SimpleDetectAnomalies)

stamps = [f"2026-07-{d:02d}T00:00:00Z" for d in range(1, 9)]
values = [1.0, 1.1, 0.9, 1.0, 1.2, 0.8, 1.1, 9.0]  # spike at the end
series = [{"timestamp": t, "value": v} for t, v in zip(stamps, values)]
df = st.DataFrame.from_dict({"series": [series]})

last = DetectLastAnomaly(url=URL, subscription_key="demo-key",
                         granularity="daily").transform(df)
print("last point anomalous:", last.collect_column("out")[0]["isAnomaly"])

whole = DetectAnomalies(url=URL, subscription_key="demo-key",
                        granularity="daily").transform(df)
flags = whole.collect_column("out")[0]["isAnomaly"]
print("per-point flags:", flags)
assert flags[-1] and not any(flags[:-1])

# %% [markdown]
# ## Flat rows: group, assemble, detect, scatter back
# The common warehouse shape — one row per (sensor, timestamp, value).

# %%
rows = []
for sensor in ("s1", "s2"):
    for t, v in zip(stamps, values):
        rows.append({"group": sensor, "timestamp": t,
                     "value": v if sensor == "s1" else 1.0})
sdf = st.DataFrame.from_rows(rows)
sda = SimpleDetectAnomalies(url=URL, subscription_key="demo-key",
                            granularity="daily")
out = sda.transform(sdf)
got = list(zip(out.collect_column("group"), out.collect_column("out")))
s1_flags = [f for g, f in got if g == "s1"]
s2_flags = [f for g, f in got if g == "s2"]
print("s1 flags:", s1_flags)
print("s2 flags:", s2_flags)
assert s1_flags[-1] and not any(s2_flags)

# %%
srv.shutdown()
print("done")
