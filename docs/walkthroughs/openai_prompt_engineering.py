# %% [markdown]
# # OpenAI services: prompt templates, chat, and embeddings over DataFrames
# The OpenAI family (reference: `services/openai/`) turns each row into a
# completion/chat/embedding request. `OpenAIPrompt` renders a template per
# row and can post-process replies (regex extraction, CSV splitting) into
# typed columns. The mock echoes the wire shapes; swap `url=` +
# `deployment_name=` for a real Azure OpenAI resource.

# %%
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class Mock(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def _json(self, payload, status=200):
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(n))
        if not self.headers.get("api-key"):
            return self._json({"error": "unauthorized"}, 401)
        if "/chat/completions" in self.path:
            user = [m for m in body["messages"] if m["role"] == "user"][-1]
            text = user["content"]
            if "capital of" in text:
                place = text.rsplit(" ", 1)[-1].strip("?")
                reply = {"France": "Paris", "Japan": "Tokyo"}.get(place, "?")
            else:
                reply = f"echo:{text}"
            return self._json({"choices": [{"message": {
                "role": "assistant", "content": reply}}]})
        if "/embeddings" in self.path:
            t = body["input"]
            return self._json({"data": [{"embedding":
                                         [float(len(t)), 1.0, 0.5]}]})
        self.send_error(404)


srv = ThreadingHTTPServer(("127.0.0.1", 0), Mock)
threading.Thread(target=srv.serve_forever, daemon=True).start()
URL = f"http://127.0.0.1:{srv.server_address[1]}"

# %% [markdown]
# ## Prompt templates: one request per row, rendered from columns

# %%
import synapseml_tpu as st
from synapseml_tpu.services import (OpenAIChatCompletion, OpenAIEmbedding,
                                    OpenAIPrompt)

df = st.DataFrame.from_dict({"country": ["France", "Japan"]})
prompt = OpenAIPrompt(url=URL, subscription_key="demo-key",
                      deployment_name="gpt-4o-mini",
                      prompt_template="What is the capital of {country}?")
out = prompt.transform(df)
print("answers:", list(out.collect_column("outParsedOutput")))
assert list(out.collect_column("outParsedOutput")) == ["Paris", "Tokyo"]

# %% [markdown]
# ## Raw chat: full message lists per row

# %%
chat_df = st.DataFrame.from_dict({"messages": [
    [{"role": "system", "content": "be terse"},
     {"role": "user", "content": "hello"}]]})
chat = OpenAIChatCompletion(url=URL, subscription_key="demo-key",
                            deployment_name="gpt-4o-mini")
print("chat:", chat.transform(chat_df).collect_column("chat_completions"))

# %% [markdown]
# ## Embeddings feed KNN / SAR / AccessAnomaly downstream

# %%
emb = OpenAIEmbedding(url=URL, subscription_key="demo-key",
                      deployment_name="text-embedding-3-small")
vecs = emb.transform(st.DataFrame.from_dict(
    {"text": ["short", "a longer sentence"]})).collect_column("embedding")
print("embedding dims:", [len(v) for v in vecs])
assert vecs[0][0] != vecs[1][0]  # mock encodes length in dim 0

# %%
srv.shutdown()
print("done")
