# %% [markdown]
# # Translator workflows: translate, transliterate, sentence metrics,
# # dictionary lookup
# The full Translator-family surface (reference:
# `services/translate/Translate.scala`) as DataFrame stages. Each
# transformer batches rows into the Translator REST body shape
# (`[{"Text": ...}]`) and parses the reply into a column. The mock below
# keeps the exact wire shapes; swap `url=` for the real endpoint.

# %%
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class Mock(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def _json(self, payload):
        body = json.dumps(payload).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(n))
        p = self.path.split("?")[0]
        if p == "/translate":
            return self._json([{"translations": [
                {"text": f"es({t['Text']})", "to": "es"}]} for t in body])
        if p == "/transliterate":
            return self._json([{"text": "namaste", "script": "Latn"}
                               for _ in body])
        if p == "/breaksentence":
            return self._json([{"sentLen": [len(s) + 1 for s in
                                            t["Text"].split(".") if s]}
                               for t in body])
        if p == "/dictionary/lookup":
            return self._json([{"translations": [
                {"normalizedTarget": "volar"},
                {"normalizedTarget": "mosca"}]} for _ in body])
        self.send_error(404)


srv = ThreadingHTTPServer(("127.0.0.1", 0), Mock)
threading.Thread(target=srv.serve_forever, daemon=True).start()
URL = f"http://127.0.0.1:{srv.server_address[1]}"

# %% [markdown]
# ## Translate a column

# %%
import synapseml_tpu as st
from synapseml_tpu.services import (BreakSentence, DictionaryLookup,
                                    Translate, Transliterate)

df = st.DataFrame.from_dict({"text": ["hello world", "good morning."]})
out = Translate(url=URL, subscription_key="demo-key",
                to_language="es").transform(df)
print("translations:", out.collect_column("translation"))

# %% [markdown]
# ## Transliterate between scripts
# Script conversion (Devanagari -> Latin here) keeps the language, changes
# the writing system.

# %%
tl = Transliterate(url=URL, subscription_key="demo-key", language="hi",
                   from_script="Deva", to_script="Latn")
print("transliterated:", tl.transform(
    st.DataFrame.from_dict({"text": ["नमस्ते"]})).collect_column("transliteration"))

# %% [markdown]
# ## Sentence boundaries and bilingual dictionary

# %%
bs = BreakSentence(url=URL, subscription_key="demo-key")
print("sentence lengths:", bs.transform(df).collect_column("sent_len"))

dl = DictionaryLookup(url=URL, subscription_key="demo-key",
                      from_language="en", to_language="es")
looked = dl.transform(st.DataFrame.from_dict({"text": ["fly"]}))
targets = list(looked.collect_column("translations")[0])
print("dictionary targets:", targets)
assert targets == ["volar", "mosca"]

# %%
srv.shutdown()
print("done")
