# %% [markdown]
# # Speech services: transcription and synthesis as pipeline stages
# `SpeechToText` posts audio bytes to the short-audio REST endpoint and lands
# the recognition result in a column; `TextToSpeech` renders SSML and returns
# synthesized audio bytes (reference: `services/speech/SpeechToTextSDK.scala`
# — redesigned over REST, documented in docs/api/services.md). This demo
# serves an in-process mock with the real request/response shapes, so it
# runs with zero egress; point `url=` at a real Azure region in production.

# %%
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class Mock(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def _send(self, body, ctype):
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n)
        p = self.path.split("?")[0]
        if "/speech/recognition/" in p:  # short-audio STT
            assert body.startswith(b"RIFF"), "audio bytes expected"
            return self._send(json.dumps(
                {"RecognitionStatus": "Success",
                 "DisplayText": "the quick brown fox"}).encode(),
                "application/json")
        if p.endswith("/cognitiveservices/v1"):  # TTS: SSML in, audio out
            assert b"<speak" in body
            return self._send(b"RIFF" + b"\x00" * 16, "audio/wav")
        self.send_error(404)


srv = ThreadingHTTPServer(("127.0.0.1", 0), Mock)
threading.Thread(target=srv.serve_forever, daemon=True).start()
URL = f"http://127.0.0.1:{srv.server_address[1]}"

# %% [markdown]
# ## Transcribe a batch of audio rows
# Audio travels as raw bytes in a DataFrame column; the transformer fans
# requests out through the shared async HTTP client (`concurrency` requests
# in flight) and never fails a batch on one bad row — errors land in the
# `error_col` instead.

# %%
import synapseml_tpu as st
from synapseml_tpu.services import SpeechToText, TextToSpeech

clips = st.DataFrame.from_dict({"audio": [b"RIFF" + bytes([i]) * 8
                                          for i in range(3)]})
stt = SpeechToText(url=URL, subscription_key="demo-key", language="en-US")
texts = stt.transform(clips)
for r in texts.collect_column("out"):
    print("transcript:", r["DisplayText"])

# %% [markdown]
# ## Synthesize speech from text
# `TextToSpeech` escapes the text into SSML with the configured voice and
# returns the rendered audio bytes — ready for a binary-file sink.

# %%
lines = st.DataFrame.from_dict({"text": ["hello <world>", "goodbye"]})
tts = TextToSpeech(url=URL, subscription_key="demo-key",
                   voice="en-US-JennyNeural")
audio = tts.transform(lines).collect_column("out")
print("synthesized:", [a[:4] for a in audio])
assert all(a.startswith(b"RIFF") for a in audio)

# %% [markdown]
# Chain them: speech in, speech out — a round-trip voice pipeline is just
# two stages in a `st.Pipeline` with the text column wired between them.

# %%
srv.shutdown()
print("done")
