# %% [markdown]
# # Walkthrough: import an ONNX model, compile it for TPU, shard it
#
# The reference runs ONNX graphs through a per-partition ONNX Runtime
# session (`onnx/ONNXModel.scala:145-423`). Here the graph converts ONCE to
# a jittable JAX function; XLA compiles it for the device, and the same
# function scales out by sharding the batch over a device mesh — no
# runtime, no per-executor session state.

# %%  Stage 1 — a real torch export (transformer-shaped ops incl. Einsum)
import numpy as np
import torch

import synapseml_tpu as st
from synapseml_tpu.onnx import ONNXModel, convert_graph


class TinyNet(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = torch.nn.Linear(16, 32)
        self.fc2 = torch.nn.Linear(32, 4)

    def forward(self, x):
        h = torch.einsum("nd,dk->nk", x, self.fc1.weight.T) + self.fc1.bias
        return self.fc2(torch.relu(h))


torch.manual_seed(0)
net = TinyNet().eval()

# torch's exporter imports an `onnx` package only to scan for custom
# onnxscript functions; our proto codec stands in for it (the
# tests/_torch_resnet.py pattern)
import io
import sys
import types

from synapseml_tpu.onnx.proto import parse_model

if "onnx" not in sys.modules:
    class _Model:
        def __init__(self, parsed):
            self.graph = parsed.graph
            self.functions = []

    shim = types.ModuleType("onnx")
    shim.load_model_from_string = lambda b: _Model(parse_model(b))
    sys.modules["onnx"] = shim

buf = io.BytesIO()
torch.onnx.export(net, torch.zeros(1, 16), buf, dynamo=False,
                  input_names=["x"], output_names=["logits"],
                  dynamic_axes={"x": {0: "N"}, "logits": {0: "N"}})
model_bytes = buf.getvalue()

# %%  Stage 2 — convert + parity check against torch
conv = convert_graph(model_bytes)
x = np.random.default_rng(0).normal(size=(8, 16)).astype(np.float32)
with torch.no_grad():
    want = net(torch.from_numpy(x)).numpy()
got = np.asarray(conv(x=x)["logits"])
np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
print("torch parity ok; ops:", end=" ")
from synapseml_tpu.onnx.proto import ModelProto
print(sorted({n.op_type for n in ModelProto.parse(model_bytes).graph.node}))

# %%  Stage 3 — the DataFrame estimator surface (ONNXModel)
df = st.DataFrame.from_dict({"feat": x})
om = ONNXModel(model_bytes=model_bytes, mini_batch_size=4,
               feed_dict={"x": "feat"}, fetch_dict={"logits": "logits"},
               argmax_dict={"logits": "pred"})
out = om.transform(df)
print("predictions:", out.collect_column("pred").tolist())

# %%  Stage 4 — scale out: shard the batch over a device mesh
# The SAME converted function runs SPMD: place the batch with a
# NamedSharding and jit — XLA partitions the matmuls and inserts any
# collectives (here: none needed, pure data parallel).
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

devs = np.array(jax.devices()[: min(8, jax.device_count())])
mesh = Mesh(devs, ("data",))
fn = jax.jit(lambda t: conv(x=t)["logits"],
             in_shardings=NamedSharding(mesh, P("data")),
             out_shardings=NamedSharding(mesh, P("data")))
big = np.random.default_rng(1).normal(size=(64, 16)).astype(np.float32)
sharded_out = np.asarray(fn(big))
np.testing.assert_allclose(
    sharded_out, np.asarray(conv(x=big)["logits"]), rtol=1e-4, atol=1e-5)
print(f"sharded over {len(devs)} devices:", sharded_out.shape)
print("walkthrough complete: export -> convert -> estimator -> shard")
