# %% [markdown]
# # Walkthrough: contextual bandits and off-policy evaluation
#
# The reference's VW arc (`VowpalWabbitContextualBandit.scala` training on
# logged CB data, then counterfactual evaluation via `policyeval/` —
# IPS/SNIPS/Cressie-Read): simulate a logged bandit dataset, learn a
# policy, and measure — WITHOUT deploying it — how much better it is than
# the logging policy.

# %%  Stage 1 — simulate logged bandit data (uniform logging policy)
import numpy as np

import synapseml_tpu as st
from synapseml_tpu.vw import (VowpalWabbitContextualBandit,
                              VowpalWabbitCSETransformer, cressie_read,
                              cressie_read_interval, ips, snips)

rng = np.random.default_rng(0)
n, A, D = 3000, 3, 4
sh_idx = np.tile(np.arange(5, dtype=np.int32), (n, 1))
sh_val = rng.normal(size=(n, 5)).astype(np.float32)
a_idx = np.tile((np.arange(A * D, dtype=np.int32) + 100).reshape(A, D), (n, 1, 1))
a_val = np.ones((n, A, D), np.float32)
best = (sh_val[:, 0] > 0).astype(int)          # context decides the best arm
chosen = rng.integers(0, A, size=n)            # uniform logging policy
cost = np.where(chosen == best, 0.0, 1.0)      # cost 0 when correct
df = st.DataFrame.from_dict({
    "shared_indices": sh_idx, "shared_values": sh_val,
    "features_indices": a_idx, "features_values": a_val,
    "chosenAction": chosen + 1, "cost": cost.astype(np.float64),
    "probability": np.full(n, 1.0 / A)})
print("logged average cost (uniform policy):", round(float(cost.mean()), 3))

# %%  Stage 2 — train the CB policy (IPS-weighted, jitted)
model = VowpalWabbitContextualBandit(num_passes=6).fit(df)
out = model.transform(df)
greedy = out.collect_column("predictedAction") - 1
match = float((greedy == best).mean())
print("greedy action == true best:", round(match, 3))
assert match > 0.6

# %%  Stage 3 — off-policy evaluation: how good is the learned policy?
# The learned (deterministic) policy only matches logged rows where it
# would have chosen the same action; importance weights reweight those.
reward = 1.0 - cost                         # evaluators use rewards
w = (greedy == chosen) / (1.0 / A)          # P_new(a|x) / P_log(a|x)
est_ips = ips(w, reward)
est_snips = snips(w, reward)
est_cr = cressie_read(w, reward)
lo, hi = cressie_read_interval(w, reward)
print(f"policy value:  logged={reward.mean():.3f}  IPS={est_ips:.3f}  "
      f"SNIPS={est_snips:.3f}  CR={est_cr:.3f}  CI=[{lo:.3f},{hi:.3f}]")
# the learned policy should evaluate clearly above the logging policy
assert est_snips > reward.mean() + 0.2
assert lo <= est_cr <= hi

# %%  Stage 4 — the DataFrame surface (CSE transformer, reference
# VowpalWabbitCSETransformer): per-row log/pred probabilities + reward in,
# full estimator battery out.
cse_df = st.DataFrame.from_dict({
    "probLog": np.full(n, 1.0 / A),
    "probPred": (greedy == chosen).astype(np.float64),  # deterministic policy
    "reward": reward})
row = VowpalWabbitCSETransformer().transform(cse_df).first()
print("CSE:", {k: round(float(v), 3) for k, v in row.items()
               if k in ("ips", "snips", "cressieRead", "count")})
assert row["count"] == n
print("walkthrough complete: simulate -> learn -> evaluate offline")
