# %% [markdown]
# # Walkthrough: recommenders — SAR from interactions to ranked top-k
#
# The reference's recommendation tier (`core/.../recommendation/`): index
# raw user/item ids (`RecommendationIndexer`), fit SAR (item-item
# similarity + time-decayed user affinity, `SAR.scala:36`), produce top-k
# recommendations, and evaluate with ranking metrics through
# `RankingTrainValidationSplit` (`RankingTrainValidationSplit.scala:25`).
# Data here is a simulated two-community catalog: users in each community
# interact overwhelmingly within their community, so a good recommender
# keeps recommendations in-community and beats a random baseline on NDCG.

# %%  Stage 1 — simulate interactions (two communities, 40 users, 24 items)
import numpy as np

import synapseml_tpu as st
from synapseml_tpu.recommendation import (
    RankingEvaluator,
    RankingTrainValidationSplit,
    RecommendationIndexer,
    SAR,
)

rs = np.random.default_rng(0)
rows = {"user": [], "item": [], "rating": [], "time": []}
for u in range(40):
    community = u % 2
    for _ in range(rs.integers(6, 12)):
        if rs.random() < 0.9:                       # in-community interaction
            item = community * 12 + int(rs.integers(0, 12))
        else:
            item = (1 - community) * 12 + int(rs.integers(0, 12))
        rows["user"].append(f"u{u}")
        rows["item"].append(f"i{item:02d}")
        rows["rating"].append(float(rs.integers(1, 6)))
        rows["time"].append(float(rs.integers(0, 1000)))
df = st.DataFrame.from_dict({
    "user": np.asarray(rows["user"], dtype=object),
    "item": np.asarray(rows["item"], dtype=object),
    "rating": np.asarray(rows["rating"]),
    "time": np.asarray(rows["time"])})
print("interactions:", df.count())

# %%  Stage 2 — index string ids to dense ints (and back)
indexer = RecommendationIndexer().fit(df)
indexed = indexer.transform(df)
assert indexed.collect_column("user_idx").dtype == np.int32
round_trip = indexer.recover_item(indexed.collect_column("item_idx"))
np.testing.assert_array_equal(round_trip, df.collect_column("item"))

# %%  Stage 3 — fit SAR and recommend top-k unseen items per user
sar = SAR(rating_col="rating", time_col="time", support_threshold=2,
          similarity_function="jaccard").fit(indexed)
recs = sar.recommend_for_all_users(k=5)
seen = np.asarray(sar.get("seen_items"))
in_community = 0
total = 0
for u, items, scores in zip(recs.collect_column("user_idx"),
                            recs.collect_column("recommendations"),
                            recs.collect_column("ratings")):
    # recommendations never repeat seen items
    assert not (set(np.asarray(items).tolist())
                & set(np.nonzero(seen[u])[0].tolist()))
    user_comm = int(str(indexer.recover_user([u])[0])[1:]) % 2
    for it, sc in zip(np.asarray(items), np.asarray(scores)):
        if sc > 0:
            item_comm = 0 if int(str(indexer.recover_item([int(it)])[0])[1:]) < 12 else 1
            in_community += int(item_comm == user_comm)
            total += 1
frac = in_community / total
print(f"in-community recommendation rate: {frac:.2f} ({total} scored recs)")
assert frac > 0.8          # the community structure is recovered

# %%  Stage 4 — model selection on a ranking metric (NDCG@5)
tvs = RankingTrainValidationSplit(
    estimator=SAR(support_threshold=1, rating_col="rating"),
    estimator_param_maps=[{"similarity_function": "jaccard"},
                          {"similarity_function": "lift"},
                          {"similarity_function": "cooccurrence"}],
    evaluator=RankingEvaluator(k=5, metric_name="ndcgAt"),
    train_ratio=0.75, seed=3)
model = tvs.fit(indexed)
metrics = model.get("validation_metrics")
print("validation NDCG@5 per similarity:",
      dict(zip(["jaccard", "lift", "cooccurrence"],
               [round(m, 3) for m in metrics])))
assert max(metrics) > 0.2  # structure beats random
ranked = model.transform(indexed)
assert set(ranked.columns) >= {"prediction", "label"}
print("walkthrough complete")
