# %% [markdown]
# # Azure Cognitive Search: schema-inferred index creation and document feed
# `AzureSearchWriter` (reference: `services/search/AzureSearch.scala:147`)
# infers an index schema from the DataFrame's columns, creates the index if
# it does not exist, and streams rows in as indexing batches — per-row
# status lands in a column. The mock keeps the service's wire shapes
# (`POST /indexes`, `POST /indexes/{name}/docs/index`).

# %%
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class Mock(BaseHTTPRequestHandler):
    indexes: set = set()
    schemas: list = []
    fed: list = []

    def log_message(self, *a):
        pass

    def _json(self, payload, status=200):
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path.split("?")[0] == "/indexes":
            return self._json({"value": [{"name": n} for n in Mock.indexes]})
        self.send_error(404)

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(n))
        p = self.path.split("?")[0]
        assert self.headers.get("api-key") == "demo-key"
        if p == "/indexes":
            Mock.schemas.append(body)
            Mock.indexes.add(body["name"])
            return self._json({"name": body["name"]}, 201)
        if p.startswith("/indexes/") and p.endswith("/docs/index"):
            name = p.split("/")[2]
            if name not in Mock.indexes:
                return self._json({"error": {"message": "no such index"}}, 404)
            Mock.fed.extend(body["value"])
            return self._json({"value": [{"key": d.get("id"), "status": True}
                                         for d in body["value"]]})
        self.send_error(404)


srv = ThreadingHTTPServer(("127.0.0.1", 0), Mock)
threading.Thread(target=srv.serve_forever, daemon=True).start()
URL = f"http://127.0.0.1:{srv.server_address[1]}"

# %% [markdown]
# ## Feed documents; the index is created from the data on first write
# Every row becomes a search document keyed by `key_col`; the index schema
# is inferred from column dtypes when `create_index_if_not_exists=True`.

# %%
import synapseml_tpu as st
from synapseml_tpu.services import AzureSearchWriter

docs = st.DataFrame.from_dict({
    "id": ["d1", "d2", "d3"],
    "title": ["intro to tpus", "sharding models", "ring attention"],
    "score": [0.9, 0.7, 0.8]})
writer = AzureSearchWriter(url=URL, subscription_key="demo-key",
                           index_name="articles",
                           create_index_if_not_exists=True, batch_size=2)
statuses = writer.write(docs)  # transform(df) = write + pass-through
print("batch statuses:", statuses)
print("index created:", Mock.indexes)
print("schema fields:", [f["name"] for f in Mock.schemas[0]["fields"]])
assert len(Mock.fed) == 3

# %% [markdown]
# ## Re-writing skips creation (idempotent) and appends documents

# %%
more = st.DataFrame.from_dict({"id": ["d4"], "title": ["pallas kernels"],
                               "score": [0.95]})
AzureSearchWriter(url=URL, subscription_key="demo-key", index_name="articles",
                  create_index_if_not_exists=True).transform(more)
print("total docs fed:", len(Mock.fed), "schemas created:", len(Mock.schemas))
assert len(Mock.schemas) == 1  # created once

# %%
srv.shutdown()
print("done")
