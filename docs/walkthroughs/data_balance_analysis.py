# %% [markdown]
# # Data balance analysis: measuring representation before training
# The three balance measures (reference: `core/.../exploratory/
# DataBalanceAnalysis` — FeatureBalanceMeasure, DistributionBalanceMeasure,
# AggregateBalanceMeasure) quantify how fairly sensitive groups are
# represented, BEFORE a model bakes the skew in. All three are pure
# column aggregations (`synapseml_tpu/exploratory/balance.py`).

# %%
import numpy as np

import synapseml_tpu as st
from synapseml_tpu.exploratory import (AggregateBalanceMeasure,
                                       DistributionBalanceMeasure,
                                       FeatureBalanceMeasure)

rs = np.random.default_rng(0)
n = 2000
gender = rs.choice(["F", "M"], n, p=[0.35, 0.65])
eth = rs.choice(["a", "b", "c", "d"], n, p=[0.55, 0.25, 0.15, 0.05])
# the label is skewed FOR M: 70% positive vs 40% for F
label = np.where(gender == "M",
                 rs.random(n) < 0.7, rs.random(n) < 0.4).astype(np.int64)
df = st.DataFrame.from_dict({"gender": gender, "eth": eth, "label": label})

# %% [markdown]
# ## Feature balance: label parity gaps between group pairs
# Statistical parity difference (and the associated gap family) between
# every pair of values of the sensitive column — positive means the first
# group receives the positive label more often.

# %%
fb = FeatureBalanceMeasure(sensitive_cols=["gender"]).transform(df)
row = fb.collect_rows()[0]
print("gender parity gaps:", {k: round(float(v), 3)
                              for k, v in row.items()
                              if isinstance(v, (int, float, np.floating))})

# %% [markdown]
# ## Distribution balance: how far from uniform is each sensitive column?

# %%
db = DistributionBalanceMeasure(sensitive_cols=["eth"]).transform(df)
m = db.collect_rows()[0]
print("eth distribution measures:", {k: round(float(v), 4)
                                     for k, v in m.items()
                                     if isinstance(v, (int, float, np.floating))})

# %% [markdown]
# ## Aggregate balance: one number per dataset
# Atkinson/Theil-style indices over the sensitive-combination counts: 0 is
# perfectly balanced; rising values mean concentration.

# %%
agg_skewed = AggregateBalanceMeasure(sensitive_cols=["eth"]).transform(df)
uniform = st.DataFrame.from_dict(
    {"eth": np.repeat(["a", "b", "c", "d"], 500)})
agg_uniform = AggregateBalanceMeasure(sensitive_cols=["eth"]).transform(uniform)
s = agg_skewed.collect_rows()[0]
u = agg_uniform.collect_rows()[0]
for k in s:
    if isinstance(s[k], (int, float, np.floating)):
        print(f"{k}: skewed {float(s[k]):.4f} vs uniform {float(u[k]):.4f}")
        assert abs(float(u[k])) <= abs(float(s[k])) + 1e-9

# %% [markdown]
# In a training pipeline these run as plain transformers — gate a `fit` on
# the measures, or log them as telemetry next to the model's metrics.
