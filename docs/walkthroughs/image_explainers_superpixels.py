# %% [markdown]
# # Explaining image models with superpixel LIME and SHAP
# `ImageLIME` / `ImageSHAP` (reference: `core/.../explainers/ImageLIME.scala`,
# `ImageSHAP.scala`) segment an image into SLIC superpixels, perturb by
# masking random superpixel subsets, score every perturbed image with YOUR
# model, and fit a local surrogate — the coefficients say which regions
# drive the prediction. TPU shape: all perturbed copies score as ONE
# batched model call (`synapseml_tpu/explainers/image.py`).

# %%
import numpy as np

import synapseml_tpu as st
from synapseml_tpu.core.pipeline import Transformer
from synapseml_tpu.explainers import ImageLIME, ImageSHAP


class LeftHalfScorer(Transformer):
    """Toy 'model': probability = mean brightness of the LEFT half. A
    faithful explainer must attribute everything to left-side regions."""

    def _transform(self, sdf):
        def score(p):
            out = []
            for im in p["image"]:
                im = np.asarray(im, np.float64)
                out.append(np.asarray([im[:, : im.shape[1] // 2].mean()]))
            return np.asarray(out)

        return sdf.with_column("probability", score)


# four flat 12x12 quadrants -> SLIC superpixels land exactly on quadrants
img = np.zeros((24, 24, 1), np.float32)
img[:12, :12], img[:12, 12:] = 60.0, 120.0
img[12:, :12], img[12:, 12:] = 180.0, 240.0
df = st.DataFrame.from_dict({"image": [img]})

# %% [markdown]
# ## LIME: ridge surrogate over superpixel on/off masks

# %%
lime = ImageLIME(model=LeftHalfScorer(), target_col="probability",
                 cell_size=12.0, num_samples=96, regularization=1e-4, seed=0)
exp = lime.transform(df)
coefs = np.asarray(exp.collect_column("explanation")[0])[0]

from synapseml_tpu.image import slic_segments

labels = slic_segments(img, cell_size=12.0)
K = labels.max() + 1
centers = np.asarray([np.mean(np.nonzero(labels == k)[1]) for k in range(K)])
left = centers < 12
print(f"{K} superpixels; |coef| left {np.abs(coefs[:K][left]).sum():.2f} "
      f"vs right {np.abs(coefs[:K][~left]).sum():.2f}")
assert np.abs(coefs[:K][left]).sum() > 2 * np.abs(coefs[:K][~left]).sum()

# %% [markdown]
# ## SHAP: Shapley sampling over the same superpixels
# Same perturb-and-score machinery, Shapley-weighted — attributions again
# concentrate on the left.

# %%
shap = ImageSHAP(model=LeftHalfScorer(), target_col="probability",
                 cell_size=12.0, num_samples=96, seed=0)
sv = np.asarray(shap.transform(df).collect_column("explanation")[0])[0]
print(f"|shap| left {np.abs(sv[:K][left]).sum():.2f} "
      f"vs right {np.abs(sv[:K][~left]).sum():.2f}")
assert np.abs(sv[:K][left]).sum() > 2 * np.abs(sv[:K][~left]).sum()

# %% [markdown]
# Any model plugs in — an `ONNXModel`, a `DeepVisionClassifier`, or a served
# pipeline — as long as it writes the `target_col` the explainer reads.
