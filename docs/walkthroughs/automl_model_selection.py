# %% [markdown]
# # Walkthrough: AutoML — featurize, tune, select, and audit a model
#
# The reference's convenience tier: `TrainClassifier` auto-featurizes mixed
# columns and fits any learner (`core/.../train/TrainClassifier.scala:52`),
# `TuneHyperparameters` random-searches a param space in parallel
# (`automl/TuneHyperparameters.scala:38`), `FindBestModel` picks among
# trained candidates (`automl/FindBestModel.scala:53`), and
# `ComputeModelStatistics` audits the winner. Same arc on real wine
# chemistry data (3 cultivars, 13 assay features).

# %%  Stage 1 — real data with a held-out split
import numpy as np
from sklearn.datasets import load_wine

import synapseml_tpu as st
from synapseml_tpu.automl import (
    DiscreteHyperParam,
    FindBestModel,
    HyperparamBuilder,
    RangeHyperParam,
    TuneHyperparameters,
)
from synapseml_tpu.gbdt import LightGBMClassifier
from synapseml_tpu.train import ComputeModelStatistics, TrainClassifier

data = load_wine()
rs = np.random.default_rng(0)
order = rs.permutation(len(data.target))
tr, te = order[:140], order[140:]


def to_df(idx):
    cols = {str(n): data.data[idx, j] for j, n in enumerate(data.feature_names)}
    cols["label"] = np.asarray([data.target_names[t] for t in data.target[idx]],
                               dtype=object)   # string labels on purpose
    return st.DataFrame.from_dict(cols)


train_df, test_df = to_df(tr), to_df(te)

# %%  Stage 2 — TrainClassifier: auto-featurize mixed columns + string labels
# Numeric columns are assembled/imputed and the string label indexed —
# the `Featurize` pipeline the reference assembles inside TrainClassifier.
tc = TrainClassifier(model=LightGBMClassifier(num_iterations=40, num_leaves=7))
tc_model = tc.fit(train_df)
scored = tc_model.transform(test_df)
acc = float(np.mean(scored.collect_column("predicted_label")
                    == test_df.collect_column("label")))
print("TrainClassifier held-out accuracy:", round(acc, 3))
assert acc > 0.9

# %%  Stage 3 — TuneHyperparameters: random search over a param space
# The tuner consumes the assembled representation (features vector +
# integer label) and cross-validates each sampled config in parallel.
def assembled(idx):
    return st.DataFrame.from_dict(
        {"features": data.data[idx].astype(np.float32),
         "label": data.target[idx].astype(np.int32)}, num_partitions=2)


space = (HyperparamBuilder()
         .add_hyperparam("num_leaves", DiscreteHyperParam([4, 7, 15, 31]))
         .add_hyperparam("num_iterations", RangeHyperParam(10, 60))
         .build())
best = TuneHyperparameters(models=[LightGBMClassifier()], hyperparam_space=space,
                           num_runs=6, parallelism=3,
                           evaluation_metric="accuracy", seed=7).fit(assembled(tr))
print("best params:", best.get("best_params"),
      "val metric:", round(best.get("best_metric"), 3))
assert best.get("best_metric") > 0.85

# %%  Stage 4 — FindBestModel across trained candidates
candidates = [LightGBMClassifier(num_iterations=3, num_leaves=3),
              LightGBMClassifier(num_iterations=50, num_leaves=15)]
res = FindBestModel(models=candidates).fit(assembled(tr))
metrics = res.get("all_model_metrics")       # list of (model name, metric)
print("candidate metrics:", [(name, round(v, 3)) for name, v in metrics])
assert res.get("best_metric") == max(v for _, v in metrics)

# %%  Stage 5 — audit the winner: ComputeModelStatistics
out = res.transform(assembled(te))
stats = ComputeModelStatistics().transform(out)
row = stats.collect_rows()[0]
print("test accuracy:", round(row["accuracy"], 3))
print("confusion matrix:\n", np.asarray(row["confusion_matrix"]))
assert row["accuracy"] > 0.85
print("walkthrough complete")
