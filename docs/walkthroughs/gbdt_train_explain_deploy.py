# %% [markdown]
# # Walkthrough: GBDT from training to deployment
#
# The full lifecycle the reference documents across
# `docs/Explore Algorithms/LightGBM/` — train on real data, hold out a
# test split, explain predictions with TreeSHAP, persist the model in the
# native LightGBM `model.txt` format, and serve it over HTTP — on the TPU
# engine (XLA histogram tree-grower; one fused program per iteration).

# %%  Stage 1 — real data, held-out split
import json
import http.client

import numpy as np
from sklearn.datasets import load_breast_cancer

import synapseml_tpu as st
from synapseml_tpu.gbdt import LightGBMClassifier

data = load_breast_cancer()
rs = np.random.default_rng(0)
order = rs.permutation(len(data.target))
split = int(0.8 * len(order))
tr, te = order[:split], order[split:]
train_df = st.DataFrame.from_rows(
    [{"features": data.data[i].astype(np.float32), "label": int(data.target[i])}
     for i in tr], num_partitions=4)
test_df = st.DataFrame.from_rows(
    [{"features": data.data[i].astype(np.float32), "label": int(data.target[i])}
     for i in te])

# %%  Stage 2 — train + evaluate (AUC on the held-out split)
clf = LightGBMClassifier(num_iterations=60, learning_rate=0.1, num_leaves=15)
model = clf.fit(train_df)
out = model.transform(test_df)
prob = np.stack(list(out.collect_column("probability")))[:, 1]
y = out.collect_column("label")
order = np.argsort(prob)
ranks = np.empty(len(prob)); ranks[order] = np.arange(1, len(prob) + 1)
n1 = y.sum(); n0 = len(y) - n1
auc = (ranks[y == 1].sum() - n1 * (n1 + 1) / 2) / (n1 * n0)
print("held-out AUC:", round(float(auc), 4))
assert auc > 0.97

# %%  Stage 3 — explain: TreeSHAP attributions (featuresShap analog)
model.set(features_shap_col="shap")
exp = model.transform(test_df)
shap = np.stack(list(exp.collect_column("shap")))
raw = np.stack(list(exp.collect_column("rawPrediction")))
assert np.allclose(shap.sum(-1), raw[:, 0], atol=1e-4)  # additivity
top = np.argsort(-np.abs(shap[:, :-1]).mean(0))[:3]
print("top-3 features:", [data.feature_names[i] for i in top])

# %%  Stage 4 — persist in the NATIVE format (LightGBMBooster model.txt)
import tempfile

from synapseml_tpu.gbdt import parse_lightgbm_string

with tempfile.TemporaryDirectory() as d:
    model.save_native_model(d)  # writes model.txt (LightGBM text format)
    back = parse_lightgbm_string(open(d + "/model.txt").read())
    Xte_f = data.data[te].astype(np.float32)
    p1 = np.asarray(model.get_booster().predict(Xte_f)).ravel()
    p2 = np.asarray(back.predict(Xte_f)).ravel()
    np.testing.assert_allclose(p1, p2, rtol=1e-4, atol=1e-5)
print("native model.txt round-trip ok")

# %%  Stage 5 — deploy: serve the trained model over HTTP
from synapseml_tpu.core.pipeline import Transformer
from synapseml_tpu.io import serve_pipeline


class Scorer(Transformer):
    def _transform(self, df):
        def per_part(p):
            X = np.stack([np.asarray((b or {}).get("features", []), np.float32)
                          for b in p["body"]])
            prob = np.asarray(model.get_booster().predict(X)).ravel()
            out = dict(p)
            out["reply"] = np.asarray(
                [{"malignant_prob": round(1.0 - float(pr), 4)} for pr in prob],
                dtype=object)
            return out

        return df.map_partitions(per_part)


server = serve_pipeline(Scorer(), batch_interval_ms=0)
host, port = server.address.split("//")[1].split(":")
conn = http.client.HTTPConnection(host, int(port), timeout=30)  # keep-alive
for i in te[:3]:
    conn.request("POST", "/",
                 body=json.dumps({"features": data.data[i].tolist()}).encode())
    r = conn.getresponse()
    reply = json.loads(r.read())
    print("served:", reply, "label:", int(data.target[i]))
    assert r.status == 200 and "malignant_prob" in reply
conn.close()
server.stop()

# %%  Stage 6 — categorical features (categoricalSlotIndexes)
# Category CODES are not ordered quantities: a many-vs-many split tests
# set membership in one node where numerical thresholds need many cuts.
rs = np.random.default_rng(1)
n = 2000
city = rs.integers(0, 20, n).astype(np.float32)
risk_cities = {2, 3, 5, 7, 11, 13, 17}
yc = np.isin(city, list(risk_cities)).astype(np.int32)
cat_df = st.DataFrame.from_rows(
    [{"features": np.array([city[i], rs.normal()], np.float32),
      "label": int(yc[i])} for i in range(n)])
cat_model = LightGBMClassifier(num_iterations=4, learning_rate=0.5,
                               num_leaves=7, min_data_in_leaf=5,
                               categorical_slot_indexes=[0]).fit(cat_df)
cat_out = cat_model.transform(cat_df)
cat_acc = float(np.mean(cat_out.collect_column("prediction")
                        == cat_out.collect_column("label")))
print("categorical membership learned in 4 tiny trees:", cat_acc)
assert cat_acc > 0.97
# %%  Stage 7 — continued training (the modelString surface)
# New data arrives after deployment: resume boosting FROM the shipped model
# instead of retraining from scratch; the continued model contains the old
# trees plus the new ones.
from synapseml_tpu.gbdt.booster import train_booster

first = model.get_booster()
n_prev = first.best_iteration or first.num_iterations
X_tr = data.data[tr].astype(np.float32)
y_tr = data.target[tr].astype(np.float32)
cont = train_booster(X_tr, y_tr, objective="binary",
                     num_iterations=10, learning_rate=0.1, num_leaves=15,
                     init_model=first)
print("continued:", n_prev, "+ 10 =", cont.num_iterations, "trees")
assert cont.num_iterations == n_prev + 10

print("walkthrough complete: train -> explain -> persist -> serve -> "
      "categorical -> continue")
