# %% [markdown]
# # Walkthrough: every parallelism is a mesh axis
#
# The reference ships three per-engine communication stacks (LightGBM's
# socket ring, VW's spanning tree, horovod's ring-allreduce) and has no
# model parallelism at all. The TPU rebuild expresses EVERY parallelism as
# an axis of ONE `jax.sharding.Mesh`:
#
# | axis     | strategy                          | collective underneath |
# |----------|-----------------------------------|-----------------------|
# | `data`   | data parallelism                  | psum (gradients)      |
# | `fsdp`   | parameter sharding inside DP      | all-gather/reduce-scatter |
# | `tensor` | tensor (model) parallelism        | all-reduce per layer  |
# | `seq`    | sequence/context parallelism      | ppermute ring / all-to-all |
# | `expert` | mixture-of-experts dispatch       | all-to-all (GSPMD-derived) |
# | `pipe`   | pipeline (stage) parallelism      | ppermute hop per tick |
#
# This walkthrough drives each one on a virtual 8-device CPU mesh — the
# exact code runs unchanged on a TPU pod slice.

# %%  Setup: an 8-device mesh world
import jax
import jax.numpy as jnp
import numpy as np

from synapseml_tpu.parallel import MeshConfig, create_mesh

print("devices:", jax.device_count())

# %% [markdown]
# ## 1. Data + FSDP + tensor + sequence parallelism in one training step
#
# A composite mesh trains a BERT-tiny classifier with ring attention on the
# `seq` axis; GSPMD inserts every collective from the sharding annotations.

# %%
from synapseml_tpu.models.flax_nets.bert import BertClassifier, bert_tiny
from synapseml_tpu.models.trainer import Trainer, TrainerConfig

mesh = create_mesh(MeshConfig(data=1, fsdp=2, tensor=2, seq=2))
cfg = bert_tiny(n_layers=2, attn_impl="ring")
trainer = Trainer(BertClassifier(cfg, num_classes=2), mesh,
                  TrainerConfig(learning_rate=1e-3, total_steps=3))
rng = np.random.default_rng(0)
batch = {"input_ids": rng.integers(0, cfg.vocab_size, (16, 32)).astype(np.int32),
         "attention_mask": np.ones((16, 32), np.int32),
         "labels": rng.integers(0, 2, (16,)).astype(np.int32)}
state = trainer.init_state(batch)
for i in range(3):
    state, metrics = trainer.train_step(state, batch)
    print(f"composite-mesh step {i}: loss={float(metrics['loss']):.4f}")

# %% [markdown]
# ## 2. Expert parallelism: a switch-MoE encoder
#
# `moe_experts=2` swaps the dense MLP for a routed mixture; expert weights
# carry the `expert` logical axis, so on this mesh each device group holds
# one expert and tokens flow through GSPMD-derived all-to-alls. The router's
# load-balance aux loss is folded into the objective by the Trainer.

# %%
mesh_ep = create_mesh(MeshConfig(data=-1, expert=2))
cfg_moe = bert_tiny(n_layers=2, moe_experts=2, moe_top_k=2)
trainer = Trainer(BertClassifier(cfg_moe, num_classes=2), mesh_ep,
                  TrainerConfig(learning_rate=1e-3, total_steps=3))
state = trainer.init_state(batch)
for i in range(3):
    state, metrics = trainer.train_step(state, batch)
    print(f"expert-parallel step {i}: loss={float(metrics['loss']):.4f}")

# %% [markdown]
# ## 3. Pipeline parallelism: a GPipe schedule over the `pipe` axis
#
# Four MLP stages live on four devices; microbatch activations rotate one
# hop per tick via `ppermute`. The schedule is one `lax.scan`, so compile
# size is independent of both ring length and microbatch count — and it is
# differentiable, so the same primitive trains.

# %%
from synapseml_tpu.parallel import pipeline_sharded, stack_stage_params

mesh_pp = create_mesh(MeshConfig(data=2, pipe=4))
d, n_micro, mb = 8, 4, 2
stages = [{"w": jnp.asarray(rng.normal(size=(d, d)) * 0.4, jnp.float32),
           "b": jnp.zeros((d,), jnp.float32)} for _ in range(4)]
params = stack_stage_params(stages)
x = jnp.asarray(rng.normal(size=(n_micro, mb, d)), jnp.float32)
target = jnp.asarray(rng.normal(size=(n_micro, mb, d)), jnp.float32)


def stage_fn(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


@jax.jit
def pp_step(params):
    def loss(p):
        out = pipeline_sharded(mesh_pp, stage_fn, p, x)
        return jnp.mean((out - target) ** 2)

    l, g = jax.value_and_grad(loss)(params)
    return jax.tree.map(lambda a, b: a - 0.5 * b, params, g), l


for i in range(3):
    params, l = pp_step(params)
    print(f"pipeline step {i}: loss={float(l):.4f}")

# %% [markdown]
# Two more schedules behind the same call: `io="sharded"` keeps microbatch
# inputs AND outputs sharded over the pipe axis (per-device activation
# memory scales as 1/stages — the production layout), and `interleave=v`
# runs the circular schedule (stages round-robin across devices, bubble
# cut ~v-fold). Both match GPipe numerically:

# %%
out_gpipe = pipeline_sharded(mesh_pp, stage_fn, params, x)
out_shard = pipeline_sharded(mesh_pp, stage_fn, params, x, io="sharded")
import numpy as np

np.testing.assert_allclose(np.asarray(out_shard), np.asarray(out_gpipe),
                           rtol=1e-5, atol=1e-6)
print("io='sharded' == GPipe; per-device outputs are 1/stages of the batch")

# %% [markdown]
# ## 4. The point
#
# Six parallelisms, zero custom communication code: the mesh names the
# topology, sharding annotations name the placement, and XLA compiles the
# collectives (psum, all-gather, ppermute, all-to-all) onto ICI links. The
# reference needed a separate native networking stack per engine to get
# one of these (data parallelism).

print("walkthrough complete")
