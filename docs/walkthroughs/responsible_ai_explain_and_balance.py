# %% [markdown]
# # Walkthrough: Responsible AI — explain a trained model, audit the data
#
# The reference's responsible-AI tier (`docs/Explore Algorithms/Responsible AI/`)
# pairs model-agnostic explainers (`core/.../explainers/`) with data-balance
# measures (`core/.../exploratory/`). Same arc here: train a GBDT on real
# clinical data, explain individual predictions with KernelSHAP and LIME,
# chart a feature's marginal effect with ICE/PDP, then audit a dataset for
# representation imbalance before anyone trains on it.

# %%  Stage 1 — train the model to be explained (real data, held-out split)
import numpy as np
from sklearn.datasets import load_breast_cancer

import synapseml_tpu as st
from synapseml_tpu.gbdt import LightGBMClassifier

data = load_breast_cancer()
rs = np.random.default_rng(0)
order = rs.permutation(len(data.target))
tr, te = order[:400], order[400:]
train_df = st.DataFrame.from_rows(
    [{"features": data.data[i].astype(np.float32), "label": int(data.target[i])}
     for i in tr])
test_df = st.DataFrame.from_rows(
    [{"features": data.data[i].astype(np.float32), "label": int(data.target[i])}
     for i in te])
model = LightGBMClassifier(num_iterations=40, learning_rate=0.1,
                           num_leaves=15).fit(train_df)
acc = float(np.mean(
    model.transform(test_df).collect_column("prediction")
    == test_df.collect_column("label")))
print("held-out accuracy:", round(acc, 3))
assert acc > 0.9

# %%  Stage 2 — KernelSHAP: per-feature attribution for single predictions
# VectorSHAP perturbs the features vector against a background sample and
# fits the Shapley kernel regression; `explanation` is [targets, K+1] with
# phi0 (the background expectation) last. target_classes=[1] explains the
# malignant-class probability.
from synapseml_tpu.explainers import VectorSHAP

shap = VectorSHAP(model=model, target_col="probability", target_classes=[1],
                  num_samples=64, seed=0, background_data=train_df)
explained = shap.transform(test_df.limit(4))
probs = np.stack(list(model.transform(test_df.limit(4))
                      .collect_column("probability")))[:, 1]
for i, phi in enumerate(explained.collect_column("explanation")):
    phi = np.asarray(phi)[0]
    # efficiency axiom: contributions + base value reconstruct the output
    np.testing.assert_allclose(phi.sum(), probs[i], atol=0.05)
print("SHAP efficiency holds on", explained.count(), "explained rows")

# %%  Stage 3 — LIME: local surrogate coefficients
from synapseml_tpu.explainers import VectorLIME

lime = VectorLIME(model=model, target_col="probability", target_classes=[1],
                  num_samples=200, seed=0, regularization=1e-4,
                  background_data=train_df)
coefs = np.asarray(list(lime.transform(test_df.limit(2))
                        .collect_column("explanation"))[0])[0]
assert coefs.shape == (data.data.shape[1],)
print("LIME top features:",
      [data.feature_names[j] for j in np.argsort(-np.abs(coefs))[:3]])

# %%  Stage 4 — ICE / PDP: marginal effect of one feature
# ICETransformer sweeps named columns over a grid per instance (ICE) or
# averaged (PDP), routing every swept batch through the model exactly like
# the reference's ICETransformer (`core/.../explainers/ICETransformer.scala:126`).
# The GBDT model consumes an assembled `features` vector, so the scorer
# wrapped here assembles the per-feature columns first — the same
# columns-to-vector step `Featurize` does inside `TrainClassifier`.
from synapseml_tpu.core.pipeline import Transformer
from synapseml_tpu.explainers import ICETransformer

feat_cols = [str(n) for n in data.feature_names]


class AssembleAndScore(Transformer):
    def _transform(self, sdf):
        X = np.stack([np.asarray(sdf.collect_column(c), np.float32)
                      for c in feat_cols], axis=1)
        scored = model.transform(st.DataFrame.from_dict({"features": X}))
        return sdf.with_column(
            "probability", np.stack(list(scored.collect_column("probability"))))


test_cols = st.DataFrame.from_dict(
    {c: data.data[te[:20], j].astype(np.float32)
     for j, c in enumerate(feat_cols)})
top_feature = feat_cols[int(np.argmax(np.abs(coefs)))]
pdp = ICETransformer(model=AssembleAndScore(), target_col="probability",
                     target_classes=[1], numeric_features=[top_feature],
                     num_splits=8, kind="average").transform(test_cols)
curve = pdp.collect_rows()[0][f"{top_feature}_dependence"]
ys = [v[0] for v in curve.values()]          # class-1 probability per grid point
assert len(ys) >= 2
print(f"PDP range of '{top_feature}':", round(max(ys) - min(ys), 4))

# %%  Stage 5 — data balance: audit BEFORE training
# FeatureBalanceMeasure compares label rates across sensitive groups
# (parity gaps); DistributionBalanceMeasure compares the observed group
# distribution to uniform; AggregateBalanceMeasure summarizes into one
# number — the reference's exploratory tier (`exploratory/DataBalanceAnalysis`).
from synapseml_tpu.exploratory import (
    AggregateBalanceMeasure,
    DistributionBalanceMeasure,
    FeatureBalanceMeasure,
)

n = 2000
gender = rs.choice(["F", "M"], n, p=[0.3, 0.7])
label = (rs.random(n) < np.where(gender == "F", 0.35, 0.65)).astype(np.int64)
hiring = st.DataFrame.from_dict({"gender": gender.astype(object), "label": label})

fb = FeatureBalanceMeasure(sensitive_cols=["gender"]).transform(hiring)
gap = fb.collect_rows()[0]
print("statistical parity gap F vs M:", round(gap["dp"], 3))
assert abs(gap["dp"]) > 0.2          # the injected bias is detected

db = DistributionBalanceMeasure(sensitive_cols=["gender"]).transform(hiring)
print("KL from uniform:", round(db.collect_rows()[0]["kl_divergence"], 4))

ab = AggregateBalanceMeasure(sensitive_cols=["gender"]).transform(hiring)
print("aggregate (atkinson):", round(ab.collect_rows()[0]["atkinson_index"], 4))
print("walkthrough complete")
