# %% [markdown]
# # Walkthrough: CyberML — unsupervised access-anomaly detection
#
# The reference's Python-only CyberML tier
# (`core/src/main/python/synapse/ml/cyber/anomaly/collaborative_filtering.py:616`):
# learn per-tenant user/resource embeddings from WHO-accessed-WHAT logs
# (ALS-style collaborative filtering), score new accesses by how far they
# fall from the learned structure, and generate realistic negative samples
# with `ComplementAccessTransformer`. No labels anywhere — the signal is
# the access structure itself.

# %%  Stage 1 — simulate access logs: two departments, disjoint resources
import numpy as np

import synapseml_tpu as st
from synapseml_tpu.cyber import (
    AccessAnomaly,
    ComplementAccessTransformer,
    PartitionedStandardScaler,
)

rs = np.random.default_rng(0)
rows = {"tenant": [], "user": [], "res": []}
for _ in range(400):
    dept = int(rs.random() < 0.5)
    user = f"u{dept * 5 + rs.integers(0, 5)}"          # u0-u4 vs u5-u9
    res = f"r{dept * 6 + rs.integers(0, 6)}"           # r0-r5 vs r6-r11
    rows["tenant"].append("contoso")
    rows["user"].append(user)
    rows["res"].append(res)
df = st.DataFrame.from_dict({k: np.asarray(v, dtype=object)
                             for k, v in rows.items()})
print("access events:", df.count())

# %%  Stage 2 — fit the anomaly model (per-tenant collaborative filtering)
model = AccessAnomaly(tenant_col="tenant", rank=6, max_iter=10, seed=1).fit(df)

# %%  Stage 3 — score accesses: in-department vs cross-department
test = st.DataFrame.from_dict({
    "tenant": np.asarray(["contoso"] * 4, dtype=object),
    "user": np.asarray(["u0", "u0", "u7", "u7"], dtype=object),
    "res": np.asarray(["r0", "r9", "r9", "r2"], dtype=object)})
scores = model.transform(test).collect_column("anomaly_score")
print("u0->r0 (normal):   ", round(float(scores[0]), 3))
print("u0->r9 (CROSS):    ", round(float(scores[1]), 3))
print("u7->r9 (normal):   ", round(float(scores[2]), 3))
print("u7->r2 (CROSS):    ", round(float(scores[3]), 3))
assert scores[1] > scores[0] + 0.5     # cross-department access flags higher
assert scores[3] > scores[2] + 0.5

# %%  Stage 4 — ComplementAccessTransformer: principled negative sampling
# Emits (tenant, user, res) triples that were NEVER observed — the
# complement of the access set — for evaluating or calibrating detectors.
comp = ComplementAccessTransformer(tenant_col="tenant", factor=1, seed=0)
negatives = comp.transform(df)
seen = set(zip(df.collect_column("tenant"), df.collect_column("user"),
               df.collect_column("res")))
for row in negatives.collect_rows():
    assert (row["tenant"], row["user"], row["res"]) not in seen
neg_scores = model.transform(negatives).collect_column("anomaly_score")
obs_scores = model.transform(df).collect_column("anomaly_score")
print("mean score — observed:", round(float(np.mean(obs_scores)), 3),
      "| never-observed:", round(float(np.nanmean(neg_scores)), 3))
assert np.nanmean(neg_scores) > np.mean(obs_scores)

# %%  Stage 5 — per-tenant feature scaling for downstream pipelines
scored_df = model.transform(df)
scaled = PartitionedStandardScaler(tenant_col="tenant",
                                   input_col="anomaly_score").fit(
    scored_df).transform(scored_df)
vals = np.asarray(scaled.collect_column("scaled"))
print("scaled mean/std:", round(float(vals.mean()), 4),
      round(float(vals.std()), 4))
assert abs(vals.mean()) < 1e-6 and abs(vals.std() - 1.0) < 1e-6
print("walkthrough complete")
