# %% [markdown]
# # Walkthrough: distributed training, async checkpoints, resume
#
# The full fault-tolerant training arc on a composite mesh: train with
# dp x fsdp x tensor shardings, checkpoint asynchronously every N steps,
# "lose the job", and resume from the latest checkpoint on a FRESH
# trainer — continuing exactly where training stopped.

# %%  Stage 1 — train on a composite mesh with async checkpoints
import tempfile

import numpy as np

from synapseml_tpu.models.flax_nets.bert import BertClassifier, bert_tiny
from synapseml_tpu.models.trainer import Trainer, TrainerConfig
from synapseml_tpu.parallel import (AsyncCheckpointer, MeshConfig,
                                    create_mesh, latest_step,
                                    restore_checkpoint)

mesh = create_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
print("mesh axes:", {k: v for k, v in mesh.axis_sizes.items() if v > 1})

cfg = bert_tiny(n_layers=2)
model = BertClassifier(cfg, num_classes=2)
rs = np.random.default_rng(0)
batch = {
    "input_ids": rs.integers(0, cfg.vocab_size, (16, 32)).astype(np.int32),
    "attention_mask": np.ones((16, 32), np.int32),
    "labels": rs.integers(0, 2, (16,)).astype(np.int32),
}

ckpt_dir = tempfile.mkdtemp()
tr = Trainer(model, mesh, TrainerConfig(learning_rate=1e-3, total_steps=10))
state = tr.init_state(batch)
losses = []
with AsyncCheckpointer(ckpt_dir, keep=2) as ck:
    for step in range(1, 7):
        state, m = tr.train_step(state, batch)
        losses.append(float(m["loss"]))
        if step % 2 == 0:
            # non-blocking: device->host copy is dispatched async, the
            # write happens on a worker thread, max one write in flight
            ck.save({"params": state.params, "opt_state": state.opt_state,
                     "step": np.int32(step)}, step)
print("losses:", [round(l, 4) for l in losses])
print("checkpoints kept (top-2 retention):", latest_step(ckpt_dir))
assert latest_step(ckpt_dir) == 6

# %%  Stage 2 — the job "dies"; resume on a FRESH trainer
restored = restore_checkpoint(ckpt_dir)
tr2 = Trainer(model, mesh, TrainerConfig(learning_rate=1e-3, total_steps=10))
state2 = tr2.resume_state(restored["params"], restored["opt_state"],
                          step=int(np.asarray(restored["step"])))
assert int(state2.step) == 6

# %%  Stage 3 — training CONTINUES (same batch keeps improving the loss)
cont = []
for _ in range(3):
    state2, m = tr2.train_step(state2, batch)
    cont.append(float(m["loss"]))
print("resumed losses:", [round(l, 4) for l in cont])
assert cont[-1] < losses[-1], (cont, losses)
assert int(state2.step) == 9
print("walkthrough complete: train -> async checkpoint -> resume -> improve")
