# %% [markdown]
# # Walkthrough: long context via sequence parallelism
#
# Long sequences don't fit one device's attention: the framework ships TWO
# sequence-parallel strategies over the mesh `seq` axis — **ring attention**
# (ppermute ring, bounded memory, exact) and **Ulysses** (all-to-all head
# scatter) — behind one switch. This runs both on an 8-device mesh and
# checks they agree with plain attention, then trains through ring.

# %%  Stage 1 — a seq-sharded mesh
import numpy as np

from synapseml_tpu.ops.attention import reference_attention
from synapseml_tpu.ops.ring_attention import ring_attention_sharded
from synapseml_tpu.ops.ulysses_attention import ulysses_attention_sharded
from synapseml_tpu.parallel import MeshConfig, create_mesh

mesh = create_mesh(MeshConfig(data=2, seq=4))
print("mesh axes:", {k: v for k, v in mesh.axis_sizes.items() if v > 1})

# %%  Stage 2 — both strategies match plain attention (causal + masked)
B, T, H, D = 2, 512, 8, 32
rs = np.random.default_rng(0)
q, k, v = (rs.normal(size=(B, T, H, D)).astype(np.float32) for _ in range(3))
mask = np.ones((B, T), bool)
mask[1, T // 2:] = False  # padded tail on one row

want = np.asarray(reference_attention(q, k, v, kv_mask=mask, causal=True))
ring = np.asarray(ring_attention_sharded(mesh, q, k, v, kv_mask=mask,
                                         causal=True))
ulys = np.asarray(ulysses_attention_sharded(mesh, q, k, v, kv_mask=mask,
                                            causal=True, local_impl="einsum"))
np.testing.assert_allclose(ring, want, rtol=2e-4, atol=2e-5)
np.testing.assert_allclose(ulys, want, rtol=2e-4, atol=2e-5)
print("ring + ulysses agree with reference attention at T =", T)

# %%  Stage 3 — train THROUGH ring attention (the attn_impl switch)
from synapseml_tpu.models.flax_nets.bert import BertClassifier, bert_tiny
from synapseml_tpu.models.trainer import Trainer, TrainerConfig

cfg = bert_tiny(n_layers=2, attn_impl="ring")
model = BertClassifier(cfg, num_classes=2)
batch = {
    "input_ids": rs.integers(0, cfg.vocab_size, (8, 128)).astype(np.int32),
    "attention_mask": np.ones((8, 128), np.int32),
    "labels": rs.integers(0, 2, (8,)).astype(np.int32),
}
tr = Trainer(model, mesh, TrainerConfig(learning_rate=1e-3, total_steps=4))
state = tr.init_state(batch)
losses = []
for _ in range(4):
    state, m = tr.train_step(state, batch)
    losses.append(float(m["loss"]))
print("losses through ring attention:", [round(l, 4) for l in losses])
assert losses[-1] < losses[0]
print("walkthrough complete: two strategies, one switch, training works")
