# %% [markdown]
# # Unsupervised anomaly detection with IsolationForest
# Isolation forests score anomalies by how FEW random splits isolate a
# point (reference: `isolationforest/` wrapping LinkedIn's isolation-forest;
# here the ensemble is built with vectorized numpy and scored with batched
# JAX path-length evaluation — `synapseml_tpu/isolationforest/`). Shorter
# isolation path -> higher anomaly score.

# %%
import numpy as np

import synapseml_tpu as st
from synapseml_tpu.isolationforest import IsolationForest

rs = np.random.default_rng(0)
normal = rs.normal(0.0, 1.0, size=(400, 4)).astype(np.float32)
outliers = rs.uniform(6.0, 9.0, size=(8, 4)).astype(np.float32)
X = np.vstack([normal, outliers])
df = st.DataFrame.from_dict({"features": X})

# %% [markdown]
# ## Fit and score
# `contamination` sets the expected anomaly fraction; the model calibrates
# its label threshold so roughly that fraction of TRAINING points flag.

# %%
forest = IsolationForest(num_estimators=100, max_samples=128.0,
                         contamination=0.02, random_seed=7)
model = forest.fit(df)
scored = model.transform(df)
scores = np.asarray(scored.collect_column("outlierScore"), np.float64)
labels = np.asarray(scored.collect_column("predictedLabel"), np.int64)
print("mean score (normal):", float(scores[:400].mean()))
print("mean score (outlier):", float(scores[400:].mean()))
assert scores[400:].mean() > scores[:400].mean()

# %% [markdown]
# ## The planted outliers dominate the flagged set

# %%
flagged = np.nonzero(labels == 1)[0]
print("flagged rows:", flagged[:12], "... total", len(flagged))
caught = np.intersect1d(flagged, np.arange(400, 408))
print(f"planted outliers caught: {len(caught)}/8")
assert len(caught) >= 6

# %% [markdown]
# ## Models persist like every stage

# %%
import tempfile

with tempfile.TemporaryDirectory() as d:
    model.save(d + "/iforest")
    from synapseml_tpu.core.serialization import load_stage

    re_scores = np.asarray(load_stage(d + "/iforest").transform(df)
                           .collect_column("outlierScore"), np.float64)
np.testing.assert_allclose(re_scores, scores)
print("save/load round-trip OK")
