# %% [markdown]
# # Walkthrough: causal inference — from naive bias to defensible effects
#
# The reference's causal tier (`core/.../causal/`: `DoubleMLEstimator:63`,
# diff-in-diff family, synthetic control with its constrained optimizer)
# answers "what did the treatment DO", not "what correlates". This
# walkthrough runs the full progression on simulated data where the true
# effect is known: show the naive estimate is wrong, fix it with DoubleML
# (using the framework's own GBDT as nuisance learners), localize the
# effect with OrthoForest, then switch to panel methods (diff-in-diff,
# synthetic control) for the aggregate-units case.

# %%  Stage 1 — simulate confounded observational data (true ATE = 2.0)
import numpy as np

import synapseml_tpu as st
from synapseml_tpu.causal import (
    DiffInDiffEstimator,
    DoubleMLEstimator,
    OrthoForestDMLEstimator,
    SyntheticControlEstimator,
    SyntheticDiffInDiffEstimator,
)
from synapseml_tpu.gbdt import LightGBMRegressor

TAU = 2.0
rs = np.random.default_rng(0)
n = 800
X = rs.normal(size=(n, 3))
treatment = X @ np.asarray([1.0, -0.5, 0.2]) + 0.5 * rs.normal(size=n)
outcome = TAU * treatment + X @ np.asarray([2.0, 1.0, -1.0]) + 0.5 * rs.normal(size=n)
df = st.DataFrame.from_dict({"features": X.astype(np.float32),
                             "treatment": treatment, "outcome": outcome})

# the naive regression of outcome on treatment absorbs the confounders
naive = float((treatment @ outcome) / (treatment @ treatment))
print("naive estimate:", round(naive, 3), "(true effect is", TAU, ")")
assert abs(naive - TAU) > 0.5

# %%  Stage 2 — DoubleML: orthogonalized ATE with GBDT nuisance models
# Both nuisance regressions (outcome ~ X, treatment ~ X) are fit by the
# framework's own TPU GBDT engine with cross-fitting sample splits, the
# reference's `DoubleMLEstimator.scala:63` recipe.
dml = DoubleMLEstimator(
    outcome_model=LightGBMRegressor(label_col="outcome", num_iterations=30,
                                    num_leaves=15),
    treatment_model=LightGBMRegressor(label_col="treatment", num_iterations=30,
                                      num_leaves=15),
    max_iter=5, seed=1)
model = dml.fit(df)
ate = model.get_avg_treatment_effect()
lo, hi = model.get_confidence_interval()
print(f"DoubleML ATE: {ate:.3f}  95% CI [{lo:.3f}, {hi:.3f}]")
assert abs(ate - TAU) < 0.3
assert lo <= ate <= hi

# %%  Stage 3 — heterogeneous effects: OrthoForest CATE
# True effect differs by segment (3.0 where h>0, 1.0 where h<=0); the
# orthogonalized forest recovers the segment-level effects.
h = rs.uniform(-1, 1, n)
tau_i = np.where(h > 0, 3.0, 1.0)
y_het = tau_i * treatment + X @ np.asarray([1.0, 1.0, 0.0]) + 0.3 * rs.normal(size=n)
df_het = st.DataFrame.from_dict({"features": X.astype(np.float32), "h": h,
                                 "treatment": treatment, "outcome": y_het})
forest = OrthoForestDMLEstimator(
    outcome_model=LightGBMRegressor(label_col="outcome", num_iterations=20),
    treatment_model=LightGBMRegressor(label_col="treatment", num_iterations=20),
    heterogeneity_cols=["h"], num_trees=10, max_depth=2,
    min_samples_leaf=20, seed=0).fit(df_het)
eff = forest.transform(df_het).collect_column("effect")
print("CATE | h>0.3:", round(float(eff[h > 0.3].mean()), 2),
      " | h<-0.3:", round(float(eff[h < -0.3].mean()), 2))
assert abs(eff[h > 0.3].mean() - 3.0) < 0.6
assert abs(eff[h < -0.3].mean() - 1.0) < 0.6

# %%  Stage 4 — panel data: diff-in-diff (true effect = 2.5)
n2 = 2000
treat = rs.integers(0, 2, n2).astype(float)
post = rs.integers(0, 2, n2).astype(float)
y_did = 1.0 + 0.5 * treat + 1.5 * post + 2.5 * treat * post \
    + 0.1 * rs.normal(size=n2)
did_df = st.DataFrame.from_dict({"outcome": y_did, "treatment": treat,
                                 "postTreatment": post})
did = DiffInDiffEstimator().fit(did_df)
print("DiD effect:", round(did.get_treatment_effect(), 3),
      "SE:", round(did.get("standard_error"), 4))
assert abs(did.get_treatment_effect() - 2.5) < 0.1

# %%  Stage 5 — one treated unit: synthetic control (true effect = 4.0)
# A weighted combination of donor units reconstructs the treated unit's
# pre-period; the post-period gap is the effect. Weights live on the
# simplex via the mirror-descent solver (`causal/opt/MirrorDescent.scala`).
T = 12
base = rs.normal(size=(10, 1)) * 2 + rs.normal(size=(10, T)) * 0.1 \
    + np.linspace(0, 1, T)[None, :] * rs.uniform(0.5, 2, (10, 1))
treated_series = 0.6 * base[0] + 0.4 * base[1] + 4.0 * (np.arange(T) >= 7)
rows = {"unit": [], "time": [], "outcome": [], "treatment": [],
        "postTreatment": []}
for u in range(10):
    for t in range(T):
        rows["unit"].append(f"c{u}"); rows["time"].append(t)
        rows["outcome"].append(base[u, t]); rows["treatment"].append(0.0)
        rows["postTreatment"].append(float(t >= 7))
for t in range(T):
    rows["unit"].append("treated"); rows["time"].append(t)
    rows["outcome"].append(treated_series[t]); rows["treatment"].append(1.0)
    rows["postTreatment"].append(float(t >= 7))
panel = st.DataFrame.from_dict({k: np.asarray(v) for k, v in rows.items()})

sc = SyntheticControlEstimator(unit_col="unit", time_col="time").fit(panel)
w = np.asarray(sc.get("unit_weights"))
print("synthetic-control effect:", round(sc.get_treatment_effect(), 3),
      "| donor mass on true donors:", round(float(w[0] + w[1]), 3))
assert abs(sc.get_treatment_effect() - 4.0) < 0.4

sdid = SyntheticDiffInDiffEstimator(unit_col="unit", time_col="time").fit(panel)
print("synthetic-DiD effect:", round(sdid.get_treatment_effect(), 3))
assert abs(sdid.get_treatment_effect() - 4.0) < 0.5
print("walkthrough complete")
