# %% [markdown]
# # Nearest-neighbor search with KNN and ConditionalKNN
# Brute-force exact KNN as one MXU matmul (reference: `nn/` ball-tree —
# redesigned per SURVEY §7.8: on TPU a `[Q, N]` distance matrix from a
# single `queries @ index.T` beats tree traversal by orders of magnitude
# for the N these estimators serve). `ConditionalKNN` filters matches by a
# per-query label set BEFORE ranking — the "find similar items of THIS
# kind" query.

# %%
import numpy as np

import synapseml_tpu as st
from synapseml_tpu.nn import KNN, ConditionalKNN

rs = np.random.default_rng(0)
N, d = 500, 16
index_vecs = rs.normal(size=(N, d)).astype(np.float32)
df = st.DataFrame.from_dict({"features": index_vecs,
                             "values": np.arange(N)})

# %% [markdown]
# ## Fit = load the index; transform = batched matmul search

# %%
model = KNN(k=5).fit(df)
queries = index_vecs[:3] + rs.normal(0, 0.01, (3, d)).astype(np.float32)
out = model.transform(st.DataFrame.from_dict({"features": queries}))
for i, matches in enumerate(out.collect_column("output")):
    ids = [m["value"] for m in matches]
    print(f"query {i}: neighbors {ids}, "
          f"top distance {matches[0]['distance']:.4f}")
    assert ids[0] == i  # a near-copy of row i finds row i first

# %% [markdown]
# ## Conditional search: restrict candidates per query
# Each query row carries the set of labels it may match; candidates outside
# the set never enter the ranking.

# %%
labels = np.asarray(["red", "green", "blue", "gold"] * (N // 4))
cdf = st.DataFrame.from_dict({"features": index_vecs,
                              "values": np.arange(N), "labels": labels})
cmodel = ConditionalKNN(k=4).fit(cdf)
conds = np.empty(2, dtype=object)
conds[0], conds[1] = ["red"], ["green", "blue"]
cout = cmodel.transform(st.DataFrame.from_dict(
    {"features": queries[:2], "conditioner": conds}))
for i, matches in enumerate(cout.collect_column("output")):
    found = {m["label"] for m in matches}
    print(f"query {i}: allowed {conds[i]}, found labels {found}")
    assert found <= set(conds[i])

# %% [markdown]
# Exactness check against numpy — no approximation anywhere:

# %%
d2 = ((queries[:, None, :] - index_vecs[None, :, :]) ** 2).sum(-1)
for i, matches in enumerate(out.collect_column("output")):
    expect = set(np.argsort(d2[i], kind="stable")[:5].tolist())
    assert {m["value"] for m in matches} == expect
print("matches == numpy brute force")
