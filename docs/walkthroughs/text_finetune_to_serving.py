# %% [markdown]
# # Walkthrough: fine-tune a text classifier, checkpoint it, serve it
#
# The reference's deep-learning arc (`DeepTextClassifier` fine-tune with
# pytorch-lightning checkpointing, then Spark Serving deployment) as one
# TPU-native flow: GSPMD fine-tune -> async checkpoints -> resume ->
# HTTP serving.

# %%  Stage 1 — fine-tune with async checkpointing
import http.client
import json
import tempfile

import numpy as np

import synapseml_tpu as st
from synapseml_tpu.models import DeepTextClassifier

POS = ["an outstanding, joyful film", "brilliant and moving", "a delight",
       "funny, warm, wonderful"]
NEG = ["tedious and painfully dull", "a disaster", "awful script",
       "boring beyond belief"]
rows = [{"text": t, "label": 1} for t in POS] * 8 + \
       [{"text": t, "label": 0} for t in NEG] * 8
df = st.DataFrame.from_rows(rows, num_partitions=4)

ckpt_dir = tempfile.mkdtemp()
est = DeepTextClassifier(checkpoint="bert-tiny", num_classes=2, batch_size=8,
                         max_token_len=16, max_steps=24, learning_rate=3e-3,
                         checkpoint_dir=ckpt_dir, checkpoint_every=8)
model = est.fit(df)
acc = float(np.mean(model.transform(df).collect_column("prediction")
                    == model.transform(df).collect_column("label")))
print("train accuracy:", acc)
assert acc > 0.9

# %%  Stage 2 — the async checkpoints are restorable mid-run state
from synapseml_tpu.parallel import latest_step, restore_checkpoint

step = latest_step(ckpt_dir)
restored = restore_checkpoint(ckpt_dir)
print("checkpoints up to step", step,
      "| restored keys:", sorted(restored))
assert step == 24 and "params" in restored

# %%  Stage 3 — serve the fine-tuned model over HTTP
from synapseml_tpu.core.pipeline import Transformer
from synapseml_tpu.io import serve_pipeline


class TextScorer(Transformer):
    def _transform(self, sdf):
        def per_part(p):
            texts = [(b or {}).get("text", "") for b in p["body"]]
            inner = st.DataFrame.from_rows([{"text": t} for t in texts])
            scored = model.transform(inner)
            pred = scored.collect_column("prediction")
            out = dict(p)
            out["reply"] = np.asarray(
                [{"sentiment": "pos" if int(c) == 1 else "neg"} for c in pred],
                dtype=object)
            return out

        return sdf.map_partitions(per_part)


server = serve_pipeline(TextScorer(), batch_interval_ms=0)
host, port = server.address.split("//")[1].split(":")
conn = http.client.HTTPConnection(host, int(port), timeout=60)
# bert-tiny from random init in 24 steps memorizes, it does not generalize —
# serve the training phrases; the point here is the serving arc
for text, want in (("brilliant and moving", "pos"),
                   ("tedious and painfully dull", "neg")):
    conn.request("POST", "/", body=json.dumps({"text": text}).encode())
    r = conn.getresponse()
    reply = json.loads(r.read())
    print(f"{text!r} ->", reply)
    assert reply["sentiment"] == want
conn.close()
server.stop()
print("walkthrough complete: fine-tune -> checkpoint -> serve")
