# %% [markdown]
# # Spatial-transformer ONNX inference: GridSample through a real export
# Detection and spatial-transformer models lean on sampling ops
# (`GridSample`, `RoiAlign`) that many converters skip. Here a torch module
# that warps its input through a learned affine grid exports to ONNX and
# converts to JAX with exact parity — the whole pipeline `torch.onnx.export
# -> convert_graph -> jit` in a few lines.

# %%
import io

import numpy as np
import torch
import torch.nn.functional as F
from torch import nn


class WarpNet(nn.Module):
    """Predict an affine warp from pooled features, sample the input
    through it, then score the warped image — the spatial-transformer
    pattern (Jaderberg et al.)."""

    def __init__(self):
        super().__init__()
        self.conv = nn.Conv2d(1, 8, 3, padding=1)
        self.loc = nn.Linear(8, 6)
        # initialize to the identity transform
        self.loc.weight.data.zero_()
        self.loc.bias.data.copy_(
            torch.tensor([1, 0, 0, 0, 1, 0], dtype=torch.float32))
        self.head = nn.Linear(8, 4)

    def forward(self, x):
        h = torch.relu(self.conv(x))
        pooled = h.mean(dim=(2, 3))
        theta = self.loc(pooled).view(-1, 2, 3)
        grid = F.affine_grid(theta, x.shape, align_corners=False)
        warped = F.grid_sample(x, grid, mode="bilinear",
                               padding_mode="zeros", align_corners=False)
        hw = torch.relu(self.conv(warped))
        return self.head(hw.mean(dim=(2, 3)))


# this environment has no `onnx` package — torch's exporter imports it only
# to scan for custom onnxscript functions, so a shim backed by our own
# protobuf codec suffices (the conversion below never needs onnx either)
import sys
import types

if "onnx" not in sys.modules:
    from synapseml_tpu.onnx.proto import parse_model

    shim = types.ModuleType("onnx")
    shim.load_model_from_string = lambda b: type(
        "M", (), {"graph": parse_model(b).graph, "functions": []})()
    sys.modules["onnx"] = shim

torch.manual_seed(0)
model = WarpNet().eval()
x = torch.randn(2, 1, 12, 12)
buf = io.BytesIO()
torch.onnx.export(model, (x,), buf, dynamo=False, opset_version=20,
                  input_names=["image"], output_names=["logits"])
print("exported", len(buf.getvalue()), "bytes")

# %% [markdown]
# ## Convert and run under jit
# `convert_graph` lowers the whole graph — affine-grid arithmetic,
# `GridSample`, convs, the head — into one jittable JAX function.

# %%
import jax

from synapseml_tpu.onnx import convert_graph

conv = convert_graph(buf.getvalue())
fn = jax.jit(lambda t: conv(image=t)["logits"])
got = np.asarray(fn(x.numpy()))
with torch.no_grad():
    want = model(x).numpy()
np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
print("parity vs torch:", np.abs(got - want).max())

# %% [markdown]
# ## Serve it like any model
# Wrap the converted graph in `ONNXModel` for the DataFrame surface.

# %%
import synapseml_tpu as st
from synapseml_tpu.onnx import ONNXModel

om = ONNXModel(model_payload=buf.getvalue(),
               feed_dict={"image": "image"},
               fetch_dict={"logits": "logits"})
df = st.DataFrame.from_dict({"image": [x.numpy()[0], x.numpy()[1]]})
out = om.transform(df).collect_column("logits")
np.testing.assert_allclose(np.stack(out), want, rtol=1e-4, atol=1e-4)
print("ONNXModel rows:", len(out), "cols:", out[0].shape)
