# %% [markdown]
# # LightGBM on real data: held-out AUC + cross-engine parity + interop
# Trains the histogram GBDT on real clinical data (sklearn's bundled
# breast-cancer corpus), evaluates on a held-out split, compares against an
# independent engine (sklearn HistGradientBoosting), and round-trips the
# model through LightGBM's own `model.txt` text format (the reference's
# `saveNativeModel`, `booster/LightGBMBooster.scala:458`).

# %%
import numpy as np
from sklearn.datasets import load_breast_cancer

from synapseml_tpu.gbdt import parse_lightgbm_string, to_lightgbm_string
from synapseml_tpu.gbdt.booster import train_booster

d = load_breast_cancer()
X, y = d.data.astype(np.float32), d.target.astype(np.float32)
rs = np.random.default_rng(7)
idx = rs.permutation(len(y))
k = int(len(y) * 0.75)
Xtr, ytr, Xte, yte = X[idx[:k]], y[idx[:k]], X[idx[k:]], y[idx[k:]]

booster = train_booster(Xtr, ytr, objective="binary", num_iterations=60,
                        learning_rate=0.1, num_leaves=15, seed=0)

from scipy.stats import rankdata


def auc(scores, labels):
    ranks = rankdata(scores)
    pos = labels == 1
    n1, n0 = pos.sum(), (~pos).sum()
    return float((ranks[pos].sum() - n1 * (n1 + 1) / 2) / (n1 * n0))


ours = auc(booster.predict(Xte).ravel(), yte)
print("held-out AUC:", round(ours, 4))
assert ours > 0.96

# %% [markdown]
# Cross-engine parity: an independent histogram-GBDT implementation with the
# same capacity reaches the same AUC on the same split.

# %%
from sklearn.ensemble import HistGradientBoostingClassifier

h = HistGradientBoostingClassifier(max_iter=60, learning_rate=0.1,
                                   max_leaf_nodes=15, random_state=0).fit(Xtr, ytr)
theirs = auc(h.predict_proba(Xte)[:, 1], yte)
print("sklearn HGB AUC:", round(theirs, 4))
assert ours >= theirs - 0.02

# %% [markdown]
# Interop: export to LightGBM's text format, re-import, identical scores.

# %%
imported = parse_lightgbm_string(to_lightgbm_string(booster))
np.testing.assert_allclose(imported.raw_score(Xte[:50]),
                           booster.raw_score(Xte[:50]), rtol=1e-5, atol=1e-5)
print("model.txt round-trip: scores identical")
