# %% [markdown]
# # Cognitive services as pipeline stages (offline demo)
# Service transformers build authenticated requests per row, send them
# through the shared async HTTP client, and parse replies into columns —
# including long-running operations (202 + poll). This demo serves a tiny
# in-process mock so it runs with zero network egress; point `url=` at the
# real Azure endpoint in production.

# %%
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class Mock(BaseHTTPRequestHandler):
    polls = {}

    def log_message(self, *a):
        pass

    def _json(self, payload, status=200, headers=None):
        body = json.dumps(payload).encode()
        self.send_response(status)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        p = self.path.split("?")[0]
        if p == "/language/analyze-text/jobs":  # LRO: accept, hand back a poll URL
            return self._json({}, 202, {"Operation-Location":
                f"http://{self.headers['Host']}/language/analyze-text/jobs/j1"})
        if p == "/translate":
            return self._json([{"translations": [{"text": "hola mundo"}]}])
        return self._json({}, 404)

    def do_GET(self):
        n = Mock.polls.get("j1", 0)
        Mock.polls["j1"] = n + 1
        if n < 1:  # first poll: still running
            return self._json({"status": "running"})
        return self._json({"status": "succeeded", "tasks": {"items": [{
            "results": {"documents": [{"id": "0",
                                       "redactedText": "call me at ****"}]}}]}})


srv = ThreadingHTTPServer(("127.0.0.1", 0), Mock)
threading.Thread(target=srv.serve_forever, daemon=True).start()
URL = f"http://127.0.0.1:{srv.server_address[1]}"

# %% [markdown]
# PII redaction is a long-running job: the transformer POSTs the document,
# polls the operation, and lands the redacted text in a column.

# %%
import synapseml_tpu as st
from synapseml_tpu.services import AnalyzeTextLRO, Translate

df = st.DataFrame.from_dict({"text": ["call me at 555-0100"]})
pii = AnalyzeTextLRO(url=URL, subscription_key="demo-key",
                     kind="PiiEntityRecognition", polling_interval_s=0.01)
out = pii.transform(df)
print("redacted:", out.collect_column("analysis")[0]["redactedText"])

# %%
tr = Translate(url=URL, subscription_key="demo-key", to_language="es")
print("translated:", tr.transform(df).collect_column("translation")[0])
srv.shutdown()
