# %% [markdown]
# # Batch LLM inference with sampling over a device mesh
# `HuggingFaceCausalLM` (the reference's `HuggingFaceCausalLMTransform`) runs
# prefill + KV-cache decode as one jitted program. Decoding is greedy by
# default; `do_sample` enables on-device temperature/top-k/nucleus sampling
# with a reproducible seed. `mesh_config` shards the weights over
# tensor/fsdp axes for models that don't fit one chip (the Llama-2-7B path).

# %%
import numpy as np

import synapseml_tpu as st
from synapseml_tpu.hf import HuggingFaceCausalLM

df = st.DataFrame.from_dict({"prompt": [
    "the mesh shards the weights",
    "collectives ride the ici links",
    "one compiled program per bucket",
]})

greedy = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=8,
                             prompt_bucket=8, batch_size=4)
g = [np.asarray(x) for x in greedy.transform(df).collect_column("completions")]
print("greedy tokens:", g[0])

# %% [markdown]
# Sampling: same seed -> same completions; different seed -> different.

# %%
sampler = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=8,
                              prompt_bucket=8, batch_size=4, do_sample=True,
                              temperature=0.9, top_p=0.95, seed=7)
s1 = [np.asarray(x) for x in sampler.transform(df).collect_column("completions")]
s2 = [np.asarray(x) for x in sampler.transform(df).collect_column("completions")]
assert all(np.array_equal(a, b) for a, b in zip(s1, s2))
sampler.set(seed=8)
s3 = [np.asarray(x) for x in sampler.transform(df).collect_column("completions")]
assert any(not np.array_equal(a, b) for a, b in zip(s1, s3))
print("sampled tokens (seed 7):", s1[0])

# %% [markdown]
# Sharded batch inference: weights distribute over the mesh; outputs match
# the unsharded run exactly.

# %%
from synapseml_tpu.parallel import MeshConfig

sharded = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=8,
                              prompt_bucket=8, batch_size=4,
                              mesh_config=MeshConfig(data=2, fsdp=2, tensor=2))
sh = [np.asarray(x) for x in sharded.transform(df).collect_column("completions")]
assert all(np.array_equal(a, b) for a, b in zip(g, sh))
print("sharded == unsharded:", True)
