# %% [markdown]
# # Multi-chip training: mesh, shardings, ring attention
# One `MeshConfig` drives every parallelism axis (data/fsdp/tensor/seq/expert);
# estimators take `mesh_config=` and the GSPMD compiler inserts the
# collectives the reference implemented three ways (LightGBM socket ring,
# VW spanning tree, horovod allreduce). This example runs on a virtual
# 8-device CPU mesh; the same code drives a TPU pod slice.

# %%
import jax

if jax.default_backend() == "cpu" and jax.device_count() < 8:
    raise SystemExit("run with XLA_FLAGS=--xla_force_host_platform_device_count=8")

import numpy as np

import synapseml_tpu as st
from synapseml_tpu.models import DeepTextClassifier
from synapseml_tpu.parallel import MeshConfig

rows = [{"text": "good fine great", "label": 1},
        {"text": "bad poor awful", "label": 0}] * 16
df = st.DataFrame.from_rows(rows)

# dp x fsdp x tp: 2 * 2 * 2 = 8 devices; attn_impl="ring" adds sequence
# parallelism when the mesh has a seq axis
model = DeepTextClassifier(
    checkpoint="bert-tiny", num_classes=2, batch_size=8, max_token_len=16,
    max_steps=10, learning_rate=3e-3,
    mesh_config=MeshConfig(data=-1, fsdp=2, tensor=2)).fit(df)
out = model.transform(df)
print("predictions ok:", out.count())
assert out.count() == 32

# long-context: ring attention over a seq axis, no O(T^2) score buffer
from synapseml_tpu.ops import ring_attention_sharded
from synapseml_tpu.parallel import create_mesh

mesh = create_mesh(MeshConfig(seq=8))
q = np.random.default_rng(0).normal(size=(1, 1024, 2, 16)).astype(np.float32)
o = ring_attention_sharded(mesh, q, q, q, causal=True, chunk=128)
print("ring attention out:", np.asarray(o).shape)
