# %% [markdown]
# # Sub-millisecond model serving
# Spark Serving's HTTP source/sink (streaming/HTTPSourceV2.scala) as a
# threaded server: requests become DataFrame rows, the pipeline transforms
# them, replies route back by request id. `serve_pipeline_distributed` runs
# the same thing across worker OS processes behind one routed port.

# %%
import json
import urllib.request

import numpy as np

from synapseml_tpu.core.pipeline import Transformer
from synapseml_tpu.io import serve_pipeline


class Doubler(Transformer):
    def _transform(self, df):
        def per_part(p):
            out = dict(p)
            out["reply"] = np.asarray(
                [{"doubled": (b or {}).get("x", 0) * 2} for b in p["body"]],
                dtype=object)
            return out

        return df.map_partitions(per_part)


server = serve_pipeline(Doubler(), batch_interval_ms=0)  # continuous mode
req = urllib.request.Request(server.address, data=json.dumps({"x": 21}).encode(),
                             method="POST")
with urllib.request.urlopen(req, timeout=30) as r:
    reply = json.loads(r.read())
print("reply:", reply)
assert reply == {"doubled": 42}
server.stop()
