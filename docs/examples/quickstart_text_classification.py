# %% [markdown]
# # Quickstart: text classification on TPU
# The `DeepTextClassifier` fine-tunes a BERT encoder with the GSPMD trainer —
# the reference's horovod `TorchEstimator` path (dl/DeepTextClassifier.py)
# rebuilt as one jitted train step over a device mesh. Pass a local HF
# checkpoint directory as `checkpoint=` for pretrained weights.

# %%
import numpy as np

import synapseml_tpu as st
from synapseml_tpu.models import DeepTextClassifier

rows = [{"text": "an outstanding, joyful film", "label": 1},
        {"text": "tedious and painfully dull", "label": 0}] * 20
df = st.DataFrame.from_rows(rows, num_partitions=4)

est = DeepTextClassifier(checkpoint="bert-tiny", num_classes=2, batch_size=8,
                         max_token_len=16, max_steps=30, learning_rate=3e-3)
model = est.fit(df)

# %% [markdown]
# `transform` appends softmax scores and argmax predictions; models save and
# reload as pipeline stages.

# %%
out = model.transform(df)
acc = float(np.mean(out.collect_column("prediction") == out.collect_column("label")))
print("train accuracy:", acc)
assert acc > 0.9

import tempfile

with tempfile.TemporaryDirectory() as d:
    model.save(d + "/m")
    reloaded = type(model).load(d + "/m")
    assert reloaded.transform(df).count() == df.count()
print("saved + reloaded ok")
