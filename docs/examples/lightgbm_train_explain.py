# %% [markdown]
# # LightGBM on TPU: train, explain, persist
# The estimator surface mirrors the reference's `LightGBMClassifier`
# (lightgbm/LightGBMClassifier.scala); the engine is an XLA histogram
# tree-grower — one fused program per boosting iteration. TreeSHAP
# (`features_shap_col`) is the `featuresShap` analog.

# %%
import numpy as np

import synapseml_tpu as st
from synapseml_tpu.gbdt import LightGBMClassifier

rs = np.random.default_rng(0)
X = rs.normal(size=(600, 8))
y = (X[:, 0] + 0.6 * X[:, 1] - X[:, 2] > 0).astype(int)
df = st.DataFrame.from_rows(
    [{"features": X[i], "label": int(y[i])} for i in range(600)])

clf = LightGBMClassifier(num_iterations=40, learning_rate=0.15,
                         bagging_fraction=0.8, bagging_freq=2)
model = clf.fit(df)
model.set(features_shap_col="shap")

# %%
out = model.transform(df)
acc = float(np.mean(out.collect_column("prediction") == out.collect_column("label")))
print("accuracy:", acc)
assert acc > 0.93

shap = np.stack(list(out.collect_column("shap")))
raw = np.stack(list(out.collect_column("rawPrediction")))
assert np.allclose(shap.sum(-1), raw[:, 0], atol=1e-4)  # SHAP additivity
print("top features by |shap|:", np.argsort(-np.abs(shap[:, :-1]).mean(0))[:3])
print("gain importance:", np.round(model.get_feature_importances("gain")[:4], 1))
print("phase timings:", {k: v for k, v in model.get_train_measures().items()
                         if k.endswith("_ms")})
