# %% [markdown]
# # Migrating from SynapseML: the generated compat namespace
# Reference users write pyspark-style code — camelCase setters, chaining,
# `fit`/`transform`. `synapseml_tpu.compat.<ns>` mirrors the reference's
# `synapse.ml.<ns>` modules with GENERATED wrappers over the native stages
# (`python -m synapseml_tpu.codegen` regenerates them; see
# docs/api/CODEGEN.md). The same estimator, both styles:

# %%
import numpy as np

import synapseml_tpu as st

rs = np.random.default_rng(11)
X = rs.normal(size=(200, 5))
y = (X[:, 0] + X[:, 1] > 0).astype(int)
df = st.DataFrame.from_rows([{"features": X[i], "label": int(y[i])}
                             for i in range(200)])

# reference style (synapse.ml.lightgbm.LightGBMClassifier):
from synapseml_tpu.compat.lightgbm import LightGBMClassifier as RefStyle

model_a = (RefStyle()
           .setNumIterations(10)
           .setLearningRate(0.3)
           .setNumLeaves(15)
           .fit(df))

# native style:
from synapseml_tpu.gbdt import LightGBMClassifier as NativeStyle

model_b = NativeStyle(num_iterations=10, learning_rate=0.3,
                      num_leaves=15).fit(df)

pa = model_a.transform(df).collect_column("prediction")
pb = model_b.transform(df).collect_column("prediction")
np.testing.assert_array_equal(pa, pb)
print("compat wrapper == native estimator:", True)

# %% [markdown]
# Wrapped models expose the same surface (`transform`, camelCase accessors)
# and `unwrap()` returns the native stage for anything beyond it.

# %%
booster = model_a.unwrap().get_booster()
print("feature importances:", booster.feature_importance("split"))

# %% [markdown]
# ## The Spark habits: files, joins, grouping
# Reference pipelines lean on `spark.read.csv`, `df.join`, and
# `df.groupBy().agg()`. The DataFrame plane carries the same verbs
# (host-side pandas engine — the TPU plane does the numeric compute):

# %%
import tempfile, os

tmp = tempfile.mkdtemp()
from synapseml_tpu.io import read_csv, write_csv

scored = model_b.transform(df).with_column(
    "segment", lambda p: (np.arange(len(p["label"])) % 3).astype(np.int64))
write_csv(scored.select("label", "prediction", "segment"),
          os.path.join(tmp, "scored"), partitioned=True)
back = read_csv(os.path.join(tmp, "scored"))
print("read back:", back.count(), "rows in", back.num_partitions, "partitions")

per_segment = back.group_by("segment").agg({"prediction": "mean",
                                            "label": "mean"})
print("per-segment rates:")
for row in per_segment.collect_rows():
    print("  segment", row["segment"], "pred", round(row["prediction_mean"], 2),
          "label", round(row["label_mean"], 2))

names = st.DataFrame.from_dict({"segment": np.arange(3),
                                "name": np.asarray(["a", "b", "c"],
                                                   dtype=object)})
joined = per_segment.join(names, on="segment")
assert sorted(joined.collect_column("name").tolist()) == ["a", "b", "c"]
print("join on segment:", joined.count(), "rows")
