# %% [markdown]
# # ONNX inference without ONNX Runtime
# `ONNXModel` converts the graph ONCE to a jittable JAX function that XLA
# compiles for the device (the reference's per-partition OrtSession +
# CUDA EP, onnx/ONNXModel.scala:145-423). Here: a small MLP built with the
# in-repo proto writer; real exported graphs work the same
# (see tests/test_onnx_resnet.py for a genuine torch ResNet-50 export).

# %%
import numpy as np

import synapseml_tpu as st
from synapseml_tpu.onnx import (
    AttributeProto, GraphProto, ModelProto, NodeProto, ONNXModel,
    ValueInfoProto, numpy_to_tensor,
)
from synapseml_tpu.onnx import proto as P

rs = np.random.default_rng(1)
W = rs.normal(size=(4, 3)).astype(np.float32)
node = NodeProto(input=["x", "W"], output=["logits"], op_type="MatMul")
g = GraphProto(name="mlp", node=[node],
               initializer=[numpy_to_tensor(W, "W")],
               input=[ValueInfoProto(name="x", elem_type=P.FLOAT, dims=["N", 4])],
               output=[ValueInfoProto(name="logits", elem_type=P.FLOAT, dims=["N", 3])])
model_bytes = ModelProto(graph=g).encode()

df = st.DataFrame.from_dict({"feat": rs.normal(size=(10, 4)).astype(np.float32)})
om = ONNXModel(model_bytes=model_bytes, mini_batch_size=4,
               feed_dict={"x": "feat"}, fetch_dict={"logits": "logits"},
               softmax_dict={"logits": "probs"}, argmax_dict={"logits": "pred"})
out = om.transform(df)
probs = np.stack(list(out.collect_column("probs")))
assert probs.shape == (10, 3) and np.allclose(probs.sum(-1), 1.0, atol=1e-5)
print("predictions:", out.collect_column("pred").tolist())
