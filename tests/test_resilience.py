"""Unified resilience layer (core/resilience.py + core/faults.py) driven
entirely under injected fault plans — no real network, loopback only.

Covers: jittered retry policies + retry-budget exhaustion, circuit breaker
closed/open/half-open cycling, deadlines capping cumulative attempt time,
seeded fault-plan determinism, the rewired http / services / distributed-
serving / parallel planes, and a RoutingFront chaos run (kill 2 of 3 workers,
resurrect, zero permanently-failed requests)."""

import json
import random
import socket
import threading
import time
import urllib.error
import urllib.request
from email.utils import formatdate
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from synapseml_tpu.core.dataframe import DataFrame
from synapseml_tpu.core.faults import FaultPlan, FaultSpec, inject_faults
from synapseml_tpu.core.instrumentation import InstrumentationMeasures
from synapseml_tpu.core.resilience import (
    CircuitBreaker,
    Deadline,
    DeadlineExpired,
    RetryBudget,
    RetryPolicy,
    resilience_measures,
)
from synapseml_tpu.io.http import (
    RETRY_AFTER_CAP_MS,
    HTTPRequest,
    _retry_after_ms,
    send_with_retries,
)


def counter(plane: str, name: str) -> int:
    return resilience_measures(plane).to_dict().get(f"{name}_count", 0)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _EchoHandler(BaseHTTPRequestHandler):
    """Replies {"port": <server port>} to any GET/POST (who served this?)."""

    def log_message(self, *a):
        pass

    def _reply(self):
        n = int(self.headers.get("Content-Length") or 0)
        if n:
            self.rfile.read(n)
        body = json.dumps({"port": self.server.server_address[1]}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    do_GET = _reply
    do_POST = _reply


def _start_echo(port: int = 0) -> ThreadingHTTPServer:
    srv = ThreadingHTTPServer(("127.0.0.1", port), _EchoHandler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


@pytest.fixture(scope="module")
def ok_server():
    srv = _start_echo()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()
    srv.server_close()


# ---------------------------------------------------------------------------
# RetryPolicy / RetryBudget
# ---------------------------------------------------------------------------

def test_retry_policy_full_jitter_deterministic_under_seed():
    sched1 = [RetryPolicy(backoffs_ms=(100, 500, 1000),
                          rng=random.Random(5)).backoff_ms(i) for i in range(3)]
    sched2 = [RetryPolicy(backoffs_ms=(100, 500, 1000),
                          rng=random.Random(5)).backoff_ms(i) for i in range(3)]
    assert sched1 == sched2  # same seed => same jittered schedule
    for wait, base in zip(sched1, (100, 500, 1000)):
        assert 0.0 <= wait <= base  # full jitter: uniform(0, base]
    # jitter actually jitters (astronomically unlikely to hit the base)
    assert sched1 != [100.0, 500.0, 1000.0]
    # and without jitter the raw schedule comes back
    plain = RetryPolicy(backoffs_ms=(100, 500), jitter=False)
    assert [plain.backoff_ms(i) for i in range(2)] == [100.0, 500.0]


def test_retry_budget_exhaustion_fails_fast():
    budget = RetryBudget(max_tokens=1.0, deposit_per_success=0.0)
    policy = RetryPolicy(backoffs_ms=(1, 1, 1), budget=budget,
                         rng=random.Random(0))
    with inject_faults([FaultSpec("connection_error",
                                  match="budget.invalid")]) as plan:
        r1 = send_with_retries(HTTPRequest(url="http://budget.invalid/a"),
                               policy=policy, timeout_s=1.0)
        # 1 token => first attempt + exactly one retry, then fail fast
        assert r1.error and len(plan.injected) == 2
        r2 = send_with_retries(HTTPRequest(url="http://budget.invalid/b"),
                               policy=policy, timeout_s=1.0)
        # bucket empty => single attempt, no retries (storms can't amplify)
        assert r2.error and len(plan.injected) == 3
    assert budget.tokens == 0.0


def test_retry_budget_refills_on_success(ok_server):
    budget = RetryBudget(max_tokens=2.0, deposit_per_success=0.5,
                         initial_tokens=0.0)
    policy = RetryPolicy(backoffs_ms=(1,), budget=budget)
    assert not policy.acquire_retry()
    for _ in range(3):
        resp = send_with_retries(HTTPRequest(url=f"{ok_server}/ok"),
                                 policy=policy, timeout_s=5.0)
        assert resp.status_code == 200
    assert budget.tokens == pytest.approx(1.5)
    assert policy.acquire_retry()  # deposits re-enable retries


def test_retry_budget_not_replenished_by_retried_success(ok_server):
    """A success that itself consumed a retry token must not deposit back —
    otherwise the bucket drains far slower than the retry-rate bound."""
    budget = RetryBudget(max_tokens=5.0, deposit_per_success=1.0,
                         initial_tokens=5.0)
    policy = RetryPolicy(backoffs_ms=(1, 1), budget=budget)
    with inject_faults([FaultSpec("status", status=503, times=1,
                                  match="/retried")]):
        resp = send_with_retries(HTTPRequest(url=f"{ok_server}/retried"),
                                 policy=policy, timeout_s=5.0)
    assert resp.status_code == 200
    assert budget.tokens == pytest.approx(4.0)  # spent 1, no deposit back


# ---------------------------------------------------------------------------
# CircuitBreaker
# ---------------------------------------------------------------------------

def test_circuit_breaker_open_half_open_closed_cycle():
    clk = FakeClock()
    m = InstrumentationMeasures()
    br = CircuitBreaker(failure_rate_threshold=0.5, window=4, min_samples=2,
                        probe_interval_s=5.0, clock=clk, measures=m)
    br.record_failure()
    assert br.state == br.CLOSED  # min_samples not reached
    br.record_failure()
    assert br.state == br.OPEN
    assert m.to_dict()["breaker_open_count"] == 1
    assert not br.allow() and not br.available()
    clk.advance(5.0)
    assert br.available()
    assert br.allow()  # probe lease; open -> half-open
    assert br.state == br.HALF_OPEN
    assert not br.allow()  # only one probe in flight
    br.record_success()
    assert br.state == br.CLOSED and br.allow()


def test_circuit_breaker_reopens_on_probe_failure():
    clk = FakeClock()
    br = CircuitBreaker(failure_rate_threshold=0.0, window=1, min_samples=1,
                        probe_interval_s=2.0, clock=clk)
    br.record_failure()
    assert br.state == br.OPEN
    clk.advance(2.0)
    assert br.allow()  # half-open probe
    br.record_failure()  # probe failed
    assert br.state == br.OPEN
    assert not br.allow()  # interval restarts from the probe failure
    clk.advance(2.0)
    assert br.allow()
    br.record_success()
    assert br.state == br.CLOSED


def test_circuit_breaker_failure_rate_window():
    br = CircuitBreaker(failure_rate_threshold=0.5, window=10, min_samples=4,
                        clock=FakeClock())
    for _ in range(3):
        br.record_success()
    br.record_failure()  # 1/4 = 0.25 < 0.5
    assert br.state == br.CLOSED
    br.record_failure()
    br.record_failure()  # 3/6 = 0.5 >= 0.5
    assert br.state == br.OPEN


# ---------------------------------------------------------------------------
# Deadline
# ---------------------------------------------------------------------------

def test_deadline_caps_attempt_timeouts():
    clk = FakeClock()
    dl = Deadline(10.0, clock=clk)
    assert dl.cap(60.0) == 10.0   # attempt timeout capped by the budget
    clk.advance(4.0)
    assert dl.cap(3.0) == 3.0     # smaller timeouts pass through
    assert dl.cap(60.0) == pytest.approx(6.0)
    assert dl.sleep_allowed(5.9) and not dl.sleep_allowed(6.1)
    clk.advance(7.0)
    assert dl.expired()
    with pytest.raises(DeadlineExpired):
        dl.cap(1.0)


def test_deadline_bounds_total_retry_time():
    """A 503 storm with a 5s Retry-After cannot stall past the deadline: the
    backoff sleep is refused and the last response returns immediately."""
    before = counter("http", "deadline_expired")
    with inject_faults([FaultSpec("status", status=503, retry_after=5,
                                  match="deadline.invalid")]):
        t0 = time.monotonic()
        resp = send_with_retries(HTTPRequest(url="http://deadline.invalid/x"),
                                 backoffs_ms=(1, 1, 1), timeout_s=1.0,
                                 deadline=Deadline(0.2))
        elapsed = time.monotonic() - t0
    assert resp.status_code == 503
    assert elapsed < 2.0  # NOT the 5s Retry-After, and no 4x amplification
    assert counter("http", "deadline_expired") == before + 1


# ---------------------------------------------------------------------------
# FaultPlan: determinism + injection kinds
# ---------------------------------------------------------------------------

def _drive_plan(seed: int) -> list:
    plan = FaultPlan([FaultSpec("connection_error", probability=0.5,
                                match="127.0.0.1:1")], seed=seed)
    with inject_faults(plan):
        for i in range(20):
            send_with_retries(HTTPRequest(url=f"http://127.0.0.1:1/{i}"),
                              backoffs_ms=(), timeout_s=0.5)
    return list(plan.injected)


def test_fault_plan_deterministic_under_seed():
    a, b = _drive_plan(seed=7), _drive_plan(seed=7)
    assert a == b                     # same seed => same injected sequence
    assert 0 < len(a) < 20            # probability actually gates
    c = _drive_plan(seed=8)
    assert a != c                     # different seed => different draws


def test_fault_injection_connection_errors_counted():
    before = counter("http", "faults_injected")
    before_retry = counter("http", "retry")
    with inject_faults([FaultSpec("connection_error",
                                  match="conn.invalid")]) as plan:
        resp = send_with_retries(HTTPRequest(url="http://conn.invalid/x"),
                                 backoffs_ms=(1, 1), timeout_s=1.0)
    assert resp.status_code == 0 and "injected" in resp.error
    assert len(plan.injected) == 3  # initial attempt + 2 retries
    assert counter("http", "faults_injected") == before + 3
    assert counter("http", "retry") == before_retry + 2


def test_fault_injection_429_retry_after_honored(ok_server):
    before = counter("http", "retry")
    with inject_faults([FaultSpec("status", status=429, retry_after=0,
                                  times=2, match="/throttle")]) as plan:
        resp = send_with_retries(HTTPRequest(url=f"{ok_server}/throttle"),
                                 backoffs_ms=(1, 1, 1), timeout_s=5.0)
    assert resp.status_code == 200          # survived the throttle window
    assert [k for _, k, _ in plan.injected] == ["status", "status"]
    assert counter("http", "retry") == before + 2


def test_fault_injection_latency_and_blackhole(ok_server):
    with inject_faults([FaultSpec("latency", latency_ms=40, times=1,
                                  match="/slowpath")]):
        t0 = time.monotonic()
        resp = send_with_retries(HTTPRequest(url=f"{ok_server}/slowpath"),
                                 backoffs_ms=(), timeout_s=5.0)
        assert resp.status_code == 200
        assert time.monotonic() - t0 >= 0.04  # latency added, then served
    with inject_faults([FaultSpec("blackhole",
                                  match="hole.invalid")]) as plan:
        resp = send_with_retries(HTTPRequest(url="http://hole.invalid/x"),
                                 backoffs_ms=(1,), timeout_s=1.0)
        assert resp.status_code == 0 and "blackhole" in resp.error
        assert len(plan.injected) == 2


def test_inject_faults_refuses_nesting():
    with inject_faults([FaultSpec("latency")]):
        with pytest.raises(RuntimeError, match="already active"):
            with inject_faults([FaultSpec("latency")]):
                pass


# ---------------------------------------------------------------------------
# Retry-After parsing (satellite: HTTP-date handling)
# ---------------------------------------------------------------------------

def test_retry_after_parses_seconds_dates_and_clamps():
    assert _retry_after_ms("3") == 3000.0
    assert _retry_after_ms(None) is None
    assert _retry_after_ms("not a date") is None        # -> backoff schedule
    assert _retry_after_ms("-5") == 0.0                 # negative clamps to 0
    assert _retry_after_ms("99999") == RETRY_AFTER_CAP_MS  # absurd waits cap
    # float('nan')/float('inf') parse without error but must not reach sleep
    assert _retry_after_ms("nan") is None
    assert _retry_after_ms("inf") is None
    # HTTP-date in the past: zero wait, not a schedule fallback
    assert _retry_after_ms("Wed, 21 Oct 2015 07:28:00 GMT") == 0.0
    # HTTP-date ~10s out parses to roughly that wait
    soon = formatdate(time.time() + 10, usegmt=True)
    assert 5_000.0 <= _retry_after_ms(soon) <= 10_500.0


# ---------------------------------------------------------------------------
# services plane (satellite: backoffs threaded; LRO deadline)
# ---------------------------------------------------------------------------

def _ping_service(url: str, **params):
    from synapseml_tpu.services.base import CognitiveServiceBase

    class PingService(CognitiveServiceBase):
        def build_request(self, rp):
            return HTTPRequest(url=f"{self.get('url')}/ping")

    return PingService(url=url, output_col="out", error_col="err", **params)


def test_service_base_threads_backoffs_ms(ok_server):
    df = DataFrame.from_dict({"x": np.asarray([1])})
    # no-retry schedule: the single injected 503 surfaces as the row error
    with inject_faults([FaultSpec("status", status=503, times=1,
                                  match="/ping")]):
        svc = _ping_service(ok_server, backoffs_ms=())
        errs = list(svc.transform(df).collect_column("err"))
    assert errs[0] and "503" in errs[0]
    # with a schedule, the same fault is retried through to success — the
    # param reaches the underlying AsyncHTTPClient (it used to be dropped)
    with inject_faults([FaultSpec("status", status=503, times=1,
                                  match="/ping")]):
        svc = _ping_service(ok_server, backoffs_ms=(1, 1))
        out = svc.transform(df)
        assert list(out.collect_column("err"))[0] is None
        assert list(out.collect_column("out"))[0] == {"port": int(ok_server.rsplit(":", 1)[1])}


def test_lro_polling_respects_deadline():
    """An LRO that never completes is cut off by lro_deadline_s, not left to
    max_poll_attempts x interval."""

    class LROHandler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _json(self, payload, status=200, headers=None):
            body = json.dumps(payload).encode()
            self.send_response(status)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            if n:
                self.rfile.read(n)
            host = self.headers.get("Host")
            self._json({"status": "accepted"}, status=202,
                       headers={"Operation-Location": f"http://{host}/poll"})

        def do_GET(self):
            self._json({"status": "running"})  # never finishes

    srv = ThreadingHTTPServer(("127.0.0.1", 0), LROHandler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        from synapseml_tpu.services.base import HasAsyncReply

        class SlowLRO(HasAsyncReply):
            def build_request(self, rp):
                return HTTPRequest(url=f"{self.get('url')}/start",
                                   method="POST", entity=b"{}")

        before = counter("services", "deadline_expired")
        svc = SlowLRO(url=url, output_col="out", error_col="err",
                      polling_interval_s=0.02, max_poll_attempts=10_000,
                      lro_deadline_s=0.3)
        df = DataFrame.from_dict({"x": np.asarray([1, 2])})
        t0 = time.monotonic()
        errs = list(svc.transform(df).collect_column("err"))
        assert time.monotonic() - t0 < 5.0  # NOT 10k polls x 20ms
        assert all(e for e in errs)  # rows carry the timeout error
        assert counter("services", "deadline_expired") > before
    finally:
        srv.shutdown()
        srv.server_close()


# ---------------------------------------------------------------------------
# parallel plane: deadline-bounded rendezvous
# ---------------------------------------------------------------------------

def test_worker_rendezvous_deadline_bounded():
    from synapseml_tpu.parallel.backend import worker_rendezvous

    before_r = counter("parallel", "retry")
    before_d = counter("parallel", "deadline_expired")
    t0 = time.monotonic()
    with pytest.raises(TimeoutError, match="rendezvous"):
        worker_rendezvous("127.0.0.1:1", "exec0", 0, timeout_s=0.5,
                          retry_interval_s=0.02)
    assert time.monotonic() - t0 < 5.0
    assert counter("parallel", "retry") > before_r
    assert counter("parallel", "deadline_expired") == before_d + 1


def test_worker_rendezvous_retries_until_late_driver():
    from synapseml_tpu.parallel.backend import worker_rendezvous

    port = _free_port()
    reply = {"coordinator": "127.0.0.1:9999", "rank": 0, "world": 1}

    def late_driver():
        time.sleep(0.3)
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", port))
        srv.listen(1)
        conn, _ = srv.accept()
        conn.makefile("r").readline()
        conn.sendall((json.dumps(reply) + "\n").encode())
        conn.close()
        srv.close()

    before = counter("parallel", "retry")
    threading.Thread(target=late_driver, daemon=True).start()
    info = worker_rendezvous(f"127.0.0.1:{port}", "exec0", 0, timeout_s=30.0,
                             retry_interval_s=0.05)
    assert info == reply
    assert counter("parallel", "retry") > before  # connect was retried


# ---------------------------------------------------------------------------
# RoutingFront / RoutingClient failure semantics (satellite coverage)
# ---------------------------------------------------------------------------

def _front_call(front, payload=b"{}", timeout=10):
    req = urllib.request.Request(front.address, data=payload, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def test_routing_front_dead_marking_and_stats():
    from synapseml_tpu.io.distributed_serving import RoutingFront

    srv = _start_echo()
    dead_port = _free_port()
    front = RoutingFront([{"host": "127.0.0.1", "port": dead_port, "pid": 1},
                          {"host": "127.0.0.1", "port": srv.server_address[1],
                           "pid": 2}],
                         timeout_s=5, resurrect_after_s=60)
    before = counter("distributed_serving", "breaker_open")
    try:
        for _ in range(6):
            status, body = _front_call(front)
            assert status == 200
            assert body["port"] == srv.server_address[1]
        breaker = front._breaker(("127.0.0.1", dead_port))
        assert breaker.state == breaker.OPEN  # connect failure tripped it
        assert counter("distributed_serving", "breaker_open") >= before + 1
        with urllib.request.urlopen(front.address + "/stats", timeout=10) as r:
            stats = json.loads(r.read())
        assert stats["breakers"][f"127.0.0.1:{dead_port}"] == "open"
        for key in ("retry_count", "breaker_open_count",
                    "deadline_expired_count", "faults_injected_count"):
            assert key in stats["resilience"]
    finally:
        front.close()
        srv.shutdown()
        srv.server_close()


def test_routing_front_time_based_resurrection():
    from synapseml_tpu.io.distributed_serving import RoutingFront

    port = _free_port()
    live = _start_echo()
    front = RoutingFront([{"host": "127.0.0.1", "port": port, "pid": 1},
                          {"host": "127.0.0.1", "port": live.server_address[1],
                           "pid": 2}],
                         timeout_s=5, resurrect_after_s=0.3)
    try:
        for _ in range(4):
            assert _front_call(front)[0] == 200
        breaker = front._breaker(("127.0.0.1", port))
        assert breaker.state == breaker.OPEN
        revived = _start_echo(port)  # worker comes back on its old port
        try:
            time.sleep(0.4)  # past the resurrection window -> half-open probe
            seen = {_front_call(front)[1]["port"] for _ in range(8)}
            assert port in seen  # resurrected worker rejoined the rotation
            assert breaker.state == breaker.CLOSED
        finally:
            revived.shutdown()
            revived.server_close()
    finally:
        front.close()
        live.shutdown()
        live.server_close()


def test_routing_front_all_dead_probes_least_recently_failed():
    from synapseml_tpu.io.distributed_serving import RoutingFront

    port_a, port_b = _free_port(), _free_port()
    front = RoutingFront([{"host": "127.0.0.1", "port": port_a, "pid": 1},
                          {"host": "127.0.0.1", "port": port_b, "pid": 2}],
                         timeout_s=2, resurrect_after_s=300)
    try:
        req = urllib.request.Request(front.address, data=b"{}", method="POST")
        with pytest.raises(urllib.error.HTTPError, match="503"):
            urllib.request.urlopen(req, timeout=10)
        # everything down, desperation probe failed too
        br_a = front._breaker(("127.0.0.1", port_a))
        br_b = front._breaker(("127.0.0.1", port_b))
        assert br_a.state == br_a.OPEN and br_b.state == br_b.OPEN
        # A becomes the stalest failure; bring ONLY A back up
        br_a.last_failure_at = br_b.last_failure_at - 10.0
        revived = _start_echo(port_a)
        try:
            status, body = _front_call(front)
            assert status == 200 and body["port"] == port_a
            assert br_a.state == br_a.CLOSED  # desperation success closed it
            assert br_b.state == br_b.OPEN
        finally:
            revived.shutdown()
            revived.server_close()
    finally:
        front.close()


def test_routing_front_registry_refresh_routes_to_late_worker():
    from synapseml_tpu.io.distributed_serving import RoutingFront, WorkerRegistry

    registry = WorkerRegistry()
    front = RoutingFront(registry=registry, timeout_s=5)
    srv = _start_echo()
    try:
        req = urllib.request.Request(front.address, data=b"{}", method="POST")
        with pytest.raises(urllib.error.HTTPError, match="503"):
            urllib.request.urlopen(req, timeout=10)  # empty routing table
        # a worker registers AFTER the front started: routed to immediately
        info = {"host": "127.0.0.1", "port": srv.server_address[1], "pid": 7}
        urllib.request.urlopen(urllib.request.Request(
            registry.address + "/register", data=json.dumps(info).encode(),
            method="POST"), timeout=10).read()
        status, body = _front_call(front)
        assert status == 200 and body["port"] == srv.server_address[1]
        # a departed worker's breaker is pruned once it leaves the registry
        # (respawn churn must not grow the breaker map forever)
        ghost_port = _free_port()
        ghost = {"host": "127.0.0.1", "port": ghost_port, "pid": 8}
        urllib.request.urlopen(urllib.request.Request(
            registry.address + "/register", data=json.dumps(ghost).encode(),
            method="POST"), timeout=10).read()
        for _ in range(4):  # routes to the ghost at least once -> breaker
            assert _front_call(front)[0] == 200
        assert ("127.0.0.1", ghost_port) in front._breakers
        registry.remove_pid(8)
        assert _front_call(front)[0] == 200  # table refresh prunes it
        assert ("127.0.0.1", ghost_port) not in front._breakers
    finally:
        front.close()
        registry.close()
        srv.shutdown()
        srv.server_close()


def test_routing_client_breaker_skips_dead_worker():
    from synapseml_tpu.io.distributed_serving import RoutingClient

    srv = _start_echo()
    dead_port = _free_port()
    client = RoutingClient(workers=[
        {"host": "127.0.0.1", "port": srv.server_address[1], "pid": 1},
        {"host": "127.0.0.1", "port": dead_port, "pid": 2}],
        timeout_s=2, resurrect_after_s=300)
    try:
        for _ in range(6):
            status, payload = client.request("/", body=b"{}")
            assert status == 200
            assert json.loads(payload)["port"] == srv.server_address[1]
        breaker = client._breaker(("127.0.0.1", dead_port))
        assert breaker.state == breaker.OPEN  # marked dead after one failure
    finally:
        client.close()
        srv.shutdown()
        srv.server_close()


@pytest.mark.chaos(timeout_s=90)
def test_routing_front_chaos_kill_two_of_three_with_resurrection():
    """Kill 2 of 3 workers under traffic, then resurrect them: every request
    (before, during, after) must succeed — zero permanently-failed requests —
    and the resurrected workers must rejoin the rotation."""
    from synapseml_tpu.io.distributed_serving import RoutingFront

    servers = [_start_echo() for _ in range(3)]
    ports = [s.server_address[1] for s in servers]
    front = RoutingFront([{"host": "127.0.0.1", "port": p, "pid": i}
                          for i, p in enumerate(ports)],
                         timeout_s=5, resurrect_after_s=0.3)
    statuses = []
    try:
        for _ in range(12):
            status, body = _front_call(front)
            statuses.append(status)
        # kill workers 0 and 1 mid-stream
        for s in servers[:2]:
            s.shutdown()
            s.server_close()
        for _ in range(12):
            status, body = _front_call(front)
            statuses.append(status)
            assert body["port"] == ports[2]  # survivor carries the traffic
        # resurrect both on their old ports
        revived = [_start_echo(p) for p in ports[:2]]
        try:
            time.sleep(0.4)
            seen = set()
            for _ in range(24):
                status, body = _front_call(front)
                statuses.append(status)
                seen.add(body["port"])
            assert seen == set(ports)  # all three serve again
        finally:
            for s in revived:
                s.shutdown()
                s.server_close()
        assert statuses == [200] * len(statuses)  # zero failed requests
    finally:
        front.close()
        servers[2].shutdown()
        servers[2].server_close()


# ---------------------------------------------------------------------------
# acceptance: exported counter keys
# ---------------------------------------------------------------------------

def test_resilience_measures_export_counter_keys():
    for plane in ("http", "distributed_serving", "services", "parallel"):
        exported = resilience_measures(plane).to_dict()
        for key in ("retry_count", "breaker_open_count",
                    "deadline_expired_count", "faults_injected_count"):
            assert key in exported, (plane, key)
