"""Continuous batching + shared shape-bucketed compile cache (PR 4).

Covers: ShapeBucketer ladder/slicing, CompiledCache hit/miss/evict/LRU and
metrics, numerical identity of bucketed/padded vs unpadded execution for
EVERY adopted stage (onnx, hf embedder, hf causal LM, deep text, deep
vision, gbdt predict, knn), the ladder-bounded compile-count guarantee
under mixed-size streams (direct and through a served pipeline), the
adaptive serve-loop scheduler, and the /admin/load-path warmup precompile.
"""

import contextlib
import json
import threading
import time

import numpy as np
import pytest

from synapseml_tpu.core import DataFrame, batching as cb
from synapseml_tpu.core.pipeline import Transformer


@pytest.fixture()
def fresh_cache():
    cache = cb.reset_compiled_cache()
    yield cache
    cb.reset_compiled_cache()


@contextlib.contextmanager
def exact_bucketer(upto: int = 64):
    """A ladder with every integer rung — bucket_for(n) == n, i.e. the
    UNPADDED reference execution."""
    prev = cb.set_default_bucketer(
        cb.ShapeBucketer(ladder=list(range(1, upto + 1))))
    try:
        yield
    finally:
        cb.set_default_bucketer(prev)


# ---------------------------------------------------------------------------
# ShapeBucketer
# ---------------------------------------------------------------------------

def test_bucketer_ladder_and_bucket_for():
    b = cb.ShapeBucketer(min_bucket=8, max_bucket=64)
    assert b.ladder == (8, 16, 32, 64)
    assert b.bucket_for(1) == 8
    assert b.bucket_for(8) == 8
    assert b.bucket_for(9) == 16
    assert b.bucket_for(64) == 64
    # beyond the ladder: exact shape, never pad toward the next pow-2
    assert b.bucket_for(1000) == 1000
    assert b.cap_for(64) == 64
    assert b.cap_for(48) == 32
    assert b.cap_for(5) == 5  # below the ladder: the cap stays a hard bound
    assert b.cap_for(200) == 200  # above it too: never silently shrunk
    assert [s for s in b.slices(500, 200)] == [
        (0, 200, 200), (200, 400, 200), (400, 500, 100)]  # tail stays exact
    assert b.buckets_upto(64) == [8, 16, 32, 64]
    assert b.buckets_upto(48) == [8, 16, 32]


def test_bucketer_seq_ladder():
    """The sequence/page dimension buckets like the batch dimension: the
    token-serving plane keys prefill executables off seq_bucket_for and
    decode executables off bucket_for, so both stay ladder-bounded."""
    b = cb.ShapeBucketer(min_bucket=8, max_bucket=64,
                         min_seq_bucket=16, max_seq_bucket=128)
    assert b.seq_ladder == (16, 32, 64, 128)
    assert b.seq_bucket_for(1) == 16
    assert b.seq_bucket_for(17) == 32
    assert b.seq_bucket_for(128) == 128
    # multiple_of: prompt buckets tile whole KV pages
    assert b.seq_bucket_for(17, multiple_of=24) == 48
    # cap clamps at a model horizon instead of padding past it
    assert b.seq_bucket_for(100, cap=120) == 120
    assert b.seq_bucket_for(100, cap=128) == 128
    with pytest.raises(ValueError):
        b.seq_bucket_for(130, cap=128)
    # warmup/compile-bound set: every rung plus the cap bucket
    assert b.seq_buckets_upto(128) == [16, 32, 64, 128]
    assert b.seq_buckets_upto(100) == [16, 32, 64, 100]
    # explicit seq ladder + validation
    assert cb.ShapeBucketer(seq_ladder=[8, 80]).seq_ladder == (8, 80)
    with pytest.raises(ValueError):
        cb.ShapeBucketer(seq_ladder=[0, 8])


def test_bucketer_slices_cover_and_bound():
    b = cb.ShapeBucketer(min_bucket=8, max_bucket=64)
    for n in (1, 7, 8, 9, 33, 64, 65, 200):
        got = list(b.slices(n, 64))
        assert got[0][0] == 0 and got[-1][1] == n
        for (s0, e0, _), (s1, _, _) in zip(got, got[1:]):
            assert e0 == s1  # contiguous, no overlap
        for s, e, bucket in got:
            assert e - s <= bucket <= 64
            assert bucket in (8, 16, 32, 64)
    assert list(b.slices(0, 64)) == []


def test_bucketer_multiple_of():
    b = cb.ShapeBucketer(min_bucket=8, max_bucket=64)
    for _s, _e, bucket in b.slices(13, 64, multiple_of=4):
        assert bucket % 4 == 0
    assert b.bucket_for(3, multiple_of=6) % 6 == 0


def test_bucketer_explicit_ladder_and_validation():
    assert cb.ShapeBucketer(ladder=[4, 2, 2]).ladder == (2, 4)
    with pytest.raises(ValueError):
        cb.ShapeBucketer(ladder=[0, 2])
    with pytest.raises(ValueError):
        cb.ShapeBucketer(min_bucket=16, max_bucket=8)


def test_pad_rows_modes():
    a = np.arange(6, dtype=np.float32).reshape(3, 2)
    zero = cb.pad_rows(a, 5)
    assert zero.shape == (5, 2) and np.all(zero[3:] == 0)
    edge = cb.pad_rows(a, 5, mode="edge")
    assert np.all(edge[3:] == a[-1])
    one = cb.pad_rows(a, 5, mode="constant", constant=1)
    assert np.all(one[3:] == 1)
    assert cb.pad_rows(a, 3) is a  # no copy when already at the bucket
    assert cb.unpad_rows(zero, 3).shape == (3, 2)


# ---------------------------------------------------------------------------
# CompiledCache
# ---------------------------------------------------------------------------

def test_compiled_cache_hit_miss_evict(fresh_cache):
    cache = cb.CompiledCache(capacity=2)
    calls = []

    def build_for(tag):
        def build():
            calls.append(tag)
            return lambda x: (tag, x)
        return build

    f8 = cache.get("fn", (8,), build_for("b8"))
    assert f8(1) == ("b8", 1)
    assert cache.get("fn", (8,), build_for("never")) is f8
    stats = cache.stats()
    assert {k: stats[k] for k in ("hits", "misses", "evictions", "size")} \
        == {"hits": 1, "misses": 1, "evictions": 0, "size": 1}
    cache.get("fn", (16,), build_for("b16"))
    cache.get("fn", (8,), build_for("never"))   # refresh 8's recency
    cache.get("fn", (32,), build_for("b32"))    # evicts 16 (LRU)
    assert calls == ["b8", "b16", "b32"]
    stats = cache.stats()
    assert stats["evictions"] == 1 and stats["size"] == 2
    cache.get("fn", (16,), build_for("b16-again"))  # rebuilt after eviction
    assert calls[-1] == "b16-again"


def test_compiled_cache_distinguishes_instance_and_dtype(fresh_cache):
    cache = cb.CompiledCache()
    a = cache.get("fn", (8,), lambda: (lambda: "a"), instance=1)
    b = cache.get("fn", (8,), lambda: (lambda: "b"), instance=2)
    c = cache.get("fn", (8,), lambda: (lambda: "c"), instance=1,
                  dtype="float64")
    assert a() == "a" and b() == "b" and c() == "c"


def test_compiled_cache_metrics_and_trace_span(fresh_cache):
    from synapseml_tpu.core import observability as obs

    cache = cb.get_compiled_cache()
    before = cache.miss_count("metrics_probe")
    fn = cache.get("metrics_probe", (4,), lambda: (lambda x: x + 1))
    assert fn(1) == 2  # first call runs under the compile span
    assert cache.miss_count("metrics_probe") == before + 1
    spans = [s for s in obs.get_tracer().spans_as_dicts()
             if s["name"] == "compile"
             and s["attributes"].get("fn") == "metrics_probe"]
    assert spans, "miss's first call must emit a compile span"
    snap = obs.get_registry().snapshot()
    trace_hist = snap.get('synapseml_compile_trace_ms{fn="metrics_probe"}')
    assert trace_hist and trace_hist["count"] >= 1


def test_compiled_cache_thread_safety(fresh_cache):
    cache = cb.CompiledCache(capacity=8)
    results = []

    def worker(i):
        fn = cache.get("t", (i % 4,), lambda i=i: (lambda: i % 4))
        results.append((i % 4, fn()))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(32)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # every caller got SOME callable for its bucket (first build wins; a
    # racing duplicate build computes the same thing)
    assert len(results) == 32
    assert len(cache) == 4


def test_instance_token_stable_and_invalidated(fresh_cache):
    class Obj:
        pass

    o = Obj()
    t1 = cb.instance_token(o)
    assert cb.instance_token(o) == t1
    cb.invalidate_token(o)
    assert cb.instance_token(o) != t1
    assert cb.instance_token(Obj()) != cb.instance_token(Obj())


def test_invalidate_token_evicts_cached_executables(fresh_cache):
    """A dead config's entries must leave the cache — their build closures
    pin the captured weights otherwise."""
    class Obj:
        pass

    cache = cb.get_compiled_cache()
    o = Obj()
    tok = cb.instance_token(o)
    cache.get("fn", (8,), lambda: (lambda: 1), instance=tok)
    cache.get("fn", (16,), lambda: (lambda: 2), instance=tok)
    other = cache.get("fn", (8,), lambda: (lambda: 3), instance="other")
    assert len(cache) == 3
    cb.invalidate_token(o)
    assert len(cache) == 1  # only the unrelated instance survives
    assert cache.get("fn", (8,), lambda: (lambda: 4),
                     instance="other") is other


def test_release_executables_walks_nested_pipelines(fresh_cache):
    from synapseml_tpu.core.pipeline import PipelineModel

    cache = cb.get_compiled_cache()
    inner = _make_onnx_mlp()
    pm = PipelineModel(stages=[inner])
    cache.get("onnx_model", (8,), lambda: (lambda: 1),
              instance=cb.instance_token(inner))
    assert len(cache) == 1
    cb.release_executables(pm)
    assert len(cache) == 0


def test_instance_token_survives_pickle_without_aliasing():
    """A stage pickled into a worker keeps its token (identical copies may
    share executables), while a stage minted in the receiving process draws
    a disjoint uuid — two DIFFERENT stages can never alias one entry."""
    import pickle

    model = _make_onnx_mlp()
    parent_token = cb.instance_token(model)
    copy = pickle.loads(pickle.dumps(parent_token))  # what travels
    fresh = _make_onnx_mlp()  # "worker-local" stage minting its own token
    assert copy == parent_token
    assert cb.instance_token(fresh) != parent_token


# ---------------------------------------------------------------------------
# property: bucketed/padded == unpadded, for every adopted stage
# ---------------------------------------------------------------------------

SIZES = (1, 3, 9)


def _make_onnx_mlp(din=4, dout=3, seed=0):
    from synapseml_tpu.onnx import ONNXModel
    from synapseml_tpu.onnx import proto as P
    from synapseml_tpu.onnx.proto import (AttributeProto, GraphProto,
                                          ModelProto, NodeProto,
                                          ValueInfoProto, numpy_to_tensor)

    rs = np.random.default_rng(seed)
    dh = 8
    W1 = rs.normal(size=(din, dh)).astype(np.float32)
    b1 = rs.normal(size=(dh,)).astype(np.float32)
    W2 = rs.normal(size=(dh, dout)).astype(np.float32)
    b2 = rs.normal(size=(dout,)).astype(np.float32)

    def node(op, inputs, outputs, **attrs):
        return NodeProto(input=list(inputs), output=list(outputs), op_type=op,
                         attribute=[AttributeProto.make(k, v)
                                    for k, v in attrs.items()])

    g = GraphProto(
        name="mlp",
        node=[node("Gemm", ["x", "W1", "b1"], ["h_pre"]),
              node("Relu", ["h_pre"], ["h"]),
              node("Gemm", ["h", "W2", "b2"], ["logits"]),
              node("Softmax", ["logits"], ["probs"], axis=-1)],
        initializer=[numpy_to_tensor(W1, "W1"), numpy_to_tensor(b1, "b1"),
                     numpy_to_tensor(W2, "W2"), numpy_to_tensor(b2, "b2")],
        input=[ValueInfoProto(name="x", elem_type=P.FLOAT, dims=["N", din])],
        output=[ValueInfoProto(name="probs", elem_type=P.FLOAT,
                               dims=["N", dout])],
    )
    return ONNXModel(ModelProto(graph=g).encode(),
                     feed_dict={"x": "features"},
                     fetch_dict={"probs": "probs"},
                     argmax_dict={"probs": "pred"}, mini_batch_size=64)


def _padded_vs_exact(transform, compare):
    """Run ``transform(n)`` under the pow-2 ladder and under the every-rung
    (unpadded) ladder; ``compare`` asserts equality per size."""
    for n in SIZES:
        padded = transform(n)
        with exact_bucketer():
            exact = transform(n)
        compare(padded, exact, n)


def test_onnx_bucketed_matches_unpadded(fresh_cache):
    model = _make_onnx_mlp()

    def transform(n):
        rs = np.random.default_rng(n)  # same inputs for padded and exact
        df = DataFrame.from_dict(
            {"features": rs.normal(size=(n, 4)).astype(np.float32)})
        out = model.transform(df)
        return (np.stack(list(out.collect_column("probs"))),
                np.asarray(out.collect_column("pred")))

    def compare(padded, exact, n):
        np.testing.assert_allclose(padded[0], exact[0], rtol=1e-6, atol=1e-7)
        np.testing.assert_array_equal(padded[1], exact[1])

    _padded_vs_exact(transform, compare)


def test_hf_embedder_bucketed_matches_unpadded(fresh_cache):
    from synapseml_tpu.hf import HuggingFaceSentenceEmbedder

    st = HuggingFaceSentenceEmbedder(model_name="bert-tiny", batch_size=8,
                                     max_token_len=16)

    def transform(n):
        df = DataFrame.from_dict({"text": np.asarray(
            [f"sentence number {i} with a few words" for i in range(n)],
            dtype=object)})
        return np.asarray(
            list(st.transform(df).collect_column("embeddings")))

    def compare(padded, exact, n):
        np.testing.assert_allclose(padded, exact, rtol=1e-5, atol=1e-6)

    _padded_vs_exact(transform, compare)


def test_hf_embedder_bucketed_matches_unpadded_normalized(fresh_cache):
    """The explicit L2-normalize param (cosine indexes) must not break
    pad-row invariance: padded == unpadded with normalize on, and the
    outputs actually ARE unit-norm."""
    from synapseml_tpu.hf import HuggingFaceSentenceEmbedder

    st = HuggingFaceSentenceEmbedder(model_name="bert-tiny", batch_size=8,
                                     max_token_len=16, normalize=True)

    def transform(n):
        df = DataFrame.from_dict({"text": np.asarray(
            [f"sentence number {i} with a few words" for i in range(n)],
            dtype=object)})
        return np.asarray(
            list(st.transform(df).collect_column("embeddings")))

    def compare(padded, exact, n):
        np.testing.assert_allclose(padded, exact, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.linalg.norm(padded, axis=-1), 1.0,
                                   atol=1e-5)

    _padded_vs_exact(transform, compare)


def test_hf_causal_lm_bucketed_matches_unpadded(fresh_cache):
    from synapseml_tpu.hf import HuggingFaceCausalLM

    st = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=4,
                             batch_size=4, prompt_bucket=8)

    def transform(n):
        df = DataFrame.from_dict({"prompt": np.asarray(
            [f"prompt {i}" for i in range(n)], dtype=object)})
        return list(st.transform(df).collect_column("completions"))

    def compare(padded, exact, n):
        for a, b in zip(padded, exact):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    _padded_vs_exact(transform, compare)


@pytest.fixture(scope="module")
def text_model():
    from synapseml_tpu.models import DeepTextClassifier

    rs = np.random.default_rng(0)
    texts = [f"{'good' if i % 2 else 'bad'} sample {i}" for i in range(16)]
    df = DataFrame.from_dict({
        "text": np.asarray(texts, dtype=object),
        "label": (np.arange(16) % 2).astype(np.int32)})
    return DeepTextClassifier(checkpoint="bert-tiny", num_classes=2,
                              batch_size=8, max_token_len=8, max_steps=2,
                              learning_rate=1e-3).fit(df)


def test_deep_text_bucketed_matches_unpadded(text_model, fresh_cache):
    def transform(n):
        df = DataFrame.from_dict({"text": np.asarray(
            [f"the {i} quick brown fox" for i in range(n)], dtype=object)})
        out = text_model.transform(df)
        return (np.asarray(list(out.collect_column("scores"))),
                np.asarray(out.collect_column("prediction")))

    def compare(padded, exact, n):
        np.testing.assert_allclose(padded[0], exact[0], rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(padded[1], exact[1])

    _padded_vs_exact(transform, compare)


def test_deep_vision_bucketed_matches_unpadded(fresh_cache):
    from synapseml_tpu.models import DeepVisionClassifier

    rs = np.random.default_rng(0)
    imgs = rs.normal(size=(12, 16, 16, 3)).astype(np.float32)
    df = DataFrame.from_rows(
        [{"image": imgs[i], "label": int(i % 2)} for i in range(12)])
    model = DeepVisionClassifier(backbone="resnet_tiny", num_classes=2,
                                 batch_size=8, max_steps=2).fit(df)

    def transform(n):
        qdf = DataFrame.from_rows([{"image": imgs[i % 12]} for i in range(n)])
        out = model.transform(qdf)
        return np.asarray(list(out.collect_column("scores")))

    def compare(padded, exact, n):
        np.testing.assert_allclose(padded, exact, rtol=1e-5, atol=1e-6)

    _padded_vs_exact(transform, compare)


def test_gbdt_bucketed_matches_unpadded(fresh_cache):
    from synapseml_tpu.gbdt import LightGBMClassifier

    rs = np.random.default_rng(3)
    X = rs.normal(size=(120, 6)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int32)
    model = LightGBMClassifier(num_iterations=5, num_leaves=7,
                               max_bin=15).fit(
        DataFrame.from_dict({"features": X, "label": y}))

    def transform(n):
        rq = np.random.default_rng(n)  # same inputs for padded and exact
        df = DataFrame.from_dict(
            {"features": rq.normal(size=(n, 6)).astype(np.float32)})
        out = model.transform(df)
        return (np.asarray(list(out.collect_column("probability"))),
                np.asarray(out.collect_column("prediction")))

    def compare(padded, exact, n):
        np.testing.assert_allclose(padded[0], exact[0], rtol=1e-6)
        np.testing.assert_array_equal(padded[1], exact[1])

    _padded_vs_exact(transform, compare)


def test_gbdt_beyond_ladder_stays_out_of_shared_cache(fresh_cache):
    """Offline scans past the ladder keep their exact shape AND stay in the
    booster's per-instance cache — arbitrary large batch sizes must not
    churn the shared LRU and evict warmed serving executables."""
    from synapseml_tpu.gbdt import LightGBMRegressor

    rs = np.random.default_rng(0)
    X = rs.normal(size=(80, 4)).astype(np.float32)
    model = LightGBMRegressor(num_iterations=3, num_leaves=7, max_bin=15).fit(
        DataFrame.from_dict({"features": X, "label": X[:, 0]}))
    booster = model.get_booster()
    cache = cb.get_compiled_cache()
    big = cb.default_bucketer().max_bucket + 1
    before = cache.stats()["size"]
    for n in (big, big + 33):
        out = booster.raw_score(rs.normal(size=(n, 4)).astype(np.float32))
        assert out.shape[0] == n
    assert cache.stats()["size"] == before  # shared LRU untouched
    # serving-sized batches still go through the shared bucketed cache
    booster.raw_score(rs.normal(size=(5, 4)).astype(np.float32))
    assert cache.stats()["size"] == before + 1


def test_knn_bucketed_matches_unpadded(fresh_cache):
    from synapseml_tpu.nn import KNN

    rs = np.random.default_rng(5)
    X = rs.normal(size=(20, 4)).astype(np.float32)
    df = DataFrame.from_rows(
        [{"features": X[i], "values": f"v{i}"} for i in range(20)])
    model = KNN(k=3, query_batch=8).fit(df)

    def transform(n):
        rq = np.random.default_rng(n)  # same inputs for padded and exact
        qdf = DataFrame.from_rows(
            [{"features": rq.normal(size=4).astype(np.float32)}
             for _ in range(n)])
        return list(model.transform(qdf).collect_column("output"))

    def compare(padded, exact, n):
        assert len(padded) == len(exact) == n
        for a, b in zip(padded, exact):
            assert [m["index"] for m in a] == [m["index"] for m in b]
            np.testing.assert_allclose([m["distance"] for m in a],
                                       [m["distance"] for m in b], rtol=1e-5)

    _padded_vs_exact(transform, compare)


# ---------------------------------------------------------------------------
# compile-count bound: a mixed-size stream compiles <= ladder-many programs
# ---------------------------------------------------------------------------

def test_three_size_stream_compiles_ladder_bound(fresh_cache):
    """The satellite unit test: 3 distinct request sizes -> at most
    ladder-size executables, asserted via the cache miss counter."""
    model = _make_onnx_mlp()
    cache = cb.get_compiled_cache()
    rs = np.random.default_rng(0)
    for n in (1, 5, 17):
        model.transform(DataFrame.from_dict(
            {"features": rs.normal(size=(n, 4)).astype(np.float32)}))
    ladder_bound = len(cb.default_bucketer().buckets_upto(64))
    assert cache.stats()["misses"] <= ladder_bound
    # the same sizes again are pure hits
    misses_before = cache.stats()["misses"]
    for n in (1, 5, 17):
        model.transform(DataFrame.from_dict(
            {"features": rs.normal(size=(n, 4)).astype(np.float32)}))
    assert cache.stats()["misses"] == misses_before


class _RowsScorerT(Transformer):
    """Serving wrapper: each request body is {"rows": [[...], ...]} and all
    bodies in a drained batch flatten into ONE stage transform — so the
    served stage sees the mixed drained-batch sizes directly."""

    def __init__(self, stage, reply_of, **kw):
        super().__init__(**kw)
        self._stage = stage
        self._reply_of = reply_of

    def _transform(self, df):
        def per_part(p):
            counts = [len(b["rows"]) for b in p["body"]]
            flat = [np.asarray(r, np.float32) for b in p["body"]
                    for r in b["rows"]]
            out = dict(p)
            if not flat:
                out["reply"] = np.empty(0, dtype=object)
                return out
            replies = self._reply_of(self._stage, flat)
            grouped, i = [], 0
            for c in counts:
                grouped.append({"n": c, "first": replies[i] if c else None})
                i += c
            out["reply"] = np.asarray(grouped, dtype=object)
            return out

        return df.map_partitions(per_part)


def _post(address, payload):
    import urllib.request

    req = urllib.request.Request(address, data=json.dumps(payload).encode(),
                                 method="POST")
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read())


def test_served_mixed_stream_compile_bound_onnx_and_text(text_model,
                                                         fresh_cache):
    """Acceptance: a mixed-batch-size request stream (sizes across 1..64)
    through a served ONNXModel and a served deep-text stage triggers at most
    len(bucket_ladder) compiles each, via the cache miss counter."""
    from synapseml_tpu.io.serving import serve_pipeline

    cache = cb.get_compiled_cache()
    rs = np.random.default_rng(0)
    sizes = [1, 2, 3, 5, 8, 13, 21, 33, 48, 64]

    # registry counters are cumulative across the test session: assert on
    # the DELTA this stream causes
    onnx_misses0 = cache.miss_count("onnx_model")
    text_misses0 = cache.miss_count("deep_text_model")
    onnx = _make_onnx_mlp()
    srv = serve_pipeline(
        _RowsScorerT(onnx, lambda st, flat: [
            int(v) for v in st.transform(DataFrame.from_dict(
                {"features": np.stack(flat)})).collect_column("pred")]),
        batch_interval_ms=5)
    try:
        for n in sizes:
            reply = _post(srv.address,
                          {"rows": rs.normal(size=(n, 4)).tolist()})
            assert reply["n"] == n
    finally:
        srv.stop()
    onnx_misses = cache.miss_count("onnx_model") - onnx_misses0
    assert 0 < onnx_misses <= len(cb.default_bucketer().buckets_upto(64))

    def text_replies(st, flat):
        texts = ["short sample text"] * len(flat)
        out = st.transform(DataFrame.from_dict(
            {"text": np.asarray(texts, dtype=object)}))
        return [int(v) for v in out.collect_column("prediction")]

    srv = serve_pipeline(_RowsScorerT(text_model, text_replies),
                         batch_interval_ms=5)
    try:
        for n in sizes[:6]:  # bert is slower; sizes still span 3 rungs
            reply = _post(srv.address,
                          {"rows": rs.normal(size=(n, 1)).tolist()})
            assert reply["n"] == n
    finally:
        srv.stop()
    text_misses = cache.miss_count("deep_text_model") - text_misses0
    assert 0 < text_misses <= len(
        cb.default_bucketer().buckets_upto(text_model.get("batch_size")))


# ---------------------------------------------------------------------------
# adaptive serve-loop scheduler + warmup precompile + serving satellites
# ---------------------------------------------------------------------------

def _enqueue(server, n, age_s=0.0):
    from synapseml_tpu.io.serving import _Exchange

    for i in range(n):
        ex = _Exchange(f"r{i}-{time.monotonic_ns()}", "POST", "/", {}, b"{}")
        ex.enqueued_at -= age_s
        server._queue.put_nowait(ex)


@pytest.fixture()
def bare_server():
    from synapseml_tpu.io.serving import ServingServer

    srv = ServingServer()
    yield srv
    srv.stop()


def test_adaptive_flushes_full_bucket_immediately(bare_server):
    _enqueue(bare_server, 8)
    t0 = time.perf_counter()
    batch = bare_server.read_batch_adaptive(
        latency_budget_s=5.0, ladder=(8, 16))
    assert batch.count() == 8
    assert time.perf_counter() - t0 < 1.0  # did NOT wait out the budget


def test_adaptive_drains_backlog_past_the_first_rung(bare_server):
    # a deep queue must NOT flush at the smallest rung — the backlog drains
    # toward max_rows before any rung/budget decision
    _enqueue(bare_server, 20)
    t0 = time.perf_counter()
    batch = bare_server.read_batch_adaptive(
        latency_budget_s=0.05, ladder=(8, 16))
    assert batch.count() == 20
    assert time.perf_counter() - t0 < 2.0


def test_adaptive_waits_latency_budget_then_flushes_partial(bare_server):
    _enqueue(bare_server, 3)
    t0 = time.perf_counter()
    batch = bare_server.read_batch_adaptive(
        latency_budget_s=0.08, ladder=(8, 16))
    elapsed = time.perf_counter() - t0
    assert batch.count() == 3
    assert 0.03 < elapsed < 2.0  # waited toward the budget, then flushed


def test_adaptive_single_request_flushes_immediately(bare_server):
    _enqueue(bare_server, 1)
    t0 = time.perf_counter()
    batch = bare_server.read_batch_adaptive(
        latency_budget_s=5.0, ladder=(8, 16))
    assert batch.count() == 1
    assert time.perf_counter() - t0 < 1.0


def test_expired_requests_dropped_not_served(bare_server):
    from synapseml_tpu.core import observability as obs

    _enqueue(bare_server, 2, age_s=bare_server.reply_timeout_s + 1)
    _enqueue(bare_server, 1)
    batch = bare_server.read_batch(timeout_s=0.01)
    assert batch.count() == 1  # the two expired ones never reach the stage
    snap = obs.get_registry().snapshot()
    assert snap.get("synapseml_serving_expired_requests_total", 0) >= 2


def test_empty_batch_schema_cached(bare_server):
    a = bare_server.read_batch(timeout_s=0.001)
    b = bare_server.read_batch(timeout_s=0.001)
    assert a.is_empty()
    assert a is b  # one immutable schema'd empty batch, reused per poll
    assert sorted(a.columns) == ["body", "id", "method", "path"]


def test_warmup_precompiles_ladder_buckets(fresh_cache):
    """/admin/load's warmup path: with a configured bucket ladder, warmup
    compiles EVERY rung's executable before the swap — follow-up requests
    at any rung size add zero misses (zero-compile-stall)."""
    from synapseml_tpu.io.serving import serve_pipeline

    cache = cb.get_compiled_cache()
    onnx = _make_onnx_mlp()
    stage = _RowsScorerT(onnx, lambda st, flat: [
        int(v) for v in st.transform(DataFrame.from_dict(
            {"features": np.stack(flat)})).collect_column("pred")])
    srv = serve_pipeline(stage, batch_interval_ms=5, bucket_ladder=(8, 16))
    try:
        warmed = srv._warmup(stage, rows=[{"rows": [[0.1] * 4]}])
        assert warmed == 1 + 8 + 16  # given size plus each ladder rung
        misses_after_warmup = cache.stats()["misses"]
        assert misses_after_warmup >= 2  # one executable per stage rung
        rs = np.random.default_rng(0)
        for n in (1, 4, 8, 11, 16):
            reply = _post(srv.address,
                          {"rows": rs.normal(size=(n, 4)).tolist()})
            assert reply["n"] == n
        assert cache.stats()["misses"] == misses_after_warmup
    finally:
        srv.stop()


def test_default_warmup_buckets_capped_and_coalesce_validated():
    from synapseml_tpu.io.serving import serve_pipeline

    srv = serve_pipeline(_make_onnx_mlp(), batch_interval_ms=5)
    try:
        # default: flush at the full process ladder, warm only the
        # latency-sensitive small rungs (deploy-plane load timeout safety)
        assert srv._bucket_ladder == tuple(
            b for b in cb.default_bucketer().ladder if b <= 1024)
        assert srv._warmup_buckets == tuple(
            b for b in srv._bucket_ladder if b <= 64)
    finally:
        srv.stop()
    from synapseml_tpu.io.distributed_serving import serve_pipeline_distributed

    with pytest.raises(ValueError, match="micro-batch"):
        serve_pipeline_distributed(_make_onnx_mlp(), num_workers=1,
                                   batch_interval_ms=0,
                                   coalesce_window_ms=5.0)


def test_reply_batch_routes_under_single_lock(bare_server):
    from synapseml_tpu.io.serving import _Exchange

    exchanges = [_Exchange(f"id{i}", "POST", "/", {}, b"") for i in range(4)]
    with bare_server._lock:
        for ex in exchanges:
            bare_server._pending[ex.request_id] = ex
    df = DataFrame.from_dict({
        "id": np.asarray([f"id{i}" for i in range(4)] + ["ghost"],
                         dtype=object),
        "reply": np.asarray([{"i": i} for i in range(5)], dtype=object)})
    n = bare_server.reply_batch(df)
    assert n == 4  # ghost id skipped, everyone else woken
    assert all(ex.reply_event.is_set() for ex in exchanges)
    assert json.loads(exchanges[2].reply_body) == {"i": 2}


def test_request_coalescer_groups_same_path():
    from synapseml_tpu.io.distributed_serving import _RequestCoalescer

    co = _RequestCoalescer(window_s=0.2, max_group=4)
    groups = []

    def join():
        groups.append(co.join("/score"))

    threads = [threading.Thread(target=join) for _ in range(4)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # all four landed in one group, released EARLY on reaching max_group
    assert len({id(g) for g in groups}) == 1
    assert groups[0].count == 4
    assert time.perf_counter() - t0 < 0.19
    # a later joiner starts a fresh group (the old one is closed)
    g2 = co.join("/score")
    assert g2 is not groups[0] and g2.count == 1
