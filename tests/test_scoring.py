"""Distributed bulk-scoring plane (``synapseml_tpu/scoring/``).

The acceptance surface of the exactly-once contract: kill/resume at three
cut points produces byte-identical output to an uninterrupted run (zero
duplicates, zero gaps), host shard partitions are a disjoint exact cover,
a whole corpus scan compiles at most ladder-many executables per stage fn,
poisoned rows/shards quarantine to the errors sidecar instead of killing
the scan, sinks stay atomic under injected write faults, and memory stays
bounded by the queue discipline on a dataset much larger than one shard.
"""

import json
import os

import numpy as np
import pytest

pytestmark = pytest.mark.scoring

from synapseml_tpu.core import batching as cb
from synapseml_tpu.core.dataframe import DataFrame
from synapseml_tpu.core.faults import FaultSpec, inject_faults
from synapseml_tpu.core.pipeline import Model, PipelineModel
from synapseml_tpu.core.resilience import RetryPolicy
from synapseml_tpu.data import MemorySource, ShardedSource
from synapseml_tpu.io import files as iofiles
from synapseml_tpu.scoring import (JsonlSink, NpySink, ScoringContractError,
                                   assign_shards, iter_shard_batches,
                                   open_sink, plan_scan, transform_source)
from synapseml_tpu.scoring.runner import ScoringReport


# ---------------------------------------------------------------------------
# fixtures: a tiny jit-backed scorer + synthetic sharded corpora
# ---------------------------------------------------------------------------

class SquareModel(Model):
    """CompiledCache-adopted toy scorer: score = sum(x^2) + 1."""

    fn_id = "scoring_test_square"

    def _transform(self, df):
        part = df.collect()
        x = np.asarray(np.stack(part["x"]), np.float32)

        def build():
            import jax

            return jax.jit(lambda a: (a * a + 1.0).sum(axis=-1))

        fn = cb.get_compiled_cache().get(self.fn_id, x.shape, build,
                                         instance=cb.instance_token(self))
        return df.with_column("score", np.asarray(fn(x)))


class NumpyModel(Model):
    """Pure-host scorer (no jit) for memory/atomicity tests."""

    def _transform(self, df):
        part = df.collect()
        x = np.asarray(np.stack(part["x"]), np.float64)
        return df.with_column("score", x.sum(axis=-1))


class PoisonModel(NumpyModel):
    """Raises on rows whose ``flag`` is set — the poisoned-row scenario."""

    def _transform(self, df):
        part = df.collect()
        if np.any(np.asarray(part["flag"]) == 1):
            raise ValueError("poisoned row in batch")
        return super()._transform(df)


class _Kill(BaseException):
    """Out-of-band kill (a BaseException so quarantine containment — which
    catches Exception — cannot swallow it; the process-kill stand-in)."""


class KillAfter(Model):
    """Delegates to an inner model, killing the scan after N batches."""

    def __init__(self, inner, after, **kw):
        super().__init__(**kw)
        self._inner = inner
        self._after = after
        self._seen = 0

    def _transform(self, df):
        if self._seen >= self._after:
            raise _Kill(f"killed after {self._seen} batches")
        self._seen += 1
        return self._inner._transform(df)


def _write_corpus(directory, sizes, n_features=4, flag_rows=(), seed=0):
    """One jsonl file per shard; rows carry a global id ``i`` so duplicate/
    gap detection is exact."""
    os.makedirs(directory, exist_ok=True)
    rs = np.random.default_rng(seed)
    i = 0
    for s, n in enumerate(sizes):
        with open(os.path.join(directory, f"in-{s:03d}.jsonl"), "w") as f:
            for _ in range(n):
                f.write(json.dumps({
                    "x": [round(float(v), 5)
                          for v in rs.normal(size=n_features)],
                    "i": i, "flag": 1 if i in flag_rows else 0}) + "\n")
                i += 1
    return i


def _source(directory):
    return ShardedSource.jsonl(os.path.join(directory, "*.jsonl"))


def _part_bytes(sink):
    """Concatenated bytes of the completed parts in shard order — the
    byte-identity surface of the exactly-once proof."""
    return b"".join(open(p, "rb").read() for p in sink.part_files())


def _ids(rows):
    return sorted(r["i"] for r in rows)


# ---------------------------------------------------------------------------
# end-to-end + contract
# ---------------------------------------------------------------------------

def test_end_to_end_matches_in_memory_transform(tmp_path):
    total = _write_corpus(tmp_path / "data", [37, 64, 5, 20])
    src = _source(tmp_path / "data")
    model = SquareModel()
    sink = JsonlSink(tmp_path / "out")
    report = model.transform_source(src, sink, batch_rows=16,
                                    host_index=0, host_count=1)
    assert report.rows_written == total
    assert report.complete and sink.is_complete()
    rows = sink.collect_rows()
    assert _ids(rows) == list(range(total))

    from synapseml_tpu.io.files import read_jsonl

    eager = model.transform(read_jsonl(str(tmp_path / "data" / "*.jsonl")))
    by_id_eager = dict(zip(eager.collect_column("i").tolist(),
                           eager.collect_column("score").tolist()))
    for r in rows:
        assert r["score"] == pytest.approx(by_id_eager[r["i"]], rel=1e-6)


def test_exactly_once_kill_resume_at_three_cut_points(tmp_path):
    total = _write_corpus(tmp_path / "data", [30, 11, 42, 7, 25])
    src = _source(tmp_path / "data")
    clean_sink = JsonlSink(tmp_path / "clean")
    SquareModel().transform_source(src, clean_sink, batch_rows=16,
                                   host_index=0, host_count=1)
    golden = _part_bytes(clean_sink)
    assert golden

    for cut in (1, 4, 7):  # batches before the kill: early / mid / late
        out = tmp_path / f"out_cut{cut}"
        killer = KillAfter(SquareModel(), cut)
        with pytest.raises(_Kill):
            transform_source(killer, src, JsonlSink(out), batch_rows=16,
                             host_index=0, host_count=1)
        # resume with a FRESH runner (new process stand-in)
        sink = JsonlSink(out)
        assert not sink.is_complete()
        report = transform_source(SquareModel(), src, sink, batch_rows=16,
                                  host_index=0, host_count=1)
        assert report.complete
        assert report.shards_skipped + report.shards_done == 5
        rows = sink.collect_rows()
        assert _ids(rows) == list(range(total))  # zero dups, zero gaps
        assert _part_bytes(sink) == golden       # byte-identical output


def test_resume_is_a_noop_when_complete(tmp_path):
    _write_corpus(tmp_path / "data", [12, 12])
    src = _source(tmp_path / "data")
    sink = JsonlSink(tmp_path / "out")
    transform_source(NumpyModel(), src, sink, batch_rows=8,
                     host_index=0, host_count=1)
    before = _part_bytes(sink)
    report = transform_source(NumpyModel(), src, sink, batch_rows=8,
                              host_index=0, host_count=1)
    assert report.rows_written == 0 and report.shards_done == 0
    assert report.shards_skipped == 2 and report.complete
    assert _part_bytes(sink) == before


# ---------------------------------------------------------------------------
# distribution: host partitions
# ---------------------------------------------------------------------------

def test_host_shard_assignment_is_disjoint_exact_cover():
    for n_shards in (1, 5, 16, 17):
        for hosts in (1, 2, 3, 4, 16, 20):
            slices = [assign_shards(n_shards, h, hosts)
                      for h in range(hosts)]
            flat = sorted(i for s in slices for i in s)
            assert flat == list(range(n_shards)), (n_shards, hosts)
            assert len(flat) == len(set(flat))
    with pytest.raises(ValueError):
        assign_shards(4, 2, 2)


def test_two_host_scan_equals_one_host_scan(tmp_path):
    total = _write_corpus(tmp_path / "data", [9, 21, 14, 3, 30])
    src = _source(tmp_path / "data")
    one = JsonlSink(tmp_path / "one")
    transform_source(NumpyModel(), src, one, batch_rows=8,
                     host_index=0, host_count=1)

    two = JsonlSink(tmp_path / "two")
    r0 = transform_source(NumpyModel(), src, two, batch_rows=8,
                          host_index=0, host_count=2)
    assert not r0.complete  # host 1's shards still missing
    r1 = transform_source(NumpyModel(), src, JsonlSink(tmp_path / "two"),
                          batch_rows=8, host_index=1, host_count=2)
    assert r1.complete  # last host to finish writes _SUCCESS
    assert r0.shards_done + r1.shards_done == 5
    assert _part_bytes(JsonlSink(tmp_path / "two")) == _part_bytes(one)
    assert _ids(JsonlSink(tmp_path / "two").collect_rows()) \
        == list(range(total))


# ---------------------------------------------------------------------------
# compile bound + batch formation
# ---------------------------------------------------------------------------

def test_corpus_scan_compile_count_bounded_by_ladder(tmp_path):
    # many shards with ragged sizes -> many distinct tail sizes, yet the
    # padded batch shapes stay within plan.buckets and the per-fn compile
    # count (CompiledCache miss counter) stays <= len(buckets)
    _write_corpus(tmp_path / "data", [3, 17, 33, 64, 50, 7, 12, 31, 2, 29])
    src = _source(tmp_path / "data")
    model = SquareModel()
    model.fn_id = "scoring_ladder_bound"
    cache = cb.get_compiled_cache()
    before = cache.miss_count(model.fn_id)
    plan = plan_scan(src, batch_rows=32, host_index=0, host_count=1)
    transform_source(model, src, JsonlSink(tmp_path / "out"), batch_rows=32,
                     host_index=0, host_count=1)
    misses = cache.miss_count(model.fn_id) - before
    assert 0 < misses <= len(plan.buckets), (misses, plan.buckets)


def test_tail_batches_pad_to_their_own_rung():
    cols = {"x": np.arange(42, dtype=np.float32).reshape(21, 2),
            "i": np.arange(21)}
    batches = list(iter_shard_batches(cols, batch_rows=16))
    assert [(n, b) for _, n, b, _ in batches] == [(16, 16), (5, 8)]
    tail = batches[1][0]
    assert tail["x"].shape == (8, 2)
    # edge padding repeats the last real row
    assert np.array_equal(tail["x"][5], tail["x"][4])


def test_padded_rows_counted_and_never_written(tmp_path):
    total = _write_corpus(tmp_path / "data", [13])
    src = _source(tmp_path / "data")
    sink = JsonlSink(tmp_path / "out")
    report = transform_source(NumpyModel(), src, sink, batch_rows=8,
                              host_index=0, host_count=1)
    assert report.rows_written == total
    assert report.rows_padded > 0
    assert len(sink.collect_rows()) == total


def test_row_count_changing_transform_is_a_contract_error(tmp_path):
    class Dropper(Model):
        def _transform(self, df):
            return df.filter(lambda p: np.asarray(p["i"]) % 2 == 0)

    _write_corpus(tmp_path / "data", [10])
    with pytest.raises(ScoringContractError, match="row-preserving"):
        transform_source(Dropper(), _source(tmp_path / "data"),
                         JsonlSink(tmp_path / "out"), batch_rows=8,
                         host_index=0, host_count=1)


# ---------------------------------------------------------------------------
# quarantine: poisoned rows and shards
# ---------------------------------------------------------------------------

def test_poisoned_rows_quarantined_scan_completes(tmp_path):
    total = _write_corpus(tmp_path / "data", [20, 20], flag_rows=(5, 27))
    src = _source(tmp_path / "data")
    sink = JsonlSink(tmp_path / "out")
    report = transform_source(PoisonModel(), src, sink, batch_rows=8,
                              host_index=0, host_count=1)
    assert report.complete
    assert report.rows_quarantined == 2
    assert report.rows_written == total - 2
    assert _ids(sink.collect_rows()) == [i for i in range(total)
                                         if i not in (5, 27)]
    errs = sink.error_records()
    assert len(errs) == 2 and all(e["kind"] == "row" for e in errs)
    assert {e["data"]["i"] for e in errs} == {5, 27}


def test_poisoned_rows_raise_when_on_error_raise(tmp_path):
    _write_corpus(tmp_path / "data", [10], flag_rows=(3,))
    with pytest.raises(ValueError, match="poisoned"):
        transform_source(PoisonModel(), _source(tmp_path / "data"),
                         JsonlSink(tmp_path / "out"), batch_rows=8,
                         on_error="raise", host_index=0, host_count=1)


def test_unreadable_shard_quarantined_after_retries(tmp_path):
    total = _write_corpus(tmp_path / "data", [11, 13, 9])
    src = ShardedSource.jsonl(str(tmp_path / "data" / "*.jsonl"),
                              retry_policy=RetryPolicy(backoffs_ms=(1,)))
    poisoned = src.shards()[1].target
    sink = JsonlSink(tmp_path / "out")
    with inject_faults([FaultSpec("connection_error", match=poisoned,
                                  planes=("data",))]) as plan:
        report = transform_source(NumpyModel(), src, sink, batch_rows=8,
                                  host_index=0, host_count=1)
    assert plan.injected  # the fault actually fired (and was retried)
    assert report.shards_quarantined == 1 and report.shards_done == 2
    assert report.complete  # quarantined shard carries a zero-row DONE
    assert report.rows_written == total - 13
    errs = sink.error_records()
    assert any(e["kind"] == "shard" for e in errs)
    done = sink.completed()
    assert done[1]["quarantined"] and done[1]["rows"] == 0
    # deliberate re-score: drop the marker, rerun without the fault
    os.unlink(sink.done_path(1))
    report2 = transform_source(NumpyModel(), src, JsonlSink(tmp_path / "out"),
                               batch_rows=8, host_index=0, host_count=1)
    assert report2.shards_done == 1 and report2.rows_written == 13
    assert _ids(JsonlSink(tmp_path / "out").collect_rows()) \
        == list(range(total))


def test_string_and_object_columns_ride_through(tmp_path):
    """Scoring corpora carry string ids/urls and heterogeneous-key
    (object) passthrough columns — batch formation must pad them
    edge-style, not die in ``cb.pad_rows``."""
    os.makedirs(tmp_path / "data")
    n = 11  # forces a padded tail rung
    with open(tmp_path / "data" / "in-000.jsonl", "w") as f:
        for i in range(n):
            rec = {"x": [float(i), 1.0], "i": i, "url": f"https://r/{i}"}
            if i % 3 == 0:
                rec["extra"] = "only-sometimes"  # object column via None-fill
            f.write(json.dumps(rec) + "\n")
    sink = JsonlSink(tmp_path / "out")
    report = transform_source(NumpyModel(), _source(tmp_path / "data"), sink,
                              batch_rows=8, host_index=0, host_count=1)
    assert report.complete and report.rows_written == n
    rows = sink.collect_rows()
    assert [r["url"] for r in sorted(rows, key=lambda r: r["i"])] \
        == [f"https://r/{i}" for i in range(n)]


def test_shard_level_failure_quarantines_not_kills(tmp_path, monkeypatch):
    """A shard whose batch FORMATION fails (outside the per-batch row
    containment) is quarantined — aborted part, zero-row DONE, sidecar
    record, report rolled back to pre-shard — instead of killing the
    scan."""
    import synapseml_tpu.scoring.runner as runner_mod

    total = _write_corpus(tmp_path / "data", [9, 9])
    real_iter = runner_mod.iter_shard_batches

    def exploding_iter(cols, *a, **kw):
        if np.any(np.asarray(cols["i"]) == 12):  # second shard only
            raise RuntimeError("synthetic batch-formation failure")
        return real_iter(cols, *a, **kw)

    monkeypatch.setattr(runner_mod, "iter_shard_batches", exploding_iter)
    sink = JsonlSink(tmp_path / "out")
    report = transform_source(NumpyModel(), _source(tmp_path / "data"), sink,
                              batch_rows=16, host_index=0, host_count=1)
    assert report.complete
    assert report.shards_done == 1 and report.shards_quarantined == 1
    assert report.rows_written == 9
    done = sink.completed()
    assert done[1]["quarantined"] and done[1]["rows"] == 0
    assert _ids(sink.collect_rows()) == list(range(9))
    assert any("batch-formation" in e.get("error", "")
               for e in sink.error_records())
    # and on_error='raise' propagates it
    with pytest.raises(RuntimeError, match="batch-formation"):
        transform_source(NumpyModel(), _source(tmp_path / "data"),
                         JsonlSink(tmp_path / "out_raise"), batch_rows=16,
                         on_error="raise", host_index=0, host_count=1)


def test_npy_sink_skips_zero_row_parts(tmp_path):
    """A shard whose every row quarantined commits a zero-append part;
    ``collect_column`` must skip it rather than crash concatenating a
    dtype-less ``(0,)`` placeholder with real 2-D chunks."""
    from synapseml_tpu.io import files as f

    p1 = f.npy_writer(str(tmp_path / "part-00000.c.npy"))
    p1.append(np.ones((4, 3), np.float32))
    p1.commit()
    p0 = f.npy_writer(str(tmp_path / "part-00001.c.npy"))
    p0.commit()  # zero appends: (0,) float64 placeholder
    sink = NpySink(tmp_path, columns=["c"])
    for shard, name in ((0, "part-00000.c.npy"), (1, "part-00001.c.npy")):
        sink._mark_done({"shard": shard, "rows": 4 if shard == 0 else 0,
                         "files": [name], "host": 0, "quarantined": False})
    out = sink.collect_column("c")
    assert out.shape == (4, 3) and out.dtype == np.float32


def test_foreign_markers_and_torn_sidecar_lines_tolerated(tmp_path):
    """A foreign/malformed DONE marker is treated as incomplete (not a
    crash), and a torn trailing sidecar/cursor line (host killed
    mid-append) still yields the intact prefix."""
    total = _write_corpus(tmp_path / "data", [7, 7])
    src = _source(tmp_path / "data")
    sink = JsonlSink(tmp_path / "out")
    transform_source(NumpyModel(), src, sink, batch_rows=8,
                     host_index=0, host_count=1)
    # valid JSON, wrong shapes: all must read as "incomplete", not raise
    for name, body in (("part-00090.DONE", "{}"), ("part-00091.DONE", "null"),
                       ("part-00092.DONE", '{"shard": "x", "files": []}')):
        (tmp_path / "out" / name).write_text(body)
    assert sorted(JsonlSink(tmp_path / "out").completed()) == [0, 1]
    # resume still a no-op over the foreign markers
    r = transform_source(NumpyModel(), src, JsonlSink(tmp_path / "out"),
                         batch_rows=8, host_index=0, host_count=1)
    assert r.complete and r.shards_skipped == 2
    # torn tails: the intact prefix survives
    with open(tmp_path / "out" / "cursor-00000.jsonl", "a") as f:
        f.write('{"shard": 9, "rows"')
    with open(tmp_path / "out" / "errors-00000.jsonl", "a") as f:
        f.write('{"kind": "row", "half')
    s2 = JsonlSink(tmp_path / "out")
    assert len(s2.cursor_records()) >= 2
    assert s2.error_records() == []


def test_npy_collect_column_matches_exact_name(tmp_path):
    """Column 'a' must not also collect a dotted-suffix column 'raw.a'."""
    from synapseml_tpu.io import files as f

    sink = NpySink(tmp_path, columns=["a", "raw.a"])
    for name, fill in (("part-00000.a.npy", 1.0),
                       ("part-00000.raw.a.npy", 2.0)):
        w = f.npy_writer(str(tmp_path / name))
        w.append(np.full((3,), fill, np.float32))
        w.commit()
    sink._mark_done({"shard": 0, "rows": 3,
                     "files": ["part-00000.a.npy", "part-00000.raw.a.npy"],
                     "host": 0, "quarantined": False})
    assert np.array_equal(sink.collect_column("a"), np.full((3,), 1.0))
    assert np.array_equal(sink.collect_column("raw.a"), np.full((3,), 2.0))


def test_estimate_rows_custom_reader_gated_by_read_fallback(tmp_path):
    """The runner's progress gauge must not cost a full shard read on a
    custom-reader source: read_fallback=False raises, transform_source
    just reports no estimate."""
    from synapseml_tpu.data.source import Shard

    reads = []

    def read(shard):
        reads.append(shard.index)
        return {"x": np.ones((4, 2)), "i": np.arange(4)}

    src = ShardedSource([Shard(0, "custom", "mem", 0, 4),
                         Shard(1, "custom", "mem", 0, 4)], read)
    with pytest.raises(ValueError, match="read_fallback"):
        src.estimate_rows(read_fallback=False)
    assert reads == []  # the gate kept the gauge free
    report = transform_source(NumpyModel(), src,
                              JsonlSink(tmp_path / "out"), batch_rows=8,
                              host_index=0, host_count=1)
    assert report.complete and report.estimated_rows is None
    assert sorted(reads) == [0, 1]  # each shard read exactly once
    assert src.estimate_rows() == 8  # explicit opt-in still works


def test_estimate_rows_image_kind_is_metadata_cheap(tmp_path, monkeypatch):
    """Image shards' start/stop are file-listing offsets (one row per
    file): estimate_rows must answer from metadata without decoding a
    single image. Exactness (minus undecodable files the reader drops)
    stays total_rows()'s read pass."""
    import synapseml_tpu.io.files as iof

    d = tmp_path / "imgs"
    os.makedirs(d)
    for i in range(7):
        (d / f"im-{i}.png").write_bytes(b"\x89PNG\r\n\x1a\nfake")
    src = ShardedSource.image_dir(str(d), shard_files=3)
    monkeypatch.setattr(iof, "decode_image_bytes", lambda *a, **k: (
        (_ for _ in ()).throw(AssertionError("decoded an image"))))
    assert src.estimate_rows() == 7  # no decode happened


# ---------------------------------------------------------------------------
# sink atomicity + write faults
# ---------------------------------------------------------------------------

def test_sink_atomic_under_injected_write_fault(tmp_path, monkeypatch):
    total = _write_corpus(tmp_path / "data", [15, 15, 15])
    src = _source(tmp_path / "data")
    out = tmp_path / "out"

    real_commit = iofiles.StreamedJsonlWriter.commit
    state = {"fails": 1}

    def flaky_commit(self):
        if state["fails"] > 0:
            state["fails"] -= 1
            raise OSError("injected write fault")
        return real_commit(self)

    monkeypatch.setattr(iofiles.StreamedJsonlWriter, "commit", flaky_commit)
    with pytest.raises(OSError, match="injected write fault"):
        transform_source(NumpyModel(), src, JsonlSink(out), batch_rows=8,
                         host_index=0, host_count=1)
    # a sink failure is never quarantined and never leaves torn state:
    # no part without a DONE, no DONE without its payload, no temp litter
    sink = JsonlSink(out)
    listing = os.listdir(out)
    assert not [n for n in listing if ".tmp." in n], listing
    committed = {os.path.basename(p) for p in sink.part_files()}
    stray = [n for n in listing if n.startswith("part-")
             and not n.endswith(".DONE") and n not in committed]
    assert not stray, stray
    # resume completes and the merged output is exact
    report = transform_source(NumpyModel(), src, JsonlSink(out), batch_rows=8,
                              host_index=0, host_count=1)
    assert report.complete
    assert _ids(JsonlSink(out).collect_rows()) == list(range(total))


def test_part_files_appear_atomically_with_done_after_payload(tmp_path):
    _write_corpus(tmp_path / "data", [6])
    src = _source(tmp_path / "data")
    sink = JsonlSink(tmp_path / "out")
    order = []
    real_replace = os.replace

    def spy_replace(a, b):
        order.append(os.path.basename(b))
        return real_replace(a, b)

    try:
        os.replace = spy_replace
        transform_source(NumpyModel(), src, sink, batch_rows=8,
                         host_index=0, host_count=1)
    finally:
        os.replace = real_replace
    # payload rename strictly precedes its DONE marker, which precedes _SUCCESS
    assert order.index("part-00000.jsonl") \
        < order.index("part-00000.DONE") < order.index("_SUCCESS")


# ---------------------------------------------------------------------------
# bounded memory on a dataset >> one shard
# ---------------------------------------------------------------------------

def test_scan_memory_bounded_by_queue_not_dataset(tmp_path):
    # ~6 MB corpus in 24 shards; the runner may hold only
    # (prefetch + in-flight + write-queue) shards at once
    directory = tmp_path / "data"
    os.makedirs(directory)
    rs = np.random.default_rng(0)
    i = 0
    for s in range(24):
        with open(directory / f"in-{s:03d}.jsonl", "w") as f:
            for _ in range(512):
                f.write(json.dumps({"x": [round(float(v), 5)
                                          for v in rs.normal(size=16)],
                                    "i": i}) + "\n")
                i += 1
    dataset_bytes = sum(os.path.getsize(directory / n)
                        for n in os.listdir(directory))
    src = ShardedSource.jsonl(str(directory / "*.jsonl"))
    assert src.num_shards == 24
    report = transform_source(NumpyModel(), src, JsonlSink(tmp_path / "out"),
                              batch_rows=64, prefetch=2,
                              host_index=0, host_count=1)
    assert report.rows_written == i
    # peak buffered bytes stay a small multiple of one shard, far under
    # the dataset — the out-of-core guarantee
    shard_bytes = dataset_bytes / 24
    assert report.peak_inflight_bytes < 8 * shard_bytes
    assert report.peak_inflight_bytes < dataset_bytes / 3


def test_estimated_rows_feed_progress(tmp_path):
    total = _write_corpus(tmp_path / "data", [40, 40, 40])
    src = _source(tmp_path / "data")
    report = transform_source(NumpyModel(), src, JsonlSink(tmp_path / "out"),
                              batch_rows=16, host_index=0, host_count=1)
    assert report.estimated_rows is not None
    assert abs(report.estimated_rows - total) / total < 0.35


# ---------------------------------------------------------------------------
# sinks + planner units
# ---------------------------------------------------------------------------

def test_npy_sink_round_trip_and_done_lists_files(tmp_path):
    total = _write_corpus(tmp_path / "data", [10, 22])
    src = _source(tmp_path / "data")
    sink = NpySink(tmp_path / "out", columns=["score", "i"])
    report = transform_source(NumpyModel(), src, sink, batch_rows=8,
                              host_index=0, host_count=1)
    assert report.complete
    ids = sink.collect_column("i")
    assert sorted(ids.tolist()) == list(range(total))
    scores = sink.collect_column("score")
    assert scores.shape == (total,) and scores.dtype == np.float64
    done = sink.completed()
    assert sorted(done) == [0, 1]
    assert sorted(done[0]["files"]) == ["part-00000.i.npy",
                                        "part-00000.score.npy"]


def test_open_sink_factory(tmp_path):
    assert isinstance(open_sink(tmp_path / "a"), JsonlSink)
    assert isinstance(open_sink(tmp_path / "b", "npy", ["score"]), NpySink)
    with pytest.raises(ValueError, match="columns"):
        open_sink(tmp_path / "c", "npy")
    with pytest.raises(ValueError, match="unknown sink format"):
        open_sink(tmp_path / "d", "parquet")


def test_jsonl_sink_column_projection(tmp_path):
    _write_corpus(tmp_path / "data", [9])
    sink = JsonlSink(tmp_path / "out", columns=["i", "score"])
    transform_source(NumpyModel(), _source(tmp_path / "data"), sink,
                     batch_rows=8, host_index=0, host_count=1)
    rows = sink.collect_rows()
    assert all(sorted(r) == ["i", "score"] for r in rows)


def test_plan_buckets_are_the_warmup_set():
    src = MemorySource({"x": np.zeros((100, 2))}, shard_rows=30)
    plan = plan_scan(src, batch_rows=64, host_index=0, host_count=1)
    assert plan.buckets == tuple(
        cb.default_bucketer().buckets_upto(64))
    assert plan.num_shards == src.num_shards


def test_cursor_is_append_only_audit_trail(tmp_path):
    _write_corpus(tmp_path / "data", [5, 5, 5])
    src = _source(tmp_path / "data")
    sink = JsonlSink(tmp_path / "out")
    transform_source(NumpyModel(), src, sink, batch_rows=8,
                     host_index=0, host_count=1)
    recs = sink.cursor_records()
    assert [r["shard"] for r in recs] == [0, 1, 2]
    assert all(r["host"] == 0 and "ts" in r for r in recs)


def test_pipeline_model_rides_the_scoring_plane(tmp_path):
    total = _write_corpus(tmp_path / "data", [14])
    pm = PipelineModel(stages=[NumpyModel()])
    sink = JsonlSink(tmp_path / "out")
    report = pm.transform_source(_source(tmp_path / "data"), sink,
                                 batch_rows=8, host_index=0, host_count=1)
    assert report.complete and report.rows_written == total
    assert "score" in sink.collect_rows()[0]


def test_scoring_metrics_series_emitted(tmp_path):
    from synapseml_tpu.core import observability as obs

    _write_corpus(tmp_path / "data", [13])
    transform_source(NumpyModel(), _source(tmp_path / "data"),
                     JsonlSink(tmp_path / "out"), batch_rows=8,
                     host_index=0, host_count=1)
    text = obs.get_registry().exposition()
    for series in ("synapseml_scoring_rows_total",
                   "synapseml_scoring_padded_rows_total",
                   "synapseml_scoring_shards_total",
                   "synapseml_scoring_batch_ms",
                   "synapseml_scoring_rows_per_sec"):
        assert series in text, series


# ---------------------------------------------------------------------------
# satellite: io/files streamed writers + jsonl error context
# ---------------------------------------------------------------------------

def test_streamed_jsonl_writer_atomic_commit_and_abort(tmp_path):
    p = str(tmp_path / "x.jsonl")
    w = iofiles.jsonl_writer(p)
    w.write_row({"a": 1})
    assert not os.path.exists(p)  # nothing visible before commit
    w.commit()
    assert os.path.exists(p)
    w2 = iofiles.jsonl_writer(p)
    w2.write_row({"a": 999})
    w2.abort()
    assert [json.loads(ln) for ln in open(p)] == [{"a": 1}]  # untouched
    assert not [n for n in os.listdir(tmp_path) if ".tmp." in n]


def test_streamed_npy_writer_header_fixup(tmp_path):
    p = str(tmp_path / "x.npy")
    with iofiles.npy_writer(p) as w:
        w.append(np.arange(6, dtype=np.float32).reshape(3, 2))
        w.append(np.full((4, 2), 7, np.float32))
    arr = np.load(p)
    assert arr.shape == (7, 2) and arr.dtype == np.float32
    assert np.array_equal(arr[:3], np.arange(6).reshape(3, 2))
    with pytest.raises(ValueError, match="does not match"):
        with iofiles.npy_writer(str(tmp_path / "y.npy")) as w:
            w.append(np.zeros((2, 2), np.float32))
            w.append(np.zeros((2, 3), np.float32))


def test_read_jsonl_names_file_and_line_on_malformed_record(tmp_path):
    p = tmp_path / "bad.jsonl"
    p.write_text('{"a": 1}\n{"a": oops}\n{"a": 2}\n')
    with pytest.raises(ValueError, match=r"bad\.jsonl:2"):
        iofiles.read_jsonl(str(p))


def test_write_jsonl_is_atomic(tmp_path):
    df = DataFrame.from_dict({"a": np.arange(3)})
    p = str(tmp_path / "out.jsonl")
    iofiles.write_jsonl(df, p)
    assert len(open(p).readlines()) == 3
    assert not [n for n in os.listdir(tmp_path) if ".tmp." in n]


# ---------------------------------------------------------------------------
# satellite: estimate_rows
# ---------------------------------------------------------------------------

def test_estimate_rows_jsonl_within_tolerance(tmp_path):
    total = _write_corpus(tmp_path / "data", [500, 500, 500])
    src = _source(tmp_path / "data")
    est = src.estimate_rows()
    assert abs(est - total) / total < 0.25
    # memoized
    assert src.estimate_rows() == est


def test_estimate_rows_exact_for_row_range_kinds(tmp_path):
    src = MemorySource({"x": np.zeros((77, 2))}, shard_rows=10)
    assert src.estimate_rows() == 77
    np.save(tmp_path / "a.npy", np.zeros((33, 2), np.float32))
    nsrc = ShardedSource.npy(str(tmp_path / "a.npy"), shard_rows=10)
    assert nsrc.estimate_rows() == 33
