"""Fleet control plane: elastic autoscaling, multi-model residency,
admission control (ISSUE 13).

Offline throughout: registries in tmp dirs, in-process thread-launcher
workers on real HTTP ports, deterministic clocks for every policy unit.
The acceptance surfaces:

* admission under concurrent mixed-priority fire — bulk shed first,
  interactive protected, 429 + Retry-After, counters reconcile with
  client-observed outcomes;
* residency E2E — 4 published versions on 2 workers under a byte budget
  that fits only 3: LRU eviction fires, executables release, every model
  answers correctly throughout with zero failed requests;
* chaos — a worker killed mid-reconcile under a FaultPlan is replaced
  within one reconcile pass with no silently-dropped request.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from _aot_pipeline import build_pipeline, sample_rows
from synapseml_tpu.core import observability as obs
from synapseml_tpu.fleet import (AdmissionController, AdmissionPolicy,
                                 FleetAutoscaler, FleetSignals, FleetSpec,
                                 ModelSLO, ResidencyManager, ThreadWorkerLauncher,
                                 TokenBucket, WorkerHandle, WorkerLauncher,
                                 model_from_path, model_path,
                                 serve_multi_model)
from synapseml_tpu.io.distributed_serving import RoutingFront, WorkerRegistry
from synapseml_tpu.registry import ModelRegistry

pytestmark = pytest.mark.fleet


# ---------------------------------------------------------------------------
# shared fixtures
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def fleet_store(tmp_path_factory):
    """One registry with a single-model pipeline and four distinct small
    versions (m0..m3) for the residency tests."""
    root = str(tmp_path_factory.mktemp("fleet_store") / "store")
    registry = ModelRegistry(root)
    registry.publish("mlp", build_pipeline(), version="v1")
    for i in range(4):
        registry.publish(f"m{i}", build_pipeline(seed=10 + i), version="v1")
    return root


def _post(url: str, body: bytes, headers: dict | None = None,
          timeout: float = 30.0):
    """(status, parsed-json, headers) — HTTPErrors become statuses."""
    req = urllib.request.Request(url, data=body, method="POST",
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read() or b"{}"), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


# ---------------------------------------------------------------------------
# units: token bucket, admission policy, spec
# ---------------------------------------------------------------------------

def test_token_bucket_rate_refill_and_reserve_floor():
    t = [0.0]
    bucket = TokenBucket(10.0, 5.0, clock=lambda: t[0])
    assert all(bucket.try_take() for _ in range(5))
    assert not bucket.try_take()
    assert bucket.wait_time_s() == pytest.approx(0.1)
    t[0] += 0.35
    assert bucket.try_take() and bucket.try_take() and bucket.try_take()
    assert not bucket.try_take()
    # the floor (priority reserve): takes refuse while they'd dip below it
    t[0] += 0.2
    assert not bucket.try_take(floor=4.0)
    assert bucket.try_take(floor=1.0)
    with pytest.raises(ValueError):
        TokenBucket(0.0, 5.0)


def test_admission_bulk_sheds_before_interactive():
    t = [0.0]
    ctrl = AdmissionController(
        {"m": AdmissionPolicy(rate_rps=10.0, burst=10.0,
                              interactive_reserve=0.3)},
        clock=lambda: t[0])
    # bulk may spend down to the 3-token reserve: 7 admits
    bulk = [ctrl.admit("m", "bulk").admitted for _ in range(9)]
    assert bulk == [True] * 7 + [False] * 2
    # interactive still has the reserve
    assert all(ctrl.admit("m", "interactive").admitted for _ in range(3))
    assert not ctrl.admit("m", "interactive").admitted
    stats = ctrl.stats()["m"]
    assert stats["admitted"] == {"interactive": 3, "bulk": 7}
    assert stats["shed"] == {"interactive": 1, "bulk": 2}


def test_admission_p99_budget_sheds_newest_first_by_class():
    ctrl = AdmissionController(
        {"m": AdmissionPolicy(p99_budget_ms=100.0, hard_shed_factor=3.0,
                              retry_after_s=2.0)})
    assert ctrl.admit("m", "bulk").admitted  # no latency data yet
    for _ in range(50):
        ctrl.observe("m", 150.0)  # budget blown, under the 3x hard line
    shed = ctrl.admit("m", "bulk")
    assert not shed.admitted and shed.reason == "p99_budget" \
        and shed.status == 429 and shed.retry_after_s == 2.0
    # interactive rides through until the HARD line
    assert ctrl.admit("m", "interactive").admitted
    for _ in range(50):
        ctrl.observe("m", 400.0)  # > 3x budget: total overload
    assert not ctrl.admit("m", "interactive").admitted
    # unknown models pass (no policy, no default)
    assert ctrl.admit("other", "bulk").admitted


def test_admission_p99_shed_cannot_lock_out_forever():
    """Shed requests never reach a worker, so they never feed the latency
    window — once no observation has landed for retry_after_s, the next
    request admits as a PROBE (at ~1/retry_after_s cadence) instead of the
    model shedding 429s forever on a stale p99."""
    t = [0.0]
    ctrl = AdmissionController(
        {"m": AdmissionPolicy(p99_budget_ms=100.0, hard_shed_factor=1.5,
                              retry_after_s=1.0)},
        clock=lambda: t[0])
    for _ in range(50):
        ctrl.observe("m", 500.0)  # blown past the hard line
    assert not ctrl.admit("m", "interactive").admitted  # fresh: shed
    t[0] = 1.5  # past retry_after_s with zero observations: probe admits
    assert ctrl.admit("m", "interactive").admitted
    # ONE probe per window: the grant stamps the window, so the rest of
    # the offered load sheds while the (possibly slow) probe is in flight
    assert not ctrl.admit("m", "interactive").admitted
    ctrl.observe("m", 500.0)  # the probe came back (still slow)
    assert not ctrl.admit("m", "bulk").admitted  # fresh again: shed
    t[0] = 3.0
    assert ctrl.admit("m", "bulk").admitted  # next probe window


def test_fleet_spec_json_round_trip_and_validation():
    spec = FleetSpec(
        models=[ModelSLO(model="a", ref="prod", min_workers=1,
                         max_workers=8, p95_slo_ms=50.0,
                         admission=AdmissionPolicy(rate_rps=100.0),
                         serve={"batch_interval_ms": 10})],
        reconcile_interval_s=0.5, byte_budget=1 << 20)
    again = FleetSpec.from_json(spec.to_json())
    assert again.models[0].admission.burst == 200.0  # 2x rate default
    assert again.models[0].serve == {"batch_interval_ms": 10}
    assert again.slo_for("a").max_workers == 8
    assert again.slo_for("missing") is None
    with pytest.raises(ValueError, match="min_workers"):
        ModelSLO(model="x", min_workers=3, max_workers=2)
    with pytest.raises(ValueError, match="duplicate"):
        FleetSpec(models=[ModelSLO(model="a"), ModelSLO(model="a")])
    with pytest.raises(ValueError, match="interactive_reserve"):
        AdmissionPolicy(interactive_reserve=1.5)
    # a config where bulk could NEVER take a token (reserve + 1 > burst)
    # is a silent permanent blackhole — refused at construction
    with pytest.raises(ValueError, match="never be admitted"):
        AdmissionPolicy(rate_rps=0.5)  # default burst 1.0, reserve 0.2


def test_model_path_round_trip():
    assert model_path("mlp") == "/m/mlp"
    assert model_from_path("/m/mlp") == "mlp"
    assert model_from_path("/m/mlp/extra") == "mlp"
    # query/fragment suffixes must not leak into the model key (they
    # would defeat eligibility routing and mint bogus admission labels)
    assert model_from_path("/m/mlp?debug=1") == "mlp"
    assert model_from_path("/m/mlp/x?k=v#frag") == "mlp"
    assert model_from_path("/") is None
    assert model_from_path("/stats") is None
    assert model_from_path("/m/") is None
    assert model_from_path("/m/?k=v") is None


# ---------------------------------------------------------------------------
# unit: autoscaler policy against a fake launcher + scripted signals
# ---------------------------------------------------------------------------

class FakeLauncher(WorkerLauncher):
    def __init__(self):
        self.n = 0
        self.dead: set[int] = set()
        self.drained: list[int] = []

    def spawn(self, slo):
        self.n += 1
        return WorkerHandle(model=slo.model, token=self.n, pid=-self.n,
                            host="127.0.0.1", port=self.n,
                            spawned_at=self.n, state="ready")

    def alive(self, h):
        return h.token not in self.dead

    def drain(self, h, timeout_s=30.0):
        self.drained.append(h.token)
        self.dead.add(h.token)  # a fake drain completes instantly
        return True

    def kill(self, h):
        self.dead.add(h.token)

    def reap(self, h):
        pass


def test_autoscaler_policy_doubling_cooldown_streaks_and_replacement():
    t = [0.0]
    sig = [FleetSignals()]
    slo = ModelSLO(model="m", min_workers=1, max_workers=8,
                   target_queue_depth=4.0, scale_down_after=2,
                   up_cooldown_s=10.0, down_cooldown_s=5.0)
    spec = FleetSpec(models=[slo], reconcile_interval_s=1.0)
    launcher = FakeLauncher()
    asc = FleetAutoscaler(spec, launcher, clock=lambda: t[0],
                          signals_fn=lambda s, live: sig[0])
    events = asc.reconcile_once()
    assert [e["event"] for e in events] == ["spawn"]  # to min_workers
    assert asc.actual("m") == asc.desired("m") == 1

    # overload: desired doubles...
    t[0] = 1.0
    sig[0] = FleetSignals(queue_per_worker=10.0)
    events = asc.reconcile_once()
    assert {e["event"] for e in events} == {"up", "spawn"}
    assert asc.desired("m") == 2 and asc.actual("m") == 2
    # ...but not inside the up-cooldown
    t[0] = 2.0
    assert asc.reconcile_once() == []
    assert asc.desired("m") == 2
    t[0] = 12.0
    asc.reconcile_once()
    assert asc.desired("m") == 4
    t[0] = 23.0
    asc.reconcile_once()
    assert asc.desired("m") == 8  # clamped at max
    t[0] = 34.0
    assert not [e for e in asc.reconcile_once() if e["event"] == "up"]

    # p95 SLO breach alone also counts as overload
    slo95 = ModelSLO(model="p", min_workers=1, max_workers=4,
                     p95_slo_ms=50.0, up_cooldown_s=0.0)
    asc95 = FleetAutoscaler(FleetSpec(models=[slo95]), FakeLauncher(),
                            clock=lambda: t[0],
                            signals_fn=lambda s, live: FleetSignals(
                                queue_per_worker=0.0, p95_ms=80.0))
    asc95.reconcile_once()
    assert asc95.desired("p") == 2  # p95 breach alone scaled it

    # crash replacement happens within the SAME reconcile pass
    victims = asc.live_handles("m")[:2]
    for h in victims:
        launcher.kill(h)
    t[0] = 35.0
    events = asc.reconcile_once()
    assert [e["event"] for e in events].count("lost") == 2
    assert [e["event"] for e in events].count("spawn") == 2
    assert asc.actual("m") == 8

    # scale-down needs a sustained underload streak, then drains by ONE
    t[0] = 36.0
    sig[0] = FleetSignals(queue_per_worker=0.0)
    assert not [e for e in asc.reconcile_once() if e["event"] == "down"]
    t[0] = 37.0
    events = asc.reconcile_once()
    assert [e["event"] for e in events] == ["down", "drain"]
    assert asc.desired("m") == 7
    # the NEWEST worker was picked: the most recently spawned token
    assert launcher.drained == [launcher.n]
    # the drained worker reaps on the next pass
    t[0] = 38.0
    events = asc.reconcile_once()
    assert "drained" in [e["event"] for e in events]
    # worker-seconds integrated over the whole run
    assert asc.worker_seconds["m"] > 0.0


def test_autoscaler_spawn_failure_does_not_kill_the_loop():
    class FailingLauncher(FakeLauncher):
        def spawn(self, slo):
            raise RuntimeError("no capacity")

    asc = FleetAutoscaler(
        FleetSpec(models=[ModelSLO(model="m")]), FailingLauncher(),
        signals_fn=lambda s, live: FleetSignals())
    events = asc.reconcile_once()
    assert [e["event"] for e in events] == ["spawn_failed"]
    assert asc.actual("m") == 0
    # and the next pass retries
    assert [e["event"] for e in asc.reconcile_once()] == ["spawn_failed"]


def test_autoscaler_default_signals_read_prefix_hit_rate():
    """The stats poller surfaces the fleet-mean prefix-cache hit rate from
    /admin/stats (the admin mirror of ``synapseml_llm_prefix_hit_rate``):
    LLM workers contribute, workers without an ``llm`` block are skipped,
    and a reconcile pass over signals carrying the new field behaves
    exactly as before — the hit rate is advisory telemetry, not a scaling
    trigger."""
    import http.server

    payloads = [
        {"queue_depth": 2, "llm": {"prefix_cache": {"hit_rate": 0.8}}},
        {"queue_depth": 4, "llm": {"prefix_cache": {"hit_rate": 0.4}}},
        {"queue_depth": 0},  # a non-LLM worker: no llm block at all
    ]
    servers, handles = [], []
    for i, payload in enumerate(payloads):
        raw = json.dumps(payload).encode()

        class H(http.server.BaseHTTPRequestHandler):
            _body = raw

            def do_GET(self):
                self.send_response(200)
                self.send_header("Content-Length", str(len(self._body)))
                self.end_headers()
                self.wfile.write(self._body)

            def log_message(self, *a):
                pass

        srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        servers.append(srv)
        handles.append(WorkerHandle(model="m", token=i + 1, pid=-(i + 1),
                                    host="127.0.0.1",
                                    port=srv.server_address[1],
                                    spawned_at=0.0, state="ready"))
    slo = ModelSLO(model="m")
    asc = FleetAutoscaler(FleetSpec(models=[slo]), FakeLauncher())
    try:
        sig = asc._default_signals(slo, handles)
        assert sig.workers_polled == 3
        assert sig.queue_per_worker == pytest.approx(2.0)
        assert sig.prefix_hit_rate == pytest.approx(0.6)  # mean of LLM two
    finally:
        for srv in servers:
            srv.shutdown()

    # reconcile non-regression: the same doubling policy fires on queue
    # depth whether or not prefix_hit_rate rides along
    t = [0.0]
    asc2 = FleetAutoscaler(
        FleetSpec(models=[ModelSLO(model="m", min_workers=1, max_workers=4,
                                   target_queue_depth=4.0,
                                   up_cooldown_s=0.0)]),
        FakeLauncher(), clock=lambda: t[0],
        signals_fn=lambda s, live: FleetSignals(queue_per_worker=10.0,
                                                prefix_hit_rate=0.9))
    events = asc2.reconcile_once()  # spawn to min + immediate doubling
    assert {e["event"] for e in events} == {"up", "spawn"}
    assert asc2.desired("m") == asc2.actual("m") == 2
    t[0] = 1.0
    asc2.reconcile_once()
    assert asc2.desired("m") == 4


# ---------------------------------------------------------------------------
# integration: thread-launcher workers on real ports
# ---------------------------------------------------------------------------

def _mk_fleet(store, spec, admission=None, front_timeout_s=30.0):
    wreg = WorkerRegistry()
    launcher = ThreadWorkerLauncher(store, wreg)
    front = RoutingFront(registry=wreg, admission=admission,
                         timeout_s=front_timeout_s)
    asc = FleetAutoscaler(spec, launcher, front=front, worker_registry=wreg)
    return wreg, launcher, front, asc


def _teardown(wreg, front, asc):
    asc.stop()
    front.close()
    wreg.close()


def test_worker_admin_stats_and_graceful_drain_zero_drops(fleet_store):
    spec = FleetSpec(models=[ModelSLO(model="mlp", ref="v1")])
    wreg, launcher, front, asc = _mk_fleet(fleet_store, spec)
    try:
        asc.reconcile_once()
        asc.wait_ready("mlp", 1, timeout_s=30)
        w = wreg.workers()[0]
        endpoint = f"http://{w['host']}:{w['port']}"
        with urllib.request.urlopen(endpoint + "/admin/stats",
                                    timeout=10) as r:
            stats = json.loads(r.read())
        assert stats["draining"] is False and stats["queue_depth"] == 0
        assert stats["swap"]["mode"] in ("jit", "aot")

        # concurrent fire, drain mid-stream: every accepted request gets a
        # REAL reply; requests arriving after the drain get terminal 503s
        body = json.dumps(sample_rows(1)[0]).encode()
        outcomes: list = []
        lock = threading.Lock()

        def client(n):
            for _ in range(n):
                try:
                    status, payload, _ = _post(endpoint + "/", body)
                except OSError as e:  # URLError / ConnectionResetError
                    # post-exit TCP refusal/reset: a TERMINAL transport
                    # outcome on a connection the worker never ACCEPTED a
                    # request from (accepted exchanges always reply before
                    # the drained server exits) — only a TIMEOUT would be
                    # a silently-dropped exchange
                    reason = getattr(e, "reason", e)
                    assert "timed out" not in str(reason).lower()
                    status, payload = "refused", str(reason)
                with lock:
                    outcomes.append((status, payload))

        threads = [threading.Thread(target=client, args=(10,))
                   for _ in range(4)]
        for th in threads:
            th.start()
        status, reply, _ = _post(endpoint + "/admin/drain", b"{}")
        assert status == 200 and reply["draining"] is True
        for th in threads:
            th.join(timeout=60)
        # zero dropped exchanges: every request has a terminal outcome —
        # a 200 with a prediction, a 503 naming the drain, or (after the
        # drained worker exited) a clean TCP refusal
        assert len(outcomes) == 40
        for status, payload in outcomes:
            assert status in (200, 503, "refused"), (status, payload)
            if status == 503:
                assert "drain" in json.dumps(payload)
        assert any(status in (503, "refused") for status, _ in outcomes)
        # the worker deregistered itself (drain != crash)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and wreg.workers():
            time.sleep(0.05)
        assert wreg.workers() == []
    finally:
        _teardown(wreg, front, asc)


def test_drain_endpoint_robustness_and_label_cap(fleet_store):
    spec = FleetSpec(models=[ModelSLO(model="mlp", ref="v1")])
    wreg, launcher, front, asc = _mk_fleet(fleet_store, spec)
    try:
        asc.reconcile_once()
        asc.wait_ready("mlp", 1, timeout_s=30)
        w = wreg.workers()[0]
        endpoint = f"http://{w['host']}:{w['port']}"
        # valid-JSON non-object drain body is a 400, never a raw 500
        status, reply, _ = _post(endpoint + "/admin/drain", b"[1]")
        assert status == 400 and "JSON object" in reply["error"]
        # two racing drains fire on_drained ONCE (one deregistration, one
        # waiter) — the second reply reports already_draining
        drained = []
        launcher._handles[0].token.on_drained = \
            (lambda cb: lambda r: (drained.append(r), cb(r)))(
                launcher._handles[0].token.on_drained)
        s1, r1, _ = _post(endpoint + "/admin/drain", b"{}")
        assert s1 == 200 and r1["already_draining"] is False
        try:
            s2, r2, _ = _post(endpoint + "/admin/drain", b"{}")
        except OSError:
            s2, r2 = None, None  # the first drain already stopped the
        if s2 is not None:       # server: a clean refusal, not a hang
            assert s2 == 200 and r2["already_draining"] is True
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not drained:
            time.sleep(0.05)
        time.sleep(0.3)
        assert len(drained) == 1
        # client-controlled /m/<model> labels cannot grow the front's stats
        # without bound: past the cap, new labels collapse to "other"
        for i in range(RoutingFront._MAX_TRACKED_LABELS + 20):
            front._record_shed(f"scan-{i}", "bulk")
        stats = front.version_stats()
        assert len(stats) <= RoutingFront._MAX_TRACKED_LABELS + 1
        assert stats["other"]["shed"]["bulk"] >= 20
    finally:
        _teardown(wreg, front, asc)


def test_elastic_scale_up_and_drain_down_over_http(fleet_store):
    sig = [FleetSignals(queue_per_worker=0.0)]
    spec = FleetSpec(models=[ModelSLO(
        model="mlp", ref="v1", min_workers=1, max_workers=3,
        target_queue_depth=2.0, scale_down_after=1,
        up_cooldown_s=0.0, down_cooldown_s=0.0)])
    wreg, launcher, front, asc = _mk_fleet(fleet_store, spec)
    asc._signals_fn = lambda slo, live: sig[0]
    try:
        asc.reconcile_once()
        asc.wait_ready("mlp", 1, timeout_s=30)
        sig[0] = FleetSignals(queue_per_worker=10.0)
        asc.reconcile_once()
        asc.wait_ready("mlp", 2, timeout_s=30)  # REAL second worker, routable
        body = json.dumps(sample_rows(1)[0]).encode()
        served_by = set()
        for _ in range(16):
            req = urllib.request.Request(front.address + "/m/mlp",
                                         data=body, method="POST")
            with urllib.request.urlopen(req, timeout=30) as r:
                assert r.status == 200
                served_by.add(r.headers.get("X-Served-By"))
        assert len(served_by) == 2  # round-robin spreads over both
        # underload: drain back down — the drained worker leaves the table
        sig[0] = FleetSignals(queue_per_worker=0.0)
        asc.reconcile_once()
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and len(wreg.workers()) > 1:
            time.sleep(0.05)
        assert len(wreg.workers()) == 1
        assert asc.desired("mlp") == 1
    finally:
        _teardown(wreg, front, asc)


def test_model_routing_never_answers_with_the_wrong_model(fleet_store):
    """A request naming /m/<B> must never be served by model A's pipeline:
    when every B-capable worker is gone, the front answers an honest 503
    instead of forwarding to an ineligible single-model worker."""
    spec = FleetSpec(models=[ModelSLO(model="m0", ref="v1"),
                             ModelSLO(model="m1", ref="v1")])
    wreg, launcher, front, asc = _mk_fleet(fleet_store, spec,
                                           front_timeout_s=5.0)
    try:
        asc.reconcile_once()
        asc.wait_ready("m0", 1, timeout_s=30)
        asc.wait_ready("m1", 1, timeout_s=30)
        row = sample_rows(1, seed=3)[0]
        status, payload, _ = _post(front.address + model_path("m1"),
                                   json.dumps(row).encode())
        assert status == 200
        victim = asc.live_handles("m1")[0]
        launcher.kill(victim)
        # a stopped thread-worker closes its LISTENER instantly but its
        # serve loop drains one final poll (~15 ms) reachable through the
        # front's pooled keep-alive connection — wait for refusal AND the
        # final-poll window before asserting
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                _post(victim.endpoint + "/", json.dumps(row).encode(),
                      timeout=2)
            except OSError:
                break
            time.sleep(0.05)
        time.sleep(0.3)
        # m0's worker stays healthy, but it is INELIGIBLE for /m/m1 —
        # the reply must be a 503, never m0's prediction
        for _ in range(4):
            status, payload, _ = _post(front.address + model_path("m1"),
                                       json.dumps(row).encode())
            assert status == 503, (status, payload)
        # m0 itself keeps serving
        status, _p, _ = _post(front.address + model_path("m0"),
                              json.dumps(row).encode())
        assert status == 200
    finally:
        _teardown(wreg, front, asc)


def test_admission_default_policy_state_is_bounded():
    ctrl = AdmissionController(
        default=AdmissionPolicy(rate_rps=100000.0, burst=100000.0))
    for i in range(AdmissionController._MAX_DEFAULT_MODELS + 50):
        assert ctrl.admit(f"scan-{i}").admitted
    # past the cap, random model strings share one overflow state — and
    # mint NO fresh Prometheus label (registry children live forever)
    assert len(ctrl.stats()) <= AdmissionController._MAX_DEFAULT_MODELS + 1
    assert "_overflow" in ctrl.stats()
    family = obs.get_registry().counter(
        "synapseml_fleet_admitted_total",
        "requests admitted by the fleet admission controller",
        ("model", "priority"))
    labels = {dict(k)["model"] for k, _ in family._child_items()}
    assert not any(lbl.startswith("scan-5")
                   and int(lbl.split("-")[1])
                   >= AdmissionController._MAX_DEFAULT_MODELS
                   for lbl in labels if lbl.startswith("scan-"))
    assert "_overflow" in labels


def test_admission_failed_replies_do_not_dilute_the_p99_window():
    """Fast failure replies (queue-full 503s during overload) must not
    pull the p99 down and reopen admission into a saturated fleet."""
    t = [0.0]
    ctrl = AdmissionController(
        {"m": AdmissionPolicy(p99_budget_ms=100.0, hard_shed_factor=1.5,
                              retry_after_s=10.0)},
        clock=lambda: t[0])
    for _ in range(50):
        ctrl.observe("m", 500.0)
    assert not ctrl.admit("m", "interactive").admitted
    for _ in range(300):  # a flood of fast 503s
        ctrl.observe("m", 2.0, ok=False)
    assert ctrl.p99_ms("m") == 500.0  # window undiluted
    assert not ctrl.admit("m", "interactive").admitted  # still shedding


def test_admission_under_concurrent_mixed_priority_fire(fleet_store):
    """ISSUE satellite: mixed interactive/bulk clients against one
    throttled model — bulk shed first, interactive p99 within budget, 429
    + Retry-After on the wire, controller counters reconcile with
    client-observed outcomes."""
    p99_budget_ms = 2000.0
    # rate sized so the PACED interactive stream (2 clients x 10 at 100 ms
    # ~ 18 rps) sits well under rate + reserve, while the unpaced bulk
    # flood must blow through the bucket
    policy = AdmissionPolicy(rate_rps=40.0, burst=16.0,
                             interactive_reserve=0.25,
                             p99_budget_ms=p99_budget_ms,
                             retry_after_s=0.5)
    spec = FleetSpec(models=[ModelSLO(model="mlp", ref="v1",
                                      admission=policy)])
    ctrl = AdmissionController.from_spec(spec)
    wreg, launcher, front, asc = _mk_fleet(fleet_store, spec,
                                           admission=ctrl)
    try:
        asc.reconcile_once()
        asc.wait_ready("mlp", 1, timeout_s=30)
        body = json.dumps(sample_rows(1)[0]).encode()
        url = front.address + model_path("mlp")
        results: dict[str, list] = {"interactive": [], "bulk": []}
        lock = threading.Lock()

        def fire(priority: str, n: int, pace_s: float):
            headers = ({"X-Priority": "bulk"} if priority == "bulk" else {})
            for _ in range(n):
                t0 = time.perf_counter()
                status, _payload, hdrs = _post(url, body, headers)
                lat_ms = (time.perf_counter() - t0) * 1e3
                with lock:
                    results[priority].append((status, lat_ms, hdrs))
                if pace_s:
                    time.sleep(pace_s)

        threads = (
            [threading.Thread(target=fire, args=("interactive", 10, 0.1))
             for _ in range(2)]
            + [threading.Thread(target=fire, args=("bulk", 25, 0.0))
               for _ in range(4)])
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=120)

        i_status = [s for s, _, _ in results["interactive"]]
        b_status = [s for s, _, _ in results["bulk"]]
        assert set(i_status) | set(b_status) <= {200, 429}
        # bulk is shed FIRST: the unpaced flood mostly bounces, while the
        # paced interactive stream (within rate+reserve) is untouched
        assert b_status.count(429) > len(b_status) // 2
        assert i_status.count(429) == 0
        # every shed reply carried Retry-After
        for status, _, hdrs in results["interactive"] + results["bulk"]:
            if status == 429:
                assert int(hdrs.get("Retry-After")) >= 1
        # interactive p99 stays within the declared budget
        i_lat = sorted(lat for _, lat, _ in results["interactive"])
        assert i_lat[int(len(i_lat) * 0.99)] < p99_budget_ms
        # counters reconcile EXACTLY with client-observed outcomes
        stats = ctrl.stats()["mlp"]
        assert stats["admitted"]["interactive"] == i_status.count(200)
        assert stats["admitted"]["bulk"] == b_status.count(200)
        assert stats["shed"]["bulk"] == b_status.count(429)
        assert stats["shed"]["interactive"] == 0
        # ...and with the front's per-priority version stats (satellite)
        vstats = front.version_stats()["mlp"]
        assert vstats["shed"]["bulk"] == b_status.count(429)
        assert vstats["inflight"] == {"interactive": 0, "bulk": 0}
        # /stats exposes the admission snapshot
        with urllib.request.urlopen(front.address + "/stats",
                                    timeout=10) as r:
            front_stats = json.loads(r.read())
        assert front_stats["admission"]["mlp"]["shed"]["bulk"] \
            == b_status.count(429)
    finally:
        _teardown(wreg, front, asc)


def test_split_weights_exported_as_gauges(fleet_store):
    wreg = WorkerRegistry()

    def split_lines():
        return {ln for ln in obs.get_registry().exposition().splitlines()
                if ln.startswith("synapseml_route_split_weight{")}

    before = split_lines()
    front = RoutingFront(registry=wreg)
    try:
        front.set_traffic_split({"va": 0.75, "vb": 0.25})
        ours = split_lines() - before  # the instance label isolates us
        weights = {}
        for ln in ours:
            if 'version="va"' in ln:
                weights["va"] = float(ln.rsplit(" ", 1)[1])
            if 'version="vb"' in ln:
                weights["vb"] = float(ln.rsplit(" ", 1)[1])
        assert weights == {"va": 0.75, "vb": 0.25}
        # a cleared split stops exporting
        front.set_traffic_split(None)
        assert split_lines() - before == set()
    finally:
        front.close()
        wreg.close()


# ---------------------------------------------------------------------------
# residency E2E (acceptance): 4 models, 2 workers, budget fits 3
# ---------------------------------------------------------------------------

def _expected_reply(seed: int, row: dict) -> dict:
    """The ground-truth reply for one request row, computed by driving a
    locally-built copy of the published pipeline through the EXACT
    serve-loop batch preparation."""
    from synapseml_tpu.core.dataframe import DataFrame
    from synapseml_tpu.io.serving import _prepare_batch

    batch = DataFrame([{
        "id": np.asarray(["x"], dtype=object),
        "method": np.asarray(["POST"], dtype=object),
        "path": np.asarray(["/"], dtype=object),
        "body": np.asarray([json.dumps(row).encode()], dtype=object),
    }])
    out = build_pipeline(seed=seed).transform(
        _prepare_batch(batch, parse_json=True, input_col="body"))
    return out.collect_column("reply")[0]


def test_residency_e2e_four_models_two_workers_budget_fits_three(fleet_store):
    # measure one artifact, then budget for 3.5 of them per worker
    probe = ResidencyManager(fleet_store, byte_budget=1 << 30)
    probe.acquire("m0")
    per_model = probe.resident()["m0"]["nbytes"]
    probe.release_all()
    budget = int(per_model * 3.5)

    wreg = WorkerRegistry()
    servers = []
    for pid in (1, 2):
        res = ResidencyManager(fleet_store, byte_budget=budget)
        srv = serve_multi_model(res, batch_interval_ms=2)
        servers.append(srv)
        urllib.request.urlopen(urllib.request.Request(
            wreg.address + "/register",
            data=json.dumps({"host": srv.host, "port": srv.port,
                             "pid": -pid, "models": []}).encode(),
            method="POST"), timeout=10).read()
    front = RoutingFront(registry=wreg)
    rows = {i: sample_rows(1, seed=100 + i)[0] for i in range(4)}
    expected = {i: _expected_reply(10 + i, rows[i]) for i in range(4)}
    # the four models answer DIFFERENTLY (seeds differ), so a routing or
    # residency mix-up cannot pass the correctness check by accident
    assert len({json.dumps(e["probs"]) for e in expected.values()}) == 4

    reg = obs.get_registry()
    evictions = reg.counter("synapseml_fleet_evictions_total",
                            "residency LRU evictions", ("model",))
    loads = reg.counter("synapseml_fleet_model_loads_total",
                        "residency slot lookups", ("model", "outcome"))
    ev0 = sum(evictions.labels(model=f"m{i}").value for i in range(4))
    miss0 = sum(loads.labels(model=f"m{i}", outcome="miss").value
                for i in range(4))
    try:
        failures = []
        # cycle all four models with an ODD number of requests per round:
        # the front's round-robin parity shifts every round, so BOTH
        # workers see all 4 models over the run and each worker's 3-slot
        # LRU must churn (an even cycle would pin each model to one
        # worker and never evict)
        for round_i in range(8):
            for i in [0, 1, 2, 3, round_i % 4]:
                status, payload, _ = _post(
                    front.address + model_path(f"m{i}"),
                    json.dumps(rows[i]).encode())
                if status != 200 or payload != expected[i]:
                    failures.append((round_i, i, status, payload))
        assert not failures, failures[:3]  # zero failed requests, all exact
        ev1 = sum(evictions.labels(model=f"m{i}").value for i in range(4))
        miss1 = sum(loads.labels(model=f"m{i}", outcome="miss").value
                    for i in range(4))
        assert ev1 - ev0 > 0  # the budget forced LRU evictions
        # every eviction's re-load is a residency MISS (retrace/AOT-rehit
        # visible in the loads counter), and each worker holds <= 3
        assert miss1 - miss0 >= (ev1 - ev0)
        for srv in servers:
            resident = srv.residency.resident()
            assert len(resident) <= 3
            assert srv.residency.resident_bytes() <= budget
    finally:
        front.close()
        wreg.close()
        for srv in servers:
            srv.residency.release_all()
            srv.stop()


def test_residency_refuses_an_artifact_larger_than_the_budget(fleet_store):
    res = ResidencyManager(fleet_store, byte_budget=16)
    with pytest.raises(ValueError, match="exceeds the whole"):
        res.acquire("m0")
    with pytest.raises(KeyError, match="neither a version nor an alias"):
        ResidencyManager(fleet_store, byte_budget=1 << 30).acquire("ghost")


def test_residency_failed_load_never_evicts_healthy_neighbors(fleet_store):
    """A broken model (unresolvable ref here; failed warmup behaves the
    same — eviction runs only AFTER a successful load) must fail its own
    request without tearing down the working set."""
    res = ResidencyManager(fleet_store, byte_budget=1 << 30,
                           refs={"m3": "ghost-ref"})
    for m in ("m0", "m1", "m2"):
        res.acquire(m)
    before = res.resident()
    assert set(before) == {"m0", "m1", "m2"}
    for _ in range(3):  # every retry fails, neighbors stay intact
        with pytest.raises(KeyError):
            res.acquire("m3")
    assert res.resident() == before
    res.release_all()


def test_trusted_version_labels_bypass_the_client_label_cap(fleet_store):
    """A path scanner filling the label cap must not blind the canary
    rollback controller: worker VERSION labels (trusted, server-side)
    always get their own version_stats entry."""
    wreg = WorkerRegistry()
    front = RoutingFront(registry=wreg)
    try:
        for i in range(RoutingFront._MAX_TRACKED_LABELS + 5):
            front._record_shed(f"scan-{i}", "bulk")
        front._record_version("canary-v2", ok=False, latency_ms=9.0)
        stats = front.version_stats()
        assert "canary-v2" in stats and stats["canary-v2"]["err"] == 1
    finally:
        front.close()
        wreg.close()


def test_admission_observe_reaches_the_overflow_state():
    ctrl = AdmissionController(
        default=AdmissionPolicy(rate_rps=100000.0, burst=100000.0,
                                p99_budget_ms=100.0))
    for i in range(AdmissionController._MAX_DEFAULT_MODELS + 5):
        ctrl.admit(f"scan-{i}")
    over_cap = f"scan-{AdmissionController._MAX_DEFAULT_MODELS + 1}"
    for _ in range(50):
        ctrl.observe(over_cap, 500.0)  # must land in _overflow
    assert ctrl.stats()["_overflow"]["p99_ms"] == 500.0
    # ...so p99 shedding engages for over-cap models too
    assert not ctrl.admit(over_cap, "bulk").admitted


# ---------------------------------------------------------------------------
# chaos: kill a worker mid-reconcile under a FaultPlan
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_chaos_worker_kill_mid_reconcile_replaced_no_silent_drops(fleet_store):
    from synapseml_tpu.core.faults import FaultSpec, inject_faults

    spec = FleetSpec(models=[ModelSLO(model="mlp", ref="v1", min_workers=2,
                                      max_workers=2)],
                     reconcile_interval_s=0.25)
    # short front timeout: a blackholed/killed worker costs one bounded
    # stall, then the breaker + reroute take over
    wreg, launcher, front, asc = _mk_fleet(fleet_store, spec,
                                           front_timeout_s=5.0)
    body = json.dumps(sample_rows(1)[0]).encode()
    outcomes: list = []
    stop_fire = threading.Event()
    lock = threading.Lock()

    def fire():
        while not stop_fire.is_set():
            try:
                status, _payload, _ = _post(front.address + "/m/mlp", body,
                                            timeout=20)
            except OSError as e:  # a TRANSPORT failure would be a drop
                status = f"transport:{e}"
            with lock:
                outcomes.append(status)
            time.sleep(0.01)

    try:
        asc.reconcile_once()
        asc.wait_ready("mlp", 2, timeout_s=30)
        asc.start()  # the live reconcile loop the kill lands inside
        threads = [threading.Thread(target=fire) for _ in range(3)]
        with inject_faults([FaultSpec("connection_error", times=3,
                                      planes=("distributed_serving",))]):
            for th in threads:
                th.start()
            time.sleep(0.5)
            victim = asc.live_handles("mlp")[0]
            launcher.kill(victim)  # SIGKILL analog: socket slams shut
            t_kill = time.monotonic()
            # replaced within one reconcile interval: a NEW live worker
            # appears (the loop reaps the corpse and respawns in one pass)
            deadline = t_kill + 10.0
            while time.monotonic() < deadline:
                handles = asc.live_handles("mlp")
                if len(handles) == 2 and victim not in handles:
                    break
                time.sleep(0.05)
            replaced_after = time.monotonic() - t_kill
            assert len(asc.live_handles("mlp")) == 2
            time.sleep(0.5)  # serve through the replacement under fire
            stop_fire.set()
            for th in threads:
                th.join(timeout=30)
        # every request got a TERMINAL HTTP outcome — the front's breakers
        # and reroute contain the blast radius; nothing hangs, nothing is
        # silently dropped
        assert outcomes
        assert all(isinstance(s, int) for s in outcomes), \
            [s for s in outcomes if not isinstance(s, int)][:3]
        assert outcomes.count(200) > len(outcomes) * 0.8
        events = [e["event"] for e in asc.events if e["model"] == "mlp"]
        assert "lost" in events and events.count("spawn") >= 3
        # "within one reconcile interval": generous wall bound — the pass
        # after the kill replaces it (spawn itself takes a moment)
        assert replaced_after < 8.0
    finally:
        stop_fire.set()
        _teardown(wreg, front, asc)


# ---------------------------------------------------------------------------
# compat + metric hygiene
# ---------------------------------------------------------------------------

def test_fleet_reconcile_emits_span_and_gauges():
    asc = FleetAutoscaler(
        FleetSpec(models=[ModelSLO(model="m", min_workers=1)]),
        FakeLauncher(), signals_fn=lambda s, live: FleetSignals())
    asc.reconcile_once()
    spans = [s for s in obs.get_tracer().finished_spans()
             if s.name == "fleet.reconcile"]
    assert spans
    snap = obs.get_registry().snapshot()
    assert snap.get('synapseml_fleet_desired_workers{model="m"}') == 1.0
    assert snap.get('synapseml_fleet_actual_workers{model="m"}') == 1.0
    assert snap.get(
        'synapseml_fleet_scale_events_total{direction="spawn",model="m"}',
        0) >= 1.0
