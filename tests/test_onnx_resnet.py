"""Real exported ResNet through the full ONNX path: torch -> onnx bytes
(genuine torch exporter output, not our own writer) -> our proto codec ->
converter -> ONNXModel transform, with torch-forward parity — the VERDICT
round-1 gap 'ONNX path never touched a real model'. Also: remote hub fetch
with SHA checks against a local server, and torchvision-naming weight
conversion driven by the same torch model."""

import json
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import numpy as np
import pytest

torch = pytest.importorskip("torch")

sys.path.insert(0, str(Path(__file__).parent))
from _torch_resnet import export_onnx_bytes, resnet50, resnet_small  # noqa: E402

from synapseml_tpu.core import DataFrame  # noqa: E402
from synapseml_tpu.onnx import ONNXHub, ONNXModel, convert_graph  # noqa: E402


@pytest.fixture(scope="module")
def small_export():
    torch.manual_seed(1)
    model = resnet_small(num_classes=10).eval()
    data = export_onnx_bytes(model, torch.zeros(1, 3, 32, 32))
    return model, data


def test_exported_resnet_parity_and_transform(small_export):
    model, data = small_export
    x = np.random.default_rng(0).normal(size=(5, 3, 32, 32)).astype(np.float32)
    with torch.no_grad():
        want = model(torch.tensor(x)).numpy()

    conv = convert_graph(data)
    got = np.asarray(conv(input=x)["logits"])
    np.testing.assert_allclose(got, want, atol=2e-4)

    # full transformer path: minibatching + argmax post-col
    df = DataFrame.from_dict({"img": x, "row": np.arange(5)}, num_partitions=2)
    om = ONNXModel(model_bytes=data, mini_batch_size=2,
                   feed_dict={"input": "img"}, fetch_dict={"logits": "logits"},
                   argmax_dict={"logits": "prediction"})
    out = om.transform(df)
    np.testing.assert_allclose(np.stack(list(out.collect_column("logits"))),
                               want, atol=2e-4)
    np.testing.assert_array_equal(out.collect_column("prediction"),
                                  want.argmax(-1))


@pytest.mark.slow
def test_full_resnet50_export_parity():
    """The actual 25.5M-param ResNet-50 (BASELINE.md ONNX config), real
    export, 224x224."""
    torch.manual_seed(2)
    model = resnet50().eval()
    data = export_onnx_bytes(model, torch.zeros(1, 3, 224, 224))
    assert len(data) > 90_000_000  # genuine full-size weights
    x = np.random.default_rng(1).normal(size=(2, 3, 224, 224)).astype(np.float32)
    with torch.no_grad():
        want = model(torch.tensor(x)).numpy()
    got = np.asarray(convert_graph(data)(input=x)["logits"])
    np.testing.assert_allclose(got, want, atol=1e-3)


def test_exported_weights_convert_to_flax(small_export):
    """The same torch model's state dict loads into our Flax ResNet
    (torchvision naming) and matches the torch forward."""
    import jax.numpy as jnp

    from synapseml_tpu.models.convert_hf import resnet_variables_from_torch
    from synapseml_tpu.models.flax_nets.resnet import ResNet

    model, _ = small_export
    sd = {k: v.numpy() for k, v in model.state_dict().items()}
    variables = resnet_variables_from_torch(sd)
    module = ResNet(stage_sizes=(1, 1), block="bottleneck", width=8,
                    num_classes=10, dtype=jnp.float32)
    x = np.random.default_rng(2).normal(size=(2, 32, 32, 3)).astype(np.float32)
    with torch.no_grad():
        want = model(torch.tensor(x.transpose(0, 3, 1, 2))).numpy()
    got = np.asarray(module.apply(variables, jnp.asarray(x)))
    np.testing.assert_allclose(got, want, atol=2e-4)


def test_hub_remote_fetch_with_sha(tmp_path, small_export):
    """ONNXHub remote-manifest path (reference ONNXHub.scala:72-255): fetch
    manifest + model from a zoo server, verify sha, cache, corrupt-sha
    rejection."""
    import hashlib

    _, data = small_export
    good_sha = hashlib.sha256(data).hexdigest()
    manifest = [{"model": "resnet-small", "model_path": "vision/resnet-small.onnx",
                 "model_sha256": good_sha, "opset_version": 17},
                {"model": "bad-model", "model_path": "vision/resnet-small.onnx",
                 "model_sha256": "0" * 64, "opset_version": 17}]

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.path.endswith("manifest.json"):
                body = json.dumps(manifest).encode()
            elif self.path.endswith(".onnx"):
                body = data
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_port}"

    try:
        hub = ONNXHub(hub_dir=str(tmp_path / "cache"), base_url=url)
        assert hub.load("resnet-small") == data          # miss -> fetch -> verify
        assert (tmp_path / "cache" / "vision" / "resnet-small.onnx").exists()
        hub2 = ONNXHub(hub_dir=str(tmp_path / "cache"))  # no URL: cache hit only
        assert hub2.load("resnet-small") == data

        with pytest.raises(ValueError, match="sha256 mismatch"):
            ONNXHub(hub_dir=str(tmp_path / "cache2"), base_url=url).load("bad-model")

        # corrupt cache entry heals via re-download
        p = tmp_path / "cache" / "vision" / "resnet-small.onnx"
        p.write_bytes(b"truncated")
        assert hub.load("resnet-small") == data

        # stale manifest refreshes when a name is missing
        manifest.append({"model": "late-model",
                         "model_path": "vision/resnet-small.onnx",
                         "model_sha256": good_sha, "opset_version": 17})
        assert hub.load("late-model") == data

        # hostile manifest: traversal is rejected
        manifest.append({"model": "evil", "model_path": "../evil.onnx",
                         "model_sha256": good_sha, "opset_version": 17})
        with pytest.raises(ValueError, match="escapes|relative"):
            ONNXHub(hub_dir=str(tmp_path / "cache3"), base_url=url).load("evil")
    finally:
        srv.shutdown()
