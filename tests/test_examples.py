"""Docs-as-tests: every example under docs/examples runs end to end
(the reference's nbtest notebook-E2E tier, core/src/test/.../nbtest/)."""

import pathlib
import subprocess
import sys

import pytest

EXAMPLES = sorted((pathlib.Path(__file__).parent.parent / "docs" / "examples")
                  .glob("*.py"))


@pytest.mark.parametrize("example", EXAMPLES, ids=lambda p: p.name)
def test_example_runs(example):
    env = {"PATH": "/usr/bin:/bin", "HOME": "/root",
           "JAX_PLATFORMS": "cpu", "PYTHONPATH": str(example.parent.parent.parent),
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    proc = subprocess.run([sys.executable, str(example)], env=env,
                          capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, f"{example.name} failed:\n{proc.stdout}\n{proc.stderr}"


@pytest.mark.slow
def test_module_selftest_passes():
    """`python -m synapseml_tpu` environment self-test: all checks PASS."""
    import subprocess
    import sys

    env = {"PATH": "/usr/bin:/bin", "HOME": "/root",
           "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": str(pathlib.Path(__file__).parent.parent)}
    proc = subprocess.run([sys.executable, "-m", "synapseml_tpu"], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "6/6 checks passed" in proc.stdout
