import os

import numpy as np
import pytest

from synapseml_tpu.core import (
    ComplexParam,
    DataFrame,
    Estimator,
    GlobalParams,
    Model,
    Param,
    Pipeline,
    PipelineModel,
    Transformer,
    load_stage,
)
from synapseml_tpu.core.params import ServiceParam, TypeConverters


class AddConst(Transformer):
    input_col = Param("input_col", "input column", default="a")
    output_col = Param("output_col", "output column", default="out")
    value = Param("value", "constant to add", default=1.0, converter=TypeConverters.to_float)

    def _transform(self, df):
        return df.with_column(self.get("output_col"),
                              lambda p: p[self.get("input_col")] + self.get("value"))


class MeanModel(Model):
    input_col = Param("input_col", "input column", default="a")
    mean = ComplexParam("mean", "fitted mean")

    def _transform(self, df):
        return df.with_column("centered", lambda p: p[self.get("input_col")] - self.get("mean"))


class MeanCenter(Estimator):
    input_col = Param("input_col", "input column", default="a")

    def _fit(self, df):
        m = float(np.mean(df.collect_column(self.get("input_col"))))
        return MeanModel(input_col=self.get("input_col"), mean=np.float32(m))


def test_param_accessors():
    t = AddConst(value=2)
    assert t.get_value() == 2.0
    t.set_value(3)
    assert t.get("value") == 3.0
    with pytest.raises(KeyError):
        t.set(nope=1)
    assert "value: constant to add" in t.explain_params()


def test_global_params():
    GlobalParams.reset()
    t = AddConst()
    assert t.get("value") == 1.0
    GlobalParams.set_default(AddConst, "value", 9.0)
    assert t.get("value") == 9.0
    t.set_value(2)
    assert t.get("value") == 2.0  # explicit set wins
    GlobalParams.reset()


def test_service_param_resolution():
    class S(Transformer):
        temp = ServiceParam("temp", "temperature")

        def _transform(self, df):
            return df

    s = S(temp=("col", "t"))
    part = {"t": np.array([0.1, 0.2])}
    assert s.resolve_row_param("temp", part, 2) == [0.1, 0.2]
    s.set(temp=0.5)
    assert s.resolve_row_param("temp", part, 2) == [0.5, 0.5]


def test_fit_transform_and_pipeline(tmp_path):
    df = DataFrame.from_dict({"a": np.arange(10, dtype=np.float32)}, num_partitions=2)
    pipe = Pipeline(stages=[AddConst(value=5, output_col="a5"), MeanCenter()])
    model = pipe.fit(df)
    assert isinstance(model, PipelineModel)
    out = model.transform(df)
    np.testing.assert_allclose(out.collect_column("centered"), np.arange(10) - 4.5)


def test_stage_save_load(tmp_path):
    path = os.path.join(tmp_path, "stage")
    t = AddConst(value=7, output_col="z")
    t.save(path)
    t2 = load_stage(path)
    assert isinstance(t2, AddConst)
    assert t2.get("value") == 7.0 and t2.get("output_col") == "z"
    assert t2.uid == t.uid


def test_model_save_load_complex(tmp_path):
    df = DataFrame.from_dict({"a": np.arange(4, dtype=np.float32)})
    model = MeanCenter().fit(df)
    path = os.path.join(tmp_path, "model")
    model.save(path)
    m2 = load_stage(path)
    np.testing.assert_allclose(np.asarray(m2.get("mean")), 1.5)
    out = m2.transform(df)
    np.testing.assert_allclose(out.collect_column("centered"), np.arange(4) - 1.5)


def test_pipeline_save_load(tmp_path):
    df = DataFrame.from_dict({"a": np.arange(6, dtype=np.float32)})
    model = Pipeline(stages=[AddConst(value=1), MeanCenter()]).fit(df)
    path = os.path.join(tmp_path, "pm")
    model.save(path)
    m2 = PipelineModel.load(path)
    a = model.transform(df).collect_column("centered")
    b = m2.transform(df).collect_column("centered")
    np.testing.assert_allclose(a, b)


def test_params_string_builder():
    from synapseml_tpu.core.utils import ParamsStringBuilder

    r = (ParamsStringBuilder(prefix="--", delimiter="=")
         .append("--first_param=a")
         .append_param_value_if_not_there("first_param", "a2")
         .append_param_value_if_not_there("second_param", "b")
         .append_param_value_if_not_there("third_param", None)
         .append_param_value_if_not_there("listy", [1, 2, 3])
         .append_flag_if_true("quiet", True)
         .append_flag_if_true("verbose", False)
         .result())
    assert r == "--first_param=a --second_param=b --listy=1,2,3 --quiet"
    # short-flag collision: "-q ..." blocks the long form
    r2 = (ParamsStringBuilder(prefix="--")
          .append("-q 1")
          .append_param_value_if_not_there("quiet_level", 2, short="q")
          .result())
    assert r2 == "-q 1"


def test_default_hyperparams_ranges():
    from synapseml_tpu.automl import DefaultHyperparams, RandomSpace
    from synapseml_tpu.gbdt import LightGBMClassifier

    space = DefaultHyperparams.default_range(LightGBMClassifier())
    assert "num_leaves" in space and "learning_rate" in space
    cands = RandomSpace(space, seed=0).configs(3)
    assert len(cands) == 3 and all(8 <= c["num_leaves"] <= 63 for c in cands)
    with pytest.raises(ValueError, match="no default"):
        DefaultHyperparams.default_range("SomethingElse")
