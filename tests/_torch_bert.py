"""Test helper: hand-built BERT-style torch encoder for REAL
``torch.onnx.export`` → converter parity (the transformer analog of
``_torch_resnet.py``; reference runs the full opset through ONNX Runtime —
``deep-learning/src/main/scala/.../onnx/ONNXModel.scala:211``).

Deliberately exercises the transformer-shaped export surface the round-3
verdict called out as unproven: ``torch.einsum`` attention (exports an
``Einsum`` node), erf-form gelu, LayerNorm, additive mask built from the
int mask input (Cast/Sub/Mul chains), and ``.view``/``.size`` dynamic
Reshape chains (Shape/Gather/Unsqueeze/Concat → Reshape).
"""

from __future__ import annotations

import io
import math

import torch
from torch import nn

from _torch_resnet import _install_onnx_shim


class EinsumSelfAttention(nn.Module):
    def __init__(self, hidden: int, heads: int):
        super().__init__()
        self.h = heads
        self.dk = hidden // heads
        self.q = nn.Linear(hidden, hidden)
        self.k = nn.Linear(hidden, hidden)
        self.v = nn.Linear(hidden, hidden)
        self.o = nn.Linear(hidden, hidden)

    def forward(self, x, bias):
        B, T = x.size(0), x.size(1)  # dynamic: exports Shape/Gather chains
        def split(t):
            return t.view(B, T, self.h, self.dk)

        q, k, v = split(self.q(x)), split(self.k(x)), split(self.v(x))
        scores = torch.einsum("bthd,bshd->bhts", q, k) / math.sqrt(self.dk)
        probs = torch.softmax(scores + bias, dim=-1)
        ctx = torch.einsum("bhts,bshd->bthd", probs, v)
        return self.o(ctx.reshape(B, T, self.h * self.dk))


class Layer(nn.Module):
    def __init__(self, hidden: int, heads: int, mlp: int):
        super().__init__()
        self.attn = EinsumSelfAttention(hidden, heads)
        self.ln1 = nn.LayerNorm(hidden)
        self.fc1 = nn.Linear(hidden, mlp)
        self.fc2 = nn.Linear(mlp, hidden)
        self.ln2 = nn.LayerNorm(hidden)

    def forward(self, x, bias):
        x = self.ln1(x + self.attn(x, bias))
        # erf-form gelu: exports Div/Erf/Add/Mul, the BERT default
        h = self.fc1(x)
        h = h * 0.5 * (1.0 + torch.erf(h / math.sqrt(2.0)))
        return self.ln2(x + self.fc2(h))


class TorchBertEncoder(nn.Module):
    def __init__(self, vocab: int = 512, hidden: int = 64, heads: int = 4,
                 layers: int = 2, mlp: int = 128, max_len: int = 128,
                 num_classes: int = 3):
        super().__init__()
        self.tok = nn.Embedding(vocab, hidden)
        self.pos = nn.Embedding(max_len, hidden)
        self.ln = nn.LayerNorm(hidden)
        self.layers = nn.ModuleList(
            Layer(hidden, heads, mlp) for _ in range(layers))
        self.head = nn.Linear(hidden, num_classes)

    def features(self, input_ids, attention_mask):
        T = input_ids.size(1)
        positions = torch.arange(T, device=input_ids.device).unsqueeze(0)
        x = self.ln(self.tok(input_ids) + self.pos(positions))
        # additive mask from the int input: Cast → Sub → Mul chain
        bias = (1.0 - attention_mask.to(x.dtype)) * -1e9
        bias = bias.unsqueeze(1).unsqueeze(2)  # [B, 1, 1, T]
        for layer in self.layers:
            x = layer(x, bias)
        return x  # [B, T, H] hidden states

    def forward(self, input_ids, attention_mask):
        return self.head(self.features(input_ids, attention_mask)[:, 0])


def export_bert_onnx_bytes(model: nn.Module, ids: torch.Tensor,
                           mask: torch.Tensor) -> bytes:
    _install_onnx_shim()
    model.eval()
    buf = io.BytesIO()
    torch.onnx.export(
        model, (ids, mask), buf, dynamo=False,
        input_names=["input_ids", "attention_mask"], output_names=["logits"],
        dynamic_axes={"input_ids": {0: "N", 1: "T"},
                      "attention_mask": {0: "N", 1: "T"},
                      "logits": {0: "N"}})
    return buf.getvalue()
