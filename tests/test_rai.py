"""Responsible-AI audit plane (ISSUE 20): fused-vs-serial explainer parity,
ladder-bounded compiles, streamed explanation kill/resume byte-identity,
partition-invariant determinism, audit artifacts, and the drift-triggered
retrain flywheel with audit evidence in the trigger reason."""

import json
import os

import numpy as np
import pytest

from synapseml_tpu.core import DataFrame
from synapseml_tpu.core import batching as cb
from synapseml_tpu.core import observability as obs
from synapseml_tpu.core.pipeline import Transformer
from synapseml_tpu.explainers import (
    ICETransformer,
    TabularSHAP,
    TextLIME,
    TextSHAP,
    VectorLIME,
    VectorSHAP,
    row_rng,
)
from synapseml_tpu.registry import ModelRegistry

pytestmark = pytest.mark.rai


# ---------------------------------------------------------------------------
# fixtures: scorers with and without the score-fn protocol
# ---------------------------------------------------------------------------

class ProtoLinear(Transformer):
    """score = x @ w + b, exposed BOTH ways: a serial DataFrame transform
    and the rai score-fn protocol (pure jax array fn) — the parity pair."""

    def __init__(self, w, b=0.0, input_col="features", **kw):
        super().__init__(**kw)
        self._w = np.asarray(w, np.float32)
        self._b = float(b)
        self._input_col = input_col

    def _transform(self, df):
        def score(p):
            X = np.stack([np.asarray(v, np.float64)
                          for v in p[self._input_col]])
            s = X @ self._w.astype(np.float64) + self._b
            return np.asarray([np.asarray([v]) for v in s])

        return df.with_column("probability", score)

    def score_fn(self):
        w, b = self._w, self._b
        return lambda X: (X.astype("float32") @ w + b)[:, None]


class ProtoColumnar(ProtoLinear):
    """The ICE shape of the protocol: ``score_cols`` names the column
    order the array fn consumes."""

    score_cols = ("x0", "x1")

    def _transform(self, df):
        def score(p):
            X = np.stack([np.asarray(p[c], np.float64)
                          for c in self.score_cols], axis=1)
            s = X @ self._w.astype(np.float64) + self._b
            return np.asarray([np.asarray([v]) for v in s])

        return df.with_column("probability", score)


class KeywordScorer(Transformer):
    """Text scorer with NO protocol — exercises the chunked-transform
    fallback (fusion at the batching level)."""

    def _transform(self, sdf):
        def score(p):
            return np.asarray(
                [np.asarray([1.0 if "good" in str(t).split() else 0.0])
                 for t in p["text"]])

        return sdf.with_column("probability", score)


def _explanations(out):
    return [np.asarray(v) for v in out.collect_column("explanation")]


def _fused_serial_pair(cls, model, df, **kw):
    serial = _explanations(cls(model=model, fused=False, seed=0,
                               **kw).transform(df))
    fused = _explanations(cls(model=model, fused=True, seed=0,
                              **kw).transform(df))
    return fused, serial


# ---------------------------------------------------------------------------
# fused-vs-serial parity + compile bounds
# ---------------------------------------------------------------------------

def test_fused_matches_serial_vector_explainers():
    """Vector SHAP/LIME through the score-fn ladder path: attributions
    match the serial reference at f32 tolerance, and the whole run compiles
    at most one executable per ladder rung."""
    rs = np.random.default_rng(1)
    w = np.asarray([1.0, -2.0, 0.5, 0.0])
    X = rs.normal(size=(12, 4)).astype(np.float32)
    df = DataFrame.from_dict({"features": X})
    model = ProtoLinear(w, b=1.0)
    cb.reset_compiled_cache()
    before = cb.get_compiled_cache().miss_count("rai.fused_score")
    for cls, kw in [(VectorSHAP, dict(num_samples=64, background_data=df)),
                    (VectorLIME, dict(num_samples=100, regularization=1e-4,
                                      background_data=df))]:
        fused, serial = _fused_serial_pair(cls, model, df, **kw)
        np.testing.assert_allclose(np.stack(fused), np.stack(serial),
                                   rtol=1e-4, atol=1e-4)
        # the 'auto' default detects the protocol
        assert cls(model=model, **kw)._use_fused()
    ladder = len(cb.default_bucketer().buckets_upto(1024))
    misses = cb.get_compiled_cache().miss_count("rai.fused_score") - before
    assert 0 < misses <= ladder, (misses, ladder)


def test_fused_matches_serial_tabular_and_text():
    """Models WITHOUT the protocol (Tabular proxy, text scorers) ride the
    chunked-transform fallback: identical numbers, zero new executables."""
    rs = np.random.default_rng(2)
    X = rs.normal(size=(8, 2)).astype(np.float32)
    tab_df = DataFrame.from_dict({"a": X[:, 0], "b": X[:, 1]})

    class ColScorer(Transformer):
        def _transform(self, sdf):
            def score(p):
                s = (np.asarray(p["a"], np.float64) * 1.5
                     - np.asarray(p["b"], np.float64))
                return np.asarray([np.asarray([v]) for v in s])

            return sdf.with_column("probability", score)

    cb.reset_compiled_cache()
    before = cb.get_compiled_cache().miss_count("rai.fused_score")
    fused, serial = _fused_serial_pair(
        TabularSHAP, ColScorer(), tab_df, input_cols=["a", "b"],
        num_samples=16, background_data=tab_df)
    np.testing.assert_allclose(np.stack(fused), np.stack(serial), rtol=1e-6)

    text_df = DataFrame.from_dict(
        {"text": ["this is a good movie", "bad film overall",
                  "good good good", "nothing to see"]})
    for cls, kw in [(TextSHAP, dict(num_samples=32)),
                    (TextLIME, dict(num_samples=32, regularization=1e-4))]:
        fused, serial = _fused_serial_pair(cls, KeywordScorer(), text_df,
                                           **kw)
        assert len(fused) == len(serial)
        for f, s in zip(fused, serial):
            np.testing.assert_allclose(f, s, rtol=1e-6)
    assert cb.get_compiled_cache().miss_count("rai.fused_score") == before


def test_ice_fused_columnar_matches_serial():
    rs = np.random.default_rng(3)
    df = DataFrame.from_dict(
        {"x0": rs.uniform(-2, 2, 20).astype(np.float32),
         "x1": rs.uniform(-2, 2, 20).astype(np.float32)})
    model = ProtoColumnar(np.asarray([2.0, -1.0]), input_col=None)
    curves = {}
    for fused in (False, True):
        ice = ICETransformer(model=model, fused=fused, target_col="probability",
                             numeric_features=["x0"], num_splits=5,
                             kind="average")
        curves[fused] = ice.transform(df).collect_column("x0_dependence")[0]
    assert curves[False].keys() == curves[True].keys()
    for k in curves[False]:
        np.testing.assert_allclose(curves[True][k], curves[False][k],
                                   rtol=1e-4, atol=1e-5)


def test_row_rng_partition_invariance():
    """Explanations are keyed on (seed, row content): repartitioning the
    frame — or explaining a row alongside different neighbors — changes
    nothing. (The pre-rai sampler drew from one sequential stream, so row
    i's design depended on how many rows preceded it.)"""
    rs = np.random.default_rng(4)
    X = rs.normal(size=(9, 3)).astype(np.float32)
    bg = DataFrame.from_dict({"features": X})
    w = np.asarray([3.0, -2.0, 0.0])

    def explain(df, cls, **kw):
        return _explanations(
            cls(model=ProtoLinear(w), seed=0, background_data=bg,
                **kw).transform(df))

    for cls, kw in [(VectorSHAP, dict(num_samples=20)),
                    (VectorLIME, dict(num_samples=50,
                                      regularization=1e-4))]:
        whole = explain(DataFrame.from_dict({"features": X}), cls, **kw)
        parts = explain(DataFrame.from_dict({"features": X},
                                            num_partitions=3), cls, **kw)
        solo = explain(DataFrame.from_dict({"features": X[4:5]}), cls, **kw)
        np.testing.assert_array_equal(np.stack(whole), np.stack(parts))
        np.testing.assert_array_equal(whole[4], solo[0])
    # the rng itself: content-keyed, seed-sensitive
    a = row_rng(0, X[0]).random(4)
    np.testing.assert_array_equal(a, row_rng(0, X[0].copy()).random(4))
    assert not np.array_equal(a, row_rng(1, X[0]).random(4))
    assert not np.array_equal(a, row_rng(0, X[1]).random(4))


# ---------------------------------------------------------------------------
# streamed explanation runs: exactly-once on the scoring plane
# ---------------------------------------------------------------------------

class _Kill(BaseException):
    """Process-kill stand-in (BaseException so quarantine can't eat it)."""


class KillAfter(Transformer):
    """Delegates to an inner explainer, killing the scan after N batches."""

    def __init__(self, inner, after, **kw):
        super().__init__(**kw)
        self._inner = inner
        self._after = after
        self._seen = 0

    def _transform(self, df):
        if self._seen >= self._after:
            raise _Kill(f"killed after {self._seen} batches")
        self._seen += 1
        return self._inner._transform(df)


def _write_corpus(directory, sizes, n_features=3, seed=0):
    os.makedirs(directory, exist_ok=True)
    rs = np.random.default_rng(seed)
    i = 0
    for s, n in enumerate(sizes):
        with open(os.path.join(directory, f"in-{s:03d}.jsonl"), "w") as f:
            for _ in range(n):
                f.write(json.dumps({
                    "features": [round(float(v), 5)
                                 for v in rs.normal(size=n_features)],
                    "i": i}) + "\n")
                i += 1
    return i


def _part_bytes(sink):
    return b"".join(open(p, "rb").read() for p in sink.part_files())


def _bg():
    rs = np.random.default_rng(9)
    return DataFrame.from_dict(
        {"features": rs.normal(size=(32, 3)).astype(np.float32)})


def _explainer():
    return VectorSHAP(model=ProtoLinear(np.asarray([1.0, -1.0, 0.5])),
                      num_samples=16, seed=0, background_data=_bg())


def test_streamed_explanations_kill_resume_byte_identical(tmp_path):
    """The scoring plane's exactly-once contract holds for explanation
    runs: kill at three cut points, resume with a fresh runner, output
    byte-identical to the uninterrupted run (content-keyed rngs mean the
    resumed rows redraw the exact same designs)."""
    from synapseml_tpu.data.source import ShardedSource
    from synapseml_tpu.scoring import JsonlSink

    total = _write_corpus(tmp_path / "data", [23, 9, 31, 6])
    src = ShardedSource.jsonl(os.path.join(tmp_path, "data", "*.jsonl"))
    clean = JsonlSink(tmp_path / "clean", columns=["i", "explanation"])
    report = _explainer().transform_source(src, clean, batch_rows=16,
                                           host_index=0, host_count=1)
    assert report.complete and report.rows_written == total
    golden = _part_bytes(clean)
    assert golden

    for cut in (1, 2, 4):
        out = tmp_path / f"out_cut{cut}"
        killer = KillAfter(_explainer(), cut)
        with pytest.raises(_Kill):
            killer.transform_source(
                src, JsonlSink(out, columns=["i", "explanation"]),
                batch_rows=16, host_index=0, host_count=1)
        sink = JsonlSink(out, columns=["i", "explanation"])
        assert not sink.is_complete()
        report = _explainer().transform_source(src, sink, batch_rows=16,
                                               host_index=0, host_count=1)
        assert report.complete
        assert report.shards_skipped + report.shards_done == 4
        assert _part_bytes(sink) == golden


def test_streamed_run_metrics_and_quarantine(tmp_path):
    """The rai series rides the run: progress lands at 100, rates are set,
    and a poisoned row quarantines instead of killing the scan."""
    from synapseml_tpu.data.source import ShardedSource
    from synapseml_tpu.scoring import JsonlSink

    obs.reset_registry()
    d = tmp_path / "data"
    total = _write_corpus(d, [12, 8])
    # poison one row: a non-numeric feature payload
    with open(os.path.join(d, "in-001.jsonl"), "a") as f:
        f.write(json.dumps({"features": "not-a-vector", "i": total}) + "\n")
    src = ShardedSource.jsonl(os.path.join(d, "*.jsonl"))
    sink = JsonlSink(tmp_path / "out", columns=["i", "explanation"])
    report = _explainer().transform_source(src, sink, batch_rows=8,
                                           host_index=0, host_count=1)
    assert report.complete and report.rows_written == total
    assert report.rows_quarantined >= 1
    snap = obs.get_registry().snapshot()
    prog = [v for k, v in snap.items()
            if k.startswith("synapseml_rai_progress_pct")]
    assert prog and max(prog) == pytest.approx(100.0)
    assert any(k.startswith("synapseml_rai_explanations_total")
               for k in snap)
    rates = [v for k, v in snap.items()
             if k.startswith("synapseml_rai_explanations_per_sec")]
    assert rates and max(rates) > 0


# ---------------------------------------------------------------------------
# audit jobs + the retrain flywheel
# ---------------------------------------------------------------------------

def _log_traffic(logdir, X, segments, labels=None, part=0, rows_per=40):
    """Committed RequestLogger-layout parts carrying (x, segment, y)."""
    os.makedirs(logdir, exist_ok=True)
    for k in range(0, len(X), rows_per):
        name = f"part-{part:05d}.jsonl"
        chunk = range(k, min(k + rows_per, len(X)))
        with open(os.path.join(logdir, name), "w") as f:
            for i in chunk:
                body = {"x": [float(v) for v in X[i]]}
                if labels is not None:
                    body["y"] = int(labels[i])
                f.write(json.dumps(
                    {"ts": i, "method": "POST", "path": f"/{segments[i]}",
                     "status": 200, "latency_ms": 1.0, "body": body,
                     "reply": {}}) + "\n")
        with open(os.path.join(logdir, name + ".DONE"), "w") as f:
            json.dump({"rows": len(list(chunk))}, f)
        part += 1
    return part


def test_audit_job_publishes_artifact_and_raises_gauge(tmp_path):
    import synapseml_tpu.rai as rai
    from synapseml_tpu.continual import annotate_drift_gauge, drift_annotation

    obs.reset_registry()
    annotate_drift_gauge(rai.DRIFT_GAUGE, None)
    rs = np.random.default_rng(0)
    ref = rs.normal(0, 1, (300, 4))
    n = 120
    segs = ["base" if i % 2 else "shifted" for i in range(n)]
    X = np.stack([rs.normal(0 if s == "base" else 4.0, 1, 4)
                  for s in segs])
    y = (X[:, 0] > 1).astype(int)
    logdir = tmp_path / "log"
    _log_traffic(str(logdir), X, segs, labels=y)
    reg = ModelRegistry(str(tmp_path / "reg"))
    spec = rai.AuditSpec(model="m", reference=ref,
                         segment_fn=lambda r: r["path"].strip("/"),
                         label_fn=lambda r: r["body"].get("y"),
                         anomaly_trees=16)
    res = rai.AuditJob(spec, reg, str(logdir)).run_once()
    assert res["status"] == "ok"
    assert res["worst_segment"] == "shifted"
    assert res["drift"]["shifted"]["drift"] > res["drift"]["base"]["drift"]
    assert res["artifact"] == "m-audit:v1"
    # the artifact: resolvable, manifest links model + window + metrics
    rm = reg.resolve("m-audit", "latest")
    manifest = json.load(open(os.path.join(rm.path, "audit",
                                           "manifest.json")))
    assert manifest["model"] == "m"
    assert manifest["window"]["rows"] == n
    assert manifest["window"]["parts"] == sorted(manifest["window"]["parts"])
    assert manifest["worst_segment"] == "shifted"
    assert manifest["metrics"]["max_segment_drift"] == pytest.approx(
        res["drift"]["shifted"]["drift"])
    per_seg = json.load(open(os.path.join(rm.path, "audit",
                                          "segment_drift.json")))
    assert set(per_seg) == {"base", "shifted"}
    assert os.path.exists(os.path.join(rm.path, "audit", "balance.jsonl"))
    assert os.path.exists(os.path.join(rm.path, "audit", "anomaly.json"))
    # gauge raised per segment + annotated with the artifact ref
    snap = obs.get_registry().snapshot()
    key = f'{rai.DRIFT_GAUGE}{{model="m",segment="shifted"}}'
    assert snap[key] > 1.0
    assert drift_annotation(rai.DRIFT_GAUGE) == "m-audit:v1"
    # a second run versions the artifact, never overwrites
    assert rai.AuditJob(spec, reg, str(logdir)).run_once()["artifact"] == \
        "m-audit:v2"


def test_audit_job_empty_window_publishes_nothing(tmp_path):
    import synapseml_tpu.rai as rai

    reg = ModelRegistry(str(tmp_path / "reg"))
    logdir = tmp_path / "log"
    os.makedirs(logdir)
    res = rai.AuditJob(
        rai.AuditSpec(model="m", reference=np.zeros((10, 4))),
        reg, str(logdir)).run_once()
    assert res["status"] == "empty"
    assert reg.list_models() == [] if hasattr(reg, "list_models") else True
    with pytest.raises(Exception):
        reg.resolve("m-audit", "latest")


def test_flywheel_drift_audit_triggers_retrain_with_evidence(tmp_path):
    """The E2E flywheel (the tentpole's acceptance path): drifted-segment
    traffic → AuditJob publishes the artifact + raises the segment gauge →
    the ContinualLoop's drift watch fires with the audit ref in the trigger
    reason → retrain promotes through the eval gate, ``prod`` untouched
    until it passes."""
    import synapseml_tpu.rai as rai
    from synapseml_tpu.continual import annotate_drift_gauge
    from test_continual import (_W_TRUE, D_IN, _loop_fixture, make_rows,
                                write_part)

    obs.reset_registry()
    annotate_drift_gauge(rai.DRIFT_GAUGE, None)
    reg, logdir, loop = _loop_fixture(tmp_path, min_new_rows=100_000,
                                      drift_gauge=rai.DRIFT_GAUGE,
                                      drift_threshold=1.0)
    # logged traffic: half healthy, half with feature 0 shifted +4 (the
    # drifted segment); shifted labels recomputed under the true rule so
    # the retrain still has consistent data
    Xh, yh = make_rows(120, seed=7)
    Xs = make_rows(120, seed=17)[0] + np.asarray(
        [4.0] + [0.0] * (D_IN - 1), np.float32)
    ys = np.digitize(Xs @ _W_TRUE,
                     np.quantile(Xs @ _W_TRUE, [1 / 3, 2 / 3])).astype(
                         np.int32)
    for k in range(4):
        write_part(str(logdir), k, Xh[k * 30:(k + 1) * 30],
                   yh[k * 30:(k + 1) * 30])
        write_part(str(logdir), 4 + k, Xs[k * 30:(k + 1) * 30],
                   ys[k * 30:(k + 1) * 30])

    # not due on freshness alone, gauge unset -> no run
    ok, _ = loop.should_run()
    assert not ok

    ref, _ = make_rows(300, seed=8)         # the healthy reference window
    spec = rai.AuditSpec(
        model="m", reference=ref,
        segment_fn=lambda r: "shifted" if r["body"]["x"][0] > 2 else "base")
    res = rai.AuditJob(spec, reg, str(logdir)).run_once()
    assert res["status"] == "ok" and res["worst_segment"] == "shifted"
    artifact = res["artifact"]

    assert reg.alias_target("m", "prod") == "v1"  # untouched pre-retrain
    ok, reason = loop.should_run()
    assert ok and "drift" in reason
    assert f"audit={artifact}" in reason
    rec = loop.run_once()
    assert rec["outcome"] == "promoted", rec
    assert f"audit={artifact}" in rec["trigger"]
    assert reg.alias_target("m", "prod") == rec["version"] != "v1"
