import numpy as np
import pytest

import jax
import jax.numpy as jnp

from synapseml_tpu.parallel import (
    MeshConfig,
    batches,
    bucket_size,
    create_mesh,
    pad_batch,
    pad_sequences,
    restore_checkpoint,
    save_checkpoint,
    unpad,
)
from synapseml_tpu.parallel.collectives import all_gather_over, pmean_over, psum_over


def test_eight_devices_present():
    assert jax.device_count() == 8


def test_mesh_config_resolution():
    assert MeshConfig(data=-1, tensor=2).resolve(8) == {
        "data": 4, "fsdp": 1, "tensor": 2, "seq": 1, "expert": 1, "pipe": 1}
    with pytest.raises(ValueError):
        MeshConfig(data=3, tensor=3).resolve(8)


def test_mesh_creation_and_sharding(mesh8):
    assert mesh8.n_devices == 8
    assert mesh8.axis_sizes == {"data": 2, "fsdp": 2, "tensor": 2, "seq": 1,
                                "expert": 1, "pipe": 1}
    x = np.arange(32, dtype=np.float32).reshape(8, 4)
    placed = mesh8.shard_batch({"x": x})
    assert placed["x"].sharding.is_equivalent_to(mesh8.batch_sharding(), 2)
    np.testing.assert_allclose(np.asarray(placed["x"]), x)


def test_jit_on_mesh_produces_correct_result(mesh_dp8):
    x = np.arange(64, dtype=np.float32).reshape(8, 8)
    placed = mesh_dp8.shard_batch({"x": x})

    @jax.jit
    def f(b):
        return jnp.sum(b["x"] ** 2)

    assert float(f(placed)) == pytest.approx(float(np.sum(x ** 2)))


def test_psum_pmean_collectives(mesh_dp8):
    f = psum_over(mesh_dp8, "data")
    out = f(jnp.ones(()))
    assert float(out) == 8.0
    g = pmean_over(mesh_dp8, "data")
    assert float(g(jnp.full((), 3.0))) == 3.0


def test_all_gather(mesh_dp8):
    x = jnp.arange(8.0)
    gathered = all_gather_over(mesh_dp8, "data")(x)
    np.testing.assert_allclose(np.asarray(gathered), np.arange(8.0))


def test_bucket_and_pad():
    assert bucket_size(5) == 8
    assert bucket_size(9) == 16
    b = pad_batch({"x": np.ones((5, 3), np.float32)}, buckets=None)
    assert b.data["x"].shape == (8, 3)
    assert b.n_valid == 5 and b.mask.sum() == 5
    res = unpad(np.arange(8), b)
    np.testing.assert_array_equal(res, np.arange(5))


def test_batches_iterator():
    arrays = {"x": np.arange(10, dtype=np.float32)}
    got = list(batches(arrays, batch_size=4))
    assert [b.n_valid for b in got] == [4, 4, 2]
    assert all(b.data["x"].shape == (4,) for b in got)


def test_pad_sequences():
    ids, mask = pad_sequences([[1, 2, 3], [4]], multiple_of=8)
    assert ids.shape == (2, 8)
    assert mask.sum() == 4
    ids2, _ = pad_sequences([[1] * 100], max_len=16, multiple_of=8)
    assert ids2.shape == (1, 16)


def test_checkpoint_roundtrip(tmp_path):
    tree = {"w": np.arange(6, dtype=np.float32).reshape(2, 3), "opt": {"mu": np.zeros(3)}}
    save_checkpoint(str(tmp_path), tree, step=3)
    save_checkpoint(str(tmp_path), jax.tree.map(lambda x: x + 1, tree), step=7)
    restored = restore_checkpoint(str(tmp_path))
    np.testing.assert_allclose(restored["w"], tree["w"] + 1)
    restored3 = restore_checkpoint(str(tmp_path), step=3)
    np.testing.assert_allclose(restored3["opt"]["mu"], np.zeros(3))


def test_rendezvous_single_host():
    from synapseml_tpu.parallel import DriverRendezvous, worker_rendezvous
    import threading

    drv = DriverRendezvous(world_size=3).start()
    results = {}

    def worker(pid):
        results[pid] = worker_rendezvous(f"localhost:{drv.port}", f"exec{pid}", pid)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    drv.join()
    for t in threads:
        t.join()
    ranks = {pid: r["rank"] for pid, r in results.items()}
    assert sorted(ranks.values()) == [0, 1, 2]
    assert ranks[0] == 0  # deterministic: min partition id -> rank 0
    worlds = {r["world"] for r in results.values()}
    assert worlds == {3}


def test_llama2_7b_sharding_fits_v5e16_abstractly():
    """The BASELINE 'Llama-2-7B sharded across v5e-16' config, validated
    without materializing 7B params: abstract-init the real model config,
    resolve every param's logical sharding on a 16-device mesh, and check the
    per-device weight footprint fits v5e HBM (16 GB)."""
    import jax
    import jax.numpy as jnp
    from flax.core import meta
    import flax.linen as nn

    from synapseml_tpu.models.flax_nets.llama import LlamaLM, llama2_7b
    from synapseml_tpu.parallel.mesh import logical_axis_rules

    cfg = llama2_7b()
    module = LlamaLM(cfg)
    abstract = jax.eval_shape(
        lambda: module.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 8), jnp.int32)))
    mesh_sizes = {"data": 1, "fsdp": 4, "tensor": 4, "seq": 1, "expert": 1}
    rules = logical_axis_rules()

    total_bytes = 0
    per_device_bytes = 0
    n_sharded = 0
    for leaf in jax.tree.leaves(
            abstract["params"],
            is_leaf=lambda x: isinstance(x, meta.Partitioned)):
        if isinstance(leaf, meta.Partitioned):
            spec = nn.logical_to_mesh_axes(leaf.names, rules=rules)
            shape = leaf.value.shape
        else:
            spec, shape = (), leaf.shape
        divisor = 1
        for dim, axis in zip(shape, tuple(spec) + (None,) * len(shape)):
            axes = (axis,) if isinstance(axis, str) else (axis or ())
            for a in axes:
                size = mesh_sizes.get(a, 1)
                if size > 1:
                    assert dim % size == 0, \
                        f"dim {dim} of {shape} not divisible by {a}={size}"
                    divisor *= size
        n_params = int(np.prod(shape))
        total_bytes += n_params * 2           # bf16 weights
        per_device_bytes += n_params * 2 // divisor
        if divisor > 1:
            n_sharded += 1

    assert total_bytes > 12e9                  # genuinely ~7B params in bf16
    assert n_sharded > 100                     # weights really partition
    # per-device weights must leave room for KV cache + activations on 16GB
    assert per_device_bytes < 4e9, f"{per_device_bytes/1e9:.2f} GB/device"


def test_async_checkpointer_overlap_retention_and_errors(tmp_path):
    """AsyncCheckpointer: snapshot-now semantics (mutating the source after
    save() doesn't corrupt the write), ordered background writes, top-k
    retention GC, restore equality, and deferred error surfacing."""
    import os

    import pytest

    from synapseml_tpu.parallel import (AsyncCheckpointer, latest_step,
                                        restore_checkpoint)

    path = str(tmp_path / "ckpts")
    tree = {"w": np.arange(8, dtype=np.float32), "b": np.float32(0.0)}
    with AsyncCheckpointer(path, keep=2) as ck:
        for step in range(5):
            tree["w"] = tree["w"] + 1.0  # new array each step
            snap = {"w": tree["w"].copy(), "b": np.float32(step)}
            ck.save(snap, step)
            snap["w"][:] = -1  # mutate AFTER save: the snapshot must win...
            # ...for device arrays; host numpy is snapshotted by np.asarray
            # only when a copy occurs, so pass fresh arrays (as trainers do)
        ck.wait()
        assert latest_step(path) == 4
        kept = sorted(d for d in os.listdir(path) if d.startswith("step_"))
        assert len(kept) == 2 and kept[-1].endswith("0000000004")
        restored = restore_checkpoint(path)
        assert float(restored["b"]) == 4.0

    bad = AsyncCheckpointer("/proc/definitely/not/writable", keep=1)
    bad.save({"x": np.zeros(2)}, 0)
    with pytest.raises(Exception):
        bad.wait()


def test_async_checkpointer_nonblocking_save_and_backpressure(tmp_path, monkeypatch):
    """save() must return without waiting for the disk write (the device→host
    fetch + serialization run on the worker), and a second save() while a
    write is in flight must BLOCK until it completes — never queue a second
    host snapshot (the OOM mode on 7B-class states)."""
    import time

    import jax.numpy as jnp

    from synapseml_tpu.parallel import checkpoint as cp

    real_save = cp.save_checkpoint
    delay = 0.4

    def slow_save(path, tree, step=0, use_orbax=None, sharding=None):
        time.sleep(delay)
        return real_save(path, tree, step, use_orbax=use_orbax,
                         sharding=sharding)

    monkeypatch.setattr(cp, "save_checkpoint", slow_save)

    tree = {"w": jnp.zeros((64, 64), jnp.float32), "b": np.float32(1.0)}
    with cp.AsyncCheckpointer(str(tmp_path / "bp"), keep=10) as ck:
        t0 = time.perf_counter()
        fut0 = ck.save(tree, 0)
        t_first = time.perf_counter() - t0
        assert t_first < delay / 2, f"save() blocked {t_first:.3f}s on the write"

        t0 = time.perf_counter()
        ck.save(tree, 1)
        t_second = time.perf_counter() - t0
        # backpressure: the second save waited out write 0 before snapshotting
        assert t_second >= delay * 0.6, f"second save returned in {t_second:.3f}s"
        assert fut0.done(), "write 0 still pending after save(1) returned"
    assert cp.latest_step(str(tmp_path / "bp")) == 1
    restored = cp.restore_checkpoint(str(tmp_path / "bp"))
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.zeros((64, 64)))


def test_async_checkpointer_error_surfaces_at_next_save():
    """With single-pending backpressure, a failed write's error is raised by
    the NEXT save (not silently dropped until close)."""
    import pytest

    from synapseml_tpu.parallel import AsyncCheckpointer

    ck = AsyncCheckpointer("/proc/definitely/not/writable", keep=1)
    ck.save({"x": np.zeros(2)}, 0)
    with pytest.raises(Exception):
        ck.save({"x": np.zeros(2)}, 1)


@pytest.mark.slow
def test_llama2_7b_training_state_fits_v5e16_abstractly():
    """TRAINING-side companion to the inference footprint check: the full
    7B train STATE (f32 params + two Adam moments + bf16 grads live during
    the step) under the fsdp=16 mesh sharding must fit v5e-16 HBM. Validates
    the training sharding rules at real width with zero materialization."""
    import jax
    import jax.numpy as jnp
    import flax.linen as nn
    from flax.core import meta

    from synapseml_tpu.models.flax_nets.llama import LlamaLM, llama2_7b
    from synapseml_tpu.parallel.mesh import logical_axis_rules

    cfg = llama2_7b()
    module = LlamaLM(cfg)
    abstract = jax.eval_shape(
        lambda: module.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 8), jnp.int32)))
    mesh_sizes = {"fsdp": 16}
    rules = logical_axis_rules()

    per_device = 0
    total_params = 0
    for leaf in jax.tree.leaves(
            abstract["params"],
            is_leaf=lambda x: isinstance(x, meta.Partitioned)):
        if isinstance(leaf, meta.Partitioned):
            spec = nn.logical_to_mesh_axes(leaf.names, rules=rules)
            shape = leaf.value.shape
        else:
            spec, shape = (), leaf.shape
        divisor = 1
        for dim, axis in zip(shape, tuple(spec) + (None,) * len(shape)):
            axes = (axis,) if isinstance(axis, str) else (axis or ())
            for a in axes:
                size = mesh_sizes.get(a, 1)
                if size > 1 and dim % size == 0:
                    divisor *= size
        n = int(np.prod(shape))
        total_params += n
        # f32 master params + 2 f32 Adam moments + bf16 grads = 14 bytes/param
        per_device += n * 14 // divisor

    assert total_params > 6e9
    gb = per_device / 1e9
    assert gb < 12, f"{gb:.2f} GB/device training state exceeds v5e headroom"


@pytest.mark.slow
def test_realistic_width_compiled_memory_divides_by_fsdp():
    """VERDICT r4 weak-#8: multichip evidence beyond toy shapes. Compile the
    REAL jitted train step at transformer-large width (hidden 1024, heads
    16, mlp 4096, 30k vocab — ~90M params at 4 layers; width, not depth, is
    what sharding must divide) on the 8-device mesh and read XLA's
    per-device memory analysis: under fsdp=8 the argument (state) bytes —
    params AND Adam moments — must be ~1/8 of the pure-DP replicated
    layout (that division IS the grad/optimizer sharding evidence), and
    the HLO must contain the param all-gather that only an fsdp layout
    needs (pure DP has all-reduce but never gathers params)."""
    import dataclasses

    import jax

    from synapseml_tpu.models.flax_nets.bert import BertClassifier, bert_tiny
    from synapseml_tpu.models.trainer import Trainer, TrainerConfig

    cfg = dataclasses.replace(bert_tiny(), hidden=1024, n_layers=4,
                              n_heads=16, mlp_dim=4096, vocab_size=30522,
                              max_len=512)
    batch = {"input_ids": np.zeros((8, 128), np.int32),
             "attention_mask": np.ones((8, 128), np.int32),
             "labels": np.zeros((8,), np.int32)}

    def compiled_for(mesh_cfg):
        mesh = create_mesh(mesh_cfg)
        tr = Trainer(BertClassifier(cfg, num_classes=2), mesh,
                     TrainerConfig(learning_rate=1e-4, total_steps=10))
        state = tr.init_state(batch)
        step = jax.jit(tr._step_fn(), donate_argnums=(0,))
        placed = tr.mesh.shard_batch(batch)
        with tr.mesh.mesh:
            compiled = step.lower(
                state.as_dict() | {"batch_stats": None}, placed).compile()
        return compiled

    fsdp = compiled_for(MeshConfig(fsdp=8))
    dp = compiled_for(MeshConfig(data=8))
    ma_f, ma_d = fsdp.memory_analysis(), dp.memory_analysis()
    # the state dominates arguments; fsdp=8 must divide it (~8x smaller,
    # allow slack for the replicated batch and scalars)
    assert ma_f.argument_size_in_bytes < ma_d.argument_size_in_bytes / 4, (
        ma_f.argument_size_in_bytes, ma_d.argument_size_in_bytes)
    # live temp memory during the step must not regress above the
    # replicated layout's (remat/collectives may add small overheads)
    assert ma_f.temp_size_in_bytes < ma_d.temp_size_in_bytes * 1.5
    # the fsdp signature collective: params gathered for use. (XLA here
    # lowers grad reduction as all-reduce over the sharded layout rather
    # than reduce-scatter; the argument-size division above is what proves
    # grads/moments are NOT replicated.)
    hlo = fsdp.as_text()
    assert "all-gather" in hlo, "fsdp step compiled without param all-gather"


def test_optimizer_state_shards_with_params():
    """ZeRO-style weight-update sharding (cf. 'Automatic Cross-Replica
    Sharding of Weight Update in Data-Parallel Training'): on an fsdp mesh
    the Adam moments must carry the SAME shardings as their params — a
    replicated moment would silently multiply optimizer memory by the fsdp
    factor."""
    import jax

    from synapseml_tpu.models.flax_nets.bert import BertClassifier, bert_tiny
    from synapseml_tpu.models.trainer import Trainer, TrainerConfig

    cfg = bert_tiny(n_layers=1)
    mesh = create_mesh(MeshConfig(data=2, fsdp=4))
    trainer = Trainer(BertClassifier(cfg, num_classes=2), mesh,
                      TrainerConfig(learning_rate=1e-3, total_steps=2))
    rs = np.random.default_rng(0)
    batch = {"input_ids": rs.integers(0, cfg.vocab_size, (8, 16)).astype(np.int32),
             "attention_mask": np.ones((8, 16), np.int32),
             "labels": rs.integers(0, 2, (8,)).astype(np.int32)}
    state = trainer.init_state(batch)

    param_shardings = {
        jax.tree_util.keystr(path): leaf.sharding
        for path, leaf in jax.tree_util.tree_flatten_with_path(state.params)[0]}
    any_sharded = any(
        any(s is not None for s in getattr(sh.spec, "_partitions", sh.spec))
        for sh in param_shardings.values()
        if hasattr(sh, "spec"))
    assert any_sharded, "fsdp mesh produced fully-replicated params"

    # any param-shaped optimizer moment (Adam mu/nu mirror the param tree)
    # must carry its param's sharding, not replication
    checked = 0
    mu_nu = [leaf for leaf in jax.tree.leaves(state.opt_state)
             if hasattr(leaf, "shape") and leaf.ndim >= 2]
    params_by_shape = {}
    for leaf in jax.tree.leaves(state.params):
        params_by_shape.setdefault(leaf.shape, leaf.sharding)
    for leaf in mu_nu:
        want = params_by_shape.get(leaf.shape)
        if want is not None:
            assert leaf.sharding == want, (
                f"opt-state leaf {leaf.shape} sharded {leaf.sharding}, "
                f"param counterpart {want}")
            checked += 1
    assert checked >= 4, "no param-shaped optimizer moments found to check"
