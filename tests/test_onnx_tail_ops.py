"""Elementwise / logic / layout tail ops vs numpy spec oracles — the long
tail of ORT's opset behind the reference ONNXModel (`ONNXRuntime.scala:25`)."""

import numpy as np
import pytest

from synapseml_tpu.onnx.convert import OP_REGISTRY


def run_op(op, ins, **attrs):
    return np.asarray(OP_REGISTRY[op](
        [None if x is None else np.asarray(x) for x in ins], attrs))


rs = np.random.default_rng(0)
X = (rs.normal(size=(3, 5)) * 3).astype(np.float32)
Y = (rs.normal(size=(3, 5)) * 3).astype(np.float32)


@pytest.mark.parametrize("op,ref", [
    ("Floor", np.floor), ("Ceil", np.ceil), ("Round", np.rint),
    ("Sign", np.sign), ("Reciprocal", lambda x: 1 / x),
    ("Softplus", lambda x: np.log1p(np.exp(x))),
    ("Softsign", lambda x: x / (1 + np.abs(x))),
    ("Mish", lambda x: x * np.tanh(np.log1p(np.exp(x)))),
    ("IsNaN", np.isnan),
])
def test_unary_elementwise(op, ref):
    np.testing.assert_allclose(run_op(op, [X]), ref(X), rtol=1e-5, atol=1e-6)


def test_round_is_half_to_even():
    x = np.asarray([0.5, 1.5, 2.5, -0.5, -1.5], np.float32)
    np.testing.assert_array_equal(run_op("Round", [x]), [0, 2, 2, -0, -2])


@pytest.mark.parametrize("op,ref", [
    ("Min", np.minimum), ("Max", np.maximum), ("Sum", np.add),
])
def test_variadic(op, ref):
    np.testing.assert_allclose(run_op(op, [X, Y, X]), ref(ref(X, Y), X))


def test_mean_variadic():
    np.testing.assert_allclose(run_op("Mean", [X, Y, X]), (X + Y + X) / 3,
                               rtol=1e-6)


def test_logic_and_comparison():
    a, b = X > 0, Y > 0
    np.testing.assert_array_equal(run_op("And", [a, b]), a & b)
    np.testing.assert_array_equal(run_op("Or", [a, b]), a | b)
    np.testing.assert_array_equal(run_op("Xor", [a, b]), a ^ b)
    np.testing.assert_array_equal(run_op("GreaterOrEqual", [X, Y]), X >= Y)
    np.testing.assert_array_equal(run_op("LessOrEqual", [X, Y]), X <= Y)


def test_mod_semantics():
    a = np.asarray([-4, 7, 5], np.int64)
    b = np.asarray([3, -3, 8], np.int64)
    np.testing.assert_array_equal(run_op("Mod", [a, b]), np.mod(a, b))
    af = np.asarray([-4.3, 7.2], np.float32)
    bf = np.asarray([2.1, -3.3], np.float32)
    np.testing.assert_allclose(run_op("Mod", [af, bf], fmod=1),
                               np.fmod(af, bf), rtol=1e-6)


def test_activation_family():
    np.testing.assert_allclose(run_op("PRelu", [X, np.float32(0.1)]),
                               np.where(X < 0, 0.1 * X, X))
    np.testing.assert_allclose(run_op("Elu", [X], alpha=0.5),
                               np.where(X < 0, 0.5 * (np.exp(X) - 1), X),
                               rtol=1e-6)
    a, g = 1.67326319217681884765625, 1.05070102214813232421875
    np.testing.assert_allclose(run_op("Selu", [X]),
                               g * np.where(X < 0, a * (np.exp(X) - 1), X),
                               rtol=1e-5)
    np.testing.assert_allclose(run_op("Celu", [X], alpha=2.0),
                               np.maximum(X, 0)
                               + np.minimum(0, 2.0 * (np.exp(X / 2.0) - 1)),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(run_op("ThresholdedRelu", [X], alpha=1.0),
                               np.where(X > 1.0, X, 0))
    np.testing.assert_allclose(
        run_op("Shrink", [X], lambd=0.5, bias=0.2),
        np.where(X < -0.5, X + 0.2, np.where(X > 0.5, X - 0.2, 0)))


def test_isinf_directions():
    x = np.asarray([np.inf, -np.inf, 1.0], np.float32)
    np.testing.assert_array_equal(run_op("IsInf", [x]), [True, True, False])
    np.testing.assert_array_equal(run_op("IsInf", [x], detect_negative=0),
                                  [True, False, False])
    np.testing.assert_array_equal(run_op("IsInf", [x], detect_positive=0),
                                  [False, True, False])


def test_bit_shift():
    a = np.asarray([1, 2, 8], np.uint8)
    np.testing.assert_array_equal(run_op("BitShift", [a, np.uint8(2)],
                                         direction="LEFT"), a << 2)
    np.testing.assert_array_equal(run_op("BitShift", [a, np.uint8(1)],
                                         direction="RIGHT"), a >> 1)


@pytest.mark.parametrize("exclusive,reverse", [(0, 0), (1, 0), (0, 1), (1, 1)])
def test_cumsum_modes(exclusive, reverse):
    x = np.asarray([[1.0, 2, 3], [4, 5, 6]], np.float32)
    got = run_op("CumSum", [x, np.asarray(1)], exclusive=exclusive,
                 reverse=reverse)
    ref = x[:, ::-1] if reverse else x
    ref = np.cumsum(ref, axis=1)
    if exclusive:
        ref = np.concatenate([np.zeros((2, 1), np.float32), ref[:, :-1]], 1)
    if reverse:
        ref = ref[:, ::-1]
    np.testing.assert_allclose(got, ref)


def test_one_hot():
    idx = np.asarray([0, 2, -1], np.int64)        # -1 wraps to depth-1
    vals = np.asarray([2.0, 9.0], np.float32)     # [off, on]
    got = run_op("OneHot", [idx, np.asarray(4), vals])
    ref = np.full((3, 4), 2.0, np.float32)
    ref[0, 0] = ref[1, 2] = ref[2, 3] = 9.0
    np.testing.assert_array_equal(got, ref)
    got_ax0 = run_op("OneHot", [idx, np.asarray(4), vals], axis=0)
    np.testing.assert_array_equal(got_ax0, ref.T)


def test_argmin_and_reduce_family():
    np.testing.assert_array_equal(
        run_op("ArgMin", [X], axis=1, keepdims=0), X.argmin(1))
    np.testing.assert_allclose(run_op("ReduceL1", [X, np.asarray([1])]),
                               np.abs(X).sum(1, keepdims=True), rtol=1e-6)
    np.testing.assert_allclose(run_op("ReduceL2", [X, np.asarray([1])]),
                               np.sqrt((X ** 2).sum(1, keepdims=True)),
                               rtol=1e-6)
    np.testing.assert_allclose(run_op("ReduceSumSquare", [X, np.asarray([1])]),
                               (X ** 2).sum(1, keepdims=True), rtol=1e-6)
    Xp = np.abs(X) + 0.1
    np.testing.assert_allclose(run_op("ReduceLogSum", [Xp, np.asarray([1])]),
                               np.log(Xp.sum(1, keepdims=True)), rtol=1e-6)
    np.testing.assert_allclose(
        run_op("ReduceLogSumExp", [X, np.asarray([1])]),
        np.log(np.exp(X).sum(1, keepdims=True)), rtol=1e-5)


@pytest.mark.parametrize("mode", ["DCR", "CRD"])
def test_depth_to_space_roundtrip(mode):
    x = rs.normal(size=(2, 8, 3, 4)).astype(np.float32)
    up = run_op("DepthToSpace", [x], blocksize=2, mode=mode)
    assert up.shape == (2, 2, 6, 8)
    if mode == "DCR":  # SpaceToDepth is DCR's exact inverse
        back = run_op("SpaceToDepth", [up], blocksize=2)
        np.testing.assert_array_equal(back, x)


def test_depth_to_space_dcr_oracle():
    # 1x4x1x1, blocksize 2 -> channels [0,1,2,3] land row-major in the 2x2
    x = np.arange(4, dtype=np.float32).reshape(1, 4, 1, 1)
    out = run_op("DepthToSpace", [x], blocksize=2)
    np.testing.assert_array_equal(out.reshape(2, 2), [[0, 1], [2, 3]])


def test_reverse_sequence():
    x = np.arange(12, dtype=np.float32).reshape(4, 3)     # [T=4, B=3]
    lens = np.asarray([4, 2, 1], np.int64)
    got = run_op("ReverseSequence", [x, lens])            # defaults T=0, B=1
    ref = x.copy()
    for b, L in enumerate(lens):
        ref[:L, b] = ref[:L, b][::-1]
    np.testing.assert_array_equal(got, ref)


def test_eye_like_and_size():
    x = np.zeros((3, 5), np.float32)
    np.testing.assert_array_equal(run_op("EyeLike", [x], k=1),
                                  np.eye(3, 5, k=1, dtype=np.float32))
    assert int(run_op("Size", [x])) == 15


def test_eye_like_jit_safe_without_dtype_attr():
    import jax

    out = jax.jit(lambda x: OP_REGISTRY["EyeLike"]([x], {}))(
        np.zeros((3, 3), np.float32))
    np.testing.assert_array_equal(np.asarray(out), np.eye(3, dtype=np.float32))
    assert np.asarray(out).dtype == np.float32


def test_one_hot_exact_int64_values():
    # on-value above 2^24: float32 blending would corrupt it
    big = 2 ** 24 + 1
    got = run_op("OneHot", [np.asarray([1], np.int64), np.asarray(3),
                            np.asarray([0, big], np.int64)])
    # jax demotes int64->int32 (x64 disabled), but the VALUE stays exact —
    # float32 blending would have rounded it to 2^24
    assert got.dtype.kind == "i"
    np.testing.assert_array_equal(got, [[0, big, 0]])


def test_argminmax_select_last_index_raises():
    with pytest.raises(NotImplementedError, match="select_last_index"):
        run_op("ArgMin", [X], select_last_index=1)
    with pytest.raises(NotImplementedError, match="select_last_index"):
        run_op("ArgMax", [X], select_last_index=1)


def test_reduce_noop_with_empty_axes():
    got = run_op("ReduceL2", [X, np.asarray([], np.int64)],
                 noop_with_empty_axes=1)
    np.testing.assert_array_equal(got, X)            # identity, per opset 18
    # without the flag an empty axes tensor means reduce-all
    got_all = run_op("ReduceSum", [X, np.asarray([], np.int64)])
    np.testing.assert_allclose(got_all, X.sum(keepdims=True).reshape(1, 1),
                               rtol=1e-6)
