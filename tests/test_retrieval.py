"""Retrieval serving plane (ISSUE 18): sharded vector index + continual
ingest.

Offline throughout: hash-trick embeddings (integer-valued vectors, so
distances are EXACT in float32 and jit-vs-numpy comparisons cannot flake),
registries in tmp dirs, real subprocess workers on real HTTP ports for the
serve/chaos surfaces. The acceptance surfaces:

* compile bound — N same-shape shards under a mixed-size query stream
  compile at most ladder-many executables TOTAL (the scorer keys
  executables by shard shape, not shard identity);
* parity — VectorIndexModel == numpy brute force == seed KNNModel on the
  same vectors, and shard partitioning never changes a result;
* kill/resume — a SIGKILLed ingest job resumed in a fresh process
  produces byte-identical delta shards, and a torn delta is invisible to
  ``registry.resolve()``;
* E2E — build -> publish -> 2-worker fan-out at recall@10 == 1.0 ->
  logged docs become queryable delta shards with zero downtime -> a
  worker SIGKILL mid-storm degrades to explicit partials, never a 500.
"""

import json
import os
import shutil
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import synapseml_tpu
from synapseml_tpu.core import batching as cb
from synapseml_tpu.core.dataframe import DataFrame
from synapseml_tpu.data.source import ShardedSource
from synapseml_tpu.io.distributed_serving import RoutingFront, WorkerRegistry
from synapseml_tpu.registry import ModelRegistry
from synapseml_tpu.retrieval import (HashEmbedder, VectorIndexModel,
                                     build_index, compact_index,
                                     ingest_deltas, list_shards, open_shard,
                                     score_batches, write_shard)
from synapseml_tpu.retrieval.scorer import FN_ID

pytestmark = pytest.mark.retrieval

DIM = 16


@pytest.fixture()
def fresh_cache():
    cache = cb.reset_compiled_cache()
    yield cache
    cb.reset_compiled_cache()


# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------

def _texts(n, start=0):
    """Each text carries a unique token, so hash embeddings are pairwise
    distinct; all coordinates are small integers (exact float32 math)."""
    return [f"doc{start + i} alpha{i % 3} beta{i % 5} gamma{i % 7}"
            for i in range(n)]


def _write_corpus(directory, n_docs, files=4):
    os.makedirs(directory, exist_ok=True)
    texts = _texts(n_docs)
    per = (n_docs + files - 1) // files
    for f_i in range(files):
        with open(os.path.join(directory, f"corpus-{f_i:03d}.jsonl"), "w") as f:
            for i in range(f_i * per, min((f_i + 1) * per, n_docs)):
                f.write(json.dumps({"id": i, "text": texts[i]}) + "\n")
    return texts


def _build(tmp_path, registry_root, n_docs=96, files=4):
    """Corpus -> embed -> multi-shard index -> published v1. Returns
    (registry, texts, embedder)."""
    texts = _write_corpus(str(tmp_path / "corpus"), n_docs, files)
    emb = HashEmbedder(dim=DIM)
    registry = ModelRegistry(registry_root)
    source = ShardedSource.jsonl(str(tmp_path / "corpus" / "*.jsonl"))
    published, report = build_index(
        registry, "docs", emb, source, str(tmp_path / "work"),
        payload_fn=lambda i: {"text": texts[i]}, k=10, batch_rows=32)
    assert published.version == "v1"
    assert report.rows_written == n_docs
    return registry, texts, emb


def _brute_topk_ids(E, ids, Q, k):
    """Exact float32 brute force with the plane's (distance, id) tie-break."""
    d = (np.sum(Q * Q, axis=1, keepdims=True) - 2.0 * Q @ E.T
         + np.sum(E * E, axis=1)[None, :])
    out = []
    for row in d:
        order = sorted(range(len(ids)), key=lambda j: (row[j], ids[j]))
        out.append([int(ids[j]) for j in order[:k]])
    return out


def _post(url, body, timeout=60.0):
    req = urllib.request.Request(url, data=body, method="POST",
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read() or b"{}"), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


# ---------------------------------------------------------------------------
# shard format
# ---------------------------------------------------------------------------

def test_shard_write_open_list_roundtrip(tmp_path):
    rs = np.random.default_rng(0)
    vec = rs.normal(size=(7, 5)).astype(np.float32)
    ids = np.arange(100, 107, dtype=np.int64)
    payloads = [{"text": f"p{i}"} for i in range(7)]
    sh = write_shard(str(tmp_path), "base-00000", vec, ids=ids,
                     payloads=payloads, kind="base")
    got = open_shard(sh.path, verify=True)
    np.testing.assert_array_equal(got.vectors(), vec)
    np.testing.assert_array_equal(got.ids(), ids)
    assert got.payloads() == payloads
    assert (got.rows, got.dim, got.kind) == (7, 5, "base")
    assert got.nbytes > 0
    # idempotent re-commit: the existing shard is kept byte-for-byte
    again = write_shard(str(tmp_path), "base-00000",
                        np.zeros((3, 5), np.float32))
    assert again.rows == 7
    assert [s.name for s in list_shards(str(tmp_path))] == ["base-00000"]


def test_torn_shard_invisible_and_corruption_detected(tmp_path):
    write_shard(str(tmp_path), "base-00000", np.ones((4, 3), np.float32))
    # a torn write is a staged .tmp-* dir: no reader ever lists it
    os.makedirs(tmp_path / ".tmp-base-00001")
    (tmp_path / ".tmp-base-00001" / "vectors.npy").write_bytes(b"torn")
    assert [s.name for s in list_shards(str(tmp_path))] == ["base-00000"]
    # bit-rot after commit fails closed through verify()
    sh = list_shards(str(tmp_path))[0]
    np.save(os.path.join(sh.path, "vectors.npy"),
            np.zeros((4, 3), np.float32))
    with pytest.raises(ValueError, match="sha mismatch"):
        open_shard(sh.path, verify=True)


def test_shard_validation_rejects_bad_inputs(tmp_path):
    with pytest.raises(ValueError, match="kind"):
        write_shard(str(tmp_path), "x", np.ones((2, 2), np.float32),
                    kind="weird")
    with pytest.raises(ValueError, match="N, D"):
        write_shard(str(tmp_path), "x", np.ones(4, np.float32))
    with pytest.raises(ValueError, match="ids"):
        write_shard(str(tmp_path), "x", np.ones((2, 2), np.float32),
                    ids=np.arange(3))


# ---------------------------------------------------------------------------
# shared scorer: parity + compile bound
# ---------------------------------------------------------------------------

def test_scorer_matches_numpy(fresh_cache):
    rs = np.random.default_rng(1)
    Q = rs.integers(-3, 4, size=(13, 8)).astype(np.float32)
    X = rs.integers(-3, 4, size=(37, 8)).astype(np.float32)
    dist, idx = score_batches(Q, X, 5, query_batch=8)
    ref = (np.sum(Q * Q, 1, keepdims=True) - 2.0 * Q @ X.T
           + np.sum(X * X, 1)[None, :])
    for i in range(len(Q)):
        want = np.sort(ref[i])[:5]
        np.testing.assert_allclose(np.sort(dist[i]), want, atol=1e-4)
        assert set(ref[i][idx[i]].round(4)) == set(dist[i].round(4))


def test_compile_bound_shared_across_same_shape_shards(fresh_cache):
    """The acceptance compile bound: scoring S same-shape shards under a
    mixed-size query stream compiles at most ladder-many executables TOTAL
    — the shard matrix is a traced argument, not a closure capture."""
    rs = np.random.default_rng(2)
    shards = [rs.normal(size=(64, DIM)).astype(np.float32) for _ in range(6)]
    sizes = [3, 17, 9, 30, 1, 24]
    bucketer = cb.default_bucketer()
    buckets = set()
    for n in sizes:
        for _s, _e, b in bucketer.slices(n, 32):
            buckets.add(b)
    miss0 = fresh_cache.miss_count(FN_ID)  # the counter is cumulative
    for n in sizes:
        Q = rs.normal(size=(n, DIM)).astype(np.float32)
        for X in shards:
            score_batches(Q, X, 5, query_batch=32)
    misses = fresh_cache.miss_count(FN_ID) - miss0
    assert misses <= len(buckets)  # NOT len(buckets) * len(shards)
    # a fresh same-shape shard adds ZERO compiles
    extra = rs.normal(size=(64, DIM)).astype(np.float32)
    score_batches(rs.normal(size=(9, DIM)).astype(np.float32), extra, 5,
                  query_batch=32)
    assert fresh_cache.miss_count(FN_ID) - miss0 == misses


def test_knn_and_vector_index_agree(fresh_cache):
    """Seed KNNModel and VectorIndexModel ride the SAME kernel — their
    results on the same vectors cannot drift."""
    from synapseml_tpu.nn import KNN

    rs = np.random.default_rng(3)
    X = rs.integers(-3, 4, size=(40, DIM)).astype(np.float32)
    Q = rs.integers(-3, 4, size=(11, DIM)).astype(np.float32)
    knn = KNN(k=7).fit(DataFrame.from_dict(
        {"features": list(X), "values": np.arange(40)}))
    knn_out = knn.transform(
        DataFrame.from_dict({"features": list(Q)})).collect_column("output")
    model = VectorIndexModel(shard_names=["s0"], dim=DIM, k=7,
                             inline_shards={"s0": {"vectors": X}})
    idx_out = model.search(Q)
    for km, vm in zip(knn_out, idx_out):
        assert [m["index"] for m in km] == [m["id"] for m in vm]
        np.testing.assert_allclose([m["distance"] for m in km],
                                   [m["distance"] for m in vm], atol=1e-5)


def test_search_invariant_to_shard_partitioning(fresh_cache):
    rs = np.random.default_rng(4)
    X = rs.integers(-4, 5, size=(60, DIM)).astype(np.float32)
    Q = rs.integers(-4, 5, size=(9, DIM)).astype(np.float32)
    one = VectorIndexModel(
        shard_names=["all"], dim=DIM, k=10,
        inline_shards={"all": {"vectors": X, "ids": np.arange(60)}})
    cuts = [(0, 23), (23, 41), (41, 60)]
    many = VectorIndexModel(
        shard_names=[f"p{i}" for i in range(3)], dim=DIM, k=10,
        inline_shards={f"p{i}": {"vectors": X[a:b],
                                 "ids": np.arange(a, b)}
                       for i, (a, b) in enumerate(cuts)})
    r1, r3 = one.search(Q), many.search(Q)
    for a, b in zip(r1, r3):
        assert [m["id"] for m in a] == [m["id"] for m in b]
        np.testing.assert_allclose([m["distance"] for m in a],
                                   [m["distance"] for m in b], atol=1e-6)
    brute = _brute_topk_ids(X, np.arange(60), Q, 10)
    for got, want in zip(r1, brute):
        assert [m["id"] for m in got] == want


def test_cosine_metric_normalizes_queries(fresh_cache):
    rs = np.random.default_rng(5)
    X = rs.normal(size=(30, DIM)).astype(np.float32)
    X /= np.linalg.norm(X, axis=1, keepdims=True)
    model = VectorIndexModel(shard_names=["s"], dim=DIM, k=3,
                             metric="cosine",
                             inline_shards={"s": {"vectors": X}})
    q = X[7] * 250.0  # scale must not matter under cosine
    r_scaled = model.search(q[None, :])[0]
    r_unit = model.search(X[7][None, :])[0]
    assert [m["id"] for m in r_scaled] == [m["id"] for m in r_unit]
    assert r_scaled[0]["id"] == 7


# ---------------------------------------------------------------------------
# build + publish + registry round trip
# ---------------------------------------------------------------------------

def test_build_publish_resolve_search(tmp_path, fresh_cache):
    registry, texts, emb = _build(tmp_path, str(tmp_path / "store"))
    resolved = registry.resolve("docs", "latest")
    extra = resolved.manifest["extra"]["retrieval"]
    assert extra["rows"] == len(texts) and extra["dim"] == DIM
    assert len(extra["shards"]) > 1  # genuinely multi-shard
    stage = resolved.stage
    # the loaded stage finds its shards through the materialized artifact
    assert os.path.isdir(stage.shards_root())
    E = emb.embed(texts)
    hits = stage.search(E[17][None, :], k=3)[0]
    assert hits[0]["id"] == 17
    assert hits[0]["payload"] == {"text": texts[17]}
    assert hits[0]["distance"] == 0.0
    # publishing again under the same version must refuse (immutability)
    with pytest.raises(FileExistsError):
        from synapseml_tpu.retrieval import publish_index
        publish_index(registry, "docs", str(tmp_path / "work" / "index"),
                      version="v1")


def test_recommendation_item_index_export(tmp_path, fresh_cache):
    """SAR item-item similarity rows become a servable IndexShard: nearest
    neighbors in similarity space ARE 'similar items'."""
    from synapseml_tpu.recommendation import (RecommendationIndexer, SAR,
                                              export_item_index)
    from test_nn_recommendation import make_interactions

    indexer = RecommendationIndexer().fit(make_interactions())
    df = indexer.transform(make_interactions())
    sar = SAR(rating_col="rating", support_threshold=2,
              similarity_function="jaccard").fit(df)
    sh = export_item_index(sar, str(tmp_path / "idx"), indexer=indexer)
    table = np.asarray(sar.get("item_data_frame"), np.float32)
    assert (sh.rows, sh.dim) == table.shape
    model = VectorIndexModel(shard_names=[sh.name], dim=sh.dim,
                             k=4).attach(str(tmp_path / "idx" / "shards"))
    hits = model.search(table[0][None, :])[0]
    assert hits[0]["id"] == 0  # an item's own row is its nearest neighbor
    assert hits[0]["payload"] == {"item": "i0"}
    # similar items stay in-clique (raw item ids i0-i5 co-occur; i6-i11
    # are the other taste clique; ids are indexer-order, so map back
    # through the payload sidecar)
    near = [h["payload"]["item"] for h in hits if h["distance"] < 1.0]
    assert near and all(int(item[1:]) < 6 for item in near)


# ---------------------------------------------------------------------------
# continual ingest
# ---------------------------------------------------------------------------

def _log_docs(log_dir, texts, ts=None):
    """Commit doc traffic through the real flywheel RequestLogger."""
    from synapseml_tpu.continual import RequestLogger

    with RequestLogger(log_dir, shard_rows=8) as lg:
        for t in texts:
            lg.log(method="POST", path="/ingest/docs",
                   body=json.dumps({"doc": t}).encode(), reply=b"ok",
                   status=200, latency_ms=1.0)
        lg.flush()


def test_ingest_deltas_freshness_and_idempotence(tmp_path, fresh_cache):
    registry, texts, emb = _build(tmp_path, str(tmp_path / "store"))
    fresh = [f"freshdoc{i} zeta{i} unique token stream" for i in range(10)]
    log_dir = str(tmp_path / "logs")
    _log_docs(log_dir, fresh)
    report = ingest_deltas(registry, "docs", log_dir, HashEmbedder(dim=DIM),
                           str(tmp_path / "ingest1"))
    assert report["base_version"] == "v1" and report["version"] == "v2"
    assert report["docs"] == len(fresh)
    assert report["delta_shards"] and report["freshness_lag_s"] > 0
    resolved = registry.resolve("docs", "latest")
    assert resolved.version == "v2"
    kinds = {s["name"]: s["kind"]
             for s in resolved.manifest["extra"]["retrieval"]["shards"]}
    assert set(report["delta_shards"]) == {
        n for n, k in kinds.items() if k == "delta"}
    # fresh docs are queryable, ids continue the global id space
    hits = resolved.stage.search(emb.embed([fresh[3]]), k=1)[0]
    assert hits[0]["id"] == len(texts) + 3
    assert hits[0]["distance"] == 0.0
    assert hits[0]["shard"].startswith("delta-v1")
    # base docs still answer from the same version (no rebuild regression)
    assert resolved.stage.search(emb.embed([texts[5]]), k=1)[0][0]["id"] == 5
    # a re-run with nothing new is a no-op (ingested_parts gate)
    assert ingest_deltas(registry, "docs", log_dir, HashEmbedder(dim=DIM),
                         str(tmp_path / "ingest2")) is None
    assert registry.resolve_ref("docs", "latest") == "v2"


def test_compaction_folds_deltas_into_one_base(tmp_path, fresh_cache):
    registry, texts, emb = _build(tmp_path, str(tmp_path / "store"),
                                  n_docs=40, files=2)
    # ONE flywheel log stream: the logger continues part numbering across
    # rounds, and the manifest's ingested_parts gate is keyed by part name
    log_dir = str(tmp_path / "logs")
    for round_i in range(2):
        fresh = [f"round{round_i}doc{i} eta{i}" for i in range(6)]
        _log_docs(log_dir, fresh)
        ingest_deltas(registry, "docs", log_dir, HashEmbedder(dim=DIM),
                      str(tmp_path / f"ingest{round_i}"))
    pre = registry.resolve("docs", "latest")
    deltas = [s for s in pre.manifest["extra"]["retrieval"]["shards"]
              if s["kind"] == "delta"]
    assert len(deltas) >= 2
    assert compact_index(registry, "docs", str(tmp_path / "nocompact"),
                         threshold=10) is None  # below threshold: no-op
    report = compact_index(registry, "docs", str(tmp_path / "compact"),
                           threshold=2)
    assert sorted(report["merged"]) == sorted(s["name"] for s in deltas)
    post = registry.resolve("docs", "latest")
    assert post.version == report["version"]
    post_shards = post.manifest["extra"]["retrieval"]["shards"]
    assert all(s["kind"] == "base" for s in post_shards)
    assert post.manifest["extra"]["retrieval"]["rows"] == \
        pre.manifest["extra"]["retrieval"]["rows"]
    # compaction must not change any answer
    probe = emb.embed(["round1doc3 eta3", texts[11]])
    for q in probe:
        a = pre.stage.search(q[None, :], k=5)[0]
        b = post.stage.search(q[None, :], k=5)[0]
        assert [m["id"] for m in a] == [m["id"] for m in b]


# ---------------------------------------------------------------------------
# chaos: SIGKILL kill/resume ingest, byte-identical
# ---------------------------------------------------------------------------

_INGEST_SCRIPT = """
import os, signal, sys
import jax
jax.config.update("jax_platforms", "cpu")
from synapseml_tpu.registry import ModelRegistry
from synapseml_tpu.retrieval import ingest_deltas
from synapseml_tpu.retrieval.build import HashEmbedder
from synapseml_tpu.retrieval import ingest as ingest_mod

root, log_dir, work_dir, cut = sys.argv[1:5]

class KillingEmbedder(HashEmbedder):
    def _transform(self, df):
        if cut == "embed":  # SIGKILL mid-embed: torn sink part, no DONE
            os.kill(os.getpid(), signal.SIGKILL)
        return super()._transform(df)

if cut == "publish":
    def _boom(*a, **k):  # SIGKILL after delta commit, BEFORE publish
        os.kill(os.getpid(), signal.SIGKILL)
    ingest_mod._republish = _boom

ingest_deltas(ModelRegistry(root), "docs", log_dir, KillingEmbedder(dim=16),
              work_dir)
"""


def _delta_shard_files(stage_dir):
    out = {}
    shards_dir = os.path.join(stage_dir, "shards")
    for name in sorted(os.listdir(shards_dir)):
        if not name.startswith("delta-"):
            continue
        d = os.path.join(shards_dir, name)
        for fn in sorted(os.listdir(d)):
            with open(os.path.join(d, fn), "rb") as f:
                out[f"{name}/{fn}"] = f.read()
    return out


@pytest.mark.chaos(timeout_s=300)
def test_ingest_sigkill_resume_byte_identical(tmp_path):
    """A SIGKILLed ingest resumed in a fresh process commits byte-identical
    delta shards at BOTH cut points (mid-embed; after shard commit but
    before publish), and until the publish lands, resolve() never sees a
    torn delta."""
    fresh = [f"killdoc{i} theta{i} resilient stream" for i in range(12)]

    def make_base(root_dir):
        reg, texts, _ = _build(tmp_path / os.path.basename(root_dir),
                               root_dir, n_docs=40, files=2)
        return reg, texts

    # golden: one uninterrupted ingest on its own (identically-built) store
    gold_reg, _ = make_base(str(tmp_path / "store_gold"))
    log_dir = str(tmp_path / "logs")
    _log_docs(log_dir, fresh)
    gold = ingest_deltas(gold_reg, "docs", log_dir, HashEmbedder(dim=DIM),
                         str(tmp_path / "gold_work"))
    golden = _delta_shard_files(gold_reg.resolve("docs", "latest").path)
    assert golden

    script = tmp_path / "run_ingest.py"
    script.write_text(_INGEST_SCRIPT)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=os.path.dirname(
        os.path.dirname(os.path.abspath(synapseml_tpu.__file__))))
    for cut in ("embed", "publish"):
        reg, _ = make_base(str(tmp_path / f"store_{cut}"))
        work = str(tmp_path / f"work_{cut}")
        proc = subprocess.run(
            [sys.executable, str(script), str(tmp_path / f"store_{cut}"),
             log_dir, work, cut], env=env, timeout=240,
            capture_output=True)
        assert proc.returncode == -9, proc.stderr.decode()[-2000:]
        # the torn state is invisible: latest still resolves to v1 with no
        # delta shards in its roster
        resolved = reg.resolve("docs", "latest")
        assert resolved.version == "v1"
        assert all(s["kind"] == "base" for s in
                   resolved.manifest["extra"]["retrieval"]["shards"])
        if cut == "publish":  # deltas DID commit locally before the kill
            assert any(s.kind == "delta" for s in
                       list_shards(os.path.join(work, "index", "shards")))
        # resume: fresh "process" (plain embedder), same work_dir
        report = ingest_deltas(reg, "docs", log_dir, HashEmbedder(dim=DIM),
                               work)
        assert report["version"] == "v2" and report["docs"] == len(fresh)
        resumed = _delta_shard_files(reg.resolve("docs", "latest").path)
        assert resumed == golden  # byte-identical to the uninterrupted run


# ---------------------------------------------------------------------------
# E2E acceptance: fan-out serve + zero-downtime ingest + partial degrade
# ---------------------------------------------------------------------------

def _spawn_worker(store, reg_url, shards, log_path):
    code = ("import synapseml_tpu.retrieval.serve as s\n"
            f"s.retrieval_worker_main({store!r}, 'docs', {reg_url!r}, "
            f"shards={shards!r}, refresh_s=0.2)\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=os.path.dirname(
        os.path.dirname(os.path.abspath(synapseml_tpu.__file__))))
    logf = open(log_path, "wb")
    return subprocess.Popen([sys.executable, "-c", code], env=env,
                            stdout=logf, stderr=subprocess.STDOUT)


@pytest.mark.chaos(timeout_s=420)
def test_e2e_fanout_ingest_and_partial_degradation(tmp_path, fresh_cache):
    store = str(tmp_path / "store")
    registry, texts, emb = _build(tmp_path, store, n_docs=96, files=4)
    roster = [s["name"] for s in
              registry.resolve("docs", "latest")
              .manifest["extra"]["retrieval"]["shards"]]
    assert len(roster) >= 2
    half = (len(roster) + 1) // 2
    subsets = [roster[:half], roster[half:]]

    wreg = WorkerRegistry()
    front = RoutingFront(registry=wreg)
    reg_url = wreg.address + "/register"
    procs = [_spawn_worker(store, reg_url, sub,
                           str(tmp_path / f"worker{i}.log"))
             for i, sub in enumerate(subsets)]
    try:
        wreg.wait_for(2, timeout_s=180)
        E = emb.embed(texts)
        ids = np.arange(len(texts))
        Q = E[[3, 17, 29, 41, 53, 67, 80, 95]]
        url = front.address + "/retrieval/docs"

        # --- recall@10 == 1.0 against exact brute force -------------------
        status, reply, hdrs = _post(url, json.dumps(
            {"queries": Q.tolist(), "k": 10}).encode())
        assert status == 200 and not reply["missing"]
        assert "X-Retrieval-Partial" not in hdrs
        assert sorted(reply["shards"]) == sorted(roster)
        brute = _brute_topk_ids(E, ids, Q, 10)
        for got, want in zip(reply["matches"], brute):
            assert [m["id"] for m in got] == want  # recall@10 == 1.0, exact

        # --- logged docs -> delta shards, zero downtime -------------------
        fresh = [f"e2edoc{i} omega{i} live ingest" for i in range(8)]
        _log_docs(str(tmp_path / "logs"), fresh)
        report = ingest_deltas(registry, "docs", str(tmp_path / "logs"),
                               HashEmbedder(dim=DIM),
                               str(tmp_path / "ingest"))
        assert report["version"] == "v2"
        probe = emb.embed([fresh[2]])[0].tolist()
        want_id = len(texts) + 2
        deadline = time.monotonic() + 60
        served_fresh = False
        while time.monotonic() < deadline and not served_fresh:
            status, reply, hdrs = _post(url, json.dumps(
                {"query": probe, "k": 3}).encode())
            assert status == 200  # ZERO downtime across the version swap
            top = reply["matches"][0]
            if top and top[0]["id"] == want_id and not reply["missing"]:
                served_fresh = True
            else:
                time.sleep(0.2)
        assert served_fresh, "delta shards never became queryable"

        # --- SIGKILL one worker mid-storm: partials, never a 500 ----------
        victim_shards = set(subsets[0])
        procs[0].kill()
        procs[0].wait(timeout=30)
        statuses, partials = [], []
        for _ in range(30):
            status, reply, hdrs = _post(url, json.dumps(
                {"queries": Q[:2].tolist(), "k": 5}).encode())
            statuses.append(status)
            if "X-Retrieval-Partial" in hdrs:
                partials.append(set(hdrs["X-Retrieval-Partial"].split(",")))
                assert set(reply["missing"]) == partials[-1]
                # surviving answers still come back, explicitly scoped
                assert reply["matches"][0]
            time.sleep(0.05)
        assert set(statuses) == {200}  # the degradation contract: no 500s
        assert partials, "the kill never surfaced a partial result"
        assert partials[-1] <= victim_shards  # only the victim's exclusives

        # --- recovery: a replacement worker restores full coverage --------
        procs[0] = _spawn_worker(store, reg_url, subsets[0],
                                 str(tmp_path / "worker0b.log"))
        deadline = time.monotonic() + 120
        recovered = False
        while time.monotonic() < deadline and not recovered:
            status, reply, hdrs = _post(url, json.dumps(
                {"queries": Q[:2].tolist(), "k": 5}).encode())
            assert status == 200
            recovered = ("X-Retrieval-Partial" not in hdrs
                         and not reply["missing"])
            if not recovered:
                time.sleep(0.3)
        assert recovered, "coverage never recovered after worker restart"
        status, reply, _ = _post(url, json.dumps(
            {"queries": Q.tolist(), "k": 10}).encode())
        E2 = np.concatenate([E, HashEmbedder(dim=DIM).embed(fresh)], axis=0)
        brute2 = _brute_topk_ids(E2, np.arange(len(E2)), Q, 10)
        for got, want in zip(reply["matches"], brute2):
            assert [m["id"] for m in got] == want
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        front.close()
        wreg.close()


# ---------------------------------------------------------------------------
# front routing units (no subprocesses)
# ---------------------------------------------------------------------------

def test_fanout_rejects_bad_requests_and_unknown_index():
    wreg = WorkerRegistry()
    front = RoutingFront(registry=wreg)
    try:
        status, reply, _ = _post(front.address + "/retrieval/docs",
                                 b"not json")
        assert status == 400
        status, reply, _ = _post(front.address + "/retrieval/docs",
                                 json.dumps({"k": 3}).encode())
        assert status == 400
        status, reply, _ = _post(front.address + "/retrieval/docs",
                                 json.dumps({"query": [1.0, 2.0]}).encode())
        assert status == 503  # no advertising workers: explicit, not a hang
    finally:
        front.close()
        wreg.close()


def test_serving_body_contract_on_the_worker_stage(fresh_cache):
    """The /m/<index> worker path: parsed JSON bodies in, per-shard top-k
    reply dicts out (the unit the fan-out front composes)."""
    rs = np.random.default_rng(6)
    X = rs.integers(-3, 4, size=(20, DIM)).astype(np.float32)
    model = VectorIndexModel(shard_names=["s0"], dim=DIM, k=4,
                             inline_shards={"s0": {"vectors": X}})
    df = DataFrame.from_dict({"body": np.asarray(
        [{"query": X[4].tolist(), "k": 2},
         {"queries": [X[9].tolist()], "k": 1, "shards": ["s0"]},
         {"nonsense": True}], dtype=object)})
    replies = model.transform(df).collect_column("reply")
    assert replies[0]["matches"][0][0]["id"] == 4
    assert len(replies[0]["matches"][0]) == 2
    assert replies[1]["matches"][0][0]["id"] == 9
    assert replies[1]["shards"] == ["s0"]
    assert "error" in replies[2]
