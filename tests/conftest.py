"""Test harness: host-count-faked JAX CPU mesh (SURVEY.md §4 rebuild implication c).

Must set XLA flags BEFORE jax initializes a backend: 8 virtual CPU devices so
every sharding/collective path is exercised without TPU hardware — the analog
of the reference running NetworkManager on local[*] Spark.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the session env points at real TPU
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The container's sitecustomize imports jax at interpreter boot (axon PJRT
# registration), capturing JAX_PLATFORMS=axon before this file runs — override
# through the config API, which wins as long as no backend is live yet.
import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax (< 0.5) has no jax_num_cpu_devices option; the
    # xla_force_host_platform_device_count flag above already forces 8
    pass

import numpy as np
import pytest


@pytest.fixture(scope="session")
def mesh8():
    from synapseml_tpu.parallel import MeshConfig, create_mesh

    return create_mesh(MeshConfig(data=2, fsdp=2, tensor=2))


@pytest.fixture(scope="session")
def mesh_dp8():
    from synapseml_tpu.parallel import MeshConfig, create_mesh

    return create_mesh(MeshConfig(data=-1))


@pytest.fixture()
def rng():
    return np.random.default_rng(42)


def make_tabular_df(n=200, d=8, classes=2, seed=0, num_partitions=2):
    """Shared synthetic dataset builder (TestBase makeBasicDF analog)."""
    from synapseml_tpu.core import DataFrame

    rs = np.random.default_rng(seed)
    X = rs.normal(size=(n, d)).astype(np.float32)
    w = rs.normal(size=(d,)).astype(np.float32)
    logits = X @ w
    if classes == 0:
        y = (logits + 0.1 * rs.normal(size=n)).astype(np.float32)  # regression
    else:
        y = (np.digitize(logits, np.quantile(logits, np.linspace(0, 1, classes + 1)[1:-1]))
             ).astype(np.int32)
    return DataFrame.from_dict({"features": X, "label": y}, num_partitions=num_partitions)


@pytest.fixture()
def tabular_df():
    return make_tabular_df()


@pytest.fixture()
def regression_df():
    return make_tabular_df(classes=0)


def pytest_addoption(parser):
    parser.addoption("--runslow", action="store_true", default=False,
                     help="also run tests marked slow (the full lane; the "
                          "default lane skips them — reference analog: the "
                          "lightgbm split1-6 CI sharding)")
    parser.addoption("--check-slow-manifest", action="store_true", default=False,
                     help="with --runslow: measure per-test durations, "
                          "regenerate resources/slow_tests.txt, and FAIL the "
                          "session on drift (a newly-slow test missing from "
                          "the manifest, or a stale nodeid)")
    parser.addoption("--lane-budget", type=float, default=0.0, metavar="SECONDS",
                     help="fail the session if total test wall time exceeds "
                          "this budget (default-lane target: 480)")


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running (full-size model) "
                            "tests, skipped unless --runslow")
    config.addinivalue_line("markers", "chaos: injected-fault / worker-kill "
                            "tests; guarded by the per-test thread watchdog "
                            "(pyproject.toml registers this marker too)")
    config.addinivalue_line("markers", "registry: model registry + "
                            "deployment plane tests (tier-1; pyproject.toml "
                            "registers this marker too)")


# ---- chaos watchdog ------------------------------------------------------
# Injected-fault tests (tests/test_resilience.py) kill workers, blackhole
# connections and drive retry loops — a bug in any of those paths could hang
# the tier-1 lane forever. Every @pytest.mark.chaos test runs under a
# stdlib-only thread-based alarm: if the test body exceeds its limit
# (default CHAOS_TIMEOUT_S; override with @pytest.mark.chaos(timeout_s=N)),
# a timer thread interrupts the main thread and the hookwrapper below
# converts that into a bounded test FAILURE instead of a session abort.

CHAOS_TIMEOUT_S = 120.0


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    marker = item.get_closest_marker("chaos")
    if marker is None:
        yield
        return
    import signal
    import threading
    import time as _time

    limit = float(marker.kwargs.get("timeout_s", CHAOS_TIMEOUT_S))
    fired = threading.Event()
    done = threading.Event()
    main_ident = threading.main_thread().ident

    def alarm():
        if done.is_set():  # test body already finished: don't interrupt
            return
        fired.set()
        try:
            # a real OS signal interrupts blocking syscalls (sleep, recv) —
            # _thread.interrupt_main() would only set a pending flag
            signal.pthread_kill(main_ident, signal.SIGINT)
        except (ValueError, OSError):
            import _thread
            _thread.interrupt_main()

    timer = threading.Timer(limit, alarm)
    timer.daemon = True
    timer.start()
    outcome = yield
    done.set()
    timer.cancel()
    if fired.is_set() and outcome.excinfo is not None:
        # replace the KeyboardInterrupt (it would abort the whole session)
        # with a bounded failure of just this test
        outcome.force_exception(
            pytest.fail.Exception(f"chaos watchdog: test exceeded {limit:.0f}s",
                                  pytrace=False))
    elif fired.is_set():
        # the alarm raced the end of the test body: absorb the SIGINT it
        # delivered so it can't abort the session in teardown / the next test
        try:
            _time.sleep(0.1)
        except KeyboardInterrupt:
            pass


def _slow_manifest() -> set:
    """Central slow-test list (the reference shards its CI into split1-6
    files, ``lightgbm/src/test/.../split*``; here one manifest of measured
    >=8s node ids keeps the default lane fast without touching test files).
    Regenerate from a --runslow run: pytest --durations=60, take >=8s."""
    path = os.path.join(os.path.dirname(__file__), "resources", "slow_tests.txt")
    try:
        with open(path) as f:
            return {line.strip() for line in f if line.strip()}
    except OSError:
        return set()


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    manifest = _slow_manifest()
    skip = pytest.mark.skip(reason="slow: run with --runslow (full lane)")
    for item in items:
        if "slow" in item.keywords or item.nodeid in manifest:
            item.add_marker(skip)


# ---- slow-manifest drift check + lane budget (VERDICT r3 next-#5) --------
# The manifest is regenerated from MEASURED durations, not hand-maintained:
#   pytest tests/ --runslow --check-slow-manifest -q
# fails (exit 1) and rewrites resources/slow_tests.txt whenever a test
# crossed the slow threshold without being listed or a listed nodeid no
# longer exists — so the default lane cannot drift upward silently.

SLOW_THRESHOLD_S = 8.0
_durations: dict = {}
_session_t0: list = []


def pytest_runtest_logreport(report):
    _durations[report.nodeid] = _durations.get(report.nodeid, 0.0) + report.duration


def pytest_sessionstart(session):
    import time

    _session_t0.append(time.monotonic())


def pytest_sessionfinish(session, exitstatus):
    import time

    config = session.config
    notes = []
    full_run = False
    if config.getoption("--check-slow-manifest"):
        # only a FULL unfiltered --runslow run may regenerate the manifest:
        # a partial run (test file args, -k, -m) would see un-run tests as
        # "stale" and gut the manifest
        tests_dir = os.path.dirname(os.path.abspath(__file__))
        full_run = (config.getoption("--runslow")
                    and not config.getoption("keyword")
                    and not config.getoption("markexpr")
                    and all(os.path.isdir(a.split("::")[0])
                            for a in (config.args or [tests_dir])))
        if not full_run:
            notes.append("--check-slow-manifest ignored: not a full "
                         "unfiltered --runslow run over the tests directory")
    if full_run:
        path = os.path.join(os.path.dirname(__file__), "resources",
                            "slow_tests.txt")
        measured_slow = {n for n, d in _durations.items()
                         if d >= SLOW_THRESHOLD_S}
        collected = set(_durations)
        old = _slow_manifest()
        stale = old - collected          # renamed/removed tests
        missing = measured_slow - old    # newly-slow, unlisted
        # hysteresis: keep listed tests that still take >= half the
        # threshold, so borderline tests don't flap in and out
        keep = {n for n in (old & collected)
                if _durations.get(n, 0.0) >= SLOW_THRESHOLD_S / 2}
        new = sorted(measured_slow | keep)
        if missing or stale:
            with open(path, "w") as f:
                f.write("\n".join(new) + "\n")
            notes.append(
                f"slow-manifest DRIFT: {len(missing)} newly-slow unlisted "
                f"{sorted(missing)}, {len(stale)} stale {sorted(stale)}; "
                f"manifest regenerated — commit it")
            session.exitstatus = 1
    budget = config.getoption("--lane-budget")
    if budget and _session_t0:
        elapsed = time.monotonic() - _session_t0[0]
        if elapsed > budget:
            notes.append(f"lane budget EXCEEDED: {elapsed:.0f}s > {budget:.0f}s "
                         "— move the offenders (pytest --durations=20) into "
                         "resources/slow_tests.txt")
            session.exitstatus = 1
    for n in notes:
        print(f"\n[conftest] {n}")
