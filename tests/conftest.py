"""Test harness: host-count-faked JAX CPU mesh (SURVEY.md §4 rebuild implication c).

Must set XLA flags BEFORE jax initializes a backend: 8 virtual CPU devices so
every sharding/collective path is exercised without TPU hardware — the analog
of the reference running NetworkManager on local[*] Spark.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the session env points at real TPU
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The container's sitecustomize imports jax at interpreter boot (axon PJRT
# registration), capturing JAX_PLATFORMS=axon before this file runs — override
# through the config API, which wins as long as no backend is live yet.
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import numpy as np
import pytest


@pytest.fixture(scope="session")
def mesh8():
    from synapseml_tpu.parallel import MeshConfig, create_mesh

    return create_mesh(MeshConfig(data=2, fsdp=2, tensor=2))


@pytest.fixture(scope="session")
def mesh_dp8():
    from synapseml_tpu.parallel import MeshConfig, create_mesh

    return create_mesh(MeshConfig(data=-1))


@pytest.fixture()
def rng():
    return np.random.default_rng(42)


def make_tabular_df(n=200, d=8, classes=2, seed=0, num_partitions=2):
    """Shared synthetic dataset builder (TestBase makeBasicDF analog)."""
    from synapseml_tpu.core import DataFrame

    rs = np.random.default_rng(seed)
    X = rs.normal(size=(n, d)).astype(np.float32)
    w = rs.normal(size=(d,)).astype(np.float32)
    logits = X @ w
    if classes == 0:
        y = (logits + 0.1 * rs.normal(size=n)).astype(np.float32)  # regression
    else:
        y = (np.digitize(logits, np.quantile(logits, np.linspace(0, 1, classes + 1)[1:-1]))
             ).astype(np.int32)
    return DataFrame.from_dict({"features": X, "label": y}, num_partitions=num_partitions)


@pytest.fixture()
def tabular_df():
    return make_tabular_df()


@pytest.fixture()
def regression_df():
    return make_tabular_df(classes=0)


def pytest_addoption(parser):
    parser.addoption("--runslow", action="store_true", default=False,
                     help="also run tests marked slow (the full lane; the "
                          "default lane skips them — reference analog: the "
                          "lightgbm split1-6 CI sharding)")


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running (full-size model) "
                            "tests, skipped unless --runslow")


def _slow_manifest() -> set:
    """Central slow-test list (the reference shards its CI into split1-6
    files, ``lightgbm/src/test/.../split*``; here one manifest of measured
    >=8s node ids keeps the default lane fast without touching test files).
    Regenerate from a --runslow run: pytest --durations=60, take >=8s."""
    path = os.path.join(os.path.dirname(__file__), "resources", "slow_tests.txt")
    try:
        with open(path) as f:
            return {line.strip() for line in f if line.strip()}
    except OSError:
        return set()


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    manifest = _slow_manifest()
    skip = pytest.mark.skip(reason="slow: run with --runslow (full lane)")
    for item in items:
        if "slow" in item.keywords or item.nodeid in manifest:
            item.add_marker(skip)
