import numpy as np
import pytest

from synapseml_tpu.core import DataFrame


def test_from_dict_and_schema():
    df = DataFrame.from_dict({"a": [1, 2, 3], "b": ["x", "y", "z"]})
    assert df.count() == 3
    assert df.columns == ["a", "b"]
    assert df.schema["a"][0].startswith("int")
    assert df.schema["b"][0] == "object"


def test_repartition_roundtrip():
    df = DataFrame.from_dict({"a": np.arange(10)}, num_partitions=3)
    assert df.num_partitions == 3
    assert df.count() == 10
    np.testing.assert_array_equal(df.collect_column("a"), np.arange(10))
    df2 = df.repartition(4).coalesce(2)
    assert df2.num_partitions == 2
    np.testing.assert_array_equal(df2.collect_column("a"), np.arange(10))


def test_select_drop_rename():
    df = DataFrame.from_dict({"a": [1], "b": [2], "c": [3]})
    assert df.select("a", "c").columns == ["a", "c"]
    assert df.drop("b").columns == ["a", "c"]
    assert df.with_column_renamed("a", "z").columns == ["z", "b", "c"]
    with pytest.raises(KeyError):
        df.select("nope")


def test_with_column_fn_and_array():
    df = DataFrame.from_dict({"a": np.arange(6, dtype=np.float32)}, num_partitions=2)
    df2 = df.with_column("double", lambda p: p["a"] * 2)
    np.testing.assert_allclose(df2.collect_column("double"), np.arange(6) * 2)
    df3 = df.with_column("idx", np.arange(6))
    np.testing.assert_array_equal(df3.collect_column("idx"), np.arange(6))


def test_filter_limit_sort():
    df = DataFrame.from_dict({"a": np.array([5, 3, 1, 4, 2])}, num_partitions=2)
    assert df.filter(lambda p: p["a"] > 2).count() == 3
    assert df.limit(2).count() == 2
    np.testing.assert_array_equal(df.sort("a").collect_column("a"), [1, 2, 3, 4, 5])


def test_map_partitions_and_rows():
    df = DataFrame.from_dict({"a": np.arange(4)}, num_partitions=2)
    df2 = df.map_partitions(lambda p: {"a": p["a"], "sq": p["a"] ** 2})
    np.testing.assert_array_equal(df2.collect_column("sq"), [0, 1, 4, 9])
    df3 = df.map_rows(lambda r: {"s": str(r["a"])})
    assert list(df3.collect_column("s")) == ["0", "1", "2", "3"]


def test_random_split_union():
    df = DataFrame.from_dict({"a": np.arange(100)})
    tr, te = df.random_split([0.8, 0.2], seed=7)
    assert tr.count() + te.count() == 100
    assert 70 <= tr.count() <= 90
    merged = tr.union(te)
    assert merged.count() == 100
    assert set(merged.collect_column("a")) == set(range(100))


def test_tensor_columns():
    X = np.random.default_rng(0).normal(size=(10, 4)).astype(np.float32)
    df = DataFrame.from_dict({"features": X}, num_partitions=3)
    assert df.schema["features"] == ("float32", (4,))
    np.testing.assert_allclose(df.collect_column("features"), X)


def test_to_pandas_roundtrip():
    df = DataFrame.from_dict({"a": [1, 2], "b": ["x", "y"]})
    pdf = df.to_pandas()
    df2 = DataFrame.from_pandas(pdf)
    assert list(df2.collect_column("b")) == ["x", "y"]


def test_group_by_agg():
    df = DataFrame.from_dict(
        {"k": np.asarray(["a", "b", "a", "b", "a"], dtype=object),
         "v": np.asarray([1.0, 2.0, 3.0, 4.0, 5.0]),
         "w": np.asarray([10, 20, 30, 40, 50])}, num_partitions=2)
    out = df.group_by("k").agg({"v": "sum", "w": "max"})
    assert sorted(out.columns) == ["k", "v_sum", "w_max"]
    rows = {r["k"]: r for r in out.collect_rows()}
    assert rows["a"]["v_sum"] == 9.0 and rows["a"]["w_max"] == 50
    assert rows["b"]["v_sum"] == 6.0 and rows["b"]["w_max"] == 40
    counts = {r["k"]: r["count"] for r in df.group_by("k").count().collect_rows()}
    assert counts == {"a": 3, "b": 2}


def test_group_by_validation():
    df = DataFrame.from_dict({"k": np.arange(3), "v": np.arange(3)})
    with pytest.raises(KeyError):
        df.group_by("nope")
    with pytest.raises(ValueError, match="unsupported"):
        df.group_by("k").agg({"v": "median_of_medians"})


def test_join_inner_and_left():
    left = DataFrame.from_dict(
        {"id": np.asarray([1, 2, 3]), "x": np.asarray([10.0, 20.0, 30.0])},
        num_partitions=2)
    right = DataFrame.from_dict(
        {"id": np.asarray([2, 3, 4]), "y": np.asarray(["b", "c", "d"],
                                                      dtype=object)})
    inner = left.join(right, on="id")
    assert inner.count() == 2
    assert sorted(inner.collect_column("id").tolist()) == [2, 3]
    outer = left.join(right, on="id", how="left")
    assert outer.count() == 3  # id=1 kept with missing y
    with pytest.raises(KeyError):
        left.join(right, on="x")
    with pytest.raises(ValueError, match="how"):
        left.join(right, on="id", how="cross")
