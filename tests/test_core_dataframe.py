import numpy as np
import pytest

from synapseml_tpu.core import DataFrame


def test_from_dict_and_schema():
    df = DataFrame.from_dict({"a": [1, 2, 3], "b": ["x", "y", "z"]})
    assert df.count() == 3
    assert df.columns == ["a", "b"]
    assert df.schema["a"][0].startswith("int")
    assert df.schema["b"][0] == "object"


def test_repartition_roundtrip():
    df = DataFrame.from_dict({"a": np.arange(10)}, num_partitions=3)
    assert df.num_partitions == 3
    assert df.count() == 10
    np.testing.assert_array_equal(df.collect_column("a"), np.arange(10))
    df2 = df.repartition(4).coalesce(2)
    assert df2.num_partitions == 2
    np.testing.assert_array_equal(df2.collect_column("a"), np.arange(10))


def test_select_drop_rename():
    df = DataFrame.from_dict({"a": [1], "b": [2], "c": [3]})
    assert df.select("a", "c").columns == ["a", "c"]
    assert df.drop("b").columns == ["a", "c"]
    assert df.with_column_renamed("a", "z").columns == ["z", "b", "c"]
    with pytest.raises(KeyError):
        df.select("nope")


def test_with_column_fn_and_array():
    df = DataFrame.from_dict({"a": np.arange(6, dtype=np.float32)}, num_partitions=2)
    df2 = df.with_column("double", lambda p: p["a"] * 2)
    np.testing.assert_allclose(df2.collect_column("double"), np.arange(6) * 2)
    df3 = df.with_column("idx", np.arange(6))
    np.testing.assert_array_equal(df3.collect_column("idx"), np.arange(6))


def test_filter_limit_sort():
    df = DataFrame.from_dict({"a": np.array([5, 3, 1, 4, 2])}, num_partitions=2)
    assert df.filter(lambda p: p["a"] > 2).count() == 3
    assert df.limit(2).count() == 2
    np.testing.assert_array_equal(df.sort("a").collect_column("a"), [1, 2, 3, 4, 5])


def test_map_partitions_and_rows():
    df = DataFrame.from_dict({"a": np.arange(4)}, num_partitions=2)
    df2 = df.map_partitions(lambda p: {"a": p["a"], "sq": p["a"] ** 2})
    np.testing.assert_array_equal(df2.collect_column("sq"), [0, 1, 4, 9])
    df3 = df.map_rows(lambda r: {"s": str(r["a"])})
    assert list(df3.collect_column("s")) == ["0", "1", "2", "3"]


def test_random_split_union():
    df = DataFrame.from_dict({"a": np.arange(100)})
    tr, te = df.random_split([0.8, 0.2], seed=7)
    assert tr.count() + te.count() == 100
    assert 70 <= tr.count() <= 90
    merged = tr.union(te)
    assert merged.count() == 100
    assert set(merged.collect_column("a")) == set(range(100))


def test_tensor_columns():
    X = np.random.default_rng(0).normal(size=(10, 4)).astype(np.float32)
    df = DataFrame.from_dict({"features": X}, num_partitions=3)
    assert df.schema["features"] == ("float32", (4,))
    np.testing.assert_allclose(df.collect_column("features"), X)


def test_to_pandas_roundtrip():
    df = DataFrame.from_dict({"a": [1, 2], "b": ["x", "y"]})
    pdf = df.to_pandas()
    df2 = DataFrame.from_pandas(pdf)
    assert list(df2.collect_column("b")) == ["x", "y"]
