"""isolation forest + data balance measures."""

import numpy as np
import pytest

from synapseml_tpu.core import DataFrame
from synapseml_tpu.exploratory import (
    AggregateBalanceMeasure,
    DistributionBalanceMeasure,
    FeatureBalanceMeasure,
)
from synapseml_tpu.isolationforest import IsolationForest, IsolationForestModel


def make_anomaly_df(n=300, n_outliers=10, d=4, seed=0):
    rs = np.random.default_rng(seed)
    inliers = rs.normal(0, 1, size=(n - n_outliers, d))
    outliers = rs.normal(0, 1, size=(n_outliers, d)) + 8.0
    X = np.vstack([inliers, outliers]).astype(np.float32)
    is_outlier = np.zeros(n, bool)
    is_outlier[-n_outliers:] = True
    return DataFrame.from_dict({"features": X, "truth": is_outlier}), is_outlier


def test_isolation_forest_separates_outliers():
    df, truth = make_anomaly_df()
    model = IsolationForest(num_estimators=50, max_samples=64.0,
                            contamination=10 / 300).fit(df)
    out = model.transform(df)
    scores = out.collect_column("outlierScore")
    assert scores[truth].mean() > scores[~truth].mean() + 0.1
    preds = out.collect_column("predictedLabel").astype(bool)
    # most true outliers flagged
    assert preds[truth].mean() > 0.8
    assert preds[~truth].mean() < 0.1


def test_isolation_forest_save_load(tmp_path):
    df, _ = make_anomaly_df(n=100, n_outliers=5)
    model = IsolationForest(num_estimators=20, contamination=0.05).fit(df)
    before = model.transform(df).collect_column("outlierScore")
    model.save(str(tmp_path / "if"))
    after = IsolationForestModel.load(str(tmp_path / "if")).transform(df) \
        .collect_column("outlierScore")
    np.testing.assert_allclose(before, after)


def test_feature_balance_measure():
    rs = np.random.default_rng(0)
    n = 2000
    gender = rs.choice(["m", "f"], size=n)
    # biased label: m positive 80%, f positive 20%
    y = np.where(gender == "m", rs.random(n) < 0.8, rs.random(n) < 0.2).astype(int)
    df = DataFrame.from_dict({"gender": gender, "label": y})
    out = FeatureBalanceMeasure(sensitive_cols=["gender"]).transform(df)
    row = out.collect_rows()[0]
    # classes sorted: ClassA=f, ClassB=m -> dp = p(y|f) - p(y|m) ~ -0.6
    assert row["ClassA"] == "f" and row["ClassB"] == "m"
    assert row["dp"] == pytest.approx(-0.6, abs=0.07)
    # balanced feature -> dp ~ 0
    fair = DataFrame.from_dict({"gender": gender,
                                "label": (rs.random(n) < 0.5).astype(int)})
    row2 = FeatureBalanceMeasure(sensitive_cols=["gender"]).transform(fair).collect_rows()[0]
    assert abs(row2["dp"]) < 0.07


def test_distribution_balance_measure():
    skewed = DataFrame.from_dict({"eth": np.asarray(["a"] * 90 + ["b"] * 10)})
    uniform = DataFrame.from_dict({"eth": np.asarray(["a", "b"] * 50)})
    m_skew = DistributionBalanceMeasure(sensitive_cols=["eth"]).transform(skewed).collect_rows()[0]
    m_unif = DistributionBalanceMeasure(sensitive_cols=["eth"]).transform(uniform).collect_rows()[0]
    for key in ("kl_divergence", "js_dist", "total_variation_dist", "chi_sq_stat"):
        assert m_skew[key] > m_unif[key]
        assert m_unif[key] == pytest.approx(0.0, abs=1e-9)
    assert m_skew["total_variation_dist"] == pytest.approx(0.4, abs=1e-9)


def test_aggregate_balance_measure():
    perfectly_balanced = DataFrame.from_dict({"a": np.asarray(["x", "y"] * 50)})
    out = AggregateBalanceMeasure(sensitive_cols=["a"]).transform(perfectly_balanced)
    row = out.collect_rows()[0]
    assert row["atkinson_index"] == pytest.approx(0.0, abs=1e-9)
    assert row["theil_t_index"] == pytest.approx(0.0, abs=1e-9)
    skew = DataFrame.from_dict({"a": np.asarray(["x"] * 99 + ["y"])})
    row2 = AggregateBalanceMeasure(sensitive_cols=["a"]).transform(skew).collect_rows()[0]
    assert row2["atkinson_index"] > 0.3
    assert row2["theil_t_index"] > 0.3
