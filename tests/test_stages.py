"""Stage transformer tests (reference suites: MiniBatchTransformerSuite etc.)."""

import numpy as np
import pytest

from synapseml_tpu.core import DataFrame, Pipeline
from synapseml_tpu.stages import (
    Cacher,
    ClassBalancer,
    DropColumns,
    DynamicMiniBatchTransformer,
    EnsembleByKey,
    Explode,
    FixedMiniBatchTransformer,
    FlattenBatch,
    Lambda,
    MultiColumnAdapter,
    PartitionConsolidator,
    RenameColumn,
    Repartition,
    SelectColumns,
    StratifiedRepartition,
    SummarizeData,
    TextPreprocessor,
    TimeIntervalMiniBatchTransformer,
    Timer,
    UDFTransformer,
    UnicodeNormalize,
)


@pytest.fixture()
def df():
    return DataFrame.from_dict(
        {"a": np.arange(10, dtype=np.float32), "b": np.arange(10, 20, dtype=np.int32)},
        num_partitions=2,
    )


def test_fixed_minibatch_roundtrip(df):
    batched = FixedMiniBatchTransformer(batch_size=3).transform(df)
    # 2 partitions of 5 rows -> [3,2] batches each
    assert batched.count() == 4
    sizes = [len(x) for x in batched.collect_column("a")]
    assert sizes == [3, 2, 3, 2]
    flat = FlattenBatch().transform(batched)
    np.testing.assert_array_equal(flat.collect_column("a"), df.collect_column("a"))
    np.testing.assert_array_equal(flat.collect_column("b"), df.collect_column("b"))


def test_dynamic_and_interval_minibatch(df):
    d = DynamicMiniBatchTransformer().transform(df)
    assert d.count() == 2  # one batch per partition
    capped = DynamicMiniBatchTransformer(max_batch_size=4).transform(df)
    assert [len(x) for x in capped.collect_column("a")] == [4, 1, 4, 1]
    t = TimeIntervalMiniBatchTransformer(max_batch_size=5).transform(df)
    assert t.count() == 2


def test_interval_batch_stream():
    t = TimeIntervalMiniBatchTransformer(millis_to_wait=10_000, max_batch_size=2)
    rows = [{"x": i} for i in range(5)]
    out = list(t.batch_stream(iter(rows)))
    assert [len(b["x"]) for b in out] == [2, 2, 1]


def test_lambda_and_udf(df):
    lam = Lambda(lambda d: d.with_column("c", lambda p: p["a"] * 2))
    out = lam.transform(df)
    np.testing.assert_array_equal(out.collect_column("c"), df.collect_column("a") * 2)

    udf = UDFTransformer(input_col="a", output_col="sq", udf=lambda a: a**2)
    np.testing.assert_array_equal(udf.transform(df).collect_column("sq"),
                                  df.collect_column("a") ** 2)
    udf2 = UDFTransformer(input_cols=["a", "b"], output_col="s", vectorized=False,
                          udf=lambda a, b: float(a + b))
    np.testing.assert_allclose(udf2.transform(df).collect_column("s"),
                               df.collect_column("a") + df.collect_column("b"))


def test_column_stages(df):
    assert SelectColumns(cols=["a"]).transform(df).columns == ["a"]
    assert DropColumns(cols=["a"]).transform(df).columns == ["b"]
    assert "z" in RenameColumn(input_col="a", output_col="z").transform(df).columns
    assert Repartition(n=5).transform(df).num_partitions == 5
    assert Cacher().transform(df) is df
    assert PartitionConsolidator(num_hosts=1).transform(df).num_partitions == 1


def test_explode():
    df = DataFrame.from_dict({"k": np.array([1, 2]),
                              "v": [[1, 2, 3], [4]]})
    out = Explode(input_col="v", output_col="e").transform(df)
    np.testing.assert_array_equal(out.collect_column("k"), [1, 1, 1, 2])
    np.testing.assert_array_equal(out.collect_column("e"), [1, 2, 3, 4])


def test_ensemble_by_key():
    df = DataFrame.from_dict({"k": np.array([0, 0, 1, 1]),
                              "score": np.array([1.0, 3.0, 5.0, 7.0])})
    out = EnsembleByKey(keys=["k"], cols=["score"]).transform(df)
    got = dict(zip(out.collect_column("k"), out.collect_column("mean(score)")))
    assert got[0] == 2.0 and got[1] == 6.0
    broad = EnsembleByKey(keys=["k"], cols=["score"], collapse_group=False).transform(df)
    assert broad.count() == 4
    np.testing.assert_allclose(broad.collect_column("mean(score)"), [2, 2, 6, 6])


def test_stratified_repartition():
    labels = np.array([0] * 8 + [1] * 2)
    df = DataFrame.from_dict({"label": labels, "x": np.arange(10)}, num_partitions=2)
    out = StratifiedRepartition(label_col="label").transform(df)
    for p in out.partitions:
        assert set(np.unique(p["label"])) == {0, 1}
    eq = StratifiedRepartition(label_col="label", mode="equal").transform(df)
    _, counts = np.unique(eq.collect_column("label"), return_counts=True)
    assert counts[0] == counts[1] == 8


def test_timer(df, capsys):
    t = Timer(stage=ClassBalancer(input_col="b"))
    model = t.fit(df)
    out = model.transform(df)
    assert "weight" in out.columns
    assert "[Timer]" in capsys.readouterr().out


def test_class_balancer():
    df = DataFrame.from_dict({"label": np.array([0, 0, 0, 1])})
    model = ClassBalancer(input_col="label").fit(df)
    np.testing.assert_allclose(model.transform(df).collect_column("weight"),
                               [1.0, 1.0, 1.0, 3.0])


def test_text_stages():
    df = DataFrame.from_dict({"text": ["Hello WORLD", "café Bad"]})
    out = TextPreprocessor(map={"Bad": "good"}, input_col="text",
                           output_col="clean").transform(df)
    assert list(out.collect_column("clean")) == ["hello world", "café good"]
    norm = UnicodeNormalize(form="NFC", input_col="text", output_col="n").transform(df)
    assert list(norm.collect_column("n"))[1].startswith("café")


def test_multi_column_adapter(df):
    from synapseml_tpu.stages.basic import UDFTransformer

    base = UDFTransformer(udf=lambda a: a * 10)
    adapter = MultiColumnAdapter(base_stage=base, input_cols=["a", "b"],
                                 output_cols=["a10", "b10"])
    out = adapter.fit(df).transform(df)
    np.testing.assert_allclose(out.collect_column("a10"), df.collect_column("a") * 10)
    np.testing.assert_allclose(out.collect_column("b10"), df.collect_column("b") * 10)


def test_summarize_data():
    df = DataFrame.from_dict({"x": np.array([1.0, 2.0, 3.0, np.nan]),
                              "s": ["a", "b", "b", "c"]})
    out = SummarizeData().transform(df).to_pandas().set_index("feature")
    assert out.loc["x", "count"] == 4
    assert out.loc["x", "missing_value_count"] == 1
    np.testing.assert_allclose(out.loc["x", "mean"], 2.0)
    np.testing.assert_allclose(out.loc["x", "p50"], 2.0)
    assert out.loc["s", "unique_value_count"] == 3
    counts_only = SummarizeData(basic=False, sample=False, percentiles=False).transform(df)
    assert set(counts_only.columns) == {"feature", "count", "unique_value_count",
                                        "missing_value_count"}


def test_stage_serialization_roundtrip(df, tmp_path):
    stage = FixedMiniBatchTransformer(batch_size=4)
    stage.save(str(tmp_path / "fmb"))
    from synapseml_tpu.core import load_stage

    loaded = load_stage(str(tmp_path / "fmb"))
    assert loaded.get("batch_size") == 4
    pipe = Pipeline(stages=[SelectColumns(cols=["a"]),
                            FixedMiniBatchTransformer(batch_size=2), FlattenBatch()])
    model = pipe.fit(df)
    model.save(str(tmp_path / "pipe"))
    from synapseml_tpu.core import PipelineModel

    reloaded = PipelineModel.load(str(tmp_path / "pipe"))
    np.testing.assert_array_equal(reloaded.transform(df).collect_column("a"),
                                  df.collect_column("a"))
