"""Modern-vision/mixed op coverage through a REAL torch export: GroupNorm
(lowered to InstanceNormalization), Hardswish, F.interpolate in both nearest
and bilinear modes (Resize with asymmetric / pytorch_half_pixel coordinate
transforms), sinusoidal Sin/Cos features, and a TopK head — all converted
and parity-checked against torch. Reference runs these through ONNX
Runtime's full opset (``onnx/ONNXModel.scala:211``)."""

import io
import math
import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).parent))

torch = pytest.importorskip("torch")
from torch import nn  # noqa: E402
import torch.nn.functional as F  # noqa: E402

from _torch_resnet import _install_onnx_shim  # noqa: E402


class MixedNet(nn.Module):
    def __init__(self):
        super().__init__()
        self.conv = nn.Conv2d(3, 8, 3, padding=1)
        self.gn = nn.GroupNorm(2, 8)
        self.act = nn.Hardswish()
        self.head = nn.Linear(8, 16)

    def forward(self, x):
        h = self.act(self.gn(self.conv(x)))
        h = F.interpolate(h, scale_factor=2.0, mode="nearest")
        h = F.interpolate(h, size=(8, 8), mode="bilinear",
                          align_corners=False)
        pooled = h.mean(dim=(2, 3))
        freq = torch.arange(4, device=x.device, dtype=torch.float32)
        enc = torch.cat([torch.sin(pooled[:, :4] * freq),
                         torch.cos(pooled[:, :4] * freq)], dim=-1)
        logits = self.head(enc)
        vals, idx = torch.topk(logits, k=3, dim=-1)
        return vals, idx


@pytest.fixture(scope="module")
def exported():
    _install_onnx_shim()
    torch.manual_seed(0)
    model = MixedNet().eval()
    buf = io.BytesIO()
    torch.onnx.export(model, (torch.randn(2, 3, 4, 4),), buf, dynamo=False,
                      input_names=["x"], output_names=["vals", "idx"],
                      dynamic_axes={"x": {0: "N"}})
    return model, buf.getvalue()


def test_mixed_export_ops_all_supported(exported):
    from synapseml_tpu.onnx.convert import OP_REGISTRY
    from synapseml_tpu.onnx.proto import ModelProto

    _, data = exported
    ops = {n.op_type for n in ModelProto.parse(data).graph.node}
    for must in ("Resize", "InstanceNormalization", "HardSwish", "Sin",
                 "Cos", "TopK"):
        assert must in ops, f"export no longer exercises {must}"
    missing = sorted(o for o in ops if o not in OP_REGISTRY)
    assert not missing, f"unsupported mixed ops: {missing}"


def test_mixed_outputs_match_torch(exported):
    import jax

    from synapseml_tpu.onnx import convert_graph

    model, data = exported
    conv = convert_graph(data)
    fn = jax.jit(lambda t: conv(x=t))

    for B in (2, 5):
        gen = torch.Generator().manual_seed(B)
        x = torch.randn(B, 3, 4, 4, generator=gen)
        with torch.no_grad():
            want_vals, want_idx = model(x)
        got = fn(x.numpy())
        np.testing.assert_allclose(np.asarray(got["vals"]),
                                   want_vals.numpy(), rtol=2e-4, atol=2e-5)
        np.testing.assert_array_equal(np.asarray(got["idx"]),
                                      want_idx.numpy())


def test_resize_modes_match_torch_interpolate():
    """Direct Resize-op checks against torch.nn.functional.interpolate for
    each mode/coordinate-transform pair torch exports."""
    from synapseml_tpu.onnx.convert import OP_REGISTRY

    x = np.arange(2 * 3 * 5 * 7, dtype=np.float32).reshape(2, 3, 5, 7)
    t = torch.from_numpy(x)

    # nearest + asymmetric + floor (torch nearest export)
    got = np.asarray(OP_REGISTRY["Resize"](
        [x, None, np.array([1.0, 1.0, 2.0, 2.0], np.float32), None],
        {"mode": "nearest", "coordinate_transformation_mode": "asymmetric",
         "nearest_mode": "floor"}))
    want = F.interpolate(t, scale_factor=2.0, mode="nearest").numpy()
    np.testing.assert_array_equal(got, want)

    # linear + pytorch_half_pixel (align_corners=False export)
    got = np.asarray(OP_REGISTRY["Resize"](
        [x, None, None, np.array([2, 3, 9, 13], np.int64)],
        {"mode": "linear",
         "coordinate_transformation_mode": "pytorch_half_pixel"}))
    want = F.interpolate(t, size=(9, 13), mode="bilinear",
                         align_corners=False).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    # linear + align_corners (align_corners=True export)
    got = np.asarray(OP_REGISTRY["Resize"](
        [x, None, None, np.array([2, 3, 10, 4], np.int64)],
        {"mode": "linear",
         "coordinate_transformation_mode": "align_corners"}))
    want = F.interpolate(t, size=(10, 4), mode="bilinear",
                         align_corners=True).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_conv_transpose_parity_with_torch():
    """ConvTranspose (the UNet upsampling op) vs torch, incl. the classic
    stride-2/pad-1/output_padding-1 doubling config, groups, and dilation."""
    import torch

    rs = np.random.default_rng(11)
    from synapseml_tpu.onnx.convert import OP_REGISTRY

    configs = [
        dict(cin=4, cout=6, k=3, stride=2, pad=1, out_pad=1, groups=1, dil=1),
        dict(cin=4, cout=4, k=2, stride=2, pad=0, out_pad=0, groups=1, dil=1),
        dict(cin=4, cout=8, k=3, stride=1, pad=1, out_pad=0, groups=2, dil=1),
        dict(cin=3, cout=3, k=3, stride=2, pad=2, out_pad=1, groups=1, dil=2),
    ]
    for c in configs:
        x = rs.normal(size=(2, c["cin"], 7, 7)).astype(np.float32)
        m = torch.nn.ConvTranspose2d(
            c["cin"], c["cout"], c["k"], stride=c["stride"], padding=c["pad"],
            output_padding=c["out_pad"], groups=c["groups"],
            dilation=c["dil"])
        with torch.no_grad():
            want = m(torch.tensor(x)).numpy()
        got = np.asarray(OP_REGISTRY["ConvTranspose"](
            [x, m.weight.detach().numpy(), m.bias.detach().numpy()],
            {"strides": [c["stride"]] * 2, "pads": [c["pad"]] * 4,
             "output_padding": [c["out_pad"]] * 2, "group": c["groups"],
             "dilations": [c["dil"]] * 2}))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4,
                                   err_msg=str(c))


def test_unet_style_export_parity(tmp_path):
    """A torch-exported encoder-decoder (conv down, ConvTranspose up, skip
    concat) through the full ONNX->JAX conversion."""
    import torch

    class MiniUNet(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.down = torch.nn.Conv2d(3, 8, 3, stride=2, padding=1)
            self.mid = torch.nn.Conv2d(8, 8, 3, padding=1)
            self.up = torch.nn.ConvTranspose2d(8, 4, 3, stride=2, padding=1,
                                               output_padding=1)
            self.out = torch.nn.Conv2d(7, 2, 1)  # 4 up + 3 skip channels

        def forward(self, x):
            d = torch.relu(self.down(x))
            m = torch.relu(self.mid(d))
            u = torch.relu(self.up(m))
            return self.out(torch.cat([u, x], dim=1))

    torch.manual_seed(0)
    model = MiniUNet().eval()
    x = np.random.default_rng(12).normal(size=(1, 3, 16, 16)).astype(np.float32)
    buf = io.BytesIO()
    torch.onnx.export(model, (torch.tensor(x),), buf, input_names=["x"],
                      output_names=["y"], dynamo=False)
    with torch.no_grad():
        want = model(torch.tensor(x)).numpy()
    from synapseml_tpu.onnx import convert_graph

    conv = convert_graph(buf.getvalue())
    got = np.asarray(conv(x=x)["y"])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
