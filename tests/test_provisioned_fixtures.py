"""Fixture-gated parity tests — activate when driver-provisioned files appear.

The container has no egress (see docs/BENCHMARKS.md "Real data, real weights,
stock-engine interop"), so two reference-strength checks can't run on
materials we can produce ourselves:

1. stock-LightGBM interop (reference ``booster/LightGBMBooster.scala:458``
   round-trips through the real engine),
2. real-pretrained-weights fine-tune (reference DL gate
   ``test_deep_text_classifier.py:48-52``: real bert-base, accuracy > 0.5).

These tests are pre-wired to the requested fixture paths and SKIP with an
explicit message until the driver provisions them. Requested layout:

    tests/resources/fixtures/stock_lightgbm/model.txt
        — a model.txt written by stock `lightgbm` (any small binary model)
    tests/resources/fixtures/stock_lightgbm/data.csv
        — the feature matrix it was trained on (no header, floats)
    tests/resources/fixtures/stock_lightgbm/pred.csv
        — stock LightGBM's predict() probabilities on data.csv, one per line
    tests/resources/fixtures/bert-base-uncased/
        — HF checkpoint dir (config.json + model.safetensors + vocab.txt)
"""

import pathlib

import numpy as np
import pytest

FIXTURES = pathlib.Path(__file__).parent / "resources" / "fixtures"
STOCK_LGBM = FIXTURES / "stock_lightgbm"
BERT_DIR = FIXTURES / "bert-base-uncased"


@pytest.mark.skipif(not (STOCK_LGBM / "model.txt").exists(),
                    reason="no driver-provisioned stock-LightGBM fixture "
                           f"({STOCK_LGBM}/model.txt); egress is blocked and "
                           "the lightgbm wheel is not in-container — see "
                           "docs/BENCHMARKS.md")
def test_stock_lightgbm_model_import_parity():
    """A model.txt written by STOCK LightGBM must load through
    parse_lightgbm_string and reproduce stock predictions exactly."""
    from synapseml_tpu.gbdt import parse_lightgbm_string

    booster = parse_lightgbm_string((STOCK_LGBM / "model.txt").read_text())
    X = np.loadtxt(STOCK_LGBM / "data.csv", delimiter=",", dtype=np.float32)
    expected = np.loadtxt(STOCK_LGBM / "pred.csv", dtype=np.float64)
    got = np.asarray(booster.predict(X)).reshape(expected.shape)
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


@pytest.mark.skipif(not (BERT_DIR / "config.json").exists(),
                    reason="no driver-provisioned bert-base-uncased checkpoint "
                           f"({BERT_DIR}); egress is blocked — see "
                           "docs/BENCHMARKS.md")
@pytest.mark.slow
def test_real_bert_weights_finetune_gate():
    """The reference's real-weights DL gate: fine-tune real bert-base on a
    small real text task and require accuracy > 0.5 (ref
    test_deep_text_classifier.py:48-52). Uses a locally-constructed real
    sentiment subset if no dataset fixture is present."""
    import synapseml_tpu as st
    from synapseml_tpu.models import DeepTextClassifier

    rows = []
    data_file = FIXTURES / "text_classification.csv"
    if data_file.exists():  # optional: driver-provisioned real dataset
        import csv

        with open(data_file) as f:
            for r in csv.DictReader(f):
                rows.append({"text": r["text"], "label": int(r["label"])})
    else:  # tiny real-English sentiment set (hand-written, still real text)
        pos = ["a wonderful film with a great cast", "truly excellent and moving",
               "I loved every minute of it", "brilliant, funny, and heartfelt",
               "one of the best this year", "a joy from start to finish"]
        neg = ["a dull and lifeless mess", "I hated the wooden acting",
               "boring from start to finish", "a complete waste of time",
               "the worst film of the year", "clumsy, tedious, and flat"]
        rows = ([{"text": t, "label": 1} for t in pos]
                + [{"text": t, "label": 0} for t in neg]) * 4
    df = st.DataFrame.from_rows(rows)
    model = DeepTextClassifier(checkpoint=str(BERT_DIR), num_classes=2,
                               batch_size=8, max_token_len=32,
                               learning_rate=3e-5, num_train_epochs=2).fit(df)
    out = model.transform(df)
    acc = float(np.mean(out.collect_column("prediction")
                        == out.collect_column("label")))
    assert acc > 0.5, f"real-weights fine-tune accuracy {acc} below gate 0.5"
