"""Mixture-of-experts MLP (switch routing, capacity-bucketed einsum
dispatch, expert-parallel sharding over the `expert` mesh axis) vs a
per-token numpy oracle."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from synapseml_tpu.models.flax_nets.transformer import (
    Encoder,
    MoEBlock,
    TransformerConfig,
)
from synapseml_tpu.parallel import MeshConfig, create_mesh
from synapseml_tpu.parallel.mesh import shard_params


def cfg_with(**kw):
    base = dict(hidden=16, n_layers=1, n_heads=4, mlp_dim=32, max_len=16,
                dtype=jnp.float32, moe_experts=4, moe_capacity_factor=2.0)
    base.update(kw)
    return TransformerConfig(**base)


def moe_oracle(x, variables, cfg):
    """Per-token reference: route by top-k of the same router, apply the
    chosen experts densely, weight by normalized gates; capacity ignored
    (use a capacity factor large enough that nothing drops)."""
    from flax.core import meta

    p = meta.unbox(variables)["params"]
    S = x.shape[0] * x.shape[1]
    xf = np.asarray(x, np.float64).reshape(S, -1)
    logits = xf @ np.asarray(p["router"]["kernel"], np.float64)
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    k = cfg.moe_top_k
    out = np.zeros_like(xf)
    from scipy.special import erf

    def gelu(v):
        return 0.5 * v * (1 + erf(v / np.sqrt(2)))

    for s in range(S):
        idx = np.argsort(-probs[s])[:k]
        gates = probs[s][idx]
        gates = gates / gates.sum() if k > 1 else gates
        for e, g in zip(idx, gates):
            h = gelu(xf[s] @ np.asarray(p["w_up"][e], np.float64)
                     + np.asarray(p["b_up"][e], np.float64))
            out[s] += g * (h @ np.asarray(p["w_dn"][e], np.float64)
                           + np.asarray(p["b_dn"][e], np.float64))
    return out.reshape(x.shape)


@pytest.mark.parametrize("dispatch", ["einsum", "scatter"])
@pytest.mark.parametrize("top_k", [1, 2])
def test_moe_matches_per_token_oracle(top_k, dispatch):
    cfg = cfg_with(moe_top_k=top_k, moe_capacity_factor=8.0,  # no drops
                   moe_dispatch=dispatch)
    block = MoEBlock(cfg)
    rs = np.random.default_rng(0)
    x = jnp.asarray(rs.normal(size=(2, 6, 16)), jnp.float32)
    variables = block.init(jax.random.PRNGKey(0), x)
    out = block.apply(variables, x)
    expect = moe_oracle(x, variables, cfg)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-4, atol=1e-5)


def test_moe_scatter_equals_einsum_dispatch():
    # same params, same input, both layouts, no drops: bitwise-equivalent
    # routing decisions must produce numerically matching outputs
    cfg_e = cfg_with(moe_top_k=2, moe_capacity_factor=8.0)
    cfg_s = dataclasses.replace(cfg_e, moe_dispatch="scatter")
    rs = np.random.default_rng(7)
    x = jnp.asarray(rs.normal(size=(2, 8, 16)), jnp.float32)
    variables = MoEBlock(cfg_e).init(jax.random.PRNGKey(0), x)
    out_e = np.asarray(MoEBlock(cfg_e).apply(variables, x))
    out_s = np.asarray(MoEBlock(cfg_s).apply(variables, x))
    np.testing.assert_allclose(out_s, out_e, rtol=1e-5, atol=1e-6)


def test_moe_scatter_equals_einsum_under_capacity_pressure():
    # k=2 with tight capacity: the two layouts must DROP THE SAME
    # assignments (choice-major fill priority — all first choices seat
    # before any second choice), not just agree in the no-drop regime
    cfg_e = cfg_with(moe_experts=2, moe_top_k=2, moe_capacity_factor=0.5)
    cfg_s = dataclasses.replace(cfg_e, moe_dispatch="scatter")
    rs = np.random.default_rng(11)
    x = jnp.asarray(rs.normal(size=(2, 8, 16)), jnp.float32)
    variables = MoEBlock(cfg_e).init(jax.random.PRNGKey(0), x)
    out_e = np.asarray(MoEBlock(cfg_e).apply(variables, x))
    out_s = np.asarray(MoEBlock(cfg_s).apply(variables, x))
    np.testing.assert_allclose(out_s, out_e, rtol=1e-5, atol=1e-6)


def test_moe_dispatch_validated():
    cfg = cfg_with(moe_dispatch="scater")
    block = MoEBlock(cfg)
    x = jnp.zeros((1, 4, 16), jnp.float32)
    with pytest.raises(ValueError, match="moe_dispatch"):
        block.init(jax.random.PRNGKey(0), x)


def test_moe_scatter_capacity_drops_tokens():
    # the scatter layout honors the same switch drop semantics as einsum
    cfg = cfg_with(moe_experts=2, moe_capacity_factor=1e-9,
                   moe_dispatch="scatter")
    block = MoEBlock(cfg)
    rs = np.random.default_rng(1)
    x = jnp.asarray(rs.normal(size=(1, 8, 16)), jnp.float32)
    variables = block.init(jax.random.PRNGKey(0), x)
    out = np.asarray(block.apply(variables, x))[0]
    nonzero_rows = np.sum(np.abs(out).sum(-1) > 1e-6)
    assert nonzero_rows <= 2, nonzero_rows


def test_moe_scatter_grads_flow():
    # the gather/scatter path must be differentiable end to end
    cfg = cfg_with(moe_top_k=2, moe_capacity_factor=8.0,
                   moe_dispatch="scatter")
    block = MoEBlock(cfg)
    rs = np.random.default_rng(9)
    x = jnp.asarray(rs.normal(size=(2, 4, 16)), jnp.float32)
    variables = block.init(jax.random.PRNGKey(0), x)

    def loss(v):
        return jnp.sum(block.apply(v, x) ** 2)

    g = jax.grad(loss)(variables)
    flat = jax.tree.leaves(jax.tree.map(lambda a: float(jnp.abs(a).sum()),
                                        g["params"]))
    assert all(np.isfinite(v) for v in flat)
    assert sum(flat) > 0.0


def test_moe_capacity_drops_tokens():
    # capacity 1 token/expert: overflowing tokens contribute ZERO (switch
    # drop semantics — the block's residual carries them)
    cfg = cfg_with(moe_experts=2, moe_capacity_factor=1e-9)
    block = MoEBlock(cfg)
    rs = np.random.default_rng(1)
    x = jnp.asarray(rs.normal(size=(1, 8, 16)), jnp.float32)
    variables = block.init(jax.random.PRNGKey(0), x)
    out = np.asarray(block.apply(variables, x))[0]
    # with C=1, at most 2 tokens (one per expert) produce nonzero output
    nonzero_rows = np.sum(np.abs(out).sum(-1) > 1e-6)
    assert nonzero_rows <= 2, nonzero_rows


def test_moe_aux_loss_sown():
    cfg = cfg_with()
    block = MoEBlock(cfg)
    x = jnp.asarray(np.random.default_rng(2).normal(size=(2, 4, 16)),
                    jnp.float32)
    variables = block.init(jax.random.PRNGKey(0), x)
    _, state = block.apply(variables, x, mutable=["intermediates"])
    (aux,) = state["intermediates"]["moe_aux_loss"]
    assert float(aux) > 0.0  # E * sum(f*P) >= 1 at balance, > 0 always


def test_moe_expert_parallel_matches_unsharded():
    cfg = cfg_with(moe_capacity_factor=8.0)
    block = MoEBlock(cfg)
    rs = np.random.default_rng(3)
    x = jnp.asarray(rs.normal(size=(2, 8, 16)), jnp.float32)
    variables = block.init(jax.random.PRNGKey(1), x)
    ref = np.asarray(block.apply(variables, x))

    mesh = create_mesh(MeshConfig(data=2, expert=4))
    placed = shard_params(variables, mesh)
    with mesh.mesh:
        out = jax.jit(lambda v, xx: block.apply(v, xx))(placed, x)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_moe_scatter_expert_parallel_matches_unsharded():
    # the HF/Mixtral dispatch layout under a sharded expert axis: GSPMD must
    # reshard the scatter/gather traffic without changing results
    cfg = cfg_with(moe_top_k=2, moe_capacity_factor=8.0,
                   moe_dispatch="scatter")
    block = MoEBlock(cfg)
    rs = np.random.default_rng(13)
    x = jnp.asarray(rs.normal(size=(2, 8, 16)), jnp.float32)
    variables = block.init(jax.random.PRNGKey(1), x)
    ref = np.asarray(block.apply(variables, x))

    mesh = create_mesh(MeshConfig(data=2, expert=4))
    placed = shard_params(variables, mesh)
    with mesh.mesh:
        out = jax.jit(lambda v, xx: block.apply(v, xx))(placed, x)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_moe_encoder_trains():
    # gradient flow end-to-end: a 2-layer MoE encoder fits a tiny regression
    cfg = cfg_with(n_layers=2, moe_top_k=2)
    enc = Encoder(cfg)
    rs = np.random.default_rng(4)
    x = jnp.asarray(rs.normal(size=(4, 8, 16)), jnp.float32)
    y = jnp.asarray(rs.normal(size=(4, 8, 16)), jnp.float32)
    variables = enc.init(jax.random.PRNGKey(0), x)

    @jax.jit
    def step(params):
        def loss(p):
            out = enc.apply({"params": p}, x)
            return jnp.mean((out - y) ** 2)

        l, g = jax.value_and_grad(loss)(params)
        return jax.tree.map(lambda a, b: a - 0.05 * b, params, g), l

    params = variables["params"]
    losses = []
    for _ in range(5):
        params, l = step(params)
        losses.append(float(l))
    assert losses[-1] < losses[0], losses
    assert all(np.isfinite(l) for l in losses)


def test_trainer_applies_moe_aux_loss():
    # the Trainer must fold the sown load-balance term into the training
    # loss — a zero vs nonzero moe_aux_weight must change the loss value
    from synapseml_tpu.models.flax_nets.bert import BertClassifier, bert_tiny
    from synapseml_tpu.models.trainer import Trainer, TrainerConfig

    cfg = bert_tiny(n_layers=1, moe_experts=2, moe_top_k=1)
    rs = np.random.default_rng(0)
    batch = {"input_ids": rs.integers(0, cfg.vocab_size, (8, 8)).astype(np.int32),
             "attention_mask": np.ones((8, 8), np.int32),
             "labels": rs.integers(0, 2, (8,)).astype(np.int32)}
    mesh = create_mesh(MeshConfig(data=-1))

    def loss_with(weight):
        tr = Trainer(BertClassifier(cfg, num_classes=2), mesh,
                     TrainerConfig(learning_rate=1e-3, total_steps=4,
                                   moe_aux_weight=weight))
        state = tr.init_state(batch)
        _, metrics = tr.train_step(state, batch)
        return float(metrics["loss"])

    l0, l1 = loss_with(0.0), loss_with(0.5)
    assert l1 > l0, (l0, l1)  # aux term is positive, so it must show up


def test_dense_mlp_unchanged_when_moe_disabled():
    cfg = cfg_with(moe_experts=0)
    enc = Encoder(cfg)
    x = jnp.zeros((1, 4, 16), jnp.float32)
    variables = enc.init(jax.random.PRNGKey(0), x)
    names = set(variables["params"]["layer_0"]["mlp"].keys())
    assert "router" not in names and "up" in names  # plain MlpBlock params
