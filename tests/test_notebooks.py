"""Notebook corpus tier (reference: docs/**/*.ipynb + the nbtest executor
`core/src/test/scala/.../nbtest/DatabricksUtilities.scala`). The committed
.ipynb files are EMITTED from the percent-cell scripts in docs/examples/ and
docs/walkthroughs/ — a drift test regenerates and diffs them (same pattern as
test_codegen for the wrapper surface), and one notebook is executed from its
.ipynb form to prove the emitted JSON is a runnable notebook, not just
well-formed."""

import json
import os
import subprocess
import sys

import pytest

from synapseml_tpu.codegen.notebooks import (
    emit_notebooks,
    notebook_code,
    percent_to_notebook,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOCS = os.path.join(REPO, "docs")
NB_DIR = os.path.join(DOCS, "notebooks")


def test_notebook_corpus_has_no_drift(tmp_path):
    """docs/notebooks/ must be exactly what the emitter produces from the
    current docs/examples/ + docs/walkthroughs/ sources."""
    out = emit_notebooks([os.path.join(DOCS, "examples"),
                          os.path.join(DOCS, "walkthroughs")], str(tmp_path))
    regenerated = {os.path.basename(p) for p in out}
    committed = {n for n in os.listdir(NB_DIR) if n.endswith(".ipynb")}
    assert regenerated == committed, (
        f"notebook corpus drift: regenerate with "
        f"`python synapseml_tpu/codegen/notebooks.py` "
        f"(missing={sorted(regenerated - committed)}, "
        f"stale={sorted(committed - regenerated)})")
    for name in sorted(regenerated):
        with open(os.path.join(str(tmp_path), name)) as f:
            fresh = f.read()
        with open(os.path.join(NB_DIR, name)) as f:
            assert f.read() == fresh, (
                f"{name} is stale — regenerate with "
                f"`python synapseml_tpu/codegen/notebooks.py`")


def test_notebooks_are_valid_nbformat4():
    for name in sorted(os.listdir(NB_DIR)):
        if not name.endswith(".ipynb"):
            continue
        with open(os.path.join(NB_DIR, name)) as f:
            nb = json.load(f)
        assert nb["nbformat"] == 4, name
        assert nb["cells"], f"{name} has no cells"
        assert nb["cells"][0]["cell_type"] == "markdown", (
            f"{name} must open with a narrative markdown cell")
        for c in nb["cells"]:
            assert c["cell_type"] in ("markdown", "code")
            assert isinstance(c["source"], list)
            if c["cell_type"] == "code":
                assert "outputs" in c and "execution_count" in c
        # every code line must survive the round trip verbatim
        assert "import" in notebook_code(nb), name


def test_percent_roundtrip_preserves_code():
    text = (
        "# %% [markdown]\n# # Title\n# prose line\n\n"
        "# %%  first stage\nx = 1\n\n\ny = x + 1\n\n"
        "# %% [markdown]\n# more prose\n# %%\nprint(y)\n")
    nb = percent_to_notebook(text)
    kinds = [c["cell_type"] for c in nb["cells"]]
    assert kinds == ["markdown", "code", "markdown", "code"]
    assert nb["cells"][0]["source"][0] == "# Title\n"
    code = notebook_code(nb)
    assert "# first stage\nx = 1" in code
    assert "y = x + 1" in code and "print(y)" in code
    env = {}
    exec(code, env)  # noqa: S102 — the point of the nbtest tier
    assert env["y"] == 2


def test_module_docstring_becomes_leading_markdown():
    text = '"""# Title\n\nProse paragraph."""\n\nimport os\n\n# %%\nprint(os.name)\n'
    nb = percent_to_notebook(text)
    kinds = [c["cell_type"] for c in nb["cells"]]
    assert kinds == ["markdown", "code", "code"]
    assert nb["cells"][0]["source"][0] == "# Title\n"
    assert "import os" in "".join(nb["cells"][1]["source"])


def test_emit_removes_stale_notebooks(tmp_path):
    src = tmp_path / "src"
    src.mkdir()
    (src / "keep.py").write_text("# %% [markdown]\n# hi\n# %%\nx = 1\n")
    out = tmp_path / "out"
    out.mkdir()
    (out / "renamed_away.ipynb").write_text("{}")
    emit_notebooks([str(src)], str(out))
    assert sorted(os.listdir(out)) == ["keep.ipynb"]


def test_emit_rejects_basename_collision(tmp_path):
    a, b = tmp_path / "a", tmp_path / "b"
    a.mkdir(), b.mkdir()
    for d in (a, b):
        (d / "same.py").write_text("# %% [markdown]\n# hi\n# %%\nx = 1\n")
    with pytest.raises(ValueError, match="collision"):
        emit_notebooks([str(a), str(b)], str(tmp_path / "out"))


@pytest.mark.slow
@pytest.mark.parametrize("notebook", ["onnx_model_inference.ipynb",
                                      "knn_similarity_search.ipynb",
                                      "data_balance_analysis.ipynb",
                                      "isolation_forest_anomaly.ipynb"])
def test_execute_emitted_notebooks(tmp_path, notebook):
    """nbtest analog: run committed .ipynb code cells in a fresh
    interpreter (CPU), proving the emitted corpus is executable as-is —
    example and walkthrough notebooks across four families."""
    with open(os.path.join(NB_DIR, notebook)) as f:
        code = notebook_code(json.load(f))
    script = tmp_path / "nb_exec.py"
    script.write_text(
        "import jax\njax.config.update('jax_platforms', 'cpu')\n" + code)
    proc = subprocess.run([sys.executable, str(script)], cwd=str(tmp_path),
                          env={**os.environ, "PYTHONPATH": REPO},
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
