"""Codegen wrapper emission (reference ``Wrappable.scala:56-389`` pyGen):
the generated pyspark-style compat surface works and cannot drift from the
stage registry."""

import filecmp
import os
import pathlib

import numpy as np
import pytest

import synapseml_tpu as st
from synapseml_tpu.codegen import emit_wrappers

COMPAT = pathlib.Path(st.__file__).parent / "compat"


def test_generated_wrappers_match_committed(tmp_path):
    """Regenerating into a clean dir reproduces the committed files exactly
    (the drift guarantee in docs/api/CODEGEN.md)."""
    out = tmp_path / "compat"
    written = emit_wrappers(str(out))
    gen_names = {os.path.basename(p) for p in written}
    committed = {p.name for p in COMPAT.glob("*.py") if p.name != "_base.py"}
    assert gen_names == committed, (
        f"namespace drift: generated {sorted(gen_names)} vs "
        f"committed {sorted(committed)}")
    diff = [n for n in gen_names
            if not filecmp.cmp(out / n, COMPAT / n, shallow=False)]
    assert not diff, (f"generated wrappers differ from committed: {diff}; "
                      "run python -m synapseml_tpu.codegen")


def test_facts_manifest_matches_live():
    """docs/api/facts.json is emitted from codegen.facts(); committed copy
    must match the live computation (same drift pattern as the wrappers)."""
    import json

    from synapseml_tpu.codegen import facts

    path = pathlib.Path(st.__file__).parent.parent / "docs" / "api" / "facts.json"
    with open(path) as f:
        committed = json.load(f)
    live = facts()
    assert committed == live, (
        f"facts drift: committed {committed} vs live {live}; "
        "run python -m synapseml_tpu.codegen")


def test_numeric_claims_quote_facts():
    """Every 'N-op registry' / 'N stage' style claim in the reports must
    equal the live fact — hand-maintained counts went stale three separate
    times before this test (VERDICT r4 weak #6)."""
    import re

    from synapseml_tpu.codegen import facts

    live = facts()
    repo = pathlib.Path(st.__file__).parent.parent
    scan = [repo / "README.md", repo / "COVERAGE.md"]
    scan += list((repo / "synapseml_tpu").rglob("*.py"))
    bad = []
    for path in scan:
        if "compat" in path.parts:
            continue
        try:
            text = path.read_text()
        except OSError:
            continue
        for m in re.finditer(r"(\d+)-op registry", text):
            if int(m.group(1)) != live["onnx_ops"]:
                bad.append(f"{path}: '{m.group(0)}' vs live "
                           f"{live['onnx_ops']}")
        for m in re.finditer(r"(\d+)[- ]stage (?:manifest|classes)", text):
            if int(m.group(1)) != live["stage_classes"]:
                bad.append(f"{path}: '{m.group(0)}' vs live "
                           f"{live['stage_classes']}")
        for m in re.finditer(r"(\d+)-notebook corpus", text):
            if int(m.group(1)) != live["notebooks"]:
                bad.append(f"{path}: '{m.group(0)}' vs live "
                           f"{live['notebooks']}")
    assert not bad, "stale numeric claims:\n" + "\n".join(bad)


def test_registry_compat_coverage():
    """Static compat check for the non-stage registry subsystem: EVERY
    public symbol of ``synapseml_tpu.registry`` must be importable from the
    generated ``synapseml_tpu.compat.registry`` passthrough (and the
    passthrough must not carry stale names). A new public registry symbol
    without regenerated compat coverage fails the suite here."""
    import synapseml_tpu.compat.registry as compat_registry
    import synapseml_tpu.registry as registry

    public = set(registry.__all__)
    covered = set(compat_registry.__all__)
    missing = sorted(public - covered)
    assert not missing, (
        f"public registry symbols missing compat coverage: {missing}; "
        "run python -m synapseml_tpu.codegen")
    stale = sorted(covered - public)
    assert not stale, (
        f"compat.registry exports symbols the registry no longer has: "
        f"{stale}; run python -m synapseml_tpu.codegen")
    for name in sorted(public):
        assert getattr(compat_registry, name) is getattr(registry, name), (
            f"compat.registry.{name} is not the registry's own object")


def test_scoring_compat_coverage():
    """Same compat coverage rule for the bulk-scoring subsystem: every
    public ``synapseml_tpu.scoring`` symbol importable from the generated
    ``compat.scoring`` passthrough, with no stale extras."""
    import synapseml_tpu.compat.scoring as compat_scoring
    import synapseml_tpu.scoring as scoring

    public = set(scoring.__all__)
    covered = set(compat_scoring.__all__)
    missing = sorted(public - covered)
    assert not missing, (
        f"public scoring symbols missing compat coverage: {missing}; "
        "run python -m synapseml_tpu.codegen")
    stale = sorted(covered - public)
    assert not stale, (
        f"compat.scoring exports symbols the scoring plane no longer has: "
        f"{stale}; run python -m synapseml_tpu.codegen")
    for name in sorted(public):
        assert getattr(compat_scoring, name) is getattr(scoring, name), (
            f"compat.scoring.{name} is not the scoring plane's own object")


def test_fleet_compat_coverage():
    """Same compat coverage rule for the fleet control plane: every public
    ``synapseml_tpu.fleet`` symbol importable from the generated
    ``compat.fleet`` passthrough, with no stale extras."""
    import synapseml_tpu.compat.fleet as compat_fleet
    import synapseml_tpu.fleet as fleet

    public = set(fleet.__all__)
    covered = set(compat_fleet.__all__)
    missing = sorted(public - covered)
    assert not missing, (
        f"public fleet symbols missing compat coverage: {missing}; "
        "run python -m synapseml_tpu.codegen")
    stale = sorted(covered - public)
    assert not stale, (
        f"compat.fleet exports symbols the fleet plane no longer has: "
        f"{stale}; run python -m synapseml_tpu.codegen")
    for name in sorted(public):
        assert getattr(compat_fleet, name) is getattr(fleet, name), (
            f"compat.fleet.{name} is not the fleet plane's own object")


def test_continual_compat_coverage():
    """Same compat coverage rule for the continual-training flywheel:
    every public ``synapseml_tpu.continual`` symbol importable from the
    generated ``compat.continual`` passthrough, with no stale extras."""
    import synapseml_tpu.compat.continual as compat_continual
    import synapseml_tpu.continual as continual

    public = set(continual.__all__)
    covered = set(compat_continual.__all__)
    missing = sorted(public - covered)
    assert not missing, (
        f"public continual symbols missing compat coverage: {missing}; "
        "run python -m synapseml_tpu.codegen")
    stale = sorted(covered - public)
    assert not stale, (
        f"compat.continual exports symbols the continual plane no longer "
        f"has: {stale}; run python -m synapseml_tpu.codegen")
    for name in sorted(public):
        assert getattr(compat_continual, name) is getattr(continual, name), (
            f"compat.continual.{name} is not the continual plane's own "
            "object")


def test_retrieval_compat_coverage():
    """Same compat coverage rule for the retrieval serving plane: every
    public ``synapseml_tpu.retrieval`` symbol importable from the generated
    ``compat.retrieval`` passthrough, with no stale extras. The plane's
    __init__ is lazy (PEP 562), so identity holds through __getattr__."""
    import synapseml_tpu.compat.retrieval as compat_retrieval
    import synapseml_tpu.retrieval as retrieval

    public = set(retrieval.__all__)
    covered = set(compat_retrieval.__all__)
    missing = sorted(public - covered)
    assert not missing, (
        f"public retrieval symbols missing compat coverage: {missing}; "
        "run python -m synapseml_tpu.codegen")
    stale = sorted(covered - public)
    assert not stale, (
        f"compat.retrieval exports symbols the retrieval plane no longer "
        f"has: {stale}; run python -m synapseml_tpu.codegen")
    for name in sorted(public):
        assert getattr(compat_retrieval, name) is getattr(retrieval, name), (
            f"compat.retrieval.{name} is not the retrieval plane's own "
            "object")


def test_rai_compat_coverage():
    """Same compat coverage rule for the responsible-AI audit plane: every
    public ``synapseml_tpu.rai`` symbol importable from the generated
    ``compat.rai`` passthrough, with no stale extras. The plane's __init__
    is lazy (PEP 562), so identity holds through __getattr__."""
    import synapseml_tpu.compat.rai as compat_rai
    import synapseml_tpu.rai as rai

    public = set(rai.__all__)
    covered = set(compat_rai.__all__)
    missing = sorted(public - covered)
    assert not missing, (
        f"public rai symbols missing compat coverage: {missing}; "
        "run python -m synapseml_tpu.codegen")
    stale = sorted(covered - public)
    assert not stale, (
        f"compat.rai exports symbols the rai plane no longer "
        f"has: {stale}; run python -m synapseml_tpu.codegen")
    for name in sorted(public):
        assert getattr(compat_rai, name) is getattr(rai, name), (
            f"compat.rai.{name} is not the rai plane's own object")


def test_no_inline_jit_in_stage_transform():
    """Static guard for the continuous-batching plane: inference-stage
    modules must acquire jitted programs through
    ``core.batching.CompiledCache`` — any ``jax.jit`` reference may appear
    ONLY inside a cache-builder function (named ``build``/``_build*``).
    An inline ``jax.jit`` in a transform path re-traces per batch shape,
    is invisible to the hit/miss/trace-time metrics, and dodges the
    ``/admin/load`` warmup precompile. (``gbdt/booster.py`` training jits
    are estimator-time — one trace per fit — and stay out of scope; its
    predict path is behavior-tested in test_batching.py.) The token-serving
    plane is held to the same rule: the paged prefill/decode programs
    (``models/paged_engine.py``, model code in ``models/flax_nets/llama.py``)
    and the ``io/serving.py`` token scheduler acquire jits only through the
    cache — that is what makes the decode-executable count bounded by the
    slot ladder and the ``/admin/load`` warmup able to precompile every
    rung. The AutoML sweep plane (``automl/``, the fused training arrays in
    ``models/fused_trainer.py`` and ``gbdt/fused.py``) is likewise bound:
    its one-executable-per-trial-rung guarantee rests on every jit going
    through the cache, where the miss counters the parity suite asserts on
    can see them. The AOT deploy plane (``registry/aot.py`` capture/load,
    ``registry/autotune.py`` search) is bound too: its jit touches live in
    ``_build*`` helpers only, so publish-time capture and load-time
    deserialization stay visible to the same counters the zero-trace
    acceptance test reads."""
    import ast

    modules = ["onnx/model.py", "hf/embedder.py", "hf/causal_lm.py",
               "models/text.py", "models/vision.py", "nn/knn.py",
               "models/paged_engine.py", "models/flax_nets/llama.py",
               # the prefix cache indexes pages (pure host bookkeeping)
               # and the distributed front routes on prefix hashes — a
               # private jit in either would put tracing on the admit or
               # routing hot path, invisible to the warmup precompile
               "models/prefix_cache.py", "io/distributed_serving.py",
               "io/serving.py",
               "automl/tune.py", "automl/hyperparams.py",
               "models/fused_trainer.py", "gbdt/fused.py",
               "scoring/planner.py", "scoring/runner.py", "scoring/sink.py",
               "registry/aot.py", "registry/autotune.py",
               # the sharding plane: placement is declarative data, never
               # an ad-hoc jit (the trainer's jits stay estimator-time);
               # the gang channel is pure protocol — a jit anywhere in it
               # would put tracing on the failure-detection path
               "parallel/partition.py", "models/pipeline_trainer.py",
               "parallel/gang.py", "parallel/checkpoint.py",
               # the fleet control plane: reconcile/residency/admission
               # code must never acquire executables outside the shared
               # CompiledCache — a control loop that traced privately
               # would dodge the warmup precompile and the AOT second
               # tier its own scale-up guarantee rests on
               "fleet/autoscaler.py", "fleet/residency.py",
               "fleet/admission.py", "fleet/spec.py",
               # the continual flywheel: orchestration/logging code must
               # never acquire executables outside the shared cache — a
               # loop that traced privately would dodge the publish-time
               # AOT capture its own zero-cold-start canaries ride
               "continual/logger.py", "continual/supervisor.py",
               "continual/loop.py",
               # the retrieval serving plane: shard scoring must ride the
               # shared scorer ladder (executables keyed by shard SHAPE) —
               # a private jit anywhere in build/ingest/serve would break
               # the ladder-many compile bound the acceptance test reads
               # off the cache miss counters
               "retrieval/scorer.py", "retrieval/model.py",
               "retrieval/build.py", "retrieval/ingest.py",
               "retrieval/serve.py",
               # the rai audit plane: the fused perturbation engine's whole
               # claim is "compile bill bounded by the ladder", which only
               # holds if every explainer/audit jit goes through the cache
               # where the miss counters the acceptance test reads can see
               # it; the explainers and the audited scorers (iforest,
               # balance) are held to the same rule
               "rai/fused.py", "rai/stream.py", "rai/audit.py",
               "rai/drift.py", "rai/metrics.py",
               "explainers/base.py", "explainers/shap.py",
               "explainers/lime.py", "explainers/ice.py",
               "isolationforest/iforest.py", "exploratory/balance.py"]
    pkg = pathlib.Path(st.__file__).parent
    offenders = []
    for rel in modules:
        tree = ast.parse((pkg / rel).read_text())

        class Visitor(ast.NodeVisitor):
            def __init__(self):
                self.stack = []

            def visit_FunctionDef(self, node):
                self.stack.append(node.name)
                self.generic_visit(node)
                self.stack.pop()

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Attribute(self, node):
                if node.attr == "jit" and not any(
                        name == "build" or name.startswith("_build")
                        for name in self.stack):
                    offenders.append(f"{rel}:{node.lineno}")
                self.generic_visit(node)

        Visitor().visit(tree)
    assert not offenders, (
        "jax.jit outside a CompiledCache builder (route it through "
        f"core.batching.CompiledCache.get): {offenders}")


def test_shardings_only_through_rule_table():
    """Static guard for the sharding plane: trainer/estimator/conversion
    modules must acquire shardings ONLY through the declarative rule table
    (``parallel.partition``) or the mesh context's helpers — no inline
    ``NamedSharding`` construction outside ``parallel/``. An inline
    sharding would fork placement off the one table that checkpoints,
    registry manifests and ``/admin/load`` round-trip, so a published
    model could silently serve with a layout its manifest does not
    record. (``gbdt/booster.py``'s row-scatter helper predates the plane
    and shards BATCHES, not params — out of scope.)"""
    import ast

    modules = ["models/trainer.py", "models/pipeline_trainer.py",
               "models/fused_trainer.py", "models/convert_hf.py",
               "hf/causal_lm.py", "hf/embedder.py", "io/serving.py",
               "registry/registry.py", "registry/deploy.py"]
    pkg = pathlib.Path(st.__file__).parent
    offenders = []
    for rel in modules:
        tree = ast.parse((pkg / rel).read_text())
        for node in ast.walk(tree):
            name = node.id if isinstance(node, ast.Name) else (
                node.attr if isinstance(node, ast.Attribute) else None)
            if name == "NamedSharding":
                offenders.append(f"{rel}:{node.lineno}")
    assert not offenders, (
        "inline NamedSharding outside parallel/ (route placement through "
        f"parallel.partition's rule table): {offenders}")
    # the positive side: the rule-table entry points are what these
    # modules consume
    trainer_src = (pkg / "models/trainer.py").read_text()
    assert "_rule_place_params" in trainer_src
    assert "partition" in trainer_src
    lm_src = (pkg / "hf/causal_lm.py").read_text()
    assert "shard_pretrained_params" in lm_src


def test_fit_paths_consume_batches_through_data_plane():
    """Static guard for the streaming data plane: ``models/trainer.py`` and
    ``gbdt/booster.py`` must consume training batches only through the
    ``data`` plane (``fit_source`` / ``train_booster_from_source``) or the
    thin ``fit_arrays`` wrapper — no ad-hoc slicing loops or direct
    ``parallel.batching`` minibatchers reintroduced. An inline slicing loop
    would fork shuffle/padding/resume semantics off the one plane the
    checkpointable-iterator guarantee rests on."""
    import ast

    pkg = pathlib.Path(st.__file__).parent
    offenders = []
    for rel in ("models/trainer.py", "gbdt/booster.py"):
        src = (pkg / rel).read_text()
        tree = ast.parse(src)
        for node in ast.walk(tree):
            # (a) the training-side minibatcher must not be imported here
            if isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.endswith("parallel.batching"):
                offenders.append(f"{rel}:{node.lineno} imports "
                                 f"parallel.batching ({[a.name for a in node.names]})")
            # (b) no 3-arg range() slicing loops (the ad-hoc batch pattern
            # `for start in range(0, n, batch_size): x[start:...]`)
            if isinstance(node, ast.For) and isinstance(node.iter, ast.Call) \
                    and isinstance(node.iter.func, ast.Name) \
                    and node.iter.func.id == "range" \
                    and len(node.iter.args) == 3 \
                    and any(isinstance(x, ast.Slice)
                            for b in node.body for x in ast.walk(b)):
                offenders.append(f"{rel}:{node.lineno} ad-hoc slicing loop")
    assert not offenders, (
        "batch consumption outside the data plane (route it through "
        f"data.DataLoader / fit_source / fit_arrays): {offenders}")
    # the positive side: the plane entry points exist and delegate
    trainer_src = (pkg / "models/trainer.py").read_text()
    assert "def fit_source(" in trainer_src
    assert "MemorySource" in trainer_src  # fit_arrays delegates to the plane
    booster_src = (pkg / "gbdt/booster.py").read_text()
    assert "def train_booster_from_source(" in booster_src


def test_wrapper_chaining_fit_transform():
    from synapseml_tpu.compat.lightgbm import (LightGBMClassificationModel,
                                               LightGBMClassifier)

    rs = np.random.default_rng(3)
    X = rs.normal(size=(150, 4))
    y = (X[:, 0] > 0).astype(int)
    df = st.DataFrame.from_rows([{"features": X[i], "label": int(y[i])}
                                 for i in range(150)])
    est = (LightGBMClassifier()
           .setNumIterations(6)
           .setLearningRate(0.3))
    assert est.getNumIterations() == 6
    model = est.fit(df)
    assert isinstance(model, LightGBMClassificationModel)  # fit re-wraps
    out = model.transform(df)
    acc = float(np.mean(out.collect_column("prediction")
                        == out.collect_column("label")))
    assert acc > 0.8
    assert model.unwrap().get("num_iterations") == 6


def test_wrapper_constructor_kwargs_both_styles():
    from synapseml_tpu.compat.lightgbm import LightGBMClassifier

    a = LightGBMClassifier(numIterations=4)
    b = LightGBMClassifier(num_iterations=4)
    assert a.getNumIterations() == b.getNumIterations() == 4
    with pytest.raises(KeyError):
        LightGBMClassifier(noSuchParam=1)


def test_wrapper_namespaces_cover_reference_families():
    """The emitted namespaces include the reference's synapse.ml families."""
    names = {p.stem for p in COMPAT.glob("*.py")}
    for expect in ("lightgbm", "vw", "onnx", "opencv", "dl", "stages",
                   "featurize", "explainers", "automl", "train",
                   "recommendation", "nn", "isolationforest", "cyber",
                   "services", "causal"):
        assert expect in names, f"missing wrapper namespace {expect}"


def test_exchange_paths_reach_terminal_reply():
    """Static guard for the survivable-serving plane: every function in
    io/serving.py and io/distributed_serving.py that ACQUIRES an
    ``_Exchange`` (constructs one or looks one up via ``exchange_for``)
    must contain a terminal-reply operation — ``respond``/``stream_end``
    (or a raw ``send_response``), or delegate to the audited
    ``fail_inflight`` helper — and any ``except`` handler in such a
    function that touches the exchange must terminally reply, re-raise,
    or bail the iteration. A dropped exchange is a client blocked to full
    timeout; this makes that regression fail at commit time instead of in
    a chaos run."""
    import ast

    TERMINAL_ATTRS = {"respond", "stream_end", "send_response"}
    TERMINAL_FUNCS = {"fail_inflight"}

    def own_nodes(fn):
        # nodes of fn itself, nested function defs excluded (each nested
        # def is audited as its own scope)
        out, stack = [], list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            out.append(node)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.extend(ast.iter_child_nodes(node))
        return out

    def is_acquisition(node):
        if not isinstance(node, ast.Call):
            return False
        f = node.func
        return (isinstance(f, ast.Attribute) and f.attr == "exchange_for") \
            or (isinstance(f, ast.Name) and f.id == "_Exchange")

    def is_terminal(node):
        if not isinstance(node, ast.Call):
            return False
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in TERMINAL_ATTRS:
            return True
        return isinstance(f, ast.Name) and f.id in TERMINAL_FUNCS

    pkg = pathlib.Path(st.__file__).parent
    offenders = []
    for rel in ("io/serving.py", "io/distributed_serving.py"):
        tree = ast.parse((pkg / rel).read_text())
        for fn in [n for n in ast.walk(tree)
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
            nodes = own_nodes(fn)
            acquired = [n for n in nodes if is_acquisition(n)]
            if not acquired or fn.name == "exchange_for":
                continue
            bound = set()
            for n in nodes:
                if isinstance(n, ast.Assign) and any(
                        is_acquisition(c) for c in ast.walk(n.value)):
                    bound |= {t.id for t in n.targets
                              if isinstance(t, ast.Name)}
            if not any(is_terminal(n) for n in nodes):
                offenders.append(
                    f"{rel}:{fn.lineno} {fn.name}: acquires an _Exchange "
                    f"but never reaches respond/stream_end/fail_inflight")
            # a swallowed exception that references the exchange must still
            # terminally reply (or re-raise / bail the loop iteration)
            for n in nodes:
                if not isinstance(n, ast.Try):
                    continue
                for handler in n.handlers:
                    hnodes = [x for b in handler.body for x in ast.walk(b)]
                    touches = any(isinstance(x, ast.Name) and x.id in bound
                                  for x in hnodes)
                    safe = any(is_terminal(x) for x in hnodes) or any(
                        isinstance(x, (ast.Raise, ast.Continue))
                        for x in hnodes)
                    if touches and not safe:
                        offenders.append(
                            f"{rel}:{handler.lineno} {fn.name}: except "
                            f"handler touches an _Exchange without a "
                            f"terminal reply or re-raise")
    assert not offenders, "dropped-exchange paths:\n" + "\n".join(offenders)
