"""REAL torch-exported transformer through the ONNX path (round-3 verdict
missing #4): a BERT-style einsum-attention encoder exported by
``torch.onnx.export`` must convert and match torch logits — the transformer
analog of ``test_onnx_resnet.py``. Reference runs the full opset through
ONNX Runtime (``deep-learning/src/main/scala/.../onnx/ONNXModel.scala:211``,
``ONNXRuntime.scala:25``); here the graph lowers to jax/XLA instead.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).parent))

torch = pytest.importorskip("torch")

from _torch_bert import TorchBertEncoder, export_bert_onnx_bytes  # noqa: E402


@pytest.fixture(scope="module")
def exported():
    torch.manual_seed(0)
    model = TorchBertEncoder(vocab=512, hidden=64, heads=4, layers=2,
                             mlp=128, max_len=128, num_classes=3)
    ids = torch.randint(0, 512, (2, 16))
    mask = torch.ones(2, 16, dtype=torch.long)
    mask[1, 10:] = 0
    data = export_bert_onnx_bytes(model, ids, mask)
    return model, data


def test_transformer_export_ops_all_supported(exported):
    """The export's op set (Einsum, LayerNormalization, dynamic Reshape
    chains via Shape/Gather/Concat, Cast mask arithmetic...) must be fully
    covered by the registry — no silent opset gap for transformers."""
    from synapseml_tpu.onnx.convert import OP_REGISTRY
    from synapseml_tpu.onnx.proto import ModelProto

    _, data = exported
    ops = {n.op_type for n in ModelProto.parse(data).graph.node}
    assert "Einsum" in ops, "export no longer exercises Einsum attention"
    assert "LayerNormalization" in ops or "ReduceMean" in ops
    missing = sorted(o for o in ops if o not in OP_REGISTRY)
    assert not missing, f"unsupported transformer ops: {missing}"


def test_transformer_logits_match_torch(exported):
    """Converted graph == torch logits, including a PADDED row (the mask
    path) and a second, longer sequence length (the dynamic-shape Reshape
    chain re-traces under jit)."""
    import jax

    from synapseml_tpu.onnx import convert_graph

    model, data = exported
    conv = convert_graph(data)
    fn = jax.jit(lambda i, m: conv(input_ids=i, attention_mask=m)["logits"])

    for B, T, pad in ((2, 16, 6), (3, 24, 0)):
        g = torch.Generator().manual_seed(B * 100 + T)
        ids = torch.randint(0, 512, (B, T), generator=g)
        mask = torch.ones(B, T, dtype=torch.long)
        if pad:
            mask[-1, -pad:] = 0
        with torch.no_grad():
            want = model(ids, mask).numpy()
        got = np.asarray(fn(ids.numpy(), mask.numpy()))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)
