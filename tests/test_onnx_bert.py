"""REAL torch-exported transformer through the ONNX path (round-3 verdict
missing #4): a BERT-style einsum-attention encoder exported by
``torch.onnx.export`` must convert and match torch logits — the transformer
analog of ``test_onnx_resnet.py``. Reference runs the full opset through
ONNX Runtime (``deep-learning/src/main/scala/.../onnx/ONNXModel.scala:211``,
``ONNXRuntime.scala:25``); here the graph lowers to jax/XLA instead.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).parent))

torch = pytest.importorskip("torch")

from _torch_bert import TorchBertEncoder, export_bert_onnx_bytes  # noqa: E402


@pytest.fixture(scope="module")
def exported():
    torch.manual_seed(0)
    model = TorchBertEncoder(vocab=512, hidden=64, heads=4, layers=2,
                             mlp=128, max_len=128, num_classes=3)
    ids = torch.randint(0, 512, (2, 16))
    mask = torch.ones(2, 16, dtype=torch.long)
    mask[1, 10:] = 0
    data = export_bert_onnx_bytes(model, ids, mask)
    return model, data


def test_transformer_export_ops_all_supported(exported):
    """The export's op set (Einsum, LayerNormalization, dynamic Reshape
    chains via Shape/Gather/Concat, Cast mask arithmetic...) must be fully
    covered by the registry — no silent opset gap for transformers."""
    from synapseml_tpu.onnx.convert import OP_REGISTRY
    from synapseml_tpu.onnx.proto import ModelProto

    _, data = exported
    ops = {n.op_type for n in ModelProto.parse(data).graph.node}
    assert "Einsum" in ops, "export no longer exercises Einsum attention"
    assert "LayerNormalization" in ops or "ReduceMean" in ops
    missing = sorted(o for o in ops if o not in OP_REGISTRY)
    assert not missing, f"unsupported transformer ops: {missing}"


def test_transformer_logits_match_torch(exported):
    """Converted graph == torch logits, including a PADDED row (the mask
    path) and a second, longer sequence length (the dynamic-shape Reshape
    chain re-traces under jit)."""
    import jax

    from synapseml_tpu.onnx import convert_graph

    model, data = exported
    conv = convert_graph(data)
    fn = jax.jit(lambda i, m: conv(input_ids=i, attention_mask=m)["logits"])

    for B, T, pad in ((2, 16, 6), (3, 24, 0)):
        g = torch.Generator().manual_seed(B * 100 + T)
        ids = torch.randint(0, 512, (B, T), generator=g)
        mask = torch.ones(B, T, dtype=torch.long)
        if pad:
            mask[-1, -pad:] = 0
        with torch.no_grad():
            want = model(ids, mask).numpy()
        got = np.asarray(fn(ids.numpy(), mask.numpy()))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_sentence_transformer_head_export_parity():
    """An EIGHTH real-export family: the sentence-transformer serving form —
    encoder + masked mean pooling + L2 normalization exported as ONE graph
    (the shape HuggingFaceSentenceEmbedder's ONNX deployments ship in)."""
    import io

    import torch.nn as tnn

    from synapseml_tpu.onnx import convert_graph

    class SentenceModel(tnn.Module):
        def __init__(self):
            super().__init__()
            torch.manual_seed(3)
            self.encoder = TorchBertEncoder(vocab=128, hidden=32, heads=2,
                                            layers=1, mlp=64, max_len=64,
                                            num_classes=3)

        def forward(self, input_ids, attention_mask):
            # reuse the encoder body up to the hidden states: emulate by
            # running embeddings+layers (the encoder's features path)
            h = self.encoder.features(input_ids, attention_mask)
            m = attention_mask.unsqueeze(-1).to(h.dtype)
            pooled = (h * m).sum(1) / m.sum(1).clamp(min=1e-9)
            return tnn.functional.normalize(pooled, p=2, dim=1)

    model = SentenceModel().eval()
    ids = torch.randint(0, 128, (3, 12))
    mask = torch.ones(3, 12, dtype=torch.long)
    mask[2, 7:] = 0
    buf = io.BytesIO()
    torch.onnx.export(model, (ids, mask), buf,
                      input_names=["input_ids", "attention_mask"],
                      output_names=["embedding"], dynamo=False)
    with torch.no_grad():
        want = model(ids, mask).numpy()
    conv = convert_graph(buf.getvalue())
    got = np.asarray(conv(input_ids=ids.numpy().astype(np.int64),
                          attention_mask=mask.numpy().astype(np.int64))
                     ["embedding"])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.linalg.norm(got, axis=1), 1.0, rtol=1e-5)
