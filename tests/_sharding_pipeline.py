"""Shared servable sharded-LM pipeline for the sharding-plane tests and
the sharded publish->load->serve round trips: JSON request bodies ->
prompt column -> HuggingFaceCausalLM (llama-tiny; ``model_params=None``
random-inits from ``PRNGKey(0)``, so every fresh load of the artifact
holds byte-identical weights) -> reply dicts. Module-level classes so
publish/load round-trips by class reference across processes."""

import numpy as np

from synapseml_tpu.core.pipeline import PipelineModel, Transformer


class BodyToPrompt(Transformer):
    """Parsed request bodies (``{"prompt": "..."}``) -> a ``prompt``
    column."""

    def _transform(self, df):
        def per_part(p):
            out = dict(p)
            out["prompt"] = np.asarray(
                [b.get("prompt", "") if isinstance(b, dict) else str(b)
                 for b in p["body"]], dtype=object)
            return out

        return df.map_partitions(per_part)


class CompletionToReply(Transformer):
    """Generated token-id rows -> one JSON-able reply dict per request."""

    def _transform(self, df):
        def per_part(p):
            out = dict(p)
            out["reply"] = np.asarray(
                [{"tokens": [int(t) for t in np.asarray(c).ravel()]}
                 for c in p["completions"]], dtype=object)
            return out

        return df.map_partitions(per_part)


def make_lm_pipeline(mesh_config=None, partition_rules=None,
                     max_new_tokens=4):
    from synapseml_tpu.hf import HuggingFaceCausalLM

    lm = HuggingFaceCausalLM(model_name="llama-tiny",
                             max_new_tokens=max_new_tokens,
                             prompt_bucket=8, batch_size=4)
    if mesh_config is not None:
        lm.set(mesh_config=mesh_config)
    if partition_rules is not None:
        lm.set(partition_rules=partition_rules)
    return PipelineModel([BodyToPrompt(), lm, CompletionToReply()])


def prompt_rows(n, seed=0):
    rs = np.random.default_rng(seed)
    words = ["alpha", "beta", "gamma", "delta", "omega", "zeta"]
    return [{"prompt": " ".join(rs.choice(words, size=3))}
            for _ in range(n)]
