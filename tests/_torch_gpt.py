"""Test helper: GPT-2-style causal decoder for REAL ``torch.onnx.export`` →
converter parity. Complements ``_torch_bert.py`` with the DECODER-side export
surface: Trilu causal masks (``torch.tril``), masked_fill → Where/Not chains,
GatherElements (``torch.gather``), Slice/chunk QKV splits, and the shape-guard
``If`` nodes the TorchScript exporter emits around dynamic dims."""

from __future__ import annotations

import io
import math

import torch
from torch import nn

from _torch_resnet import _install_onnx_shim


class CausalBlock(nn.Module):
    def __init__(self, d: int, h: int):
        super().__init__()
        self.h, self.dk = h, d // h
        self.qkv = nn.Linear(d, 3 * d)
        self.o = nn.Linear(d, d)
        self.ln1, self.ln2 = nn.LayerNorm(d), nn.LayerNorm(d)
        self.mlp = nn.Sequential(nn.Linear(d, 4 * d), nn.GELU(),
                                 nn.Linear(4 * d, d))

    def forward(self, x):
        B, T, D = x.size(0), x.size(1), x.size(2)
        q, k, v = self.qkv(self.ln1(x)).chunk(3, dim=-1)

        def sp(t):
            return t.view(B, T, self.h, self.dk).transpose(1, 2)

        q, k, v = sp(q), sp(k), sp(v)
        att = (q @ k.transpose(-2, -1)) / math.sqrt(self.dk)
        mask = torch.tril(torch.ones(T, T, dtype=torch.bool, device=x.device))
        att = att.masked_fill(~mask, float("-inf"))   # Not + Where export
        y = torch.softmax(att, dim=-1) @ v
        y = y.transpose(1, 2).reshape(B, T, D)
        x = x + self.o(y)
        return x + self.mlp(self.ln2(x))


class TorchTinyGPT(nn.Module):
    def __init__(self, vocab: int = 256, d: int = 32, layers: int = 2,
                 heads: int = 2, max_len: int = 64):
        super().__init__()
        self.tok = nn.Embedding(vocab, d)
        self.pos = nn.Embedding(max_len, d)
        self.blocks = nn.ModuleList(
            CausalBlock(d, heads) for _ in range(layers))
        self.lnf = nn.LayerNorm(d)
        self.head = nn.Linear(d, vocab, bias=False)

    def forward(self, ids, gather_idx):
        T = ids.size(1)
        x = self.tok(ids) + self.pos(
            torch.arange(T, device=ids.device)).unsqueeze(0)
        for b in self.blocks:
            x = b(x)
        logits = self.head(self.lnf(x))
        # per-row logits at each row's own position: torch.gather exports
        # GatherElements (the last-valid-token pick every batched LM does)
        idx = gather_idx.unsqueeze(-1).unsqueeze(-1).expand(
            -1, 1, logits.size(-1))
        return torch.gather(logits, 1, idx).squeeze(1)


def export_gpt_onnx_bytes(model: nn.Module, ids: torch.Tensor,
                          gather_idx: torch.Tensor) -> bytes:
    _install_onnx_shim()
    model.eval()
    buf = io.BytesIO()
    torch.onnx.export(
        model, (ids, gather_idx), buf, dynamo=False,
        input_names=["ids", "gather_idx"], output_names=["logits"],
        dynamic_axes={"ids": {0: "N", 1: "T"}, "gather_idx": {0: "N"},
                      "logits": {0: "N"}})
    return buf.getvalue()
