"""Featurize module tests (reference featurize suites)."""

import numpy as np
import pytest

from synapseml_tpu.core import DataFrame, Pipeline, load_stage
from synapseml_tpu.featurize import (
    CleanMissingData,
    CountSelector,
    DataConversion,
    Featurize,
    IndexToValue,
    MultiNGram,
    PageSplitter,
    TextFeaturizer,
    ValueIndexer,
)


def test_clean_missing_data():
    df = DataFrame.from_dict({"x": np.array([1.0, np.nan, 3.0]),
                              "y": np.array([np.nan, 2.0, 4.0])})
    m = CleanMissingData(input_cols=["x", "y"], cleaning_mode="Mean").fit(df)
    out = m.transform(df)
    np.testing.assert_allclose(out.collect_column("x"), [1, 2, 3])
    np.testing.assert_allclose(out.collect_column("y"), [3, 2, 4])
    med = CleanMissingData(input_cols=["x"], cleaning_mode="Median").fit(df).transform(df)
    np.testing.assert_allclose(med.collect_column("x"), [1, 2, 3])
    cust = (CleanMissingData(input_cols=["x"], cleaning_mode="Custom", custom_value=-1)
            .fit(df).transform(df))
    np.testing.assert_allclose(cust.collect_column("x"), [1, -1, 3])


def test_data_conversion():
    df = DataFrame.from_dict({"x": np.array([1.7, 2.2]), "s": ["3", "4"]})
    out = DataConversion(cols=["x"], convert_to="integer").transform(df)
    assert out.collect_column("x").dtype == np.int32
    out2 = DataConversion(cols=["s"], convert_to="double").transform(df)
    np.testing.assert_allclose(out2.collect_column("s"), [3.0, 4.0])
    cat = DataConversion(cols=["s"], convert_to="toCategorical").transform(df)
    np.testing.assert_array_equal(cat.collect_column("s"), [0, 1])


def test_value_indexer_roundtrip(tmp_path):
    df = DataFrame.from_dict({"c": ["b", "a", "b", "c"]})
    model = ValueIndexer(input_col="c", output_col="i").fit(df)
    out = model.transform(df)
    np.testing.assert_array_equal(out.collect_column("i"), [1, 0, 1, 2])
    inv = IndexToValue(input_col="i", output_col="back", levels=model.get("levels"))
    assert list(inv.transform(out).collect_column("back")) == ["b", "a", "b", "c"]
    # unseen value errors by default, tolerated with unknown_index
    df2 = DataFrame.from_dict({"c": ["z"]})
    with pytest.raises(ValueError):
        model.transform(df2)
    model.set(unknown_index=0)
    assert model.transform(df2).collect_column("i")[0] == 0
    model.save(str(tmp_path / "vi"))
    np.testing.assert_array_equal(
        load_stage(str(tmp_path / "vi")).transform(df).collect_column("i"), [1, 0, 1, 2])


def test_count_selector():
    X = np.array([[1.0, 0.0, 2.0], [3.0, 0.0, 0.0]], np.float32)
    df = DataFrame.from_dict({"features": X})
    m = CountSelector().fit(df)
    out = np.stack(list(m.transform(df).collect_column("features")))
    assert out.shape == (2, 2)
    np.testing.assert_allclose(out, [[1, 2], [3, 0]])


def test_featurize_mixed():
    df = DataFrame.from_dict({
        "num": np.array([1.0, np.nan, 3.0, 4.0]),
        "cat": ["red", "blue", "red", "green"],
        "vec": np.ones((4, 2), np.float32),
    })
    model = Featurize(input_cols=["num", "cat", "vec"]).fit(df)
    out = model.transform(df)
    X = np.stack(list(out.collect_column("features")))
    # 1 numeric + 3 onehot + 2 vec = 6
    assert X.shape == (4, 6)
    assert X[1, 0] == pytest.approx((1 + 3 + 4) / 3)  # imputed mean
    assert X[:, 1:4].sum() == 4  # one-hot rows sum to 1
    assert model.feature_dim == 6


def test_featurize_high_cardinality_hashing():
    vals = [f"user_{i}" for i in range(100)]
    df = DataFrame.from_dict({"id": vals})
    model = Featurize(input_cols=["id"], max_one_hot_cardinality=10,
                      num_features=64).fit(df)
    X = np.stack(list(model.transform(df).collect_column("features")))
    assert X.shape == (100, 64)
    assert (X.sum(axis=1) == 1).all()


def test_text_featurizer_idf():
    df = DataFrame.from_dict({"text": ["the cat sat", "the dog ran", "cat and dog"]})
    model = TextFeaturizer(input_col="text", num_features=256).fit(df)
    X = np.stack(list(model.transform(df).collect_column("features")))
    assert X.shape == (3, 256)
    # 'the' (df=2) weighs less than 'sat' (df=1)
    no_idf = TextFeaturizer(input_col="text", num_features=256, use_idf=False).fit(df)
    X0 = np.stack(list(no_idf.transform(df).collect_column("features")))
    assert (X0 >= 0).all() and X0.max() == 1.0


def test_text_featurizer_in_pipeline_with_vw():
    from synapseml_tpu.stages import UDFTransformer

    texts = (["good great excellent"] * 30) + (["bad awful terrible"] * 30)
    labels = np.array([1] * 30 + [0] * 30)
    df = DataFrame.from_dict({"text": texts, "label": labels})
    tf = TextFeaturizer(input_col="text", output_col="features", num_features=128)
    model = tf.fit(df)
    out = model.transform(df)
    X = np.stack(list(out.collect_column("features")))
    from sklearn.linear_model import LogisticRegression

    assert LogisticRegression().fit(X, labels).score(X, labels) == 1.0


def test_page_splitter():
    text = "word " * 100  # 500 chars
    df = DataFrame.from_dict({"text": [text.strip()]})
    out = PageSplitter(maximum_page_length=120, minimum_page_length=80).transform(df)
    pages = out.collect_column("pages")[0]
    assert all(len(p) <= 120 for p in pages)
    assert "".join(pages) == text.strip()


def test_multi_ngram():
    df = DataFrame.from_dict({"tokens": [["a", "b", "c"]]})
    out = MultiNGram(lengths=[1, 2]).transform(df)
    assert list(out.collect_column("ngrams")[0]) == ["a", "b", "c", "a b", "b c"]
