"""Round-2 cognitive service breadth (form/vision/face/anomaly/geospatial/
speech/aifoundry/langchain) against a local mock server — the reference tests
these against live Azure endpoints (``CognitiveServicesCommon``); the mock
keeps the same request/response shapes."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from synapseml_tpu.core import DataFrame
from synapseml_tpu.services import (
    AddressGeocoder,
    AIFoundryChatCompletion,
    AnalyzeDocument,
    AnalyzeImage,
    AnalyzeInvoices,
    CheckPointInPolygon,
    DescribeImage,
    DetectAnomalies,
    DetectFace,
    DetectLastAnomaly,
    DetectMultivariateAnomaly,
    FitMultivariateAnomaly,
    FormOntologyLearner,
    GenerateThumbnails,
    LangChainTransformer,
    ReadImage,
    ReverseAddressGeocoder,
    SimpleDetectAnomalies,
    SpeechToText,
    TextToSpeech,
    VerifyFaces,
)


class Handler(BaseHTTPRequestHandler):
    lro: dict = {}

    def log_message(self, *a):
        pass

    def _json(self, payload, status=200, headers=None):
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _bytes(self, data, status=200):
        self.send_response(status)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _body(self):
        n = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(n) if n else b""

    def do_GET(self):  # noqa: N802
        p = self.path.split("?")[0]
        if p.startswith("/lro/"):
            op = p.rsplit("/", 1)[-1]
            n = Handler.lro.get(op, 0)
            Handler.lro[op] = n + 1
            if n < 1:
                return self._json({"status": "running"})
            if op.startswith("mvdetect"):
                return self._json({"summary": {"status": "READY"},
                                   "results": [{"timestamp": "t0", "value": {
                                       "isAnomaly": False}}]})
            if op.startswith("form"):
                return self._json({
                    "status": "succeeded",
                    "analyzeResult": {"content": "INVOICE #42", "documents": [
                        {"fields": {"Total": {"type": "number", "valueNumber": 42.5},
                                    "Vendor": {"type": "string",
                                               "valueString": "Tailspin"}}}]}})
            return self._json({"status": "succeeded",
                               "analyzeResult": {"readResults": [
                                   {"lines": [{"text": "hello"}]}]}})
        if "/search/address/reverse/json" in p:
            return self._json({"addresses": [{"address": {"freeformAddress": "1 Main St"}}]})
        if "/search/address/json" in p:
            return self._json({"results": [{"position": {"lat": 47.6, "lon": -122.1}}]})
        if "/spatial/pointInPolygon/json" in p:
            return self._json({"result": {"pointInPolygons": True}})
        if "/multivariate/models/" in p:
            return self._json({"modelInfo": {"status": "READY"}})
        return self._json({"error": f"unknown GET {p}"}, 404)

    def do_POST(self):  # noqa: N802
        p = self.path.split("?")[0]
        body = self._body()
        host = f"http://{self.headers.get('Host')}"
        if "documentModels/" in p and ":analyze" in p:
            op = "form1"
            Handler.lro.setdefault(op, 0)
            return self._json({}, 202,
                              {"Operation-Location": f"{host}/lro/{op}"})
        if p.endswith("/vision/v3.2/analyze"):
            assert json.loads(body)["url"]
            return self._json({"tags": [{"name": "cat", "confidence": 0.99}]})
        if p.endswith("/vision/v3.2/describe"):
            return self._json({"description": {"captions": [{"text": "a cat"}]}})
        if p.endswith("/vision/v3.2/read/analyze"):
            op = "read1"
            Handler.lro.setdefault(op, 0)
            return self._json({}, 202,
                              {"Operation-Location": f"{host}/lro/{op}"})
        if "/vision/v3.2/generateThumbnail" in p:
            return self._bytes(b"\x89PNGfake")
        if p.endswith("/face/v1.0/detect"):
            return self._json([{"faceId": "f-1", "faceRectangle": {"top": 1}}])
        if p.endswith("/face/v1.0/verify"):
            b = json.loads(body)
            return self._json({"isIdentical": b["faceId1"] == b["faceId2"],
                               "confidence": 0.9})
        if p.endswith("/timeseries/last/detect"):
            return self._json({"isAnomaly": True, "expectedValue": 1.0})
        if p.endswith("/timeseries/entire/detect"):
            n = len(json.loads(body)["series"])
            flags = [i == n - 1 for i in range(n)]
            return self._json({"isAnomaly": flags})
        if p.endswith("/multivariate/models"):
            return self._json({"modelId": "mv-7"}, 201,
                              {"Location": f"{host}/multivariate/models/mv-7"})
        if "/multivariate/models/" in p and p.endswith("/detect"):
            # real API: 201/202 with the result job URL in Location (NOT
            # Operation-Location) — exercises DetectMultivariateAnomaly's
            # poll_location override
            op = "mvdetect"
            Handler.lro.setdefault(op, 0)
            return self._json({}, 202, {"Location": f"{host}/lro/{op}"})
        if "/speech/recognition/" in p:
            assert body == b"RIFFaudio"
            return self._json({"RecognitionStatus": "Success",
                               "DisplayText": "hello world"})
        if p.endswith("/cognitiveservices/v1"):  # TTS
            assert b"<speak" in body
            return self._bytes(b"RIFFsynth")
        if p.endswith("/openai/responses"):
            body_j = json.loads(body)
            assert "input" in body_j
            return self._json({"output": [{"content": [
                {"type": "output_text", "text": "resp: ok"}]}]})
        if p.endswith("/chat/completions"):
            assert self.headers.get("Authorization") == "Bearer k"
            return self._json({"choices": [{"message": {
                "content": "foundry says hi"}}]})
        return self._json({"error": f"unknown POST {p}"}, 404)


@pytest.fixture(scope="module")
def server():
    Handler.lro = {}
    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()


def test_analyze_document_lro_and_ontology(server):
    df = DataFrame.from_rows([{"doc": "https://x/invoice.pdf"}])
    az = AnalyzeDocument(url=server, subscription_key="k", image_url_col="doc",
                         polling_interval_s=0.01)
    out = az.transform(df)
    res = list(out.collect_column("out"))[0]
    assert res["content"] == "INVOICE #42"
    assert list(out.collect_column("errors"))[0] is None

    learner = FormOntologyLearner(input_col="out", output_col="onto")
    model = learner.fit(out)
    onto = model.get("ontology")
    assert set(onto) == {"Total", "Vendor"} and onto["Total"] == "number"
    proj = list(model.transform(out).collect_column("onto"))[0]
    assert proj == {"Total": 42.5, "Vendor": "Tailspin"}


def test_analyze_invoices_bytes_input(server):
    Handler.lro["form1"] = 0
    df = DataFrame.from_rows([{"raw": b"%PDF-fake"}])
    az = AnalyzeInvoices(url=server, subscription_key="k", image_bytes_col="raw",
                         polling_interval_s=0.01)
    res = list(az.transform(df).collect_column("out"))[0]
    assert "documents" in res


def test_vision_family(server):
    df = DataFrame.from_rows([{"img": "https://x/cat.png"}])
    tags = list(AnalyzeImage(url=server, subscription_key="k",
                             image_url_col="img").transform(df)
                .collect_column("out"))[0]
    assert tags["tags"][0]["name"] == "cat"
    desc = list(DescribeImage(url=server, subscription_key="k",
                              image_url_col="img").transform(df)
                .collect_column("out"))[0]
    assert desc["captions"][0]["text"] == "a cat"
    read = list(ReadImage(url=server, subscription_key="k", image_url_col="img",
                          polling_interval_s=0.01).transform(df)
                .collect_column("out"))[0]
    assert read["readResults"][0]["lines"][0]["text"] == "hello"
    thumb = list(GenerateThumbnails(url=server, subscription_key="k",
                                    image_url_col="img").transform(df)
                 .collect_column("out"))[0]
    assert thumb.startswith(b"\x89PNG")


def test_face_family(server):
    df = DataFrame.from_rows([{"url": "https://x/face.png"}])
    det = list(DetectFace(url=server, subscription_key="k").transform(df)
               .collect_column("out"))[0]
    assert det[0]["faceId"] == "f-1"
    df2 = DataFrame.from_rows([{"faceId1": "a", "faceId2": "a"},
                               {"faceId1": "a", "faceId2": "b"}])
    ver = list(VerifyFaces(url=server, subscription_key="k").transform(df2)
               .collect_column("out"))
    assert ver[0]["isIdentical"] is True and ver[1]["isIdentical"] is False


def test_anomaly_family(server):
    series = [{"timestamp": f"2024-01-0{i+1}T00:00:00Z", "value": float(i)}
              for i in range(4)]
    df = DataFrame.from_rows([{"series": series}])
    last = list(DetectLastAnomaly(url=server, subscription_key="k")
                .transform(df).collect_column("out"))[0]
    assert last["isAnomaly"] is True
    ent = list(DetectAnomalies(url=server, subscription_key="k")
               .transform(df).collect_column("out"))[0]
    assert ent["isAnomaly"] == [False, False, False, True]

    rows = [{"group": "g1", "timestamp": s["timestamp"], "value": s["value"]}
            for s in series]
    sdf = DataFrame.from_rows(rows)
    sda = SimpleDetectAnomalies(url=server, subscription_key="k",
                                output_col="isAnomaly")
    out = sda.transform(sdf)
    flags = list(out.collect_column("isAnomaly"))
    assert flags == [False, False, False, True]


def test_multivariate_anomaly_fit_detect(server):
    est = FitMultivariateAnomaly(url=server, subscription_key="k",
                                 source="https://blob/sas", polling_interval_s=0.01,
                                 start_time="2024-01-01T00:00:00Z",
                                 end_time="2024-02-01T00:00:00Z")
    model = est.fit(DataFrame.from_rows([{"x": 1}]))
    assert isinstance(model, DetectMultivariateAnomaly)
    assert model.get("model_id") == "mv-7"
    df = DataFrame.from_rows([{"source": "https://blob/sas2",
                               "startTime": "t0", "endTime": "t1"}])
    res = list(model.transform(df).collect_column("out"))[0]
    assert res[0]["value"]["isAnomaly"] is False


def test_geospatial_family(server):
    df = DataFrame.from_rows([{"address": "1 Main St, Seattle"}])
    geo = list(AddressGeocoder(url=server, subscription_key="k").transform(df)
               .collect_column("out"))[0]
    assert geo[0]["position"]["lat"] == 47.6
    df2 = DataFrame.from_rows([{"lat": 47.6, "lon": -122.1}])
    rev = list(ReverseAddressGeocoder(url=server, subscription_key="k")
               .transform(df2).collect_column("out"))[0]
    assert rev[0]["address"]["freeformAddress"] == "1 Main St"
    pip_ = list(CheckPointInPolygon(url=server, subscription_key="k",
                                    user_data_id="u1").transform(df2)
                .collect_column("out"))[0]
    assert pip_["pointInPolygons"] is True


def test_speech_family(server):
    df = DataFrame.from_rows([{"audio": b"RIFFaudio"}])
    stt = list(SpeechToText(url=server, subscription_key="k").transform(df)
               .collect_column("out"))[0]
    assert stt["DisplayText"] == "hello world"
    df2 = DataFrame.from_rows([{"text": "hi <there>"}])
    tts = list(TextToSpeech(url=server, subscription_key="k").transform(df2)
               .collect_column("out"))[0]
    assert tts == b"RIFFsynth"


def test_aifoundry_chat(server):
    df = DataFrame.from_rows([{"messages": [{"role": "user", "content": "hi"}]}])
    out = list(AIFoundryChatCompletion(url=server, subscription_key="k",
                                       model="m1").transform(df)
               .collect_column("chat_completions"))[0]
    assert out == "foundry says hi"


def test_langchain_transformer():
    class FakeChain:
        def invoke(self, text):
            if "boom" in text:
                raise RuntimeError("chain exploded")
            return text.upper()

    df = DataFrame.from_rows([{"text": "hello"}, {"text": "boom"}])
    out = LangChainTransformer(chain=FakeChain()).transform(df)
    vals = list(out.collect_column("out"))
    errs = list(out.collect_column("errors"))
    assert vals[0] == "HELLO" and vals[1] is None
    assert errs[0] is None and "chain exploded" in errs[1]


def test_missing_image_input_raises(server):
    df = DataFrame.from_rows([{"img": "x"}])
    with pytest.raises(ValueError, match="image_url_col or"):
        AnalyzeImage(url=server, subscription_key="k").transform(df)


def test_openai_responses(server):
    from synapseml_tpu.services import OpenAIResponses

    df = DataFrame.from_rows([{"input": "hello"},
                              {"input": [{"role": "user", "content": "hi"}]}])
    out = OpenAIResponses(url=server, subscription_key="k",
                          deployment_name="d").transform(df)
    vals = list(out.collect_column("responses"))
    assert vals == ["resp: ok", "resp: ok"]
