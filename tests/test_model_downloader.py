"""ModelDownloader (reference ``synapse/ml/downloader/ModelDownloader.py``):
local checkpoint enumeration, remote index + fetch with sha256
verification against an in-process mock repository, and the downloaded
model feeding straight into checkpoint ingestion."""

import hashlib
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from synapseml_tpu.models import ModelDownloader, ModelSchema

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def model_repo(tmp_path_factory):
    """A mock model server with one tiny GPT-2 checkpoint in its index."""
    from transformers import GPT2Config, GPT2LMHeadModel

    src = tmp_path_factory.mktemp("repo") / "gpt2-nano"
    torch.manual_seed(0)
    cfg = GPT2Config(vocab_size=61, n_embd=32, n_layer=1, n_head=4,
                     n_positions=48)
    m = GPT2LMHeadModel(cfg).eval()
    m.save_pretrained(src, safe_serialization=True)
    cfg.save_pretrained(src)
    files = sorted(p.name for p in src.iterdir() if p.is_file())
    digests = {f: hashlib.sha256((src / f).read_bytes()).hexdigest()
               for f in files}
    index = [ModelSchema(name="gpt2-nano", kind="causal-lm", files=files,
                         sha256=digests,
                         size_bytes=sum((src / f).stat().st_size
                                        for f in files)).to_dict()]

    class Repo(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.path == "/index.json":
                body = json.dumps(index).encode()
            else:
                name = self.path.strip("/").split("/", 1)[-1]
                target = src / name
                if not target.is_file():
                    self.send_error(404)
                    return
                body = target.read_bytes()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Repo)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}", src
    srv.shutdown()


def test_remote_index_and_download(model_repo, tmp_path):
    url, _src = model_repo
    dl = ModelDownloader(str(tmp_path / "cache"), server_url=url)
    remote = dl.remote_models()
    assert [s.name for s in remote] == ["gpt2-nano"]
    local = dl.download_by_name("gpt2-nano")
    assert local.uri.endswith("gpt2-nano")

    # the downloaded checkpoint ingests through the normal pretrained path
    import jax.numpy as jnp

    from synapseml_tpu.models.convert_hf import pretrained_causal_lm
    from synapseml_tpu.models.flax_nets.llama import LlamaLM

    cfg, params = pretrained_causal_lm(local.uri, dtype=jnp.float32)
    logits = LlamaLM(cfg).apply(
        {"params": params}, jnp.zeros((1, 4), jnp.int32))
    assert logits.shape == (1, 4, 61)

    # and local_models() now lists it
    names = [s.name for s in dl.local_models()]
    assert "gpt2-nano" in names


def test_sha256_mismatch_rejected(model_repo, tmp_path):
    url, _ = model_repo
    dl = ModelDownloader(str(tmp_path / "cache2"), server_url=url)
    (schema,) = dl.remote_models()
    bad = dict(schema.sha256)
    bad[schema.files[0]] = "0" * 64
    import dataclasses

    with pytest.raises(RuntimeError, match="sha256 mismatch"):
        dl.download_model(dataclasses.replace(schema, sha256=bad))


def test_path_traversal_from_index_rejected(model_repo, tmp_path):
    # the remote index is UNTRUSTED: names/files must not escape the cache
    url, _ = model_repo
    dl = ModelDownloader(str(tmp_path / "cache3"), server_url=url)
    evil_name = ModelSchema(name="../evil", files=("config.json",))
    with pytest.raises(ValueError, match="escapes|relative"):
        dl.download_model(evil_name)
    evil_file = ModelSchema(name="ok", files=("../../evil.txt",))
    with pytest.raises(ValueError, match="escapes|relative"):
        dl.download_model(evil_file)
    assert not (tmp_path / "evil.txt").exists()


def test_sha256_failure_leaves_no_partial_model(model_repo, tmp_path):
    url, _ = model_repo
    cache = tmp_path / "cache4"
    dl = ModelDownloader(str(cache), server_url=url)
    (schema,) = dl.remote_models()
    bad = dict(schema.sha256)
    bad[schema.files[-1]] = "0" * 64  # last file fails AFTER earlier ones
    import dataclasses

    with pytest.raises(RuntimeError, match="sha256 mismatch"):
        dl.download_model(dataclasses.replace(schema, sha256=bad))
    # nothing staged, nothing half-installed, nothing listed
    assert not (cache / schema.name).exists()
    assert not (cache / (schema.name + ".staging")).exists()
    assert list(dl.local_models()) == []


def test_http_404_is_not_reported_as_unreachable(model_repo, tmp_path):
    url, _ = model_repo
    dl = ModelDownloader(str(tmp_path / "cache5"), server_url=url)
    schema = ModelSchema(name="gpt2-nano", files=("no_such_file.bin",))
    with pytest.raises(RuntimeError, match="returned 404"):
        dl.download_model(schema)


def test_zero_egress_error_is_actionable(tmp_path):
    dl = ModelDownloader(str(tmp_path), server_url="http://127.0.0.1:9",
                         timeout_s=0.5)
    with pytest.raises(RuntimeError, match="local_models"):
        dl.remote_models()


def test_local_models_empty_cache(tmp_path):
    dl = ModelDownloader(str(tmp_path / "fresh"))
    assert list(dl.local_models()) == []
    with pytest.raises(ValueError, match="server_url"):
        dl.remote_models()


def test_safe_path_rejects_cache_root_itself(tmp_path):
    """A remote index name of '', '.' or 'x/..' must not resolve to the
    cache root — download_model's pre-replace rmtree would then delete the
    ENTIRE local model cache (ADVICE r5 medium)."""
    dl = ModelDownloader(str(tmp_path / "cache6"))
    for name in ("", ".", "x/.."):
        with pytest.raises(ValueError):
            dl._safe_path(name)
    assert dl._safe_path("gpt2-nano").endswith("gpt2-nano")
