"""VW-equivalent module tests (featurizer, learners, bandits, policy eval)."""

import numpy as np
import pytest

from synapseml_tpu.core import DataFrame
from synapseml_tpu.vw import (
    VowpalWabbitClassificationModel,
    VowpalWabbitClassifier,
    VowpalWabbitContextualBandit,
    VowpalWabbitCSETransformer,
    VowpalWabbitDSJsonTransformer,
    VowpalWabbitFeaturizer,
    VowpalWabbitGeneric,
    VowpalWabbitRegressor,
    cressie_read,
    ips,
    snips,
)
from synapseml_tpu.vw.hashing import hash_feature, murmur3_32
from synapseml_tpu.vw.learner import LinearConfig, train_linear
from synapseml_tpu.vw.policyeval import KahanSum, cressie_read_interval


class TestHashing:
    def test_murmur3_reference_vectors(self):
        # public murmur3_32 test vectors
        assert murmur3_32(b"", 0) == 0
        assert murmur3_32(b"hello", 0) == 0x248BFA47
        assert murmur3_32(b"hello, world", 0) == 0x149BBB7F
        assert murmur3_32(b"", 1) == 0x514E28B7

    def test_hash_feature_bits(self):
        for bits in (10, 18, 24):
            assert 0 <= hash_feature("foo", "ns", bits) < (1 << bits)

    def test_namespace_changes_hash(self):
        assert hash_feature("f", "a") != hash_feature("f", "b")


class TestFeaturizer:
    def test_mixed_types(self):
        df = DataFrame.from_dict({
            "num": [1.5, 0.0, -2.0],
            "cat": ["a", "b", "a"],
            "flag": [True, False, True],
        })
        out = VowpalWabbitFeaturizer(input_cols=["num", "cat", "flag"]).transform(df)
        idx = out.collect_column("features_indices")
        val = out.collect_column("features_values")
        assert idx.shape == val.shape
        # row 0: num + cat + flag = 3 features; row 1: num==0 dropped, flag False dropped
        assert (val[0] != 0).sum() == 3
        assert (val[1] != 0).sum() == 1

    def test_string_split(self):
        df = DataFrame.from_dict({"text": ["good great", "bad"]})
        out = VowpalWabbitFeaturizer(input_cols=["text"],
                                     string_split_cols=["text"]).transform(df)
        assert (out.collect_column("features_values")[0] != 0).sum() == 2

    def test_array_and_dict_columns(self):
        df = DataFrame.from_rows([
            {"vec": [1.0, 0.0, 2.0], "m": {"k1": 3.0, "k2": "x"}},
            {"vec": [0.0, 1.0, 0.0], "m": {"k1": 1.0}},
        ])
        out = VowpalWabbitFeaturizer(input_cols=["vec", "m"]).transform(df)
        assert (out.collect_column("features_values")[0] != 0).sum() == 4  # 2 vec + 2 map

    def test_global_padding_consistent_across_partitions(self):
        df = DataFrame.from_dict({"t": ["a b c d", "a"]}, num_partitions=2)
        out = VowpalWabbitFeaturizer(input_cols=["t"], string_split_cols=["t"]).transform(df)
        assert out.collect_column("features_indices").shape[1] == 4


@pytest.fixture(scope="module")
def vw_binary():
    rng = np.random.default_rng(3)
    n = 1500
    x1, x2 = rng.normal(size=n), rng.normal(size=n)
    y = ((2 * x1 - x2) > 0).astype(int)
    df = DataFrame.from_dict({"x1": x1, "x2": x2, "label": y}, num_partitions=2)
    fdf = VowpalWabbitFeaturizer(input_cols=["x1", "x2"]).transform(df)
    return fdf, y


class TestLearners:
    def test_classifier_gate(self, vw_binary):
        fdf, y = vw_binary
        model = VowpalWabbitClassifier(num_passes=4).fit(fdf)
        out = model.transform(fdf)
        assert (out.collect_column("prediction") == y).mean() > 0.9
        assert {"probability", "rawPrediction"} <= set(out.columns)

    def test_classifier_save_load(self, vw_binary, tmp_path):
        fdf, y = vw_binary
        model = VowpalWabbitClassifier(num_passes=2).fit(fdf)
        model.save(str(tmp_path / "vw"))
        m2 = VowpalWabbitClassificationModel.load(str(tmp_path / "vw"))
        np.testing.assert_allclose(m2.transform(fdf).collect_column("probability"),
                                   model.transform(fdf).collect_column("probability"))

    def test_regressor_gate(self):
        rng = np.random.default_rng(4)
        n = 1200
        x1, x2 = rng.normal(size=n), rng.normal(size=n)
        y = 3 * x1 - 2 * x2 + rng.normal(scale=0.05, size=n)
        df = DataFrame.from_dict({"x1": x1, "x2": x2, "label": y})
        fdf = VowpalWabbitFeaturizer(input_cols=["x1", "x2"]).transform(df)
        pred = VowpalWabbitRegressor(num_passes=5).fit(fdf).transform(fdf)
        assert np.corrcoef(pred.collect_column("prediction"), y)[0, 1] > 0.95

    def test_warm_start_initial_model(self, vw_binary):
        fdf, y = vw_binary
        m1 = VowpalWabbitClassifier(num_passes=1).fit(fdf)
        warm = VowpalWabbitClassifier(num_passes=1,
                                      initial_model=m1.get("model_weights")).fit(fdf)
        # warm-started model should beat or match the 1-pass model
        a1 = (m1.transform(fdf).collect_column("prediction") == y).mean()
        a2 = (warm.transform(fdf).collect_column("prediction") == y).mean()
        assert a2 >= a1 - 0.02

    def test_quantile_loss(self):
        # constant-feature fit converges to the tau quantile of the labels
        rng = np.random.default_rng(5)
        y = rng.normal(size=1200).astype(np.float32)
        n = len(y)
        idx = np.zeros((n, 1), np.int32)
        val = np.ones((n, 1), np.float32)
        for tau in (0.1, 0.9):
            cfg = LinearConfig(loss="quantile", quantile_tau=tau, num_passes=40,
                               learning_rate=0.5, adaptive=False, seed=1)
            w = train_linear(idx, val, y, cfg)
            target = np.quantile(y, tau)
            assert abs(float(w[0]) - target) < 0.25, (tau, float(w[0]), target)

    def test_generic_vw_text(self):
        rng = np.random.default_rng(6)
        x = rng.normal(size=600)
        y = (x > 0).astype(int)
        lines = [f"{1 if yi else -1} | x:{xi:.4f}" for yi, xi in zip(y, x)]
        df = DataFrame.from_dict({"input": lines})
        model = VowpalWabbitGeneric(loss_function="logistic", num_passes=4).fit(df)
        pred = model.transform(df).collect_column("prediction")
        assert (((pred > 0.5).astype(int)) == y).mean() > 0.9


class TestContextualBandit:
    def test_cb_learns_best_action(self):
        rng = np.random.default_rng(7)
        n, A, D = 1500, 3, 4
        sh_idx = np.tile(np.arange(5, dtype=np.int32), (n, 1))
        sh_val = rng.normal(size=(n, 5)).astype(np.float32)
        # action features identify the action
        a_idx = np.tile((np.arange(A * D, dtype=np.int32) + 100).reshape(A, D), (n, 1, 1))
        a_val = np.ones((n, A, D), np.float32)
        best = (sh_val[:, 0] > 0).astype(int)  # context decides best action (0 or 1)
        chosen = rng.integers(0, A, size=n)
        cost = np.where(chosen == best, 0.0, 1.0)
        df = DataFrame.from_dict({
            "shared_indices": sh_idx, "shared_values": sh_val,
            "features_indices": a_idx, "features_values": a_val,
            "chosenAction": chosen + 1, "cost": cost.astype(np.float64),
            "probability": np.full(n, 1.0 / A)})
        model = VowpalWabbitContextualBandit(num_passes=4).fit(df)
        out = model.transform(df)
        scores = out.collect_column("prediction")
        assert scores.shape == (n, A)
        # greedy action should match the context-dependent best often
        match = (out.collect_column("predictedAction") - 1 == best).mean()
        assert match > 0.6

    def test_parallel_fit(self):
        rng = np.random.default_rng(8)
        n, A, D = 200, 2, 3
        df = DataFrame.from_dict({
            "shared_indices": np.tile(np.arange(4, dtype=np.int32), (n, 1)),
            "shared_values": rng.normal(size=(n, 4)).astype(np.float32),
            "features_indices": np.tile(np.arange(A * D, dtype=np.int32).reshape(A, D), (n, 1, 1)),
            "features_values": np.ones((n, A, D), np.float32),
            "chosenAction": rng.integers(1, A + 1, size=n),
            "cost": rng.random(n), "probability": np.full(n, 0.5)})
        models = VowpalWabbitContextualBandit().parallel_fit(
            df, [{"learning_rate": 0.1}, {"learning_rate": 0.9}])
        assert len(models) == 2


class TestPolicyEval:
    def test_kahan(self):
        s = KahanSum()
        for _ in range(1000):
            s.add(0.1)
        assert abs(s.value - 100.0) < 1e-9

    def test_ips_snips_identity_policy(self):
        r = np.random.default_rng(9).random(1000)
        w = np.ones(1000)
        assert abs(ips(w, r) - r.mean()) < 1e-12
        assert abs(snips(w, r) - r.mean()) < 1e-12
        assert abs(cressie_read(w, r) - r.mean()) < 1e-6

    def test_cressie_read_shrinks_extremes(self):
        rng = np.random.default_rng(10)
        w = np.concatenate([np.ones(990), np.full(10, 50.0)])
        r = np.concatenate([np.full(990, 0.1), np.ones(10)])
        cr = cressie_read(w, r)
        # CR should land below the unstable IPS estimate
        assert cr < ips(w, r)

    def test_interval_contains_point(self):
        rng = np.random.default_rng(11)
        w = np.exp(rng.normal(scale=0.2, size=500))
        r = rng.random(500)
        lo, hi = cressie_read_interval(w, r)
        assert lo <= cressie_read(w, r) <= hi

    def test_cse_transformer(self):
        rng = np.random.default_rng(12)
        df = DataFrame.from_dict({
            "probLog": np.full(300, 0.5),
            "probPred": np.clip(rng.random(300), 0.05, 1.0),
            "reward": rng.random(300)})
        out = VowpalWabbitCSETransformer().transform(df)
        row = out.first()
        assert row["count"] == 300
        assert row["cressieReadLower"] <= row["cressieRead"] <= row["cressieReadUpper"]


class TestDSJson:
    def test_parse(self):
        lines = [
            '{"EventId":"a","_label_cost":-1,"_label_probability":0.8,"_labelIndex":1,'
            '"a":[2,1],"p":[0.8,0.2],"c":{"f":1}}',
            "not json",
        ]
        out = VowpalWabbitDSJsonTransformer().transform(
            DataFrame.from_dict({"value": lines}))
        assert out.count() == 1
        row = out.first()
        assert row["chosenAction"] == 2 and row["cost"] == -1.0 and row["actionCount"] == 2


# ---------------------------------------------------------------------------
# progressive mode + sync schedules (reference VowpalWabbitBaseProgressive,
# VowpalWabbitSyncSchedule.scala:72)
# ---------------------------------------------------------------------------

def _stream_data(n=400, seed=3):
    rs = np.random.default_rng(seed)
    X = rs.normal(size=(n, 4)).astype(np.float32)
    y = X @ np.array([1.0, -2.0, 0.5, 0.0], np.float32)
    idx = np.tile(np.arange(4, dtype=np.int32), (n, 1))
    return idx, X, y


def test_progressive_one_step_ahead_semantics():
    """batch_size=1 progressive == manual strictly-online SGD: every output
    is the prediction BEFORE that row's update."""
    from synapseml_tpu.vw.learner import LinearConfig, train_linear_progressive

    idx, val, y = _stream_data(60)
    cfg = LinearConfig(num_bits=4, loss="squared", learning_rate=0.3,
                       power_t=0.0, adaptive=False, batch_size=1)
    w, preds = train_linear_progressive(idx, val, y, cfg)

    wm = np.zeros(16, np.float32)
    want = []
    for i in range(60):
        p = float(val[i] @ wm[idx[i]])
        want.append(p)
        g = (p - y[i]) * val[i]
        np.add.at(wm, idx[i], -0.3 * g)
    np.testing.assert_allclose(preds, want, rtol=1e-4, atol=1e-4)
    assert preds[0] == 0.0  # first row predicted by the zero model
    # progressive loss improves over the stream
    early = float(np.mean((preds[:20] - y[:20]) ** 2))
    late = float(np.mean((preds[-20:] - y[-20:]) ** 2))
    assert late < early


def test_progressive_transformer_surface():
    import synapseml_tpu as st
    from synapseml_tpu.vw import VowpalWabbitProgressive

    idx, val, y = _stream_data(200)
    df = st.DataFrame.from_dict({"features_indices": idx, "features_values": val,
                                 "label": y}, num_partitions=3)
    prog = VowpalWabbitProgressive(num_bits=6, learning_rate=0.3, batch_size=8)
    out, model = prog.transform_progressive(df)
    preds = np.asarray(out.collect_column("progressive_prediction"))
    assert preds.shape == (200,)
    # the trained model scores better than the early progressive outputs
    scored = model.transform(df)
    final = np.asarray(scored.collect_column("prediction"))
    assert float(np.mean((final - y) ** 2)) < float(np.mean((preds[:50] - y[:50]) ** 2))
    # fit() alone returns the trained model too
    m2 = prog.fit(df)
    np.testing.assert_allclose(m2.get("model_weights"), model.get("model_weights"))


def test_sync_schedules_partitioned_training():
    from synapseml_tpu.vw import SyncSchedulePassBoundary, SyncScheduleRowCount
    from synapseml_tpu.vw.learner import LinearConfig, train_linear_partitioned

    idx, val, y = _stream_data(600, seed=4)
    parts = [(idx[i::3], val[i::3], y[i::3]) for i in range(3)]
    cfg = LinearConfig(num_bits=4, loss="squared", learning_rate=0.02,
                       power_t=0.0, adaptive=False, batch_size=8, num_passes=3)

    w_pass = train_linear_partitioned(parts, cfg, SyncSchedulePassBoundary())
    w_rows = train_linear_partitioned(parts, cfg, SyncScheduleRowCount(50))
    truth = np.zeros(16, np.float32)
    truth[:4] = [1.0, -2.0, 0.5, 0.0]
    # both schedules converge near the generating weights; more frequent sync
    # should do at least as well
    assert float(np.linalg.norm(w_pass - truth)) < 0.5
    assert float(np.linalg.norm(w_rows - truth)) < 0.5

    from synapseml_tpu.vw.sync import SyncScheduleRowCount as S
    assert list(S(250).boundaries(600, 1)) == [(0, 250), (250, 500), (500, 600)]
    with pytest.raises(ValueError):
        S(0)


def test_partitioned_unequal_sizes_and_state_carry():
    """Review regressions: tail rows of larger partitions train too, and the
    lr schedule does not restart at sync boundaries."""
    from synapseml_tpu.vw import SyncScheduleRowCount
    from synapseml_tpu.vw.learner import LinearConfig, train_linear, train_linear_partitioned

    idx, val, y = _stream_data(500, seed=6)
    parts = [(idx[:100], val[:100], y[:100]), (idx[100:], val[100:], y[100:])]
    cfg = LinearConfig(num_bits=4, loss="squared", learning_rate=0.02,
                       power_t=0.0, adaptive=False, batch_size=8, num_passes=2)
    w = train_linear_partitioned(parts, cfg, SyncScheduleRowCount(80))
    truth = np.zeros(16, np.float32)
    truth[:4] = [1.0, -2.0, 0.5, 0.0]
    # converges only if rows 100..399 of partition 2 were actually used
    assert float(np.linalg.norm(w - truth)) < 0.4

    # state carry: training in two windows with carried state == one window
    half = 50
    w1, st1 = train_linear(idx[:half], val[:half], y[:half],
                           cfg._replace(num_passes=1), return_state=True)
    w2 = train_linear(idx[half:100], val[half:100], y[half:100],
                      cfg._replace(num_passes=1), initial_weights=w1,
                      initial_state=st1)
    w_once = train_linear(idx[:100], val[:100], y[:100],
                          cfg._replace(num_passes=1, batch_size=8, seed=0))
    # not bitwise equal (different shuffles), but the schedules agree: the
    # carried step count must make window-2 updates smaller, not restart.
    _, st2 = train_linear(idx[half:100], val[half:100], y[half:100],
                          cfg._replace(num_passes=1), initial_weights=w1,
                          initial_state=st1, return_state=True)
    assert st2[1] > st1[1] > 0


def test_progressive_logistic_probabilities():
    import synapseml_tpu as st
    from synapseml_tpu.vw import VowpalWabbitProgressive
    from synapseml_tpu.vw.estimators import VowpalWabbitClassificationModel

    rs = np.random.default_rng(7)
    n = 200
    X = rs.normal(size=(n, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    idx = np.tile(np.arange(4, dtype=np.int32), (n, 1))
    df = st.DataFrame.from_dict({"features_indices": idx, "features_values": X,
                                 "label": y})
    out, model = VowpalWabbitProgressive(
        loss_function="logistic", num_bits=6, learning_rate=0.5,
        batch_size=4).transform_progressive(df)
    p = np.asarray(out.collect_column("progressive_prediction"))
    assert np.all((p >= 0) & (p <= 1))  # probabilities, not raw margins
    assert isinstance(model, VowpalWabbitClassificationModel)
    scored = model.transform(df)
    probs = np.asarray(scored.collect_column("probability"))
    assert np.all((probs >= 0) & (probs <= 1))
