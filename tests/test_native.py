"""native C++ ops: build, parity with the pure-Python implementations, and
the TextFeaturizer fast path."""

import numpy as np
import pytest

from synapseml_tpu import native
from synapseml_tpu.core import DataFrame
from synapseml_tpu.featurize import TextFeaturizer
from synapseml_tpu.vw.hashing import hash_feature, hash_features_batch, murmur3_32, namespace_seed


def test_native_builds():
    # g++ is part of the environment contract: the library must build
    assert native.available(), "native library failed to build/load"


def test_murmur_parity():
    cases = [b"", b"a", b"ab", b"abc", b"abcd", b"hello world",
             "naïve café".encode("utf-8"), b"x" * 1000]
    for data in cases:
        for seed in (0, 42, 0xDEADBEEF):
            assert native.murmur3_32_native(data, seed) == murmur3_32(data, seed), \
                (data, seed)


def test_murmur_batch_parity():
    names = [f"feature_{i}" for i in range(100)] + ["", "a", "日本語"]
    got = native.murmur3_batch(names, seed=namespace_seed("ns"), num_bits=18)
    want = [hash_feature(n, "ns", 18) for n in names]
    np.testing.assert_array_equal(got, want)


def test_batch_api_with_and_without_native():
    names = ["alpha", "beta", "gamma"]
    out = hash_features_batch(names, "", 18)
    np.testing.assert_array_equal(out, [hash_feature(n, "", 18) for n in names])


def test_docs_token_hashes_parity():
    texts = ["Hello World foo_bar", "  multiple   spaces\tand\nlines ",
             "punct!u@a#tion, splits;tokens", "", "UPPER lower 123",
             "tok" * 200]  # long token (> 256 bytes) exercises buffer growth
    nbits = 12
    got = native.docs_token_hashes(texts, seed=namespace_seed(""), num_bits=nbits,
                                   lower=True)
    assert got is not None
    import re

    for text, hashes in zip(texts, got):
        toks = re.findall(r"[A-Za-z0-9_]+", text.lower())
        want = [hash_feature(t, "", nbits) for t in toks]
        np.testing.assert_array_equal(hashes, want), text


def test_text_featurizer_native_matches_python(monkeypatch):
    texts = ["the quick brown fox", "jumps over the lazy dog", "the the the"]
    df = DataFrame.from_dict({"text": texts})
    model = TextFeaturizer(num_features=256, use_idf=True).fit(df)
    native_out = model.transform(df).collect_column("features")

    # force the pure-Python path and compare
    monkeypatch.setattr(native, "docs_token_hashes", lambda *a, **k: None)
    model2 = TextFeaturizer(num_features=256, use_idf=True).fit(df)
    python_out = model2.transform(df).collect_column("features")
    np.testing.assert_allclose(np.asarray(native_out), np.asarray(python_out),
                               atol=1e-6)


def test_native_speedup_sanity():
    import time

    names = [f"col_{i}_value_{i % 97}" for i in range(20000)]
    t0 = time.perf_counter()
    native.murmur3_batch(names, 0, 18)
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    [murmur3_32.__wrapped__(n.encode(), 0) for n in names[:2000]]  # uncached
    t_python = (time.perf_counter() - t0) * 10  # scale to 20k
    assert t_native < t_python, f"native {t_native:.4f}s vs python {t_python:.4f}s"


def test_codegen_docs(tmp_path):
    from synapseml_tpu.codegen import discover_stages, generate_markdown_docs, write_docs

    stages = discover_stages()
    assert len(stages) > 80
    docs = generate_markdown_docs()
    assert "gbdt" in docs and "LightGBMClassifier" in docs["gbdt"]
    assert "| param |" in docs["gbdt"]
    written = write_docs(str(tmp_path / "api"))
    assert any(p.endswith("stages.json") for p in written)
    import json

    with open([p for p in written if p.endswith("stages.json")][0]) as f:
        manifest = json.load(f)
    entry = next(e for e in manifest if e["name"] == "ONNXModel")
    assert entry["kind"] == "Transformer"
    assert any(p["name"] == "model_payload" for p in entry["params"])


def test_bin_rows_parity_with_numpy_path():
    """native.bin_rows == BinMapper's numpy searchsorted path bit-for-bit
    (float32 input: double(float32) is lossless), incl. NaN and categorical
    identity binning with out-of-range codes."""
    from synapseml_tpu.gbdt.binning import BinMapper

    rs = np.random.default_rng(3)
    X = rs.normal(size=(5000, 6)).astype(np.float32)
    X[::17, 1] = np.nan
    X[:, 4] = rs.integers(-3, 40, len(X))  # categorical incl. invalid codes
    m = BinMapper(max_bin=31, categorical=(4,)).fit(X)
    got = native.bin_rows(X, m.boundaries_, m.nan_bin, m.max_bin,
                          categorical=(4,))
    if got is None:
        pytest.skip("native library unavailable")
    # numpy oracle: float64 path through the same mapper
    expect = m.transform(X.astype(np.float64))
    np.testing.assert_array_equal(got.astype(expect.dtype), expect)
    # boundary exactness: values exactly ON a boundary go right
    b0 = float(m.boundaries_[0, 3])
    Xb = np.full((2, 6), 0.0, np.float32)
    Xb[0, 0] = np.float32(b0)
    g = native.bin_rows(Xb, m.boundaries_, m.nan_bin, m.max_bin,
                        categorical=(4,))
    e = m.transform(Xb.astype(np.float64))
    np.testing.assert_array_equal(g.astype(e.dtype), e)


def test_bin_rows_single_thread_matches_multi():
    from synapseml_tpu.gbdt.binning import BinMapper

    rs = np.random.default_rng(5)
    X = rs.normal(size=(9000, 4)).astype(np.float32)
    m = BinMapper(max_bin=63).fit(X)
    a = native.bin_rows(X, m.boundaries_, m.nan_bin, m.max_bin, n_threads=1)
    b = native.bin_rows(X, m.boundaries_, m.nan_bin, m.max_bin, n_threads=8)
    if a is None:
        pytest.skip("native library unavailable")
    np.testing.assert_array_equal(a, b)
