"""Round-3 service breadth against a local mock server: analyze-text LRO jobs
(PII/healthcare/summarization, reference
``AnalyzeTextLongRunningOperations.scala``), Azure Search index management
(``AzureSearchAPI.scala:64`` createIfNoneExists + schema inference from the
DataFrame, ``AzureSearch.scala:147``), and translator breadth
(Transliterate/BreakSentence/DictionaryLookup/DictionaryExamples,
``services/translate/Translate.scala``)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from synapseml_tpu.core import DataFrame
from synapseml_tpu.services import (
    AnalyzeTextLRO,
    AzureSearchWriter,
    BreakSentence,
    DictionaryExamples,
    DictionaryLookup,
    Transliterate,
    infer_index_schema,
)


class Handler(BaseHTTPRequestHandler):
    lro: dict = {}
    indexes: set = set()
    created_schemas: list = []
    job_bodies: list = []
    transcription_jobs: list = []

    def log_message(self, *a):
        pass

    def _json(self, payload, status=200, headers=None):
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self):
        n = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(n)) if n else None

    def do_GET(self):  # noqa: N802
        p = self.path.split("?")[0]
        if p.startswith("/language/analyze-text/jobs/"):
            job = p.rsplit("/", 1)[-1]
            n = Handler.lro.get(job, 0)
            Handler.lro[job] = n + 1
            if n < 1:
                return self._json({"status": "running"})
            kind = job.split(":")[0]
            docs = {"PiiEntityRecognition": {
                        "id": "0", "redactedText": "my name is ****",
                        "entities": [{"text": "Satya", "category": "Person"}]},
                    "Healthcare": {
                        "id": "0", "entities": [{"text": "ibuprofen",
                                                 "category": "MedicationName"}]},
                    "ExtractiveSummarization": {
                        "id": "0", "sentences": [{"text": "First.",
                                                  "rankScore": 1.0}]}}
            return self._json({"status": "succeeded", "tasks": {"items": [
                {"kind": kind, "results": {"documents": [docs[kind]]}}]}})
        if p == "/indexes":
            return self._json({"value": [{"name": n} for n in Handler.indexes]})
        host = f"http://{self.headers.get('Host')}"
        if p.startswith("/speechtotext/") and p.endswith("/tx1"):
            n = Handler.lro.get("tx1", 0)
            Handler.lro["tx1"] = n + 1
            if n < 1:
                return self._json({"status": "Running"})
            return self._json({"status": "Succeeded", "links": {
                "files": f"{host}/speechtotext/v3.2/transcriptions/tx1/files"}})
        if p.endswith("/tx1/files"):
            return self._json({"values": [
                {"kind": "TranscriptionReport", "links": {"contentUrl": f"{host}/report"}},
                {"kind": "Transcription",
                 "links": {"contentUrl": f"{host}/result.json"}}]})
        if p == "/result.json":
            return self._json({"recognizedPhrases": [
                {"speaker": 1, "offset": "PT0S",
                 "nBest": [{"display": "hello there"}]},
                {"speaker": 2, "offset": "PT2S",
                 "nBest": [{"display": "hi"}]}]})
        return self._json({"error": f"unknown GET {p}"}, 404)

    def do_POST(self):  # noqa: N802
        p = self.path.split("?")[0]
        body = self._body()
        host = f"http://{self.headers.get('Host')}"
        if p.startswith("/speechtotext/") and p.endswith("/transcriptions"):
            Handler.transcription_jobs.append(body)
            Handler.lro.setdefault("tx1", 0)
            return self._json(
                {"self": f"{host}/speechtotext/v3.2/transcriptions/tx1",
                 "status": "NotStarted"}, 201)
        if p == "/language/analyze-text/jobs":
            kind = body["tasks"][0]["kind"]
            Handler.job_bodies.append(body)
            job = f"{kind}:{len(Handler.job_bodies)}"
            Handler.lro.setdefault(job, 0)
            return self._json({}, 202, {
                "Operation-Location": f"{host}/language/analyze-text/jobs/{job}"})
        if p == "/indexes":
            assert self.headers.get("api-key") == "k"
            Handler.created_schemas.append(body)
            Handler.indexes.add(body["name"])
            return self._json({"name": body["name"]}, 201)
        if p.startswith("/indexes/") and p.endswith("/docs/index"):
            name = p.split("/")[2]
            if name not in Handler.indexes:
                return self._json({"error": {"message": "no such index"}}, 404)
            return self._json({"value": [{"key": d.get("id"), "status": True}
                                         for d in body["value"]]})
        if p == "/transliterate":
            return self._json([{"text": "namaste", "script": "Latn"}])
        if p == "/breaksentence":
            text = body[0]["Text"]
            return self._json([{"sentLen": [len(s) + 1 for s in
                                            text.split(".") if s]}])
        if p == "/dictionary/lookup":
            return self._json([{"translations": [
                {"normalizedTarget": "volar"}, {"normalizedTarget": "mosca"}]}])
        if p == "/dictionary/examples":
            assert body[0]["Translation"] == "volar"
            return self._json([{"examples": [
                {"targetPrefix": "Quiero ", "targetTerm": "volar",
                 "targetSuffix": " hoy."}]}])
        return self._json({"error": f"unknown POST {p}"}, 404)


@pytest.fixture(scope="module")
def server():
    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()
    srv.server_close()


@pytest.mark.parametrize("kind,check", [
    ("PiiEntityRecognition",
     lambda r: r["redactedText"] == "my name is ****"),
    ("Healthcare",
     lambda r: r["entities"][0]["category"] == "MedicationName"),
    ("ExtractiveSummarization",
     lambda r: r["sentences"][0]["text"] == "First."),
])
def test_analyze_text_lro_kinds(server, kind, check):
    df = DataFrame.from_dict({"text": ["my name is Satya"]})
    t = AnalyzeTextLRO(url=server, subscription_key="k", kind=kind,
                       polling_interval_s=0.01,
                       task_parameters={"modelVersion": "latest"})
    out = t.transform(df).collect_column("analysis")
    assert check(out[0]), out[0]
    sent = Handler.job_bodies[-1]
    assert sent["tasks"][0]["kind"] == kind
    assert sent["tasks"][0]["parameters"] == {"modelVersion": "latest"}
    assert sent["analysisInput"]["documents"][0]["text"] == "my name is Satya"


def test_infer_index_schema_types():
    df = DataFrame.from_rows([{
        "id": "a", "title": "doc one", "score": 1.5, "views": 7,
        "flag": True, "tags": ["x", "y"], "vec": np.asarray([0.1, 0.2])}])
    schema = infer_index_schema(df, "idx", key_col="id")
    by_name = {f["name"]: f for f in schema["fields"]}
    assert by_name["id"]["key"] is True
    assert by_name["id"]["type"] == "Edm.String"
    assert by_name["title"]["type"] == "Edm.String"
    assert by_name["score"]["type"] == "Edm.Double"
    assert by_name["views"]["type"] == "Edm.Int64"
    assert by_name["flag"]["type"] == "Edm.Boolean"
    assert by_name["tags"]["type"] == "Collection(Edm.String)"
    assert by_name["vec"]["type"] == "Collection(Edm.Double)"
    assert not by_name["tags"]["sortable"]
    with pytest.raises(ValueError, match="key column"):
        infer_index_schema(df, "idx", key_col="nope")


def test_search_writer_creates_missing_index(server):
    Handler.indexes.clear()
    Handler.created_schemas.clear()
    df = DataFrame.from_rows([{"id": "1", "title": "hello", "score": 0.5},
                              {"id": "2", "title": "world", "score": 0.9}])
    w = AzureSearchWriter(url=server, subscription_key="k",
                          index_name="docs-v1",
                          create_index_if_not_exists=True, batch_size=1)
    statuses = w.write(df)
    assert len(statuses) == 2 and all("error" not in s for s in statuses)
    assert Handler.created_schemas[0]["name"] == "docs-v1"
    # second write: index exists now, no second create
    w.write(df)
    assert len(Handler.created_schemas) == 1


def test_search_writer_without_create_fails_on_missing_index(server):
    Handler.indexes.clear()
    df = DataFrame.from_rows([{"id": "1", "title": "x"}])
    w = AzureSearchWriter(url=server, subscription_key="k",
                          index_name="absent")
    with pytest.raises(RuntimeError, match="failed batches"):
        w._transform(df)


def test_transliterate_breaksentence(server):
    df = DataFrame.from_dict({"text": ["First. Second."]})
    tr = Transliterate(url=server, subscription_key="k", language="hi",
                       from_script="Deva", to_script="Latn")
    assert tr.transform(df).collect_column("transliteration")[0] == "namaste"
    bs = BreakSentence(url=server, subscription_key="k")
    lens = bs.transform(df).collect_column("sent_len")[0]
    assert list(lens) == [6, 8]


def test_dictionary_lookup_and_examples(server):
    df = DataFrame.from_dict({"text": ["fly"], "translation": ["volar"]})
    dl = DictionaryLookup(url=server, subscription_key="k",
                          from_language="en", to_language="es")
    assert list(dl.transform(df).collect_column("translations")[0]) == \
        ["volar", "mosca"]
    de = DictionaryExamples(url=server, subscription_key="k",
                            from_language="en", to_language="es")
    assert list(de.transform(df).collect_column("examples")[0]) == \
        ["Quiero volar hoy."]


def test_analyze_text_lro_failed_job_is_an_error(server):
    df = DataFrame.from_dict({"text": ["boom"]})
    # mock: a kind the GET handler doesn't know -> craft via direct jobs map
    t = AnalyzeTextLRO(url=server, subscription_key="k",
                       kind="PiiEntityRecognition", polling_interval_s=0.01)
    # make the next job report failed status
    orig_get = Handler.do_GET

    def failing_get(self):
        p = self.path.split("?")[0]
        if p.startswith("/language/analyze-text/jobs/"):
            return self._json({"status": "failed",
                               "errors": [{"code": "InvalidRequest"}]})
        return orig_get(self)

    Handler.do_GET = failing_get
    try:
        out = t.transform(df)
        assert out.collect_column("analysis")[0] is None
        assert "job failed" in out.collect_column("errors")[0]
    finally:
        Handler.do_GET = orig_get


def test_translator_required_params_fail_fast(server):
    df = DataFrame.from_dict({"text": ["hi"]})
    with pytest.raises(ValueError, match="to_script"):
        Transliterate(url=server, subscription_key="k",
                      language="hi", from_script="Deva").transform(df)
    with pytest.raises(ValueError, match="from_language, to_language"):
        DictionaryLookup(url=server, subscription_key="k").transform(df)


def test_infer_index_schema_skips_leading_nones():
    df = DataFrame.from_rows([{"id": "a", "score": None},
                              {"id": "b", "score": 2.5}])
    schema = infer_index_schema(df, "idx", key_col="id")
    by_name = {f["name"]: f for f in schema["fields"]}
    assert by_name["score"]["type"] == "Edm.Double"


def test_conversation_transcriber_diarization(server):
    """Batch-transcription flow (reference ConversationTranscription,
    SpeechToTextSDK.scala:564): create job -> poll -> files -> diarized
    phrases with speaker ids."""
    from synapseml_tpu.services import ConversationTranscriber

    df = DataFrame.from_dict({"audio_url": ["https://example.com/a.wav"]})
    t = ConversationTranscriber(url=server, subscription_key="k",
                                max_speakers=3, polling_interval_s=0.01)
    out = t.transform(df).collect_column("transcription")[0]
    assert [p["speaker"] for p in out] == [1, 2]
    assert out[0]["text"] == "hello there"
    sent = Handler.transcription_jobs[-1]
    assert sent["properties"]["diarizationEnabled"] is True
    assert sent["properties"]["diarization"]["speakers"]["maxCount"] == 3
    assert sent["contentUrls"] == ["https://example.com/a.wav"]


def test_conversation_transcriber_failed_job_is_an_error(server):
    from synapseml_tpu.services import ConversationTranscriber

    orig_get = Handler.do_GET

    def failing_get(self):
        p = self.path.split("?")[0]
        if p.startswith("/speechtotext/") and p.endswith("/tx1"):
            return self._json({"status": "Failed", "properties": {
                "error": {"code": "InvalidUri", "message": "no such blob"}}})
        return orig_get(self)

    Handler.do_GET = failing_get
    try:
        df = DataFrame.from_dict({"audio_url": ["https://example.com/x.wav"]})
        t = ConversationTranscriber(url=server, subscription_key="k",
                                    polling_interval_s=0.01)
        out = t.transform(df)
        assert out.collect_column("transcription")[0] is None
        assert "job failed" in out.collect_column("errors")[0]
    finally:
        Handler.do_GET = orig_get


def test_conversation_transcriber_empty_nbest_segment(server):
    """A silence segment with nBest=[] must not discard the good phrases."""
    from synapseml_tpu.services import ConversationTranscriber

    orig_get = Handler.do_GET

    def silence_get(self):
        p = self.path.split("?")[0]
        if p == "/result.json":
            return self._json({"recognizedPhrases": [
                {"speaker": 1, "offset": "PT0S",
                 "nBest": [{"display": "hello"}]},
                {"speaker": 2, "offset": "PT1S", "nBest": []}]})
        return orig_get(self)

    Handler.do_GET = silence_get
    Handler.lro.pop("tx1", None)
    try:
        df = DataFrame.from_dict({"audio_url": ["https://example.com/y.wav"]})
        t = ConversationTranscriber(url=server, subscription_key="k",
                                    polling_interval_s=0.01)
        out = t.transform(df).collect_column("transcription")[0]
        assert [p["text"] for p in out] == ["hello", ""]
    finally:
        Handler.do_GET = orig_get
