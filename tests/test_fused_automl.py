"""Horizontally fused training arrays: fused-vs-serial parity, early-stop
masking + compaction, compile-count bounds, and the rewired AutoML sweep
(TuneHyperparameters / FindBestModel fusable-group partitioning)."""

import numpy as np
import pytest

import flax.linen as nn
import jax

from synapseml_tpu.core import batching as cb
from synapseml_tpu.core.params import Param
from synapseml_tpu.core.pipeline import Estimator, Model
from synapseml_tpu.automl import (
    DiscreteHyperParam,
    FindBestModel,
    HyperparamBuilder,
    TuneHyperparameters,
)
from synapseml_tpu.automl.hyperparams import DefaultHyperparams, fusable_param_names
from synapseml_tpu.automl.tune import _evaluate
from synapseml_tpu.gbdt import LightGBMClassifier, LightGBMRegressor
from synapseml_tpu.gbdt.booster import train_booster
from synapseml_tpu.gbdt.fused import fused_train_boosters
from synapseml_tpu.models.fused_trainer import FusedTrainer, fused_fit_arrays
from synapseml_tpu.models.trainer import Trainer, TrainerConfig, fit_arrays
from synapseml_tpu.parallel.mesh import MeshConfig, create_mesh

pytestmark = pytest.mark.automl


class _MLP(nn.Module):
    @nn.compact
    def __call__(self, x):
        return nn.Dense(3)(nn.tanh(nn.Dense(16)(x)))


@pytest.fixture(scope="module")
def mesh():
    return create_mesh(MeshConfig())


def _mlp_data(n=256, d=8, seed=0):
    rs = np.random.default_rng(seed)
    return {"x": rs.normal(size=(n, d)).astype(np.float32),
            "labels": rs.integers(0, 3, n).astype(np.int32)}


def _param_trees_close(a, b, rtol=2e-4, atol=1e-6):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=rtol, atol=atol)


# ---------------------------------------------------------------------------
# FusedTrainer: parity / masking / compaction / compile bounds
# ---------------------------------------------------------------------------

def test_fused_vs_serial_trainer_parity(mesh):
    """N trials trained fused match N independent serial fits under f32:
    same seeds, same data order via the deterministic DataLoader."""
    data = _mlp_data()
    trials = [{"learning_rate": 1e-2, "weight_decay": 0.0},
              {"learning_rate": 3e-3, "weight_decay": 0.01},
              {"learning_rate": 1e-3, "weight_decay": 0.1},
              {"learning_rate": 3e-2, "weight_decay": 0.001}]
    STEPS, BATCH, SEED = 12, 32, 5

    ft = FusedTrainer(_MLP(), mesh, TrainerConfig(total_steps=STEPS),
                      [dict(t) for t in trials])
    state = fused_fit_arrays(ft, data, batch_size=BATCH, total_steps=STEPS,
                             seed=SEED)
    fused_states = ft.unstack(state)

    for i, t in enumerate(trials):
        serial = Trainer(_MLP(), mesh,
                         TrainerConfig(total_steps=STEPS,
                                       lr_schedule="constant", **t))
        st = fit_arrays(serial, data, batch_size=BATCH, total_steps=STEPS,
                        seed=SEED)
        assert int(fused_states[i].step) == int(st.step) == STEPS
        _param_trees_close(jax.device_get(st.params), fused_states[i].params)


def test_fused_loss_metrics_match_serial(mesh):
    """Per-trial fused step losses equal each serial fit's step losses."""
    data = _mlp_data(n=128)
    trials = [{"learning_rate": 1e-2}, {"learning_rate": 1e-3}]
    STEPS, BATCH, SEED = 6, 32, 3
    ft = FusedTrainer(_MLP(), mesh, TrainerConfig(), [dict(t) for t in trials])
    losses = {0: [], 1: []}
    orig_step = ft.train_step

    def spy(state, batch):
        state, metrics = orig_step(state, batch)
        host = np.asarray(metrics["loss"])
        for tid in losses:
            losses[tid].append(float(host[tid]))
        return state, metrics

    ft.train_step = spy
    fused_fit_arrays(ft, data, batch_size=BATCH, total_steps=STEPS, seed=SEED)

    for i, t in enumerate(trials):
        serial = Trainer(_MLP(), mesh,
                         TrainerConfig(lr_schedule="constant", **t))
        serial_losses = []
        st = None
        from synapseml_tpu.data.source import MemorySource
        from synapseml_tpu.data import DataLoader

        loader = DataLoader(MemorySource(data), BATCH, seed=SEED)
        it = iter(loader)
        first = next(it)
        st = serial.init_state(first, jax.random.PRNGKey(SEED))
        batch = first
        for _ in range(STEPS):
            st, m = serial.train_step(st, batch)
            serial_losses.append(float(m["loss"]))
            batch = next(it)
        loader.close()
        np.testing.assert_allclose(losses[i], serial_losses, rtol=1e-4,
                                   atol=1e-6)


def test_early_stop_mask_and_compact_identity(mesh):
    """Deactivated trials freeze without recompiles; compact() gathers
    survivors into a smaller rung and their trajectories are unchanged."""
    data = _mlp_data(n=128)
    batch = {k: v[:32] for k, v in data.items()}
    trials = [{"learning_rate": 10 ** -(1 + 0.3 * i)} for i in range(6)]

    def run(do_compact):
        ft = FusedTrainer(_MLP(), mesh, TrainerConfig(),
                          [dict(t) for t in trials])
        st = ft.init_state(batch, default_seed=3)
        for _ in range(4):
            st, _ = ft.train_step(st, batch)
        st = ft.deactivate(st, [0, 1, 4, 5])
        frozen = {t: s.params for t, s in ft.unstack(st).items()
                  if t in (0, 1)}
        if do_compact:
            st = ft.compact(st)
            assert ft.rung == 2
            assert ft.live_trials() == [2, 3]
        for _ in range(4):
            st, _ = ft.train_step(st, batch)
        return ft, st, frozen

    ft_a, st_a, frozen = run(False)
    ft_b, st_b, _ = run(True)
    out_a, out_b = ft_a.unstack(st_a), ft_b.unstack(st_b)
    for tid in (2, 3):
        _param_trees_close(out_a[tid].params, out_b[tid].params, rtol=2e-5)
    # dead trials stay frozen through further steps (masked updates)
    st2, _ = ft_a.train_step(st_a, batch)
    out2 = ft_a.unstack(st2)
    for tid in (0, 1):
        for la, lb in zip(jax.tree.leaves(out2[tid].params),
                          jax.tree.leaves(frozen[tid])):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
        assert int(out2[tid].step) == int(out_a[tid].step)  # step frozen too


def test_fused_step_compile_count_bounded_by_trial_ladder(mesh):
    """One executable per trial-count RUNG (not per trial count, not per
    sweep): the CompiledCache miss counter is the acceptance surface."""
    batch = {k: v[:32] for k, v in _mlp_data(n=64).items()}
    cache = cb.get_compiled_cache()
    before = cache.miss_count("fused_train_step")
    ft = FusedTrainer(_MLP(), mesh, TrainerConfig(),
                      [{"learning_rate": 10 ** -(1 + i % 3)}
                       for i in range(6)])  # 6 trials -> rung 8
    st = ft.init_state(batch)
    for _ in range(2):
        st, _ = ft.train_step(st, batch)
    st = ft.deactivate(st, [0, 1, 2, 3])
    st = ft.compact(st)  # rung 8 -> 2
    for _ in range(2):
        st, _ = ft.train_step(st, batch)
    # shrinking live-trial counts 6 -> 2 share the two rung executables;
    # masking (deactivate) costs zero compiles
    new_misses = cache.miss_count("fused_train_step") - before
    assert new_misses == 2  # rungs {8, 2}; bounded by the trial ladder
    assert new_misses <= len(cb.TRIAL_LADDER)


def test_fused_trainer_rejects_unsupported_configs(mesh):
    with pytest.raises(ValueError, match="constant learning rates"):
        FusedTrainer(_MLP(), mesh, TrainerConfig(lr_schedule="cosine"),
                     [{}, {}])
    with pytest.raises(ValueError, match="grad_accum"):
        FusedTrainer(_MLP(), mesh, TrainerConfig(grad_accum=4), [{}, {}])
    with pytest.raises(ValueError, match="non-fusable keys"):
        FusedTrainer(_MLP(), mesh, TrainerConfig(),
                     [{"warmup_steps": 5}])


def test_custom_loss_fn_rejects_loss_hparam_overrides(mesh):
    """A custom loss_fn has no hyperparameter argument, so a per-trial
    label_smoothing override would be silently discarded — reject it."""
    def loss_fn(variables, batch):  # pragma: no cover - never reached
        return 0.0

    with pytest.raises(ValueError, match="custom.*loss_fn"):
        FusedTrainer(_MLP(), mesh, TrainerConfig(),
                     [{"label_smoothing": 0.0}, {"label_smoothing": 0.2}],
                     loss_fn=loss_fn)
    # without overrides a custom loss_fn is fine
    FusedTrainer(_MLP(), mesh, TrainerConfig(),
                 [{"learning_rate": 1e-2}], loss_fn=loss_fn)


def test_fused_fit_arrays_accepts_drop_remainder_override(mesh):
    """Explicit drop_remainder must override the size-derived default, not
    collide with it (duplicate-kwarg TypeError)."""
    data = _mlp_data(n=48)
    ft = FusedTrainer(_MLP(), mesh, TrainerConfig(), [{}, {}])
    state = fused_fit_arrays(ft, data, batch_size=32, total_steps=2, seed=0,
                             drop_remainder=False)
    assert all(int(s.step) == 2 for s in ft.unstack(state).values())


def test_hpo_array_metrics_family_is_shared():
    """The NN and GBDT fused engines must emit through ONE family definition
    so the two cannot drift into conflicting registrations."""
    from synapseml_tpu.core.hpo_metrics import HPO_ARRAY_METRICS
    from synapseml_tpu.gbdt import fused as gbdt_fused
    from synapseml_tpu.models import fused_trainer as nn_fused

    assert nn_fused._HPO_METRICS is HPO_ARRAY_METRICS
    assert gbdt_fused._HPO_METRICS is HPO_ARRAY_METRICS


def test_hpo_metrics_emitted(mesh):
    from synapseml_tpu.core.observability import get_registry

    batch = {k: v[:32] for k, v in _mlp_data(n=32).items()}
    ft = FusedTrainer(_MLP(), mesh, TrainerConfig(), [{}, {"learning_rate": 1e-3}])
    st = ft.init_state(batch)
    ft.fit(st, iter([batch, batch]), max_steps=2)
    text = get_registry().exposition()
    for series in ("synapseml_hpo_active_trials",
                   "synapseml_hpo_fused_step_ms",
                   "synapseml_hpo_trials_per_sec",
                   "synapseml_hpo_fused_steps_total"):
        assert series in text


# ---------------------------------------------------------------------------
# fused GBDT sweep
# ---------------------------------------------------------------------------

def _gbdt_data(n=300, seed=2):
    rs = np.random.default_rng(seed)
    X = rs.normal(size=(n, 6)).astype(np.float32)
    y = ((X[:, 0] + 0.5 * X[:, 1] - X[:, 2] ** 2
          + 0.1 * rs.normal(size=n)) > 0).astype(np.float32)
    return X, y


def test_fused_gbdt_matches_serial_boosters():
    """Per-trial fused boosters score identically to serial train_booster
    runs of the same configs (shared split math, shared binning)."""
    X, y = _gbdt_data()
    trials = [
        {"learning_rate": 0.1, "num_leaves": 15, "num_iterations": 12},
        {"learning_rate": 0.3, "num_leaves": 15, "lambda_l2": 0.5,
         "num_iterations": 12},
        {"learning_rate": 0.05, "num_leaves": 15, "lambda_l1": 0.1,
         "min_data_in_leaf": 5, "num_iterations": 8},
    ]
    fused = fused_train_boosters(X, y, trials, objective="binary",
                                 max_depth=5, seed=0)
    for i, t in enumerate(trials):
        kw = dict(t)
        n_it = kw.pop("num_iterations")
        serial = train_booster(X, y, objective="binary", num_iterations=n_it,
                               max_depth=5, seed=0, **kw)
        np.testing.assert_allclose(fused[i].raw_score(X), serial.raw_score(X),
                                   rtol=1e-4, atol=1e-5)
        assert fused[i].num_iterations == n_it


def test_fused_gbdt_iteration_compiles_once_per_rung():
    X, y = _gbdt_data(n=200)
    cache = cb.get_compiled_cache()
    before = cache.miss_count("gbdt_fused_iter")
    # two sweeps, different hyperparameters, same rung -> ONE executable
    for lr in (0.1, 0.2):
        fused_train_boosters(
            X, y, [{"learning_rate": lr, "num_iterations": 3},
                   {"learning_rate": lr / 2, "num_iterations": 3},
                   {"lambda_l2": 1.0, "num_iterations": 3}],
            objective="binary", max_depth=4, seed=0)
    assert cache.miss_count("gbdt_fused_iter") - before == 1


def test_fused_gbdt_depth_mismatch_rejected():
    X, y = _gbdt_data(n=100)
    with pytest.raises(ValueError, match="effective max_depth"):
        fused_train_boosters(X, y, [{"num_leaves": 4}, {"num_leaves": 63}],
                             objective="binary", seed=0)


# ---------------------------------------------------------------------------
# TuneHyperparameters / FindBestModel rewiring
# ---------------------------------------------------------------------------

def test_tune_fused_matches_serial_sweep(tabular_df):
    space = (HyperparamBuilder()
             .add_hyperparam("learning_rate",
                             DiscreteHyperParam([0.05, 0.1, 0.2, 0.3]))
             .add_hyperparam("lambda_l2", DiscreteHyperParam([0.0, 0.5]))
             .build())

    def sweep(fuse):
        return TuneHyperparameters(
            models=[LightGBMClassifier(num_iterations=10, num_leaves=15)],
            hyperparam_space=space, search_mode="grid",
            evaluation_metric="accuracy", seed=7, fuse_trials=fuse,
        ).fit(tabular_df)

    cache = cb.get_compiled_cache()
    before = cache.miss_count("gbdt_fused_iter")
    fused, serial = sweep(True), sweep(False)
    assert cache.miss_count("gbdt_fused_iter") - before >= 1  # fused ran
    assert fused.get("best_params") == serial.get("best_params")
    assert fused.get("best_metric") == pytest.approx(
        serial.get("best_metric"), abs=1e-9)
    for (na, ca, va), (nb, cbv, vb) in zip(fused.get("all_results"),
                                           serial.get("all_results")):
        assert (na, ca) == (nb, cbv)
        assert va == pytest.approx(vb, abs=1e-9)
    out = fused.transform(tabular_df)
    assert "prediction" in out.columns


def test_all_results_record_estimator_identity(tabular_df):
    """Two candidate estimators: every result names which model its config
    belonged to (the reference lost this, keeping only (config, metric))."""
    space_a = {"num_iterations": DiscreteHyperParam([5, 10])}
    space_b = {"num_iterations": DiscreteHyperParam([8])}
    best = TuneHyperparameters(
        models=[LightGBMClassifier(num_leaves=7),
                LightGBMClassifier(num_leaves=31)],
        hyperparam_space=[space_a, space_b], search_mode="grid",
        evaluation_metric="accuracy", seed=1).fit(tabular_df)
    results = best.get("all_results")
    assert len(results) == 3
    names = [r[0] for r in results]
    assert names == ["LightGBMClassifier[0]", "LightGBMClassifier[0]",
                     "LightGBMClassifier[1]"]
    for name, cfg, metric in results:
        assert isinstance(cfg, dict) and np.isfinite(metric)


def test_tune_architecture_changing_params_fall_back_serial(tabular_df):
    """max_bin changes binning (architecture): configs split into distinct
    signatures and still sweep correctly via grouping/serial."""
    space = {"max_bin": DiscreteHyperParam([15, 63]),
             "learning_rate": DiscreteHyperParam([0.1, 0.3])}
    best = TuneHyperparameters(
        models=[LightGBMClassifier(num_iterations=8)], hyperparam_space=space,
        search_mode="grid", evaluation_metric="accuracy", seed=3).fit(tabular_df)
    assert best.get("best_metric") > 0.7
    assert len(best.get("all_results")) == 4


def test_tune_bad_candidate_does_not_sink_fused_sweep(tabular_df):
    """A candidate whose config cannot even be applied records __error__ +
    NaN while the fusable rest of the sweep still trains as one array."""
    best = TuneHyperparameters(
        models=[LightGBMClassifier(num_leaves=15), _FailingEstimator()],
        hyperparam_space=[
            {"num_iterations": DiscreteHyperParam([5, 9])},
            {"no_such_param": DiscreteHyperParam([1])},
        ],
        search_mode="grid", evaluation_metric="accuracy",
        seed=0).fit(tabular_df)
    results = best.get("all_results")
    assert len(results) == 3
    bad = [r for r in results if not np.isfinite(r[2])]
    assert len(bad) == 1 and "__error__" in bad[0][1]
    assert bad[0][0] == "_FailingEstimator[1]"
    assert best.get("best_metric") > 0.7


class _FailingEstimator(Estimator):
    def _fit(self, df):
        raise RuntimeError("deliberately broken candidate")


class _NoPredictionModel(Model):
    out_col = Param("out_col", "output column", default="weird_scores")

    def _transform(self, df):
        return df.with_column(self.get("out_col"),
                              np.zeros(df.count(), np.float64))


class _DeclaredColModel(Model):
    prediction_col = Param("prediction_col", "prediction output column",
                           default="score")

    def _transform(self, df):
        return df.with_column(self.get("prediction_col"),
                              np.asarray(df.collect_column("label"),
                                         np.float64))


def test_evaluate_prefers_declared_prediction_col(tabular_df):
    v = _evaluate(_DeclaredColModel(), tabular_df, "accuracy", "label")
    assert v == 1.0  # scored its own label column under the declared name


def test_evaluate_errors_name_available_columns(tabular_df):
    with pytest.raises(ValueError) as err:
        _evaluate(_NoPredictionModel(), tabular_df, "accuracy", "label")
    msg = str(err.value)
    assert "weird_scores" in msg and "prediction_col" in msg


def test_find_best_model_contains_failures_and_fuses(tabular_df):
    cache = cb.get_compiled_cache()
    before = cache.miss_count("gbdt_fused_iter")
    res = FindBestModel(models=[
        LightGBMClassifier(num_iterations=3, num_leaves=15),
        LightGBMClassifier(num_iterations=25, num_leaves=15),
        _FailingEstimator(),
    ]).fit(tabular_df)
    assert cache.miss_count("gbdt_fused_iter") - before >= 1  # pair fused
    metrics = res.get("all_model_metrics")
    assert len(metrics) == 3
    assert sum(1 for _n, v in metrics if np.isfinite(v)) == 2
    failed = [n for n, v in metrics if not np.isfinite(v)]
    assert failed == ["_FailingEstimator[2]"]
    # uniform 'ClassName[i]' labels keep duplicate-class candidates distinct
    assert [n for n, v in metrics if np.isfinite(v)] == [
        "LightGBMClassifier[0]", "LightGBMClassifier[1]"]
    assert res.get("best_metric") >= 0.8


def test_find_best_model_all_failures_raise(tabular_df):
    with pytest.raises(RuntimeError, match="every candidate failed"):
        FindBestModel(models=[_FailingEstimator(), _FailingEstimator()]
                      ).fit(tabular_df)


def test_fusable_param_names_and_fused_range():
    names = fusable_param_names(LightGBMClassifier)
    assert "learning_rate" in names and "num_leaves" in names
    assert fusable_param_names("LightGBMRegressor") == \
        fusable_param_names(LightGBMRegressor())
    space = DefaultHyperparams.fused_range("LightGBMClassifier")
    assert set(space) <= set(names)
    # name, class, and instance are equivalent (class used to resolve to
    # the metaclass name and raise)
    assert set(DefaultHyperparams.fused_range(LightGBMClassifier)) \
        == set(DefaultHyperparams.fused_range(LightGBMClassifier())) \
        == set(space)
    with pytest.raises(ValueError, match="no fused training path"):
        DefaultHyperparams.fused_range("VowpalWabbitClassifier")
    with pytest.raises(ValueError, match="VowpalWabbitClassifier"):
        from synapseml_tpu.vw import VowpalWabbitClassifier
        DefaultHyperparams.fused_range(VowpalWabbitClassifier)


def test_fused_plan_signatures():
    a = LightGBMClassifier(num_iterations=5, num_leaves=15)
    b = LightGBMClassifier(num_iterations=50, num_leaves=15)
    assert a._fused_plan({}) == b._fused_plan({})  # scalars don't split
    assert a._fused_plan({"max_bin": 31}) != b._fused_plan({})  # structure does
    assert a._fused_plan({"boosting_type": "dart"}) is None
    assert a._fused_plan({"bagging_fraction": 0.5, "bagging_freq": 1}) is None
    assert LightGBMClassifier(
        categorical_slot_indexes=[0])._fused_plan({}) is None
