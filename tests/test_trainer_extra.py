import numpy as np
import pytest

import jax

from synapseml_tpu.models.flax_nets.bert import BertClassifier, bert_tiny
from synapseml_tpu.models.flax_nets.llama import LlamaLM, greedy_generate, llama_tiny
from synapseml_tpu.models.trainer import Trainer, TrainerConfig
from synapseml_tpu.parallel import MeshConfig, create_mesh, restore_checkpoint, save_checkpoint
from synapseml_tpu.parallel.batching import bucket_size


def _batch(B=8, T=16, vocab=1024, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, vocab, (B, T)).astype(np.int32),
            "attention_mask": np.ones((B, T), np.int32),
            "labels": rng.integers(0, 2, (B,)).astype(np.int32)}


def test_scan_matches_stepwise(mesh_dp8):
    cfg = bert_tiny()
    model = BertClassifier(cfg, num_classes=2)
    batch = _batch(vocab=cfg.vocab_size)

    tr1 = Trainer(model, mesh_dp8, TrainerConfig(total_steps=10))
    s1 = tr1.init_state(batch, jax.random.PRNGKey(0))
    losses = []
    for _ in range(4):
        s1, m = tr1.train_step(s1, batch)
        losses.append(float(m["loss"]))

    tr2 = Trainer(model, mesh_dp8, TrainerConfig(total_steps=10))
    s2 = tr2.init_state(batch, jax.random.PRNGKey(0))
    stacked = jax.tree.map(lambda x: np.broadcast_to(x, (4,) + x.shape).copy(), batch)
    s2, metrics = tr2.train_steps_scan(s2, stacked)
    np.testing.assert_allclose(np.asarray(metrics["loss"]), losses, rtol=1e-4, atol=1e-5)
    assert int(s2.step) == 4


def test_resume_after_checkpoint(tmp_path, mesh_dp8):
    cfg = bert_tiny()
    model = BertClassifier(cfg, num_classes=2)
    batch = _batch(vocab=cfg.vocab_size)
    tr = Trainer(model, mesh_dp8, TrainerConfig(total_steps=10))
    state = tr.init_state(batch)
    state, _ = tr.train_step(state, batch)
    save_checkpoint(str(tmp_path), {"params": state.params}, step=1)

    # fresh trainer, restore params, resume WITHOUT init_state
    tr2 = Trainer(model, mesh_dp8, TrainerConfig(total_steps=10))
    restored = restore_checkpoint(str(tmp_path))
    s2 = tr2.resume_state(restored["params"], step=1)
    s2, m = tr2.train_step(s2, batch)
    assert np.isfinite(float(m["loss"]))
    assert int(s2.step) == 2


def test_train_step_without_init_raises(mesh_dp8):
    tr = Trainer(BertClassifier(bert_tiny(), num_classes=2), mesh_dp8,
                 TrainerConfig())
    with pytest.raises(RuntimeError, match="optimizer not built"):
        tr.train_step(object(), _batch())  # state never inspected before the guard


def test_bucket_overflow_raises():
    with pytest.raises(ValueError, match="exceeds largest bucket"):
        bucket_size(20, buckets=[8, 16])


def test_generate_with_padded_prompt():
    """Rows padded to the prompt bucket must generate as if unpadded."""
    cfg = llama_tiny()
    m = LlamaLM(cfg)
    ids_short = np.array([[5, 7]], np.int32)                     # true length 2
    variables = m.init(jax.random.PRNGKey(0), ids_short)
    params = variables["params"]
    dm = LlamaLM(cfg, decode=True)

    # unpadded reference: bucket exactly fits the prompt
    out_ref = np.asarray(greedy_generate(dm, params, ids_short, max_new_tokens=5))
    # padded to P=8 with a mask
    P = 8
    ids_pad = np.zeros((1, P), np.int32)
    ids_pad[0, :2] = ids_short[0]
    mask = np.zeros((1, P), np.int32)
    mask[0, :2] = 1
    out_pad = np.asarray(greedy_generate(dm, params, ids_pad, max_new_tokens=5,
                                         prompt_mask=mask))
    np.testing.assert_array_equal(out_ref[0, 2:], out_pad[0, P:])


def test_streaming_fit_chunked_matches_per_step(mesh_dp8):
    """Trainer.fit's chunked/prefetched path (any iterator) produces the same
    final state as the per-step loop, including with a varying batch shape
    mid-stream and a finite iterator shorter than max_steps (VERDICT round-2
    weak #7: the streaming path previously dispatched per step)."""
    cfg = bert_tiny()
    model = BertClassifier(cfg, num_classes=2)

    def make_batches():
        out = []
        for i in range(7):
            out.append(_batch(seed=i, vocab=cfg.vocab_size))
        # shape change mid-stream: the chunker must flush and keep going
        out.append(_batch(seed=99, B=16, T=8, vocab=cfg.vocab_size))
        out.append(_batch(seed=100, B=16, T=8, vocab=cfg.vocab_size))
        return out

    batches = make_batches()

    tr1 = Trainer(model, mesh_dp8, TrainerConfig(total_steps=20))
    s1 = tr1.init_state(batches[0], jax.random.PRNGKey(0))
    s1 = tr1.fit(s1, iter(batches), max_steps=20, scan_chunk=1)  # per-step

    tr2 = Trainer(model, mesh_dp8, TrainerConfig(total_steps=20))
    s2 = tr2.init_state(batches[0], jax.random.PRNGKey(0))
    s2 = tr2.fit(s2, iter(batches), max_steps=20, scan_chunk=3)  # chunked

    assert int(s1.step) == int(s2.step) == 9  # finite iterator < max_steps
    a = jax.tree.leaves(s1.params)
    b = jax.tree.leaves(s2.params)
    for x, y in zip(a, b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=2e-4, atol=2e-5)


def test_fit_periodic_async_checkpointing(tmp_path, mesh_dp8):
    """Both fit paths write async checkpoints every N steps plus a final one;
    the restored state resumes training via resume_state."""
    from synapseml_tpu.parallel import (AsyncCheckpointer, latest_step,
                                        restore_checkpoint)

    cfg = bert_tiny()
    model = BertClassifier(cfg, num_classes=2)
    batch = _batch(vocab=cfg.vocab_size)
    batches = [_batch(seed=i, vocab=cfg.vocab_size) for i in range(6)]

    tr = Trainer(model, mesh_dp8, TrainerConfig(total_steps=10))
    state = tr.init_state(batch, jax.random.PRNGKey(0))
    with AsyncCheckpointer(str(tmp_path / "chunked"), keep=10) as ck:
        state = tr.fit(state, iter(batches), max_steps=6, scan_chunk=2,
                       checkpointer=ck, checkpoint_every=2)
    assert latest_step(str(tmp_path / "chunked")) == 6
    import os
    steps = sorted(d for d in os.listdir(tmp_path / "chunked"))
    assert len(steps) == 3  # saved at 2, 4, 6 (6 is also the final save)

    restored = restore_checkpoint(str(tmp_path / "chunked"))
    tr2 = Trainer(model, mesh_dp8, TrainerConfig(total_steps=10))
    s2 = tr2.resume_state(restored["params"], restored["opt_state"],
                          step=int(np.asarray(restored["step"])))
    s2, m = tr2.train_step(s2, batch)
    assert np.isfinite(float(m["loss"])) and int(s2.step) == 7

    # per-step path saves too (callback forces it)
    tr3 = Trainer(model, mesh_dp8, TrainerConfig(total_steps=10))
    s3 = tr3.init_state(batch, jax.random.PRNGKey(0))
    with AsyncCheckpointer(str(tmp_path / "stepwise"), keep=10) as ck:
        tr3.fit(s3, iter(batches[:3]), max_steps=5, callback=lambda i, m: None,
                checkpointer=ck, checkpoint_every=2)
    assert latest_step(str(tmp_path / "stepwise")) == 3  # finite iter: final save


def test_align_restored_matches_dicts_by_key_not_position():
    """resume_state's structural pour: dict children match BY KEY (so a
    serialized iteration order differing from jax's sorted flatten order
    cannot swap same-shaped leaves), kinds/shapes/keys are validated with
    the failing path in the error."""
    import pytest

    from synapseml_tpu.models.trainer import _align_restored

    class Pair(tuple):
        pass  # stand-in: plain tuples model deserialized NamedTuples

    fresh = (
        {"mu": jax.ShapeDtypeStruct((2,), np.float32),
         "nu": jax.ShapeDtypeStruct((2,), np.float32)},
        jax.ShapeDtypeStruct((), np.int32),
    )
    # restored dict built in REVERSED insertion order: nu first, then mu
    got = ({"nu": np.array([3.0, 4.0], np.float32),
            "mu": np.array([1.0, 2.0], np.float32)}, np.int32(7))
    leaves = list(_align_restored(fresh, got, "opt"))
    # flatten order is sorted keys: mu then nu — values must follow keys
    np.testing.assert_array_equal(leaves[0], [1.0, 2.0])
    np.testing.assert_array_equal(leaves[1], [3.0, 4.0])
    assert leaves[2] == 7

    with pytest.raises(ValueError, match=r"missing \['mu'\]"):
        list(_align_restored(fresh, ({"nu": got[0]["nu"]}, got[1]), "opt"))
    with pytest.raises(ValueError, match="expects 2"):
        list(_align_restored(fresh, (got[0],), "opt"))
    bad_shape = ({"mu": np.zeros(3, np.float32), "nu": got[0]["nu"]}, got[1])
    with pytest.raises(ValueError, match=r"opt\.0\['mu'\]"):
        list(_align_restored(fresh, bad_shape, "opt"))
