"""GBDT engine tests — accuracy gates in the reference's benchmark-CSV spirit
(``lightgbm/src/test/resources/benchmarks/*.csv``: name,value,precision)."""

import numpy as np
import pytest

from synapseml_tpu.core import DataFrame
from synapseml_tpu.gbdt import (
    BinMapper,
    LightGBMClassificationModel,
    LightGBMClassifier,
    LightGBMRanker,
    LightGBMRegressor,
    TpuBooster,
)
from synapseml_tpu.gbdt.booster import train_booster


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(7)


@pytest.fixture(scope="module")
def binary_data(rng):
    n, f = 3000, 10
    x = rng.normal(size=(n, f))
    logit = 2 * x[:, 0] - x[:, 1] + 0.5 * x[:, 2] * x[:, 3]
    y = (logit + rng.normal(scale=0.5, size=n) > 0).astype(np.float32)
    return x, y


class TestBinMapper:
    def test_roundtrip_monotone(self, rng):
        x = rng.normal(size=(500, 3))
        m = BinMapper(max_bin=31)
        codes = m.fit_transform(x)
        assert codes.shape == (500, 3)
        assert codes.max() < m.num_bins
        # binning preserves order within a feature
        order = np.argsort(x[:, 0])
        assert (np.diff(codes[order, 0].astype(int)) >= 0).all()

    def test_nan_bin(self, rng):
        x = rng.normal(size=(100, 2))
        x[::7, 0] = np.nan
        m = BinMapper(max_bin=15)
        codes = m.fit_transform(x)
        assert (codes[::7, 0] == m.nan_bin).all()

    def test_low_cardinality_gets_exact_bins(self):
        x = np.tile(np.array([[0.0], [1.0], [2.0]]), (50, 1))
        m = BinMapper(max_bin=255).fit(x)
        codes = m.transform(np.array([[0.0], [1.0], [2.0]]))
        assert len(np.unique(codes)) == 3

    def test_serialization(self, rng):
        m = BinMapper(max_bin=31).fit(rng.normal(size=(200, 2)))
        m2 = BinMapper.from_dict(m.to_dict())
        x = rng.normal(size=(50, 2))
        np.testing.assert_array_equal(m.transform(x), m2.transform(x))


class TestBoosterTraining:
    def test_binary_accuracy_gate(self, binary_data):
        x, y = binary_data
        b = train_booster(x, y, objective="binary", num_iterations=30,
                          num_leaves=15, learning_rate=0.2)
        acc = ((b.predict(x) > 0.5) == y).mean()
        assert acc > 0.92  # tolerance gate

    def test_regression_gate(self, rng):
        n, f = 3000, 6
        x = rng.normal(size=(n, f))
        y = (3 * x[:, 0] + np.sin(2 * x[:, 1]) + rng.normal(scale=0.1, size=n)).astype(np.float32)
        b = train_booster(x, y, objective="regression", num_iterations=50, learning_rate=0.2)
        rmse = float(np.sqrt(np.mean((b.predict(x) - y) ** 2)))
        assert rmse < 0.25 * float(y.std())

    def test_multiclass_gate(self, rng):
        x = rng.normal(size=(2000, 5))
        y = np.digitize(x[:, 0] + 0.3 * x[:, 1], [-0.5, 0.5]).astype(np.float32)
        b = train_booster(x, y, objective="multiclass", num_class=3,
                          num_iterations=20, learning_rate=0.3)
        assert (np.argmax(b.predict(x), 1) == y).mean() > 0.9

    def test_l1_and_quantile_objectives(self, rng):
        x = rng.normal(size=(1000, 4))
        y = (x[:, 0] + rng.normal(scale=0.2, size=1000)).astype(np.float32)
        for objective in ("regression_l1", "quantile", "huber"):
            b = train_booster(x, y, objective=objective, num_iterations=20,
                              learning_rate=0.3, objective_alpha=0.5)
            mae = np.mean(np.abs(b.predict(x) - y))
            assert mae < 0.8 * np.mean(np.abs(y - np.median(y))), objective

    def test_nan_features_route(self, rng):
        x = rng.normal(size=(1500, 4))
        x[rng.random(x.shape) < 0.1] = np.nan
        y = (np.nan_to_num(x[:, 0]) > 0).astype(np.float32)
        b = train_booster(x, y, objective="binary", num_iterations=20, learning_rate=0.3)
        acc = ((b.predict(x) > 0.5) == y).mean()
        assert acc > 0.85

    def test_bagging_and_feature_fraction(self, binary_data):
        x, y = binary_data
        b = train_booster(x, y, objective="binary", num_iterations=20,
                          learning_rate=0.2, bagging_fraction=0.7, bagging_freq=1,
                          feature_fraction=0.8)
        assert ((b.predict(x) > 0.5) == y).mean() > 0.9

    def test_early_stopping(self, binary_data):
        x, y = binary_data
        b = train_booster(x[:2000], y[:2000], objective="binary",
                          num_iterations=200, learning_rate=0.5,
                          valid_features=x[2000:], valid_labels=y[2000:],
                          early_stopping_round=3)
        assert b.best_iteration is not None
        assert b.num_iterations < 200

    def test_min_data_in_leaf_limits_growth(self, binary_data):
        x, y = binary_data
        b = train_booster(x, y, objective="binary", num_iterations=3,
                          min_data_in_leaf=1000, learning_rate=0.1)
        # huge min_data -> few splits per tree
        assert (b.feature >= 0).sum() <= 3 * 3


class TestBoosterApi:
    def test_save_load_identical(self, binary_data, tmp_path):
        x, y = binary_data
        b = train_booster(x, y, objective="binary", num_iterations=10, learning_rate=0.2)
        b.save(str(tmp_path / "b"))
        b2 = TpuBooster.load(str(tmp_path / "b"))
        np.testing.assert_allclose(b.predict(x[:100]), b2.predict(x[:100]), rtol=1e-6)

    def test_predict_leaf_shape(self, binary_data):
        x, y = binary_data
        b = train_booster(x, y, objective="binary", num_iterations=5, learning_rate=0.2)
        leaves = b.predict_leaf(x[:50])
        assert leaves.shape == (50, 5)
        assert (leaves >= 0).all()

    def test_raw_score_and_predict_matches_separate_calls(self, binary_data):
        """The fused (raw, prob) executable — the classifier serving /
        bulk-scoring hot path — must equal the independent raw-only
        executable + an eager objective transform over it (predict() now
        delegates to the fused path, so comparing against predict() would
        be tautological), on a ladder bucket AND on the beyond-ladder
        polymorphic path."""
        import jax.numpy as jnp

        from synapseml_tpu.gbdt import objectives as obj

        x, y = binary_data
        b = train_booster(x, y, objective="binary", num_iterations=10,
                          learning_rate=0.2)
        o = obj.get_objective(b.objective, num_class=b.num_model_out)
        for n in (37, len(x)):  # padded ladder rung / polymorphic
            raw, prob = b.raw_score_and_predict(x[:n])
            ref_raw = b.raw_score(x[:n])
            np.testing.assert_allclose(raw, ref_raw, rtol=1e-6)
            np.testing.assert_allclose(
                prob, np.asarray(o.transform(jnp.asarray(ref_raw))),
                rtol=1e-6)
            assert raw.shape[0] == n and prob.shape[0] == n

    def test_feature_importance(self, binary_data):
        x, y = binary_data
        b = train_booster(x, y, objective="binary", num_iterations=10, learning_rate=0.2)
        for kind in ("split", "gain"):
            imp = b.feature_importance(kind)
            assert imp.shape == (x.shape[1],)
            # features 0/1 drive the label; they should dominate noise features
            assert imp[0] + imp[1] > imp[4:].sum()

    def test_dump_text(self, binary_data):
        x, y = binary_data
        b = train_booster(x, y, objective="binary", num_iterations=2, learning_rate=0.2)
        txt = b.dump_text()
        assert "tpu_booster" in txt and "tree 0.0" in txt


class TestEstimators:
    def test_classifier_pipeline(self, binary_data, tmp_path):
        x, y = binary_data
        df = DataFrame.from_dict({"features": x, "label": y.astype(int)}, num_partitions=3)
        model = LightGBMClassifier(num_iterations=15, learning_rate=0.2).fit(df)
        out = model.transform(df)
        assert {"prediction", "probability", "rawPrediction"} <= set(out.columns)
        assert (out.collect_column("prediction") == y).mean() > 0.9
        model.save(str(tmp_path / "m"))
        m2 = LightGBMClassificationModel.load(str(tmp_path / "m"))
        np.testing.assert_allclose(m2.transform(df).collect_column("probability"),
                                   out.collect_column("probability"), rtol=1e-6)

    def test_classifier_string_labels(self, rng):
        x = rng.normal(size=(600, 4))
        y = np.where(x[:, 0] > 0, "pos", "neg")
        df = DataFrame.from_dict({"features": x, "label": y})
        out = LightGBMClassifier(num_iterations=10, learning_rate=0.3).fit(df).transform(df)
        assert set(np.unique(out.collect_column("prediction"))) <= {"pos", "neg"}
        assert (out.collect_column("prediction") == y).mean() > 0.95

    def test_feature_cols_mode(self, rng):
        x = rng.normal(size=(500, 3))
        df = DataFrame.from_dict({"a": x[:, 0], "b": x[:, 1], "c": x[:, 2],
                                  "label": (x[:, 0] > 0).astype(int)})
        est = LightGBMRegressor(feature_cols=["a", "b", "c"], num_iterations=10,
                                learning_rate=0.3)
        out = est.fit(df).transform(df)
        assert "prediction" in out.columns

    def test_regressor_weights(self, rng):
        x = rng.normal(size=(800, 3))
        y = x[:, 0].astype(np.float32)
        w = np.ones(800); w[:400] = 0.0  # zero-weight half the data
        df = DataFrame.from_dict({"features": x, "label": y, "w": w})
        model = LightGBMRegressor(weight_col="w", num_iterations=15, learning_rate=0.3).fit(df)
        pred = model.transform(df).collect_column("prediction")
        assert np.corrcoef(pred, y)[0, 1] > 0.8

    def test_ranker_ndcg(self, rng):
        n = 1000
        x = rng.normal(size=(n, 5))
        groups = np.repeat(np.arange(50), 20)
        rel = np.clip((x[:, 0]) * 2 + 2, 0, 4).round()
        df = DataFrame.from_dict({"features": x, "label": rel, "group": groups})
        model = LightGBMRanker(num_iterations=10, num_leaves=7, learning_rate=0.3).fit(df)
        pred = model.transform(df).collect_column("prediction")
        assert np.corrcoef(pred, rel)[0, 1] > 0.6


class TestSharded:
    def test_sharded_matches_single_device(self, binary_data, mesh_dp8):
        x, y = binary_data
        kw = dict(objective="binary", num_iterations=8, learning_rate=0.2, num_leaves=15)
        b1 = train_booster(x, y, **kw)
        b8 = train_booster(x, y, mesh=mesh_dp8.mesh, **kw)
        # identical split decisions -> near-identical predictions
        np.testing.assert_allclose(b1.predict(x[:200]), b8.predict(x[:200]),
                                   rtol=1e-4, atol=1e-5)

    def test_sharded_uneven_rows(self, mesh_dp8, rng):
        # n not divisible by 8 exercises the padded-row path
        x = rng.normal(size=(1001, 4))
        y = (x[:, 0] > 0).astype(np.float32)
        b = train_booster(x, y, objective="binary", num_iterations=5,
                          learning_rate=0.3, mesh=mesh_dp8.mesh)
        assert ((b.predict(x) > 0.5) == y).mean() > 0.9


# ---------------------------------------------------------------------------
# TreeSHAP (reference featuresShap, booster/LightGBMBooster.scala:418)
# ---------------------------------------------------------------------------

def _expectation(feature, threshold, value, cover, x, known):
    """Conditional expectation of a heap tree given the feature subset
    ``known`` (the Shapley value function for trees)."""
    def rec(node):
        f = int(feature[node])
        if f < 0:
            return float(value[node])
        left, right = 2 * node + 1, 2 * node + 2
        if f in known:
            go_left = x[f] <= threshold[node]
            return rec(left if go_left else right)
        c = max(float(cover[node]), 1e-12)
        return (cover[left] / c) * rec(left) + (cover[right] / c) * rec(right)
    return rec(0)


def _brute_shapley(feature, threshold, value, cover, x, F):
    import itertools, math
    phi = np.zeros(F + 1)
    full = set(range(F))
    for i in range(F):
        others = full - {i}
        for r in range(len(others) + 1):
            for S in itertools.combinations(sorted(others), r):
                S = set(S)
                w = (math.factorial(len(S)) * math.factorial(F - len(S) - 1)
                     / math.factorial(F))
                phi[i] += w * (_expectation(feature, threshold, value, cover, x, S | {i})
                               - _expectation(feature, threshold, value, cover, x, S))
    phi[F] = _expectation(feature, threshold, value, cover, x, set())
    return phi


def test_treeshap_matches_bruteforce_shapley():
    """forest_shap == exact Shapley values computed by subset enumeration."""
    from synapseml_tpu.gbdt.booster import train_booster

    rs = np.random.default_rng(5)
    N, F = 400, 3
    X = rs.normal(size=(N, F))
    y = (2 * X[:, 0] - X[:, 1] + 0.5 * X[:, 0] * X[:, 2]
         + 0.1 * rs.normal(size=N)).astype(np.float32)
    b = train_booster(X, y, objective="regression", num_iterations=5,
                      learning_rate=0.5, num_leaves=8, max_depth=3)
    contrib = b.predict_contrib(X[:4])
    for i in range(4):
        want = np.zeros(F + 1)
        for t in range(b.num_iterations):
            want += _brute_shapley(b.feature[t, 0], b.threshold_value[t, 0],
                                   b.leaf_value[t, 0], b.cover[t, 0], X[i], F)
        want[F] += float(b.init_score[0])
        np.testing.assert_allclose(contrib[i, 0], want, atol=1e-5)


def test_treeshap_additivity_and_duplicate_features():
    """sum(contrib) == raw_score even with repeated features on a path
    (deep trees split the same feature multiple times)."""
    from synapseml_tpu.gbdt.booster import train_booster

    rs = np.random.default_rng(6)
    N, F = 500, 2
    X = rs.normal(size=(N, F))
    y = (np.sin(2 * X[:, 0]) + 0.3 * X[:, 1]).astype(np.float32)  # needs repeated splits on f0
    b = train_booster(X, y, objective="regression", num_iterations=10,
                      learning_rate=0.3, num_leaves=16, max_depth=5)
    Xt = X[:50]
    contrib = b.predict_contrib(Xt)
    np.testing.assert_allclose(contrib[:, 0, :].sum(-1), b.raw_score(Xt)[:, 0],
                               atol=1e-4)


def test_treeshap_multiclass_and_model_column():
    import synapseml_tpu as st
    from synapseml_tpu.gbdt import LightGBMClassifier

    rs = np.random.default_rng(7)
    N, F = 300, 4
    X = rs.normal(size=(N, F))
    y = np.argmax(X[:, :3] + 0.3 * rs.normal(size=(N, 3)), axis=1)
    df = st.DataFrame.from_rows(
        [{"features": X[i], "label": int(y[i])} for i in range(N)])
    model = LightGBMClassifier(num_iterations=8, learning_rate=0.3).fit(df)
    model.set(features_shap_col="shap")
    out = model.transform(df)
    shap_col = np.stack(list(out.collect_column("shap")))
    assert shap_col.shape == (N, 3, F + 1)
    raw = np.stack(list(out.collect_column("rawPrediction")))
    np.testing.assert_allclose(shap_col.sum(-1), raw, atol=1e-4)


# ---------------------------------------------------------------------------
# boosting modes (reference params/LightGBMParams.scala boostingType)
# ---------------------------------------------------------------------------

def _mode_dataset(seed=8, n=800):
    rs = np.random.default_rng(seed)
    X = rs.normal(size=(n, 6))
    y = (X[:, 0] + 0.5 * X[:, 1] - X[:, 2] > 0).astype(np.float32)
    return X, y


@pytest.mark.parametrize("mode", ["goss", "dart", "rf"])
def test_boosting_modes_accuracy(mode):
    from synapseml_tpu.gbdt.booster import train_booster

    X, y = _mode_dataset()
    kw = dict(objective="binary", num_iterations=30, num_leaves=15, seed=0)
    if mode == "rf":
        kw.update(bagging_fraction=0.7, bagging_freq=1, num_iterations=40)
    else:
        kw.update(learning_rate=0.2)
    b = train_booster(X, y, boosting_type=mode, **kw)
    acc = float(np.mean((b.predict(X) >= 0.5) == y))
    assert acc > 0.9, f"{mode} acc={acc}"
    assert b.params["boosting_type"] == mode
    if mode == "rf":
        assert b.average_output
        # averaged output keeps probabilities calibrated-ish (not summed blowup)
        p = b.predict(X)
        assert 0.0 < p.mean() < 1.0


def test_dart_additivity_after_rescaling():
    """DART mutates past trees; prediction from stored arrays must equal the
    training-time running scores (consistency of the normalization)."""
    from synapseml_tpu.gbdt.booster import train_booster

    X, y = _mode_dataset(seed=9, n=300)
    b = train_booster(X, y, objective="binary", boosting_type="dart",
                      num_iterations=12, learning_rate=0.3, num_leaves=7,
                      drop_rate=0.4, skip_drop=0.2, seed=3)
    # TreeSHAP additivity also exercises cover+scaled leaves coherently
    contrib = b.predict_contrib(X[:20])
    np.testing.assert_allclose(contrib[:, 0, :].sum(-1), b.raw_score(X[:20])[:, 0],
                               atol=1e-4)


def test_rf_with_early_stopping():
    from synapseml_tpu.gbdt.booster import train_booster

    X, y = _mode_dataset(seed=10)
    b = train_booster(X[:600], y[:600], objective="binary", boosting_type="rf",
                      bagging_fraction=0.7, bagging_freq=1, num_iterations=40,
                      valid_features=X[600:], valid_labels=y[600:],
                      early_stopping_round=5)
    acc = float(np.mean((b.predict(X[600:]) >= 0.5) == y[600:]))
    assert acc > 0.85


def test_train_measures_instrumentation():
    """Per-phase timing travels with the model (reference
    TaskInstrumentationMeasures, LightGBMPerformance.scala)."""
    import synapseml_tpu as st
    from synapseml_tpu.gbdt import LightGBMRegressor

    rs = np.random.default_rng(11)
    X = rs.normal(size=(200, 3))
    y = X[:, 0].astype(np.float32)
    df = st.DataFrame.from_rows(
        [{"features": X[i], "label": float(y[i])} for i in range(200)])
    model = LightGBMRegressor(num_iterations=5).fit(df)
    m = model.get_train_measures()
    assert m["iterations_count"] == 5
    assert m["binning_ms"] > 0 and m["training_ms"] > 0
    assert m["total_ms"] >= m["training_ms"]


def test_rf_shap_additivity():
    """rf averages trees; SHAP must scale accordingly (review regression)."""
    from synapseml_tpu.gbdt.booster import train_booster

    X, y = _mode_dataset(seed=12, n=300)
    b = train_booster(X, y, objective="binary", boosting_type="rf",
                      bagging_fraction=0.7, bagging_freq=1, num_iterations=10)
    contrib = b.predict_contrib(X[:20])
    np.testing.assert_allclose(contrib[:, 0, :].sum(-1), b.raw_score(X[:20])[:, 0],
                               atol=1e-4)


def test_dart_early_stopping_returns_measured_model():
    """With DART + early stopping, the returned trees must reproduce the
    validation scores that selected best_iteration (later drop-normalizations
    must not leak into the returned model)."""
    from synapseml_tpu.gbdt.booster import train_booster

    X, y = _mode_dataset(seed=13, n=600)
    b = train_booster(X[:400], y[:400], objective="binary", boosting_type="dart",
                      num_iterations=25, learning_rate=0.3, drop_rate=0.5,
                      skip_drop=0.1, valid_features=X[400:], valid_labels=y[400:],
                      early_stopping_round=3, seed=5)
    assert b.best_iteration is not None
    # stored forest is trimmed to the best iteration with snapshot leaf scales
    assert b.feature.shape[0] == b.best_iteration
    # additivity still holds on the snapshot
    contrib = b.predict_contrib(X[:10])
    np.testing.assert_allclose(contrib[:, 0, :].sum(-1), b.raw_score(X[:10])[:, 0],
                               atol=1e-4)


# ---------------------------------------------------------------------------
# LightGBM model-string interop (reference saveNativeModel / modelString,
# booster/LightGBMBooster.scala:458)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("objective", ["regression", "binary", "multiclass"])
def test_lightgbm_string_round_trip(objective):
    from synapseml_tpu.gbdt import parse_lightgbm_string, to_lightgbm_string
    from synapseml_tpu.gbdt.booster import train_booster

    rs = np.random.default_rng(21)
    X = rs.normal(size=(300, 5))
    if objective == "multiclass":
        y = np.argmax(X[:, :3], axis=1).astype(np.float32)
        kw = {"num_class": 3}
    elif objective == "binary":
        y = (X[:, 0] > 0).astype(np.float32)
        kw = {}
    else:
        y = (X[:, 0] * 2 + X[:, 1]).astype(np.float32)
        kw = {}
    b = train_booster(X, y, objective=objective, num_iterations=8,
                      learning_rate=0.3, num_leaves=7, **kw)
    text = to_lightgbm_string(b)
    assert "Tree=0" in text and "end of trees" in text
    imp = parse_lightgbm_string(text)
    np.testing.assert_allclose(imp.raw_score(X[:50]), b.raw_score(X[:50]),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(imp.predict(X[:50])),
                               np.asarray(b.predict(X[:50])),
                               rtol=1e-5, atol=1e-5)


def test_parse_handwritten_lightgbm_file():
    """Pin the format semantics against a hand-computed stock-LightGBM-style
    file: negative-child leaf encoding, default-left NaN routing."""
    from synapseml_tpu.gbdt import parse_lightgbm_string

    text = """tree
version=v3
num_class=1
num_tree_per_iteration=1
max_feature_idx=1
objective=regression

Tree=0
num_leaves=3
num_cat=0
split_feature=0 1
split_gain=10 5
threshold=0.5 -1
decision_type=10 0
left_child=1 -2
right_child=-1 -3
leaf_value=100 200 300
shrinkage=1

end of trees

parameters:
end of parameters
"""
    # node0: f0<=0.5, decision_type=10 = default-left + missing_type NaN
    # node1: f1<=-1, decision_type=0 = default-right
    imp = parse_lightgbm_string(text)
    X = np.array([
        [0.4, -2.0],   # left, left   -> 200
        [0.4, 0.0],    # left, right  -> 300
        [0.6, 9.9],    # right        -> 100
        [np.nan, 0.0], # default-left at node0, right at node1 -> 300
        [0.4, np.nan], # left, default-RIGHT at node1 -> 300
    ])
    got = imp.raw_score(X)[:, 0]
    np.testing.assert_allclose(got, [200, 300, 100, 300, 300])


def test_imported_booster_in_model_transformer(tmp_path):
    """save_native_model writes LightGBM format; the parsed booster slots into
    the classification model transformer."""
    import synapseml_tpu as st
    from synapseml_tpu.gbdt import (LightGBMClassificationModel,
                                    LightGBMClassifier, parse_lightgbm_string)

    rs = np.random.default_rng(22)
    X = rs.normal(size=(200, 4))
    y = (X[:, 0] - X[:, 1] > 0).astype(int)
    df = st.DataFrame.from_rows([{"features": X[i], "label": int(y[i])}
                                 for i in range(200)])
    model = LightGBMClassifier(num_iterations=10, learning_rate=0.3).fit(df)
    model.save_native_model(str(tmp_path / "native"))
    text = (tmp_path / "native" / "model.txt").read_text()
    assert "objective=binary sigmoid:1" in text

    imported = parse_lightgbm_string(text)
    m2 = LightGBMClassificationModel(booster=imported,
                                     classes=model.get("classes"))
    out1 = model.transform(df)
    out2 = m2.transform(df)
    np.testing.assert_array_equal(out1.collect_column("prediction"),
                                  out2.collect_column("prediction"))
    np.testing.assert_allclose(
        np.stack(list(out1.collect_column("probability"))),
        np.stack(list(out2.collect_column("probability"))), atol=1e-5)


def test_rf_mode_string_round_trip():
    from synapseml_tpu.gbdt import parse_lightgbm_string, to_lightgbm_string
    from synapseml_tpu.gbdt.booster import train_booster

    X, y = _mode_dataset(seed=23, n=300)
    b = train_booster(X, y, objective="binary", boosting_type="rf",
                      bagging_fraction=0.7, bagging_freq=1, num_iterations=6)
    imp = parse_lightgbm_string(to_lightgbm_string(b))
    assert imp.average_output
    np.testing.assert_allclose(imp.raw_score(X[:40]), b.raw_score(X[:40]),
                               rtol=1e-5, atol=1e-5)


def test_lightgbm_string_nan_round_trip():
    """NaN routing survives export/import (our trees route NaN right; the
    export declares missing_type=NaN with default-right)."""
    from synapseml_tpu.gbdt import parse_lightgbm_string, to_lightgbm_string
    from synapseml_tpu.gbdt.booster import train_booster

    rs = np.random.default_rng(24)
    X = rs.normal(size=(400, 3))
    X[rs.random(400) < 0.2, 0] = np.nan  # NaNs in a split feature
    y = (np.nan_to_num(X[:, 0]) + X[:, 1] > 0).astype(np.float32)
    b = train_booster(X, y, objective="binary", num_iterations=6,
                      learning_rate=0.3, num_leaves=7)
    imp = parse_lightgbm_string(to_lightgbm_string(b))
    Xt = X[:80]
    np.testing.assert_allclose(imp.raw_score(Xt), b.raw_score(Xt),
                               rtol=1e-5, atol=1e-5)


def test_imported_zero_as_missing_semantics():
    """missing_type=Zero (decision_type bit value 4): 0.0 and NaN follow the
    default direction."""
    from synapseml_tpu.gbdt import parse_lightgbm_string

    text = """tree
version=v3
num_class=1
num_tree_per_iteration=1
max_feature_idx=0
objective=regression

Tree=0
num_leaves=2
num_cat=0
split_feature=0
split_gain=1
threshold=-5
decision_type=6
left_child=-1
right_child=-2
leaf_value=111 222
shrinkage=1

end of trees
"""
    # decision_type=6 = default_left(2) + missing_type Zero(4):
    # 0.0 and NaN are missing -> LEFT (111); ordinary values compare to -5
    imp = parse_lightgbm_string(text)
    got = imp.raw_score(np.array([[0.0], [np.nan], [-7.0], [3.0]]))[:, 0]
    np.testing.assert_allclose(got, [111, 111, 111, 222])


def test_imported_num_iterations_clamped():
    from synapseml_tpu.gbdt import parse_lightgbm_string, to_lightgbm_string
    from synapseml_tpu.gbdt.booster import train_booster

    rs = np.random.default_rng(25)
    X = rs.normal(size=(200, 3))
    y = X[:, 0].astype(np.float32)
    b = train_booster(X, y, objective="regression", num_iterations=5)
    imp = parse_lightgbm_string(to_lightgbm_string(b))
    np.testing.assert_allclose(imp.raw_score(X[:10], num_iterations=50),
                               imp.raw_score(X[:10]), rtol=1e-6)


def test_poisson_objective_string_round_trip():
    """Link-carrying objectives survive the model-string round-trip (review
    regression: poisson must not degrade to plain regression)."""
    from synapseml_tpu.gbdt import parse_lightgbm_string, to_lightgbm_string
    from synapseml_tpu.gbdt.booster import train_booster

    rs = np.random.default_rng(26)
    X = rs.normal(size=(300, 3))
    y = rs.poisson(np.exp(0.5 * X[:, 0])).astype(np.float32)
    b = train_booster(X, y, objective="poisson", num_iterations=6,
                      learning_rate=0.2)
    text = to_lightgbm_string(b)
    assert "objective=poisson" in text
    assert "average_output" not in text  # presence == true in stock LightGBM
    imp = parse_lightgbm_string(text)
    np.testing.assert_allclose(np.asarray(imp.predict(X[:30])).ravel(),
                               np.asarray(b.predict(X[:30])).ravel(),
                               rtol=1e-5, atol=1e-5)


def test_tweedie_objective():
    """Tweedie (1<rho<2, log link): grad/hess match autodiff of the
    deviance, and a fitted regressor recovers group means of skewed
    nonnegative targets through the exp link."""
    import jax
    import jax.numpy as jnp

    from synapseml_tpu.gbdt.objectives import get_objective

    rho = 1.4
    o = get_objective("tweedie", tweedie_variance_power=rho)
    rs = np.random.default_rng(30)
    s = jnp.asarray(rs.normal(size=(50, 1)), jnp.float32)
    y = jnp.asarray(rs.gamma(2.0, 1.5, 50), jnp.float32)

    def deviance(si, yi):
        return (-yi * jnp.exp((1 - rho) * si) / (1 - rho)
                + jnp.exp((2 - rho) * si) / (2 - rho))

    grad, hess = o.grad_hess(s, y)
    want_g = jax.vmap(jax.grad(deviance))(s[:, 0], y)
    want_h = jax.vmap(jax.grad(jax.grad(deviance)))(s[:, 0], y)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(want_g),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(hess), np.asarray(want_h),
                               rtol=1e-4, atol=1e-5)

    # estimator surface: two regimes, predictions near the group means
    import synapseml_tpu as st
    from synapseml_tpu.gbdt import LightGBMRegressor

    X = np.zeros((400, 1), np.float32)
    X[200:] = 1.0
    yv = np.where(X[:, 0] > 0.5, rs.gamma(2.0, 3.0, 400),
                  rs.gamma(2.0, 0.5, 400)).astype(np.float32)
    df = st.DataFrame.from_dict({"features": X, "label": yv})
    model = LightGBMRegressor(objective="tweedie",
                              tweedie_variance_power=1.3,
                              num_iterations=40, learning_rate=0.2,
                              num_leaves=3).fit(df)
    pred = np.asarray(model.transform(df).collect_column("prediction"))
    assert np.all(pred > 0)  # log link: predictions live on the mean scale
    lo, hi = pred[:200].mean(), pred[200:].mean()
    assert abs(lo - yv[:200].mean()) < 0.3 * yv[:200].mean()
    assert abs(hi - yv[200:].mean()) < 0.3 * yv[200:].mean()

    with pytest.raises(ValueError, match="tweedie_variance_power"):
        get_objective("tweedie", tweedie_variance_power=2.5)

    # negative labels fail fast (LightGBM parity): the log-link hessian
    # would flip sign and silently destabilize leaf weights
    from synapseml_tpu.gbdt.booster import train_booster

    bad_y = yv.copy()
    bad_y[0] = -1.0
    with pytest.raises(ValueError, match="non-negative"):
        train_booster(X, bad_y, objective="tweedie", num_iterations=2)

    # model-string round-trip keeps the log link (like poisson)
    from synapseml_tpu.gbdt import parse_lightgbm_string, to_lightgbm_string

    b = model.get("booster")
    text = to_lightgbm_string(b)
    assert "objective=tweedie" in text
    imp = parse_lightgbm_string(text)
    np.testing.assert_allclose(np.asarray(imp.predict(X[:20])).ravel(),
                               np.asarray(b.predict(X[:20])).ravel(),
                               rtol=1e-5, atol=1e-5)


def test_gamma_and_mape_objectives():
    """Gamma (log link) grad/hess vs autodiff of the deviance; MAPE
    recovers group MEDIANS (L1-style) with per-row 1/|y| weighting."""
    import jax
    import jax.numpy as jnp

    from synapseml_tpu.gbdt.objectives import get_objective

    rs = np.random.default_rng(31)
    s = jnp.asarray(rs.normal(size=(40, 1)), jnp.float32)
    y = jnp.asarray(rs.gamma(2.0, 1.5, 40), jnp.float32)

    o = get_objective("gamma")

    def gamma_dev(si, yi):
        # gamma deviance (log link), up to y-only terms: si + yi e^{-si}
        return si + yi * jnp.exp(-si)

    grad, hess = o.grad_hess(s, y)
    want_g = jax.vmap(jax.grad(gamma_dev))(s[:, 0], y)
    want_h = jax.vmap(jax.grad(jax.grad(gamma_dev)))(s[:, 0], y)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(want_g),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(hess), np.asarray(want_h),
                               rtol=1e-4, atol=1e-5)

    # estimator surfaces: gamma predictions positive and near group means
    import synapseml_tpu as st
    from synapseml_tpu.gbdt import LightGBMRegressor

    X = np.zeros((400, 1), np.float32)
    X[200:] = 1.0
    yv = np.where(X[:, 0] > 0.5, rs.gamma(3.0, 2.0, 400),
                  rs.gamma(3.0, 0.5, 400)).astype(np.float32)
    df = st.DataFrame.from_dict({"features": X, "label": yv})
    g = LightGBMRegressor(objective="gamma", num_iterations=40,
                          learning_rate=0.2, num_leaves=3).fit(df)
    pred = np.asarray(g.transform(df).collect_column("prediction"))
    assert np.all(pred > 0)
    assert abs(pred[200:].mean() - yv[200:].mean()) < 0.3 * yv[200:].mean()

    m = LightGBMRegressor(objective="mape", num_iterations=150,
                          learning_rate=0.3, num_leaves=3).fit(df)
    mp = np.asarray(m.transform(df).collect_column("prediction"))

    def weighted_median(v):
        # MAPE's optimum: the 1/|y|-weighted median (small targets weigh more)
        w = 1.0 / np.maximum(np.abs(v), 1.0)
        order = np.argsort(v)
        cw = np.cumsum(w[order])
        return v[order][np.searchsorted(cw, cw[-1] / 2)]

    hi_target = weighted_median(yv[200:])
    lo_target = weighted_median(yv[:200])
    assert abs(mp[200:].mean() - hi_target) < 0.35 * hi_target, \
        (mp[200:].mean(), hi_target)
    assert mp[200:].mean() > mp[:200].mean() > 0  # group ordering preserved
    assert abs(mp[:200].mean() - lo_target) < 0.5 * max(lo_target, 1.0)

    # negative labels fail fast for gamma too
    from synapseml_tpu.gbdt.booster import train_booster

    bad = yv.copy(); bad[0] = -2.0
    with pytest.raises(ValueError, match="non-negative"):
        train_booster(X, bad, objective="gamma", num_iterations=2)


def test_imported_booster_save_native_round_trip(tmp_path):
    """Migrate-in models persist: ImportedBooster-backed transformers
    save_native_model and reload with identical scores."""
    import synapseml_tpu as st
    from synapseml_tpu.gbdt import (LightGBMRegressionModel, LightGBMRegressor,
                                    parse_lightgbm_string, to_lightgbm_string)

    rs = np.random.default_rng(27)
    X = rs.normal(size=(150, 3))
    y = X[:, 0].astype(np.float32)
    df = st.DataFrame.from_rows([{"features": X[i], "label": float(y[i])}
                                 for i in range(150)])
    m = LightGBMRegressor(num_iterations=5).fit(df)
    imp = parse_lightgbm_string(to_lightgbm_string(m.get_booster()))
    m2 = LightGBMRegressionModel(booster=imp)
    m2.save_native_model(str(tmp_path / "n2"))
    re_imp = parse_lightgbm_string((tmp_path / "n2" / "model.txt").read_text())
    np.testing.assert_allclose(re_imp.raw_score(X[:20]), imp.raw_score(X[:20]),
                               rtol=1e-6)


def test_model_cache_invalidated_on_set():
    """set(model_params=...) after a transform must take effect (review
    regression: the cached apply closure froze the old weights)."""
    import synapseml_tpu as st
    from synapseml_tpu.models import DeepTextClassifier

    rows = [{"text": "good", "label": 1}, {"text": "bad", "label": 0}] * 8
    df = st.DataFrame.from_rows(rows)
    m = DeepTextClassifier(checkpoint="bert-tiny", num_classes=2, batch_size=8,
                           max_token_len=8, max_steps=5,
                           learning_rate=3e-3).fit(df)
    p1 = np.stack(list(m.transform(df).collect_column("scores")))
    import jax
    zeroed = jax.tree.map(np.zeros_like, m.get("model_params"))
    m.set(model_params=zeroed)
    p2 = np.stack(list(m.transform(df).collect_column("scores")))
    assert not np.allclose(p1, p2)  # new params actually used


def test_monotone_constraints_enforced():
    """monotone_constraints (+1 on f0): predictions must be non-decreasing in
    f0 along a sweep with other features fixed (reference monotoneConstraints,
    'basic' method: split gating + midpoint bounds)."""
    from synapseml_tpu.gbdt.booster import train_booster

    rs = np.random.default_rng(30)
    N = 1500
    X = rs.uniform(-2, 2, size=(N, 3))
    # monotone-increasing signal in f0 with heavy noise (unconstrained trees
    # will show local violations)
    y = (X[:, 0] + 0.3 * np.sin(6 * X[:, 0]) + X[:, 1]
         + 0.6 * rs.normal(size=N)).astype(np.float32)
    kw = dict(objective="regression", num_iterations=40, learning_rate=0.15,
              num_leaves=31, seed=0)
    b_mono = train_booster(X, y, monotone_constraints=[1, 0, 0], **kw)

    sweep = np.linspace(-2, 2, 201)
    for other in (-1.0, 0.0, 1.0):
        grid = np.stack([sweep, np.full_like(sweep, other),
                         np.full_like(sweep, other)], axis=1)
        pred = np.asarray(b_mono.predict(grid)).ravel()
        diffs = np.diff(pred)
        assert np.all(diffs >= -1e-5), \
            f"monotonicity violated: min diff {diffs.min()}"
    # still a useful model, not a constant
    assert np.std(np.asarray(b_mono.predict(X[:200]))) > 0.3


def test_scale_pos_weight_and_is_unbalance():
    """Positive reweighting shifts predicted probabilities upward on an
    imbalanced binary task (reference scalePosWeight / isUnbalance)."""
    import synapseml_tpu as st
    from synapseml_tpu.gbdt import LightGBMClassifier

    rs = np.random.default_rng(31)
    N = 1000
    X = rs.normal(size=(N, 4))
    # noisy imbalanced task (~14% positives): leaves stay impure, so class
    # weighting actually moves the fitted probabilities
    y = ((X[:, 0] + rs.normal(0, 1.0, N) > 1.5)).astype(int)
    df = st.DataFrame.from_rows([{"features": X[i], "label": int(y[i])}
                                 for i in range(N)])
    base = LightGBMClassifier(num_iterations=20).fit(df)
    up = LightGBMClassifier(num_iterations=20, is_unbalance=True).fit(df)
    p0 = np.stack(list(base.transform(df).collect_column("probability")))[:, 1]
    p1 = np.stack(list(up.transform(df).collect_column("probability")))[:, 1]
    assert p1.mean() > p0.mean() + 0.02  # reweighting raised positive mass
    # recall on positives improves
    r0 = ((p0 >= 0.5) & (y == 1)).sum() / max(y.sum(), 1)
    r1 = ((p1 >= 0.5) & (y == 1)).sum() / max(y.sum(), 1)
    assert r1 >= r0


def test_monotone_constraint_validation():
    from synapseml_tpu.gbdt.booster import train_booster

    X = np.random.default_rng(0).normal(size=(100, 3))
    y = X[:, 0].astype(np.float32)
    with pytest.raises(ValueError, match="3 features"):
        train_booster(X, y, objective="regression", num_iterations=2,
                      monotone_constraints=[1])
    with pytest.raises(ValueError, match="-1/0"):
        train_booster(X, y, objective="regression", num_iterations=2,
                      monotone_constraints=[2, 0, 0])
    with pytest.raises(ValueError, match="not both"):
        train_booster(X, (y > 0).astype(np.float32), objective="binary",
                      num_iterations=2, is_unbalance=True, scale_pos_weight=5.0)
    # all-zero == unconstrained (no constrained program compiled)
    b = train_booster(X, y, objective="regression", num_iterations=2,
                      monotone_constraints=[0, 0, 0])
    assert b.num_iterations == 2


def test_predict_leaf_truncates_to_best_iteration():
    """predict_leaf follows best_iteration like raw_score (LightGBM defaults
    pred_leaf to the best iteration too), with num_iterations override."""
    from synapseml_tpu.gbdt.booster import train_booster

    X, y = _mode_dataset(seed=31, n=250)
    b = train_booster(X, y, objective="binary", num_iterations=8)
    assert b.predict_leaf(X[:20]).shape[1] == 8
    b.best_iteration = 3
    assert b.predict_leaf(X[:20]).shape[1] == 3
    assert b.predict_leaf(X[:20], num_iterations=6).shape[1] == 6


def test_imported_booster_shap_raises_clearly():
    """features_shap_col / predict_contrib on an imported booster (no cover
    stats) raise NotImplementedError, not an AttributeError."""
    import synapseml_tpu as st
    from synapseml_tpu.gbdt import (LightGBMClassificationModel,
                                    LightGBMClassifier, parse_lightgbm_string,
                                    to_lightgbm_string)

    rs = np.random.default_rng(32)
    X = rs.normal(size=(120, 3))
    y = (X[:, 0] > 0).astype(int)
    df = st.DataFrame.from_rows([{"features": X[i], "label": int(y[i])}
                                 for i in range(120)])
    model = LightGBMClassifier(num_iterations=4).fit(df)
    imported = parse_lightgbm_string(to_lightgbm_string(model.get_booster()))
    m2 = LightGBMClassificationModel(booster=imported,
                                     classes=model.get("classes"),
                                     features_shap_col="shap")
    with pytest.raises(NotImplementedError, match="cover statistics"):
        m2.transform(df)
    with pytest.raises(NotImplementedError, match="cover statistics"):
        m2.predict_contrib(X)


def test_histogram_backends_equivalent():
    """'onehot' (MXU matmul) and 'segment' (scatter) histogram backends grow
    identical forests and score identically (one-hot 0/1 values are exact, so
    only float summation order differs)."""
    from synapseml_tpu.gbdt.booster import train_booster

    X, y = _mode_dataset(seed=41, n=400)
    kw = dict(objective="binary", num_iterations=8, learning_rate=0.2,
              num_leaves=15, seed=0)
    b_seg = train_booster(X, y, histogram_impl="segment", **kw)
    b_oh = train_booster(X, y, histogram_impl="onehot", **kw)
    np.testing.assert_array_equal(b_seg.feature, b_oh.feature)
    np.testing.assert_allclose(b_seg.threshold_value, b_oh.threshold_value,
                               rtol=1e-6)
    np.testing.assert_allclose(b_seg.raw_score(X[:60]), b_oh.raw_score(X[:60]),
                               rtol=1e-4, atol=1e-5)


def test_pallas_histogram_matches_segment_sum():
    """The Pallas VMEM one-hot kernel (gbdt/pallas_hist.py) IS segment_sum:
    exact bin routing, f32 summation — including out-of-range padding ids
    and non-tile-aligned segment counts."""
    import jax
    import jax.numpy as jnp

    from synapseml_tpu.gbdt.pallas_hist import pallas_segment_histogram

    rs = np.random.default_rng(7)
    for n, wb in [(513, 130), (2048, 512), (100, 31 * 8)]:
        seg = rs.integers(0, wb + 5, n).astype(np.int32)  # some out-of-range
        data = rs.normal(size=(n, 3)).astype(np.float32)
        in_range = seg < wb
        ref = jax.ops.segment_sum(jnp.asarray(data[in_range]),
                                  jnp.asarray(seg[in_range]), num_segments=wb)
        got = pallas_segment_histogram(jnp.asarray(seg), jnp.asarray(data), wb)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


def test_pallas_histogram_backend_grows_same_tree():
    """hist_impl='pallas' grows the same forest as 'segment' (small config —
    the kernel runs in interpret mode on CPU)."""
    from synapseml_tpu.gbdt.booster import train_booster

    X, y = _mode_dataset(seed=41, n=200)
    kw = dict(objective="binary", num_iterations=3, learning_rate=0.2,
              num_leaves=7, max_bin=63, seed=0)
    b_seg = train_booster(X, y, histogram_impl="segment", **kw)
    b_pl = train_booster(X, y, histogram_impl="pallas", **kw)
    np.testing.assert_array_equal(b_seg.feature, b_pl.feature)
    np.testing.assert_allclose(b_seg.raw_score(X[:50]), b_pl.raw_score(X[:50]),
                               rtol=1e-4, atol=1e-5)


def test_warm_start_continued_training():
    """init_model continuation (reference modelString, LightGBMBase.scala:48-60):
    training resumes from the previous booster's margins, its trees ride
    along in the returned model, and the continued model beats the prefix."""
    X, y = _mode_dataset(seed=51, n=500)
    a = train_booster(X, y, objective="binary", num_iterations=5,
                      learning_rate=0.2, num_leaves=7, seed=0)
    b = train_booster(X, y, objective="binary", num_iterations=5,
                      learning_rate=0.2, num_leaves=7, seed=1, init_model=a)
    assert b.num_iterations == 10
    # continuation == the margins keep improving the train loss
    def logloss(m, n_it=None):
        p = np.clip(np.asarray(m.predict(X, num_iterations=n_it)).ravel(),
                    1e-6, 1 - 1e-6)
        return float(-np.mean(y * np.log(p) + (1 - y) * np.log1p(-p)))

    assert logloss(b) < logloss(a)
    # the first 5 trees of the continued model ARE the previous model
    np.testing.assert_allclose(b.raw_score(X[:50], num_iterations=5),
                               a.raw_score(X[:50]), rtol=1e-5, atol=1e-6)


def test_warm_start_from_model_string_and_estimator():
    X, y = _mode_dataset(seed=52, n=300)
    a = train_booster(X, y, objective="binary", num_iterations=4,
                      learning_rate=0.3, num_leaves=7, seed=0)
    from synapseml_tpu.gbdt.interop import to_lightgbm_string

    s = to_lightgbm_string(a)
    b = train_booster(X, y, objective="binary", num_iterations=3,
                      learning_rate=0.3, num_leaves=7, init_model=s)
    assert b.num_iterations == 7
    # the merged forest's first 4 trees reproduce the source model
    np.testing.assert_allclose(b.raw_score(X[:40], num_iterations=4),
                               a.raw_score(X[:40]), rtol=1e-4, atol=1e-4)

    df = DataFrame.from_dict({"features": X.astype(np.float32), "label": y})
    est = LightGBMClassifier(num_iterations=3, num_leaves=7, model_string=a)
    model = est.fit(df)
    assert model.get_booster().num_iterations == 7


def test_warm_start_validation():
    X, y = _mode_dataset(seed=53, n=200)
    a = train_booster(X, y, objective="binary", num_iterations=2, num_leaves=7)
    with pytest.raises(ValueError, match="features"):
        train_booster(X[:, :4], y, objective="binary", num_iterations=2,
                      init_model=a)
    rf = train_booster(X, y, objective="binary", num_iterations=2,
                       boosting_type="rf", bagging_fraction=0.8,
                       bagging_freq=1, num_leaves=7)
    with pytest.raises(ValueError, match="averaged"):
        train_booster(X, y, objective="binary", num_iterations=2,
                      init_model=rf)


def test_warm_start_truncates_early_stopped_prev():
    """Continuation from an early-stopped model must drop its stale
    post-best trees: merged prefix == prev's TRUNCATED raw scores."""
    X, y = _mode_dataset(seed=54, n=600)
    a = train_booster(X[:400], y[:400], objective="binary", num_iterations=100,
                      learning_rate=0.5, num_leaves=7,
                      valid_features=X[400:], valid_labels=y[400:],
                      early_stopping_round=2)
    assert a.best_iteration and a.best_iteration < a.num_iterations
    b = train_booster(X[:400], y[:400], objective="binary", num_iterations=3,
                      learning_rate=0.5, num_leaves=7, init_model=a)
    assert b.num_iterations == a.best_iteration + 3
    np.testing.assert_allclose(
        b.raw_score(X[:50], num_iterations=a.best_iteration),
        a.raw_score(X[:50]), rtol=1e-5, atol=1e-6)


def test_warm_start_rf_rejected():
    X, y = _mode_dataset(seed=55, n=200)
    a = train_booster(X, y, objective="binary", num_iterations=2, num_leaves=7)
    with pytest.raises(ValueError, match="rf"):
        train_booster(X, y, objective="binary", num_iterations=2,
                      boosting_type="rf", bagging_fraction=0.8,
                      bagging_freq=1, init_model=a)
