"""Test helper: hand-built torchvision-layout ResNet (no torchvision in the
image) + ONNX export that shims the absent ``onnx`` package with our own
proto codec (torch's exporter only needs it to splice custom onnxscript
functions, which standard convnets don't have)."""

from __future__ import annotations

import io
import sys
import types

import torch
from torch import nn


class Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, cin, width, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2d(cin, width, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(width)
        self.conv2 = nn.Conv2d(width, width, 3, stride, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(width)
        self.conv3 = nn.Conv2d(width, width * 4, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(width * 4)
        self.relu = nn.ReLU(inplace=True)
        self.downsample = downsample

    def forward(self, x):
        idt = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            idt = self.downsample(x)
        return self.relu(out + idt)


class TorchResNet(nn.Module):
    """torchvision-compatible naming: conv1/bn1/layer{1..4}.{j}.convK/
    downsample.0/fc — the state dict converts via
    convert_hf.resnet_variables_from_torch."""

    def __init__(self, layers=(3, 4, 6, 3), num_classes=1000, width0=64):
        super().__init__()
        self.num_stages = len(layers)
        self.inplanes = width0
        self.conv1 = nn.Conv2d(3, width0, 7, 2, 3, bias=False)
        self.bn1 = nn.BatchNorm2d(width0)
        self.relu = nn.ReLU(inplace=True)
        self.maxpool = nn.MaxPool2d(3, 2, 1)
        for i, n in enumerate(layers):
            setattr(self, f"layer{i + 1}",
                    self._make_layer(width0 * (2 ** i), n, 1 if i == 0 else 2))
        self.avgpool = nn.AdaptiveAvgPool2d((1, 1))
        self.fc = nn.Linear(self.inplanes, num_classes)

    def _make_layer(self, width, blocks, stride):
        down = None
        if stride != 1 or self.inplanes != width * 4:
            down = nn.Sequential(
                nn.Conv2d(self.inplanes, width * 4, 1, stride, bias=False),
                nn.BatchNorm2d(width * 4))
        layers = [Bottleneck(self.inplanes, width, stride, down)]
        self.inplanes = width * 4
        layers += [Bottleneck(self.inplanes, width) for _ in range(blocks - 1)]
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        for i in range(self.num_stages):
            x = getattr(self, f"layer{i + 1}")(x)
        x = self.avgpool(x).flatten(1)
        return self.fc(x)


def resnet50(num_classes=1000):
    return TorchResNet((3, 4, 6, 3), num_classes)


def resnet_small(num_classes=10):
    return TorchResNet((1, 1), num_classes, width0=8)


def _install_onnx_shim():
    """Minimal stand-in for the ``onnx`` package backed by our proto codec:
    torch's TorchScript exporter imports it only to scan for custom
    onnxscript functions (none in plain convnets)."""
    if "onnx" in sys.modules:
        return
    from synapseml_tpu.onnx.proto import parse_model

    class _Model:
        def __init__(self, parsed):
            self.graph = parsed.graph
            self.functions = []

    shim = types.ModuleType("onnx")
    shim.load_model_from_string = lambda b: _Model(parse_model(b))
    sys.modules["onnx"] = shim


def export_onnx_bytes(model: nn.Module, example: torch.Tensor) -> bytes:
    _install_onnx_shim()
    model.eval()
    buf = io.BytesIO()
    torch.onnx.export(model, example, buf, dynamo=False,
                      input_names=["input"], output_names=["logits"],
                      dynamic_axes={"input": {0: "N"}, "logits": {0: "N"}})
    return buf.getvalue()
