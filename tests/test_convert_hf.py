"""Pretrained-checkpoint ingestion parity: HF torch forward == converted Flax
forward on the same inputs (reference loads these checkpoints via
AutoModelForSequenceClassification / torchvision / AutoModelForCausalLM —
dl/DeepTextClassifier.py, dl/DeepVisionClassifier.py,
hf/HuggingFaceCausalLMTransform.py)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp  # noqa: E402

from synapseml_tpu.models import convert_hf as C  # noqa: E402

ATOL = 2e-4


def _save(model, tmp_path, config):
    d = tmp_path / "ckpt"
    model.eval()
    model.save_pretrained(d, safe_serialization=True)
    config.save_pretrained(d)
    return str(d)


@pytest.fixture(scope="module")
def bert_ckpt(tmp_path_factory):
    from transformers import BertConfig, BertForSequenceClassification

    torch.manual_seed(0)
    cfg = BertConfig(vocab_size=97, hidden_size=48, num_hidden_layers=2,
                     num_attention_heads=3, intermediate_size=96,
                     max_position_embeddings=64, num_labels=3,
                     hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    model = BertForSequenceClassification(cfg)
    d = _save(model, tmp_path_factory.mktemp("bert"), cfg)
    return d, model, cfg


def test_bert_sequence_classifier_parity(bert_ckpt):
    d, tmodel, tcfg = bert_ckpt
    from synapseml_tpu.models.flax_nets.bert import BertClassifier

    cfg, params = C.pretrained_text_classifier(d, num_classes=3,
                                               dtype=jnp.float32)
    assert cfg.n_heads == 3 and cfg.norm_position == "post"
    module = BertClassifier(cfg, num_classes=3)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, 97, (2, 10)).astype(np.int32)
    mask = np.ones((2, 10), np.int32)
    mask[1, 6:] = 0

    with torch.no_grad():
        want = tmodel(input_ids=torch.tensor(ids, dtype=torch.long),
                      attention_mask=torch.tensor(mask, dtype=torch.long)
                      ).logits.numpy()
    got = np.asarray(module.apply({"params": params}, jnp.asarray(ids),
                                  jnp.asarray(mask)))
    np.testing.assert_allclose(got, want, atol=ATOL)


def test_bert_encoder_parity(bert_ckpt):
    """Headless encoder path (HuggingFaceSentenceEmbedder backbone)."""
    d, tmodel, tcfg = bert_ckpt
    import flax.linen as nn

    from synapseml_tpu.models.flax_nets.bert import BertEmbeddings
    from synapseml_tpu.models.flax_nets.transformer import Encoder

    cfg, params = C.pretrained_encoder(d, dtype=jnp.float32)
    assert "classifier" not in params and "pooler" not in params

    class Net(nn.Module):
        @nn.compact
        def __call__(self, ids, mask):
            x = BertEmbeddings(cfg, name="embeddings")(ids)
            return Encoder(cfg, name="encoder")(
                x, mask[:, None, None, :].astype(bool))

    rng = np.random.default_rng(1)
    ids = rng.integers(0, 97, (2, 8)).astype(np.int32)
    mask = np.ones((2, 8), np.int32)
    with torch.no_grad():
        want = tmodel.bert(input_ids=torch.tensor(ids, dtype=torch.long),
                           attention_mask=torch.tensor(mask, dtype=torch.long)
                           ).last_hidden_state.numpy()
    got = np.asarray(Net().apply({"params": params}, jnp.asarray(ids),
                                 jnp.asarray(mask)))
    np.testing.assert_allclose(got, want, atol=ATOL)


def test_vit_parity(tmp_path):
    from transformers import ViTConfig, ViTForImageClassification

    torch.manual_seed(1)
    tcfg = ViTConfig(image_size=32, patch_size=8, num_channels=3,
                     hidden_size=48, num_hidden_layers=2, num_attention_heads=3,
                     intermediate_size=96, num_labels=5,
                     hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    tmodel = ViTForImageClassification(tcfg)
    d = _save(tmodel, tmp_path, tcfg)

    from synapseml_tpu.models.flax_nets.vit import ViTClassifier

    kind, info, variables = C.pretrained_vision(d, num_classes=5,
                                                dtype=jnp.float32)
    assert kind == "vit" and info["patch"] == 8
    module = ViTClassifier(info["cfg"], num_classes=5, patch=info["patch"])

    x = np.random.default_rng(2).normal(size=(2, 32, 32, 3)).astype(np.float32)
    with torch.no_grad():
        want = tmodel(torch.tensor(x.transpose(0, 3, 1, 2))).logits.numpy()
    got = np.asarray(module.apply(variables, jnp.asarray(x)))
    np.testing.assert_allclose(got, want, atol=ATOL)


def test_llama_parity_gqa(tmp_path):
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(2)
    tcfg = LlamaConfig(vocab_size=89, hidden_size=48, num_hidden_layers=2,
                       num_attention_heads=4, num_key_value_heads=2,
                       intermediate_size=96, max_position_embeddings=64,
                       rms_norm_eps=1e-5, attention_dropout=0.0)
    tmodel = LlamaForCausalLM(tcfg)
    d = _save(tmodel, tmp_path, tcfg)

    from synapseml_tpu.models.flax_nets.llama import LlamaLM

    cfg, params = C.pretrained_causal_lm(d, dtype=jnp.float32)
    assert cfg.n_heads == 4 and cfg.kv_heads == 2 and cfg.causal
    module = LlamaLM(cfg)

    ids = np.random.default_rng(3).integers(0, 89, (2, 12)).astype(np.int32)
    with torch.no_grad():
        want = tmodel(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    got = np.asarray(module.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, atol=5e-4)


def test_gpt2_parity(tmp_path):
    """GPT-2 family ingestion: learned positions, LayerNorm pre-norm with
    biases, fused-qkv Conv1D split, tied LM head — logits vs torch."""
    from transformers import GPT2Config, GPT2LMHeadModel

    torch.manual_seed(7)
    tcfg = GPT2Config(vocab_size=93, n_embd=48, n_layer=2, n_head=4,
                      n_positions=64, resid_pdrop=0.0, embd_pdrop=0.0,
                      attn_pdrop=0.0)
    tmodel = GPT2LMHeadModel(tcfg)
    d = _save(tmodel, tmp_path, tcfg)

    from synapseml_tpu.models.flax_nets.llama import LlamaLM

    cfg, params = C.pretrained_causal_lm(d, dtype=jnp.float32)
    assert cfg.learned_pos and cfg.norm == "layernorm" and cfg.causal
    assert cfg.act == "gelu_tanh" and not cfg.use_rope
    module = LlamaLM(cfg)

    ids = np.random.default_rng(8).integers(0, 93, (2, 10)).astype(np.int32)
    with torch.no_grad():
        want = tmodel(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    got = np.asarray(module.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, atol=5e-4)


def test_gpt2_greedy_decode_matches_torch(tmp_path):
    from transformers import GPT2Config, GPT2LMHeadModel

    torch.manual_seed(9)
    tcfg = GPT2Config(vocab_size=61, n_embd=32, n_layer=2, n_head=4,
                      n_positions=48, resid_pdrop=0.0, embd_pdrop=0.0,
                      attn_pdrop=0.0)
    tmodel = GPT2LMHeadModel(tcfg)
    d = _save(tmodel, tmp_path, tcfg)

    from synapseml_tpu.models.flax_nets.llama import LlamaLM, greedy_generate

    cfg, params = C.pretrained_causal_lm(d, dtype=jnp.float32)
    prompt = np.random.default_rng(10).integers(0, 61, (1, 6)).astype(np.int32)
    got = np.asarray(greedy_generate(LlamaLM(cfg, decode=True), params,
                                     jnp.asarray(prompt), max_new_tokens=8))
    want = tmodel.generate(torch.tensor(prompt, dtype=torch.long),
                           max_new_tokens=8, do_sample=False,
                           pad_token_id=0).numpy()
    np.testing.assert_array_equal(got[:, prompt.shape[1]:],
                                  want[:, prompt.shape[1]:])


def test_gpt2_through_causal_lm_transformer(tmp_path):
    # the user-facing path: checkpoint dir -> HuggingFaceCausalLM -> decode
    from transformers import GPT2Config, GPT2LMHeadModel

    torch.manual_seed(11)
    tcfg = GPT2Config(vocab_size=61, n_embd=32, n_layer=1, n_head=4,
                      n_positions=48, resid_pdrop=0.0, embd_pdrop=0.0,
                      attn_pdrop=0.0)
    d = _save(GPT2LMHeadModel(tcfg), tmp_path, tcfg)

    from synapseml_tpu.core import DataFrame
    from synapseml_tpu.hf import HuggingFaceCausalLM
    from synapseml_tpu.models.tokenizer import HashingTokenizer

    lm = HuggingFaceCausalLM(model_name=d, max_new_tokens=4,
                             tokenizer=HashingTokenizer(vocab_size=61),
                             prompt_bucket=16)  # fit the 48-position cache
    df = DataFrame.from_dict({"prompt": np.asarray(["hello there"],
                                                   dtype=object)})
    gens = list(lm.transform(df).collect_column("completions"))
    assert len(gens) == 1 and len(gens[0]) == 4


def test_mixtral_parity_sparse_moe(tmp_path):
    """Mixtral-family ingestion: SwiGLU experts + top-2 routing converted
    from a (tiny, random) HF MixtralForCausalLM, logits vs torch."""
    from transformers import MixtralConfig, MixtralForCausalLM

    torch.manual_seed(5)
    tcfg = MixtralConfig(vocab_size=97, hidden_size=32, num_hidden_layers=2,
                         num_attention_heads=4, num_key_value_heads=2,
                         intermediate_size=48, max_position_embeddings=64,
                         num_local_experts=4, num_experts_per_tok=2,
                         rms_norm_eps=1e-5, attention_dropout=0.0,
                         sliding_window=None, output_router_logits=False)
    tmodel = MixtralForCausalLM(tcfg)
    d = _save(tmodel, tmp_path, tcfg)

    from synapseml_tpu.models.flax_nets.llama import LlamaLM

    cfg, params = C.pretrained_causal_lm(d, dtype=jnp.float32)
    assert cfg.moe_experts == 4 and cfg.moe_top_k == 2 and cfg.gated_mlp
    assert cfg.moe_capacity_factor >= 4.0 / 2  # dropless: C = S exactly
    module = LlamaLM(cfg)

    ids = np.random.default_rng(6).integers(0, 97, (2, 10)).astype(np.int32)
    with torch.no_grad():
        want = tmodel(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    got = np.asarray(module.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, atol=2e-3, rtol=1e-3)

    # end-to-end greedy decode parity through the KV-cache path (MoE runs
    # per decode step on a single-token slice)
    from synapseml_tpu.models.flax_nets.llama import LlamaLM as LM, greedy_generate

    with torch.no_grad():
        twant = tmodel.generate(torch.tensor(ids[:1], dtype=torch.long),
                                max_new_tokens=5, do_sample=False,
                                num_beams=1).numpy()
    ours = np.asarray(greedy_generate(LM(cfg, decode=True), params,
                                      jnp.asarray(ids[:1]), 5))
    np.testing.assert_array_equal(ours, twant)


def test_resnet_parity_hf(tmp_path):
    from transformers import ResNetConfig, ResNetForImageClassification

    torch.manual_seed(3)
    tcfg = ResNetConfig(embedding_size=8, hidden_sizes=[32, 64], depths=[1, 1],
                        layer_type="bottleneck", num_labels=4)
    tmodel = ResNetForImageClassification(tcfg)
    d = _save(tmodel, tmp_path, tcfg)

    from synapseml_tpu.models.flax_nets.resnet import ResNet

    kind, arch, variables = C.pretrained_vision(d, num_classes=4)
    assert kind == "resnet"
    module = ResNet(num_classes=4, dtype=jnp.float32, **arch)

    x = np.random.default_rng(4).normal(size=(2, 32, 32, 3)).astype(np.float32)
    with torch.no_grad():
        want = tmodel(torch.tensor(x.transpose(0, 3, 1, 2))).logits.numpy()
    got = np.asarray(module.apply(variables, jnp.asarray(x)))
    np.testing.assert_allclose(got, want, atol=ATOL)


def test_torchvision_style_resnet_keys(tmp_path):
    """torchvision naming (layer1.0.conv1 / downsample.0) converts too —
    the DeepVisionClassifier reference consumes torchvision backbones."""
    from transformers import ResNetConfig, ResNetForImageClassification

    torch.manual_seed(4)
    tcfg = ResNetConfig(embedding_size=8, hidden_sizes=[32, 64], depths=[1, 1],
                        layer_type="bottleneck", num_labels=4)
    tmodel = ResNetForImageClassification(tcfg).eval()
    hf_sd = {k: v.numpy() for k, v in tmodel.state_dict().items()}
    tv_sd = C._hf_resnet_to_torchvision_keys(hf_sd)
    assert "conv1.weight" in tv_sd and "layer1.0.conv1.weight" in tv_sd
    assert "layer2.0.downsample.0.weight" in tv_sd and "fc.weight" in tv_sd

    from synapseml_tpu.models.flax_nets.resnet import ResNet

    variables = C.resnet_variables_from_torch(tv_sd)
    module = ResNet(stage_sizes=(1, 1), block="bottleneck", width=8,
                    num_classes=4, dtype=jnp.float32)
    x = np.random.default_rng(5).normal(size=(1, 32, 32, 3)).astype(np.float32)
    with torch.no_grad():
        want = tmodel(torch.tensor(x.transpose(0, 3, 1, 2))).logits.numpy()
    got = np.asarray(module.apply(variables, jnp.asarray(x)))
    np.testing.assert_allclose(got, want, atol=ATOL)


def test_sharded_safetensors_index(tmp_path, bert_ckpt):
    """Sharded checkpoints (model.safetensors.index.json) load too."""
    import json

    from safetensors.numpy import save_file

    d0, _, _ = bert_ckpt
    sd = C.load_safetensors(str(d0) + "/model.safetensors")
    keys = sorted(sd)
    half = len(keys) // 2
    shard_map = {}
    for name, ks in [("model-00001-of-00002.safetensors", keys[:half]),
                     ("model-00002-of-00002.safetensors", keys[half:])]:
        save_file({k: sd[k] for k in ks}, tmp_path / name)
        shard_map.update({k: name for k in ks})
    with open(tmp_path / "model.safetensors.index.json", "w") as f:
        json.dump({"weight_map": shard_map}, f)
    re = C.load_safetensors(str(tmp_path / "model.safetensors.index.json"))
    assert sorted(re) == keys
    np.testing.assert_array_equal(re[keys[0]], sd[keys[0]])


# ---------------------------------------------------------------------------
# estimator wiring: checkpoint-dir -> fit/transform end to end
# ---------------------------------------------------------------------------

def test_deep_text_classifier_from_checkpoint_dir(bert_ckpt):
    d, _, _ = bert_ckpt
    import synapseml_tpu as st
    from synapseml_tpu.models import DeepTextClassifier
    from synapseml_tpu.models.tokenizer import HashingTokenizer

    rows = ([{"text": "good great fine", "label": 1},
             {"text": "bad awful poor", "label": 0}] * 12)
    df = st.DataFrame.from_rows(rows)
    est = DeepTextClassifier(checkpoint=d, num_classes=2, batch_size=8,
                             max_token_len=16, max_steps=25, learning_rate=5e-3,
                             tokenizer=HashingTokenizer(vocab_size=97))
    model = est.fit(df)
    out = model.transform(df)
    acc = float(np.mean(out.collect_column("prediction")
                        == out.collect_column("label")))
    # the reference gate: accuracy > 0.5 after a short fine-tune
    # (deep-learning/src/test/python/.../test_deep_text_classifier.py:48-52)
    assert acc > 0.5
    # arch came from the checkpoint's config.json, not a preset
    assert model.get("arch_config").hidden == 48

    # save/load roundtrip keeps the pretrained architecture
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        model.save(td + "/m")
        re = type(model).load(td + "/m")
        out2 = re.transform(df)
        np.testing.assert_array_equal(out.collect_column("prediction"),
                                      out2.collect_column("prediction"))


def test_deep_vision_classifier_from_resnet_dir(tmp_path):
    from transformers import ResNetConfig, ResNetForImageClassification

    torch.manual_seed(5)
    tcfg = ResNetConfig(embedding_size=8, hidden_sizes=[32, 64], depths=[1, 1],
                        layer_type="bottleneck", num_labels=2)
    d = _save(ResNetForImageClassification(tcfg), tmp_path, tcfg)

    import synapseml_tpu as st
    from synapseml_tpu.models import DeepVisionClassifier

    rng = np.random.default_rng(0)
    rows = []
    for i in range(24):
        label = i % 2
        img = np.full((16, 16, 3), label, np.float32) + \
            rng.normal(0, 0.1, (16, 16, 3)).astype(np.float32)
        rows.append({"image": img, "label": label})
    df = st.DataFrame.from_rows(rows)
    model = DeepVisionClassifier(backbone=d, num_classes=2, batch_size=8,
                                 max_steps=20, learning_rate=5e-3).fit(df)
    out = model.transform(df)
    acc = float(np.mean(out.collect_column("prediction")
                        == out.collect_column("label")))
    assert acc > 0.5


def test_causal_lm_from_checkpoint_dir(tmp_path):
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(6)
    tcfg = LlamaConfig(vocab_size=89, hidden_size=48, num_hidden_layers=2,
                       num_attention_heads=4, num_key_value_heads=2,
                       intermediate_size=96, max_position_embeddings=128)
    tmodel = LlamaForCausalLM(tcfg)
    d = _save(tmodel, tmp_path, tcfg)

    import synapseml_tpu as st
    from synapseml_tpu.hf import HuggingFaceCausalLM
    from synapseml_tpu.models.tokenizer import HashingTokenizer

    lm = HuggingFaceCausalLM(model_name=d, max_new_tokens=4, batch_size=2,
                             prompt_bucket=8,
                             tokenizer=HashingTokenizer(vocab_size=89))
    df = st.DataFrame.from_rows([{"prompt": "hello world"},
                                 {"prompt": "the quick brown fox"}])
    out = lm.transform(df)
    gens = list(out.collect_column("completions"))
    assert len(gens) == 2 and all(len(g) == 4 for g in gens)

    # greedy parity with HF on the first step: same next token from the
    # pretrained weights (full-prompt, no padding)
    from synapseml_tpu.models.convert_hf import pretrained_causal_lm
    from synapseml_tpu.models.flax_nets.llama import LlamaLM

    cfg, params = pretrained_causal_lm(d, dtype=jnp.float32)
    ids = np.array([[3, 14, 15, 9, 26]], np.int32)
    with torch.no_grad():
        want = tmodel(torch.tensor(ids, dtype=torch.long)).logits[0, -1].argmax().item()
    logits = LlamaLM(cfg).apply({"params": params}, jnp.asarray(ids))
    assert int(np.asarray(logits)[0, -1].argmax()) == want


def test_sentence_embedder_from_checkpoint_dir(bert_ckpt):
    d, tmodel, _ = bert_ckpt
    import synapseml_tpu as st
    from synapseml_tpu.hf import HuggingFaceSentenceEmbedder
    from synapseml_tpu.models.tokenizer import HashingTokenizer

    emb = HuggingFaceSentenceEmbedder(model_name=d, max_token_len=16,
                                      tokenizer=HashingTokenizer(vocab_size=97),
                                      normalize=True)
    df = st.DataFrame.from_rows([{"text": "alpha beta"}, {"text": "gamma"}])
    out = np.asarray(list(emb.transform(df).collect_column("embeddings")))
    assert out.shape == (2, 48)
    np.testing.assert_allclose(np.linalg.norm(out, axis=-1), 1.0, atol=1e-5)


def test_vocab_guard_on_pretrained_paths(tmp_path):
    """Oversized tokenizer vocab vs checkpoint embedding table fails loudly on
    every pretrained path (ids would be silently clamped by XLA gather)."""
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(7)
    tcfg = LlamaConfig(vocab_size=89, hidden_size=48, num_hidden_layers=1,
                       num_attention_heads=4, num_key_value_heads=2,
                       intermediate_size=96, max_position_embeddings=64)
    d = _save(LlamaForCausalLM(tcfg), tmp_path, tcfg)

    import synapseml_tpu as st
    from synapseml_tpu.hf import HuggingFaceCausalLM
    from synapseml_tpu.models.tokenizer import HashingTokenizer

    lm = HuggingFaceCausalLM(model_name=d, tokenizer=HashingTokenizer())  # 30522
    df = st.DataFrame.from_rows([{"prompt": "x"}])
    with pytest.raises(ValueError, match="exceeds the checkpoint"):
        lm.transform(df)

    # tokenizer=None on a model-only dir gives an actionable error, not a loop
    lm2 = HuggingFaceCausalLM(model_name=d)
    with pytest.raises(ValueError, match="pass tokenizer="):
        lm2.transform(df)


def test_legacy_prenorm_artifact_detection():
    """DeepTextModel artifacts saved before the post-norm change (pre-norm
    param layout, no arch_config) must evaluate with the architecture they
    were trained as, not the new post-norm preset."""
    import dataclasses

    import jax
    from synapseml_tpu.models.flax_nets.bert import BertClassifier, bert_tiny
    from synapseml_tpu.models.text import DeepTextModel
    from synapseml_tpu.models.tokenizer import HashingTokenizer

    tok = HashingTokenizer(vocab_size=64)
    old_cfg = dataclasses.replace(bert_tiny(vocab_size=64), norm_position="pre",
                                  norm_eps=1e-6, act="gelu_tanh",
                                  dtype=jnp.float32)
    module = BertClassifier(old_cfg, num_classes=2)
    ids = np.ones((1, 8), np.int32)
    params = module.init(jax.random.PRNGKey(0), ids, np.ones((1, 8), np.int32))["params"]
    assert "LayerNorm_0" in params["encoder"]  # pre-norm final-norm layout

    model = DeepTextModel(model_params=jax.tree.map(np.asarray, params),
                          arch_config=None, tokenizer_config=tok.to_config(),
                          checkpoint="bert-tiny", num_classes=2,
                          max_token_len=8, batch_size=4)
    import synapseml_tpu as st

    df = st.DataFrame.from_rows([{"text": "hello world"}])
    out = model.transform(df)  # post-norm module would fail/mis-bind; must work
    want = np.asarray(jax.nn.softmax(module.apply(
        {"params": params}, *[jnp.asarray(v) for v in tok(["hello world"], max_len=8).values()]), -1))
    got = np.asarray(list(out.collect_column("scores")))[0]
    # the served model computes in bf16 (arch default); reference is f32
    np.testing.assert_allclose(got, want[0], atol=5e-3)


def test_mixtral_through_causal_lm_transformer(tmp_path):
    """The user-facing path: a Mixtral checkpoint dir on HuggingFaceCausalLM
    batch inference (greedy, KV cache), hashing tokenizer supplied like any
    tokenizer-less local checkpoint."""
    from transformers import MixtralConfig, MixtralForCausalLM

    torch.manual_seed(7)
    tcfg = MixtralConfig(vocab_size=61, hidden_size=16, num_hidden_layers=1,
                         num_attention_heads=2, num_key_value_heads=2,
                         intermediate_size=32, max_position_embeddings=64,
                         num_local_experts=2, num_experts_per_tok=2,
                         sliding_window=None)
    tmodel = MixtralForCausalLM(tcfg)
    d = _save(tmodel, tmp_path, tcfg)

    import synapseml_tpu as st
    from synapseml_tpu.hf import HuggingFaceCausalLM
    from synapseml_tpu.models.tokenizer import HashingTokenizer

    lm = HuggingFaceCausalLM(model_name=d, max_new_tokens=4, batch_size=2,
                             prompt_bucket=8,
                             tokenizer=HashingTokenizer(vocab_size=61))
    df = st.DataFrame.from_rows([{"prompt": "route me through experts"},
                                 {"prompt": "sparse mixture decoding"}])
    out = lm.transform(df)
    gens = list(out.collect_column("completions"))
    assert len(gens) == 2 and all(len(g) == 4 for g in gens)
