"""train/ and automl/ module tests."""

import numpy as np
import pytest

from synapseml_tpu.core import DataFrame
from synapseml_tpu.train import (
    ComputeModelStatistics,
    ComputePerInstanceStatistics,
    TrainClassifier,
    TrainRegressor,
)
from synapseml_tpu.train.statistics import confusion_matrix, roc_auc
from synapseml_tpu.automl import (
    DiscreteHyperParam,
    FindBestModel,
    HyperparamBuilder,
    RangeHyperParam,
    TuneHyperparameters,
)
from synapseml_tpu.gbdt import LightGBMClassifier, LightGBMRegressor


def make_mixed_df(n=160, seed=0):
    rng = np.random.default_rng(seed)
    num = rng.normal(size=n)
    cat = np.array(["a", "b"])[(rng.random(n) > 0.5).astype(int)]
    label = ((num > 0) ^ (cat == "b")).astype(np.int32)
    return DataFrame.from_dict({"num": num, "cat": cat, "label": label},
                               num_partitions=2)


def test_train_classifier_mixed_columns():
    df = make_mixed_df()
    model = TrainClassifier(model=LightGBMClassifier(num_iterations=20)).fit(df)
    out = model.transform(df)
    acc = (out.collect_column("prediction") == df.collect_column("label")).mean()
    assert acc > 0.9


def test_train_classifier_string_labels():
    df = make_mixed_df()
    df = df.with_column("label", np.where(df.collect_column("label") == 1, "yes", "no"))
    model = TrainClassifier(model=LightGBMClassifier(num_iterations=15)).fit(df)
    out = model.transform(df)
    assert set(np.unique(out.collect_column("predicted_label"))) <= {"yes", "no"}
    acc = (out.collect_column("predicted_label") == df.collect_column("label")).mean()
    assert acc > 0.9


def test_train_regressor():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(200, 3))
    y = x @ np.array([1.0, -2.0, 0.5]) + 0.01 * rng.normal(size=200)
    df = DataFrame.from_dict({"f0": x[:, 0], "f1": x[:, 1], "f2": x[:, 2], "label": y})
    model = TrainRegressor(model=LightGBMRegressor(num_iterations=50)).fit(df)
    pred = model.transform(df).collect_column("prediction")
    assert np.corrcoef(pred, y)[0, 1] > 0.95


def test_compute_model_statistics_classification():
    df = DataFrame.from_dict({"label": np.array([0, 0, 1, 1]),
                              "prediction": np.array([0, 1, 1, 1]),
                              "probability": np.array([0.1, 0.6, 0.8, 0.9])})
    stats = ComputeModelStatistics(scored_probabilities_col="probability").transform(df)
    row = stats.collect_rows()[0]
    assert row["accuracy"] == 0.75
    assert row["AUC"] == 1.0
    np.testing.assert_array_equal(row["confusion_matrix"], [[1, 1], [0, 2]])


def test_compute_model_statistics_regression():
    y = np.array([1.0, 2.0, 3.0])
    df = DataFrame.from_dict({"label": y, "prediction": y + 0.1})
    row = ComputeModelStatistics(evaluation_metric="regression").transform(df).collect_rows()[0]
    np.testing.assert_allclose(row["mean_squared_error"], 0.01, atol=1e-9)
    assert row["R^2"] > 0.98


def test_roc_auc_and_confusion():
    assert roc_auc(np.array([0, 0, 1, 1]), np.array([0.1, 0.4, 0.35, 0.8])) == 0.75
    cm = confusion_matrix(np.array(["x", "y"]), np.array(["x", "x"]))
    np.testing.assert_array_equal(cm, [[1, 0], [1, 0]])


def test_per_instance_statistics():
    df = DataFrame.from_dict({"label": np.array([0, 1]),
                              "prediction": np.array([0, 0]),
                              "probability": np.array([0.2, 0.3])})
    out = ComputePerInstanceStatistics(scored_probabilities_col="probability").transform(df)
    np.testing.assert_array_equal(out.collect_column("correct"), [1.0, 0.0])
    np.testing.assert_allclose(out.collect_column("log_loss"),
                               [-np.log(0.8), -np.log(0.3)])
    reg = ComputePerInstanceStatistics(evaluation_metric="regression").transform(
        DataFrame.from_dict({"label": np.array([1.0]), "prediction": np.array([1.5])}))
    np.testing.assert_allclose(reg.collect_column("squared_error"), [0.25])


def test_tune_hyperparameters(tabular_df):
    space = (HyperparamBuilder()
             .add_hyperparam("num_leaves", DiscreteHyperParam([4, 15]))
             .add_hyperparam("num_iterations", RangeHyperParam(5, 15))
             .build())
    best = TuneHyperparameters(models=[LightGBMClassifier()], hyperparam_space=space,
                               num_runs=3, parallelism=2,
                               evaluation_metric="accuracy", seed=7).fit(tabular_df)
    assert best.get("best_metric") > 0.7
    assert "num_leaves" in best.get("best_params")
    out = best.transform(tabular_df)
    assert "prediction" in out.columns


def test_find_best_model(tabular_df):
    models = [LightGBMClassifier(num_iterations=3),
              LightGBMClassifier(num_iterations=25)]
    res = FindBestModel(models=models).fit(tabular_df)
    assert res.get("best_metric") >= 0.8
    assert len(res.get("all_model_metrics")) == 2
