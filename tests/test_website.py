"""Static website tier (reference: the docusaurus ``website/`` over docs
markdown, with doctest.py running its code blocks — here the docs-as-tests
suites are the doctest tier and the site is emitted by codegen/website.py,
committed and drift-tested like the notebook corpus)."""

import os

import pytest

from synapseml_tpu.codegen.website import emit_site, markdown_to_html

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SITE = os.path.join(REPO, "docs", "site")


def test_site_has_no_drift(tmp_path):
    out = emit_site(out_dir=str(tmp_path))
    regenerated = {os.path.basename(p) for p in out}
    committed = {n for n in os.listdir(SITE) if n.endswith(".html")}
    assert regenerated == committed, (
        "site drift: regenerate with `python synapseml_tpu/codegen/website.py`"
        f" (missing={sorted(regenerated - committed)},"
        f" stale={sorted(committed - regenerated)})")
    for name in sorted(regenerated):
        with open(os.path.join(str(tmp_path), name)) as f:
            fresh = f.read()
        with open(os.path.join(SITE, name)) as f:
            assert f.read() == fresh, (
                f"{name} is stale — regenerate with "
                f"`python synapseml_tpu/codegen/website.py`")


def test_site_index_links_resolve():
    with open(os.path.join(SITE, "index.html")) as f:
        index = f.read()
    import re

    for href in re.findall(r'href="([^"]+\.html)"', index):
        assert os.path.exists(os.path.join(SITE, href)), f"dangling link {href}"
    assert "API reference" in index and "Notebook corpus" in index


@pytest.mark.parametrize("md,expect", [
    ("# Title", "<h1>Title</h1>"),
    ("plain `code` here", "<code>code</code>"),
    ("a [link](x.html) b", '<a href="x.html">link</a>'),
    ("**bold** and *em*", "<strong>bold</strong>"),
    ("- one\n- two", "<li>one</li>"),
    ("1. first\n2. second", "<ol>"),
    ("> quoted", "<blockquote>quoted</blockquote>"),
])
def test_markdown_renderer_constructs(md, expect):
    assert expect in markdown_to_html(md)


def test_markdown_code_fence_escapes_html():
    out = markdown_to_html("```\nx = a < b & c\n<script>\n```")
    assert "<script>" not in out
    assert "&lt;script&gt;" in out
    assert out.count("<pre><code>") == 1


def test_markdown_table():
    out = markdown_to_html("| a | b |\n|---|---|\n| 1 | `c` |")
    assert "<table>" in out and "<th>a</th>" in out
    assert "<td><code>c</code></td>" in out


def test_markdown_paragraph_joins_wrapped_lines():
    out = markdown_to_html("first line\nsecond line\n\nnew para")
    assert out.count("<p>") == 2
    assert "first line second line" in out
