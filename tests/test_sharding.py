"""Sharding plane (ISSUE 10): regex partition rules, ZeRO-sharded weight
updates, pipeline-stage training, and the sharded publish->load->serve
round trip.

Covers: the matcher's first-match-wins / scalar-skip / unmatched-leaf
semantics and JSON round trip; rule-table placement over plain pytrees;
optimizer-state spec inheritance + ZeRO replica-group sharding; ZeRO-vs-
replicated training parity (per-step losses AND final params under one
seeded DataLoader stream) with the per-replica memory bound; pipeline-
split fit parity vs the single-stage chain on a 2-stage CPU mesh;
checkpoint sharding metadata + the path-aware shard-slice restore through
``fit_source(resume_from=...)``; and the registry manifest ``sharding``
section applied by ``/admin/load`` before warmup — with the mismatched-
mesh demote-to-replicated path."""

import json
import logging
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from _sharding_pipeline import make_lm_pipeline, prompt_rows
from synapseml_tpu.core.dataframe import DataFrame
from synapseml_tpu.models.pipeline_trainer import PipelineTrainer
from synapseml_tpu.models.trainer import (Trainer, TrainerConfig,
                                          fit_arrays, fit_source)
from synapseml_tpu.parallel import partition as pp
from synapseml_tpu.parallel.mesh import MeshConfig, create_mesh
from synapseml_tpu.parallel.partition import PartitionRules
from synapseml_tpu.registry import ModelRegistry

pytestmark = pytest.mark.sharding

P = jax.sharding.PartitionSpec


# ---------------------------------------------------------------------------
# matcher units
# ---------------------------------------------------------------------------

def test_first_match_wins():
    rules = PartitionRules(rules=(
        (r"kernel$", (None, "tensor")),
        (r"dense/kernel$", ("fsdp", None)),  # shadowed: never reached
    ))
    assert rules.spec_for("dense/kernel", (8, 8)) == P(None, "tensor")


def test_scalar_and_single_element_leaves_replicate():
    rules = PartitionRules(rules=((r".*", ("data",)),))
    assert rules.spec_for("count", ()) == P()
    assert rules.spec_for("one", (1,)) == P()
    assert rules.spec_for("one2", (1, 1)) == P()
    assert rules.spec_for("vec", (8,)) == P("data")


def test_unmatched_policy():
    tree = {"a": {"w": np.zeros((4, 4))}, "b": np.zeros(8)}
    lax = PartitionRules(rules=((r"a/w$", ("data", None)),))
    specs = pp.match_partition_rules(lax, tree)
    assert specs["a"]["w"] == P("data", None)
    assert specs["b"] == P()  # default: replicate
    strict = PartitionRules(rules=((r"a/w$", ("data", None)),),
                            unmatched="error")
    with pytest.raises(ValueError, match="b"):
        pp.match_partition_rules(strict, tree)


def test_rule_rank_overflow_rejected():
    rules = PartitionRules(rules=((r"w$", ("data", None, None)),))
    with pytest.raises(ValueError, match="rank"):
        rules.spec_for("w", (4, 4))


def test_bad_regex_rejected_at_table_build():
    with pytest.raises(Exception):
        PartitionRules(rules=((r"(unclosed", ("data",)),))


def test_json_round_trip_and_digest():
    rules = PartitionRules(
        rules=((r"kernel$", (None, ("data", "fsdp"))),
               (r"embedding$", ("tensor", None))),
        unmatched="replicate", zero_axes=("data",),
        stage_regex=r"layer_(\d+)",
        mesh=MeshConfig(data=2, fsdp=2, tensor=2))
    back = PartitionRules.from_json(
        json.loads(json.dumps(rules.to_json())))
    assert back == rules
    assert back.digest() == rules.digest()
    # a rule edit changes the digest (the manifest drift signal)
    edited = PartitionRules.from_json(
        {**rules.to_json(), "unmatched": "error"})
    assert edited.digest() != rules.digest()


def test_stage_regex_needs_one_group():
    with pytest.raises(ValueError, match="capture group"):
        PartitionRules(stage_regex=r"layer_\d+")


# ---------------------------------------------------------------------------
# placement over plain pytrees
# ---------------------------------------------------------------------------

def test_shard_tree_places_plain_pytree(mesh8):
    rules = PartitionRules(rules=((r"dense/kernel$", (None, "tensor")),
                                  (r"emb$", (("data", "fsdp"), None))))
    tree = {"dense": {"kernel": jnp.ones((4, 8)), "bias": jnp.ones(8)},
            "emb": jnp.ones((16, 4)), "step": jnp.ones(())}
    placed = pp.shard_tree(tree, mesh8, rules)
    assert placed["dense"]["kernel"].sharding.spec == P(None, "tensor")
    assert placed["emb"].sharding.spec == P(("data", "fsdp"), None)
    assert placed["dense"]["bias"].sharding.spec == P()
    # genuinely partitioned: one shard holds a strict subset
    shard0 = placed["emb"].addressable_shards[0].data
    assert int(np.prod(shard0.shape)) < int(np.prod(placed["emb"].shape))


def test_indivisible_dim_rejected_with_path(mesh8):
    rules = PartitionRules(rules=((r"w$", (("data", "fsdp"),)),))
    with pytest.raises(ValueError, match="a/w"):
        pp.shard_tree({"a": {"w": jnp.ones(6)}}, mesh8, rules)  # 6 % 4 != 0


def test_unknown_axis_rejected(mesh8):
    rules = PartitionRules(rules=((r"w$", ("bogus",)),))
    with pytest.raises(ValueError, match="bogus"):
        pp.shard_tree({"w": jnp.ones(8)}, mesh8, rules)


def test_opt_state_specs_inherit_param_rules(mesh8):
    import optax

    rules = PartitionRules(rules=((r"dense/kernel$", (None, "tensor")),),
                           zero_axes=("data", "fsdp"))
    params = {"dense": {"kernel": jnp.ones((8, 8)), "bias": jnp.ones(8)}}
    tx = optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(1e-3))
    skel = jax.eval_shape(tx.init, params)
    flat = {pp.tree_path_name(path): spec for path, spec in
            jax.tree_util.tree_flatten_with_path(
                pp.opt_state_specs(rules, skel, mesh8, zero=False),
                is_leaf=lambda x: isinstance(x, P))[0]}
    # the param's rule carried through to its Adam moments
    assert flat["1/0/mu/dense/kernel"] == P(None, "tensor")
    assert flat["1/0/nu/dense/kernel"] == P(None, "tensor")
    assert flat["1/0/count"] == P()  # scalar skip
    zeroed = {pp.tree_path_name(path): spec for path, spec in
              jax.tree_util.tree_flatten_with_path(
                  pp.opt_state_specs(rules, skel, mesh8, zero=True),
                  is_leaf=lambda x: isinstance(x, P))[0]}
    # ZeRO adds the replica-group axes on the first free divisible dim
    assert zeroed["1/0/mu/dense/kernel"] == P(("data", "fsdp"), "tensor")
    assert zeroed["1/0/mu/dense/bias"] == P(("data", "fsdp"))
    assert zeroed["1/0/count"] == P()


def test_zero_shard_spec_edge_cases():
    sizes = {"data": 4, "fsdp": 1, "tensor": 2}
    # no free divisible dim: spec unchanged
    assert pp.zero_shard_spec(P(), (6,), sizes, ("data",)) == P()
    # axes already used by the spec are filtered out
    assert pp.zero_shard_spec(P("data"), (8, 8), sizes, ("data",)) \
        == P("data")
    # size-1 axes contribute nothing
    assert pp.zero_shard_spec(P(), (8,), sizes, ("fsdp",)) == P()
    # picks the FIRST free divisible dim, skipping taken dims
    assert pp.zero_shard_spec(P("tensor"), (8, 12), sizes, ("data",)) \
        == P("tensor", "data")


def test_default_rules_adapt_to_fsdp_only_mesh():
    """A tensor-less mesh must still shard the default tables (the
    pre-rule-table logical rules supported fsdp-only sharded inference —
    a model that fit then must not silently replicate now)."""
    fs = pp.default_llama_rules(mesh=MeshConfig(data=2, fsdp=4))
    # fsdp layout shards the HIDDEN dim (head/kv dims stay whole, so
    # small-kv-head models divide on any fsdp size)
    assert fs.spec_for("embed/embedding", (256, 64)) == P(None, "fsdp")
    assert fs.spec_for("decoder/layer_0/attn/k/kernel", (64, 2, 16)) \
        == P("fsdp", None, None)
    tn = pp.default_llama_rules(mesh=MeshConfig(data=2, fsdp=2, tensor=2))
    assert tn.spec_for("embed/embedding", (256, 64)) == P("tensor", None)
    # behavioral: an fsdp-only mesh_config distributes the LM's weights
    from synapseml_tpu.hf import HuggingFaceCausalLM

    lm = HuggingFaceCausalLM(model_name="llama-tiny",
                             mesh_config=MeshConfig(data=2, fsdp=4))
    emb = lm._model_and_params()[1]["embed"]["embedding"]
    shard0 = emb.addressable_shards[0].data
    assert int(np.prod(shard0.shape)) < int(np.prod(emb.shape))


# ---------------------------------------------------------------------------
# stage splits
# ---------------------------------------------------------------------------

def _flat_stage_tree(h=4, n=3, seed=0):
    rs = np.random.default_rng(seed)
    tree = {"head": {"w": rs.normal(size=(h, 2)).astype(np.float32)}}
    for i in range(n):
        tree[f"block_{i}"] = {
            "w": rs.normal(size=(h, h)).astype(np.float32),
            "b": np.zeros(h, np.float32)}
    return tree


def test_split_stage_params():
    shared, stages = pp.split_stage_params(_flat_stage_tree(n=3),
                                           r"block_(\d+)")
    assert list(shared) == ["head"]
    assert len(stages) == 3
    assert all(list(s) == ["block_#"] for s in stages)


def test_split_stage_params_rejects_gaps_and_drift():
    tree = _flat_stage_tree(n=3)
    del tree["block_1"]
    with pytest.raises(ValueError, match="contiguous"):
        pp.split_stage_params(tree, r"block_(\d+)")
    tree = _flat_stage_tree(n=2)
    tree["block_1"]["extra"] = np.zeros(2, np.float32)
    with pytest.raises(ValueError, match="stage 1"):
        pp.split_stage_params(tree, r"block_(\d+)")
    with pytest.raises(ValueError, match="matched no params"):
        pp.split_stage_params({"head": np.zeros(2)}, r"block_(\d+)")


# ---------------------------------------------------------------------------
# ZeRO-vs-replicated training parity (one seeded DataLoader stream)
# ---------------------------------------------------------------------------

class _MLP:
    def __new__(cls):
        import flax.linen as nn

        class MLP(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(2)(nn.relu(nn.Dense(64)(x)))

        return MLP()


def _mlp_data(n=512, d=16, seed=0):
    rs = np.random.default_rng(seed)
    X = rs.normal(size=(n, d)).astype(np.float32)
    return {"x": X, "labels": (X[:, 0] > 0).astype(np.int32)}


def _fit_mlp(zero: bool, steps=10):
    mesh = create_mesh(MeshConfig(data=-1))
    cfg = TrainerConfig(total_steps=steps, learning_rate=1e-2)
    if zero:
        cfg.partition_rules = PartitionRules(zero_axes=("data", "fsdp"))
        cfg.zero_shard = True
    trainer = Trainer(_MLP(), mesh, cfg)
    losses = []
    state = trainer.init_state(
        {k: v[:64] for k, v in _mlp_data().items()},
        jax.random.PRNGKey(7))
    from synapseml_tpu.data import DataLoader
    from synapseml_tpu.data.source import MemorySource

    loader = DataLoader(MemorySource(_mlp_data()), 64, seed=7,
                        multiple_of=mesh.data_parallel_size())
    state = trainer.fit(state, iter(loader), max_steps=steps,
                        callback=lambda i, m: losses.append(
                            float(m["loss"])))
    loader.close()
    return trainer, state, losses


def test_zero_vs_replicated_parity_and_memory():
    tr_a, st_a, losses_a = _fit_mlp(zero=False)
    tr_b, st_b, losses_b = _fit_mlp(zero=True)
    # per-step losses equal under the same seeded stream
    np.testing.assert_allclose(losses_a, losses_b, rtol=0, atol=1e-5)
    # final params equal to f32
    for a, b in zip(jax.tree.leaves(st_a.params),
                    jax.tree.leaves(st_b.params)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=0, atol=2e-6)
    # the ZeRO arm's per-replica optimizer-state bytes are bounded by
    # replicated/dp + epsilon (small unshardable leaves)
    dp = tr_b.mesh.data_parallel_size()
    assert dp >= 2
    replicated = pp.per_device_bytes(st_a.opt_state)
    sharded = pp.per_device_bytes(st_b.opt_state)
    eps = 512  # count scalar + the (2,) bias moments that cannot split
    assert sharded <= replicated / dp + eps, (sharded, replicated, dp)


def test_shard_metrics_emitted():
    from synapseml_tpu.core import observability as obs

    tr, st, _ = _fit_mlp(zero=True, steps=2)
    snap = pp.emit_shard_metrics(st.params, st.opt_state, tr.mesh)
    assert snap["opt_state"]["bytes_per_device"] \
        < snap["opt_state"]["total_bytes"]
    text = obs.get_registry().exposition()
    assert "synapseml_shard_total_bytes" in text
    assert "synapseml_shard_bytes_per_device" in text


# ---------------------------------------------------------------------------
# pipeline-split training parity (2-stage CPU mesh vs single-stage chain)
# ---------------------------------------------------------------------------

def _pipe_pieces():
    def embed_fn(shared, b):
        return b["x"]

    def head_loss_fn(shared, h, b):
        logits = h @ shared["head"]["w"]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        nll = -jnp.take_along_axis(
            logp, b["labels"][:, None].astype(jnp.int32), axis=-1)[..., 0]
        return jnp.mean(nll)

    def stage_fn(p, h):
        return jax.nn.relu(h @ p["block_#"]["w"] + p["block_#"]["b"])

    return embed_fn, head_loss_fn, stage_fn


def _fit_pipeline(pipe: int, steps=8, zero=False):
    embed_fn, head_loss_fn, stage_fn = _pipe_pieces()
    mesh = create_mesh(MeshConfig(data=1, pipe=pipe),
                       devices=jax.devices()[:max(pipe, 1)],
                       allow_fewer=False)
    cfg = TrainerConfig(total_steps=steps, learning_rate=1e-2,
                        partition_rules=PartitionRules(
                            stage_regex=r"block_(\d+)"),
                        zero_shard=zero)
    trainer = PipelineTrainer(mesh, cfg, stage_fn=stage_fn,
                              embed_fn=embed_fn,
                              head_loss_fn=head_loss_fn, n_micro=4)
    data = _mlp_data(n=256, d=8, seed=1)
    flat = _flat_stage_tree(h=8, n=2, seed=2)
    state = fit_arrays(trainer, data, batch_size=64, total_steps=steps,
                       seed=5, scan_chunk=1, init_params=flat)
    return trainer, state


def test_pipeline_split_fit_matches_single_stage():
    tr1, st1 = _fit_pipeline(pipe=1)
    tr2, st2 = _fit_pipeline(pipe=2)
    for a, b in zip(jax.tree.leaves(st1.params),
                    jax.tree.leaves(st2.params)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=0, atol=3e-6)
    # stage weights AND their optimizer moments live on the pipe axis
    stages = jax.tree.leaves(st2.params["stages"])[0]
    assert stages.sharding.spec == P("pipe")
    shard0 = stages.addressable_shards[0].data
    assert shard0.shape[0] == 1 and stages.shape[0] == 2
    opt_specs = {str(leaf.sharding.spec)
                 for leaf in jax.tree.leaves(st2.opt_state)
                 if np.ndim(leaf) >= 2}
    assert str(P("pipe")) in opt_specs


def test_pipeline_trainer_requires_stage_declaration():
    embed_fn, head_loss_fn, stage_fn = _pipe_pieces()
    mesh = create_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    trainer = PipelineTrainer(mesh, TrainerConfig(),
                              stage_fn=stage_fn, embed_fn=embed_fn,
                              head_loss_fn=head_loss_fn, n_micro=2)
    with pytest.raises(ValueError, match="stage_regex"):
        trainer.init_state({"x": np.zeros((4, 8), np.float32)},
                           init_params=_flat_stage_tree(h=8, n=2))
    with pytest.raises(ValueError, match="init_params"):
        trainer.init_state({"x": np.zeros((4, 8), np.float32)})


# ---------------------------------------------------------------------------
# checkpoint round trip + sharded resume through fit_source
# ---------------------------------------------------------------------------

def test_checkpoint_carries_sharding_and_restores_placed(tmp_path, mesh8):
    from synapseml_tpu.parallel.checkpoint import (checkpoint_sharding,
                                                   restore_checkpoint,
                                                   save_checkpoint)

    # rules are written against LIVE param names ('w'), not the
    # train-state-prefixed restore paths ('params/w') — the anchored form
    # must place identically on save-side and restore-side
    rules = PartitionRules(rules=((r"^w$", (None, "tensor")),),
                           mesh=MeshConfig(data=2, fsdp=2, tensor=2))
    tree = {"params": {"w": np.ones((4, 8), np.float32)},
            "step": np.int32(3),
            "data_iter": {"seed": np.int64(7)}}
    save_checkpoint(str(tmp_path), tree, step=3,
                    sharding=pp.sharding_manifest_section(rules))
    section = checkpoint_sharding(str(tmp_path))
    assert section is not None
    back = PartitionRules.from_json(section["rules"])
    assert back.digest() == rules.digest()
    restored = restore_checkpoint(
        str(tmp_path), sharding_fn=pp.checkpoint_sharding_fn(back, mesh8))
    assert restored["params"]["w"].sharding.spec == P(None, "tensor")
    # loader state stays host-side numpy (sharding_fn returned None)
    assert isinstance(restored["data_iter"]["seed"], np.generic) \
        or isinstance(restored["data_iter"]["seed"], np.ndarray)


def test_fit_source_resume_from_sharded_checkpoint(tmp_path):
    from synapseml_tpu.data.source import MemorySource
    from synapseml_tpu.parallel.checkpoint import (AsyncCheckpointer,
                                                   checkpoint_sharding)

    data = _mlp_data(n=512, d=16, seed=3)

    def trainer():
        mesh = create_mesh(MeshConfig(data=-1))
        cfg = TrainerConfig(total_steps=12, learning_rate=1e-2,
                            partition_rules=PartitionRules(
                                zero_axes=("data", "fsdp")),
                            zero_shard=True)
        return Trainer(_MLP(), mesh, cfg)

    ckdir = str(tmp_path / "ck")
    # phase 1: 6 of 12 steps, checkpointed
    with AsyncCheckpointer(ckdir, keep=3) as ck:
        fit_source(trainer(), MemorySource(data), batch_size=64,
                   total_steps=6, seed=11, scan_chunk=2, checkpointer=ck,
                   checkpoint_every=2)
    # the checkpoint carries the rule table + mesh
    assert checkpoint_sharding(ckdir) is not None
    # phase 2: resume to 12 — restored THROUGH the rule-table sharding_fn
    resumed = fit_source(trainer(), MemorySource(data), batch_size=64,
                         total_steps=12, seed=11, scan_chunk=2,
                         resume_from=ckdir)
    # reference: uninterrupted 12-step run, same seed/stream
    reference = fit_source(trainer(), MemorySource(data), batch_size=64,
                           total_steps=12, seed=11, scan_chunk=2)
    assert int(resumed.step) == int(reference.step) == 12
    for a, b in zip(jax.tree.leaves(resumed.params),
                    jax.tree.leaves(reference.params)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=0, atol=2e-6)
    # the resumed state is actually sharded (ZeRO): opt bytes per device
    # are a strict subset of the total
    assert pp.per_device_bytes(resumed.opt_state) \
        < pp.total_bytes(resumed.opt_state)


# ---------------------------------------------------------------------------
# registry manifest round trip + /admin/load application
# ---------------------------------------------------------------------------

LM_MESH = dict(data=2, fsdp=2, tensor=2)


def _lm_rules():
    """Exactness-preserving table for the prediction-parity round trips:
    only NON-contraction dims shard (embed rows over the vocab dim,
    lm_head column-parallel), so the sharded program performs bitwise the
    same reductions as the dense one and greedy decode cannot flip on a
    float near-tie of the random-init weights. (The full Megatron table —
    `default_llama_rules` — ALSO reshards contraction dims, whose psum
    order can legitimately flip a near-tie argmax at f32; parity for that
    layout is covered at tighter model scale in test_hf_cyber.)"""
    return PartitionRules(rules=(
        (r"embed/embedding$", ("tensor", None)),
        (r"lm_head/kernel$", (None, "tensor")),
    ), mesh=MeshConfig(**LM_MESH))


def _publish_lm(tmp_path, sharding=None, version=None, name="lm"):
    reg = ModelRegistry(str(tmp_path / "store"))
    pipeline = make_lm_pipeline()
    pub = reg.publish(name, pipeline, version=version, sharding=sharding)
    return reg, pub


def test_publish_resolve_sharding_round_trip(tmp_path):
    reg, pub = _publish_lm(tmp_path, sharding=_lm_rules())
    section = pub.manifest["sharding"]
    assert PartitionRules.from_json(section["rules"]).digest() \
        == _lm_rules().digest()
    assert section["mesh"]["tensor"] == 2
    resolved = reg.resolve("lm", "v1")
    assert resolved.manifest["sharding"] == section
    # applying the section reconfigures the nested LM stage
    applied, reason = pp.apply_manifest_sharding(resolved.stage, section)
    assert applied and reason is None
    lm = resolved.stage.get("stages")[1]
    assert lm.get("mesh_config") == MeshConfig(**LM_MESH)
    assert lm.get("partition_rules").digest() == _lm_rules().digest()
    # sharded predictions == the unsharded reference (same PRNGKey(0)
    # init), and no device holds the full embed table
    rows = prompt_rows(4, seed=2)
    df = DataFrame.from_rows([{"body": r} for r in rows])
    ref = make_lm_pipeline().transform(df).collect_column("reply")
    got = resolved.stage.transform(df).collect_column("reply")
    assert [r["tokens"] for r in got] == [r["tokens"] for r in ref]
    emb = lm._model_and_params()[1]["embed"]["embedding"]
    shard0 = emb.addressable_shards[0].data
    assert int(np.prod(shard0.shape)) < int(np.prod(emb.shape))


def test_publish_sharding_auto_lifts_stage_params(tmp_path):
    from synapseml_tpu.parallel.partition import default_llama_rules

    reg = ModelRegistry(str(tmp_path / "store"))
    pipeline = make_lm_pipeline(mesh_config=MeshConfig(**LM_MESH),
                                partition_rules=default_llama_rules())
    pub = reg.publish("lm", pipeline, sharding="auto")
    section = pub.manifest["sharding"]
    assert section["mesh"]["tensor"] == 2
    assert PartitionRules.from_json(
        section["rules"]).stage_regex == r"layer_(\d+)"
    # a stage with no mesh_config has no topology to lift
    with pytest.raises(ValueError, match="mesh_config"):
        reg.publish("lm2", make_lm_pipeline(), sharding="auto")


def test_apply_manifest_sharding_mismatch_demotes(tmp_path, caplog):
    reg, pub = _publish_lm(
        tmp_path, sharding=PartitionRules(
            mesh=MeshConfig(data=1, pipe=16)))  # 16 > the 8 CPU devices
    resolved = reg.resolve("lm", "v1")
    lm = resolved.stage.get("stages")[1]
    lm.set(mesh_config=MeshConfig(data=1, pipe=16))  # saved-in config
    with caplog.at_level(logging.WARNING,
                         logger="synapseml_tpu.parallel.partition"):
        applied, reason = pp.apply_manifest_sharding(
            resolved.stage, resolved.manifest["sharding"])
    assert not applied and "devices" in reason
    # the stage was stripped to a replicated load — and still transforms
    assert lm.get("mesh_config") is None
    assert lm.get("partition_rules") is None
    records = [r for r in caplog.records
               if "sharding_demoted_to_replicated" in r.getMessage()]
    assert len(records) == 1  # ONE structured warning
    payload = json.loads(records[0].getMessage())
    assert payload["event"] == "sharding_demoted_to_replicated"
    df = DataFrame.from_rows([{"body": r} for r in prompt_rows(2)])
    assert len(resolved.stage.transform(df).collect_column("reply")) == 2


def _post(base, path, payload, timeout=120):
    req = urllib.request.Request(base + path,
                                 data=json.dumps(payload).encode(),
                                 method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def test_admin_load_applies_sharding_before_warmup(tmp_path):
    from synapseml_tpu.io.serving import serve_pipeline

    reg, _ = _publish_lm(tmp_path, sharding=_lm_rules(), version="v1")
    # v2: a mesh this host cannot build -> demoted, swap still succeeds
    _publish_lm(tmp_path, sharding=PartitionRules(
        mesh=MeshConfig(data=1, pipe=16)), version="v2")
    srv = serve_pipeline(make_lm_pipeline(), batch_interval_ms=5,
                         version="v0")
    try:
        rows = prompt_rows(3, seed=4)
        df = DataFrame.from_rows([{"body": r} for r in rows])
        ref = [r["tokens"] for r in
               make_lm_pipeline().transform(df).collect_column("reply")]
        status, reply = _post(srv.address, "/admin/load",
                              {"registry": str(tmp_path / "store"),
                               "model": "lm", "ref": "v1",
                               "warmup": rows[:1]})
        assert status == 200 and reply["ok"], reply
        assert reply["warmup"]["sharding"] == "applied"
        # the served pipeline's LM runs on the manifest mesh
        lm = srv.pipeline_holder.pipeline.get("stages")[1]
        assert lm.get("mesh_config") == MeshConfig(**LM_MESH)
        # predictions over HTTP == the unsharded reference
        for i, row in enumerate(rows):
            status, out = _post(srv.address, "/", row)
            assert status == 200 and out["tokens"] == ref[i], (i, out)
        # mismatched mesh: demoted to replicated, swap succeeds, serves
        status, reply = _post(srv.address, "/admin/load",
                              {"registry": str(tmp_path / "store"),
                               "model": "lm", "ref": "v2",
                               "warmup": rows[:1]})
        assert status == 200 and reply["ok"], reply
        assert reply["warmup"]["sharding"].startswith("replicated")
        status, out = _post(srv.address, "/", rows[0])
        assert status == 200 and out["tokens"] == ref[0]
        # per-load opt-out: v1 again with sharding disabled
        status, reply = _post(srv.address, "/admin/load",
                              {"registry": str(tmp_path / "store"),
                               "model": "lm", "ref": "v1",
                               "sharding": False, "warmup": rows[:1]})
        assert status == 200 and reply["ok"], reply
        assert "disabled" in reply["warmup"]["sharding"]
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# >=2-process mesh: publish -> fresh-process load -> serve, no host ever
# holding the full param tree on device
# ---------------------------------------------------------------------------

MP_WORKER_TMPL = """
import hashlib
import sys

import jax

jax.config.update("jax_platforms", "cpu")

from synapseml_tpu.parallel.backend import initialize_backend

driver_addr, executor_id, partition_id = (sys.argv[1], sys.argv[2],
                                          int(sys.argv[3]))
backend = initialize_backend(driver_addr, executor_id=executor_id,
                             partition_id=partition_id)
assert backend.initialized and backend.world == 2
assert len(jax.devices()) == 2  # one per process -> a real 2-host mesh

sys.path.insert(0, {tests_dir!r})
import numpy as np

from _sharding_pipeline import make_lm_pipeline
from synapseml_tpu.parallel import partition as pp
from synapseml_tpu.registry import ModelRegistry

reg = ModelRegistry({store!r}, cache_dir={store!r} + "/.cache-" + executor_id)
resolved = reg.resolve("lm", "v1")
applied, reason = pp.apply_manifest_sharding(resolved.stage,
                                             resolved.manifest["sharding"])
assert applied, reason
lm = resolved.stage.get("stages")[1]
params = lm._model_and_params()[1]
total = pp.total_bytes(params)
local = sum(int(np.prod(s.data.shape)) * s.data.dtype.itemsize
            for leaf in jax.tree.leaves(params)
            for s in leaf.addressable_shards)
print(f"BYTES {{local}} {{total}}", flush=True)
assert local < total, (local, total)
emb = params["embed"]["embedding"]
for s in emb.addressable_shards:
    lo, hi = s.index[0].start or 0, s.index[0].stop or emb.shape[0]
    digest = hashlib.sha256(np.ascontiguousarray(
        np.asarray(s.data))).hexdigest()[:16]
    print(f"SHARD {{lo}} {{hi}} {{digest}}", flush=True)
print("SHARDED_OK", flush=True)
"""


def test_two_process_sharded_publish_load(tmp_path):
    """The multi-host acceptance: a model published with a sharding
    section loads in TWO fresh OS processes forming one 2-process mesh
    (``tensor`` axis across hosts). Each host materializes ONLY its shard
    slices (addressable bytes a strict subset of the tree — no host ever
    holds the full param tree on device), the two hosts' embed shards are
    disjoint, cover the table exactly, and are byte-identical to the
    unsharded reference weights. (Cross-process XLA *compute* is
    unimplemented on this CPU backend — jit partitioning rejects it, see
    test_multiprocess_backend — so predictions-equality runs on the
    single-process multi-device mesh in
    test_admin_load_applies_sharding_before_warmup; the placement
    machinery proven here is the same.)"""
    import hashlib
    import os

    from test_multiprocess_backend import _run_two_workers

    rules = pp.default_llama_rules(mesh=MeshConfig(data=1, tensor=2))
    reg = ModelRegistry(str(tmp_path / "store"))
    reg.publish("lm", make_lm_pipeline(), version="v1", sharding=rules)

    # reference weights: the same artifact loaded unsharded in-process
    # (the module init keeps nn.Partitioned boxes on the no-mesh path)
    from flax.core import meta

    ref_leaf = make_lm_pipeline().get("stages")[1]._model_and_params()[1][
        "embed"]["embedding"]
    ref_emb = np.asarray(ref_leaf.value
                         if isinstance(ref_leaf, meta.Partitioned)
                         else ref_leaf)

    script = MP_WORKER_TMPL.format(
        tests_dir=os.path.dirname(os.path.abspath(__file__)),
        store=str(tmp_path / "store"))
    outs = _run_two_workers(script, tmp_path, partition_order=(0, 1),
                            timeout_s=240)
    ranges = []
    for out in outs:
        assert "SHARDED_OK" in out, out
        local, total = next(
            tuple(map(int, line.split()[1:]))
            for line in out.splitlines() if line.startswith("BYTES "))
        assert local < total
        for line in out.splitlines():
            if not line.startswith("SHARD "):
                continue
            _, lo, hi, digest = line.split()
            lo, hi = int(lo), int(hi)
            # byte-identical to the reference slice: the shard a host
            # reads is exactly the published weights' rows
            want = hashlib.sha256(np.ascontiguousarray(
                ref_emb[lo:hi])).hexdigest()[:16]
            assert digest == want, (lo, hi)
            ranges.append((lo, hi))
    # disjoint exact cover of the vocab dim across the two hosts
    ranges.sort()
    assert ranges[0][0] == 0 and ranges[-1][1] == ref_emb.shape[0]
    for (a_lo, a_hi), (b_lo, b_hi) in zip(ranges, ranges[1:]):
        assert a_hi == b_lo, ranges
