"""Quantized (QDQ / QLinear) and detection-tail ONNX ops against spec
oracles — the opset families behind ONNX Runtime's quantized-model and
detection-head support (reference `ONNXRuntime.scala:25` runs the full ORT
opset; these are the remaining high-traffic groups after the conv / einsum
/ decoder / recurrent families proven on real torch exports)."""

import numpy as np
import pytest

from synapseml_tpu.core import DataFrame
from synapseml_tpu.onnx import (
    AttributeProto,
    GraphProto,
    ModelProto,
    NodeProto,
    ONNXModel,
    ValueInfoProto,
    convert_graph,
    numpy_to_tensor,
)
from synapseml_tpu.onnx import proto as P
from synapseml_tpu.onnx.convert import OP_REGISTRY


def node(op, inputs, outputs, **attrs):
    return NodeProto(input=list(inputs), output=list(outputs), op_type=op,
                     attribute=[AttributeProto.make(k, v) for k, v in attrs.items()])


def run_op(op, ins, **attrs):
    return OP_REGISTRY[op]([None if x is None else np.asarray(x) for x in ins],
                           attrs)


# ---------------- quantization family ----------------

def quant_ref(x, scale, zp, dtype):
    info = np.iinfo(dtype)
    q = np.rint(x / scale) + zp          # rint = round-half-even, per spec
    return np.clip(q, info.min, info.max).astype(dtype)


def test_quantize_dequantize_per_tensor():
    rs = np.random.default_rng(0)
    x = (rs.normal(size=(5, 7)) * 4).astype(np.float32)
    scale, zp = np.float32(0.05), np.uint8(128)
    q = run_op("QuantizeLinear", [x, scale, zp])
    np.testing.assert_array_equal(np.asarray(q), quant_ref(x, 0.05, 128, np.uint8))
    deq = run_op("DequantizeLinear", [np.asarray(q), scale, zp])
    np.testing.assert_allclose(np.asarray(deq),
                               (quant_ref(x, 0.05, 128, np.uint8).astype(np.float32)
                                - 128) * 0.05, atol=1e-7)
    # int8 variant with negative zero point
    q8 = run_op("QuantizeLinear", [x, scale, np.int8(-3)])
    np.testing.assert_array_equal(np.asarray(q8), quant_ref(x, 0.05, -3, np.int8))


def test_quantize_per_axis():
    rs = np.random.default_rng(1)
    x = rs.normal(size=(3, 4, 5)).astype(np.float32)
    scale = np.asarray([0.1, 0.02, 0.3, 0.5], np.float32)
    zp = np.asarray([0, 10, -5, 3], np.int8)
    q = np.asarray(run_op("QuantizeLinear", [x, scale, zp], axis=1))
    for c in range(4):
        np.testing.assert_array_equal(q[:, c], quant_ref(x[:, c], scale[c],
                                                         int(zp[c]), np.int8))
    deq = np.asarray(run_op("DequantizeLinear", [q, scale, zp], axis=1))
    for c in range(4):
        np.testing.assert_allclose(deq[:, c],
                                   (q[:, c].astype(np.float32) - zp[c]) * scale[c])


def test_dynamic_quantize_linear_spec():
    x = np.asarray([[-1.0, 0.0, 2.5, 3.1]], np.float32)
    y, scale, zp = run_op("DynamicQuantizeLinear", [x])
    lo, hi = min(x.min(), 0.0), max(x.max(), 0.0)
    ref_scale = (hi - lo) / 255.0
    ref_zp = np.clip(np.rint(-lo / ref_scale), 0, 255).astype(np.uint8)
    assert float(scale) == pytest.approx(ref_scale)
    assert int(zp) == int(ref_zp)
    np.testing.assert_array_equal(
        np.asarray(y), np.clip(np.rint(x / ref_scale) + int(ref_zp),
                               0, 255).astype(np.uint8))
    # all-zero input must not divide by zero
    y0, s0, z0 = run_op("DynamicQuantizeLinear", [np.zeros((3,), np.float32)])
    assert np.asarray(y0).dtype == np.uint8 and float(s0) > 0


def test_matmul_integer_exact():
    rs = np.random.default_rng(2)
    a = rs.integers(0, 255, (6, 9)).astype(np.uint8)
    b = rs.integers(-128, 127, (9, 4)).astype(np.int8)
    out = np.asarray(run_op("MatMulInteger", [a, b, np.uint8(113), np.int8(-7)]))
    ref = (a.astype(np.int32) - 113) @ (b.astype(np.int32) + 7)
    assert out.dtype == np.int32
    np.testing.assert_array_equal(out, ref)
    # zero points optional
    out2 = np.asarray(run_op("MatMulInteger", [a, b]))
    np.testing.assert_array_equal(out2, a.astype(np.int32) @ b.astype(np.int32))


def qlinear_matmul_ref(a, a_s, a_z, b, b_s, b_z, y_s, y_z):
    acc = (a.astype(np.int32) - a_z) @ (b.astype(np.int32) - b_z)
    y = np.rint(acc.astype(np.float64) * (a_s * b_s / y_s)) + y_z
    info = np.iinfo(np.uint8)
    return np.clip(y, info.min, info.max).astype(np.uint8)


def test_qlinear_matmul():
    rs = np.random.default_rng(3)
    a = rs.integers(0, 255, (5, 8)).astype(np.uint8)
    b = rs.integers(0, 255, (8, 6)).astype(np.uint8)
    args = [a, np.float32(0.02), np.uint8(120), b, np.float32(0.05),
            np.uint8(131), np.float32(0.4), np.uint8(7)]
    out = np.asarray(run_op("QLinearMatMul", args))
    np.testing.assert_array_equal(
        out, qlinear_matmul_ref(a, 0.02, 120, b, 0.05, 131, 0.4, 7))


def test_qlinear_matmul_per_row_scale_zp():
    """ONNX allows a_scale/a_zero_point of shape [M] (per-row)."""
    rs = np.random.default_rng(30)
    M, K, N = 4, 7, 5
    a = rs.integers(0, 255, (M, K)).astype(np.uint8)
    b = rs.integers(0, 255, (K, N)).astype(np.uint8)
    a_s = rs.uniform(0.01, 0.05, M).astype(np.float32)
    a_z = rs.integers(100, 150, M).astype(np.uint8)
    args = [a, a_s, a_z, b, np.float32(0.05), np.uint8(131),
            np.float32(0.4), np.uint8(7)]
    out = np.asarray(run_op("QLinearMatMul", args))
    ref = np.empty((M, N), np.uint8)
    for m in range(M):
        ref[m] = qlinear_matmul_ref(a[m:m + 1], float(a_s[m]), int(a_z[m]),
                                    b, 0.05, 131, 0.4, 7)[0]
    np.testing.assert_array_equal(out, ref)


def test_qlinear_conv_per_channel():
    rs = np.random.default_rng(4)
    x = rs.integers(0, 255, (2, 3, 8, 8)).astype(np.uint8)
    w = rs.integers(-100, 100, (5, 3, 3, 3)).astype(np.int8)
    bias = rs.integers(-1000, 1000, (5,)).astype(np.int32)
    x_s, x_z = np.float32(0.03), np.uint8(110)
    w_s = rs.uniform(0.01, 0.05, 5).astype(np.float32)    # per-output-channel
    w_z = np.zeros(5, np.int8)
    y_s, y_z = np.float32(0.1), np.uint8(128)
    out = np.asarray(run_op(
        "QLinearConv", [x, x_s, x_z, w, w_s, w_z, y_s, y_z, bias],
        kernel_shape=[3, 3], pads=[1, 1, 1, 1]))
    # float oracle: integer-exact conv then requantize
    from scipy.signal import correlate

    xf = x.astype(np.float64) - 110
    ref_acc = np.zeros((2, 5, 8, 8))
    for n in range(2):
        for m in range(5):
            s = sum(correlate(xf[n, c], w[m, c].astype(np.float64), mode="same")
                    for c in range(3))
            ref_acc[n, m] = s + bias[m]
    ref = np.clip(np.rint(ref_acc * (0.03 * w_s[None, :, None, None] / 0.1))
                  + 128, 0, 255).astype(np.uint8)
    np.testing.assert_array_equal(out, ref)


def test_qdq_model_end_to_end():
    """A quantized MLP (Quantize -> QLinearMatMul -> Dequantize -> Relu)
    through the full ONNXModel transformer path."""
    rs = np.random.default_rng(5)
    W = rs.integers(0, 255, (4, 3)).astype(np.uint8)
    g = GraphProto(
        name="qmlp",
        node=[
            node("QuantizeLinear", ["x", "xs", "xz"], ["xq"]),
            node("QLinearMatMul", ["xq", "xs", "xz", "W", "ws", "wz",
                                   "ys", "yz"], ["yq"]),
            node("DequantizeLinear", ["yq", "ys", "yz"], ["yf"]),
            node("Relu", ["yf"], ["out"]),
        ],
        initializer=[
            numpy_to_tensor(W, "W"),
            numpy_to_tensor(np.float32(0.02), "xs"),
            numpy_to_tensor(np.uint8(128), "xz"),
            numpy_to_tensor(np.float32(0.05), "ws"),
            numpy_to_tensor(np.uint8(131), "wz"),
            numpy_to_tensor(np.float32(0.3), "ys"),
            numpy_to_tensor(np.uint8(100), "yz"),
        ],
        input=[ValueInfoProto(name="x", elem_type=P.FLOAT, dims=["N", 4])],
        output=[ValueInfoProto(name="out", elem_type=P.FLOAT, dims=["N", 3])],
    )
    data = ModelProto(graph=g).encode()
    X = (rs.normal(size=(9, 4)) * 2).astype(np.float32)
    om = ONNXModel(model_bytes=data, mini_batch_size=4,
                   feed_dict={"x": "features"}, fetch_dict={"out": "out"})
    out = np.stack(list(om.transform(DataFrame.from_dict({"features": X}))
                        .collect_column("out")))
    xq = quant_ref(X, 0.02, 128, np.uint8)
    yq = qlinear_matmul_ref(xq, 0.02, 128, W, 0.05, 131, 0.3, 100)
    ref = np.maximum((yq.astype(np.float32) - 100) * 0.3, 0)
    np.testing.assert_allclose(out, ref, atol=1e-6)


# ---------------- advanced indexing ----------------

def test_gather_nd():
    x = np.arange(24).reshape(2, 3, 4).astype(np.float32)
    idx = np.asarray([[0, 2], [1, 0]], np.int64)          # -> x[0,2], x[1,0]
    out = np.asarray(run_op("GatherND", [x, idx]))
    np.testing.assert_array_equal(out, np.stack([x[0, 2], x[1, 0]]))
    # batch_dims=1: per-batch row gather
    idx_b = np.asarray([[1], [2]], np.int64)              # x[0,1], x[1,2]
    out_b = np.asarray(run_op("GatherND", [x, idx_b], batch_dims=1))
    np.testing.assert_array_equal(out_b, np.stack([x[0, 1], x[1, 2]]))


def test_scatter_nd_set_and_add():
    x = np.zeros((4, 3), np.float32)
    idx = np.asarray([[1], [3]], np.int64)
    upd = np.asarray([[1.0, 2, 3], [4, 5, 6]], np.float32)
    out = np.asarray(run_op("ScatterND", [x, idx, upd]))
    ref = x.copy(); ref[1] = upd[0]; ref[3] = upd[1]
    np.testing.assert_array_equal(out, ref)
    out_add = np.asarray(run_op("ScatterND", [np.ones((4, 3), np.float32),
                                              idx, upd], reduction="add"))
    np.testing.assert_array_equal(out_add, np.ones((4, 3)) + ref)


def test_scatter_reductions_min_max_and_unknown():
    x = np.asarray([5.0, 5.0, 5.0], np.float32)
    idx = np.asarray([[0], [1], [2]], np.int64)
    upd = np.asarray([9.0, 1.0, 9.0], np.float32)
    np.testing.assert_array_equal(
        np.asarray(run_op("ScatterND", [x, idx, upd], reduction="max")),
        [9.0, 5.0, 9.0])
    np.testing.assert_array_equal(
        np.asarray(run_op("ScatterND", [x, idx, upd], reduction="min")),
        [5.0, 1.0, 5.0])
    with pytest.raises(NotImplementedError, match="reduction"):
        run_op("ScatterElements", [x, np.asarray([0, 1, 2]), upd],
               reduction="bogus")


def test_index_ops_jit_safe_with_runtime_indices():
    """GatherND/ScatterND/ScatterElements must accept traced index tensors
    (NMS/TopK outputs feed them inside ONNXModel's jitted execution)."""
    import jax

    x = np.arange(12, dtype=np.float32).reshape(4, 3)
    nd_idx = np.asarray([[1], [3]], np.int64)
    upd = np.ones((2, 3), np.float32)

    out_g = jax.jit(lambda d, i: OP_REGISTRY["GatherND"]([d, i], {}))(x, nd_idx)
    np.testing.assert_array_equal(np.asarray(out_g), x[[1, 3]])
    out_s = jax.jit(lambda d, i, u: OP_REGISTRY["ScatterND"]([d, i, u],
                                                             {}))(x, nd_idx, upd)
    assert np.asarray(out_s)[1].tolist() == [1, 1, 1]
    el_idx = np.asarray([[0], [2], [1], [0]], np.int64)
    out_e = jax.jit(lambda d, i, u: OP_REGISTRY["ScatterElements"](
        [d, i, u], {"axis": 1}))(x, el_idx, np.zeros((4, 1), np.float32))
    assert np.asarray(out_e)[1, 2] == 0.0


def test_scatter_elements_matches_put_along_axis():
    rs = np.random.default_rng(6)
    x = rs.normal(size=(4, 5)).astype(np.float32)
    idx = rs.integers(0, 5, (4, 2)).astype(np.int64)
    upd = rs.normal(size=(4, 2)).astype(np.float32)
    out = np.asarray(run_op("ScatterElements", [x, idx, upd], axis=1))
    ref = x.copy()
    np.put_along_axis(ref, idx, upd, axis=1)
    np.testing.assert_array_equal(out, ref)
    # negative indices wrap
    out_n = np.asarray(run_op("ScatterElements",
                              [x, idx - 5, upd], axis=1))
    np.testing.assert_array_equal(out_n, ref)


def test_tile_and_reduce_prod():
    x = np.arange(6).reshape(2, 3).astype(np.float32)
    np.testing.assert_array_equal(np.asarray(run_op("Tile", [x, np.asarray([2, 1])])),
                                  np.tile(x, (2, 1)))
    np.testing.assert_allclose(np.asarray(run_op("ReduceProd", [x + 1, np.asarray([1])])),
                               np.prod(x + 1, axis=1, keepdims=True))


# ---------------- NonMaxSuppression ----------------

def nms_ref(boxes, scores, max_out, iou_thr, score_thr):
    """Greedy numpy oracle, padded to B*C*max_out rows with -1."""
    B, N, _ = boxes.shape
    C = scores.shape[1]
    rows = []
    for b in range(B):
        y1 = np.minimum(boxes[b, :, 0], boxes[b, :, 2])
        y2 = np.maximum(boxes[b, :, 0], boxes[b, :, 2])
        x1 = np.minimum(boxes[b, :, 1], boxes[b, :, 3])
        x2 = np.maximum(boxes[b, :, 1], boxes[b, :, 3])
        area = (y2 - y1) * (x2 - x1)
        for c in range(C):
            alive = np.ones(N, bool)
            picked = []
            while len(picked) < max_out:
                masked = np.where(alive, scores[b, c], -np.inf)
                i = int(masked.argmax())
                if not (masked[i] > score_thr):
                    break
                picked.append(i)
                yy1, yy2 = np.maximum(y1, y1[i]), np.minimum(y2, y2[i])
                xx1, xx2 = np.maximum(x1, x1[i]), np.minimum(x2, x2[i])
                inter = np.maximum(yy2 - yy1, 0) * np.maximum(xx2 - xx1, 0)
                iou = inter / np.maximum(area + area[i] - inter, 1e-12)
                alive &= iou <= iou_thr
                alive[i] = False
            for k in range(max_out):
                rows.append([b, c, picked[k]] if k < len(picked) else [-1, -1, -1])
    return np.asarray(rows, np.int32)


@pytest.mark.parametrize("iou_thr,score_thr", [(0.5, 0.0), (0.3, 0.35)])
def test_nms_matches_greedy_oracle(iou_thr, score_thr):
    rs = np.random.default_rng(7)
    B, N, C = 2, 24, 3
    centers = rs.uniform(0, 10, (B, N, 2))
    sizes = rs.uniform(0.5, 3.0, (B, N, 2))
    boxes = np.concatenate([centers - sizes / 2, centers + sizes / 2],
                           axis=-1).astype(np.float32)
    scores = rs.uniform(0, 1, (B, C, N)).astype(np.float32)
    out = np.asarray(run_op(
        "NonMaxSuppression",
        [boxes, scores, np.asarray([5]), np.float32(iou_thr),
         np.float32(score_thr)]))
    np.testing.assert_array_equal(out, nms_ref(boxes, scores, 5,
                                               iou_thr, score_thr))


def test_nms_center_point_and_suppression():
    # two near-identical boxes + one far box: exactly two survive
    boxes = np.asarray([[[5, 5, 2, 2], [5.1, 5, 2, 2], [20, 20, 2, 2]]],
                       np.float32)                        # center format
    scores = np.asarray([[[0.9, 0.8, 0.7]]], np.float32)
    out = np.asarray(run_op(
        "NonMaxSuppression",
        [boxes, scores, np.asarray([3]), np.float32(0.5), None],
        center_point_box=1))
    kept = out[out[:, 2] >= 0][:, 2].tolist()
    assert kept == [0, 2]                                 # 1 suppressed by 0


def test_nms_in_converted_graph():
    """NMS as a graph node with initializer thresholds, via convert_graph."""
    g = GraphProto(
        name="det",
        node=[node("NonMaxSuppression",
                   ["boxes", "scores", "mo", "iou"], ["sel"])],
        initializer=[numpy_to_tensor(np.asarray([2], np.int64), "mo"),
                     numpy_to_tensor(np.float32(0.5), "iou")],
        input=[ValueInfoProto(name="boxes", elem_type=P.FLOAT, dims=[1, 4, 4]),
               ValueInfoProto(name="scores", elem_type=P.FLOAT, dims=[1, 1, 4])],
        output=[ValueInfoProto(name="sel", elem_type=P.INT32, dims=[2, 3])],
    )
    conv = convert_graph(ModelProto(graph=g).encode())
    boxes = np.asarray([[[0, 0, 1, 1], [0, 0, 1.05, 1], [3, 3, 4, 4],
                         [8, 8, 9, 9]]], np.float32)
    scores = np.asarray([[[0.9, 0.85, 0.6, 0.2]]], np.float32)
    sel = np.asarray(conv(boxes=boxes, scores=scores)["sel"])
    np.testing.assert_array_equal(sel, [[0, 0, 0], [0, 0, 2]])


def test_detection_tail_jitted_through_onnx_model():
    """The real detection-head tail — NMS -> Slice/Concat the (batch, box)
    index pairs -> GatherND the selected boxes — through ONNXModel's JITTED
    execution path, with runtime indices flowing between the new ops."""
    g = GraphProto(
        name="dettail",
        node=[
            node("NonMaxSuppression", ["boxes", "scores", "mo", "iou"],
                 ["sel"]),
            node("Slice", ["sel", "s0", "e1", "ax1"], ["col_b"]),   # [:, 0:1]
            node("Slice", ["sel", "s2", "e3", "ax1"], ["col_i"]),   # [:, 2:3]
            node("Concat", ["col_b", "col_i"], ["idx"], axis=1),
            node("GatherND", ["boxes", "idx"], ["picked"]),
        ],
        initializer=[numpy_to_tensor(np.asarray([3], np.int64), "mo"),
                     numpy_to_tensor(np.float32(0.5), "iou"),
                     numpy_to_tensor(np.asarray([0], np.int64), "s0"),
                     numpy_to_tensor(np.asarray([1], np.int64), "e1"),
                     numpy_to_tensor(np.asarray([2], np.int64), "s2"),
                     numpy_to_tensor(np.asarray([3], np.int64), "e3"),
                     numpy_to_tensor(np.asarray([1], np.int64), "ax1")],
        input=[ValueInfoProto(name="boxes", elem_type=P.FLOAT, dims=[1, 3, 4]),
               ValueInfoProto(name="scores", elem_type=P.FLOAT, dims=[1, 1, 3])],
        output=[ValueInfoProto(name="picked", elem_type=P.FLOAT, dims=[3, 4])],
    )
    import jax

    conv = convert_graph(ModelProto(graph=g).encode())
    # three well-separated boxes -> all three selected, ordered by score
    boxes = np.asarray([[[0, 0, 1, 1], [3, 3, 4, 4], [8, 8, 9, 9]]],
                       np.float32)
    scores = np.asarray([[[0.7, 0.9, 0.8]]], np.float32)
    # same jit wrapping as ONNXModel._jitted: feeds are tracers, so the
    # NMS -> Slice/Concat -> GatherND index flow runs fully traced
    picked = jax.jit(lambda b, s: conv(boxes=b, scores=s)["picked"])(
        boxes, scores)
    np.testing.assert_array_equal(np.asarray(picked), boxes[0][[1, 2, 0]])
