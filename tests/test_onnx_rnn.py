"""Recurrent model family through the ONNX path: torch nn.LSTM / nn.GRU
export as native ONNX LSTM/GRU nodes, lowered here to ``lax.scan``
recurrences (the TPU-idiomatic form — static shapes, no per-step Python).
Covers bidirectional LSTM, GRU with linear_before_reset (the torch export
default), and end-to-end parity of a stacked recurrent classifier.
Reference runs these through ONNX Runtime (``onnx/ONNXModel.scala:211``)."""

import io
import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).parent))

torch = pytest.importorskip("torch")
from torch import nn  # noqa: E402

from _torch_resnet import _install_onnx_shim  # noqa: E402


class RecNet(nn.Module):
    def __init__(self):
        super().__init__()
        self.lstm = nn.LSTM(8, 16, num_layers=1, bidirectional=True)
        self.gru = nn.GRU(32, 12)
        self.head = nn.Linear(12, 4)

    def forward(self, x):
        h, _ = self.lstm(x)
        g, _ = self.gru(h)
        return self.head(g[-1])


def _export(model, args, **kw):
    _install_onnx_shim()
    model.eval()
    buf = io.BytesIO()
    torch.onnx.export(model, args, buf, dynamo=False, **kw)
    return buf.getvalue()


@pytest.fixture(scope="module")
def exported():
    torch.manual_seed(0)
    model = RecNet()
    data = _export(model, (torch.randn(10, 3, 8),), input_names=["x"],
                   output_names=["y"])
    return model, data


def test_rnn_export_ops_all_supported(exported):
    from synapseml_tpu.onnx.convert import OP_REGISTRY
    from synapseml_tpu.onnx.proto import ModelProto

    _, data = exported
    ops = {n.op_type for n in ModelProto.parse(data).graph.node}
    assert {"LSTM", "GRU"} <= ops
    missing = sorted(o for o in ops if o not in OP_REGISTRY)
    assert not missing, f"unsupported recurrent ops: {missing}"


def test_stacked_bilstm_gru_matches_torch(exported):
    import jax

    from synapseml_tpu.onnx import convert_graph

    model, data = exported
    conv = convert_graph(data)
    fn = jax.jit(lambda t: conv(x=t)["y"])
    x = torch.randn(10, 3, 8, generator=torch.Generator().manual_seed(1))
    with torch.no_grad():
        want = model(x).numpy()
    np.testing.assert_allclose(np.asarray(fn(x.numpy())), want,
                               rtol=2e-4, atol=2e-5)


def test_lstm_all_outputs_and_initial_state():
    """Y / Y_h / Y_c all match a direct torch LSTM given a nonzero initial
    state passed as graph inputs."""
    import jax

    from synapseml_tpu.onnx import convert_graph

    class Bare(nn.Module):
        def __init__(self):
            super().__init__()
            self.lstm = nn.LSTM(5, 7)

        def forward(self, x, h0, c0):
            y, (h, c) = self.lstm(x, (h0, c0))
            return y, h, c

    torch.manual_seed(2)
    m = Bare()
    x = torch.randn(6, 2, 5)
    h0, c0 = torch.randn(1, 2, 7), torch.randn(1, 2, 7)
    data = _export(m, (x, h0, c0), input_names=["x", "h0", "c0"],
                   output_names=["y", "h", "c"])
    conv = convert_graph(data)
    out = jax.jit(lambda *a: conv(x=a[0], h0=a[1], c0=a[2]))(
        x.numpy(), h0.numpy(), c0.numpy())
    with torch.no_grad():
        wy, (wh, wc) = m.lstm(x, (h0, c0))
    # torch's exporter already squeezes Y to the [T, B, H] torch layout
    np.testing.assert_allclose(np.asarray(out["y"]), wy.numpy(),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(out["h"]), wh.numpy(),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(out["c"]), wc.numpy(),
                               rtol=2e-4, atol=2e-5)
