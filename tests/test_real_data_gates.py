"""Real-data accuracy gates + cross-engine parity.

The reference pins accuracy on REAL datasets (SURVEY §4;
``lightgbm/src/test/resources/benchmarks/benchmarks_VerifyLightGBMClassifier
StreamBasic.csv`` — BreastTissue 0.8774±0.07 etc., and the DL gate
``deep-learning/src/test/python/synapsemltest/dl/test_deep_text_classifier.py``
accuracy > 0.5 on real emotion data). The container has no egress, so the
reference's exact datasets (BreastTissue, Higgs, emotion) can't be fetched;
these gates use scikit-learn's BUNDLED real datasets instead — breast_cancer
(569 real clinical records), wine, digits (1797 real handwritten images),
diabetes (442 real patient records) — which are real measured data, not
synthetic stand-ins, evaluated on held-out splits.

Cross-engine parity: sklearn's HistGradientBoosting* is an independent
LightGBM-style histogram GBDT available in-container; matching its held-out
accuracy on the same split is the locally-falsifiable analog of the
reference's stock-LightGBM comparisons.
"""

import numpy as np
import pytest

from sklearn.datasets import (load_breast_cancer, load_diabetes, load_digits,
                              load_wine)

import synapseml_tpu as st
from synapseml_tpu.gbdt.booster import train_booster

from test_benchmark_gates import _assert_gate  # tests/ is a rootdir, not a package


def _split(X, y, seed=7, frac=0.75):
    rs = np.random.default_rng(seed)
    idx = rs.permutation(len(y))
    k = int(len(y) * frac)
    return (X[idx[:k]], y[idx[:k]], X[idx[k:]], y[idx[k:]])


def _auc(scores, y):
    from scipy.stats import rankdata

    ranks = rankdata(scores)  # ties get averaged ranks (exact Mann-Whitney)
    pos = y == 1
    n1, n0 = pos.sum(), (~pos).sum()
    return float((ranks[pos].sum() - n1 * (n1 + 1) / 2) / (n1 * n0))


def test_breast_cancer_auc_gate_and_parity():
    """Binary AUC on real clinical data, held out, gated AND compared against
    sklearn HistGradientBoosting with the same capacity on the same split."""
    d = load_breast_cancer()
    Xtr, ytr, Xte, yte = _split(d.data.astype(np.float32),
                                d.target.astype(np.float32))
    b = train_booster(Xtr, ytr, objective="binary", num_iterations=60,
                      learning_rate=0.1, num_leaves=15, seed=0)
    ours = _auc(b.predict(Xte).ravel(), yte)
    _assert_gate("real_breast_cancer_gbdt_auc", ours)

    from sklearn.ensemble import HistGradientBoostingClassifier

    h = HistGradientBoostingClassifier(
        max_iter=60, learning_rate=0.1, max_leaf_nodes=15,
        random_state=0).fit(Xtr, ytr)
    theirs = _auc(h.predict_proba(Xte)[:, 1], yte)
    assert ours >= theirs - 0.02, \
        f"AUC parity vs sklearn HGB: ours {ours:.4f} vs theirs {theirs:.4f}"


def test_wine_multiclass_gate():
    d = load_wine()
    Xtr, ytr, Xte, yte = _split(d.data.astype(np.float32),
                                d.target.astype(np.float32))
    b = train_booster(Xtr, ytr, objective="multiclass", num_class=3,
                      num_iterations=40, learning_rate=0.1, num_leaves=7,
                      seed=0)
    acc = float(np.mean(np.argmax(b.predict(Xte), 1) == yte))
    _assert_gate("real_wine_gbdt_accuracy", acc)


def test_digits_multiclass_gate():
    d = load_digits()
    Xtr, ytr, Xte, yte = _split(d.data.astype(np.float32),
                                d.target.astype(np.float32))
    b = train_booster(Xtr, ytr, objective="multiclass", num_class=10,
                      num_iterations=30, learning_rate=0.2, num_leaves=15,
                      seed=0)
    acc = float(np.mean(np.argmax(b.predict(Xte), 1) == yte))
    _assert_gate("real_digits_gbdt_accuracy", acc)


def test_diabetes_regression_gate_and_parity():
    d = load_diabetes()
    Xtr, ytr, Xte, yte = _split(d.data.astype(np.float32),
                                d.target.astype(np.float32))
    b = train_booster(Xtr, ytr, objective="regression", num_iterations=60,
                      learning_rate=0.1, num_leaves=7, seed=0)
    rmse = float(np.sqrt(np.mean((b.predict(Xte).ravel() - yte) ** 2)))
    _assert_gate("real_diabetes_gbdt_rmse", rmse)

    from sklearn.ensemble import HistGradientBoostingRegressor

    h = HistGradientBoostingRegressor(
        max_iter=60, learning_rate=0.1, max_leaf_nodes=7,
        random_state=0).fit(Xtr, ytr)
    theirs = float(np.sqrt(np.mean((h.predict(Xte) - yte) ** 2)))
    assert rmse <= theirs * 1.10, \
        f"RMSE parity vs sklearn HGB: ours {rmse:.2f} vs theirs {theirs:.2f}"


def test_vw_breast_cancer_gate():
    """VW linear classifier through the estimator surface on real data."""
    from synapseml_tpu.vw.estimators import VowpalWabbitClassifier

    d = load_breast_cancer()
    X = ((d.data - d.data.mean(0)) / (d.data.std(0) + 1e-9)).astype(np.float32)
    rs = np.random.default_rng(7)
    idx = rs.permutation(len(X))
    k = int(len(X) * 0.75)
    f = X.shape[1]

    def mk(ix):
        return st.DataFrame.from_rows(
            [{"features_indices": np.arange(f, dtype=np.int32),
              "features_values": X[i], "label": int(d.target[i])}
             for i in ix])

    m = VowpalWabbitClassifier(num_passes=10, learning_rate=0.5).fit(mk(idx[:k]))
    out = m.transform(mk(idx[k:]))
    acc = float(np.mean(out.collect_column("prediction")
                        == out.collect_column("label")))
    _assert_gate("real_breast_cancer_vw_accuracy", acc)
    prob = np.asarray(list(out.collect_column("probability")), np.float64)
    assert np.all((prob >= 0) & (prob <= 1)) and np.all(np.isfinite(prob))


@pytest.mark.slow
def test_deep_vision_digits_gate():
    """DeepVisionClassifier fine-tune gate on real handwritten-digit images —
    the analog of the reference's real-data DL gate (accuracy > 0.5,
    test_deep_text_classifier.py:48-52); ours pins the measured accuracy."""
    from synapseml_tpu.models.vision import DeepVisionClassifier

    d = load_digits()
    imgs = (d.images / 16.0).astype(np.float32)[..., None].repeat(3, -1)
    rs = np.random.default_rng(7)
    idx = rs.permutation(len(imgs))
    tr, te = idx[:1200], idx[1200:]
    df_tr = st.DataFrame.from_rows(
        [{"image": imgs[i], "label": int(d.target[i])} for i in tr])
    df_te = st.DataFrame.from_rows(
        [{"image": imgs[i], "label": int(d.target[i])} for i in te])
    m = DeepVisionClassifier(backbone="resnet_tiny", num_classes=10,
                             batch_size=64, num_train_epochs=4,
                             learning_rate=3e-3).fit(df_tr)
    out = m.transform(df_te)
    acc = float(np.mean(out.collect_column("prediction")
                        == out.collect_column("label")))
    _assert_gate("real_digits_resnet_tiny_accuracy", acc)


@pytest.mark.slow
def test_bootstrapped_breast_cancer_100k_gate():
    """Non-toy row count (VERDICT r3 next-#8): the round-3 gates top out at
    1,797 rows; this one runs the fused boosting loop AND the partitioned
    estimator path at 120,000 rows.

    The container has no egress (Higgs-1M unreachable), so the dataset is
    real breast_cancer TRAIN rows bootstrapped 120k-fold with small
    label-preserving feature noise (0.15 x per-feature std) — documented
    synthetic AUGMENTATION of real data, not synthetic data. The gate is
    honest: AUC is measured on HELD-OUT ORIGINAL rows that were never
    bootstrapped or noised.
    """
    d = load_breast_cancer()
    Xtr, ytr, Xte, yte = _split(d.data.astype(np.float32),
                                d.target.astype(np.float32))
    rs = np.random.default_rng(3)
    N = 120_000
    pick = rs.integers(0, len(ytr), N)
    noise = rs.normal(size=(N, Xtr.shape[1])).astype(np.float32)
    Xbig = Xtr[pick] + 0.15 * Xtr.std(axis=0, keepdims=True) * noise
    ybig = ytr[pick]

    # fused single-program loop
    b = train_booster(Xbig, ybig, objective="binary", num_iterations=40,
                      learning_rate=0.15, num_leaves=31, seed=0)
    fused_auc = _auc(b.predict(Xte).ravel(), yte)
    assert fused_auc > 0.97, f"fused loop AUC {fused_auc:.4f} at 120k rows"

    # partitioned estimator path (distributed histogram merge)
    from synapseml_tpu.gbdt import LightGBMClassifier

    df = st.DataFrame.from_dict({"features": Xbig, "label": ybig},
                                num_partitions=8)
    model = LightGBMClassifier(num_iterations=40, learning_rate=0.15,
                               num_leaves=31, seed=0).fit(df)
    test_out = model.transform(st.DataFrame.from_dict(
        {"features": Xte, "label": yte}))
    prob = np.stack(list(test_out.collect_column("probability")))[:, 1]
    part_auc = _auc(prob, yte)
    assert part_auc > 0.97, f"partitioned path AUC {part_auc:.4f} at 120k rows"
    # both engines see the same data; their generalization must agree closely
    assert abs(part_auc - fused_auc) < 0.02, (part_auc, fused_auc)
