"""Property-style ``save_stage`` → ``load_stage`` round trips for EVERY
registered stage type (the classes the registry manifest can reference),
guarding the manifest's ``param_schema_sha256``: if the serialization wire
format or a stage's param registry drifts, these fail before a published
artifact does."""

import os

import numpy as np
import pytest

from synapseml_tpu.codegen import discover_stages
from synapseml_tpu.core import serialization
from synapseml_tpu.core.params import ComplexParam, Param
from synapseml_tpu.core.pipeline import Transformer
from synapseml_tpu.registry import param_schema_hash

pytestmark = pytest.mark.registry


def _stage_classes():
    # one entry per class (discover_stages maps re-exports too)
    seen = {}
    for full, cls in sorted(discover_stages().items()):
        seen.setdefault(f"{cls.__module__}.{cls.__qualname__}", cls)
    return sorted(seen.items())


@pytest.mark.parametrize("full_name,cls", _stage_classes(),
                         ids=[f for f, _ in _stage_classes()])
def test_every_registered_stage_roundtrips(full_name, cls, tmp_path):
    """Default-constructed instance of every registered stage class saves,
    loads back as the same class, and preserves every param value —
    simple params by equality, complex pytree params leaf-by-leaf."""
    stage = cls()
    path = str(tmp_path / "stage")
    serialization.save_stage(stage, path)
    loaded = serialization.load_stage(path)
    assert type(loaded) is cls
    assert loaded.uid == stage.uid

    before = stage.simple_param_values()
    after = loaded.simple_param_values()
    assert set(after) == set(before)
    for name, value in before.items():
        got = after[name]
        if isinstance(value, np.ndarray):
            np.testing.assert_array_equal(got, value)
        else:
            assert got == value or (value != value and got != got), (
                f"{full_name}.{name}: {value!r} != {got!r}")

    cb, ca = stage.complex_param_values(), loaded.complex_param_values()
    assert set(ca) == set(cb)
    for name, value in cb.items():
        _assert_trees_equal(value, ca[name], f"{full_name}.{name}")

    # the registry's schema hash is a pure function of the artifact: a
    # save -> load -> save round trip must not move it
    path2 = str(tmp_path / "stage2")
    serialization.save_stage(loaded, path2)
    assert param_schema_hash(path) == param_schema_hash(path2)


def _assert_trees_equal(a, b, at):
    from synapseml_tpu.core.pipeline import PipelineStage

    if isinstance(a, PipelineStage):
        assert type(b) is type(a), at
        assert b.simple_param_values() == a.simple_param_values(), at
        return
    if isinstance(a, dict):
        assert isinstance(b, dict) and set(a) == set(b), at
        for k in a:
            _assert_trees_equal(a[k], b[k], f"{at}.{k}")
        return
    if isinstance(a, (list, tuple)):
        assert len(a) == len(b), at
        for i, (x, y) in enumerate(zip(a, b)):
            _assert_trees_equal(x, y, f"{at}[{i}]")
        return
    if isinstance(a, np.ndarray) or hasattr(a, "__array__"):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), at)
        return
    if callable(a):
        assert callable(b), at  # pickled callables: same kind is the contract
        return
    assert a == b or a is b, f"{at}: {a!r} != {b!r}"


class PytreeCarrier(Transformer):
    """Local stage with one complex pytree param (the property target)."""

    payload = ComplexParam("payload", "arbitrary pytree")
    label = Param("label", "simple string param", default="x")

    def _transform(self, df):
        return df


def _random_pytree(rng, depth=0):
    kind = rng.integers(0, 6 if depth < 3 else 3)
    if kind == 0:
        return rng.normal(size=tuple(rng.integers(1, 4, size=rng.integers(0, 3)))).astype(np.float32)
    if kind == 1:
        return rng.integers(-100, 100, size=(rng.integers(1, 5),))
    if kind == 2:
        return float(rng.normal())
    if kind == 3:
        return {f"k{i}": _random_pytree(rng, depth + 1)
                for i in range(rng.integers(1, 4))}
    if kind == 4:
        return [_random_pytree(rng, depth + 1)
                for _ in range(rng.integers(1, 4))]
    return tuple(_random_pytree(rng, depth + 1)
                 for _ in range(rng.integers(1, 3)))


@pytest.mark.parametrize("seed", range(8))
def test_pytree_complex_param_property(seed, tmp_path):
    """Seeded random nested pytrees (dict/list/tuple of arrays + scalars)
    survive the npz round trip leaf-for-leaf, structure-for-structure."""
    rng = np.random.default_rng(seed)
    stage = PytreeCarrier(payload=_random_pytree(rng), label=f"s{seed}")
    path = str(tmp_path / "stage")
    serialization.save_stage(stage, path)
    loaded = serialization.load_stage(path)
    assert loaded.get("label") == f"s{seed}"
    _assert_trees_equal(stage.get("payload"), loaded.get("payload"),
                        f"seed{seed}")


def test_non_array_complex_values_roundtrip(tmp_path):
    """bytes/str/mixed payloads fall back to pickle and come back intact
    (the npz path must NOT capture them — 0-d S/U arrays break consumers)."""
    for payload in (b"raw-bytes", "a string", {"mixed": [1, "two", b"3"]},
                    {"fn": len}):
        stage = PytreeCarrier(payload=payload)
        path = str(tmp_path / "s")
        serialization.save_stage(stage, path)
        loaded = serialization.load_stage(path)
        got = loaded.get("payload")
        if isinstance(payload, dict) and "fn" in payload:
            assert callable(got["fn"])
        else:
            assert got == payload and type(got) is type(payload)


def test_schema_hash_differs_when_params_differ(tmp_path):
    """The schema hash keys on the param REGISTRY (names/kinds), not values:
    same class different values -> same hash; different class -> different
    hash (what the registry compares across versions)."""
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    serialization.save_stage(PytreeCarrier(payload=[1.0], label="a"), a)
    serialization.save_stage(PytreeCarrier(payload={"x": np.ones(3)},
                                           label="b"), b)
    assert param_schema_hash(a) == param_schema_hash(b)

    from synapseml_tpu.stages import RenameColumn

    c = str(tmp_path / "c")
    serialization.save_stage(RenameColumn(input_col="i", output_col="o"), c)
    assert param_schema_hash(c) != param_schema_hash(a)
