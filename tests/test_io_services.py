"""io.http fabric + serving + cognitive services against a local mock server."""

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from synapseml_tpu.core import DataFrame
from synapseml_tpu.core.pipeline import Transformer
from synapseml_tpu.io import (
    HTTPRequest,
    HTTPTransformer,
    JSONInputParser,
    SimpleHTTPTransformer,
    send_with_retries,
    serve_pipeline,
)
from synapseml_tpu.services import (
    AnalyzeText,
    AzureSearchWriter,
    OpenAIChatCompletion,
    OpenAIDefaults,
    OpenAIEmbedding,
    OpenAIPrompt,
    TextSentiment,
    Translate,
)


class MockServiceHandler(BaseHTTPRequestHandler):
    """One handler mocking every service shape the tests need."""

    flaky_counts: dict = {}
    lro_state: dict = {}

    def log_message(self, *a):
        pass

    def _reply(self, payload, status=200, headers=None):
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self):
        n = int(self.headers.get("Content-Length") or 0)
        return json.loads(self.rfile.read(n).decode() or "null")

    def do_GET(self):
        if self.path.startswith("/flaky-date/"):
            key = self.path.split("/")[-1]
            MockServiceHandler.flaky_counts[key] = \
                MockServiceHandler.flaky_counts.get(key, 0) + 1
            if MockServiceHandler.flaky_counts[key] < 2:
                self._reply({"err": "throttled"}, status=429,
                            headers={"Retry-After": "Wed, 21 Oct 2015 07:28:00 GMT"})
            else:
                self._reply({"ok": True})
        elif self.path.startswith("/flaky/"):
            key = self.path.split("/")[-1]
            MockServiceHandler.flaky_counts[key] = \
                MockServiceHandler.flaky_counts.get(key, 0) + 1
            if MockServiceHandler.flaky_counts[key] < 3:
                self._reply({"err": "throttled"}, status=429,
                            headers={"Retry-After": "0.01"})
            else:
                self._reply({"ok": True, "attempts": MockServiceHandler.flaky_counts[key]})
        elif self.path.startswith("/lro/poll/"):
            key = self.path.split("/")[-1]
            MockServiceHandler.lro_state[key] = MockServiceHandler.lro_state.get(key, 0) + 1
            if MockServiceHandler.lro_state[key] < 2:
                self._reply({"status": "running"})
            else:
                self._reply({"status": "succeeded", "results": {"value": 42}})
        elif self.path == "/echo":
            self._reply({"method": "GET", "path": self.path})
        else:
            self._reply({"error": "not found"}, status=404)

    def do_POST(self):
        body = self._body()
        if "/chat/completions" in self.path:
            user_msg = [m for m in body["messages"] if m["role"] == "user"][-1]
            reply = {"choices": [{"message": {
                "role": "assistant",
                "content": f"echo:{user_msg['content']}"
                if "json" not in user_msg["content"].lower()
                else '{"answer": 7, "reason": "mock"}'}}]}
            if not self.headers.get("api-key"):
                self._reply({"error": "unauthorized"}, status=401)
                return
            self._reply(reply)
        elif "/embeddings" in self.path:
            text = body["input"]
            self._reply({"data": [{"embedding": [float(len(text)), 1.0, 2.0]}]})
        elif ":analyze-text" in self.path:
            doc = body["analysisInput"]["documents"][0]
            kind = body["kind"]
            if kind == "SentimentAnalysis":
                sentiment = "positive" if "good" in doc["text"] else "negative"
                self._reply({"results": {"documents": [
                    {"id": "0", "sentiment": sentiment}]}})
            else:
                self._reply({"results": {"documents": [
                    {"id": "0", "keyPhrases": doc["text"].split()[:2]}]}})
        elif self.path.startswith("/translate"):
            self._reply([{"translations": [{"text": f"xx:{body[0]['Text']}",
                                            "to": "xx"}]}])
        elif "/docs/index" in self.path:
            if not self.headers.get("api-key"):
                self._reply({"error": "no key"}, status=403)
                return
            self._reply({"value": [{"key": d.get("id"), "status": True,
                                    "statusCode": 201} for d in body["value"]]})
        elif self.path == "/lro/start":
            key = str(len(MockServiceHandler.lro_state))
            MockServiceHandler.lro_state[key] = 0
            host = self.headers.get("Host")
            self._reply({"status": "accepted"}, status=202,
                        headers={"Operation-Location": f"http://{host}/lro/poll/{key}"})
        else:
            self._reply({"echo": body, "path": self.path})


@pytest.fixture(scope="module")
def mock_server():
    server = ThreadingHTTPServer(("127.0.0.1", 0), MockServiceHandler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{server.server_address[1]}"
    server.shutdown()
    server.server_close()


def test_send_with_retries_429(mock_server):
    MockServiceHandler.flaky_counts.clear()
    resp = send_with_retries(HTTPRequest(url=f"{mock_server}/flaky/a"),
                             backoffs_ms=(5, 5, 5))
    assert resp.status_code == 200
    assert resp.json()["attempts"] == 3  # two 429s then success


def test_send_with_retries_connection_error():
    resp = send_with_retries(HTTPRequest(url="http://127.0.0.1:1/none"),
                             backoffs_ms=(1,))
    assert resp.status_code == 0
    assert resp.error


def test_http_transformer_with_nulls(mock_server):
    reqs = np.empty(3, dtype=object)
    reqs[0] = HTTPRequest(url=f"{mock_server}/echo")
    reqs[1] = None
    reqs[2] = HTTPRequest(url=f"{mock_server}/missing")
    df = DataFrame.from_dict({"request": reqs})
    out = HTTPTransformer(concurrency=3).transform(df).collect_column("response")
    assert out[0].status_code == 200
    assert out[1] is None
    assert out[2].status_code == 404


def test_simple_http_transformer(mock_server):
    df = DataFrame.from_dict({"input": [{"a": 1}, {"a": 2}]})
    t = SimpleHTTPTransformer(
        input_parser=JSONInputParser(url=f"{mock_server}/post"),
        input_col="input", output_col="out")
    res = t.transform(df)
    outs = res.collect_column("out")
    assert outs[0]["echo"] == {"a": 1}
    assert list(res.collect_column("errors")) == [None, None]


def test_serving_round_trip():
    class Doubler(Transformer):
        def _transform(self, df):
            def fn(p):
                out = np.empty(len(p["body"]), dtype=object)
                for i, b in enumerate(p["body"]):
                    out[i] = {"doubled": b["x"] * 2}
                return out
            return df.with_column("reply", fn)

    server = serve_pipeline(Doubler(), batch_interval_ms=5)
    try:
        results = {}

        def call(i):
            req = urllib.request.Request(server.address, method="POST",
                                         data=json.dumps({"x": i}).encode(),
                                         headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as r:
                results[i] = json.loads(r.read())

        threads = [threading.Thread(target=call, args=(i,)) for i in range(8)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert results == {i: {"doubled": 2 * i} for i in range(8)}
    finally:
        server.stop()


def test_serving_error_replies():
    class Boom(Transformer):
        def _transform(self, df):
            raise RuntimeError("kaput")

    server = serve_pipeline(Boom(), batch_interval_ms=5)
    try:
        req = urllib.request.Request(server.address, method="POST", data=b"{}")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 500
        assert json.loads(exc.value.read())["error"] == "kaput"
    finally:
        server.stop()


def test_openai_chat_and_defaults(mock_server):
    OpenAIDefaults.reset()
    OpenAIDefaults.set_deployment_name("gpt-test")
    OpenAIDefaults.set_subscription_key("k123")
    try:
        msgs = np.empty(2, dtype=object)
        msgs[0] = [{"role": "user", "content": "hello"}]
        msgs[1] = [{"role": "user", "content": "world"}]
        df = DataFrame.from_dict({"messages": msgs})
        chat = OpenAIChatCompletion(url=mock_server)
        out = chat.transform(df)
        replies = [r["choices"][0]["message"]["content"]
                   for r in out.collect_column("chat_completions")]
        assert replies == ["echo:hello", "echo:world"]
        assert list(out.collect_column("errors")) == [None, None]
    finally:
        OpenAIDefaults.reset()


def test_openai_missing_key_gives_error_column(mock_server):
    OpenAIDefaults.reset()
    msgs = np.empty(1, dtype=object)
    msgs[0] = [{"role": "user", "content": "hi"}]
    df = DataFrame.from_dict({"messages": msgs})
    out = OpenAIChatCompletion(url=mock_server, deployment_name="d").transform(df)
    assert "401" in out.collect_column("errors")[0]


def test_openai_embedding(mock_server):
    df = DataFrame.from_dict({"text": ["abc", "hello"]})
    emb = OpenAIEmbedding(url=mock_server, deployment_name="e", subscription_key="k")
    out = emb.transform(df).collect_column("embedding")
    np.testing.assert_allclose(out[0], [3.0, 1.0, 2.0])
    np.testing.assert_allclose(out[1], [5.0, 1.0, 2.0])


def test_openai_prompt_parsers(mock_server):
    df = DataFrame.from_dict({"q": ["what", "why"], "ctx": ["a", "b"]})
    prompt = OpenAIPrompt(url=mock_server, deployment_name="d", subscription_key="k",
                          prompt_template="Answer {q} given {ctx} in JSON",
                          post_processing="json")
    out = prompt.transform(df).collect_column("outParsedOutput")
    assert out[0] == {"answer": 7, "reason": "mock"}

    regex = OpenAIPrompt(url=mock_server, deployment_name="d", subscription_key="k",
                         prompt_template="say {q}", post_processing="regex",
                         post_processing_options={"regex": "echo:say (\\w+)",
                                                  "regexGroup": 1})
    out2 = regex.transform(df).collect_column("outParsedOutput")
    assert list(out2) == ["what", "why"]

    with pytest.raises(ValueError, match="template columns"):
        OpenAIPrompt(url=mock_server, deployment_name="d", subscription_key="k",
                     prompt_template="{missing_col}").transform(df)


def test_text_services(mock_server):
    df = DataFrame.from_dict({"text": ["good day", "awful day"]})
    sent = TextSentiment(url=mock_server, subscription_key="k")
    out = sent.transform(df).collect_column("sentiment")
    assert list(out) == ["positive", "negative"]

    kp = AnalyzeText(url=mock_server, subscription_key="k", kind="KeyPhraseExtraction")
    doc = kp.transform(df).collect_column("out")[0]
    assert doc["keyPhrases"] == ["good", "day"]


def test_translate(mock_server):
    df = DataFrame.from_dict({"text": ["hola"]})
    tr = Translate(url=mock_server, subscription_key="k", to_language="xx")
    assert tr.transform(df).collect_column("translation")[0] == ["xx:hola"]


def test_search_writer(mock_server):
    df = DataFrame.from_dict({"id": ["1", "2", "3"], "content": ["a", "b", "c"]})
    w = AzureSearchWriter(url=mock_server, subscription_key="k",
                          index_name="idx", batch_size=2)
    statuses = w.write(df)
    assert len(statuses) == 2  # 3 docs / batch 2
    assert statuses[0]["value"][0]["statusCode"] == 201
    # missing key -> failed batches raise in transform
    bad = AzureSearchWriter(url=mock_server, index_name="idx")
    with pytest.raises(RuntimeError, match="failed batches"):
        bad.transform(df)


def test_async_lro(mock_server):
    from synapseml_tpu.io.http import HTTPRequest as Req
    from synapseml_tpu.services.base import HasAsyncReply

    class LROService(HasAsyncReply):
        def build_request(self, rp):
            return Req(url=f"{mock_server}/lro/start", method="POST",
                       entity=json.dumps({}))

    MockServiceHandler.lro_state.clear()
    df = DataFrame.from_dict({"x": [1]})
    svc = LROService(url=mock_server, polling_interval_s=0.02)
    out = svc.transform(df).collect_column("out")
    assert out[0]["status"] == "succeeded"
    assert out[0]["results"]["value"] == 42


def test_prompt_with_literal_braces(mock_server):
    df = DataFrame.from_dict({"q": ["thing"]})
    prompt = OpenAIPrompt(url=mock_server, deployment_name="d", subscription_key="k",
                          prompt_template='Classify {q}. Reply as {"label": "..."} json',
                          post_processing="json")
    out = prompt.transform(df).collect_column("outParsedOutput")
    assert out[0] == {"answer": 7, "reason": "mock"}  # braces passed through


def test_retry_after_http_date(mock_server):
    # date-formatted Retry-After is PARSED (email.utils.parsedate_to_datetime);
    # a past date clamps to a zero wait instead of crashing in float()
    MockServiceHandler.flaky_counts.clear()
    resp = send_with_retries(HTTPRequest(url=f"{mock_server}/flaky-date/x"),
                             backoffs_ms=(5, 5))
    assert resp.status_code == 200
    assert resp.json()["ok"] is True
