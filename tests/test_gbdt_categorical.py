"""Categorical feature support (reference ``categoricalSlotIndexes``,
``params/LightGBMParams.scala``; LightGBM many-vs-many categorical splits).

The engine sorts a categorical feature's bins per node by
grad/(hess+cat_smooth) and scans prefixes of that order — one fused
histogram pass, same as numerical thresholds. Membership is stored as a
per-node bin mask; unseen/out-of-range/NaN categories route right.
"""

import numpy as np
import pytest

import synapseml_tpu as st
from synapseml_tpu.gbdt import LightGBMClassifier
from synapseml_tpu.gbdt.booster import TpuBooster, train_booster


def _cat_data(n=3000, n_cats=30, seed=0, noise=0.05):
    """Label = membership of a scrambled category subset — a single
    many-vs-many split captures it; numerical thresholds on the codes
    need many cuts."""
    rs = np.random.default_rng(seed)
    cats = rs.integers(0, n_cats, n)
    good = rs.permutation(n_cats)[: n_cats // 2]
    y = np.isin(cats, good).astype(np.float32)
    flip = rs.random(n) < noise
    y[flip] = 1 - y[flip]
    X = np.column_stack([
        rs.normal(size=n),                  # numeric noise
        cats.astype(np.float64),            # the categorical signal
        rs.normal(size=n) * 0.1,            # weak numeric
    ]).astype(np.float32)
    return X, y, good


def _acc(b, X, y):
    return float(((np.asarray(b.predict(X)).ravel() > 0.5) == (y > 0.5)).mean())


def test_categorical_split_beats_numerical_treatment():
    X, y, _ = _cat_data()
    # few shallow trees: one many-vs-many split captures the scattered
    # subset, while numerical thresholds get only 2*3 cuts in total
    kw = dict(objective="binary", num_iterations=2, learning_rate=0.5,
              num_leaves=7, max_depth=3, min_data_in_leaf=5, seed=0)
    b_cat = train_booster(X, y, categorical_features=[1], **kw)
    b_num = train_booster(X, y, **kw)
    acc_cat, acc_num = _acc(b_cat, X, y), _acc(b_num, X, y)
    assert acc_cat > 0.92, acc_cat
    assert acc_cat > acc_num + 0.05, (acc_cat, acc_num)


def test_unseen_and_invalid_categories_route_like_missing():
    X, y, good = _cat_data()
    b = train_booster(X, y, objective="binary", num_iterations=6,
                      learning_rate=0.3, num_leaves=7, max_depth=3,
                      min_data_in_leaf=5, seed=0, categorical_features=[1])
    probe = np.tile(X[:1], (4, 1)).astype(np.float32)
    probe[0, 1] = 254.0      # in-range but never seen in training
    probe[1, 1] = 3000.0     # out of the bin range entirely
    probe[2, 1] = -5.0       # negative code
    probe[3, 1] = np.nan     # missing
    p = np.asarray(b.predict(probe)).ravel()
    # all four are non-members everywhere -> identical (right-routing) paths
    assert np.allclose(p, p[0]), p


def test_categorical_save_load_leaf_shap_and_dump(tmp_path):
    X, y, _ = _cat_data(n=1500)
    b = train_booster(X, y, objective="binary", num_iterations=5,
                      learning_rate=0.3, num_leaves=7, max_depth=3,
                      min_data_in_leaf=5, seed=0, categorical_features=[1])
    # save/load keeps categorical routing byte-identical
    b.save(str(tmp_path / "m"))
    b2 = TpuBooster.load(str(tmp_path / "m"))
    assert b2.categorical_features == (1,)
    np.testing.assert_allclose(np.asarray(b.predict(X)),
                               np.asarray(b2.predict(X)), rtol=1e-6)
    # leaf indexing follows categorical routing (same path both ways)
    np.testing.assert_array_equal(b.predict_leaf(X[:64]), b2.predict_leaf(X[:64]))
    # exact TreeSHAP additivity holds through categorical nodes
    contrib = b.predict_contrib(X[:128])
    np.testing.assert_allclose(contrib.sum(-1)[:, 0],
                               b.raw_score(X[:128])[:, 0], rtol=1e-4, atol=1e-5)
    # the categorical signal dominates the attributions
    mean_abs = np.abs(contrib[:, 0, :-1]).mean(0)
    assert mean_abs[1] > 5 * max(mean_abs[0], mean_abs[2]), mean_abs
    # dump shows set-membership nodes
    assert " in [" in b.dump_text()


@pytest.mark.parametrize("boosting_type", ["goss", "dart"])
def test_categorical_with_sampling_modes(boosting_type):
    X, y, _ = _cat_data(n=1500)
    b = train_booster(X, y, objective="binary", num_iterations=6,
                      learning_rate=0.3, num_leaves=7, max_depth=3,
                      min_data_in_leaf=5, seed=0, categorical_features=[1],
                      boosting_type=boosting_type)
    assert _acc(b, X, y) > 0.85


def test_estimator_categorical_slot_indexes():
    X, y, _ = _cat_data(n=1500)
    df = st.DataFrame.from_dict({"features": X, "label": y.astype(np.int32)},
                                num_partitions=4)
    clf = LightGBMClassifier(num_iterations=8, learning_rate=0.3,
                             num_leaves=7, max_depth=3, min_data_in_leaf=5,
                             categorical_slot_indexes=[1])
    model = clf.fit(df)
    out = model.transform(df)
    acc = float(np.mean(out.collect_column("prediction")
                        == out.collect_column("label")))
    assert acc > 0.92, acc


def test_categorical_native_model_txt_round_trip():
    """model.txt interop for categorical trees: decision_type bit 1,
    cat_boundaries/cat_threshold 32-bit bitset words (reference
    ``booster/LightGBMBooster.scala:458`` saveNativeModel round trip).
    Export -> parse -> predictions match the trained booster, and a second
    export is byte-stable."""
    from synapseml_tpu.gbdt import parse_lightgbm_string, to_lightgbm_string

    X, y, _ = _cat_data(n=1500)
    b = train_booster(X, y, objective="binary", num_iterations=5,
                      learning_rate=0.3, num_leaves=7, max_depth=3,
                      min_data_in_leaf=5, seed=0, categorical_features=[1])
    text = to_lightgbm_string(b)
    assert "num_cat=" in text and "cat_threshold=" in text
    imported = parse_lightgbm_string(text)
    probe = np.vstack([X[:200], X[:1]])
    probe[-1, 1] = np.nan  # missing categorical routes right both sides
    np.testing.assert_allclose(np.asarray(imported.predict(probe)).ravel(),
                               np.asarray(b.predict(probe)).ravel(),
                               rtol=1e-5, atol=1e-6)
    assert to_lightgbm_string(imported) == to_lightgbm_string(imported)
    # and the re-serialized form parses back to the same predictions
    again = parse_lightgbm_string(to_lightgbm_string(imported))
    np.testing.assert_allclose(np.asarray(again.predict(probe)).ravel(),
                               np.asarray(imported.predict(probe)).ravel(),
                               rtol=1e-6)
