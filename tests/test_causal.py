"""causal module: DML ATE recovery under confounding, ortho forest
heterogeneity, DiD family on synthetic panels, simplex solvers."""

import numpy as np
import pytest

from synapseml_tpu.core import DataFrame
from synapseml_tpu.core.pipeline import Estimator, Model
from synapseml_tpu.core.params import ComplexParam, Param
from synapseml_tpu.causal import (
    DiffInDiffEstimator,
    DoubleMLEstimator,
    OrthoForestDMLEstimator,
    ResidualTransformer,
    SyntheticControlEstimator,
    SyntheticDiffInDiffEstimator,
    constrained_least_squares,
    mirror_descent_simplex,
)


class RidgeRegressor(Estimator):
    """Minimal nuisance learner: ridge on the 'features' vector column,
    predicting the column named by label_col."""

    label_col = Param("label_col", "target column", default="label")

    def _fit(self, df):
        X = np.stack([np.asarray(v, np.float64) for v in df.collect_column("features")])
        y = np.asarray(df.collect_column(self.get("label_col")), np.float64)
        Xb = np.concatenate([X, np.ones((len(X), 1))], axis=1)
        coef = np.linalg.solve(Xb.T @ Xb + 1e-6 * np.eye(Xb.shape[1]), Xb.T @ y)
        return RidgeModel(coef=coef.tolist())


class RidgeModel(Model):
    coef = ComplexParam("coef", "weights+intercept")

    def _transform(self, df):
        c = np.asarray(self.get("coef"))

        def pred(p):
            X = np.stack([np.asarray(v, np.float64) for v in p["features"]])
            return X @ c[:-1] + c[-1]

        return df.with_column("prediction", pred)


def make_confounded(n=600, tau=2.0, seed=0):
    rs = np.random.default_rng(seed)
    X = rs.normal(size=(n, 3))
    t = X @ np.asarray([1.0, -0.5, 0.2]) + 0.5 * rs.normal(size=n)
    y = tau * t + X @ np.asarray([2.0, 1.0, -1.0]) + 0.5 * rs.normal(size=n)
    return DataFrame.from_dict({"features": X.astype(np.float32),
                                "treatment": t, "outcome": y})


def test_simplex_solvers():
    rs = np.random.default_rng(0)
    A = rs.normal(size=(50, 4))
    w_true = np.asarray([0.5, 0.3, 0.2, 0.0])
    b = A @ w_true
    w = mirror_descent_simplex(A, b, n_iter=5000)
    np.testing.assert_allclose(w, w_true, atol=0.02)
    assert w.sum() == pytest.approx(1.0)
    w2, b0 = constrained_least_squares(A, b + 5.0, fit_intercept=True, n_iter=5000)
    np.testing.assert_allclose(w2, w_true, atol=0.05)
    assert b0 == pytest.approx(5.0, abs=0.1)


def test_residual_transformer():
    df = DataFrame.from_dict({"label": [1.0, 0.0, 1.0],
                              "prediction": [0.8, 0.3, 0.5]})
    out = ResidualTransformer(observed_col="label").transform(df)
    np.testing.assert_allclose(out.collect_column("residual"), [0.2, -0.3, 0.5])


def test_double_ml_recovers_ate_under_confounding():
    df = make_confounded(tau=2.0)
    # naive OLS is badly biased by the confounders
    t = df.collect_column("treatment")
    y = df.collect_column("outcome")
    naive = float((t @ y) / (t @ t))
    assert abs(naive - 2.0) > 0.5

    dml = DoubleMLEstimator(outcome_model=RidgeRegressor(label_col="outcome"),
                            treatment_model=RidgeRegressor(label_col="treatment"),
                            max_iter=5, seed=1)
    model = dml.fit(df)
    ate = model.get_avg_treatment_effect()
    assert ate == pytest.approx(2.0, abs=0.15)
    lo, hi = model.get_confidence_interval()
    assert lo <= ate <= hi
    # transform stamps the effect
    assert model.transform(df).collect_column("effect")[0] == pytest.approx(ate)


def test_ortho_forest_heterogeneous_effects():
    rs = np.random.default_rng(2)
    n = 800
    X = rs.normal(size=(n, 2))
    h = rs.uniform(-1, 1, n)
    tau = np.where(h > 0, 3.0, 1.0)
    t = X @ np.asarray([0.8, -0.4]) + 0.5 * rs.normal(size=n)
    y = tau * t + X @ np.asarray([1.0, 1.0]) + 0.3 * rs.normal(size=n)
    df = DataFrame.from_dict({"features": X.astype(np.float32), "h": h,
                              "treatment": t, "outcome": y})
    est = OrthoForestDMLEstimator(
        outcome_model=RidgeRegressor(label_col="outcome"),
        treatment_model=RidgeRegressor(label_col="treatment"),
        heterogeneity_cols=["h"], num_trees=10, max_depth=2,
        min_samples_leaf=20, seed=0)
    model = est.fit(df)
    out = model.transform(df)
    eff = out.collect_column("effect")
    assert eff[h > 0.3].mean() == pytest.approx(3.0, abs=0.5)
    assert eff[h < -0.3].mean() == pytest.approx(1.0, abs=0.5)


def test_diff_in_diff():
    rs = np.random.default_rng(3)
    n = 2000
    treat = rs.integers(0, 2, n).astype(float)
    post = rs.integers(0, 2, n).astype(float)
    y = 1.0 + 0.5 * treat + 1.5 * post + 2.5 * treat * post + 0.1 * rs.normal(size=n)
    df = DataFrame.from_dict({"outcome": y, "treatment": treat, "postTreatment": post})
    model = DiffInDiffEstimator().fit(df)
    assert model.get_treatment_effect() == pytest.approx(2.5, abs=0.05)
    assert model.get("standard_error") < 0.05


def make_panel(tau=4.0, seed=0):
    """10 control units; treated unit = 0.6*u0 + 0.4*u1 (+effect after t=7)."""
    rs = np.random.default_rng(seed)
    T = 12
    base = rs.normal(size=(10, 1)) * 2 + rs.normal(size=(10, T)) * 0.1 \
        + np.linspace(0, 1, T)[None, :] * rs.uniform(0.5, 2, (10, 1))
    treated = 0.6 * base[0] + 0.4 * base[1]
    post = np.arange(T) >= 7
    treated = treated + tau * post
    rows = {"unit": [], "time": [], "outcome": [], "treatment": [], "postTreatment": []}
    for u in range(10):
        for t in range(T):
            rows["unit"].append(f"c{u}")
            rows["time"].append(t)
            rows["outcome"].append(base[u, t])
            rows["treatment"].append(0.0)
            rows["postTreatment"].append(float(post[t]))
    for t in range(T):
        rows["unit"].append("treated")
        rows["time"].append(t)
        rows["outcome"].append(treated[t])
        rows["treatment"].append(1.0)
        rows["postTreatment"].append(float(post[t]))
    return DataFrame.from_dict({k: np.asarray(v) for k, v in rows.items()})


def test_synthetic_control():
    df = make_panel(tau=4.0)
    model = SyntheticControlEstimator(unit_col="unit", time_col="time").fit(df)
    assert model.get_treatment_effect() == pytest.approx(4.0, abs=0.3)
    w = np.asarray(model.get("unit_weights"))
    assert w.sum() == pytest.approx(1.0, abs=1e-6)
    assert w[0] + w[1] > 0.85  # mass on the true donors


def test_synthetic_diff_in_diff():
    df = make_panel(tau=4.0, seed=1)
    model = SyntheticDiffInDiffEstimator(unit_col="unit", time_col="time").fit(df)
    assert model.get_treatment_effect() == pytest.approx(4.0, abs=0.4)
    assert np.asarray(model.get("time_weights")).sum() == pytest.approx(1.0, abs=1e-6)
