"""Elastic gang training: coordinated sharded checkpoints, failure
detection over the kept-alive rendezvous channel, and N→M elastic resume.

Unit layer: two-phase-commit checkpoint semantics, ElasticPlan stream
redistribution exactness, retention GC, gang fault-plane hooks, the
in-process (threaded) gang lifecycle. Chaos layer (multiprocess backend,
real OS processes): SIGKILL one of four workers mid-step and
SIGTERM-with-grace-window — the ISSUE-15 acceptance proofs. All chaos
tests ride the conftest watchdog so a protocol bug can never hang tier-1.
"""

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from synapseml_tpu.core.faults import FaultSpec, inject_faults
from synapseml_tpu.parallel import checkpoint as cp
from synapseml_tpu.parallel.backend import DriverRendezvous
from synapseml_tpu.parallel.gang import (EXIT_PREEMPTED, EXIT_RESIZE,
                                         GangAborted, GangCoordinator,
                                         GangWorker, Preempted,
                                         elastic_restore)

# ---------------------------------------------------------------------------
# coordinated sharded checkpoints: two-phase commit
# ---------------------------------------------------------------------------


def _row_chunk_fn(rank):
    """Test chunker: rank r owns row r of every 'params/w' leaf."""
    def chunk_fn(name, leaf):
        if name == "params/w":
            arr = np.asarray(leaf)
            return [([rank, 0], [rank + 1, arr.shape[1]],
                     arr[rank:rank + 1])]
        return None
    return chunk_fn


def _write_shards(path, step, world=3, host_extra=None):
    tree = {"params": {"w": np.arange(world * 4, dtype=np.float32)
                       .reshape(world, 4)},
            "step": np.int32(step),
            "opt": (np.ones(3, np.float32), {"mu": np.zeros(2, np.float32)})}
    for r in range(world):
        cp.save_checkpoint_shard(
            path, tree, step, process_index=r, process_count=world,
            host_tree={"data_iter": {str(r): {"epoch": np.int64(r)}}},
            meta={"orig_world": world} if r == 0 else None,
            chunk_fn=_row_chunk_fn(r))
    return tree


def test_two_phase_commit_roundtrip(tmp_path):
    d = str(tmp_path)
    tree = _write_shards(d, 7)
    # phase 1 only: invisible to every restore entry point
    assert cp.latest_step(d) is None
    assert cp.latest_verified_step(d) is None
    with pytest.raises(cp.CheckpointCorrupt, match="torn multi-host"):
        cp.restore_checkpoint(d, step=7)
    # phase 2: commit -> restorable, world + meta + host states readable
    assert cp.commit_checkpoint(d, 7, 3) is not None
    assert cp.latest_verified_step(d) == 7
    assert cp.checkpoint_world(d, 7) == 3
    assert cp.checkpoint_meta(d) == {"orig_world": 3}
    got = cp.restore_checkpoint(d)
    np.testing.assert_array_equal(got["params"]["w"], tree["params"]["w"])
    assert isinstance(got["opt"], tuple)  # sequence kinds survive assembly
    hosts = cp.restore_host_states(d)
    assert sorted(hosts) == [0, 1, 2]
    assert int(hosts[1]["data_iter"]["1"]["epoch"]) == 1


def test_commit_refuses_incomplete_ack_set(tmp_path):
    d = str(tmp_path)
    tree = {"w": np.zeros(4, np.float32)}
    for r in (0, 2):  # rank 1 never acked
        cp.save_checkpoint_shard(d, tree, 5, process_index=r,
                                 process_count=3)
    assert cp.commit_checkpoint(d, 5, 3) is None
    assert cp.latest_step(d) is None
    # a DONE marker beside a missing shard is torn, not restorable
    cp.save_checkpoint_shard(d, tree, 5, process_index=1, process_count=3)
    assert cp.commit_checkpoint(d, 5, 3) is not None
    os.remove(os.path.join(d, "step_0000000005",
                           "state.shard00001-of-00003.npz"))
    assert cp.latest_step(d) is None
    with pytest.raises(cp.CheckpointCorrupt):
        cp.restore_checkpoint(d, step=5)


def test_torn_shard_payload_is_checkpoint_corrupt(tmp_path):
    d = str(tmp_path)
    _write_shards(d, 7)
    assert cp.commit_checkpoint(d, 7, 3)
    payload = os.path.join(d, "step_0000000007",
                           "state.shard00001-of-00003.npz")
    with open(payload, "rb") as f:
        raw = f.read()
    with open(payload, "wb") as f:
        f.write(raw[:-9])  # torn tail
    assert cp.latest_verified_step(d) is None  # demoted, never restored
    with pytest.raises(cp.CheckpointCorrupt):
        cp.restore_checkpoint(d, step=7)


def test_commit_run_id_fences_stale_acks(tmp_path):
    """A killed run's leftover ACK in a torn step dir must never combine
    with a relaunched run's ACKs into a commit — the payload the stale ACK
    vouches for may still be mid-overwrite by the new incarnation."""
    d = str(tmp_path)
    tree = {"w": np.zeros(4, np.float32)}
    # old incarnation: rank 0 landed its shard+ACK, rank 1 died first
    cp.save_checkpoint_shard(d, tree, 9, process_index=0, process_count=2,
                             run_id="run-old")
    # relaunch: only rank 1 of the NEW incarnation has written so far
    cp.save_checkpoint_shard(d, tree, 9, process_index=1, process_count=2,
                             run_id="run-new")
    # full ACK set on disk, but mixed incarnations: the fence refuses —
    # and surfaces ONE structured warning (a worker launched without the
    # rendezvous run_id would otherwise no-commit forever, invisibly)
    import logging

    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    h = _Capture()
    logging.getLogger("synapseml_tpu.parallel.checkpoint").addHandler(h)
    try:
        assert cp.commit_checkpoint(d, 9, 2, run_id="run-new") is None
        assert cp.commit_checkpoint(d, 9, 2, run_id="run-new") is None
        fenced = [r for r in records if "checkpoint_commit_run_fenced" in r]
        assert len(fenced) == 1  # deduped per (dir, step)
    finally:
        logging.getLogger("synapseml_tpu.parallel.checkpoint"
                          ).removeHandler(h)
    # unfenced legacy commit (no run_id) still works
    cp.save_checkpoint_shard(d, tree, 9, process_index=0, process_count=2,
                             run_id="run-new")
    assert cp.commit_checkpoint(d, 9, 2, run_id="run-new") is not None
    assert cp.latest_verified_step(d) == 9


def test_commit_cleans_stale_incarnation_files(tmp_path):
    """An N→M resume re-reaching a step a killed N-world run half-wrote
    must not be poisoned by the leftovers: verify_checkpoint hashes EVERY
    sidecar'd payload in the dir, so one stale torn file would brick the
    recommitted step as corrupt forever. The commit (driver-side, every
    current-run ACK in) sweeps files no current ACK vouches for."""
    d = str(tmp_path)
    tree = {"w": np.ones(4, np.float32)}
    # old 2-world run: rank 1's shard lands TORN (sidecar intact), dies
    cp.save_checkpoint_shard(d, tree, 7, process_index=1, process_count=2,
                             run_id="old")
    stale = os.path.join(d, "step_%010d" % 7, "state.shard00001-of-00002.npz")
    with open(stale, "r+b") as f:
        raw = f.read()
        f.seek(0), f.truncate(), f.write(raw[:-7])
    # the survivor resumes N=2→M=1 and re-reaches step 7
    cp.save_checkpoint_shard(d, tree, 7, process_index=0, process_count=1,
                             run_id="new")
    assert cp.commit_checkpoint(d, 7, 1, run_id="new") is not None
    left = os.listdir(os.path.join(d, "step_%010d" % 7))
    assert not any("of-00002" in n for n in left), left
    assert cp.latest_verified_step(d) == 7  # stale torn file can't demote
    out = cp.restore_checkpoint(d, step=7)
    np.testing.assert_array_equal(out["w"], tree["w"])


def test_overlapping_chunks_do_not_mask_holes(tmp_path):
    """Coverage is validated element-wise, not by count: two overlapping
    4-element chunks of an 8-element leaf sum to 8 'covered' elements but
    leave [6:8] as uninitialized memory — that must restore as
    CheckpointCorrupt, never as garbage params."""
    d = str(tmp_path)
    leaf = np.arange(8, dtype=np.float32)

    def chunks_a(name, x):
        return [((0,), (4,), x[0:4])]

    def chunks_b(name, x):
        return [((2,), (6,), x[2:6])]  # overlaps A; hole at [6:8]

    cp.save_checkpoint_shard(d, {"w": leaf}, 5, process_index=0,
                             process_count=2, chunk_fn=chunks_a, run_id="r")
    cp.save_checkpoint_shard(d, {"w": leaf}, 5, process_index=1,
                             process_count=2, chunk_fn=chunks_b, run_id="r")
    assert cp.commit_checkpoint(d, 5, 2, run_id="r") is not None
    with pytest.raises(cp.CheckpointCorrupt, match="tile"):
        cp.restore_checkpoint(d, step=5)


def test_gc_prunes_torn_coordinated_dirs(tmp_path):
    """Phase-1-only (uncommitted) step dirs older than the newest verified
    step are crash leftovers that can never become the resume point — GC
    must remove them or a preemption-heavy week fills the disk and the
    commit scanner re-parses their ACK sets forever."""
    d = str(tmp_path)
    tree = {"w": np.zeros(4, np.float32)}
    # torn coordinated write at step 3 (one shard of two, never committed)
    cp.save_checkpoint_shard(d, tree, 3, process_index=0, process_count=2)
    # torn write NEWER than anything verified (possibly in-flight): kept
    cp.save_checkpoint_shard(d, tree, 20, process_index=0, process_count=2)
    for step in (5, 8):
        for r in range(2):
            cp.save_checkpoint_shard(d, tree, step, process_index=r,
                                     process_count=2)
        cp.commit_checkpoint(d, step, 2)
    pruned = cp.gc_checkpoints(d, keep=2)
    left = sorted(int(x.split("_")[1]) for x in os.listdir(d)
                  if x.startswith("step_"))
    assert 3 in pruned
    assert left == [5, 8, 20]  # both verified kept; newer torn dir kept


def test_single_host_checkpoint_unchanged(tmp_path):
    """The legacy single-host layout keeps its exact semantics."""
    d = str(tmp_path)
    cp.save_checkpoint(d, {"w": np.arange(3, dtype=np.float32)}, step=2)
    assert cp.checkpoint_world(d, 2) is None
    assert cp.restore_host_states(d) == {}
    assert cp.latest_verified_step(d) == 2


# ---------------------------------------------------------------------------
# retention GC + verified-resume defaults
# ---------------------------------------------------------------------------


def test_gc_keeps_last_k_verified_never_newest(tmp_path):
    d = str(tmp_path)
    for step in range(1, 7):
        cp.save_checkpoint(d, {"w": np.full(2, step, np.float32)}, step=step)
    # corrupt step 3's payload (older) and step 6's (the newest completed)
    for s in (3, 6):
        payload = os.path.join(d, f"step_{s:010d}", "state.npz")
        with open(payload, "ab") as f:
            f.write(b"xx")
    pruned = cp.gc_checkpoints(d, keep=2)
    left = sorted(int(x.split("_")[1]) for x in os.listdir(d)
                  if x.startswith("step_"))
    # verified = [1,2,4,5]; keep last 2 verified {4,5}; 6 is newer than the
    # newest verified step -> untouched; 1,2,3 pruned
    assert pruned == [1, 2, 3]
    assert left == [4, 5, 6]
    assert cp.latest_verified_step(d) == 5


def test_save_checkpoint_keep_param(tmp_path):
    d = str(tmp_path)
    for step in range(4):
        cp.save_checkpoint(d, {"w": np.zeros(2, np.float32)}, step=step,
                           keep=2)
    left = sorted(x for x in os.listdir(d) if x.startswith("step_"))
    assert left == ["step_0000000002", "step_0000000003"]


def test_checkpoint_sharding_defaults_to_verified(tmp_path):
    d = str(tmp_path)
    cp.save_checkpoint(d, {"w": np.zeros(2, np.float32)}, step=1,
                       sharding={"digest": "old"})
    cp.save_checkpoint(d, {"w": np.zeros(2, np.float32)}, step=2,
                       sharding={"digest": "new"})
    payload = os.path.join(d, "step_0000000002", "state.npz")
    with open(payload, "ab") as f:
        f.write(b"xx")  # torn newest
    # the torn step's rule table must not pair with the verified params
    assert cp.checkpoint_sharding(d)["digest"] == "old"


def test_torn_newest_does_not_wedge_supervisor_resume(tmp_path):
    """Kill-mid-write recovery: the supervisor's resume point demotes past
    a torn final checkpoint instead of crash-looping on CheckpointCorrupt."""
    from synapseml_tpu.continual.supervisor import TrainSupervisor

    d = str(tmp_path)
    cp.save_checkpoint(d, {"w": np.zeros(2, np.float32)}, step=4)
    cp.save_checkpoint(d, {"w": np.ones(2, np.float32)}, step=8)
    with open(os.path.join(d, "step_0000000008", "state.npz"), "ab") as f:
        f.write(b"xx")
    sup = TrainSupervisor(d, max_restarts=1)
    assert sup.checkpoint_progress() == 4
    tree = cp.restore_checkpoint(d)  # default: latest VERIFIED
    np.testing.assert_array_equal(tree["w"], np.zeros(2, np.float32))


# ---------------------------------------------------------------------------
# elastic plan: N→M stream redistribution
# ---------------------------------------------------------------------------


def _stream_rows(set_):
    out = []
    try:
        while True:
            b = next(set_)
            out.extend(np.asarray(b["idx"])[b["_valid"] > 0]
                       .astype(int).tolist())
    except StopIteration:
        pass
    return out


@pytest.mark.parametrize("new_world", [1, 2, 3, 4])
def test_elastic_plan_n_to_m_zero_replay_zero_skip(tmp_path, new_world):
    """4 virtual streams consumed partway, then resumed on M hosts: the
    union of post-resume rows equals exactly the rows a 4-host
    continuation would emit — zero replayed, zero skipped, any M."""
    from synapseml_tpu.data import ElasticPlan, ElasticStreamSet, MemorySource

    rows = np.arange(240, dtype=np.int64)
    src = MemorySource({"idx": rows}, shard_rows=20)
    kw = dict(shuffle_rows="none", epochs=2, drop_remainder=False)

    plan = ElasticPlan.fresh(4, seed=5)
    host_states, consumed_before = {}, []
    for r in range(4):
        s = ElasticStreamSet(src, 8, plan, r, 4, **kw)
        for _ in range(3):
            b = next(s)
            consumed_before.extend(
                np.asarray(b["idx"])[b["_valid"] > 0].astype(int).tolist())
        host_states[r] = {"data_iter": s.state_for_batch(3)}
        s.close()

    # reference: uninterrupted 4-host continuation
    ref_plan = ElasticPlan.from_host_states(4, host_states)
    ref = []
    for r in range(4):
        s = ElasticStreamSet(src, 8, ref_plan, r, 4, **kw)
        ref.extend(_stream_rows(s))
        s.close()

    # resumed: M survivors multiplexing the same 4 streams
    got = []
    res_plan = ElasticPlan.from_host_states(4, host_states)
    for j in range(new_world):
        s = ElasticStreamSet(src, 8, res_plan, j, new_world, **kw)
        got.extend(_stream_rows(s))
        s.close()

    assert sorted(got) == sorted(ref)
    # the whole run consumed exactly 2 epochs, each row exactly twice
    assert sorted(consumed_before + got) == sorted(rows.tolist() * 2)


def test_elastic_mid_cycle_resume_keeps_interleaving(tmp_path):
    """A host serving 2+ streams checkpointed mid-cycle (streams unevenly
    consumed) must continue the exact interleaved batch ORDER an
    uninterrupted run would produce — stream choice is a function of the
    checkpointed cursors, not a host-local cycle position."""
    from synapseml_tpu.data import ElasticPlan, ElasticStreamSet, MemorySource

    rows = np.arange(160, dtype=np.int64)
    src = MemorySource({"idx": rows}, shard_rows=16)
    kw = dict(shuffle_rows="none", epochs=1, drop_remainder=False)

    def batches(set_, n=None):
        out, k = [], 0
        try:
            while n is None or k < n:
                b = next(set_)
                out.append(tuple(np.asarray(b["idx"])[b["_valid"] > 0]
                                 .astype(int).tolist()))
                k += 1
        except StopIteration:
            pass
        return out

    # uninterrupted: 2 virtual streams on ONE host, full ordered sequence
    ref_set = ElasticStreamSet(src, 8, ElasticPlan.fresh(2, seed=9),
                               0, 1, **kw)
    ref = batches(ref_set)
    ref_set.close()

    # interrupted at an ODD batch count (mid round-robin cycle)
    s1 = ElasticStreamSet(src, 8, ElasticPlan.fresh(2, seed=9), 0, 1, **kw)
    head = batches(s1, n=5)
    snap = {0: {"data_iter": s1.state_for_batch(5)}}
    s1.close()
    s2 = ElasticStreamSet(src, 8, ElasticPlan.from_host_states(2, snap),
                          0, 1, **kw)
    tail = batches(s2)
    s2.close()
    assert head + tail == ref  # exact ORDER, not just the row multiset


def test_elastic_uneven_streams_drain_completely():
    """Streams need not exhaust together (odd shard counts): a dry stream
    leaves the rotation and the survivors' union still covers every row —
    ending on the FIRST StopIteration would silently drop the longer
    streams' tail batches."""
    from synapseml_tpu.data import ElasticPlan, ElasticStreamSet, MemorySource

    rows = np.arange(140, dtype=np.int64)  # 7 shards over 2 streams: 4 vs 3
    src = MemorySource({"idx": rows}, shard_rows=20)
    kw = dict(shuffle_rows="none", epochs=1, drop_remainder=False)

    for world in (1, 2):
        got = []
        for r in range(world):
            s = ElasticStreamSet(src, 8, ElasticPlan.fresh(2, seed=3),
                                 r, world, **kw)
            got.extend(_stream_rows(s))
            s.close()
        assert sorted(got) == rows.tolist(), (
            f"world={world}: {len(got)} of {len(rows)} rows emitted")


def test_elastic_plan_missing_stream_raises():
    from synapseml_tpu.data import ElasticPlan, IteratorState

    with pytest.raises(ValueError, match="missing cursors"):
        ElasticPlan.from_host_states(3, {
            0: {"data_iter": {"0": IteratorState(seed=1).to_tree()}},
            1: {"data_iter": {"1": IteratorState(seed=1).to_tree()}}})
    plan = ElasticPlan.fresh(2, seed=0)
    assert plan.assignment(3) == [[0], [1], []]  # hosts beyond N idle

    # cursors BEYOND orig_world = the caller undercounted the frozen
    # world; silently dropping them would skip those streams' rows forever
    with pytest.raises(ValueError, match="undercounts"):
        ElasticPlan.from_host_states(1, {
            0: {"data_iter": {"0": IteratorState(seed=1).to_tree(),
                              "1": IteratorState(seed=1).to_tree()}}})


# ---------------------------------------------------------------------------
# gang fault-plane hooks (seeded-deterministic chaos)
# ---------------------------------------------------------------------------


def test_fault_plan_gang_drop_delay_and_kill_at_step():
    plan_specs = [
        FaultSpec("drop", planes=("gang",), match="rank=1", times=2),
        FaultSpec("crash", planes=("gang",), match="step=9", times=1),
    ]
    with inject_faults(plan_specs, seed=11) as plan:
        drops = [plan.on_gang(f"beat:rank=1:step={s}") for s in range(3)]
        assert drops == [True, True, False]  # times=2, deterministic
        assert plan.on_gang("beat:rank=0:step=5") is False
        with pytest.raises(ConnectionResetError):
            plan.on_gang("beat:rank=0:step=9")  # kill-worker-at-step N
    assert [k for _, k, _ in plan.injected] == ["drop", "drop", "crash"]
    assert all(p == "gang" for p, _, _ in plan.injected)


# ---------------------------------------------------------------------------
# in-process gang lifecycle (threads over socketpairs)
# ---------------------------------------------------------------------------


class _TinyGangHarness:
    """World-of-N gang whose 'training' is a fake step loop calling the
    exact seams the real fit loop uses (heartbeat / check / checkpoint /
    ack), so protocol behavior tests need no jax at all."""

    def __init__(self, world, ckdir=None, **coord_kw):
        self.pairs = [socket.socketpair() for _ in range(world)]
        kw = dict(beat_timeout_s=30.0, grace_s=10.0, poll_s=0.02)
        kw.update(coord_kw)
        self.coord = GangCoordinator(
            {r: self.pairs[r][0] for r in range(world)},
            checkpoint_dir=ckdir, **kw).start()
        self.workers = [GangWorker(self.pairs[r][1], r, world,
                                   grace_s=10.0).start()
                        for r in range(world)]

    def close(self):
        self.coord.close()


def test_gang_heartbeats_straggler_gauges_and_eof_failure():
    from synapseml_tpu.core import observability as obs
    from synapseml_tpu.core.resilience import resilience_measures

    h = _TinyGangHarness(2)
    try:
        for step in range(1, 4):
            for w in h.workers:
                w.heartbeat(step)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            st = h.coord.status()
            if all(st[r]["last_step"] == 3 for r in (0, 1)):
                break
            time.sleep(0.02)
        assert all(h.coord.status()[r]["last_step"] == 3 for r in (0, 1))
        snap = obs.get_registry().snapshot()
        assert any(k.startswith("synapseml_train_gang_step_latency_ms")
                   for k in snap)
        assert any(k.startswith("synapseml_train_gang_beats_total")
                   for k in snap)
        before = resilience_measures("parallel").to_dict().get(
            "gang_abort_count", 0)
        # rank 1's process "dies": its socket drops without a bye
        # (shutdown = what the kernel does to every fd of a SIGKILLed
        # process; a bare close() would be held open by makefile refs)
        h.workers[1].sock.shutdown(socket.SHUT_RDWR)
        h.workers[1].sock.close()
        assert h.coord.wait_failure(5.0) is not None
        assert h.coord.failure[0] == 1
        # survivor sees the resize verdict at its next boundary
        deadline = time.monotonic() + 5
        v = None
        while time.monotonic() < deadline and v != "resize":
            v = h.workers[0].check(4)
            time.sleep(0.02)
        assert v == "resize"
        after = resilience_measures("parallel").to_dict()["gang_abort_count"]
        assert after == before + 1
    finally:
        h.close()


def test_gang_missed_beats_trigger_resize():
    """No traffic at all (beats dropped, socket alive): the deadline-based
    detector — not EOF — must mark the member dead."""
    from synapseml_tpu.core.resilience import resilience_measures

    before = resilience_measures("parallel").to_dict().get(
        "beats_missed_count", 0)
    h = _TinyGangHarness(2, beat_timeout_s=0.3)
    try:
        t0 = time.monotonic()
        while h.coord.failure is None and time.monotonic() - t0 < 5:
            h.workers[0].heartbeat(1)  # only rank 0 beats
            time.sleep(0.05)
        assert h.coord.failure is not None
        after = resilience_measures("parallel").to_dict()[
            "beats_missed_count"]
        assert after >= before + 1
    finally:
        h.close()


def test_gang_preempt_dance_commits_at_sync_step(tmp_path):
    """The full emergency dance: preempt notice → abort_and_checkpoint →
    ready/sync(max) → per-rank shard writes → ack → driver COMMIT →
    committed broadcast. Ranks at DIFFERENT steps synchronize on the max."""
    d = str(tmp_path)
    h = _TinyGangHarness(2, ckdir=d, grace_s=10.0)
    steps = {0: 5, 1: 7}  # rank 1 is ahead
    results = {}

    def member(rank):
        w = h.workers[rank]
        step = steps[rank]
        if rank == 0:
            w.preempt()  # SIGTERM hook body
        while True:
            w.heartbeat(step)
            v = w.check(step)
            if v == "resize":
                results[rank] = ("resize", step)
                return
            if isinstance(v, tuple):
                sync = v[1]
                while step < sync:  # train forward to the sync step
                    step += 1
                cp.save_checkpoint_shard(
                    d, {"w": np.full(2, rank, np.float32)}, step,
                    process_index=rank, process_count=2,
                    host_tree={"data_iter": {str(rank): {"s": np.int64(1)}}})
                ok = w.ack_and_wait_commit(step)
                results[rank] = ("preempted" if ok else "resize", step)
                return
            time.sleep(0.02)

    ts = [threading.Thread(target=member, args=(r,)) for r in (0, 1)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=20)
    try:
        assert results == {0: ("preempted", 7), 1: ("preempted", 7)}
        assert h.coord.preempt_commit_step == 7
        assert cp.latest_verified_step(d) == 7
        assert sorted(cp.restore_host_states(d)) == [0, 1]
    finally:
        h.close()


def test_trainer_fit_gang_abort_and_preempt(tmp_path, mesh_dp8):
    """Trainer.fit(gang=...) honors both verdicts: resize raises
    GangAborted mid-run; a sync verdict forces the emergency checkpoint
    and raises Preempted after the commit handshake."""
    import flax.linen as nn

    from synapseml_tpu.models.trainer import Trainer, TrainerConfig

    class FakeGang:
        def __init__(self, verdict_at, verdict):
            self.verdict_at = verdict_at
            self.verdict = verdict
            self.beats = []
            self.acked = None

        def heartbeat(self, step):
            self.beats.append(int(step))

        def check(self, step):
            if step >= self.verdict_at:
                if self.verdict == "resize":
                    return "resize"
                return ("sync", step + 2)
            return None

        def ack_and_wait_commit(self, step):
            self.acked = int(step)
            return True

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(2)(x)

    rs = np.random.default_rng(0)
    batch = {"x": rs.normal(size=(8, 4)).astype(np.float32),
             "labels": rs.integers(0, 2, 8).astype(np.int32)}

    def batches():
        while True:
            yield dict(batch)

    tr = Trainer(MLP(), mesh_dp8, TrainerConfig(total_steps=50))
    state = tr.init_state(batch)
    g = FakeGang(3, "resize")
    with pytest.raises(GangAborted):
        tr.fit(state, batches(), max_steps=50, gang=g)
    assert g.beats[0] == 0  # pre-compile liveness beat

    tr2 = Trainer(MLP(), mesh_dp8, TrainerConfig(total_steps=50))
    state2 = tr2.init_state(batch)
    ck = cp.AsyncCheckpointer(str(tmp_path), process_index=0,
                              process_count=1, coordinated=True)
    g2 = FakeGang(3, "sync")
    with pytest.raises(Preempted) as ei:
        tr2.fit(state2, batches(), max_steps=50, gang=g2,
                checkpointer=ck, checkpoint_every=100)
    ck.close()
    assert ei.value.step == 5 and g2.acked == 5  # trained to sync step
    # phase-1 shard landed; the DRIVER would commit it
    assert cp.commit_checkpoint(str(tmp_path), 5, 1) is not None
    assert cp.latest_verified_step(str(tmp_path)) == 5


def test_supervisor_preempt_budget(tmp_path):
    from synapseml_tpu.continual.supervisor import TrainSupervisor

    calls = []

    def attempt_fn(attempt):
        calls.append(attempt.index)
        if len(calls) == 1:
            raise Preempted(12)
        if len(calls) == 2:
            raise GangAborted("resize")
        return "ok"

    sup = TrainSupervisor(str(tmp_path), max_restarts=0, max_preempts=4)
    assert sup.run(attempt_fn) == "ok"
    assert sup.preempts == 2 and sup.restarts == 0  # no crash budget spent

    sup2 = TrainSupervisor(str(tmp_path), max_restarts=0, max_preempts=1)
    calls.clear()
    with pytest.raises(GangAborted):
        sup2.run(attempt_fn)  # budget of 1 exhausted by the 2nd preempt


# ---------------------------------------------------------------------------
# chaos: real multiprocess gangs (the acceptance proofs)
# ---------------------------------------------------------------------------

GANG_WORKER = textwrap.dedent("""
    import json, sys, time

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import flax.linen as nn

    from synapseml_tpu.parallel.gang import run_gang_member
    from synapseml_tpu.models.trainer import Trainer, TrainerConfig
    from synapseml_tpu.parallel.mesh import MeshConfig, create_mesh
    from synapseml_tpu.data.source import MemorySource

    addr, part = sys.argv[1], int(sys.argv[2])
    ckdir, logp = sys.argv[3], sys.argv[4]
    total_steps, step_ms = int(sys.argv[5]), float(sys.argv[6])

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(2)(nn.relu(nn.Dense(16)(x)))

    N = 4096
    rs = np.random.default_rng(7)
    X = rs.normal(size=(N, 4)).astype(np.float32)
    data = {"x": X, "labels": (X[:, 0] > 0).astype(np.int32)}
    src = MemorySource(data, shard_rows=64)

    log = open(logp, "a")
    rank_box = []

    def trainer_fn(info):
        rank_box.append(info["rank"])
        mesh = create_mesh(MeshConfig(data=1))
        return Trainer(MLP(), mesh, TrainerConfig(
            total_steps=total_steps, learning_rate=1e-2))

    def cb(i, metrics):
        log.write(json.dumps({"rank": rank_box[0],
                              "loss": float(metrics["loss"])}) + "\\n")
        log.flush()
        if step_ms:
            time.sleep(step_ms / 1000.0)

    def on_exit(kind, payload):
        rank = rank_box[0]
        if kind == "done":
            log.write(json.dumps({"rank": rank,
                                  "final_step": int(payload.step)}) + "\\n")
        elif kind == "preempted":
            log.write(json.dumps({"rank": rank,
                                  "preempted_at": payload.step}) + "\\n")
        else:
            log.write(json.dumps({"rank": rank, "resized": True}) + "\\n")

    code = run_gang_member(addr, part, trainer_fn=trainer_fn, source=src,
                           checkpoint_dir=ckdir, total_steps=total_steps,
                           batch_size=16, seed=3, checkpoint_every=4,
                           grace_s=60.0, on_exit=on_exit, epochs=None,
                           shuffle_rows="none", callback=cb)
    log.close()
    sys.exit(code)
""")


def _launch_gang(tmp_path, tag, world, ckdir, total_steps, step_ms,
                 coord_kw=None):
    """Start a real OS-process gang; returns (procs, coord, driver,
    log_paths)."""
    import pathlib

    from synapseml_tpu.parallel.gang import launch_gang_processes

    script = tmp_path / f"worker_{tag}.py"
    script.write_text(GANG_WORKER)
    repo_root = str(pathlib.Path(__file__).resolve().parent.parent)
    env = {"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": repo_root, "HOME": "/root",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    logs = [str(tmp_path / f"log_{tag}_{p}.jsonl") for p in range(world)]
    kw = dict(beat_timeout_s=90.0, grace_s=60.0, poll_s=0.05)
    kw.update(coord_kw or {})
    procs, coord, driver = launch_gang_processes(
        str(script), world, checkpoint_dir=ckdir,
        worker_args_fn=lambda p, addr: [
            addr, str(p), ckdir, logs[p], str(total_steps), str(step_ms)],
        env=env, coordinator_kw=kw)
    return procs, coord, driver, logs


def _finish(procs, coord, timeout_s=120, wait_commit_step=None):
    from synapseml_tpu.parallel.gang import finish_gang_processes

    return finish_gang_processes(procs, coord, timeout_s=timeout_s,
                                 wait_commit_step=wait_commit_step)


def _losses(log_path):
    out = []
    with open(log_path) as f:
        for line in f:
            rec = json.loads(line)
            if "loss" in rec:
                out.append(rec["loss"])
    return out


@pytest.mark.chaos(timeout_s=300)
def test_sigkill_one_of_four_resumes_on_three(tmp_path):
    """The ISSUE-15 acceptance chaos proof: kill 1 of 4 multiprocess hosts
    mid-run → survivors exit EXIT_RESIZE → the run resumes on 3 hosts from
    the last verified commit → f32 loss parity with an uninterrupted
    3-host run started from the identical checkpoint and fed the identical
    post-resume batch stream."""
    ckdir = str(tmp_path / "ck")
    os.makedirs(ckdir)
    total = 24

    # phase A: 4 hosts, SIGKILL rank 2 after the step-8 commit lands
    procs, coord, driver, _ = _launch_gang(
        tmp_path, "a", 4, ckdir, total, step_ms=150)
    committed = coord.wait_commit(step=8, timeout_s=120)
    assert committed == 8, f"no step-8 commit: {coord.events()}"
    procs[2].send_signal(signal.SIGKILL)
    failure = coord.wait_failure(60.0)
    assert failure is not None and failure[0] == 2
    outs, codes = _finish(procs, coord)
    assert codes[2] == -signal.SIGKILL
    assert all(c == EXIT_RESIZE for i, c in enumerate(codes) if i != 2), \
        (codes, outs)

    resume_step = cp.latest_verified_step(ckdir)
    assert resume_step is not None and resume_step >= 8
    assert cp.checkpoint_world(ckdir, resume_step) == 4
    refdir = str(tmp_path / "ref")
    shutil.copytree(ckdir, refdir)

    # phase B: resume on 3 survivors, run to completion
    procs, coord, driver, logs_b = _launch_gang(
        tmp_path, "b", 3, ckdir, total, step_ms=0)
    outs, codes = _finish(procs, coord, wait_commit_step=total)
    assert codes == [0, 0, 0], (codes, outs)
    assert cp.latest_verified_step(ckdir) == total

    # phase C: uninterrupted 3-host reference from the SAME checkpoint
    procs, coord, driver, logs_c = _launch_gang(
        tmp_path, "c", 3, refdir, total, step_ms=0)
    outs, codes = _finish(procs, coord, wait_commit_step=total)
    assert codes == [0, 0, 0], (codes, outs)

    # f32 loss parity, per rank, across the whole post-resume run
    for lb, lc in zip(logs_b, logs_c):
        assert _losses(lb) == _losses(lc)
    # final states byte-identical (params AND optimizer state)
    tb = cp.restore_checkpoint(ckdir, total)
    tc = cp.restore_checkpoint(refdir, total)
    import jax

    for b, c in zip(jax.tree.leaves(tb), jax.tree.leaves(tc)):
        np.testing.assert_array_equal(np.asarray(b), np.asarray(c))
    # zero replayed / zero skipped, by cursor accounting: before the kill
    # all 4 streams advanced to resume_step; post-resume the 3 survivors
    # each ran (total - resume_step) steps, every step consuming exactly
    # one batch from exactly one virtual stream — so the stream cursors
    # must sum to 4*resume_step + 3*(total - resume_step), with no stream
    # ever moving backwards (replay) or jumping (skip)
    hosts = cp.restore_host_states(ckdir, total)
    cursors = {}
    for tree in hosts.values():
        cursors.update(tree["data_iter"])
    assert sorted(int(k) for k in cursors) == [0, 1, 2, 3]
    assert all(int(np.asarray(c["batches_emitted"])) >= resume_step
               for c in cursors.values())
    assert sum(int(np.asarray(c["batches_emitted"]))
               for c in cursors.values()) \
        == 4 * resume_step + 3 * (total - resume_step)


@pytest.mark.chaos(timeout_s=300)
def test_sigterm_grace_window_emergency_checkpoint(tmp_path):
    """Preemption notice: SIGTERM one of two workers → the gang runs the
    emergency-checkpoint dance inside the grace window → BOTH exit
    EXIT_PREEMPTED with a committed step newer than the last periodic one
    → a relaunch resumes from it and completes."""
    ckdir = str(tmp_path / "ck")
    os.makedirs(ckdir)
    total = 400  # far more than will run: the preempt ends the run

    procs, coord, driver, _ = _launch_gang(
        tmp_path, "t", 2, ckdir, total, step_ms=100)
    periodic = coord.wait_commit(step=4, timeout_s=120)
    assert periodic == 4, f"no periodic commit: {coord.events()}"
    procs[1].send_signal(signal.SIGTERM)
    outs, codes = _finish(procs, coord, timeout_s=150)
    assert codes == [EXIT_PREEMPTED, EXIT_PREEMPTED], (codes, outs)
    emergency = coord.preempt_commit_step
    assert emergency is not None and emergency > periodic
    assert cp.latest_verified_step(ckdir) == emergency
    assert cp.checkpoint_world(ckdir, emergency) == 2

    # resume both workers; finish a short remainder
    finish_at = emergency + 6
    procs, coord, driver, logs = _launch_gang(
        tmp_path, "r", 2, ckdir, finish_at, step_ms=0)
    outs, codes = _finish(procs, coord, wait_commit_step=finish_at)
    assert codes == [0, 0], (codes, outs)
    assert cp.latest_verified_step(ckdir) == finish_at


@pytest.mark.chaos(timeout_s=180)
def test_chatty_worker_stdout_does_not_stall_gang(tmp_path):
    """A worker writing far more than the OS pipe buffer to stdout must
    not block mid-step: the launcher drains each pipe from launch, so
    heartbeats keep flowing and the gang completes instead of being
    resized as dead."""
    import textwrap as _tw  # noqa: F401  (GANG_WORKER already dedented)

    ckdir = str(tmp_path / "ck")
    os.makedirs(ckdir)
    total = 6
    chatty = GANG_WORKER.replace(
        "def cb(i, metrics):",
        "def cb(i, metrics):\n    print('#' * 65536, flush=True)")
    assert chatty != GANG_WORKER  # the anchor must exist
    script = tmp_path / "worker_chatty.py"
    script.write_text(chatty)

    import pathlib

    from synapseml_tpu.parallel.gang import launch_gang_processes

    repo_root = str(pathlib.Path(__file__).resolve().parent.parent)
    env = {"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": repo_root, "HOME": "/root",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    logs = [str(tmp_path / f"log_chatty_{p}.jsonl") for p in range(2)]
    procs, coord, driver = launch_gang_processes(
        str(script), 2, checkpoint_dir=ckdir,
        worker_args_fn=lambda p, addr: [
            addr, str(p), ckdir, logs[p], str(total), "0"],
        env=env, coordinator_kw=dict(beat_timeout_s=90.0, grace_s=60.0,
                                     poll_s=0.05))
    outs, codes = _finish(procs, coord, wait_commit_step=total)
    assert codes == [0, 0], (codes, [o[-500:] for o in outs])
    # each worker printed total * 64KiB >> the ~64KiB pipe capacity; the
    # drained output made it back to the launcher intact
    assert all(len(o) >= total * 65536 for o in outs)
    assert cp.latest_verified_step(ckdir) == total
