"""Streaming data plane: sharded sources, loader determinism, resume.

The determinism suite the plane's resume guarantee rests on:
same seed ⇒ identical batch stream across runs AND across a save/restore
mid-epoch; different host ranks ⇒ disjoint shard coverage whose union is
exactly the dataset, once per epoch.
"""

import json
import os

import numpy as np
import pytest

pytestmark = pytest.mark.data

from synapseml_tpu.core.faults import FaultSpec, inject_faults
from synapseml_tpu.core.resilience import (RetryPolicy, reset_resilience_measures,
                                           resilience_measures)
from synapseml_tpu.data import (DataLoader, IteratorState, MemorySource,
                                ShardedSource)
from synapseml_tpu.data.state import row_order, shard_order


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

N_ROWS, N_SHARDS, ROWS_PER_SHARD = 120, 4, 30


def _write_jsonl(tmp_path, n_files=N_SHARDS, rows_per=ROWS_PER_SHARD):
    rs = np.random.default_rng(0)
    X = rs.normal(size=(n_files * rows_per, 4)).astype(np.float32)
    for i in range(n_files):
        with open(tmp_path / f"part-{i:03d}.jsonl", "w") as f:
            for j in range(rows_per):
                rid = i * rows_per + j
                f.write(json.dumps({"x": X[rid].tolist(),
                                    "labels": int(rid % 3),
                                    "rid": rid}) + "\n")
    return X


def _rids(batch):
    return np.asarray(batch["rid"])[np.asarray(batch["_valid"]) > 0].tolist()


def _stream(src, seed=7, epochs=2, batch_size=16, **kw):
    return [_rids(b) for b in DataLoader(src, batch_size, seed=seed,
                                         epochs=epochs, host_index=0,
                                         host_count=1, **kw)]


# ---------------------------------------------------------------------------
# sources
# ---------------------------------------------------------------------------

def test_jsonl_byte_range_shards_cover_every_row_exactly_once(tmp_path):
    _write_jsonl(tmp_path)
    for shard_bytes in (64, 500, 1 << 30):
        src = ShardedSource.jsonl(str(tmp_path / "*.jsonl"),
                                  shard_bytes=shard_bytes)
        rids = sorted(r for _, cols in src.iter_shards() if cols
                      for r in np.asarray(cols["rid"]))
        assert rids == list(range(N_ROWS)), f"shard_bytes={shard_bytes}"
    assert ShardedSource.jsonl(str(tmp_path / "*.jsonl"),
                               shard_bytes=64).num_shards > N_SHARDS


def test_csv_byte_range_shards_cover_every_row_exactly_once(tmp_path):
    p = tmp_path / "t.csv"
    with open(p, "w") as f:
        f.write("a,b\n")
        for i in range(57):
            f.write(f"{i},{i * 2}\n")
    for shard_bytes in (32, 100, 1 << 30):
        src = ShardedSource.csv(str(p), shard_bytes=shard_bytes)
        vals = sorted(v for _, cols in src.iter_shards() if cols
                      for v in np.asarray(cols["a"]))
        assert vals == list(range(57)), f"shard_bytes={shard_bytes}"


def test_csv_quoted_multiline_field_across_boundary_fails_loud(tmp_path):
    """Byte-range CSV sharding assumes one record per line; a quoted field
    with an embedded newline straddling a shard boundary must raise a clear
    error, never feed a torn fragment into training as a spurious row."""
    p = tmp_path / "q.csv"
    with open(p, "w") as f:
        f.write("a,b\n")
        for i in range(6):
            f.write(f'{i},"line one\nline two number {i}"\n')
    with pytest.raises(ValueError, match="quoted multi-line"):
        for _ in ShardedSource.csv(str(p), shard_bytes=20).iter_shards():
            pass
    # one shard per file parses it fine (whole records stay together)
    src = ShardedSource.csv(str(p), shard_bytes=1 << 20)
    (_, cols), = src.iter_shards()
    assert len(cols["a"]) == 6
    # a bare literal quote in an unquoted field is NOT a torn record when
    # the shard covers the whole file — it must parse like the eager path
    q = tmp_path / "bare.csv"
    q.write_text('h,w\n5\'10",170\n6\'1",190\n')
    (_, cols2), = ShardedSource.csv(str(q), shard_bytes=1 << 20).iter_shards()
    assert len(cols2["h"]) == 2


def test_streamed_gbdt_missing_label_column_is_actionable(tmp_path):
    from synapseml_tpu.gbdt import train_booster_from_source

    with open(tmp_path / "g.jsonl", "w") as f:
        for i in range(20):
            f.write(json.dumps({"feat": [float(i)], "y": float(i)}) + "\n")
    src = ShardedSource.jsonl(str(tmp_path / "g.jsonl"))
    with pytest.raises(ValueError, match="label_col"):
        train_booster_from_source(src, num_iterations=2)


def test_npy_row_range_shards(tmp_path):
    p = tmp_path / "x.npy"
    np.save(p, np.arange(40, dtype=np.float32).reshape(20, 2))
    src = ShardedSource.npy(str(p), column="x", shard_rows=6)
    assert src.num_shards == 4 and src.total_rows() == 20
    got = np.concatenate([c["x"] for _, c in src.iter_shards()])
    assert np.array_equal(got, np.arange(40).reshape(20, 2))


def test_image_dir_source(tmp_path):
    PIL = pytest.importorskip("PIL.Image")
    for i in range(5):
        PIL.new("RGB", (4 + i, 3), color=(i, 0, 0)).save(tmp_path / f"{i}.png")
    src = ShardedSource.image_dir(str(tmp_path), shard_files=2)
    assert src.num_shards == 3
    rows = [dict(zip(cols, vals)) for _, cols in src.iter_shards()
            for vals in zip(*cols.values())]
    assert len(rows) == 5
    assert {r["width"] for r in rows} == {4, 5, 6, 7, 8}


def test_memory_source_matches_dataframe_partitions():
    from synapseml_tpu.core import DataFrame

    df = DataFrame.from_dict({"a": np.arange(20)}, num_partitions=4)
    src = MemorySource(df)
    assert src.num_shards == 4
    assert sorted(v for _, c in src.iter_shards()
                  for v in np.asarray(c["a"])) == list(range(20))
    resharded = MemorySource(df, shard_rows=7)
    assert resharded.num_shards == 3  # 7 + 7 + 6


# ---------------------------------------------------------------------------
# determinism suite
# ---------------------------------------------------------------------------

def test_same_seed_identical_stream_across_runs(tmp_path):
    _write_jsonl(tmp_path)
    src = ShardedSource.jsonl(str(tmp_path / "*.jsonl"))
    a = _stream(src, seed=7)
    b = _stream(ShardedSource.jsonl(str(tmp_path / "*.jsonl")), seed=7)
    assert a == b and len(a) == (N_ROWS // 16) * 2
    c = _stream(src, seed=8)
    assert a != c  # a different seed must reshuffle


def test_epochs_reshuffle_but_cover_identically(tmp_path):
    _write_jsonl(tmp_path)
    src = ShardedSource.jsonl(str(tmp_path / "*.jsonl"))
    batches = [_rids(b) for b in DataLoader(src, 30, seed=3, epochs=2,
                                            drop_remainder=False,
                                            host_index=0, host_count=1)]
    per_epoch = len(batches) // 2
    e0 = sorted(r for b in batches[:per_epoch] for r in b)
    e1 = sorted(r for b in batches[per_epoch:] for r in b)
    assert e0 == e1 == list(range(N_ROWS))
    assert batches[:per_epoch] != batches[per_epoch:]  # re-shuffled


def test_host_ranks_disjoint_union_is_exactly_the_dataset(tmp_path):
    _write_jsonl(tmp_path)
    for host_count in (2, 4):
        per_host = []
        for h in range(host_count):
            src = ShardedSource.jsonl(str(tmp_path / "*.jsonl"))
            rows = [r for b in DataLoader(src, 16, seed=11, epochs=1,
                                          drop_remainder=False, host_index=h,
                                          host_count=host_count)
                    for r in _rids(b)]
            per_host.append(rows)
        flat = [r for rows in per_host for r in rows]
        assert len(flat) == len(set(flat)) == N_ROWS  # disjoint + complete
        assert sorted(flat) == list(range(N_ROWS))


def test_resume_mid_epoch_is_bit_identical(tmp_path):
    _write_jsonl(tmp_path)

    def fresh():
        return ShardedSource.jsonl(str(tmp_path / "*.jsonl"))

    full = _stream(fresh(), seed=5, epochs=3)
    for cut in (2, 5, 9):  # mid-first-epoch, boundary-ish, mid-second-epoch
        ld = DataLoader(fresh(), 16, seed=5, epochs=3, host_index=0,
                        host_count=1)
        it = iter(ld)
        for _ in range(cut):
            next(it)
        snap = ld.state_for_batch(cut)
        assert snap is not None
        ld.close()
        rest = [_rids(b) for b in DataLoader(fresh(), 16, seed=5, epochs=3,
                                             host_index=0, host_count=1,
                                             state=snap)]
        assert rest == full[cut:], f"divergence resuming after batch {cut}"


def test_resume_state_round_trips_through_pytree_serialization(tmp_path):
    from synapseml_tpu.core import serialization

    st = IteratorState(epoch=2, rows_emitted=48, batches_emitted=17, seed=9,
                       shard_counts=np.array([30, 30, -1, 30], np.int64))
    serialization.save_pytree(st.to_tree(), str(tmp_path / "it"))
    restored = IteratorState.from_tree(
        serialization.load_pytree(str(tmp_path / "it")))
    assert (restored.epoch, restored.rows_emitted, restored.batches_emitted,
            restored.seed) == (2, 48, 17, 9)
    assert np.array_equal(restored.shard_counts, st.shard_counts)


def test_loader_rejects_mismatched_resume_state(tmp_path):
    _write_jsonl(tmp_path)
    src = ShardedSource.jsonl(str(tmp_path / "*.jsonl"))
    bad_layout = IteratorState(seed=7, shard_counts=np.full(99, -1, np.int64))
    with pytest.raises(ValueError, match="shard layout"):
        DataLoader(src, 16, seed=7, state=bad_layout, host_index=0,
                   host_count=1)
    with pytest.raises(ValueError, match="seed"):
        DataLoader(src, 16, seed=8, state=IteratorState(seed=7), host_index=0,
                   host_count=1)


def test_window_shuffle_is_deterministic_bounded_permutation():
    o1 = row_order(3, 1, 2, 500, "window", 32)
    o2 = row_order(3, 1, 2, 500, "window", 32)
    assert np.array_equal(o1, o2)
    assert sorted(o1.tolist()) == list(range(500))
    # locality bound: position j can only emit rows already streamed into
    # the window — source index < j + window
    assert all(o1[j] < j + 32 for j in range(500))
    assert not np.array_equal(o1, np.arange(500))  # actually shuffles


def test_shard_order_and_row_order_pure_functions():
    assert np.array_equal(shard_order(1, 4, 10), shard_order(1, 4, 10))
    assert not np.array_equal(shard_order(1, 4, 10), shard_order(1, 5, 10))
    assert np.array_equal(row_order(1, 2, 3, 50), row_order(1, 2, 3, 50))
    assert np.array_equal(row_order(0, 0, 0, 5, "none"), np.arange(5))


# ---------------------------------------------------------------------------
# batch assembly + observability + faults
# ---------------------------------------------------------------------------

def test_tail_batches_pad_to_bucket_ladder(tmp_path):
    _write_jsonl(tmp_path)
    src = ShardedSource.jsonl(str(tmp_path / "*.jsonl"))
    batches = list(DataLoader(src, 50, seed=0, epochs=1, drop_remainder=False,
                              host_index=0, host_count=1))
    sizes = [np.asarray(b["x"]).shape[0] for b in batches]
    valid = [int(np.asarray(b["_valid"]).sum()) for b in batches]
    assert sizes[:2] == [50, 50] and valid[:2] == [50, 50]
    # 20-row tail pads to its own ladder rung (32), not the full batch
    assert sizes[2] == 32 and valid[2] == 20
    assert sum(valid) == N_ROWS


def test_loader_emits_metrics_series(tmp_path):
    from synapseml_tpu.core import observability as obs

    _write_jsonl(tmp_path)
    src = ShardedSource.jsonl(str(tmp_path / "*.jsonl"))
    ld = DataLoader(src, 16, seed=0, epochs=1, host_index=0, host_count=1)
    n_batches = sum(1 for _ in ld)
    text = obs.get_registry().exposition()
    for series in ("synapseml_data_prefetch_queue_depth",
                   "synapseml_data_batch_wait_ms",
                   "synapseml_data_shard_read_ms",
                   "synapseml_data_rows_total",
                   "synapseml_data_rows_per_sec"):
        assert series in text, series
    stats = ld.stats()
    assert stats["batches"] == n_batches
    assert stats["rows"] == n_batches * 16
    # a data.prefetch span per shard read landed in the tracer ring
    spans = [s for s in obs.get_tracer().finished_spans()
             if s.name == "data.prefetch"]
    assert len(spans) >= N_SHARDS


def test_shard_read_faults_are_retried_and_counted(tmp_path):
    _write_jsonl(tmp_path)
    reset_resilience_measures("data")
    src = ShardedSource.jsonl(
        str(tmp_path / "*.jsonl"),
        retry_policy=RetryPolicy(backoffs_ms=(1, 1, 1), jitter=False))
    with inject_faults([FaultSpec("connection_error", times=2,
                                  planes=("data",))]) as plan:
        stream = _stream(src, seed=7, epochs=1)
    assert len(stream) == N_ROWS // 16  # faults were absorbed, not dropped
    assert len(plan.injected) == 2
    assert resilience_measures("data").to_dict()["retry_count"] == 2
    assert resilience_measures("data").to_dict()["faults_injected_count"] == 2


def test_exhausted_read_retries_surface_to_the_consumer(tmp_path):
    _write_jsonl(tmp_path)
    src = ShardedSource.jsonl(
        str(tmp_path / "*.jsonl"),
        retry_policy=RetryPolicy(backoffs_ms=(1,), jitter=False))
    with inject_faults([FaultSpec("connection_error", planes=("data",))]):
        with pytest.raises(ConnectionRefusedError):
            _stream(src, seed=7, epochs=1)


def test_object_columns_fail_fast_with_column_hint(tmp_path):
    with open(tmp_path / "t.jsonl", "w") as f:
        for i in range(20):
            f.write(json.dumps({"text": f"row {i}", "rid": i}) + "\n")
    src = ShardedSource.jsonl(str(tmp_path / "t.jsonl"))
    with pytest.raises(TypeError, match="text"):
        list(DataLoader(src, 8, seed=0, epochs=1, host_index=0, host_count=1))
    # columns=[...] selects the trainable subset
    got = [r for b in DataLoader(src, 8, seed=0, epochs=1, columns=["rid"],
                                 drop_remainder=False, host_index=0,
                                 host_count=1)
           for r in _rids(b)]
    assert sorted(got) == list(range(20))


def test_starved_epoch_raises_instead_of_spinning(tmp_path):
    """batch_size larger than a host's whole epoch slice under
    drop_remainder=True must surface an error, not spin re-reading the
    dataset forever while the consumer blocks."""
    _write_jsonl(tmp_path)
    src = ShardedSource.jsonl(str(tmp_path / "*.jsonl"))
    ld = DataLoader(src, 4096, seed=0, host_index=0, host_count=1)  # epochs=None
    with pytest.raises(ValueError, match="drop_remainder"):
        next(iter(ld))
    # the non-dropping configuration still yields the short batch
    got = list(DataLoader(src, 4096, seed=0, epochs=1, drop_remainder=False,
                          host_index=0, host_count=1))
    assert len(got) == 1 and int(np.asarray(got[0]["_valid"]).sum()) == N_ROWS


def test_empty_shards_are_skipped_even_with_column_selection(tmp_path):
    _write_jsonl(tmp_path, n_files=2, rows_per=20)
    (tmp_path / "part-zzz.jsonl").write_text("")  # zero-size file is filtered
    # a shard range landing inside one long line reads zero rows
    with open(tmp_path / "part-big.jsonl", "w") as f:
        f.write(json.dumps({"x": [0.0] * 500, "labels": 0, "rid": 40}) + "\n")
    src = ShardedSource.jsonl(str(tmp_path / "part-*.jsonl"), shard_bytes=256)
    assert any(_n == 0 for _n in
               (len(next(iter(c.values()))) if c else 0
                for _, c in src.iter_shards()))
    got = [r for b in DataLoader(src, 8, seed=0, epochs=1, columns=["rid"],
                                 drop_remainder=False, host_index=0,
                                 host_count=1)
           for r in np.asarray(b["rid"])[np.asarray(b["_valid"]) > 0]]
    assert sorted(got) == list(range(41))


def test_schema_drift_across_shards_fails_fast_with_shard_named(tmp_path):
    with open(tmp_path / "a.jsonl", "w") as f:
        for i in range(10):
            f.write(json.dumps({"x": float(i), "labels": 0}) + "\n")
    with open(tmp_path / "b.jsonl", "w") as f:
        for i in range(10):
            f.write(json.dumps({"y": float(i), "labels": 0}) + "\n")
    src = ShardedSource.jsonl(str(tmp_path / "*.jsonl"))
    with pytest.raises(ValueError, match="missing column"):
        list(DataLoader(src, 4, seed=0, epochs=1, shuffle_shards=False,
                        drop_remainder=False, host_index=0, host_count=1))
    # extra keys in later shards drop; shared selection works
    got = list(DataLoader(src, 4, seed=0, epochs=1, columns=["labels"],
                          drop_remainder=False, host_index=0, host_count=1))
    assert sum(int(np.asarray(b["_valid"]).sum()) for b in got) == 20


def test_snapshot_history_is_bounded(tmp_path):
    _write_jsonl(tmp_path)
    src = ShardedSource.jsonl(str(tmp_path / "*.jsonl"))
    ld = DataLoader(src, 8, seed=0, epochs=2, host_index=0, host_count=1,
                    state_history=5)
    for _ in ld:
        assert len(ld._snapshots) <= 5
    assert ld.state_for_batch(ld.stats()["batches"]) is not None  # newest kept


def test_streamed_gbdt_rejects_schema_drift(tmp_path):
    from synapseml_tpu.gbdt import train_booster_from_source

    with open(tmp_path / "a.jsonl", "w") as f:
        for i in range(30):
            f.write(json.dumps({"f0": float(i), "label": float(i)}) + "\n")
    with open(tmp_path / "b.jsonl", "w") as f:
        for i in range(30):
            f.write(json.dumps({"f0": float(i), "f1": 1.0,
                                "label": float(i)}) + "\n")
    src = ShardedSource.jsonl(str(tmp_path / "*.jsonl"))
    with pytest.raises(ValueError, match="feature_cols"):
        train_booster_from_source(src, label_col="label", num_iterations=2)


def test_close_wakes_a_blocked_consumer(tmp_path):
    """close() must wake a consumer blocked in next() (the chunked-fit
    error path would otherwise leak a permanently blocked thread)."""
    import threading
    import time as _time

    _write_jsonl(tmp_path)
    src = ShardedSource.jsonl(str(tmp_path / "*.jsonl"))
    with inject_faults([FaultSpec("latency", latency_ms=3000,
                                  planes=("data",))]):
        ld = DataLoader(src, 16, seed=0, epochs=1, host_index=0, host_count=1)
        it = iter(ld)
        outcome = {}

        def consume():
            try:
                next(it)
                outcome["got"] = "batch"
            except StopIteration:
                outcome["got"] = "stop"

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        _time.sleep(0.2)  # consumer is now blocked on the empty queue
        ld.close()
        t.join(timeout=2)
        assert not t.is_alive() and outcome.get("got") == "stop"


def test_total_rows_from_metadata_for_row_range_kinds(tmp_path):
    np.save(tmp_path / "x.npy", np.zeros((20, 2), np.float32))
    src = ShardedSource.npy(str(tmp_path / "x.npy"), shard_rows=6)
    assert src.total_rows() == 20  # no read pass needed

    class Boom(Exception):
        pass

    def explode(shard):
        raise Boom

    src._reader = explode
    assert src.total_rows() == 20  # memoization + metadata: reader untouched


def test_read_csv_max_rows_composes_with_caller_nrows(tmp_path):
    pytest.importorskip("pandas")
    from synapseml_tpu.io.files import read_csv

    p = tmp_path / "t.csv"
    with open(p, "w") as f:
        f.write("a\n" + "\n".join(str(i) for i in range(30)) + "\n")
    assert read_csv(str(p), nrows=10).count() == 10       # passthrough intact
    assert read_csv(str(p), nrows=10, max_rows=4).count() == 4
    assert read_csv(str(p), nrows=3, max_rows=10).count() == 3


# ---------------------------------------------------------------------------
# trainer integration (the acceptance path)
# ---------------------------------------------------------------------------

class _MLP:
    """Lazy flax module factory (keeps collection errors local)."""

    def __new__(cls):
        import flax.linen as nn

        class MLP(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(3)(nn.relu(nn.Dense(16)(x)))

        return MLP()


def _trainer(total_steps):
    from synapseml_tpu.models.trainer import Trainer, TrainerConfig
    from synapseml_tpu.parallel.mesh import MeshConfig, create_mesh

    return Trainer(_MLP(), create_mesh(MeshConfig()),
                   TrainerConfig(total_steps=total_steps))


def test_fit_source_matches_fit_arrays_on_same_rows(tmp_path):
    """Multi-shard on-disk jsonl through fit_source == fit_arrays over the
    same rows with the same seed (shard-aligned layout): identical loss
    trajectory AND bit-identical final params."""
    import jax

    from synapseml_tpu.models.trainer import fit_arrays, fit_source

    X = _write_jsonl(tmp_path)
    y = np.arange(N_ROWS) % 3
    src = ShardedSource.jsonl(str(tmp_path / "*.jsonl"))
    assert src.num_shards == N_SHARDS > 1

    t1 = _trainer(14)
    s1 = fit_source(t1, src, batch_size=16, total_steps=14, seed=3,
                    columns=["x", "labels"])
    t2 = _trainer(14)
    s2 = fit_arrays(t2, {"x": X, "labels": y.astype(np.int32)},
                    batch_size=16, total_steps=14, seed=3,
                    shard_rows=ROWS_PER_SHARD)
    assert int(s1.step) == int(s2.step) == 14
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_fit_source_kill_resume_equals_uninterrupted(tmp_path):
    """Checkpoint at step 8 of 12, restore into a fresh trainer + loader,
    run the remaining 4 steps: final params bit-identical to the
    uninterrupted 12-step run."""
    import jax

    from synapseml_tpu.models.trainer import fit_source
    from synapseml_tpu.parallel.checkpoint import (AsyncCheckpointer,
                                                   restore_checkpoint)

    _write_jsonl(tmp_path)

    def fresh():
        return ShardedSource.jsonl(str(tmp_path / "*.jsonl"))

    cols = ["x", "labels"]
    t_full = _trainer(12)
    full = fit_source(t_full, fresh(), batch_size=16, total_steps=12, seed=5,
                      scan_chunk=1, columns=cols)

    ckdir = tmp_path / "ck"
    t_int = _trainer(12)
    with AsyncCheckpointer(str(ckdir), keep=5) as ck:
        fit_source(t_int, fresh(), batch_size=16, total_steps=8, seed=5,
                   scan_chunk=1, checkpointer=ck, checkpoint_every=4,
                   columns=cols)
    tree = restore_checkpoint(str(ckdir), step=8)
    assert "data_iter" in tree  # iterator state rode along

    t_res = _trainer(12)
    state = t_res.resume_state(tree["params"], tree["opt_state"],
                               step=int(np.asarray(tree["step"])))
    res = fit_source(t_res, fresh(), batch_size=16, total_steps=12, seed=5,
                     scan_chunk=1, state=state, data_state=tree["data_iter"],
                     columns=cols)
    assert int(res.step) == int(full.step) == 12
    for a, b in zip(jax.tree.leaves(full.params), jax.tree.leaves(res.params)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_fit_arrays_is_single_stream_under_multi_process_topology(
        tmp_path, monkeypatch):
    """mesh.shard_batch expects every process to supply the SAME global
    batch, so fit_arrays/fit_source must feed one logical stream even when
    jax reports a multi-process topology (host-striding a single-shard
    MemorySource would starve every host but one)."""
    import jax

    from synapseml_tpu.models.trainer import fit_arrays

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    X = np.random.default_rng(0).normal(size=(40, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int32)
    t = _trainer(4)
    s = fit_arrays(t, {"x": X, "labels": y}, batch_size=16, total_steps=4,
                   seed=0)
    assert int(s.step) == 4


def test_fit_source_chunked_scan_path(tmp_path):
    """The lax.scan fused path (scan_chunk>1) consumes the streamed batches
    too — same rows, same final step count."""
    from synapseml_tpu.models.trainer import fit_source

    _write_jsonl(tmp_path)
    src = ShardedSource.jsonl(str(tmp_path / "*.jsonl"))
    t = _trainer(10)
    s = fit_source(t, src, batch_size=16, total_steps=10, seed=1,
                   scan_chunk=4, columns=["x", "labels"])
    assert int(s.step) == 10


def test_large_scan_chunk_checkpoints_keep_data_iter(tmp_path):
    """With a big scan_chunk the chunked fit's producer runs far ahead of
    the checkpointed step; the snapshot history must outlive that lag so
    every save still carries its data_iter subtree."""
    from synapseml_tpu.models.trainer import fit_source
    from synapseml_tpu.parallel.checkpoint import (AsyncCheckpointer,
                                                   restore_checkpoint)

    _write_jsonl(tmp_path)
    src = ShardedSource.jsonl(str(tmp_path / "*.jsonl"))
    ckdir = tmp_path / "ck"
    t = _trainer(64)
    with AsyncCheckpointer(str(ckdir), keep=5) as ck:
        fit_source(t, src, batch_size=4, total_steps=64, seed=2,
                   scan_chunk=32, checkpointer=ck, checkpoint_every=32,
                   columns=["x", "labels"])
    tree = restore_checkpoint(str(ckdir), step=32)
    assert "data_iter" in tree
    assert int(np.asarray(tree["data_iter"]["batches_emitted"])) == 32


# ---------------------------------------------------------------------------
# streamed GBDT
# ---------------------------------------------------------------------------

def _gbdt_dataset(tmp_path, n=2400, f=6, shards=4):
    rs = np.random.default_rng(1)
    X = rs.normal(size=(n, f)).astype(np.float32)
    w = rs.normal(size=f)
    y = (X @ w + 0.1 * rs.normal(size=n)).astype(np.float32)
    per = n // shards
    for i in range(shards):
        with open(tmp_path / f"g{i}.jsonl", "w") as fo:
            for j in range(i * per, (i + 1) * per):
                fo.write(json.dumps({"feat": X[j].tolist(),
                                     "label": float(y[j]),
                                     "cls": float(y[j] > 0)}) + "\n")
    return X, y


def test_streamed_gbdt_matches_in_memory_engine(tmp_path):
    from synapseml_tpu.gbdt import train_booster_from_source
    from synapseml_tpu.gbdt.booster import train_booster

    X, y = _gbdt_dataset(tmp_path)
    src = ShardedSource.jsonl(str(tmp_path / "g*.jsonl"))
    streamed = train_booster_from_source(
        src, label_col="label", feature_cols=["feat"],
        objective="regression", num_iterations=15, max_depth=5,
        chunk_rows=512)
    in_mem = train_booster(X, y, objective="regression", num_iterations=15,
                           max_depth=5)
    mse_s = float(np.mean((streamed.predict(X) - y) ** 2))
    mse_m = float(np.mean((in_mem.predict(X) - y) ** 2))
    var = float(np.var(y))
    assert mse_s < 0.5 * var, "streamed booster did not learn"
    assert mse_s < mse_m * 1.25, (mse_s, mse_m)  # parity with the device path
    assert streamed.num_iterations == 15
    assert streamed.train_measures["iterations_count"] == 15


def test_streamed_gbdt_binary_and_persistence(tmp_path):
    from synapseml_tpu.gbdt import train_booster_from_source
    from synapseml_tpu.gbdt.booster import TpuBooster

    X, y = _gbdt_dataset(tmp_path)
    src = ShardedSource.jsonl(str(tmp_path / "g*.jsonl"))
    b = train_booster_from_source(src, label_col="cls", feature_cols=["feat"],
                                  objective="binary", num_iterations=15,
                                  max_depth=5, chunk_rows=512)
    acc = float(np.mean((b.predict(X) > 0.5) == (y > 0)))
    assert acc > 0.85, acc
    b.save(str(tmp_path / "model"))
    b2 = TpuBooster.load(str(tmp_path / "model"))
    assert np.allclose(b2.predict(X[:64]), b.predict(X[:64]))


def test_streamed_gbdt_skips_empty_byte_range_shards(tmp_path):
    """Shards whose byte range holds no complete line read as empty; both
    GBDT passes must agree they hold zero rows (spill/count alignment)."""
    from synapseml_tpu.gbdt import train_booster_from_source

    rs = np.random.default_rng(0)
    with open(tmp_path / "g.jsonl", "w") as f:
        for i in range(120):
            x = rs.normal(size=4)
            row = {"feat": [round(float(v), 6) for v in x],
                   "label": float(x.sum())}
            if i % 40 == 0:  # a long line spanning several byte ranges
                row["pad"] = "z" * 2000
            f.write(json.dumps(row) + "\n")
    src = ShardedSource.jsonl(str(tmp_path / "g.jsonl"), shard_bytes=512)
    assert any(not cols for _, cols in src.iter_shards())  # empties exist
    b = train_booster_from_source(src, label_col="label",
                                  feature_cols=["feat"],
                                  objective="regression", num_iterations=5,
                                  max_depth=4, chunk_rows=64)
    assert b.num_iterations == 5


def test_fit_source_resume_requires_explicit_data_state(tmp_path):
    """Resuming params without data_state must fail fast (the loader would
    silently restart the stream from epoch 0); data_state='fresh' is the
    deliberate restart, and keeps the step<->batch alignment."""
    from synapseml_tpu.models.trainer import fit_source
    from synapseml_tpu.parallel.checkpoint import (AsyncCheckpointer,
                                                   restore_checkpoint)

    _write_jsonl(tmp_path)
    src = ShardedSource.jsonl(str(tmp_path / "*.jsonl"))
    ckdir = tmp_path / "ck"
    t = _trainer(8)
    with AsyncCheckpointer(str(ckdir)) as ck:
        fit_source(t, src, batch_size=16, total_steps=8, seed=6,
                   scan_chunk=1, checkpointer=ck, checkpoint_every=4,
                   columns=["x", "labels"])
    tree = restore_checkpoint(str(ckdir), step=8)
    t2 = _trainer(12)
    state = t2.resume_state(tree["params"], tree["opt_state"],
                            step=int(np.asarray(tree["step"])))
    with pytest.raises(ValueError, match="data_state"):
        fit_source(t2, src, batch_size=16, total_steps=12, seed=6,
                   scan_chunk=1, state=state, columns=["x", "labels"])
    res = fit_source(t2, ShardedSource.jsonl(str(tmp_path / "*.jsonl")),
                     batch_size=16, total_steps=12, seed=6, scan_chunk=1,
                     state=state, data_state="fresh", columns=["x", "labels"])
    assert int(res.step) == 12


def test_empty_tabular_sources_fail_with_clear_error(tmp_path):
    (tmp_path / "h.csv").write_text("a,b\n")  # header only
    with pytest.raises(ValueError, match="headers only"):
        ShardedSource.csv(str(tmp_path / "h.csv"))
    (tmp_path / "e.jsonl").write_text("")  # zero-byte file
    with pytest.raises(ValueError, match="no data rows"):
        ShardedSource.jsonl(str(tmp_path / "e.jsonl"))


def test_streamed_gbdt_derives_depth_from_num_leaves(tmp_path):
    """max_depth=-1 means 'derive from num_leaves' (the in-memory engine's
    convention) — it must not clamp to depth-1 stumps."""
    from synapseml_tpu.gbdt import train_booster_from_source

    X, y = _gbdt_dataset(tmp_path, n=600, shards=2)
    src = ShardedSource.jsonl(str(tmp_path / "g*.jsonl"))
    b = train_booster_from_source(src, label_col="label",
                                  feature_cols=["feat"],
                                  objective="regression", num_iterations=3,
                                  max_depth=-1, num_leaves=31)
    assert b.max_depth >= 3
    # deeper than a stump: some nodes below the root actually split
    assert (b.feature[:, :, 1:3] >= 0).any()


def test_streamed_gbdt_rejects_lambdarank(tmp_path):
    from synapseml_tpu.gbdt import train_booster_from_source

    _gbdt_dataset(tmp_path, n=200, shards=1)
    src = ShardedSource.jsonl(str(tmp_path / "g*.jsonl"))
    with pytest.raises(ValueError, match="lambdarank"):
        train_booster_from_source(src, label_col="label",
                                  feature_cols=["feat"],
                                  objective="lambdarank")


# ---------------------------------------------------------------------------
# io/files max_rows fast path
# ---------------------------------------------------------------------------

def test_read_jsonl_max_rows_stops_early(tmp_path):
    from synapseml_tpu.io.files import read_jsonl

    _write_jsonl(tmp_path)
    df = read_jsonl(str(tmp_path / "*.jsonl"), max_rows=45)
    assert df.count() == 45
    # budget smaller than one file: only that many rows parse
    assert read_jsonl(str(tmp_path / "*.jsonl"), max_rows=7).count() == 7
    assert read_jsonl(str(tmp_path / "*.jsonl")).count() == N_ROWS


def test_read_csv_max_rows_stops_early(tmp_path):
    pytest.importorskip("pandas")
    from synapseml_tpu.io.files import read_csv, write_csv
    from synapseml_tpu.core import DataFrame

    df = DataFrame.from_dict({"a": np.arange(40)}, num_partitions=4)
    write_csv(df, str(tmp_path / "out"), partitioned=True)
    got = read_csv(str(tmp_path / "out"), max_rows=25)
    assert got.count() == 25
    assert read_csv(str(tmp_path / "out")).count() == 40


# ---------------------------------------------------------------------------
# checkpoint hardening (satellite)
# ---------------------------------------------------------------------------

def test_latest_step_ignores_partially_written_dirs(tmp_path):
    from synapseml_tpu.parallel.checkpoint import (latest_step,
                                                   restore_checkpoint,
                                                   save_checkpoint)

    root = tmp_path / "ck"
    save_checkpoint(str(root), {"w": np.ones(3)}, step=5)
    assert latest_step(str(root)) == 5

    # crash during a later save: dir exists, payload exists, no DONE marker
    partial = root / "step_0000000009"
    os.makedirs(partial)
    np.savez(partial / "state.npz", w=np.zeros(3))
    # crash even earlier: marker written but payload missing entirely
    ghost = root / "step_0000000011"
    os.makedirs(ghost)
    (ghost / "DONE").write_text("11")
    # a foreign dir that merely looks step-like must not crash the scan
    os.makedirs(root / "step_tmp")

    assert latest_step(str(root)) == 5
    tree = restore_checkpoint(str(root))  # resolves to the completed step
    assert np.array_equal(tree["w"], np.ones(3))
    with pytest.raises(FileNotFoundError, match="incomplete"):
        restore_checkpoint(str(root), step=9)
    with pytest.raises(FileNotFoundError, match="incomplete"):
        restore_checkpoint(str(root), step=11)


def test_async_checkpointer_gc_prunes_stale_partials(tmp_path):
    from synapseml_tpu.parallel.checkpoint import AsyncCheckpointer, latest_step

    root = tmp_path / "ck"
    # crash leftover from an older run
    partial = root / "step_0000000001"
    os.makedirs(partial)
    np.savez(partial / "state.npz", w=np.zeros(2))
    with AsyncCheckpointer(str(root), keep=2) as ck:
        for step in (2, 3, 4):
            ck.save({"w": np.full(2, step)}, step=step)
            ck.wait()
    assert latest_step(str(root)) == 4
    names = sorted(os.listdir(root))
    assert "step_0000000001" not in names  # stale partial pruned
    assert names == ["step_0000000003", "step_0000000004"]  # keep=2
