"""nn (matmul KNN) + recommendation (SAR, indexer, ranking metrics/split)."""

import numpy as np
import pytest

from synapseml_tpu.core import DataFrame
from synapseml_tpu.nn import KNN, ConditionalKNN
from synapseml_tpu.recommendation import (
    RankingAdapter,
    RankingEvaluator,
    RankingTrainValidationSplit,
    RecommendationIndexer,
    SAR,
)
from synapseml_tpu.recommendation.evaluator import map_at_k, ndcg_at_k


# ---------------- KNN ----------------

def make_points(n=50, d=8, seed=0):
    rs = np.random.default_rng(seed)
    X = rs.normal(size=(n, d)).astype(np.float32)
    return X


def test_knn_matches_numpy_bruteforce():
    X = make_points()
    df = DataFrame.from_dict({"features": X, "values": np.arange(len(X))})
    model = KNN(k=4).fit(df)
    Q = make_points(7, seed=1)
    out = model.transform(DataFrame.from_dict({"features": Q}, num_partitions=2))
    matches = out.collect_column("output")
    d2 = ((Q[:, None, :] - X[None, :, :]) ** 2).sum(-1)
    for i, row in enumerate(matches):
        got = [m["value"] for m in row]
        expect = np.argsort(d2[i], kind="stable")[:4]
        assert set(got) == set(expect.tolist())
        # sorted by distance
        dists = [m["distance"] for m in row]
        assert dists == sorted(dists)


def test_conditional_knn_filters_labels():
    X = make_points(40)
    labels = np.asarray(["a", "b", "c", "d"] * 10)
    df = DataFrame.from_dict({"features": X, "values": np.arange(40), "labels": labels})
    model = ConditionalKNN(k=5).fit(df)
    Q = make_points(6, seed=2)
    conds = np.empty(6, dtype=object)
    for i in range(6):
        conds[i] = ["a", "b"] if i % 2 == 0 else ["c"]
    out = model.transform(DataFrame.from_dict({"features": Q, "conditioner": conds}))
    for i, row in enumerate(out.collect_column("output")):
        allowed = {"a", "b"} if i % 2 == 0 else {"c"}
        assert row, "expected matches"
        assert {m["label"] for m in row} <= allowed


def test_knn_model_save_load(tmp_path):
    X = make_points(20)
    df = DataFrame.from_dict({"features": X, "values": np.arange(20)})
    model = KNN(k=3).fit(df)
    q = DataFrame.from_dict({"features": X[:5]})
    before = [[m["value"] for m in r] for r in model.transform(q).collect_column("output")]
    model.save(str(tmp_path / "knn"))
    from synapseml_tpu.nn import KNNModel
    reloaded = KNNModel.load(str(tmp_path / "knn"))
    after = [[m["value"] for m in r] for r in reloaded.transform(q).collect_column("output")]
    assert before == after
    # self-queries find themselves at distance 0
    assert all(r[0] == i for i, r in enumerate(before))


# ---------------- recommendation ----------------

def make_interactions(seed=0):
    """Two user cliques with disjoint item tastes + a few crossover events."""
    rs = np.random.default_rng(seed)
    rows = []
    for u in range(12):
        liked = range(0, 6) if u < 6 else range(6, 12)
        for i in liked:
            if rs.random() < 0.85:
                rows.append((f"u{u}", f"i{i}", 1.0, 1000.0 + u))
    rows.append(("u0", "i7", 1.0, 1000.0))
    return DataFrame.from_dict({
        "user": np.asarray([r[0] for r in rows]),
        "item": np.asarray([r[1] for r in rows]),
        "rating": np.asarray([r[2] for r in rows], np.float32),
        "time": np.asarray([r[3] for r in rows], np.float64),
    })


def test_indexer_roundtrip_and_unseen():
    df = make_interactions()
    model = RecommendationIndexer().fit(df)
    out = model.transform(df)
    assert out.collect_column("user_idx").dtype == np.int32
    np.testing.assert_array_equal(model.recover_item(out.collect_column("item_idx")),
                                  df.collect_column("item"))
    bad = DataFrame.from_dict({"user": ["nope"], "item": ["i0"]})
    with pytest.raises(ValueError, match="unseen ids"):
        model.transform(bad)


def test_sar_recommends_within_clique():
    indexer = RecommendationIndexer().fit(make_interactions())
    df = indexer.transform(make_interactions())
    model = SAR(rating_col="rating", time_col="time", support_threshold=2,
                similarity_function="jaccard").fit(df)
    recs = model.recommend_for_all_users(k=3)
    users = recs.collect_column("user_idx")
    rec_items = recs.collect_column("recommendations")
    rec_scores = recs.collect_column("ratings")
    # item ids are strings, so indexer order is lexicographic — map back to
    # the numeric clique via recover_item
    def clique_of(item_idx):
        return 0 if int(str(indexer.recover_item([item_idx])[0])[1:]) < 6 else 1

    seen = np.asarray(model.get("seen_items"))
    sim = np.asarray(model.get("item_data_frame"))
    assert sim.shape[0] == 12
    for u, items, scores in zip(users, rec_items, rec_scores):
        user_seen = set(np.nonzero(seen[u])[0].tolist())
        clique0_seen = [i for i in user_seen if clique_of(i) == 0]
        pure = len(clique0_seen) == len(user_seen)
        if pure and len(clique0_seen) >= 4:  # pure clique-0 user (no crossover)
            # every POSITIVE-score rec stays in-clique (zero-score slots are
            # arbitrary fills when the user has seen the whole clique)
            for it, sc in zip(np.asarray(items), np.asarray(scores)):
                if sc > 0:
                    assert clique_of(int(it)) == 0
        assert not (set(np.asarray(items).tolist()) & user_seen)  # remove_seen


def test_sar_similarity_functions_differ():
    df = RecommendationIndexer().fit(make_interactions()).transform(make_interactions())
    sims = {}
    for fn in ("jaccard", "lift", "cooccurrence"):
        m = SAR(similarity_function=fn, support_threshold=2).fit(df)
        sims[fn] = np.asarray(m.get("item_data_frame"))
    assert not np.allclose(sims["jaccard"], sims["lift"])
    assert sims["cooccurrence"].max() > 1.0  # raw counts
    assert sims["jaccard"].max() <= 1.0 + 1e-6


def test_ranking_metrics():
    assert ndcg_at_k([1, 2, 3], [1, 2, 3], 3) == pytest.approx(1.0)
    assert ndcg_at_k([9, 8, 1], [1], 3) == pytest.approx(1 / np.log2(4) / 1.0)
    assert map_at_k([1, 9, 2], [1, 2], 3) == pytest.approx((1 + 2 / 3) / 2)
    assert map_at_k([], [1], 3) == 0.0


def test_ranking_adapter_and_split():
    df = RecommendationIndexer().fit(make_interactions()).transform(make_interactions())
    ev = RankingEvaluator(k=5, metric_name="ndcgAt")
    tvs = RankingTrainValidationSplit(
        estimator=SAR(support_threshold=1, rating_col="rating"),
        estimator_param_maps=[{"similarity_function": "jaccard"},
                              {"similarity_function": "lift"}],
        evaluator=ev, train_ratio=0.75, seed=3)
    model = tvs.fit(df)
    metrics = model.get("validation_metrics")
    assert len(metrics) == 2
    assert all(0.0 <= m <= 1.0 for m in metrics)
    ranked = model.transform(df)
    assert set(ranked.columns) >= {"prediction", "label"}
    # strong structure -> the winning model should beat random (ndcg > 0.2)
    assert max(metrics) > 0.2
