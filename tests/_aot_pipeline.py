"""Shared servable pipeline for the AOT deploy tests and the
deploy-coldstart bench: JSON request bodies -> features -> ONNX MLP (the
CompiledCache-adopted stage whose executables the registry AOT-compiles) ->
reply dicts. Module-level classes so publish/load round-trips by class
reference across processes (subprocess drivers add ``tests/`` to
``sys.path``)."""

import numpy as np

from synapseml_tpu.core.params import Param, TypeConverters
from synapseml_tpu.core.pipeline import PipelineModel, Transformer


class BodyToFeatures(Transformer):
    """Parsed request bodies (``{"features": [...]}``) -> a rectangular
    float32 ``features`` column."""

    din = Param("din", "feature width", default=4,
                converter=TypeConverters.to_int)

    def _transform(self, df):
        d = self.get("din")

        def per_part(p):
            out = dict(p)
            feats = np.zeros((len(p["body"]), d), np.float32)
            for i, body in enumerate(p["body"]):
                if isinstance(body, dict) and "features" in body:
                    feats[i] = np.asarray(body["features"], np.float32)
            out["features"] = feats
            return out

        return df.map_partitions(per_part)


class PredToReply(Transformer):
    """ONNX outputs -> one JSON-able reply dict per request row."""

    def _transform(self, df):
        def per_part(p):
            out = dict(p)
            preds = p["pred"]
            probs = p["probs"]
            out["reply"] = np.asarray(
                [{"pred": int(preds[i]),
                  "probs": [round(float(x), 6) for x in probs[i]]}
                 for i in range(len(preds))], dtype=object)
            return out

        return df.map_partitions(per_part)


class TunableAffine(Transformer):
    """Autotune-search target: two 'backends' computing the same affine
    shift, one artificially slow — the publish-time search must pin
    'fast' and /admin/load must re-apply the pin."""

    impl = Param("impl", "backend: fast | slow", default="slow",
                 validator=lambda v: v in ("fast", "slow"))
    _AUTOTUNE_PARAMS = {"impl": ("fast", "slow")}

    def _transform(self, df):
        if self.get("impl") == "slow":
            import time

            time.sleep(0.003)

        def per_part(p):
            out = dict(p)
            if "features" in p:
                out["features"] = np.asarray(p["features"],
                                             np.float32) + 0.0
            return out

        return df.map_partitions(per_part)


def make_mlp_onnx(din=4, dout=3, width=8, depth=2, seed=0,
                  mini_batch_size=64):
    """Hand-built ONNX MLP (no external onnx dependency — the repo's own
    proto codec), depth controls compile-time signal for the bench."""
    from synapseml_tpu.onnx import ONNXModel
    from synapseml_tpu.onnx import proto as P
    from synapseml_tpu.onnx.proto import (AttributeProto, GraphProto,
                                          ModelProto, NodeProto,
                                          ValueInfoProto, numpy_to_tensor)

    rs = np.random.default_rng(seed)

    def node(op, inputs, outputs, **attrs):
        return NodeProto(input=list(inputs), output=list(outputs),
                         op_type=op,
                         attribute=[AttributeProto.make(k, v)
                                    for k, v in attrs.items()])

    nodes, inits = [], []
    prev, prev_w = "x", din
    for layer in range(depth):
        w = rs.normal(size=(prev_w, width)).astype(np.float32) * 0.3
        b = rs.normal(size=(width,)).astype(np.float32) * 0.1
        inits += [numpy_to_tensor(w, f"W{layer}"),
                  numpy_to_tensor(b, f"b{layer}")]
        nodes += [node("Gemm", [prev, f"W{layer}", f"b{layer}"],
                       [f"h{layer}_pre"]),
                  node("Relu", [f"h{layer}_pre"], [f"h{layer}"])]
        prev, prev_w = f"h{layer}", width
    w = rs.normal(size=(prev_w, dout)).astype(np.float32) * 0.3
    b = rs.normal(size=(dout,)).astype(np.float32) * 0.1
    inits += [numpy_to_tensor(w, "Wout"), numpy_to_tensor(b, "bout")]
    nodes += [node("Gemm", [prev, "Wout", "bout"], ["logits"]),
              node("Softmax", ["logits"], ["probs"], axis=-1)]
    g = GraphProto(
        name="mlp", node=nodes, initializer=inits,
        input=[ValueInfoProto(name="x", elem_type=P.FLOAT,
                              dims=["N", din])],
        output=[ValueInfoProto(name="probs", elem_type=P.FLOAT,
                               dims=["N", dout])],
    )
    return ONNXModel(ModelProto(graph=g).encode(),
                     feed_dict={"x": "features"},
                     fetch_dict={"probs": "probs"},
                     argmax_dict={"probs": "pred"},
                     mini_batch_size=mini_batch_size)


def build_pipeline(din=4, dout=3, width=8, depth=2, seed=0,
                   mini_batch_size=64):
    return PipelineModel(stages=[
        BodyToFeatures(din=din),
        make_mlp_onnx(din=din, dout=dout, width=width, depth=depth,
                      seed=seed, mini_batch_size=mini_batch_size),
        PredToReply(),
    ])


def sample_rows(n=4, din=4, seed=7):
    rs = np.random.default_rng(seed)
    return [{"features": [round(float(x), 6) for x in
                          rs.normal(size=din)]} for _ in range(n)]
