"""ops module: flash attention (Pallas, interpret on CPU) + ring attention
(shard_map over the 8-device seq mesh) vs the XLA reference oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from synapseml_tpu.ops import flash_attention, reference_attention, ring_attention_sharded
from synapseml_tpu.parallel import MeshConfig, create_mesh


def make_qkv(B=2, T=64, H=4, D=32, seed=0):
    rs = np.random.default_rng(seed)
    q, k, v = (jnp.asarray(rs.normal(size=(B, T, H, D)), jnp.float32) for _ in range(3))
    mask = jnp.asarray(rs.random((B, T)) > 0.2)
    return q, k, v, mask


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("with_mask", [False, True])
def test_flash_matches_reference(causal, with_mask):
    q, k, v, mask = make_qkv()
    kv_mask = mask if with_mask else None
    ref = reference_attention(q, k, v, kv_mask=kv_mask, causal=causal)
    out = flash_attention(q, k, v, kv_mask=kv_mask, causal=causal, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


def test_flash_gradients_match():
    q, k, v, mask = make_qkv()

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v, kv_mask=mask, causal=True) ** 2)

    g_ref = jax.grad(loss(reference_attention), argnums=(0, 1, 2))(q, k, v)
    g_fa = jax.grad(loss(lambda *a, **kw: flash_attention(*a, block_q=16, block_k=16, **kw)),
                    argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fa):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_flash_bf16_matches_f32_reference():
    # the MXU training path: bf16 q/k/v, dots in bf16 with f32 accumulation
    # (NOT pre-upcast to f32 — that would hit the ~4x slower f32 MXU path).
    # Values and grads must track the f32 oracle within bf16 resolution.
    # (small T keeps this in the fast default lane; shape/pad coverage lives
    # in the f32 tests above)
    q, k, v, mask = make_qkv(T=16)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    ref = reference_attention(q, k, v, kv_mask=mask, causal=True)
    out = flash_attention(qb, kb, vb, kv_mask=mask, causal=True,
                          block_q=16, block_k=16)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(ref),
                               np.asarray(out, dtype=np.float32), atol=3e-2)

    def loss(fn):
        return lambda q, k, v: jnp.sum(
            fn(q, k, v, kv_mask=mask, causal=True).astype(jnp.float32) ** 2)

    g_ref = jax.grad(loss(reference_attention), argnums=(0, 1, 2))(q, k, v)
    g_fa = jax.grad(loss(lambda *a, **kw: flash_attention(
        *a, block_q=16, block_k=16, **kw)), argnums=(0, 1, 2))(qb, kb, vb)
    for a, b in zip(g_ref, g_fa):
        np.testing.assert_allclose(np.asarray(a),
                                   np.asarray(b, dtype=np.float32),
                                   atol=0.15, rtol=0.05)


def test_flash_unaligned_shapes():
    # T not a multiple of the block, D not a multiple of 128: pad/slice path
    q, k, v, _ = make_qkv(T=50, D=24)
    ref = reference_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


def test_fully_masked_rows_zero():
    q, k, v, _ = make_qkv(T=16)
    mask = jnp.zeros((2, 16), bool).at[:, :4].set(True)
    # causal+mask: no fully masked rows among the first 4, rows attending only
    # to masked positions produce exactly zero
    out = flash_attention(q, k, v, kv_mask=mask, block_q=8, block_k=8)
    ref = reference_attention(q, k, v, kv_mask=mask)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)
    all_masked = jnp.zeros((2, 16), bool)
    out0 = flash_attention(q, k, v, kv_mask=all_masked, block_q=8, block_k=8)
    assert float(jnp.max(jnp.abs(out0))) == 0.0


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    q, k, v, mask = make_qkv()
    mesh = create_mesh(MeshConfig(data=1, seq=8))
    ref = reference_attention(q, k, v, kv_mask=mask, causal=causal)
    out = ring_attention_sharded(mesh, q, k, v, kv_mask=mask, causal=causal)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


def test_ring_attention_bf16_matches_f32_reference():
    # MXU training path: bf16 shards, ring einsums in bf16 with f32
    # accumulation and f32 softmax statistics/traveling grad accumulators
    q, k, v, mask = make_qkv(T=32)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    mesh = create_mesh(MeshConfig(data=1, seq=8))
    ref = reference_attention(q, k, v, kv_mask=mask, causal=True)
    out = ring_attention_sharded(mesh, qb, kb, vb, kv_mask=mask, causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(ref),
                               np.asarray(out, dtype=np.float32), atol=3e-2)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(
            mesh, q, k, v, kv_mask=mask, causal=True).astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, kv_mask=mask,
                                           causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(qb, kb, vb)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(a),
                                   np.asarray(b, dtype=np.float32),
                                   atol=0.15, rtol=0.05)


def test_ring_attention_mixed_mesh():
    # data×seq mesh: batch and sequence sharded simultaneously
    q, k, v, mask = make_qkv(B=4, T=32)
    mesh = create_mesh(MeshConfig(data=2, seq=4))
    ref = reference_attention(q, k, v, kv_mask=mask, causal=True)
    out = ring_attention_sharded(mesh, q, k, v, kv_mask=mask, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


def test_ring_attention_differentiable():
    q, k, v, _ = make_qkv(T=32)
    mesh = create_mesh(MeshConfig(data=1, seq=8))

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(mesh, q, k, v, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_encoder_attn_impls_agree():
    """The same Encoder weights produce the same output under einsum, flash,
    ring, and ulysses (on a seq mesh) attention backends (valid positions
    only)."""
    import dataclasses

    from synapseml_tpu.models.flax_nets.transformer import Encoder, TransformerConfig

    base = TransformerConfig(hidden=32, n_layers=2, n_heads=4, mlp_dim=64,
                             max_len=32, dtype=jnp.float32, causal=True)
    B, T = 2, 32
    rs = np.random.default_rng(0)
    x = jnp.asarray(rs.normal(size=(B, T, base.hidden)), jnp.float32)
    mask_1d = np.ones((B, T), bool)
    mask_1d[:, -5:] = False
    mask = jnp.asarray(mask_1d)[:, None, None, :]

    enc = Encoder(base)
    variables = enc.init(jax.random.PRNGKey(0), x, mask)

    out_einsum = enc.apply(variables, x, mask)
    out_flash = Encoder(dataclasses.replace(base, attn_impl="flash")).apply(variables, x, mask)
    valid = np.asarray(mask_1d)
    np.testing.assert_allclose(np.asarray(out_einsum)[valid],
                               np.asarray(out_flash)[valid], atol=2e-4)

    mesh = create_mesh(MeshConfig(data=2, seq=4))
    with mesh.mesh:
        out_ring = Encoder(dataclasses.replace(base, attn_impl="ring")).apply(variables, x, mask)
    np.testing.assert_allclose(np.asarray(out_einsum)[valid],
                               np.asarray(out_ring)[valid], atol=2e-4)

    with mesh.mesh:  # n_heads=4 divides seq=4: ulysses eligible
        out_uly = Encoder(dataclasses.replace(base, attn_impl="ulysses")).apply(variables, x, mask)
    np.testing.assert_allclose(np.asarray(out_einsum)[valid],
                               np.asarray(out_uly)[valid], atol=2e-4)


def test_ring_attention_grad_matches_reference_with_mask():
    """Custom-VJP gradients == autodiff through reference_attention, with
    padding mask + causal + chunked inner (chunk < T_local)."""
    mesh = create_mesh(MeshConfig(seq=4))
    rs = np.random.default_rng(7)
    B, T, H, D = 2, 64, 2, 16
    q = jnp.asarray(rs.normal(size=(B, T, H, D)), jnp.float32)
    k = jnp.asarray(rs.normal(size=(B, T, H, D)), jnp.float32)
    v = jnp.asarray(rs.normal(size=(B, T, H, D)), jnp.float32)
    mask = np.ones((B, T), bool)
    mask[1, 50:] = False
    mask = jnp.asarray(mask)
    w = jnp.asarray(rs.normal(size=(B, T, H, D)), jnp.float32)  # cotangent mix

    def loss_ring(q, k, v):
        out = ring_attention_sharded(mesh, q, k, v, kv_mask=mask, causal=True,
                                     chunk=8)
        return jnp.sum(out * w)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, kv_mask=mask, causal=True) * w)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_ring_attention_long_context_32k():
    """T=32k over an 8-way seq mesh: rolled ring + chunked inner must compile
    (compile size independent of ring length) and run without a [T_loc, T_loc]
    score materialization. reference check on a strided sample of rows."""
    mesh = create_mesh(MeshConfig(seq=8))
    rs = np.random.default_rng(11)
    B, T, H, D = 1, 32768, 1, 64
    q = jnp.asarray(rs.normal(size=(B, T, H, D)), jnp.bfloat16)
    k = jnp.asarray(rs.normal(size=(B, T, H, D)), jnp.bfloat16)
    v = jnp.asarray(rs.normal(size=(B, T, H, D)), jnp.bfloat16)
    out = np.asarray(ring_attention_sharded(mesh, q, k, v, causal=True,
                                            chunk=1024))
    assert out.shape == (B, T, H, D)
    assert np.all(np.isfinite(out))
    # spot-check rows against local attention over their causal prefix
    qf, kf, vf = (np.asarray(x, np.float32) for x in (q, k, v))
    for t in (0, 5000, 20000, 32767):
        s = (qf[0, t, 0] @ kf[0, : t + 1, 0].T) / np.sqrt(D)
        p = np.exp(s - s.max())
        p /= p.sum()
        np.testing.assert_allclose(out[0, t, 0], p @ vf[0, : t + 1, 0],
                                   atol=3e-2)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_reference(causal):
    from synapseml_tpu.ops import ulysses_attention_sharded

    q, k, v, mask = make_qkv(H=8)  # ulysses: heads divisible by seq size
    mesh = create_mesh(MeshConfig(data=1, seq=8))
    ref = reference_attention(q, k, v, kv_mask=mask, causal=causal)
    out = ulysses_attention_sharded(mesh, q, k, v, kv_mask=mask, causal=causal)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


def test_ulysses_attention_mixed_mesh_and_grad():
    """data x seq mesh; gradients flow through both all-to-alls correctly."""
    from synapseml_tpu.ops import ulysses_attention_sharded

    q, k, v, mask = make_qkv(B=4, T=32)
    mesh = create_mesh(MeshConfig(data=2, seq=4))
    ref = reference_attention(q, k, v, kv_mask=mask, causal=True)
    out = ulysses_attention_sharded(mesh, q, k, v, kv_mask=mask, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)

    def loss_u(q, k, v):
        return jnp.sum(ulysses_attention_sharded(mesh, q, k, v, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    g_u = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_u):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


@pytest.mark.parametrize("strategy", ["ulysses", "ring"])
def test_seq_parallel_with_tensor_parallel_heads(strategy):
    """seq AND tensor axes together (VERDICT r3 weak-6: the head_axis x TP
    interaction was untested beyond the divisibility guard). H=8 over
    tensor=2 engages head sharding — for Ulysses the divisor is
    tensor*seq=4 (heads split across the seq axis by the all-to-all too);
    outputs and grads must match unsharded reference attention."""
    from synapseml_tpu.ops import ring_attention_sharded, ulysses_attention_sharded

    fn = (ulysses_attention_sharded if strategy == "ulysses"
          else ring_attention_sharded)
    q, k, v, mask = make_qkv(B=2, T=32, H=8)
    mesh = create_mesh(MeshConfig(data=2, seq=2, tensor=2))
    for causal in (False, True):
        ref = reference_attention(q, k, v, kv_mask=mask, causal=causal)
        out = fn(mesh, q, k, v, kv_mask=mask, causal=causal)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)

    def loss_s(q, k, v):
        return jnp.sum(fn(mesh, q, k, v, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    g_s = jax.grad(loss_s, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_s):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_ulysses_head_axis_disengages_when_indivisible():
    """H=6 divides the seq size (3 heads per shard after the all-to-all) but
    not tensor*seq=4, so the head PartitionSpec must silently drop the
    tensor axis rather than produce a wrong sharding — output still exact."""
    from synapseml_tpu.ops import ulysses_attention_sharded

    q, k, v, mask = make_qkv(B=2, T=32, H=6)
    mesh = create_mesh(MeshConfig(data=2, seq=2, tensor=2))
    ref = reference_attention(q, k, v, kv_mask=mask, causal=True)
    out = ulysses_attention_sharded(mesh, q, k, v, kv_mask=mask, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


def test_ulysses_rejects_indivisible_heads():
    from synapseml_tpu.ops.ulysses_attention import ulysses_attention

    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(jnp.zeros((1, 4, 6, 8)), jnp.zeros((1, 4, 6, 8)),
                          jnp.zeros((1, 4, 6, 8)), axis_name="seq",
                          axis_size=4)
