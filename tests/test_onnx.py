"""onnx module: proto codec roundtrip, ONNX->JAX conversion, ONNXModel
transformer (padding, post-cols, slicing), hub, ImageFeaturizer."""

import numpy as np
import pytest

from synapseml_tpu.core import DataFrame
from synapseml_tpu.onnx import (
    AttributeProto,
    GraphProto,
    ImageFeaturizer,
    ModelProto,
    NodeProto,
    ONNXHub,
    ONNXModel,
    ValueInfoProto,
    convert_graph,
    numpy_to_tensor,
    parse_model,
    slice_model_at_outputs,
)
from synapseml_tpu.onnx import proto as P


def node(op, inputs, outputs, **attrs):
    return NodeProto(input=list(inputs), output=list(outputs), op_type=op,
                     attribute=[AttributeProto.make(k, v) for k, v in attrs.items()])


def make_mlp_bytes(seed=0, din=4, dh=8, dout=3):
    rs = np.random.default_rng(seed)
    W1 = rs.normal(size=(din, dh)).astype(np.float32)
    b1 = rs.normal(size=(dh,)).astype(np.float32)
    W2 = rs.normal(size=(dh, dout)).astype(np.float32)
    b2 = rs.normal(size=(dout,)).astype(np.float32)
    g = GraphProto(
        name="mlp",
        node=[
            node("Gemm", ["x", "W1", "b1"], ["h_pre"]),
            node("Relu", ["h_pre"], ["h"]),
            node("Gemm", ["h", "W2", "b2"], ["logits"]),
            node("Softmax", ["logits"], ["probs"], axis=-1),
        ],
        initializer=[numpy_to_tensor(W1, "W1"), numpy_to_tensor(b1, "b1"),
                     numpy_to_tensor(W2, "W2"), numpy_to_tensor(b2, "b2")],
        input=[ValueInfoProto(name="x", elem_type=P.FLOAT, dims=["N", din])],
        output=[ValueInfoProto(name="probs", elem_type=P.FLOAT, dims=["N", dout])],
    )
    return ModelProto(graph=g).encode(), (W1, b1, W2, b2)


def mlp_reference(x, W1, b1, W2, b2):
    h = np.maximum(x @ W1 + b1, 0)
    logits = h @ W2 + b2
    e = np.exp(logits - logits.max(-1, keepdims=True))
    return h, logits, e / e.sum(-1, keepdims=True)


def test_proto_roundtrip():
    data, (W1, *_rest) = make_mlp_bytes()
    m = parse_model(data)
    assert m.graph.name == "mlp"
    assert [n.op_type for n in m.graph.node] == ["Gemm", "Relu", "Gemm", "Softmax"]
    re_encoded = m.encode()
    m2 = parse_model(re_encoded)
    assert [t.name for t in m2.graph.initializer] == ["W1", "b1", "W2", "b2"]
    np.testing.assert_array_equal(P.tensor_to_numpy(m2.graph.initializer[0]), W1)
    assert m2.graph.input[0].dims == ["N", 4]


def test_convert_mlp_matches_numpy():
    data, weights = make_mlp_bytes()
    conv = convert_graph(data)
    assert conv.input_names == ["x"]
    x = np.random.default_rng(1).normal(size=(5, 4)).astype(np.float32)
    out = conv(x=x)
    _, _, probs = mlp_reference(x, *weights)
    np.testing.assert_allclose(np.asarray(out["probs"]), probs, atol=1e-5)


def test_convert_conv_ops():
    # 1x1 conv with known weights == per-pixel linear map; then global pooling
    rs = np.random.default_rng(0)
    W = rs.normal(size=(2, 3, 1, 1)).astype(np.float32)  # OIHW
    b = rs.normal(size=(2,)).astype(np.float32)
    g = GraphProto(
        name="cnn",
        node=[
            node("Conv", ["x", "W", "b"], ["c"], kernel_shape=[1, 1]),
            node("Relu", ["c"], ["r"]),
            node("GlobalAveragePool", ["r"], ["gap"]),
            node("Flatten", ["gap"], ["flat"], axis=1),
        ],
        initializer=[numpy_to_tensor(W, "W"), numpy_to_tensor(b, "b")],
        input=[ValueInfoProto(name="x", dims=["N", 3, 6, 6])],
        output=[ValueInfoProto(name="flat", dims=["N", 2])],
    )
    data = ModelProto(graph=g).encode()
    x = rs.normal(size=(2, 3, 6, 6)).astype(np.float32)
    out = np.asarray(convert_graph(data)(x=x)["flat"])
    ref = np.maximum(np.einsum("nchw,oc->nohw", x, W[:, :, 0, 0]) + b[None, :, None, None], 0)
    ref = ref.mean(axis=(2, 3))
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_convert_maxpool_batchnorm():
    scale = np.asarray([2.0], np.float32)
    bias = np.asarray([1.0], np.float32)
    mean = np.asarray([0.5], np.float32)
    var = np.asarray([4.0], np.float32)
    g = GraphProto(
        name="bnpool",
        node=[
            node("BatchNormalization", ["x", "s", "bB", "m", "v"], ["bn"], epsilon=0.0),
            node("MaxPool", ["bn"], ["mp"], kernel_shape=[2, 2], strides=[2, 2]),
        ],
        initializer=[numpy_to_tensor(scale, "s"), numpy_to_tensor(bias, "bB"),
                     numpy_to_tensor(mean, "m"), numpy_to_tensor(var, "v")],
        input=[ValueInfoProto(name="x", dims=["N", 1, 4, 4])],
        output=[ValueInfoProto(name="mp", dims=["N", 1, 2, 2])],
    )
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    out = np.asarray(convert_graph(ModelProto(graph=g).encode())(x=x)["mp"])
    bn = (x - 0.5) / 2.0 * 2.0 + 1.0
    ref = bn.reshape(1, 1, 2, 2, 2, 2).max(axis=(3, 5))
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_unsupported_op_raises_at_conversion():
    g = GraphProto(node=[node("NonexistentOp", ["x"], ["y"])],
                   input=[ValueInfoProto(name="x", dims=[1])],
                   output=[ValueInfoProto(name="y", dims=[1])])
    with pytest.raises(NotImplementedError, match="NonexistentOp"):
        convert_graph(ModelProto(graph=g).encode())


def test_onnx_model_transform_with_post_cols():
    data, weights = make_mlp_bytes()
    rs = np.random.default_rng(2)
    X = rs.normal(size=(23, 4)).astype(np.float32)  # 23 % batch 8 != 0 -> padding
    df = DataFrame.from_dict({"features": X, "row": np.arange(23)}, num_partitions=3)
    om = ONNXModel(model_bytes=data, mini_batch_size=8,
                   feed_dict={"x": "features"}, fetch_dict={"probs": "probs"},
                   argmax_dict={"probs": "prediction"})
    out = om.transform(df)
    probs = np.stack(list(out.collect_column("probs")))
    _, _, ref = mlp_reference(X, *weights)
    np.testing.assert_allclose(probs, ref, atol=1e-5)
    preds = out.collect_column("prediction")
    np.testing.assert_array_equal(preds, ref.argmax(-1))
    assert out.collect_column("row").tolist() == list(range(23))


def test_model_slicing():
    data, (W1, b1, *_rest) = make_mlp_bytes()
    sliced = slice_model_at_outputs(data, ["h"])
    conv = convert_graph(sliced)
    assert conv.output_names == ["h"]
    assert [n.op_type for n in conv.model.graph.node] == ["Gemm", "Relu"]
    assert set(conv.weights) == {"W1", "b1"}
    x = np.random.default_rng(3).normal(size=(4, 4)).astype(np.float32)
    h_ref = np.maximum(x @ W1 + b1, 0)
    np.testing.assert_allclose(np.asarray(conv(x=x)["h"]), h_ref, atol=1e-5)


def test_hub_roundtrip(tmp_path):
    hub = ONNXHub(hub_dir=str(tmp_path))
    data, _ = make_mlp_bytes()
    hub.save("tiny-mlp", data)
    assert hub.load("tiny-mlp") == data
    assert hub.get_model_info("tiny-mlp")["model_sha256"]
    with pytest.raises(FileNotFoundError, match="no network egress"):
        hub.load("resnet50")
    # sha mismatch detection
    with open(hub.model_path("tiny-mlp"), "ab") as f:
        f.write(b"corrupt")
    with pytest.raises(ValueError, match="sha256 mismatch"):
        hub.load("tiny-mlp")


def test_image_featurizer_headless(tmp_path):
    rs = np.random.default_rng(0)
    W = rs.normal(size=(5, 3, 3, 3)).astype(np.float32)
    b = np.zeros(5, np.float32)
    Wfc = rs.normal(size=(5, 2)).astype(np.float32)
    g = GraphProto(
        name="tiny-vision",
        node=[
            node("Conv", ["img", "W", "b"], ["c"], kernel_shape=[3, 3],
                 strides=[2, 2], pads=[1, 1, 1, 1]),
            node("Relu", ["c"], ["feat"]),
            node("GlobalAveragePool", ["feat"], ["pooled"]),
            node("Flatten", ["pooled"], ["emb"], axis=1),
            node("MatMul", ["emb", "Wfc"], ["logits"]),
        ],
        initializer=[numpy_to_tensor(W, "W"), numpy_to_tensor(b, "b"),
                     numpy_to_tensor(Wfc, "Wfc")],
        input=[ValueInfoProto(name="img", dims=["N", 3, 16, 16])],
        output=[ValueInfoProto(name="logits", dims=["N", 2])],
    )
    data = ModelProto(graph=g).encode()
    imgs = [rs.integers(0, 256, size=(20, 24, 3)).astype(np.float32) for _ in range(3)]
    df = DataFrame.from_dict({"image": imgs})
    feats = (ImageFeaturizer(input_col="image", output_col="features",
                             image_height=16, image_width=16, head_less=True,
                             feature_tensor_name="emb", mini_batch_size=4)
             .set(model_payload=data).transform(df))
    out = feats.partitions[0]["features"]
    assert out.shape == (3, 5)  # cut at embedding, head (MatMul) dropped
    full = (ImageFeaturizer(input_col="image", output_col="features",
                            image_height=16, image_width=16, head_less=False,
                            mini_batch_size=4)
            .set(model_payload=data).transform(df))
    assert full.partitions[0]["features"].shape == (3, 2)


def test_empty_partitions_keep_schema():
    data, _ = make_mlp_bytes()
    df = DataFrame.from_dict({"features": np.ones((2, 4), np.float32)},
                             num_partitions=4)  # 2 empty partitions
    om = ONNXModel(model_bytes=data, feed_dict={"x": "features"},
                   fetch_dict={"probs": "probs"}, argmax_dict={"probs": "pred"})
    out = om.transform(df)
    assert out.count() == 2
    assert len(out.collect_column("pred")) == 2


def test_flatten_negative_axis_and_same_lower_pool():
    g = GraphProto(name="f", node=[node("Flatten", ["x"], ["y"], axis=-1)],
                   input=[ValueInfoProto(name="x", dims=[2, 3, 4])],
                   output=[ValueInfoProto(name="y", dims=[6, 4])])
    y = convert_graph(ModelProto(graph=g).encode())(x=np.zeros((2, 3, 4), np.float32))["y"]
    assert np.asarray(y).shape == (6, 4)
    g2 = GraphProto(name="p",
                    node=[node("MaxPool", ["x"], ["y"], kernel_shape=[2, 2],
                               strides=[2, 2], auto_pad="SAME_LOWER")],
                    input=[ValueInfoProto(name="x", dims=["N", 1, 3, 3])],
                    output=[ValueInfoProto(name="y", dims=["N", 1, 2, 2])])
    x = np.arange(9, dtype=np.float32).reshape(1, 1, 3, 3)
    y2 = np.asarray(convert_graph(ModelProto(graph=g2).encode())(x=x)["y"])
    assert y2.reshape(2, 2).tolist() == [[0.0, 2.0], [6.0, 8.0]]  # pad at begin


def test_headless_without_tensor_name_raises():
    data, _ = make_mlp_bytes()
    rs = np.random.default_rng(0)
    imgs = [rs.integers(0, 256, (8, 8, 3)).astype(np.float32)]
    df = DataFrame.from_dict({"image": imgs})
    feat = ImageFeaturizer(head_less=True).set(model_payload=data)
    with pytest.raises(ValueError, match="feature_tensor_name"):
        feat.transform(df)


def test_float16_int32_data_decoded_as_bit_patterns():
    # fp16 stored via int32_data holds uint16 bit patterns: 15360 == 1.0
    one, half = 15360, 14336
    t = P.TensorProto(dims=[2], data_type=P.FLOAT16, int32_data=[one, half])
    np.testing.assert_array_equal(P.tensor_to_numpy(t),
                                  np.array([1.0, 0.5], np.float16))


def test_bfloat16_raw_and_int32_data():
    import ml_dtypes

    vals = np.array([1.0, -2.5, 0.125], ml_dtypes.bfloat16)
    t = P.TensorProto(dims=[3], data_type=P.BFLOAT16, raw_data=vals.tobytes())
    np.testing.assert_array_equal(P.tensor_to_numpy(t), vals)
    bits = vals.view(np.uint16)
    t2 = P.TensorProto(dims=[3], data_type=P.BFLOAT16,
                       int32_data=[int(b) for b in bits])
    np.testing.assert_array_equal(P.tensor_to_numpy(t2), vals)


@pytest.mark.parametrize("end,step,expect", [
    (np.iinfo(np.int64).max, 1, slice(1, None, 1)),       # INT64_MAX "to end"
    (2**31 + 7, 1, slice(1, None, 1)),                    # between 2^31 and 2^63
    (3, 1, slice(1, 3, 1)),                               # plain end preserved
    (np.iinfo(np.int64).min, -1, slice(3, None, -1)),     # negative-step to-start
])
def test_slice_end_sentinels(end, step, expect):
    x = np.arange(20, dtype=np.float32).reshape(4, 5)
    start = 1 if step > 0 else 3
    g = GraphProto(
        name="s",
        node=[node("Slice", ["x", "st", "en", "ax", "sp"], ["y"])],
        initializer=[numpy_to_tensor(np.array([start], np.int64), "st"),
                     numpy_to_tensor(np.array([end], np.int64), "en"),
                     numpy_to_tensor(np.array([0], np.int64), "ax"),
                     numpy_to_tensor(np.array([step], np.int64), "sp")],
        input=[ValueInfoProto(name="x", elem_type=P.FLOAT, dims=[4, 5])],
        output=[ValueInfoProto(name="y", elem_type=P.FLOAT, dims=["M", 5])],
    )
    fn = convert_graph(ModelProto(graph=g).encode())
    np.testing.assert_array_equal(np.asarray(fn(x=x)["y"]), x[expect])


def test_slice_sentinel_survives_concat_cast_chain():
    """INT64_MAX 'to end' built through Concat/Cast/Unsqueeze of int64
    constants (a common exporter pattern) must not wrap to -1."""
    big = np.iinfo(np.int64).max
    g = GraphProto(
        name="chain",
        node=[
            node("Unsqueeze", ["e0", "zero"], ["e0u"]),
            node("Cast", ["e0u"], ["e0c"], to=P.INT64),
            node("Concat", ["e0c"], ["ends"], axis=0),
            node("Slice", ["x", "st", "ends", "ax", "sp"], ["y"]),
        ],
        initializer=[numpy_to_tensor(np.array(big, np.int64), "e0"),
                     numpy_to_tensor(np.array([0], np.int64), "zero"),
                     numpy_to_tensor(np.array([1], np.int64), "st"),
                     numpy_to_tensor(np.array([0], np.int64), "ax"),
                     numpy_to_tensor(np.array([1], np.int64), "sp")],
        input=[ValueInfoProto(name="x", elem_type=P.FLOAT, dims=[4, 5])],
        output=[ValueInfoProto(name="y", elem_type=P.FLOAT, dims=["M", 5])],
    )
    x = np.arange(20, dtype=np.float32).reshape(4, 5)
    fn = convert_graph(ModelProto(graph=g).encode())
    np.testing.assert_array_equal(np.asarray(fn(x=x)["y"]), x[1:])
