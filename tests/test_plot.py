"""Plotting glue (reference `synapse/ml/plot/plot.py`) — headless rendering,
label-order pinning, and label-coding tolerance."""

import numpy as np
import pytest

matplotlib = pytest.importorskip("matplotlib")
matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

from synapseml_tpu.core import DataFrame  # noqa: E402
from synapseml_tpu.plot import confusionMatrix, roc  # noqa: E402


def scored_df(label_kind="int"):
    rs = np.random.default_rng(0)
    y = rs.integers(0, 2, 200)
    scores = np.clip(y * 0.6 + rs.normal(0.2, 0.25, 200), 0, 1)
    pred = (scores > 0.5).astype(int)
    if label_kind == "str":
        names = np.asarray(["neg", "pos"], dtype=object)
        y, pred = names[y], names[pred]
    return DataFrame.from_dict({"label": y, "prob": scores, "pred": pred})


def test_confusion_matrix_renders_and_reports_accuracy():
    fig, ax = plt.subplots()
    out = confusionMatrix(scored_df(), "label", "pred", labels=["neg", "pos"],
                          ax=ax)
    assert out.get_xlabel() == "Predicted Label"
    assert "Accuracy" in out.get_title()
    plt.close(fig)


def test_confusion_matrix_pins_caller_label_order():
    # string classes with labels REVERSED vs sorted order: cell (0,0) must be
    # the 'pos'->'pos' count, not sklearn-style sorted 'neg' first
    df = scored_df(label_kind="str")
    y = df.collect_column("label")
    p = df.collect_column("pred")
    pos_pos = int(np.sum((y == "pos") & (p == "pos")))
    fig, ax = plt.subplots()
    confusionMatrix(df, "label", "pred", labels=["pos", "neg"], ax=ax)
    texts = [t.get_text() for t in ax.texts]
    assert texts[0] == str(pos_pos), (texts, pos_pos)
    plt.close(fig)


def test_confusion_matrix_single_class_keeps_grid():
    df = DataFrame.from_dict({"label": np.ones(10, np.int64),
                              "pred": np.ones(10, np.int64)})
    fig, ax = plt.subplots()
    confusionMatrix(df, "label", "pred", labels=["neg", "pos"], ax=ax)
    assert len(ax.texts) == 4  # full 2x2 grid, absent class renders zeros
    assert ax.texts[3].get_text() == "10"  # (pos, pos) cell
    plt.close(fig)


@pytest.mark.parametrize("kind", ["int", "str"])
def test_roc_handles_label_codings(kind):
    fig, ax = plt.subplots()
    out = roc(scored_df(kind), "label", "prob", ax=ax)
    legend = out.get_legend().get_texts()[0].get_text()
    assert "AUC" in legend
    auc = float(legend.split("=")[1])
    assert auc > 0.7  # scores genuinely separate the classes
    plt.close(fig)


def test_roc_pm1_coding():
    rs = np.random.default_rng(1)
    y = rs.choice([-1, 1], 100)
    scores = (y > 0) * 0.5 + rs.normal(0.25, 0.2, 100)
    df = DataFrame.from_dict({"label": y, "prob": scores})
    fig, ax = plt.subplots()
    out = roc(df, "label", "prob", ax=ax)
    assert "AUC" in out.get_legend().get_texts()[0].get_text()
    plt.close(fig)
