"""CNTKModel — the legacy scoring surface (reference
`deep-learning/.../cntk/CNTKModel.py`), evaluated through the ONNX
interchange path (CNTK's supported export format)."""

import numpy as np
import pytest

import synapseml_tpu as st
from synapseml_tpu.models import CNTKModel
from tests.test_onnx import make_mlp_bytes, mlp_reference


def test_cntk_model_scores_onnx_interchange(tmp_path):
    data, (W1, b1, W2, b2) = make_mlp_bytes()
    path = tmp_path / "exported.onnx"
    path.write_bytes(data)
    m = (CNTKModel(location=str(path))
         .set_feed_dict("x", "features")
         .set_fetch_dict("probs_col", "probs"))
    X = np.random.default_rng(0).normal(size=(9, 4)).astype(np.float32)
    df = st.DataFrame.from_dict({"features": X})
    out = m.transform(df)
    _, _, probs = mlp_reference(X, W1, b1, W2, b2)
    np.testing.assert_allclose(np.stack(out.collect_column("probs_col")),
                               probs, rtol=1e-4, atol=1e-5)


def test_cntk_native_checkpoint_rejected(tmp_path):
    path = tmp_path / "legacy.dnn"
    path.write_bytes(b"CNTK\x02legacy-checkpoint-bytes")
    with pytest.raises(ValueError, match="ONNX"):
        CNTKModel(location=str(path))
