"""Driver-contract regression tests for __graft_entry__.py.

Round 1 failed its MULTICHIP artifact because dryrun_multichip only forced the
virtual CPU mesh from the __main__ block; the driver imports the module and
calls the function directly, so the function itself must self-configure.
These tests exercise the exact driver call patterns in fresh subprocesses.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(code, extra_env=None, timeout=300):
    env = dict(os.environ)
    # simulate the driver: no JAX_PLATFORMS/XLA_FLAGS pre-set by our conftest
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    if extra_env:
        env.update(extra_env)
    return subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=timeout)


def test_dryrun_multichip_driver_import():
    # the driver's pattern: import module, call function — nothing else
    r = _run("import __graft_entry__; __graft_entry__.dryrun_multichip(8)")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "dryrun_multichip ok" in r.stdout


def test_dryrun_multichip_after_backend_init():
    # caller already initialized a (wrong-sized) backend before calling us
    r = _run(
        "import jax\n"
        "jax.config.update('jax_platforms','cpu')\n"
        "jax.config.update('jax_num_cpu_devices', 1)\n"
        "assert jax.device_count() == 1\n"
        "import __graft_entry__\n"
        "__graft_entry__.dryrun_multichip(8)\n")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "dryrun_multichip ok" in r.stdout


def test_entry_single_chip_compiles():
    r = _run(
        "import jax\n"
        "jax.config.update('jax_platforms','cpu')\n"
        "import __graft_entry__\n"
        "fn, args = __graft_entry__.entry()\n"
        "out = jax.jit(fn)(*args)\n"
        "print('entry ok', out.shape)\n")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "entry ok" in r.stdout


def test_bench_cpu_smoke_emits_json():
    import json

    # flagship only: the full rotation (5 CPU-smoke configs) belongs to the
    # driver's bench run, not the test lane
    r = _run("import bench; bench.main()",
             extra_env={"JAX_PLATFORMS": "cpu", "BENCH_CONFIGS": "flagship"},
             timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    line = [l for l in r.stdout.splitlines() if l.startswith("{")][-1]
    payload = json.loads(line)
    assert {"metric", "value", "unit", "vs_baseline"} <= set(payload)
    assert payload["value"] > 0
