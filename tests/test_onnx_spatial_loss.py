"""Spatial-sampling, normalization, and training-loss ONNX ops: GridSample
and the losses parity-checked against REAL torch exports; RoiAlign and the
opset-18 tail vs numpy spec oracles (no torchvision in the image).
Reference runs these through ONNX Runtime (``onnx/ONNXModel.scala:211``)."""

import io
import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).parent))

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402
from torch import nn  # noqa: E402

from _torch_resnet import _install_onnx_shim  # noqa: E402

from synapseml_tpu.onnx.convert import OP_REGISTRY  # noqa: E402


def run_op(op, ins, **attrs):
    return OP_REGISTRY[op]([None if x is None else np.asarray(x)
                            for x in ins], attrs)


# ---------------------------------------------------------------------------
# GridSample vs a real torch export
# ---------------------------------------------------------------------------

class SamplerNet(nn.Module):
    def __init__(self, mode, padding_mode, align_corners):
        super().__init__()
        self.kw = dict(mode=mode, padding_mode=padding_mode,
                       align_corners=align_corners)

    def forward(self, x, grid):
        return F.grid_sample(x, grid, **self.kw)


@pytest.mark.parametrize("mode", ["bilinear", "nearest"])
@pytest.mark.parametrize("padding_mode", ["zeros", "border", "reflection"])
@pytest.mark.parametrize("align_corners", [False, True])
def test_grid_sample_matches_torch_export(mode, padding_mode, align_corners):
    from synapseml_tpu.onnx import convert_graph

    _install_onnx_shim()
    torch.manual_seed(0)
    model = SamplerNet(mode, padding_mode, align_corners).eval()
    x = torch.randn(2, 3, 5, 7)
    # grid spills past [-1, 1] so the padding mode actually matters
    grid = (torch.rand(2, 4, 6, 2) * 2.6 - 1.3)
    buf = io.BytesIO()
    torch.onnx.export(model, (x, grid), buf, dynamo=False,
                      input_names=["x", "grid"], output_names=["y"],
                      opset_version=16)
    conv = convert_graph(buf.getvalue())
    got = np.asarray(conv(x=x.numpy(), grid=grid.numpy())["y"])
    with torch.no_grad():
        want = model(x, grid).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# losses vs real torch exports
# ---------------------------------------------------------------------------

class CELossNet(nn.Module):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean"):
        super().__init__()
        self.kw = dict(ignore_index=ignore_index, reduction=reduction)
        self.weight = weight

    def forward(self, scores, labels):
        return F.cross_entropy(scores, labels, weight=self.weight, **self.kw)


@pytest.mark.parametrize("reduction", ["mean", "sum", "none"])
@pytest.mark.parametrize("weighted", [False, True])
def test_softmax_ce_loss_matches_torch_export(reduction, weighted):
    from synapseml_tpu.onnx import convert_graph

    _install_onnx_shim()
    torch.manual_seed(1)
    weight = torch.rand(5) + 0.5 if weighted else None
    model = CELossNet(weight=weight, ignore_index=3,
                      reduction=reduction).eval()
    scores = torch.randn(8, 5)
    labels = torch.tensor([0, 1, 2, 3, 4, 0, 3, 2])  # two ignored rows
    buf = io.BytesIO()
    torch.onnx.export(model, (scores, labels), buf, dynamo=False,
                      input_names=["scores", "labels"], output_names=["loss"])
    conv = convert_graph(buf.getvalue())
    got = np.asarray(conv(scores=scores.numpy(), labels=labels.numpy())["loss"])
    with torch.no_grad():
        want = model(scores, labels).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_nll_loss_direct_matches_torch():
    torch.manual_seed(2)
    log_prob = F.log_softmax(torch.randn(6, 4), dim=1)
    labels = torch.tensor([0, 1, 2, 3, 1, 0])
    for reduction in ("mean", "sum", "none"):
        got = run_op("NegativeLogLikelihoodLoss",
                     [log_prob.numpy(), labels.numpy()],
                     reduction=reduction)
        want = F.nll_loss(log_prob, labels, reduction=reduction).numpy()
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5,
                                   atol=1e-6)


# ---------------------------------------------------------------------------
# RoiAlign vs a numpy spec oracle
# ---------------------------------------------------------------------------

def roi_align_oracle(x, rois, batch_idx, out_h, out_w, ratio, scale,
                     mode="avg", half_pixel=True):
    """ONNX Runtime RoiAlign semantics: samples past the 1-pixel halo
    contribute zero, everything else clamps into the image; the legacy >=1
    ROI-size clamp applies only in output_half_pixel mode; max mode maxes
    the WEIGHTED corner contributions."""
    N, C, H, W = x.shape
    out = np.zeros((len(rois), C, out_h, out_w), np.float32)
    off = 0.5 if half_pixel else 0.0

    def sample(b, yy, xx):
        if yy < -1.0 or yy > H or xx < -1.0 or xx > W:
            return [np.zeros(C, np.float32)] * 4
        yy, xx = min(max(yy, 0.0), H - 1), min(max(xx, 0.0), W - 1)
        x0, y0 = int(np.floor(xx)), int(np.floor(yy))
        wx, wy = xx - x0, yy - y0
        cs = []
        for dy, fy in ((0, 1 - wy), (1, wy)):
            for dx, fx in ((0, 1 - wx), (1, wx)):
                ix = min(x0 + dx, W - 1)
                iy = min(y0 + dy, H - 1)
                cs.append(x[b, :, iy, ix] * fx * fy)
        return cs

    for r, (roi, b) in enumerate(zip(rois, batch_idx)):
        x1, y1, x2, y2 = roi * scale - off
        rw, rh = x2 - x1, y2 - y1
        if not half_pixel:
            rw, rh = max(rw, 1.0), max(rh, 1.0)
        bw, bh = rw / out_w, rh / out_h
        for oy in range(out_h):
            for ox in range(out_w):
                corners = [sample(
                    b, y1 + (oy * ratio + sy + 0.5) * bh / ratio,
                    x1 + (ox * ratio + sx + 0.5) * bw / ratio)
                    for sy in range(ratio) for sx in range(ratio)]
                if mode == "max":
                    agg = np.max([c for cs in corners for c in cs], axis=0)
                else:
                    agg = np.mean([np.sum(cs, axis=0) for cs in corners],
                                  axis=0)
                out[r, :, oy, ox] = agg
    return out


@pytest.mark.parametrize("mode", ["avg", "max"])
@pytest.mark.parametrize("half_pixel", [True, False])
def test_roi_align_matches_oracle(mode, half_pixel):
    rs = np.random.default_rng(0)
    x = rs.normal(size=(2, 3, 10, 12)).astype(np.float32)
    # includes an edge-touching ROI (border clamp) and a tiny sub-pixel ROI
    # (exercises the mode-dependent legacy size clamp)
    rois = np.asarray([[1.0, 1.0, 8.0, 7.0], [0.0, 2.0, 11.0, 9.0],
                       [3.0, 0.5, 6.0, 4.0], [0.0, 0.0, 3.0, 2.0],
                       [2.0, 2.0, 2.4, 2.4]], np.float32)
    bidx = np.asarray([0, 1, 0, 1, 0], np.int64)
    ctm = b"half_pixel" if half_pixel else b"output_half_pixel"
    got = np.asarray(run_op("RoiAlign", [x, rois, bidx], output_height=4,
                            output_width=3, sampling_ratio=2,
                            spatial_scale=1.0, mode=mode.encode(),
                            coordinate_transformation_mode=ctm))
    want = roi_align_oracle(x, rois, bidx, 4, 3, 2, 1.0, mode=mode,
                            half_pixel=half_pixel)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# opset-18 tail vs oracles
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("align", [False, True])
def test_affine_grid_matches_torch(align):
    torch.manual_seed(13)
    theta = torch.randn(2, 2, 3)
    want = F.affine_grid(theta, (2, 3, 5, 7), align_corners=align).numpy()
    got = np.asarray(run_op("AffineGrid",
                            [theta.numpy(), np.asarray([2, 3, 5, 7])],
                            align_corners=int(align)))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    # 3D volumetric grids too
    theta3 = torch.randn(1, 3, 4)
    want3 = F.affine_grid(theta3, (1, 2, 3, 4, 5),
                          align_corners=align).numpy()
    got3 = np.asarray(run_op("AffineGrid",
                             [theta3.numpy(), np.asarray([1, 2, 3, 4, 5])],
                             align_corners=int(align)))
    np.testing.assert_allclose(got3, want3, rtol=1e-5, atol=1e-6)


def test_roi_align_max_is_weighted_corner_max():
    # constant image, sample centered in a cell (all corner weights 0.25):
    # ORT max mode yields 0.25 * value, NOT the interpolated value
    x = np.full((1, 1, 6, 6), 4.0, np.float32)
    rois = np.asarray([[1.0, 1.0, 3.0, 3.0]], np.float32)
    got = np.asarray(run_op("RoiAlign", [x, rois, np.asarray([0])],
                            output_height=1, output_width=1,
                            sampling_ratio=1, spatial_scale=1.0,
                            mode=b"max",
                            coordinate_transformation_mode=b"half_pixel"))
    np.testing.assert_allclose(got, [[[[1.0]]]], rtol=1e-6)


def test_grid_sample_size_one_dim_reflection():
    # H=1 with align_corners reflection: the reflect span is 0 — must return
    # the single row, never NaN
    x = np.arange(5, dtype=np.float32).reshape(1, 1, 1, 5)
    grid = np.stack(np.meshgrid(np.linspace(-1.2, 1.2, 4),
                                np.asarray([0.3])), axis=-1)[None].astype(
        np.float32)
    got = np.asarray(run_op("GridSample", [x, grid], mode=b"bilinear",
                            padding_mode=b"reflection", align_corners=1))
    assert np.all(np.isfinite(got)), got


def test_group_normalization_both_param_shapes():
    rs = np.random.default_rng(1)
    x = rs.normal(size=(2, 6, 4, 4)).astype(np.float32)
    G = 3
    # per-channel params (opset 21 / torch GroupNorm semantics)
    scale_c = rs.normal(size=6).astype(np.float32)
    bias_c = rs.normal(size=6).astype(np.float32)
    got = np.asarray(run_op("GroupNormalization", [x, scale_c, bias_c],
                            num_groups=G, epsilon=1e-5))
    want = F.group_norm(torch.from_numpy(x), G, torch.from_numpy(scale_c),
                        torch.from_numpy(bias_c), eps=1e-5).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    # per-group params (opset-18 shape [num_groups]) = repeat to channels
    scale_g = rs.normal(size=G).astype(np.float32)
    bias_g = rs.normal(size=G).astype(np.float32)
    got_g = np.asarray(run_op("GroupNormalization", [x, scale_g, bias_g],
                              num_groups=G, epsilon=1e-5))
    want_g = F.group_norm(torch.from_numpy(x), G,
                          torch.from_numpy(np.repeat(scale_g, 2)),
                          torch.from_numpy(np.repeat(bias_g, 2)),
                          eps=1e-5).numpy()
    np.testing.assert_allclose(got_g, want_g, rtol=1e-4, atol=1e-5)


def test_mean_variance_normalization():
    rs = np.random.default_rng(2)
    x = rs.normal(loc=3.0, scale=2.0, size=(2, 3, 4, 5)).astype(np.float32)
    got = np.asarray(run_op("MeanVarianceNormalization", [x]))
    mean = x.mean(axis=(0, 2, 3), keepdims=True)
    std = x.std(axis=(0, 2, 3), keepdims=True)
    np.testing.assert_allclose(got, (x - mean) / (std + 1e-9), rtol=1e-4,
                               atol=1e-5)


def test_bitwise_family():
    rs = np.random.default_rng(3)
    a = rs.integers(0, 255, (4, 5)).astype(np.int32)
    b = rs.integers(0, 255, (4, 5)).astype(np.int32)
    np.testing.assert_array_equal(run_op("BitwiseAnd", [a, b]), a & b)
    np.testing.assert_array_equal(run_op("BitwiseOr", [a, b]), a | b)
    np.testing.assert_array_equal(run_op("BitwiseXor", [a, b]), a ^ b)
    np.testing.assert_array_equal(run_op("BitwiseNot", [a]), ~a)


def test_dft_matches_numpy():
    rs = np.random.default_rng(12)
    x = rs.normal(size=(2, 16, 1)).astype(np.float32)
    # forward full FFT along axis 1
    got = np.asarray(run_op("DFT", [x], axis=1))
    want = np.fft.fft(x[..., 0], axis=1)
    np.testing.assert_allclose(got[..., 0] + 1j * got[..., 1], want,
                               rtol=1e-4, atol=1e-4)
    # onesided on real input
    got1 = np.asarray(run_op("DFT", [x], axis=1, onesided=1))
    want1 = np.fft.rfft(x[..., 0], axis=1)
    np.testing.assert_allclose(got1[..., 0] + 1j * got1[..., 1], want1,
                               rtol=1e-4, atol=1e-4)
    # inverse on complex input round-trips
    xc = np.stack([want.real, want.imag], axis=-1).astype(np.float32)
    back = np.asarray(run_op("DFT", [xc], axis=1, inverse=1))
    np.testing.assert_allclose(back[..., 0], x[..., 0], rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(back[..., 1], 0.0, atol=1e-4)
    # dft_length pads the axis
    got_pad = np.asarray(run_op("DFT", [x, np.asarray(32, np.int64)], axis=1))
    want_pad = np.fft.fft(np.pad(x[..., 0], ((0, 0), (0, 16))), axis=1)
    np.testing.assert_allclose(got_pad[..., 0] + 1j * got_pad[..., 1],
                               want_pad, rtol=1e-4, atol=1e-4)
    # negative axis counts against the FULL rank (component dim included):
    # axis=-2 on [B, T, 1] is the T axis
    got_neg = np.asarray(run_op("DFT", [x], axis=-2))
    np.testing.assert_allclose(got_neg, got, rtol=1e-6)
    # the component dim itself is not a transform axis; complex+onesided
    # is rejected like ORT
    with pytest.raises(NotImplementedError, match="component"):
        run_op("DFT", [x], axis=2)
    xc2 = np.stack([x[..., 0], x[..., 0]], axis=-1)
    with pytest.raises(NotImplementedError, match="onesided"):
        run_op("DFT", [xc2], axis=1, onesided=1)


def test_stft_matches_torch():
    torch.manual_seed(5)
    B, L, n_fft, hop = 2, 64, 16, 4
    sig = torch.randn(B, L)
    win = torch.hann_window(n_fft)
    want = torch.stft(sig, n_fft=n_fft, hop_length=hop, win_length=n_fft,
                      window=win, center=False, onesided=True,
                      return_complex=True)
    got = np.asarray(run_op("STFT", [sig.numpy(),
                                     np.asarray(hop, np.int64),
                                     win.numpy()], onesided=1))
    # ONNX layout [B, frames, bins, 2]; torch returns [B, bins, frames]
    got_c = got[..., 0] + 1j * got[..., 1]
    np.testing.assert_allclose(got_c.transpose(0, 2, 1), want.numpy(),
                               rtol=1e-4, atol=1e-4)
    # 3D real-input layout [B, L, 1] is the spec's canonical signal shape
    got3 = np.asarray(run_op("STFT", [sig.numpy()[..., None],
                                      np.asarray(hop, np.int64),
                                      win.numpy()], onesided=1))
    np.testing.assert_allclose(got3, got, rtol=1e-6)


def test_stft_complex_input():
    # complex [B, L, 2] layout with onesided=0: full FFT of the COMPLEX
    # signal, never the FFT of just the real part; onesided=1 on complex
    # input is rejected like ORT does
    torch.manual_seed(7)
    B, L, n_fft, hop = 1, 32, 8, 4
    sig_c = torch.randn(B, L, dtype=torch.complex64)
    win = torch.hann_window(n_fft)
    want = torch.stft(sig_c, n_fft=n_fft, hop_length=hop, win_length=n_fft,
                      window=win, center=False, onesided=False,
                      return_complex=True)
    sig_ri = np.stack([sig_c.real.numpy(), sig_c.imag.numpy()], axis=-1)
    got = np.asarray(run_op("STFT", [sig_ri, np.asarray(hop, np.int64),
                                     win.numpy()], onesided=0))
    got_c = got[..., 0] + 1j * got[..., 1]
    np.testing.assert_allclose(got_c.transpose(0, 2, 1), want.numpy(),
                               rtol=1e-4, atol=1e-4)
    with pytest.raises(NotImplementedError, match="onesided"):
        run_op("STFT", [sig_ri, np.asarray(hop, np.int64), win.numpy()],
               onesided=1)


def test_col2im_inverts_unfold():
    # fold(unfold(x)) multiplies each pixel by its patch coverage count —
    # the torch F.fold oracle, including stride/padding/dilation
    torch.manual_seed(6)
    x = torch.randn(2, 3, 8, 10)
    for kw_args in (dict(kernel_size=(3, 3), stride=(2, 2), padding=(1, 1),
                         dilation=(1, 1)),
                    dict(kernel_size=(2, 4), stride=(1, 2), padding=(0, 1),
                         dilation=(2, 1))):
        cols = F.unfold(x, **kw_args)
        want = F.fold(cols, output_size=(8, 10), **kw_args).numpy()
        k = kw_args["kernel_size"]
        p = kw_args["padding"]
        got = np.asarray(run_op(
            "Col2Im",
            [cols.numpy(), np.asarray([8, 10]), np.asarray(k)],
            strides=list(kw_args["stride"]),
            dilations=list(kw_args["dilation"]),
            pads=[p[0], p[1], p[0], p[1]]))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_random_sampling_family():
    # deterministic under a seed; statistics match the declared law
    a = np.asarray(run_op("RandomNormal", [], shape=[2000],
                          mean=3.0, scale=2.0, seed=1.0))
    b = np.asarray(run_op("RandomNormal", [], shape=[2000],
                          mean=3.0, scale=2.0, seed=1.0))
    np.testing.assert_array_equal(a, b)  # same seed, same draw
    assert abs(a.mean() - 3.0) < 0.2 and abs(a.std() - 2.0) < 0.2

    u = np.asarray(run_op("RandomUniform", [], shape=[2000],
                          low=-1.0, high=5.0, seed=2.0))
    assert u.min() >= -1.0 and u.max() <= 5.0
    assert abs(u.mean() - 2.0) < 0.3

    like = np.asarray(run_op("RandomNormalLike",
                             [np.zeros((3, 4), np.float32)], seed=3.0))
    assert like.shape == (3, 4) and like.dtype == np.float32

    p = np.full((4000,), 0.3, np.float32)
    bern = np.asarray(run_op("Bernoulli", [p], seed=4.0))
    assert set(np.unique(bern)) <= {0.0, 1.0}
    assert abs(bern.mean() - 0.3) < 0.05
    bern_bool = np.asarray(run_op("Bernoulli", [p], seed=4.0, dtype=9))
    assert bern_bool.dtype == np.bool_  # spec dtype=9 (bool) honored

    # two UNSEEDED nodes must draw independently (ORT draws per node)
    u1 = np.asarray(run_op("RandomNormalLike", [np.zeros((64,), np.float32)]))
    u2 = np.asarray(run_op("RandomNormalLike", [np.zeros((64,), np.float32)]))
    assert not np.array_equal(u1, u2)

    # multinomial: heavily peaked logits pick the peak class almost always
    logits = np.log(np.asarray([[0.01, 0.98, 0.01],
                                [0.98, 0.01, 0.01]], np.float32))
    m = np.asarray(run_op("Multinomial", [logits], sample_size=200, seed=5.0))
    assert m.shape == (2, 200) and m.dtype == np.int32
    assert (m[0] == 1).mean() > 0.9 and (m[1] == 0).mean() > 0.9


def test_center_crop_pad():
    rs = np.random.default_rng(4)
    x = rs.normal(size=(3, 8, 5)).astype(np.float32)
    # crop dim 1 (8 -> 4, center), pad dim 2 (5 -> 9, center)
    got = np.asarray(run_op("CenterCropPad", [x, np.asarray([4, 9])],
                            axes=[1, 2]))
    assert got.shape == (3, 4, 9)
    np.testing.assert_allclose(got[:, :, 2:7], x[:, 2:6, :])
    assert np.all(got[:, :, :2] == 0) and np.all(got[:, :, 7:] == 0)
    # all-axes form with odd crop: extra element comes off the end
    got2 = np.asarray(run_op("CenterCropPad", [x, np.asarray([3, 3, 3])]))
    np.testing.assert_allclose(got2, x[:, 2:5, 1:4])
