"""Fuzzing harness (reference ``core/src/test/.../fuzzing/Fuzzing.scala`` +
root ``FuzzingTest.scala``): reflect over EVERY PipelineStage in the package
and auto-derive contract tests — getter/setter fuzzing, copy semantics,
serialization round trips — so a new stage cannot ship without the basic
contracts holding (the reference asserts every Wrappable has fuzzing
coverage; here every discovered class is exercised, no opt-in)."""

import importlib
import inspect
import pkgutil

import numpy as np
import pytest

import synapseml_tpu
from synapseml_tpu.core.params import ComplexParam, Params, ServiceParam
from synapseml_tpu.core.pipeline import (
    Estimator,
    Model,
    Pipeline,
    PipelineModel,
    PipelineStage,
    Transformer,
)

_ABSTRACT_BASES = {PipelineStage, Transformer, Estimator, Model}


def _walk_stage_classes():
    classes = {}
    for modinfo in pkgutil.walk_packages(synapseml_tpu.__path__,
                                         prefix="synapseml_tpu."):
        try:
            mod = importlib.import_module(modinfo.name)
        except Exception as e:  # pragma: no cover
            raise AssertionError(f"module {modinfo.name} failed to import: {e}")
        for name, obj in vars(mod).items():
            if (inspect.isclass(obj) and issubclass(obj, PipelineStage)
                    and obj.__module__.startswith("synapseml_tpu")
                    and not name.startswith("_")
                    and obj not in _ABSTRACT_BASES):
                classes[f"{obj.__module__}.{name}"] = obj
    return classes


STAGES = _walk_stage_classes()


def test_discovery_finds_the_framework():
    """The walk sees every module family (coverage gate: a new top-level
    module whose stages fail to import breaks this)."""
    families = {name.split(".")[1] for name in STAGES}
    expected = {"automl", "causal", "cyber", "exploratory", "explainers",
                "featurize", "gbdt", "hf", "image", "io", "isolationforest",
                "nn", "onnx", "recommendation", "services", "stages", "train",
                "vw", "core"}
    missing = expected - families
    assert not missing, f"stage families with no discovered stages: {missing}"
    assert len(STAGES) > 80, f"only {len(STAGES)} stages discovered"


@pytest.mark.parametrize("name", sorted(STAGES), ids=lambda n: n.split(".", 1)[1])
def test_stage_contracts(name):
    cls = STAGES[name]
    # 1) default construction (stages must not require ctor args)
    stage = cls()
    assert stage.uid.startswith(cls.__name__)

    # 2) explain_params never crashes and mentions every param
    text = stage.explain_params()
    for pname in cls.params():
        assert pname in text

    # 3) getter/setter sugar round-trips simple params with defaults
    for pname, p in cls.params().items():
        if isinstance(p, (ComplexParam, ServiceParam)) or p.default is None:
            continue
        value = p.default
        getattr(stage, f"set_{pname}")(value)
        got = getattr(stage, f"get_{pname}")()
        assert got == p.coerce(value)  # converters may change container type

    # 4) unknown params fail fast
    with pytest.raises(KeyError):
        stage.set(definitely_not_a_param_xyz=1)

    # 5) copy() isolates param values
    stage2 = stage.copy()
    simple = [(k, v) for k, v in cls.params().items()
              if not isinstance(v, (ComplexParam, ServiceParam))
              and isinstance(v.default, (int, float))]
    if simple:
        pname = simple[0][0]
        stage2.set(**{pname: simple[0][1].default})
        stage2._param_values[pname] = "changed"
        assert stage._param_values.get(pname) != "changed"

    # 6) stage type taxonomy is coherent
    assert isinstance(stage, (Estimator, Transformer))
    if isinstance(stage, Model):
        assert isinstance(stage, Transformer)


@pytest.mark.parametrize("name", sorted(STAGES), ids=lambda n: n.split(".", 1)[1])
def test_stage_serialization_roundtrip(name, tmp_path):
    """SerializationFuzzing analog: save/load a default-constructed stage and
    compare params (complex params skipped unless picklable)."""
    cls = STAGES[name]
    stage = cls()
    path = str(tmp_path / "stage")
    stage.save(path)
    # Pipeline/PipelineModel persist stages as numbered subdirectories and
    # load through their own classmethod
    loader = cls if cls in (Pipeline, PipelineModel) else PipelineStage
    loaded = loader.load(path)
    assert type(loaded) is cls
    assert loaded.uid == stage.uid
    for pname, p in cls.params().items():
        if isinstance(p, ComplexParam):
            continue
        if stage.is_set(pname):
            got, want = loaded.get(pname), stage.get(pname)
            if isinstance(want, np.ndarray):
                np.testing.assert_array_equal(got, want)
            else:
                assert got == want, f"param {pname} changed over save/load"
