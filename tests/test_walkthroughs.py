"""Docs-as-tests for the narrative walkthroughs (VERDICT r3 next-#9): each
multi-stage walkthrough under docs/walkthroughs runs end to end — the
reference's executed-notebook tier (``docs/Explore Algorithms/`` +
``nbtest/DatabricksUtilities.scala``) as plain runnable scripts."""

import pathlib
import subprocess
import sys

import pytest

WALKTHROUGHS = sorted((pathlib.Path(__file__).parent.parent / "docs"
                       / "walkthroughs").glob("*.py"))


@pytest.mark.slow  # multi-stage: each trains + serves; full lane only
@pytest.mark.parametrize("walkthrough", WALKTHROUGHS, ids=lambda p: p.name)
def test_walkthrough_runs(walkthrough):
    # clean env like test_examples: no inherited PALLAS_AXON_POOL_IPS means
    # the axon relay backend cannot be selected in the child at all
    env = {"PATH": "/usr/bin:/bin", "HOME": "/root",
           "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": str(walkthrough.parent.parent.parent),
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    proc = subprocess.run([sys.executable, str(walkthrough)], env=env,
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, (
        f"{walkthrough.name} failed:\n{proc.stdout}\n{proc.stderr}")
