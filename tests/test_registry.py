"""Model registry + deployment plane (ISSUE 3): content-addressed artifact
store, versioned publish/resolve with aliases, hot-swap serving, canary
splits, shadow traffic, and the auto-rollback controller."""

import functools
import json
import os
import threading
import time
import urllib.error
import urllib.request
from http.server import SimpleHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from synapseml_tpu.core.params import Param
from synapseml_tpu.core.pipeline import Transformer
from synapseml_tpu.registry import (
    ArtifactStore,
    CanaryController,
    Deployment,
    IntegrityError,
    ModelRegistry,
    RegistryReadOnlyError,
)
from synapseml_tpu.registry.store import write_stream_verified

pytestmark = pytest.mark.registry


class VersionTag(Transformer):
    """Serving payload that replies with its version tag (module-level so
    worker processes can unpickle/load it by reference)."""

    tag = Param("tag", "version tag echoed in every reply", default="base")

    def _transform(self, df):
        t = self.get("tag")

        def per_part(p):
            out = dict(p)
            out["reply"] = np.asarray(
                [{"v": t, "pid": os.getpid()} for _ in p["body"]],
                dtype=object)
            return out

        return df.map_partitions(per_part)


class BrokenStage(Transformer):
    """A version that cannot serve (its warmup must block the swap)."""

    def _transform(self, df):
        raise RuntimeError("this version is broken on purpose")


# ---------------------------------------------------------------------------
# store
# ---------------------------------------------------------------------------

def test_write_stream_verified_atomic(tmp_path):
    import io

    dest = tmp_path / "out.bin"
    digest = write_stream_verified(io.BytesIO(b"payload"), str(dest))
    assert dest.read_bytes() == b"payload"
    import hashlib

    assert digest == hashlib.sha256(b"payload").hexdigest()
    # mismatch: destination never appears, no temp litter
    bad = tmp_path / "bad.bin"
    with pytest.raises(IntegrityError, match="sha256 mismatch"):
        write_stream_verified(io.BytesIO(b"payload"), str(bad), "0" * 64)
    assert not bad.exists()
    assert [p.name for p in tmp_path.iterdir()] == ["out.bin"]


def test_blob_store_dedup_and_integrity(tmp_path):
    store = ArtifactStore(str(tmp_path))
    d1 = store.put_blob_bytes(b"weights")
    d2 = store.put_blob_bytes(b"weights")
    assert d1 == d2 and store.get_blob(d1) == b"weights"
    # silent corruption surfaces as IntegrityError, not wrong bytes
    with open(store.blob_path(d1), "wb") as f:
        f.write(b"tampered")
    with pytest.raises(IntegrityError, match="corrupt"):
        store.get_blob(d1)
    with pytest.raises(IntegrityError):
        store.materialize_blob(d1, str(tmp_path / "copy"))
    assert not (tmp_path / "copy").exists()


def test_alias_pointer_swap_is_atomic_file(tmp_path):
    store = ArtifactStore(str(tmp_path))
    store.write_alias("m", "prod", "v1")
    assert store.read_alias("m", "prod") == "v1"
    store.write_alias("m", "prod", "v2")  # swap, not append
    assert store.read_alias("m", "prod") == "v2"
    assert store.list_aliases("m") == {"prod": "v2"}
    assert store.read_alias("m", "nope") is None


# ---------------------------------------------------------------------------
# registry: publish / resolve / versions / aliases
# ---------------------------------------------------------------------------

def test_publish_resolve_manifest_roundtrip(tmp_path):
    reg = ModelRegistry(str(tmp_path / "reg"))
    pub = reg.publish("echo", VersionTag(tag="v1"),
                      metrics={"acc": 0.91, "p95_ms": 1.2})
    assert pub.version == "v1"
    m = pub.manifest
    assert m["stages"] == [f"{VersionTag.__module__}.VersionTag"]
    assert len(m["param_schema_sha256"]) == 64
    assert m["metrics"]["acc"] == 0.91
    assert m["framework"]["numpy"]
    assert m["files"] and all(len(e["sha256"]) == 64 for e in m["files"])

    res = reg.resolve("echo", "latest")
    assert res.version == "v1"
    assert isinstance(res.stage, VersionTag)
    assert res.stage.get("tag") == "v1"
    # same params -> same schema hash across republish
    pub2 = reg.publish("echo", VersionTag(tag="v2"))
    assert pub2.version == "v2"
    assert (pub2.manifest["param_schema_sha256"]
            == m["param_schema_sha256"])


def test_manifest_signature_tamper_detected(tmp_path):
    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish("echo", VersionTag(tag="v1"))
    store = ArtifactStore(str(tmp_path / "reg"))
    path = store.manifest_path("echo", "v1")
    with open(path) as f:
        manifest = json.load(f)
    manifest["metrics"] = {"acc": 1.0}  # juice the publish-time metrics
    with open(path, "w") as f:
        json.dump(manifest, f)
    with pytest.raises(IntegrityError, match="signature"):
        reg.manifest("echo", "v1")


def test_versions_aliases_pin(tmp_path):
    reg = ModelRegistry(str(tmp_path / "reg"))
    for _ in range(3):
        reg.publish("echo", VersionTag(tag="x"))
    assert reg.list_versions("echo") == ["v1", "v2", "v3"]
    assert reg.aliases("echo") == {"latest": "v3"}
    assert reg.pin("echo", "prod", "v2") == "v2"
    assert reg.resolve("echo", "prod").version == "v2"
    # pin through another alias resolves to its concrete version
    reg.pin("echo", "canary", "latest")
    assert reg.alias_target("echo", "canary") == "v3"
    with pytest.raises(KeyError):
        reg.resolve("echo", "v99")
    with pytest.raises(FileExistsError):  # versions are immutable
        reg.publish("echo", VersionTag(), version="v2")


def test_unsafe_names_rejected(tmp_path):
    reg = ModelRegistry(str(tmp_path / "reg"))
    for bad in ("../evil", "a/b", ".hidden", ""):
        with pytest.raises(ValueError, match="unsafe"):
            reg.publish(bad, VersionTag())
    reg.publish("ok", VersionTag())
    with pytest.raises(ValueError, match="unsafe"):
        reg.pin("ok", "../../alias", "v1")


def test_remote_registry_over_http(tmp_path):
    root = str(tmp_path / "reg")
    reg = ModelRegistry(root)
    reg.publish("echo", VersionTag(tag="v1"))
    reg.publish("echo", VersionTag(tag="v2"))
    reg.pin("echo", "prod", "v1")

    handler = functools.partial(SimpleHTTPRequestHandler, directory=root)
    srv = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        url = f"http://127.0.0.1:{srv.server_address[1]}"
        remote = ModelRegistry(url, cache_dir=str(tmp_path / "cache"))
        assert remote.list_versions("echo") == ["v1", "v2"]
        assert remote.alias_target("echo", "prod") == "v1"
        res = remote.resolve("echo", "prod")
        assert res.version == "v1" and res.stage.get("tag") == "v1"
        # remote is read-only
        with pytest.raises(RegistryReadOnlyError):
            remote.publish("echo", VersionTag())
        with pytest.raises(RegistryReadOnlyError):
            remote.pin("echo", "prod", "v2")
        # a corrupted blob on the server cannot materialize
        manifest = remote.manifest("echo", "v2")
        victim = manifest["files"][0]["sha256"]
        with open(os.path.join(root, "blobs", victim), "ab") as f:
            f.write(b"junk")
        with pytest.raises((IntegrityError, RuntimeError)):
            remote.resolve("echo", "v2")
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# hot swap on one worker
# ---------------------------------------------------------------------------

def _post(url, data):
    req = urllib.request.Request(url, data=json.dumps(data).encode(),
                                 method="POST")
    try:
        with urllib.request.urlopen(req, timeout=15) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_hot_swap_zero_dropped_requests(tmp_path):
    from synapseml_tpu.io.serving import serve_pipeline

    root = str(tmp_path / "reg")
    reg = ModelRegistry(root)
    reg.publish("echo", VersionTag(tag="v1"), version="v1")
    reg.publish("echo", VersionTag(tag="v2"), version="v2")
    reg.publish("echo", BrokenStage(), version="v3")

    srv = serve_pipeline(VersionTag(tag="v1"), batch_interval_ms=0,
                         version="v1")
    base = f"http://{srv.host}:{srv.port}"
    try:
        assert _post(base + "/", {"i": 0}) == (200, {"v": "v1",
                                                     "pid": os.getpid()})
        # hammer while swapping: no request may fail across the swap
        results, stop = [], threading.Event()

        def pound():
            i = 0
            while not stop.is_set():
                results.append(_post(base + "/", {"i": i}))
                i += 1

        t = threading.Thread(target=pound)
        t.start()
        try:
            status, reply = _post(base + "/admin/load",
                                  {"registry": root, "model": "echo",
                                   "ref": "v2", "warmup": [{"i": -1}]})
        finally:
            time.sleep(0.2)  # a few post-swap requests land in results
            stop.set()
            t.join(timeout=30)
        assert status == 200 and reply["ok"] and reply["previous"] == "v1"
        assert reply["warmup_rows"] == 1
        assert results and all(s == 200 for s, _ in results)
        tags = {b["v"] for _, b in results}
        assert tags <= {"v1", "v2"} and "v2" in tags

        with urllib.request.urlopen(base + "/admin/version",
                                    timeout=10) as r:
            assert json.loads(r.read())["version"] == "v2"

        # a broken version fails its warmup batch and is NOT swapped in
        status, reply = _post(base + "/admin/load",
                              {"registry": root, "model": "echo",
                               "ref": "v3", "warmup": [{"i": -1}]})
        assert status == 409 and "broken on purpose" in reply["error"]
        assert _post(base + "/", {"i": 1})[1]["v"] == "v2"  # untouched
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# auto-rollback controller (deterministic unit test, no processes)
# ---------------------------------------------------------------------------

class _FakeFront:
    def __init__(self):
        self.stats = {}
        self.split = None
        self.shadow_cleared = False

    def version_stats(self):
        return {v: dict(s) for v, s in self.stats.items()}

    def set_traffic_split(self, split):
        self.split = split

    def clear_shadow(self):
        self.shadow_cleared = True


def test_canary_controller_trips_on_error_rate(tmp_path):
    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish("echo", VersionTag(tag="v1"), version="v1")
    reg.publish("echo", VersionTag(tag="v2"), version="v2")
    reg.pin("echo", "prod", "v2")  # the rollout moved prod; rollback must flip it

    front = _FakeFront()
    ctl = CanaryController(front, stable="v1", canary="v2", registry=reg,
                           model="echo", error_rate_threshold=0.5,
                           window=10, min_samples=4)
    front.stats = {"v1": {"ok": 50, "err": 0},
                   "v2": {"ok": 5, "err": 0}}
    assert ctl.check_once() is None  # healthy canary: no trip
    front.stats["v2"] = {"ok": 5, "err": 1}
    assert ctl.check_once() is None  # 1/6 in the window: under threshold
    front.stats["v2"] = {"ok": 5, "err": 6}
    reason = ctl.check_once()
    assert reason is not None and "error rate" in reason
    ctl._trip(reason)
    assert ctl.rolled_back
    assert front.split == {"v1": 1.0} and front.shadow_cleared
    assert reg.alias_target("echo", "prod") == "v1"  # alias flipped back


def test_canary_controller_ignores_history_before_start():
    """A long-lived front carries counters from EARLIER rollouts of the
    same version; a fresh controller must baseline against them, not
    replay old failures into its new breaker (which would roll back a
    healthy re-canary instantly)."""
    front = _FakeFront()
    front.stats = {"v2": {"ok": 0, "err": 50}}  # last rollout's wreckage
    ctl = CanaryController(front, stable="v1", canary="v2",
                           error_rate_threshold=0.5, window=10,
                           min_samples=2)
    assert ctl.check_once() is None  # history not replayed
    front.stats["v2"] = {"ok": 1, "err": 53}  # 3 NEW errors, 1 new ok
    reason = ctl.check_once()
    assert reason is not None and "error rate" in reason


def test_canary_controller_trips_on_p95_regression():
    front = _FakeFront()
    ctl = CanaryController(front, stable="v1", canary="v2",
                           error_rate_threshold=1.1,  # errors can't trip
                           p95_regression_factor=2.0,
                           min_latency_samples=10)
    front.stats = {
        "v1": {"ok": 100, "err": 0, "p95_ms": 2.0, "n_latencies": 100},
        "v2": {"ok": 20, "err": 0, "p95_ms": 3.0, "n_latencies": 20},
    }
    assert ctl.check_once() is None  # 1.5x: within budget
    front.stats["v2"] = {"ok": 40, "err": 0, "p95_ms": 9.0,
                         "n_latencies": 40}
    reason = ctl.check_once()
    assert reason is not None and "p95" in reason


# ---------------------------------------------------------------------------
# acceptance: publish -> serve -> canary -> metrics -> fault -> auto-rollback
# ---------------------------------------------------------------------------

@pytest.mark.chaos(timeout_s=110)
def test_e2e_canary_rollout_with_autorollback(tmp_path):
    """The ISSUE-3 acceptance path: publish v1+v2, serve v1 on a 2-worker
    DistributedServing, hot-swap one worker to a 90/10 canary of v2 with
    zero failed requests during the swap, see per-version series under
    ``GET /metrics``, then fault-inject v2 (PR-1 FaultPlan) and watch the
    auto-rollback controller flip ``prod`` back to v1."""
    from synapseml_tpu.core.faults import FaultSpec, inject_faults
    from synapseml_tpu.io.distributed_serving import serve_pipeline_distributed

    root = str(tmp_path / "reg")
    reg = ModelRegistry(root)
    reg.publish("echo", VersionTag(tag="v1"), version="v1")
    reg.publish("echo", VersionTag(tag="v2"), version="v2")
    reg.pin("echo", "prod", "v2")  # eager promote the rollback must undo

    handle = serve_pipeline_distributed(VersionTag(tag="v1"), num_workers=2,
                                        batch_interval_ms=0, version="v1")
    try:
        def call(i):
            status, body = _post(handle.address, {"i": i})
            return status, body

        for i in range(6):
            status, body = call(i)
            assert status == 200 and body["v"] == "v1"

        dep = Deployment(handle, reg, "echo", warmup=[{"i": -1}])
        handle.front._split_rng.seed(1234)

        # swap under fire: zero dropped requests while one worker hot-swaps
        results, stop = [], threading.Event()

        def pound():
            i = 0
            while not stop.is_set():
                results.append(call(i)[0])
                i += 1

        t = threading.Thread(target=pound)
        t.start()
        try:
            dep.canary("v2", weight=0.1, num_workers=1)
        finally:
            stop.set()
            t.join(timeout=30)
        assert results and all(s == 200 for s in results)
        assert reg.alias_target("echo", "canary") == "v2"
        assert handle.front.traffic_split() == {"v1": 0.9, "v2": 0.1}

        # the 90/10 split routes to both versions
        replies = [call(i)[1]["v"] for i in range(80)]
        assert set(replies) == {"v1", "v2"}
        assert replies.count("v1") > replies.count("v2")

        # shadow traffic: duplicates of stable requests hit the canary
        handle.front.set_shadow("v2")
        for i in range(20):
            call(i)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if any(s.get("shadow_ok", 0) + s.get("shadow_err", 0) > 0
                   for s in handle.front.version_stats().values()):
                break
            time.sleep(0.05)
        handle.front.clear_shadow()
        stats = handle.front.version_stats()
        assert stats["v2"].get("shadow_ok", 0) >= 1

        # per-version series on the front's /metrics (PR-2 registry)
        with urllib.request.urlopen(handle.address + "/metrics",
                                    timeout=10) as r:
            text = r.read().decode()
        assert "synapseml_route_version_requests_total{" in text
        assert 'version="v2"' in text and 'version="v1"' in text
        assert "synapseml_route_shadow_requests_total{" in text

        # fault-inject the canary worker; every request keeps succeeding
        # (stable fallback) while the controller watches v2 fail
        controller = CanaryController(
            handle.front, stable="v1", canary="v2", registry=reg,
            model="echo", error_rate_threshold=0.5, window=4,
            min_samples=2, interval_s=0.05).start()
        (v2_worker,) = [w for w in handle.registry.workers()
                        if w.get("version") == "v2"]
        key = f"{v2_worker['host']}:{v2_worker['port']}"
        try:
            with inject_faults([FaultSpec(kind="connection_error",
                                          match=key,
                                          planes=("distributed_serving",))]):
                deadline = time.monotonic() + 45
                i = 0
                while (time.monotonic() < deadline
                       and not controller.rolled_back):
                    status, _ = call(i)
                    assert status == 200  # zero dropped requests throughout
                    i += 1
        finally:
            controller.stop()
        assert controller.rolled_back, "controller never tripped"
        assert "error rate" in (controller.reason or "")
        # the alias flipped back and traffic snapped to stable
        assert reg.alias_target("echo", "prod") == "v1"
        assert handle.front.traffic_split() == {"v1": 1.0}
        assert call(0)[1]["v"] == "v1"
    finally:
        handle.stop()
