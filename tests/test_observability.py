"""Unified observability plane (core/observability.py): metrics registry,
trace spans, traceparent propagation, Prometheus + Chrome exporters, and the
wired hot paths (serving, routing front, stage telemetry).

Reference: ``SynapseMLLogging.scala`` stage events + LightGBM
``TaskInstrumentationMeasures`` — here unified into one registry/tracer.
All offline under JAX_PLATFORMS=cpu.
"""

import json
import re
import threading
import time
import urllib.request

import numpy as np
import pytest

from synapseml_tpu.core import observability as obs
from synapseml_tpu.core.dataframe import DataFrame
from synapseml_tpu.core.instrumentation import InstrumentationMeasures
from synapseml_tpu.core.logging import scrub
from synapseml_tpu.core.pipeline import Estimator, Model, Pipeline, Transformer


@pytest.fixture(autouse=True)
def fresh_plane():
    """Each test gets a clean global registry + tracer (the plane is
    process-wide by design; tests must not see each other's series)."""
    obs.reset_registry()
    obs.reset_tracer()
    yield
    obs.reset_registry()
    obs.reset_tracer()


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_counter_gauge_histogram_basics():
    reg = obs.get_registry()
    c = reg.counter("t_total", "help", ("plane",))
    c.inc(plane="http")
    c.inc(2, plane="http")
    assert c.labels(plane="http").value == 3
    with pytest.raises(ValueError):
        c.labels(plane="http").inc(-1)  # counters only go up

    g = reg.gauge("t_gauge", "help")
    g.labels().set(7.0)
    g.labels().inc(1.5)
    assert g.labels().value == 8.5

    h = reg.histogram("t_ms", "help", buckets=(1, 10, 100))
    for v in (0.5, 5, 50, 500):
        h.observe(v)
    snap = h.labels().snapshot()
    assert snap["count"] == 4 and snap["sum"] == 555.5
    assert snap["buckets"]["+Inf"] == 1


def test_histogram_bucket_mismatch_raises():
    reg = obs.get_registry()
    reg.histogram("b_ms", "x", buckets=(1, 10, 100))
    with pytest.raises(ValueError):
        reg.histogram("b_ms", "x", buckets=(1000, 60000))
    # omitting buckets means "whatever the family has" — no raise
    assert reg.histogram("b_ms", "x").buckets == (1.0, 10.0, 100.0)


def test_registry_rejects_kind_and_label_mismatch():
    reg = obs.get_registry()
    reg.counter("dup_total", "x", ("a",))
    with pytest.raises(ValueError):
        reg.gauge("dup_total", "x", ("a",))
    with pytest.raises(ValueError):
        reg.counter("dup_total", "x", ("b",))  # label-set drift
    # same spec is idempotent (get-or-create)
    assert reg.counter("dup_total", "x", ("a",)) is reg.counter(
        "dup_total", "x", ("a",))
    with pytest.raises(ValueError):
        reg.counter("dup_total", "x", ("a",)).labels(wrong="v")


def test_exposition_parses_and_counters_are_monotonic():
    """Parse the text output like a Prometheus scraper would: TYPE lines,
    sample lines, cumulative bucket ordering, counter monotonicity across
    two scrapes (the ISSUE acceptance check)."""
    reg = obs.get_registry()
    c = reg.counter("req_total", "requests", ("status",))
    h = reg.histogram("lat_ms", "latency", buckets=(1, 10, 100))
    c.inc(status="2xx")
    h.observe(5)

    def parse(text):
        samples = {}
        for line in text.splitlines():
            if not line or line.startswith("#"):
                continue
            m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? (\S+)$",
                         line)
            assert m, f"unparseable exposition line: {line!r}"
            samples[m.group(1) + (m.group(2) or "")] = float(m.group(3))
        return samples

    first = parse(reg.exposition())
    assert first['req_total{status="2xx"}'] == 1
    assert first['lat_ms_count'] == 1 and first['lat_ms_sum'] == 5
    # buckets are CUMULATIVE and ordered
    assert first['lat_ms_bucket{le="1"}'] == 0
    assert first['lat_ms_bucket{le="10"}'] == 1
    assert first['lat_ms_bucket{le="+Inf"}'] == 1

    c.inc(status="2xx")
    h.observe(50)
    second = parse(reg.exposition())
    for key, v in first.items():
        if "_total" in key or "_count" in key or "_bucket" in key:
            assert second[key] >= v, f"counter {key} went backwards"
    # TYPE metadata present for every family
    text = reg.exposition()
    assert "# TYPE req_total counter" in text
    assert "# TYPE lat_ms histogram" in text


def test_histogram_snapshot_quantiles():
    reg = obs.get_registry()
    h = reg.histogram("q_ms", "q", buckets=(10, 20, 50, 100))
    for v in [5] * 50 + [15] * 40 + [80] * 10:
        h.observe(v)
    snap = h.labels().snapshot()
    assert snap["count"] == 100
    assert 0 < snap["p50"] <= 10          # 50th obs is in the first bucket
    assert 10 < snap["p95"] <= 100
    assert snap["p99"] <= 100
    empty = reg.histogram("e_ms", "e", buckets=(1,)).labels().snapshot()
    assert empty["p50"] is None and empty["count"] == 0


def test_registry_thread_safety_under_contention():
    reg = obs.get_registry()
    c = reg.counter("hammer_total", "x", ("t",))
    h = reg.histogram("hammer_ms", "x")

    def work(tid):
        for i in range(500):
            c.inc(t=str(tid % 2))
            h.observe(float(i % 7))

    threads = [threading.Thread(target=work, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = sum(c.labels(t=s).value for s in ("0", "1"))
    assert total == 8 * 500
    assert h.labels().snapshot()["count"] == 8 * 500


def test_collector_samples_and_resilience_adapter():
    from synapseml_tpu.core.resilience import (reset_resilience_measures,
                                               resilience_measures)

    reset_resilience_measures()
    resilience_measures("http").count("retry", 3)
    text = obs.get_registry().exposition()
    assert 'synapseml_resilience_retry_total{plane="http"} 3' in text
    # a crashing collector must not take down the endpoint
    obs.get_registry().register_collector(lambda: 1 / 0)
    assert "synapseml_resilience_retry_total" in obs.get_registry().exposition()
    reset_resilience_measures()


def test_register_instrumentation_exports_phases_and_counts():
    m = InstrumentationMeasures()
    with m.measure("binning"):
        pass
    m.count("iterations", 4)
    obs.register_instrumentation("synapseml_gbdt", m, {"uid": "b1"})
    snap = obs.get_registry().snapshot()
    assert snap['synapseml_gbdt_iterations_total{uid="b1"}'] == 4
    assert 'synapseml_gbdt_binning_ms{uid="b1"}' in snap


# ---------------------------------------------------------------------------
# instrumentation thread-safety (satellite)
# ---------------------------------------------------------------------------

def test_instrumentation_measures_concurrent_mutation():
    """measure()/mark() used to mutate without the lock count() takes —
    hammer all mutators while snapshotting; totals must be exact."""
    m = InstrumentationMeasures()
    stop = threading.Event()

    def mutate(i):
        for k in range(300):
            with m.measure(f"phase{i % 3}"):
                pass
            m.mark(f"mark{i % 3}")
            m.count("events")

    def snapshot():
        while not stop.is_set():
            d = m.to_dict()
            assert isinstance(d.get("total_ms"), float)

    reader = threading.Thread(target=snapshot)
    reader.start()
    threads = [threading.Thread(target=mutate, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    reader.join()
    assert m.to_dict()["events_count"] == 6 * 300


# ---------------------------------------------------------------------------
# scrubber (satellite)
# ---------------------------------------------------------------------------

def test_scrub_query_string_style_still_works():
    assert scrub("https://x/y?sig=ABC123&other=1") == \
        "https://x/y?sig=####&other=1"
    assert scrub("Authorization: Bearer abc.DEF-123") == \
        "Authorization: Bearer ####"


def test_scrub_json_style_payloads():
    """Regression: JSON key/value secrets passed through unscrubbed."""
    assert scrub('{"apiKey": "abc123"}') == '{"apiKey": "####"}'
    assert scrub('{"Ocp-Apim-Subscription-Key": "deadbeef"}') == \
        '{"Ocp-Apim-Subscription-Key": "####"}'
    assert scrub('{"password": "hunter2", "user": "bob"}') == \
        '{"password": "####", "user": "bob"}'
    # non-string secret values are masked too
    assert scrub('{"apiKey": 12345}') == '{"apiKey": "####"}'
    # escaped quotes inside the secret cannot leak a suffix
    assert scrub('{"secret": "a\\"b"}') == '{"secret": "####"}'
    # innocent keys survive
    assert scrub('{"count": 3, "className": "X"}') == \
        '{"count": 3, "className": "X"}'


def test_log_stage_event_scrubs_json_payload_for_sinks():
    from synapseml_tpu.core.logging import (add_telemetry_sink,
                                            log_stage_event,
                                            remove_telemetry_sink)

    seen = []
    add_telemetry_sink(seen.append)
    try:
        log_stage_event({"uid": "u1", "apiKey": "supersecret",
                         "url": "https://x?sig=TOPSECRET"})
    finally:
        remove_telemetry_sink(seen.append)
    assert seen and seen[0]["apiKey"] == "####"
    assert "TOPSECRET" not in json.dumps(seen[0])


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------

def test_span_nesting_and_context_stack():
    t = obs.get_tracer()
    assert t.current_span() is None
    with t.span("outer") as outer:
        assert t.current_span() is outer
        with t.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
        assert t.current_span() is outer
    assert t.current_span() is None
    done = {s.name: s for s in t.finished_spans()}
    assert set(done) == {"outer", "inner"}
    assert done["inner"].duration_ms is not None
    assert done["outer"].duration_ms >= done["inner"].duration_ms


def test_span_error_status():
    t = obs.get_tracer()
    with pytest.raises(RuntimeError):
        with t.span("boom"):
            raise RuntimeError("nope")
    s = t.finished_spans()[-1]
    assert s.status == "error" and "RuntimeError" in s.attributes["error"]


def test_traceparent_roundtrip_and_malformed():
    t = obs.get_tracer()
    with t.span("root") as root:
        headers = t.inject({})
    ctx = obs.parse_traceparent(headers["traceparent"])
    assert ctx.trace_id == root.trace_id and ctx.span_id == root.span_id
    for bad in (None, "", "garbage", "00-zz-yy-01", "00-" + "0" * 32 +
                "-" + "1" * 16 + "-01", "00-abc-def-01"):
        assert obs.parse_traceparent(bad) is None
    # case-insensitive header extraction
    assert obs.extract_context(
        {"TraceParent": headers["traceparent"]}).trace_id == root.trace_id


def test_remote_parent_pins_trace():
    t = obs.get_tracer()
    remote = obs.SpanContext("ab" * 16, "cd" * 8)
    with t.span("handler", parent=remote) as s:
        assert s.trace_id == remote.trace_id
        assert s.parent_id == remote.span_id


def test_tracer_ring_buffer_bounded():
    t = obs.reset_tracer(max_spans=10)
    for i in range(25):
        with t.span(f"s{i}"):
            pass
    names = [s.name for s in t.finished_spans()]
    assert len(names) == 10 and names[-1] == "s24" and names[0] == "s15"


def test_chrome_trace_export(tmp_path):
    t = obs.get_tracer()
    with t.span("parent", {"k": "v"}):
        with t.span("child"):
            pass
    path = obs.export_chrome_trace(str(tmp_path / "trace.json"))
    with open(path) as f:
        doc = json.load(f)
    events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in events} == {"parent", "child"}
    for e in events:
        assert e["ts"] > 0 and e["dur"] >= 0 and "trace_id" in e["args"]
    assert any(e["ph"] == "M" for e in doc["traceEvents"])  # process meta


# ---------------------------------------------------------------------------
# stage telemetry -> plane (histogram + span tree)
# ---------------------------------------------------------------------------

class _AddOne(Transformer):
    def _transform(self, df):
        return df.with_column("x", lambda p: p["x"] + 1)


class _FitCount(Estimator):
    def _fit(self, df):
        return _AddOne()


def _df():
    return DataFrame([{"x": np.arange(4, dtype=np.float32)}])


def test_stage_verbs_feed_histogram_and_spans():
    pipe = Pipeline(stages=[_FitCount(), _AddOne()])
    model = pipe.fit(_df())
    model.transform(_df())
    snap = obs.get_registry().snapshot()
    fit_series = [k for k in snap
                  if k.startswith("synapseml_stage_duration_ms")
                  and 'method="fit"' in k]
    assert any("Pipeline" in k for k in fit_series)
    assert any("_FitCount" in k for k in fit_series)
    ok = [k for k in snap if k.startswith("synapseml_stage_events_total")
          and 'status="ok"' in k]
    assert ok, snap.keys()


def test_pipeline_fit_renders_as_span_tree():
    """Pipeline.fit -> pipeline.stage[i] -> Stage.fit: depth >= 3, one
    trace."""
    Pipeline(stages=[_FitCount(), _AddOne()]).fit(_df())
    spans = {s.span_id: s for s in obs.get_tracer().finished_spans()}
    roots = [s for s in spans.values() if s.name == "Pipeline.fit"]
    assert len(roots) == 1
    root = roots[0]
    assert all(s.trace_id == root.trace_id for s in spans.values())

    def depth(s):
        if s.parent_id is None:
            return 1
        parent = spans.get(s.parent_id)
        return 1 + (depth(parent) if parent else 1)

    assert max(depth(s) for s in spans.values()) >= 3
    slots = [s for s in spans.values() if s.name.startswith("pipeline.stage")]
    assert {s.parent_id for s in slots} == {root.span_id}


def test_stage_error_counted():
    class _Boom(Transformer):
        def _transform(self, df):
            raise ValueError("x")

    with pytest.raises(ValueError):
        _Boom().transform(_df())
    snap = obs.get_registry().snapshot()
    errs = [k for k in snap if k.startswith("synapseml_stage_events_total")
            and 'status="error"' in k and "_Boom" in k]
    assert errs
    assert obs.get_tracer().finished_spans()[-1].status == "error"


# ---------------------------------------------------------------------------
# static check: every public stage routes through StageTelemetry (satellite)
# ---------------------------------------------------------------------------

def test_every_stage_routes_verbs_through_stage_telemetry():
    """No silent unobserved stages: a stage overriding fit()/transform()
    instead of _fit()/_transform() would bypass log_verb (and with it the
    stage histogram, the span tree, and the JSON stage events)."""
    from synapseml_tpu.codegen import discover_stages
    from synapseml_tpu.core.logging import StageTelemetry

    stages = discover_stages()
    assert len(stages) > 50  # the walk found the real registry
    offenders = []
    for name, cls in stages.items():
        if not issubclass(cls, StageTelemetry):
            offenders.append(f"{name}: not a StageTelemetry")
            continue
        if issubclass(cls, Estimator) and cls.fit is not Estimator.fit:
            offenders.append(f"{name}: overrides fit() — bypasses log_verb")
        if issubclass(cls, Transformer) and \
                cls.transform is not Transformer.transform:
            offenders.append(
                f"{name}: overrides transform() — bypasses log_verb")
    assert not offenders, "\n".join(offenders)


# ---------------------------------------------------------------------------
# serving endpoints + end-to-end distributed trace
# ---------------------------------------------------------------------------

class EchoObs(Transformer):
    """Picklable echo pipeline for worker processes."""

    def _transform(self, df):
        import os

        def per_part(p):
            out = dict(p)
            out["reply"] = np.asarray(
                [{"ok": True, "pid": os.getpid()}] * len(p["body"]),
                dtype=object)
            return out

        return df.map_partitions(per_part)


def test_serving_server_metrics_and_trace_endpoints():
    from synapseml_tpu.io.serving import serve_pipeline

    srv = serve_pipeline(EchoObs(), batch_interval_ms=0)
    try:
        t = obs.get_tracer()
        with t.span("client.request") as cs:
            req = urllib.request.Request(
                srv.address + "/predict", data=b'{"a": 1}',
                headers=t.inject({}), method="POST")
            with urllib.request.urlopen(req, timeout=30) as r:
                assert json.loads(r.read())["ok"] is True
        with urllib.request.urlopen(srv.address + "/metrics", timeout=30) as r:
            assert r.headers.get("Content-Type", "").startswith("text/plain")
            text = r.read().decode()
        assert "synapseml_serving_request_duration_ms_bucket" in text
        assert "synapseml_serving_queue_wait_ms" in text
        assert 'synapseml_serving_requests_total{method="POST",status="2xx"} 1' \
            in text
        with urllib.request.urlopen(srv.address + "/trace", timeout=30) as r:
            spans = json.loads(r.read())
        served = [s for s in spans if s["name"] == "serving.request"]
        assert served and served[0]["trace_id"] == cs.trace_id
        assert served[0]["parent_id"] == cs.span_id
    finally:
        srv.stop()


@pytest.mark.chaos(timeout_s=120)
def test_distributed_trace_stitches_across_processes():
    """THE acceptance check: one RoutingFront request over 2 local worker
    processes -> one trace (shared trace_id, >= 3 spans, >= 2 pids), valid
    Chrome trace-event JSON, and /metrics on front AND worker serving
    Prometheus text with latency buckets + breaker gauges."""
    from synapseml_tpu.io.distributed_serving import (
        collect_distributed_trace, serve_pipeline_distributed)

    handle = serve_pipeline_distributed(EchoObs(), num_workers=2,
                                        batch_interval_ms=0)
    try:
        t = obs.get_tracer()
        with t.span("client.request") as cs:
            req = urllib.request.Request(
                handle.address + "/predict", data=b'{"q": 1}',
                headers=t.inject({}), method="POST")
            with urllib.request.urlopen(req, timeout=30) as r:
                assert json.loads(r.read())["ok"] is True

        # ---- stitched trace ----
        # the front's route.request span finishes a hair AFTER the reply
        # bytes reach the client (post-reply accounting runs inside the
        # span), so /trace polled immediately can race it on a loaded
        # machine — poll briefly for the settled span set
        import time as _time

        deadline = _time.monotonic() + 5.0
        while True:
            spans = collect_distributed_trace(handle.address)
            ours = [s for s in spans if s["trace_id"] == cs.trace_id]
            names = {s["name"] for s in ours}
            if {"client.request", "route.request",
                    "serving.request"} <= names \
                    or _time.monotonic() >= deadline:
                break
            _time.sleep(0.05)
        assert {"client.request", "route.request", "serving.request"} <= names
        assert len(ours) >= 3
        assert len({s["pid"] for s in ours}) >= 2  # multi-process
        by_id = {s["span_id"]: s for s in ours}
        route = next(s for s in ours if s["name"] == "route.request")
        serving = next(s for s in ours if s["name"] == "serving.request")
        assert route["parent_id"] == cs.span_id
        assert serving["parent_id"] == route["span_id"]
        assert by_id  # parent links resolve within the stitched set

        # ---- valid Chrome trace-event JSON ----
        doc = json.loads(json.dumps(obs.chrome_trace_events(ours)))
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert {e["name"] for e in xs} >= {"client.request", "route.request",
                                           "serving.request"}
        assert all(isinstance(e["ts"], float) and e["dur"] >= 0 for e in xs)

        # ---- front /metrics ----
        with urllib.request.urlopen(handle.address + "/metrics",
                                    timeout=30) as r:
            front_text = r.read().decode()
        assert "synapseml_route_request_duration_ms_bucket" in front_text
        assert 'synapseml_breaker_state{' in front_text
        assert "synapseml_route_pick_ms" in front_text

        # ---- worker /metrics (hit every worker so both have served) ----
        with urllib.request.urlopen(handle.address + "/routes",
                                    timeout=30) as r:
            table = json.loads(r.read())
        assert len(table) == 2
        for _ in range(4):  # round-robin touches both workers
            urllib.request.urlopen(urllib.request.Request(
                handle.address + "/predict", data=b'{}', method="POST"),
                timeout=30).read()
        for w in table:
            url = f"http://{w['host']}:{w['port']}/metrics"
            with urllib.request.urlopen(url, timeout=30) as r:
                wtext = r.read().decode()
            assert "synapseml_serving_request_duration_ms_bucket" in wtext, \
                f"worker {w} /metrics missing request histogram"
    finally:
        handle.stop()


def test_front_forwards_post_to_metrics_path():
    """/metrics and /trace are GET-only reserved names on the front: a POST
    to a pipeline path literally named /metrics must still forward."""
    from synapseml_tpu.io.distributed_serving import RoutingFront
    from synapseml_tpu.io.serving import serve_pipeline

    srv = serve_pipeline(EchoObs(), batch_interval_ms=0)
    front = RoutingFront([{"host": srv.host, "port": srv.port, "pid": 1}],
                         timeout_s=10)
    try:
        req = urllib.request.Request(front.address + "/metrics", data=b"{}",
                                     method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            assert json.loads(r.read())["ok"] is True  # worker reply, not
        with urllib.request.urlopen(front.address + "/metrics",  # exposition
                                    timeout=30) as r:
            assert r.read().startswith(b"# HELP")
    finally:
        front.close()
        srv.stop()


def test_routing_front_breaker_gauge_reports_open():
    """A worker that fails a connect shows up as breaker_state=2 (open) in
    the front's exposition."""
    from synapseml_tpu.io.distributed_serving import RoutingFront
    from synapseml_tpu.io.serving import serve_pipeline
    import socket

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    srv = serve_pipeline(EchoObs(), batch_interval_ms=0)
    dead = free_port()
    front = RoutingFront([{"host": srv.host, "port": srv.port, "pid": 1},
                          {"host": "127.0.0.1", "port": dead, "pid": 2}],
                         timeout_s=5, resurrect_after_s=300)
    try:
        for _ in range(4):
            req = urllib.request.Request(front.address + "/p", data=b"{}",
                                         method="POST")
            with urllib.request.urlopen(req, timeout=30) as r:
                assert r.status == 200
        with urllib.request.urlopen(front.address + "/metrics",
                                    timeout=30) as r:
            text = r.read().decode()
        open_line = [line for line in text.splitlines()
                     if line.startswith("synapseml_breaker_state")
                     and f"127.0.0.1:{dead}" in line]
        assert open_line and open_line[0].endswith(" 2"), open_line
    finally:
        front.close()
        srv.stop()


def test_http_client_metrics_and_trace_header():
    """send_with_retries: latency histogram + status counter + the injected
    traceparent header reaches the server."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    from synapseml_tpu.io.http import HTTPRequest, send_with_retries

    seen = {}

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            seen["traceparent"] = self.headers.get("traceparent")
            body = b"ok"
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        t = obs.get_tracer()
        with t.span("caller") as cs:
            resp = send_with_retries(HTTPRequest(
                url=f"http://127.0.0.1:{srv.server_address[1]}/"))
        assert resp.status_code == 200
        ctx = obs.parse_traceparent(seen["traceparent"])
        assert ctx is not None and ctx.trace_id == cs.trace_id
        snap = obs.get_registry().snapshot()
        assert snap['synapseml_http_requests_total'
                    '{method="GET",status="2xx"}'] == 1
        hist = snap['synapseml_http_request_duration_ms{method="GET"}']
        assert hist["count"] == 1
    finally:
        srv.shutdown()
        srv.server_close()


def test_http_retry_counter_by_status():
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    from synapseml_tpu.io.http import HTTPRequest, send_with_retries

    calls = {"n": 0}

    class Flaky(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            calls["n"] += 1
            status = 503 if calls["n"] == 1 else 200
            self.send_response(status)
            self.send_header("Content-Length", "0")
            self.end_headers()

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Flaky)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        resp = send_with_retries(
            HTTPRequest(url=f"http://127.0.0.1:{srv.server_address[1]}/"),
            backoffs_ms=(10, 10))
        assert resp.status_code == 200 and calls["n"] == 2
        snap = obs.get_registry().snapshot()
        assert snap['synapseml_http_retries_total'
                    '{plane="http",status="503"}'] == 1
    finally:
        srv.shutdown()
        srv.server_close()


def test_rendezvous_duration_histogram():
    import socket as socket_mod
    from synapseml_tpu.parallel.backend import worker_rendezvous

    reply = {"coordinator": "127.0.0.1:9999", "rank": 0, "world": 1}
    srv = socket_mod.socket()
    srv.setsockopt(socket_mod.SOL_SOCKET, socket_mod.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def driver():
        conn, _ = srv.accept()
        conn.makefile("r").readline()
        conn.sendall((json.dumps(reply) + "\n").encode())
        conn.close()

    threading.Thread(target=driver, daemon=True).start()
    info = worker_rendezvous(f"127.0.0.1:{port}", "e0", 0, timeout_s=30)
    srv.close()
    assert info == reply
    snap = obs.get_registry().snapshot()
    assert snap["synapseml_rendezvous_duration_ms"]["count"] == 1
    names = [s.name for s in obs.get_tracer().finished_spans()]
    assert "parallel.rendezvous" in names


def test_gbdt_fit_populates_step_histogram():
    from synapseml_tpu.gbdt.booster import train_booster

    rng = np.random.default_rng(0)
    X = rng.normal(size=(120, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    train_booster(X, y, objective="binary", num_iterations=3, num_leaves=7)
    snap = obs.get_registry().snapshot()
    hist = snap['synapseml_train_step_duration_ms{engine="gbdt"}']
    assert hist["count"] >= 1 and hist["p50"] is not None
