"""Zero-cold-start deploys (ISSUE 9): AOT-compiled executable ladders in
the registry, the CompiledCache second tier, autotuned backend pinning,
store garbage collection, and the runtime-mismatch / corrupt-blob fallback
paths."""

import json
import os
import subprocess
import sys
import textwrap
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from _aot_pipeline import (TunableAffine, build_pipeline, make_mlp_onnx,
                           sample_rows)
from synapseml_tpu.core import batching as cb
from synapseml_tpu.core.pipeline import PipelineModel, Transformer
from synapseml_tpu.registry import ArtifactStore, ModelRegistry
from synapseml_tpu.registry import aot as raot

pytestmark = pytest.mark.aot

BUCKETS = [8, 16, 32]


@pytest.fixture()
def fresh_cache():
    cache = cb.reset_compiled_cache()
    yield cache
    cb.reset_compiled_cache()


class Placeholder(Transformer):
    """Initial pipeline a worker boots with before its first hot swap."""

    def _transform(self, df):
        def per_part(p):
            out = dict(p)
            out["reply"] = np.asarray([{"placeholder": True}] * len(p["id"]),
                                      dtype=object)
            return out

        return df.map_partitions(per_part)


def _post(base, path, payload, timeout=60):
    req = urllib.request.Request(base + path,
                                 data=json.dumps(payload).encode(),
                                 method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _publish(tmp_path, version="v1", aot=True, autotune=None, **pipe_kw):
    reg = ModelRegistry(str(tmp_path / "store"))
    pub = reg.publish(
        "mlp", build_pipeline(**pipe_kw), version=version,
        aot={"rows": sample_rows(), "buckets": BUCKETS} if aot else None,
        autotune=autotune)
    return reg, pub


# ---------------------------------------------------------------------------
# mechanics: mechanism probe, fingerprints, keys, template codec
# ---------------------------------------------------------------------------

def test_mechanism_detected():
    # this environment ships a jaxlib with executable serialization; the
    # probe must find SOME mechanism (graceful None is for foreign jaxes)
    assert raot.aot_mechanism() in ("xla", "export")


def test_fingerprint_match_and_mismatch_reasons():
    fp = raot.runtime_fingerprint()
    assert raot.fingerprint_mismatch(fp) is None
    for field in ("platform", "jax", "jaxlib", "xla_flags_sha256"):
        doctored = dict(fp, **{field: "something-else"})
        reason = raot.fingerprint_mismatch(doctored)
        assert reason is not None and field in reason


def test_key_digest_stable_across_tuple_list_spelling():
    a = raot.aot_key_digest("fn", (8, ("x", 1)), "float32")
    b = raot.aot_key_digest("fn", [8, ["x", 1]], "float32")
    assert a == b
    assert a != raot.aot_key_digest("fn", (16, ("x", 1)), "float32")
    assert a != raot.aot_key_digest("other", (8, ("x", 1)), "float32")


def test_template_codec_roundtrip_matches_tree_flatten_order():
    import jax.tree_util as jtu

    obj = {"b": (np.ones(2), [np.zeros(3), None]), "a": np.full(1, 7.0)}
    counter = [0]
    template = raot._encode_template(obj, counter)
    leaves = jtu.tree_leaves(obj)
    assert counter[0] == len(leaves)
    rebuilt = raot._decode_template(template, leaves)
    assert isinstance(rebuilt["b"], tuple) and rebuilt["b"][1][1] is None
    flat2 = jtu.tree_leaves(rebuilt)
    assert all(np.array_equal(x, y) for x, y in zip(leaves, flat2))


# ---------------------------------------------------------------------------
# ordinal binding: two same-class instances must never swap executables
# ---------------------------------------------------------------------------

def test_ordinal_binding_two_instances_keep_their_weights(tmp_path,
                                                          fresh_cache):
    import jax

    def make_builder(scale):
        def build():
            return jax.jit(lambda x: x * scale)

        return build

    class Obj:
        pass

    a, b = Obj(), Obj()
    capture = raot.AOTCapture()
    cache = fresh_cache
    cache.set_capture(capture)
    try:
        # same fn_id, same shape, same dtype — only the instance differs
        fa = cache.get("f", (4,), make_builder(2.0),
                       instance=cb.instance_token(a))
        fb = cache.get("f", (4,), make_builder(10.0),
                       instance=cb.instance_token(b))
        x = np.ones(4, np.float32)
        assert float(np.asarray(fa(x))[0]) == 2.0
        assert float(np.asarray(fb(x))[0]) == 10.0
    finally:
        cache.set_capture(None)
    import hashlib

    blobs = {}

    def put_blob(data):
        digest = hashlib.sha256(data).hexdigest()
        blobs[digest] = data
        return digest

    entries, skipped = capture.export(raot.aot_mechanism(), put_blob)
    assert not skipped and len(entries) == 2
    blob_dir = tmp_path / "aot"
    blob_dir.mkdir()
    for digest, data in blobs.items():
        (blob_dir / digest).write_bytes(data)
    for entry in entries:
        entry.setdefault("mechanism", raot.aot_mechanism())
    provider = raot.AOTExecutableSet(
        {"mechanism": raot.aot_mechanism(), "entries": entries},
        str(blob_dir))
    provider.begin_binding()
    # fresh process simulation: new instances, first-seen order preserved
    a2, b2 = Obj(), Obj()
    fa2 = provider.lookup("f", cb.instance_token(a2), (4,), None)
    fb2 = provider.lookup("f", cb.instance_token(b2), (4,), None)
    provider.freeze()
    x = np.ones(4, np.float32)
    assert float(np.asarray(fa2(x))[0]) == 2.0
    assert float(np.asarray(fb2(x))[0]) == 10.0
    # frozen: an unknown instance falls back to tracing, never aliases
    c = Obj()
    assert provider.lookup("f", cb.instance_token(c), (4,), None) is None
    # off-thread lookups during a binding window are ignored
    provider2 = raot.AOTExecutableSet(
        {"mechanism": raot.aot_mechanism(), "entries": entries},
        str(blob_dir))
    provider2.begin_binding()
    seen = {}

    def other_thread():
        seen["fn"] = provider2.lookup("f", cb.instance_token(Obj()),
                                      (4,), None)

    t = threading.Thread(target=other_thread)
    t.start()
    t.join()
    assert seen["fn"] is None


# ---------------------------------------------------------------------------
# publish: manifest entries, blobs, store gc
# ---------------------------------------------------------------------------

def test_publish_records_aot_entries_and_blobs(tmp_path, fresh_cache):
    reg, pub = _publish(tmp_path)
    aot = pub.manifest["aot"]
    assert aot["mechanism"] == raot.aot_mechanism()
    assert len(aot["entries"]) == len(BUCKETS)
    assert aot["warmup"]["buckets"] == BUCKETS
    assert raot.fingerprint_mismatch(aot["runtime"]) is None
    store = ArtifactStore(str(tmp_path / "store"))
    for entry in aot["entries"]:
        assert store.has_blob(entry["sha256"])
        assert entry["bytes"] > 0 and entry["fn_id"] == "onnx_model"
    # the signed manifest survives verification with the aot section
    assert store.read_manifest("mlp", "v1")["aot"]["entries"]
    # publish evicted its temporary capture executables from the cache
    assert len(fresh_cache) == 0


def test_store_gc_prunes_orphans_keeps_referenced(tmp_path, fresh_cache):
    reg, pub = _publish(tmp_path)
    store = ArtifactStore(str(tmp_path / "store"))
    orphan = store.put_blob_bytes(b"orphaned by a failed publish")
    referenced = {e["sha256"] for e in pub.manifest["files"]}
    referenced |= {e["sha256"] for e in pub.manifest["aot"]["entries"]}
    # dry run: reports, deletes nothing
    report = store.gc(dry_run=True, min_age_s=0.0)
    assert report["pruned"] == [orphan] and report["dry_run"]
    assert store.has_blob(orphan)
    # young-blob grace window protects in-flight publishes
    report = store.gc(min_age_s=3600.0)
    assert report["pruned"] == [] and report["kept_young"] == 1
    # real gc: orphan gone, every referenced blob survives
    report = store.gc(min_age_s=0.0)
    assert report["pruned"] == [orphan]
    assert not store.has_blob(orphan)
    assert all(store.has_blob(d) for d in referenced)
    # the version still resolves and serves after gc
    resolved = ModelRegistry(str(tmp_path / "store")).resolve("mlp", "v1")
    assert resolved.version == "v1"


# ---------------------------------------------------------------------------
# /admin/load: the zero-cold-start acceptance surface
# ---------------------------------------------------------------------------

def _serve_placeholder():
    from synapseml_tpu.io.serving import serve_pipeline

    return serve_pipeline(Placeholder(), batch_interval_ms=5, version="v0")


def test_admin_load_aot_serves_first_request_with_zero_traces(tmp_path,
                                                              fresh_cache):
    from synapseml_tpu.core import observability as obs

    reg, pub = _publish(tmp_path)
    srv = _serve_placeholder()
    try:
        cache = cb.get_compiled_cache()
        misses0 = cache.miss_count("onnx_model")
        status, reply = _post(srv.address, "/admin/load",
                              {"registry": str(tmp_path / "store"),
                               "model": "mlp", "ref": "v1"})
        assert status == 200 and reply["ok"]
        wu = reply["warmup"]
        assert wu["mode"] == "aot" and wu["fallback_reason"] is None
        assert wu["aot_hits"] == len(BUCKETS)
        assert wu["executables_loaded"] == len(BUCKETS)
        assert wu["executables_traced"] == 0
        assert wu["compile_ms"] == 0.0 and wu["io_ms"] > 0
        # first post-swap request over HTTP, then direct transforms at
        # every ladder rung (7->8, 12->16, 30->32): ZERO new traces —
        # every executable came from the artifact's blobs
        status, out = _post(srv.address, "/", sample_rows(1, seed=101)[0])
        assert status == 200 and "pred" in out
        from synapseml_tpu.core.dataframe import DataFrame

        loaded = srv.pipeline_holder.pipeline
        onnx = loaded.get("stages")[1]
        rs = np.random.default_rng(5)
        for n in (7, 12, 30):
            out_df = onnx.transform(DataFrame.from_dict(
                {"features": rs.normal(size=(n, 4)).astype(np.float32)}))
            assert len(out_df.collect_column("pred")) == n
        assert cache.miss_count("onnx_model") - misses0 == 0
        assert cache.stats()["aot_hits"] == len(BUCKETS)
        # satellite: the same fields surface as synapseml_deploy_* series
        text = obs.prometheus_exposition()[0].decode()
        assert "synapseml_deploy_aot_hits_total" in text
        assert "synapseml_deploy_warmup_io_ms" in text
        assert "synapseml_deploy_executables_loaded_total" in text
        # a FRESH pipeline's instances never alias the frozen provider:
        # direct transform of a new stage traces (miss), correct output
        onnx2 = make_mlp_onnx(seed=3)
        from synapseml_tpu.core.dataframe import DataFrame

        feats = np.ones((4, 4), np.float32)
        out2 = onnx2.transform(DataFrame.from_dict({"features": feats}))
        assert cache.miss_count("onnx_model") - misses0 == 1
        assert len(out2.collect_column("pred")) == 4
    finally:
        srv.stop()


def test_aot_and_jit_arms_give_identical_predictions(tmp_path, fresh_cache):
    reg, pub = _publish(tmp_path)
    bodies = sample_rows(6, seed=42)
    replies = {}
    for arm in ("aot", "jit"):
        srv = _serve_placeholder()
        try:
            status, reply = _post(srv.address, "/admin/load",
                                  {"registry": str(tmp_path / "store"),
                                   "model": "mlp", "ref": "v1",
                                   "aot": arm == "aot"})
            assert status == 200
            assert reply["warmup"]["mode"] == arm
            if arm == "jit":
                assert reply["warmup"]["fallback_reason"] == \
                    "aot disabled by request"
            replies[arm] = [_post(srv.address, "/", b)[1] for b in bodies]
        finally:
            srv.stop()
        cb.reset_compiled_cache()
    # byte-identical across arms: the deserialized executable computes the
    # exact program the JIT arm compiles
    assert json.dumps(replies["aot"], sort_keys=True) == \
        json.dumps(replies["jit"], sort_keys=True)


def test_warmup_cap_lifted_when_aot_present(tmp_path, fresh_cache):
    reg = ModelRegistry(str(tmp_path / "store"))
    big = [8, 16, 32, 64, 128, 256]
    reg.publish("mlp", build_pipeline(mini_batch_size=256), version="v1",
                aot={"rows": sample_rows(), "buckets": big})
    srv = _serve_placeholder()
    try:
        status, reply = _post(srv.address, "/admin/load",
                              {"registry": str(tmp_path / "store"),
                               "model": "mlp", "ref": "v1"})
        assert status == 200
        wu = reply["warmup"]
        # default JIT warmup stops at rungs <= 64; with AOT blobs the full
        # published ladder (incl. 128/256) maps in with zero compiles
        assert wu["aot_hits"] == len(big) and wu["executables_traced"] == 0
        misses0 = cb.get_compiled_cache().miss_count("onnx_model")
        status, out = _post(srv.address, "/",
                            sample_rows(1, seed=9)[0])
        assert status == 200 and "pred" in out
        assert cb.get_compiled_cache().miss_count("onnx_model") == misses0
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# fallback paths: runtime mismatch, corrupt blob — swap NEVER fails
# ---------------------------------------------------------------------------

def _doctor_manifest(tmp_path, **runtime_overrides):
    store = ArtifactStore(str(tmp_path / "store"))
    manifest = store.read_manifest("mlp", "v1")
    manifest.pop("signature", None)
    manifest["aot"]["runtime"].update(runtime_overrides)
    store.write_manifest("mlp", "v1", manifest)


@pytest.mark.parametrize("overrides,needle", [
    ({"platform": "tpu"}, "platform"),
    ({"jaxlib": "9.9.9"}, "jaxlib"),
])
def test_runtime_mismatch_falls_back_to_jit_and_swaps(tmp_path, fresh_cache,
                                                      overrides, needle,
                                                      caplog):
    import logging

    reg, pub = _publish(tmp_path)
    _doctor_manifest(tmp_path, **overrides)
    srv = _serve_placeholder()
    try:
        with caplog.at_level(logging.WARNING,
                             logger="synapseml_tpu.registry.aot"):
            status, reply = _post(srv.address, "/admin/load",
                                  {"registry": str(tmp_path / "store"),
                                   "model": "mlp", "ref": "v1"})
        # the swap SUCCEEDS on the JIT path with one structured warning
        assert status == 200 and reply["ok"]
        wu = reply["warmup"]
        assert wu["mode"] == "jit"
        assert needle in wu["fallback_reason"]
        assert wu["aot_hits"] == 0 and wu["executables_traced"] > 0
        warnings = [r for r in caplog.records
                    if "aot_fallback" in r.getMessage()]
        assert len(warnings) == 1
        payload = json.loads(warnings[0].getMessage())
        assert needle in payload["reason"]
        # and it still serves correctly
        status, out = _post(srv.address, "/", sample_rows(1)[0])
        assert status == 200 and "pred" in out
    finally:
        srv.stop()


def test_corrupted_blob_rejected_falls_back_swap_succeeds(tmp_path,
                                                          fresh_cache):
    reg, pub = _publish(tmp_path)
    # materialize the version cache, then corrupt every aot blob IN PLACE
    resolved = reg.resolve("mlp", "v1")
    aot_dir = os.path.join(os.path.dirname(resolved.path), "aot")
    blobs = os.listdir(aot_dir)
    assert len(blobs) == len(BUCKETS)
    for name in blobs:
        with open(os.path.join(aot_dir, name), "r+b") as f:
            f.seek(0)
            f.write(b"\x00corrupted\x00")
    srv = _serve_placeholder()
    try:
        status, reply = _post(srv.address, "/admin/load",
                              {"registry": str(tmp_path / "store"),
                               "model": "mlp", "ref": "v1"})
        # integrity check rejects each blob; warmup traces instead; the
        # swap still succeeds and serves correct predictions
        assert status == 200 and reply["ok"]
        wu = reply["warmup"]
        assert wu["mode"] == "aot"
        assert wu["aot_errors"] == len(BUCKETS)
        assert wu["aot_hits"] == 0
        assert wu["executables_traced"] >= len(BUCKETS)
        status, out = _post(srv.address, "/", sample_rows(1)[0])
        assert status == 200 and "pred" in out
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# autotune: search records winners, load pins them
# ---------------------------------------------------------------------------

def test_autotune_records_winner_and_load_pins_it(tmp_path, fresh_cache):
    reg = ModelRegistry(str(tmp_path / "store"))
    pipe = PipelineModel(stages=[TunableAffine(impl="slow"),
                                 build_pipeline().get("stages")[0],
                                 make_mlp_onnx(), ])
    pub = reg.publish(
        "tuned", pipe, version="v1",
        aot={"rows": sample_rows(), "buckets": [8]},
        autotune={"trials": 2, "winners": {"histogram_impl": "onehot"}})
    tune = pub.manifest["autotune"]
    assert tune["winners"]["impl"] == "fast"
    # the search's warm cache entries must not hide rungs from capture
    assert len(pub.manifest["aot"]["entries"]) == 1
    # bench-fed override recorded verbatim next to the searched winner
    assert tune["winners"]["histogram_impl"] == "onehot"
    assert tune["timings_ms"]["impl"]["slow"]["8"] > \
        tune["timings_ms"]["impl"]["fast"]["8"]
    # load pins the winner onto the freshly loaded stage (saved artifact
    # still says 'slow')
    srv = _serve_placeholder()
    try:
        status, reply = _post(srv.address, "/admin/load",
                              {"registry": str(tmp_path / "store"),
                               "model": "tuned", "ref": "v1"})
        assert status == 200
        applied = reply["warmup"].get("autotune") or []
        assert {"stage": "TunableAffine", "param": "impl",
                "from": "slow", "to": "fast"} in applied
        loaded = srv.pipeline_holder.pipeline
        assert loaded.get("stages")[0].get("impl") == "fast"
        # opting out keeps the saved defaults — and since the shipped AOT
        # executables were compiled WITH the winners baked in, the load
        # must also demote to JIT (serving tuned kernels under untuned
        # configs would make the opt-out a lie)
        status, reply = _post(srv.address, "/admin/load",
                              {"registry": str(tmp_path / "store"),
                               "model": "tuned", "ref": "v1",
                               "autotune": False})
        assert status == 200
        assert srv.pipeline_holder.pipeline.get("stages")[0].get("impl") \
            == "slow"
        wu = reply["warmup"]
        assert wu["mode"] == "jit"
        assert "autotune disabled" in wu["fallback_reason"]
    finally:
        srv.stop()


def test_autotune_all_candidates_failing_restores_original(fresh_cache):
    from synapseml_tpu.core.params import Param
    from synapseml_tpu.registry.autotune import autotune_stage

    class Exploding(Transformer):
        impl = Param("impl", "always broken", default="a",
                     validator=lambda v: v in ("a", "b"))
        _AUTOTUNE_PARAMS = {"impl": ("a", "b")}

        def _transform(self, df):
            raise RuntimeError("kaboom")

    stage = Exploding(impl="a")
    section = autotune_stage(stage, sample_rows(), [8],
                             {"parse_json": True, "input_col": "body"})
    # no winner recorded, and the stage is NOT left on the last failing
    # candidate for the AOT capture that follows
    assert section is None
    assert stage.get("impl") == "a"


def test_export_mechanism_serves_but_keeps_rung_cap(tmp_path, fresh_cache,
                                                    monkeypatch):
    # force the portable jax.export fallback end-to-end: blobs skip
    # tracing but still XLA-compile at load, so the full-ladder rung-cap
    # lift must NOT apply
    monkeypatch.setattr(raot, "aot_mechanism", lambda: "export")
    reg = ModelRegistry(str(tmp_path / "store"))
    big = [8, 16, 32, 64, 128, 256]
    pub = reg.publish("mlp", build_pipeline(mini_batch_size=256),
                      version="v1",
                      aot={"rows": sample_rows(), "buckets": big})
    assert pub.manifest["aot"]["mechanism"] == "export"
    assert len(pub.manifest["aot"]["entries"]) == len(big)
    srv = _serve_placeholder()
    try:
        status, reply = _post(srv.address, "/admin/load",
                              {"registry": str(tmp_path / "store"),
                               "model": "mlp", "ref": "v1"})
        assert status == 200
        wu = reply["warmup"]
        assert wu["mode"] == "aot"
        # default cap (rungs <= 64) applied: 128/256 NOT warmed at load
        assert wu["aot_hits"] == len([b for b in big if b <= 64])
        assert wu["executables_traced"] == 0
        # and the deserialized module still serves correctly
        status, out = _post(srv.address, "/", sample_rows(1)[0])
        assert status == 200 and "pred" in out
    finally:
        srv.stop()


def test_missing_aot_blob_self_heals_on_next_resolve(tmp_path, fresh_cache):
    reg, pub = _publish(tmp_path)
    resolved = reg.resolve("mlp", "v1")
    aot_dir = os.path.join(os.path.dirname(resolved.path), "aot")
    victim = os.path.join(aot_dir, os.listdir(aot_dir)[0])
    os.unlink(victim)
    # the .complete marker is already written; a transient fetch failure
    # must not become a permanent JIT fallback — resolve re-fetches
    reg.resolve("mlp", "v1")
    assert os.path.isfile(victim)


def test_autotune_skips_foreign_platform(tmp_path, fresh_cache):
    from synapseml_tpu.registry.autotune import apply_autotune

    stage = TunableAffine(impl="slow")
    applied = apply_autotune(stage, {"platform": "tpu",
                                     "winners": {"impl": "fast"}})
    assert applied == [] and stage.get("impl") == "slow"


# ---------------------------------------------------------------------------
# cross-process: publish in one process, zero-trace serve in a fresh one
# ---------------------------------------------------------------------------

_SERVE_DRIVER = textwrap.dedent("""
    import json, os, sys, urllib.request
    sys.path.insert(0, {repo!r}); sys.path.insert(0, {tests!r})
    import numpy as np
    from _aot_pipeline import sample_rows
    from synapseml_tpu.core import batching as cb
    from synapseml_tpu.core.pipeline import Transformer
    from synapseml_tpu.io.serving import serve_pipeline

    class Placeholder(Transformer):
        def _transform(self, df):
            def pp(p):
                out = dict(p)
                out["reply"] = np.asarray([{{}}] * len(p["id"]), dtype=object)
                return out
            return df.map_partitions(pp)

    srv = serve_pipeline(Placeholder(), batch_interval_ms=5, version="v0")

    def post(path, payload):
        req = urllib.request.Request(srv.address + path,
                                     data=json.dumps(payload).encode(),
                                     method="POST")
        with urllib.request.urlopen(req, timeout=120) as r:
            return json.loads(r.read())

    cache = cb.get_compiled_cache()
    misses0 = cache.miss_count("onnx_model")
    reply = post("/admin/load", {{"registry": {store!r}, "model": "mlp",
                                  "ref": "v1"}})
    preds = [post("/", b) for b in sample_rows(6, seed=42)]
    print(json.dumps({{
        "warmup": reply["warmup"],
        "miss_delta": cache.miss_count("onnx_model") - misses0,
        "aot_hits": cache.stats()["aot_hits"],
        "preds": preds,
    }}))
    srv.stop()
""")

_PUBLISH_DRIVER = textwrap.dedent("""
    import json, sys
    sys.path.insert(0, {repo!r}); sys.path.insert(0, {tests!r})
    from _aot_pipeline import build_pipeline, sample_rows
    from synapseml_tpu.core.dataframe import DataFrame
    from synapseml_tpu.registry import ModelRegistry
    import numpy as np

    reg = ModelRegistry({store!r})
    pipe = build_pipeline()
    reg.publish("mlp", pipe, version="v1",
                aot={{"rows": sample_rows(), "buckets": [8, 16, 32]}})
    # reference predictions straight through the published pipeline
    feats = np.stack([np.asarray(b["features"], np.float32)
                      for b in sample_rows(6, seed=42)])
    df = DataFrame.from_dict({{
        "id": np.asarray([str(i) for i in range(6)], dtype=object),
        "method": np.asarray(["POST"] * 6, dtype=object),
        "path": np.asarray(["/"] * 6, dtype=object),
        "body": np.asarray(list(sample_rows(6, seed=42)), dtype=object)}})
    out = pipe.transform(df)
    print(json.dumps({{"preds": list(out.collect_column("reply"))}},
                     default=str))
""")


def _run_driver(script: str) -> dict:
    proc = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        timeout=240, env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, f"driver failed:\n{proc.stderr[-4000:]}"
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_cross_process_publish_then_zero_trace_serve(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tests = os.path.join(repo, "tests")
    store = str(tmp_path / "store")
    pub_out = _run_driver(_PUBLISH_DRIVER.format(repo=repo, tests=tests,
                                                 store=store))
    serve_out = _run_driver(_SERVE_DRIVER.format(repo=repo, tests=tests,
                                                 store=store))
    wu = serve_out["warmup"]
    # the acceptance criterion: a FRESH process serves the ladder with
    # zero traces — every executable came from the artifact's blobs
    assert wu["mode"] == "aot", wu
    assert wu["executables_traced"] == 0 and wu["compile_ms"] == 0.0
    assert serve_out["miss_delta"] == 0
    assert serve_out["aot_hits"] == 3
    # and the served predictions equal the publisher's direct transform
    served = [p["pred"] for p in serve_out["preds"]]
    direct = [p["pred"] for p in pub_out["preds"]]
    assert served == direct
