"""Explainers: LIME/SHAP against analytic ground truth on linear models, ICE."""

import numpy as np
import pytest

from synapseml_tpu.core import DataFrame
from synapseml_tpu.core.pipeline import Transformer
from synapseml_tpu.explainers import (
    ICETransformer,
    ImageLIME,
    ImageSHAP,
    TabularSHAP,
    TextLIME,
    TextSHAP,
    VectorLIME,
    VectorSHAP,
    lasso_regression,
    weighted_least_squares,
)


class LinearScorer(Transformer):
    """score = x @ w + b, exposed as a 1-column 'probability'."""

    def __init__(self, w, b=0.0, input_col="features", **kw):
        super().__init__(**kw)
        self._w = np.asarray(w, np.float64)
        self._b = b
        self._input_col = input_col

    def _transform(self, df):
        def score(p):
            X = np.stack([np.asarray(v, np.float64) for v in p[self._input_col]])
            s = X @ self._w + self._b
            return np.asarray([np.asarray([v]) for v in s])

        return df.with_column("probability", score)


def test_solvers():
    rs = np.random.default_rng(0)
    X = rs.normal(size=(200, 4))
    beta_true = np.asarray([2.0, -1.0, 0.0, 0.5])
    y = X @ beta_true + 3.0
    w = np.ones(200)
    coef, b0 = weighted_least_squares(X, y, w)
    np.testing.assert_allclose(coef, beta_true, atol=1e-6)
    assert b0 == pytest.approx(3.0, abs=1e-6)
    coef_l, b0_l = lasso_regression(X, y, w, alpha=1e-4)
    np.testing.assert_allclose(coef_l, beta_true, atol=1e-2)
    # strong alpha shrinks everything toward 0
    coef_strong, _ = lasso_regression(X, y, w, alpha=10.0)
    assert np.abs(coef_strong).sum() < np.abs(coef_l).sum()


def test_vector_shap_linear_model_exact():
    """For a linear model, SHAP values are w_i * (x_i - E[x_i]) exactly."""
    rs = np.random.default_rng(1)
    w = np.asarray([1.0, -2.0, 0.5, 0.0])
    X = rs.normal(size=(30, 4)).astype(np.float32)
    df = DataFrame.from_dict({"features": X})
    shap = VectorSHAP(model=LinearScorer(w, b=1.0), target_col="probability",
                      num_samples=64, seed=0, background_data=df)
    out = shap.transform(df.limit(5))
    bg_mean = X.mean(axis=0)
    for i, phi in enumerate(out.collect_column("explanation")):
        phi = np.asarray(phi)[0]                  # [K+1], phi0 last
        expected = w * (X[i] - bg_mean)
        np.testing.assert_allclose(phi[:-1], expected, atol=5e-2)
        # efficiency: phi0 + sum(phi) == f(x)
        fx = float(X[i] @ w + 1.0)
        assert phi.sum() == pytest.approx(fx, abs=5e-2)


def test_tabular_shap_matches_vector():
    rs = np.random.default_rng(2)
    w = np.asarray([1.5, -1.0])
    X = rs.normal(size=(20, 2)).astype(np.float32)
    df = DataFrame.from_dict({"a": X[:, 0], "b": X[:, 1]})

    class ColScorer(Transformer):
        def _transform(self, sdf):
            def score(p):
                s = np.asarray(p["a"], np.float64) * 1.5 - np.asarray(p["b"], np.float64)
                return np.asarray([np.asarray([v]) for v in s])
            return sdf.with_column("probability", score)

    shap = TabularSHAP(model=ColScorer(), input_cols=["a", "b"],
                       target_col="probability", num_samples=16, seed=0,
                       background_data=df)
    out = shap.transform(df.limit(4))
    bg = X.mean(axis=0)
    for i, phi in enumerate(out.collect_column("explanation")):
        phi = np.asarray(phi)[0]
        np.testing.assert_allclose(phi[:-1], w * (X[i] - bg), atol=5e-2)


def test_vector_lime_recovers_linear_signs():
    rs = np.random.default_rng(3)
    w = np.asarray([3.0, -2.0, 0.0])
    X = rs.normal(size=(40, 3)).astype(np.float32)
    df = DataFrame.from_dict({"features": X})
    lime = VectorLIME(model=LinearScorer(w), target_col="probability",
                      num_samples=200, seed=0, regularization=1e-4,
                      background_data=df)
    out = lime.transform(df.limit(3))
    std = X.std(axis=0)
    for coefs in out.collect_column("explanation"):
        c = np.asarray(coefs)[0]                  # standardized design -> w*std
        np.testing.assert_allclose(c, w * std, rtol=0.15, atol=0.05)


def test_text_explainers_find_key_token():
    class KeywordScorer(Transformer):
        def _transform(self, sdf):
            def score(p):
                return np.asarray([np.asarray([1.0 if "good" in str(t).split() else 0.0])
                                   for t in p["text"]])
            return sdf.with_column("probability", score)

    df = DataFrame.from_dict({"text": ["this is a good movie", "bad film overall"]})
    lime = TextLIME(model=KeywordScorer(), target_col="probability",
                    num_samples=64, seed=0, regularization=1e-4)
    out = lime.transform(df)
    tokens0 = list(out.collect_column("tokens")[0])
    coefs0 = np.asarray(out.collect_column("explanation")[0])[0]
    assert tokens0[int(np.argmax(coefs0))] == "good"
    # second row: no 'good' token -> flat zero scores -> near-zero coefs
    coefs1 = np.asarray(out.collect_column("explanation")[1])[0]
    assert np.abs(coefs1).max() < 0.05

    shap = TextSHAP(model=KeywordScorer(), target_col="probability",
                    num_samples=64, seed=0)
    sout = shap.transform(df.limit(1))
    phi = np.asarray(sout.collect_column("explanation")[0])[0]
    toks = list(sout.collect_column("tokens")[0])
    assert toks[int(np.argmax(phi[:-1]))] == "good"


def test_image_explainers_localize_signal():
    """Model scores the mean of the left half; explanations should put the
    mass on left-half superpixels."""

    class LeftHalfScorer(Transformer):
        def _transform(self, sdf):
            def score(p):
                out = []
                for im in p["image"]:
                    im = np.asarray(im, np.float64)
                    out.append(np.asarray([im[:, : im.shape[1] // 2].mean()]))
                return np.asarray(out)
            return sdf.with_column("probability", score)

    # four flat quadrants -> SLIC segments match quadrants exactly
    img = np.zeros((24, 24, 1), np.float32)
    img[:12, :12] = 60.0
    img[:12, 12:] = 120.0
    img[12:, :12] = 180.0
    img[12:, 12:] = 240.0
    df = DataFrame.from_dict({"image": [img]})
    for cls, kw in [(ImageLIME, dict(num_samples=64, regularization=1e-4)),
                    (ImageSHAP, dict(num_samples=64))]:
        expl = cls(model=LeftHalfScorer(), target_col="probability",
                   cell_size=12.0, seed=0, **kw).transform(df)
        from synapseml_tpu.image import slic_segments
        labels = slic_segments(img, cell_size=12.0)
        coefs = np.asarray(expl.collect_column("explanation")[0])[0]
        K = labels.max() + 1
        centers = np.asarray([np.mean(np.nonzero(labels == k)[1]) for k in range(K)])
        left = centers < 12
        left_mass = np.abs(coefs[:K][left]).sum()
        right_mass = np.abs(coefs[:K][~left]).sum()
        assert left_mass > 2 * right_mass, f"{cls.__name__}: {left_mass} vs {right_mass}"


def test_ice_transformer():
    class SquareScorer(Transformer):
        def _transform(self, sdf):
            return sdf.with_column(
                "probability",
                lambda p: np.asarray([np.asarray([float(v) ** 2]) for v in p["x"]]))

    rs = np.random.default_rng(0)
    df = DataFrame.from_dict({"x": rs.uniform(-2, 2, 30).astype(np.float32),
                              "cat": rs.choice(["u", "v"], 30)})
    ice = ICETransformer(model=SquareScorer(), target_col="probability",
                         numeric_features=["x"], num_splits=5, kind="individual")
    out = ice.transform(df)
    curve = out.collect_column("x_dependence")[0]
    grid_vals = sorted(float(k) for k in curve.keys())
    ys = [curve[str(g)][0] for g in grid_vals] if str(grid_vals[0]) in curve else None
    # curve follows x^2 over the grid regardless of the row
    for k, v in curve.items():
        assert v[0] == pytest.approx(float(k) ** 2, abs=1e-4)

    pdp = ICETransformer(model=SquareScorer(), target_col="probability",
                         numeric_features=["x"], num_splits=5, kind="average")
    avg = pdp.transform(df)
    row = avg.collect_column("x_dependence")[0]
    for k, v in row.items():
        assert v[0] == pytest.approx(float(k) ** 2, abs=1e-4)

    with pytest.raises(ValueError, match="numeric_features"):
        ICETransformer(model=SquareScorer()).transform(df)


def test_lime_text_through_sharded_inference():
    """Explainer perturbation batches route through mesh-sharded model
    inference (VERDICT round-1 weak 9 / SURVEY §7 step 8): explanations on a
    mesh-scored model match the single-device ones."""
    import synapseml_tpu as st
    from synapseml_tpu.explainers import TextLIME
    from synapseml_tpu.models import DeepTextClassifier
    from synapseml_tpu.parallel import MeshConfig

    rows = [{"text": "good great fine nice", "label": 1},
            {"text": "bad awful poor sad", "label": 0}] * 10
    df = st.DataFrame.from_rows(rows)
    model = DeepTextClassifier(checkpoint="bert-tiny", num_classes=2,
                               batch_size=8, max_token_len=16, max_steps=15,
                               learning_rate=3e-3).fit(df)

    expl_df = st.DataFrame.from_rows([{"text": "good great bad"}])
    lime_plain = TextLIME(model=model, target_classes=[1], num_samples=64,
                          seed=0, target_col="scores")
    plain = np.asarray(list(lime_plain.transform(expl_df)
                            .collect_column("explanation"))[0])

    model.set(mesh_config=MeshConfig(data=-1, fsdp=2))
    model._post_load()  # rebuild the apply fn with the mesh in place
    assert model._get_apply() is not None and model._mesh is not None
    lime_sharded = TextLIME(model=model, target_classes=[1], num_samples=64,
                            seed=0, target_col="scores")
    sharded = np.asarray(list(lime_sharded.transform(expl_df)
                              .collect_column("explanation"))[0])
    # bf16 scoring + mesh-aligned batch padding shift logits slightly; the
    # surrogate coefficients must still agree closely
    np.testing.assert_allclose(sharded, plain, atol=0.02)
    assert np.all(np.sign(sharded) == np.sign(plain))
