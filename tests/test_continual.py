"""Continual-training flywheel (ISSUE 14): request logging + scrubbing,
crash-safe supervised training, checkpoint verification, and the
fault-contained serve→log→retrain→canary loop. The chaos suite drives a
fault at every seam and asserts ``prod`` stays untouched."""

import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from synapseml_tpu.core.faults import FaultSpec, inject_faults
from synapseml_tpu.core.logging import scrub
from synapseml_tpu.core.params import ComplexParam
from synapseml_tpu.core.pipeline import Transformer
from synapseml_tpu.registry import Deployment, ModelRegistry

pytestmark = pytest.mark.continual

D_IN, N_CLASSES = 4, 3
_W_TRUE = np.random.default_rng(3).normal(size=(D_IN,))


# ---------------------------------------------------------------------------
# shared model bits (module-level so worker subprocesses can unpickle/load)
# ---------------------------------------------------------------------------

def _mlp():
    import flax.linen as nn

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(N_CLASSES)(nn.relu(nn.Dense(8)(x)))

    return MLP()


def _forward(params, X):
    """Numpy mirror of the flax MLP (Dense_1 = input layer, Dense_0 = the
    first-constructed output layer)."""
    h = np.maximum(X @ np.asarray(params["Dense_1"]["kernel"])
                   + np.asarray(params["Dense_1"]["bias"]), 0)
    return (h @ np.asarray(params["Dense_0"]["kernel"])
            + np.asarray(params["Dense_0"]["bias"]))


class MLPScorer(Transformer):
    """Servable classifier over a published params pytree — replies
    ``{"pred": <argmax>}`` per request body ``{"x": [...]}``."""

    params = ComplexParam("params", "weights pytree", default=None)

    def _transform(self, df):
        W = self.get("params")

        def per_part(p):
            out = dict(p)
            preds = [{"pred": int(np.argmax(_forward(
                W, np.asarray(b["x"], dtype=np.float32)[None, :])))}
                for b in p["body"]]
            out["reply"] = np.asarray(preds, dtype=object)
            return out

        return df.map_partitions(per_part)


def _trainer(steps, lr=0.05, action="raise"):
    from synapseml_tpu.models.trainer import Trainer, TrainerConfig
    from synapseml_tpu.parallel.mesh import MeshConfig, create_mesh

    return Trainer(_mlp(), create_mesh(MeshConfig()),
                   TrainerConfig(total_steps=steps, learning_rate=lr,
                                 nonfinite_action=action))


def make_rows(n, seed, poison=False):
    r = np.random.default_rng(seed)
    X = r.normal(size=(n, D_IN)).astype(np.float32)
    y = np.digitize(X @ _W_TRUE,
                    np.quantile(X @ _W_TRUE, [1 / 3, 2 / 3])).astype(np.int32)
    if poison:
        y = r.integers(0, N_CLASSES, size=n).astype(np.int32)
    return X, y


def _v1_stage(seed=1):
    """A deliberately under-trained v1 (2 steps, default lr)."""
    import jax

    from synapseml_tpu.data.source import MemorySource
    from synapseml_tpu.models.trainer import fit_source

    X0, y0 = make_rows(64, 0)
    s = fit_source(_trainer(2, lr=1e-4), MemorySource(
        {"x": X0, "labels": y0}, shard_rows=32),
        batch_size=16, total_steps=2, seed=seed)
    return MLPScorer().set(params=jax.tree.map(np.asarray, s.params))


def write_part(logdir, idx, Xp, yp, garbage=0, drop_y=0):
    """Hand-craft one committed log part (the layout RequestLogger emits)."""
    name = f"part-{idx:05d}.jsonl"
    with open(os.path.join(logdir, name), "w") as f:
        for i in range(len(Xp)):
            body = {"x": [float(v) for v in Xp[i]]}
            if i >= drop_y:
                body["y"] = int(yp[i])
            f.write(json.dumps({"ts": 0, "method": "POST", "path": "/",
                                "status": 200, "latency_ms": 1.0,
                                "body": body, "reply": {}}) + "\n")
        for _ in range(garbage):
            f.write("{torn json!!\n")
    with open(os.path.join(logdir, name + ".DONE"), "w") as f:
        json.dump({"rows": len(Xp)}, f)
    return name


def row_fn(record):
    b = record["body"]
    return {"x": np.asarray(b["x"], dtype=np.float32),
            "labels": np.int32(b["y"])}


def make_train_fn(total_steps=30, batch_size=16):
    def train_fn(ctx, attempt):
        import jax

        from synapseml_tpu.data.source import MemorySource
        from synapseml_tpu.models.trainer import fit_source
        from synapseml_tpu.parallel.checkpoint import AsyncCheckpointer

        src = MemorySource(ctx.train_cols, shard_rows=32)
        t = _trainer(total_steps)
        init = ctx.prod.stage.get("params") if ctx.prod is not None else None
        with AsyncCheckpointer(ctx.checkpoint_dir, keep=10) as ck:
            state = fit_source(
                t, src, batch_size=batch_size, total_steps=total_steps,
                seed=ctx.spec.seed, init_params=init, scan_chunk=1,
                checkpointer=ck, checkpoint_every=5,
                resume_from=ctx.checkpoint_dir, skip_fn=attempt.skip_fn,
                callback=lambda i, m: attempt.heartbeat(i))
        return MLPScorer().set(params=jax.tree.map(np.asarray, state.params))

    return train_fn


def eval_fn(stage, holdout):
    """Mean NLL of the scorer on the held-out slice (lower = better)."""
    logits = _forward(stage.get("params"), holdout["x"].astype(np.float32))
    z = logits - logits.max(-1, keepdims=True)
    logp = z - np.log(np.exp(z).sum(-1, keepdims=True))
    return float(-logp[np.arange(len(logits)),
                       holdout["labels"].astype(int)].mean())


def _params_equal(a, b):
    import jax

    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


# ---------------------------------------------------------------------------
# scrubber satellite
# ---------------------------------------------------------------------------

def test_scrub_free_text_patterns():
    counts = {}
    out = scrub('user a.user+tag@example.co.uk paid with '
                '4111 1111 1111 1111, token eyJhbGciOiJIUzI1NiJ9.eyJzdWIi'
                'OiIxIn0.sig-part and Authorization: Bearer abc.def.ghi; '
                'also apiKey=supersecret and "password": "hunter2"', counts)
    assert "@" not in out.replace("####@####", "")
    assert "4111" not in out
    assert "eyJ" not in out
    assert "supersecret" not in out and "hunter2" not in out
    assert counts["email"] == 1 and counts["digits"] == 1
    assert counts["jwt"] == 1 and counts["bearer"] == 1
    assert counts["keyvalue"] == 1 and counts["json"] == 1


def test_scrub_preserves_nonsecret_text():
    counts = {}
    text = ('{"durationMs": 1234.567, "uid": "abc123", "n": 42, '
            '"note": "step 1000000 of 2000000"}')
    assert scrub(text, counts) == text
    assert counts == {}


# ---------------------------------------------------------------------------
# request logger
# ---------------------------------------------------------------------------

def test_request_logger_atomic_parts_and_source(tmp_path):
    from synapseml_tpu.continual import RequestLogger, logged_request_source

    with RequestLogger(str(tmp_path), shard_rows=4, seed=1) as lg:
        for i in range(10):
            lg.log(method="POST", path="/", status=200, latency_ms=1.0,
                   body=json.dumps({"x": [i], "email": "u@x.io"}).encode(),
                   reply={"pred": i % 3})
        lg.flush()
        parts = lg.committed_parts()
        assert len(parts) == 3  # 4 + 4 + 2 (flush commits the tail)
        # DONE markers carry rows + the scrub tally
        done = json.load(open(parts[0] + ".DONE"))
        assert done["rows"] == 4 and done["scrubbed"].get("email", 0) > 0
        # no in-flight litter visible to a part glob
        assert not [n for n in os.listdir(tmp_path)
                    if n.endswith(".jsonl") and not os.path.exists(
                        os.path.join(tmp_path, n + ".DONE"))]
        src = logged_request_source(str(tmp_path))
        rows = sum(len(next(iter(c.values())))
                   for _, c in src.iter_shards())
        assert rows == 10
        body = src.read_shard(0)["body"][0]
        assert body["email"] == "####@####"  # scrubbed at write time
        assert lg.stats()["logged"] == 10


def test_request_logger_sampling_deterministic(tmp_path):
    from synapseml_tpu.continual import RequestLogger

    def run(sub):
        with RequestLogger(str(tmp_path / sub), sample_rate=0.5,
                           seed=42, shard_rows=1000) as lg:
            for i in range(200):
                lg.log(method="POST", path="/", body=b"{}", reply={},
                       status=200, latency_ms=0.1)
            lg.flush()
            return lg.stats()["logged"]

    a, b = run("a"), run("b")
    assert a == b  # one seeded RNG ⇒ identical kept-set size
    assert 50 < a < 150  # actually sampling, not pass/drop-everything


def test_request_logger_sheds_when_queue_full(tmp_path):
    from synapseml_tpu.continual import RequestLogger

    lg = RequestLogger(str(tmp_path), shard_rows=1000, max_queue=2)
    gate = threading.Event()
    orig = lg._write_record

    def slow(item):
        gate.wait(10)
        orig(item)

    lg._write_record = slow
    for i in range(20):
        lg.log(method="POST", path="/", body=b"{}", reply={}, status=200,
               latency_ms=0.1)
    assert lg.dropped > 0  # shed (never blocked the serving thread)
    gate.set()
    lg.close()
    assert lg.stats()["logged"] + lg.dropped == 20


@pytest.mark.chaos
def test_request_logger_commit_fault_sheds_shard(tmp_path):
    """An injected fault at the commit seam sheds that shard's rows and
    the logger keeps committing — degraded, never a torn committed part."""
    from synapseml_tpu.continual import RequestLogger

    with RequestLogger(str(tmp_path), shard_rows=4) as lg:
        with inject_faults([FaultSpec("crash", match="log_commit",
                                      times=1, planes=("continual",))]):
            for i in range(8):
                lg.log(method="POST", path="/", body=b"{}", reply={},
                       status=200, latency_ms=0.1)
            lg.flush()
        assert lg.dropped == 4 and lg.logged == 4
        parts = lg.committed_parts()
        assert len(parts) == 1
        # every committed part parses end to end (never torn)
        for p in parts:
            for line in open(p):
                json.loads(line)


# ---------------------------------------------------------------------------
# checkpoint verification satellite
# ---------------------------------------------------------------------------

def _small_fit(ckdir, steps=8, every=2):
    from synapseml_tpu.data.source import MemorySource
    from synapseml_tpu.models.trainer import fit_source
    from synapseml_tpu.parallel.checkpoint import AsyncCheckpointer

    X, y = make_rows(64, 2)
    with AsyncCheckpointer(str(ckdir), keep=10) as ck:
        return fit_source(_trainer(steps), MemorySource(
            {"x": X, "labels": y}, shard_rows=32),
            batch_size=16, total_steps=steps, seed=3, scan_chunk=1,
            checkpointer=ck, checkpoint_every=every)


def test_checkpoint_sidecar_verification_demotes(tmp_path):
    from synapseml_tpu.parallel.checkpoint import (
        CheckpointCorrupt, latest_step, latest_verified_step,
        restore_checkpoint, verify_checkpoint)

    _small_fit(tmp_path)
    newest = latest_step(str(tmp_path))
    assert verify_checkpoint(str(tmp_path), newest)
    # corrupt the newest payload in place (torn write / bit rot)
    npz = os.path.join(str(tmp_path), f"step_{newest:010d}", "state.npz")
    with open(npz, "r+b") as f:
        f.seek(80)
        f.write(b"\xff\xff\xff\xff")
    assert not verify_checkpoint(str(tmp_path), newest)
    demoted = latest_verified_step(str(tmp_path))
    assert demoted is not None and demoted < newest
    # default restore demotes; explicitly asking for the corrupt step raises
    tree = restore_checkpoint(str(tmp_path))
    assert int(np.asarray(tree["step"])) == demoted
    with pytest.raises(CheckpointCorrupt):
        restore_checkpoint(str(tmp_path), step=newest)
    # the tree-structure JSON is a payload too: tearing it demotes again
    with open(os.path.join(str(tmp_path), f"step_{demoted:010d}",
                           "state.tree.json"), "a") as f:
        f.write("garbage")
    assert not verify_checkpoint(str(tmp_path), demoted)
    assert latest_verified_step(str(tmp_path)) < demoted


# ---------------------------------------------------------------------------
# trainer satellites: non-finite guard + skip windows
# ---------------------------------------------------------------------------

def test_nonfinite_loss_counts_and_raises():
    from synapseml_tpu.core import observability as obs
    from synapseml_tpu.data.source import MemorySource
    from synapseml_tpu.models.trainer import NonFiniteLossError, fit_source

    X, y = make_rows(64, 4)
    X_bad = X.copy()
    X_bad[32:48] = np.nan  # third 16-row shard poisons step 2 (unshuffled)
    src = MemorySource({"x": X_bad, "labels": y}, shard_rows=16)

    t = _trainer(4, action="count")
    before = obs.get_registry().counter(
        "synapseml_train_nonfinite_total", "", ("engine",))
    n0 = before.labels(engine="trainer").value
    # chunked path: losses are already materialized per chunk, so "count"
    # mode observes them for free (the per-step path samples log windows)
    fit_source(t, src, batch_size=16, total_steps=4, seed=0, scan_chunk=4,
               shuffle_rows="none")
    assert before.labels(engine="trainer").value > n0  # counted, not raised
    assert t.last_finite_step >= 2

    t2 = _trainer(4, action="raise")
    with pytest.raises(NonFiniteLossError) as ei:
        fit_source(t2, MemorySource({"x": X_bad, "labels": y},
                                    shard_rows=16),
                   batch_size=16, total_steps=4, seed=0, scan_chunk=1,
                   shuffle_rows="none")
    # the shard order is a seeded permutation: the poisoned step is
    # deterministic per seed but not positionally pinned here
    assert 1 <= ei.value.step <= 4
    assert ei.value.last_finite_step == ei.value.step - 1


def test_fit_source_skip_fn_consumes_without_training():
    import jax

    from synapseml_tpu.data.source import MemorySource
    from synapseml_tpu.models.trainer import fit_source

    X, y = make_rows(64, 5)

    def run(skip):
        t = _trainer(4)
        return fit_source(t, MemorySource({"x": X, "labels": y},
                                          shard_rows=16),
                          batch_size=16, total_steps=4, seed=6,
                          scan_chunk=1, skip_fn=skip)

    full = run(None)
    skipped = run(lambda i: True)  # consume everything, train nothing
    assert int(skipped.step) == int(full.step) == 4
    assert not _params_equal(full.params, skipped.params)
    # skipping batch 0 only: steps still advance to the total
    partial = run(lambda i: i == 0)
    assert int(partial.step) == 4
    assert not _params_equal(partial.params, full.params)
    leaves = [np.ptp(np.asarray(x)) for x in jax.tree.leaves(skipped.params)]
    assert any(v > 0 for v in leaves)  # params are the real init, not zeros


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------

def _supervised_fit(att, ckdir, steps=12):
    from synapseml_tpu.data.source import MemorySource
    from synapseml_tpu.models.trainer import fit_source
    from synapseml_tpu.parallel.checkpoint import AsyncCheckpointer

    X, y = make_rows(96, 9)
    with AsyncCheckpointer(str(ckdir), keep=10) as ck:
        return fit_source(_trainer(steps), MemorySource(
            {"x": X, "labels": y}, shard_rows=32),
            batch_size=16, total_steps=steps, seed=9, scan_chunk=1,
            checkpointer=ck, checkpoint_every=3, resume_from=str(ckdir),
            skip_fn=att.skip_fn, callback=lambda i, m: att.heartbeat(i))


@pytest.mark.chaos
def test_supervisor_crash_restart_bit_parity(tmp_path):
    """Injected trainer crash at step 5 → bounded restart resumes from the
    latest verified checkpoint; final params bit-identical to an
    uninterrupted run (the checkpointable-iterator guarantee)."""
    from synapseml_tpu.continual import TrainSupervisor
    from synapseml_tpu.core.resilience import resilience_measures

    ref = _supervised_fit(_NoopAttempt(), tmp_path / "ref")

    sup = TrainSupervisor(str(tmp_path / "sup"), max_restarts=2)
    r0 = resilience_measures("training").to_dict().get("retry_count", 0)
    with inject_faults([FaultSpec("crash", match="step:5", times=1,
                                  planes=("training",))]) as plan:
        state = sup.run(lambda att: _supervised_fit(att, tmp_path / "sup"))
    assert sup.restarts == 1
    assert len(plan.injected) == 1
    assert resilience_measures("training").to_dict().get(
        "retry_count", 0) == r0 + 1
    assert int(state.step) == 12
    assert _params_equal(ref.params, state.params)


class _NoopAttempt:
    skip_fn = None
    resume = False

    def heartbeat(self, step):
        pass


@pytest.mark.chaos
def test_supervisor_nan_rewind_skips_poisoned_window(tmp_path):
    """A NaN batch raises; the supervisor rewinds to the verified
    checkpoint and the retry SKIPS the poisoned window — training
    completes with finite params and the rewind counters move."""
    from synapseml_tpu.continual import TrainSupervisor
    from synapseml_tpu.data.source import MemorySource
    from synapseml_tpu.models.trainer import fit_source
    from synapseml_tpu.parallel.checkpoint import AsyncCheckpointer

    X, y = make_rows(128, 10)
    X[96:112] = np.nan  # shard 6 of 8 → poisons exactly one batch

    def attempt(att):
        with AsyncCheckpointer(str(tmp_path), keep=10) as ck:
            return fit_source(
                _trainer(8), MemorySource({"x": X, "labels": y},
                                          shard_rows=16),
                batch_size=16, total_steps=8, seed=0, scan_chunk=1,
                shuffle_rows="none", checkpointer=ck, checkpoint_every=2,
                resume_from=str(tmp_path), skip_fn=att.skip_fn,
                callback=lambda i, m: att.heartbeat(i))

    sup = TrainSupervisor(str(tmp_path), max_restarts=1, max_rewinds=2)
    state = sup.run(attempt)
    assert sup.rewinds == 1 and sup.restarts == 0
    assert int(state.step) == 8
    assert all(np.isfinite(np.asarray(x)).all()
               for x in __import__("jax").tree.leaves(state.params))
    lo, hi = sup.skip_windows[0]
    assert 0 <= lo < hi <= 8  # window covers the seed-placed poisoned step


_CHILD_SCRIPT = r"""
import os, signal, sys, time
ckdir, mode, marker = sys.argv[1], sys.argv[2], sys.argv[3]
import numpy as np
import flax.linen as nn
from synapseml_tpu.models.trainer import Trainer, TrainerConfig, fit_source
from synapseml_tpu.parallel.mesh import MeshConfig, create_mesh
from synapseml_tpu.parallel.checkpoint import (AsyncCheckpointer,
                                               latest_verified_step)
from synapseml_tpu.data.source import MemorySource

class MLP(nn.Module):
    @nn.compact
    def __call__(self, x):
        return nn.Dense(3)(nn.relu(nn.Dense(8)(x)))

r = np.random.default_rng(9)
X = r.normal(size=(96, 4)).astype(np.float32)
y = (np.arange(96) % 3).astype(np.int32)
t = Trainer(MLP(), create_mesh(MeshConfig()),
            TrainerConfig(total_steps=16, learning_rate=0.05))
base = latest_verified_step(ckdir) or 0

def cb(i, m):
    if mode != "clean" and not os.path.exists(marker) and base + i == 6:
        with open(marker, "w") as f:
            f.write("hit")
        if mode == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(3600)  # mode == "hang": wedge without dying

with AsyncCheckpointer(ckdir, keep=10) as ck:
    fit_source(t, MemorySource({"x": X, "labels": y}, shard_rows=32),
               batch_size=16, total_steps=16, seed=9, scan_chunk=1,
               checkpointer=ck, checkpoint_every=3, resume_from=ckdir,
               callback=cb)
"""


@pytest.mark.chaos(timeout_s=300)
def test_supervisor_subprocess_sigkill_and_hang_watchdog(tmp_path):
    """The real thing: a subprocess trainer SIGKILLed mid-fit resumes to a
    final state byte-identical to an uninterrupted run; a WEDGED trainer
    (no checkpoint progress) is hang-detected, killed and restarted."""
    from synapseml_tpu.continual import TrainSupervisor
    from synapseml_tpu.parallel.checkpoint import restore_checkpoint

    script = tmp_path / "child.py"
    script.write_text(_CHILD_SCRIPT)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))

    def run(mode, hang_timeout=60.0):
        ckdir = tmp_path / mode
        sup = TrainSupervisor(str(ckdir), max_restarts=2,
                              hang_timeout_s=hang_timeout, poll_s=0.2)
        attempts = sup.run_subprocess(
            [sys.executable, str(script), str(ckdir), mode,
             str(tmp_path / f"{mode}.marker")], env=env, timeout_s=240)
        return sup, attempts, restore_checkpoint(str(ckdir), step=16)

    _, attempts, clean = run("clean")
    assert attempts == 1

    sup_k, attempts_k, killed = run("kill")
    assert attempts_k == 2 and sup_k.restarts == 1
    assert _params_equal(clean["params"], killed["params"])

    sup_h, attempts_h, hung = run("hang", hang_timeout=5.0)
    assert attempts_h == 2 and sup_h.restarts == 1
    assert _params_equal(clean["params"], hung["params"])


# ---------------------------------------------------------------------------
# the loop (no fleet): gate + containment
# ---------------------------------------------------------------------------

def _loop_fixture(tmp_path, **spec_kw):
    from synapseml_tpu.continual import ContinualLoop, ContinualSpec

    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish("m", _v1_stage(), version="v1")
    reg.pin("m", "prod", "v1")
    logdir = tmp_path / "log"
    os.makedirs(logdir, exist_ok=True)
    kw = {"min_new_rows": 50, "gate_min_margin": 0.05, "seed": 5}
    kw.update(spec_kw)
    spec = ContinualSpec(model="m", **kw)
    loop = ContinualLoop(spec, reg, str(logdir), make_train_fn(), eval_fn,
                         row_fn=row_fn, state_dir=str(tmp_path / "state"))
    return reg, logdir, loop


def _write_clean_parts(logdir, start=0, n_parts=8, rows=30, seed=7):
    X, y = make_rows(n_parts * rows, seed)
    for k in range(n_parts):
        write_part(str(logdir), start + k, X[k * rows:(k + 1) * rows],
                   y[k * rows:(k + 1) * rows])


def test_loop_promotes_then_fails_gate_on_poison(tmp_path):
    """Iteration 1 (clean data): candidate beats prod → promoted.
    Iteration 2 (poisoned train parts, clean holdout): gate fails, prod
    untouched, malformed + label-less rows quarantined."""
    reg, logdir, loop = _loop_fixture(tmp_path)
    _write_clean_parts(logdir)
    rec = loop.run_once()
    assert rec["outcome"] == "promoted", rec
    v2 = rec["version"]
    assert reg.alias_target("m", "prod") == v2
    assert rec["gate"]["margin"] > 0.05
    assert loop.state["champion_ckpt"]

    # craft iteration 2 so the poisoned parts land in the TRAIN split and
    # the clean ones in the HOLDOUT split (the split is a seeded hash)
    names = [f"part-{i:05d}.jsonl" for i in range(90, 102)]
    holdout = [n for n in names if loop._holdout_part(n)]
    train = [n for n in names if not loop._holdout_part(n)]
    assert holdout and train
    Xp, yp = make_rows(300, 11, poison=True)
    Xc, yc = make_rows(120, 12)
    for j, n in enumerate(train):
        write_part(str(logdir), int(n[5:10]), Xp[j * 30:(j + 1) * 30],
                   yp[j * 30:(j + 1) * 30], garbage=2, drop_y=2)
    for j, n in enumerate(holdout):
        write_part(str(logdir), int(n[5:10]), Xc[j * 16:(j + 1) * 16],
                   yc[j * 16:(j + 1) * 16])

    rec2 = loop.run_once()
    assert rec2["outcome"] == "gate_failed", rec2
    assert rec2["quarantined"] >= 2 * len(train)  # garbage + label-less rows
    assert reg.alias_target("m", "prod") == v2  # prod untouched
    assert reg.list_versions("m") == ["v1", v2]  # nothing published


def test_loop_skips_when_not_due_and_drift_triggers(tmp_path):
    from synapseml_tpu.core import observability as obs

    reg, logdir, loop = _loop_fixture(tmp_path, min_new_rows=10_000,
                                      drift_gauge="synapseml_test_drift",
                                      drift_threshold=0.5)
    _write_clean_parts(logdir, n_parts=2)
    ok, reason = loop.should_run()
    assert not ok
    rec = loop.run_once()
    assert rec["outcome"] == "skipped:not_due"
    assert loop._new_parts()  # nothing consumed
    obs.get_registry().gauge("synapseml_test_drift", "t").set(0.9)
    ok, reason = loop.should_run()
    assert ok and "drift" in reason


@pytest.mark.chaos
def test_loop_seam_faults_contained(tmp_path):
    """A seeded fault at EVERY seam aborts exactly one iteration with
    ``prod`` untouched; the next iteration (fault exhausted) promotes."""
    reg, logdir, loop = _loop_fixture(tmp_path)
    _write_clean_parts(logdir)
    for seam in ("watch", "snapshot", "train", "eval", "publish",
                 "promote"):
        with inject_faults([FaultSpec("crash", match=f"m:{seam}", times=1,
                                      planes=("continual",))]) as plan:
            rec = loop.run_once()
        assert rec["outcome"] == f"error:{seam}", (seam, rec)
        assert len(plan.injected) == 1
        # the containment contract: prod NEVER moves on a failed iteration
        assert reg.alias_target("m", "prod") == "v1", seam
        if seam != "promote":
            # ...and nothing is published before the promote seam
            assert reg.list_versions("m") == ["v1"], seam
        if seam in ("eval", "publish", "promote"):
            # those iterations consumed the data before failing — refeed
            _write_clean_parts(logdir,
                               start=200 + 10 * len(loop.history))
    # raise_errors: same containment + recorded outcome, then re-raised
    from synapseml_tpu.continual import LoopAborted

    with inject_faults([FaultSpec("crash", match="m:watch", times=1,
                                  planes=("continual",))]):
        with pytest.raises(LoopAborted):
            loop.run_once(raise_errors=True)
    assert loop.history[-1]["outcome"] == "error:watch"
    assert reg.alias_target("m", "prod") == "v1"

    rec = loop.run_once()  # no plan active: the loop recovered
    assert rec["outcome"] == "promoted"
    assert reg.alias_target("m", "prod") == rec["version"]


# ---------------------------------------------------------------------------
# E2E flywheel acceptance: two live-fleet iterations + SIGKILL-equivalent
# mid-train crash + canary p95 rollback
# ---------------------------------------------------------------------------

def _post(address, body: dict, path="/"):
    req = urllib.request.Request(
        address + path, data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=15) as r:
        return r.read()


def _send_labeled_traffic(address, n, seed):
    X, y = make_rows(n, seed)
    for i in range(n):
        _post(address, {"x": [float(v) for v in X[i]], "y": int(y[i])})


@pytest.mark.chaos(timeout_s=420)
def test_e2e_flywheel_two_iterations_live_fleet(tmp_path):
    """The ISSUE-14 acceptance: a live 2-worker fleet serves v1; logged
    traffic retrains it. Iteration 1 survives a mid-train trainer crash
    (supervisor restart) and promotes a genuinely better v2 through the
    canary — its params BYTE-IDENTICAL to an uninterrupted reference
    iteration. Iteration 2 is fed fault-injected (poisoned) data, fails
    the gate, and prod + its serving outputs are byte-identical to before.
    Iteration 3 passes the gate but regresses canary p95 — auto-rollback
    leaves prod untouched."""
    import dataclasses

    from synapseml_tpu.continual import (ContinualLoop, ContinualSpec,
                                         RequestLogger)
    from synapseml_tpu.io.distributed_serving import \
        serve_pipeline_distributed

    v1 = _v1_stage()
    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish("m", v1, version="v1")
    reg.pin("m", "prod", "v1")
    logdir = str(tmp_path / "log")

    handle = serve_pipeline_distributed(v1, num_workers=2,
                                        batch_interval_ms=0, version="v1")
    lg = None
    try:
        lg = RequestLogger(logdir, shard_rows=30, seed=0)
        handle.front.set_request_logger(lg)
        _send_labeled_traffic(handle.address, 240, seed=7)
        lg.flush()
        assert lg.stats()["logged"] == 240

        dep = Deployment(handle, reg, "m", warmup=[{"x": [0.0] * D_IN}])
        spec = ContinualSpec(model="m", min_new_rows=50,
                             gate_min_margin=0.05, seed=5,
                             canary_weight=0.5, canary_min_requests=8,
                             canary_timeout_s=90.0, canary={})
        loop = ContinualLoop(spec, reg, logdir, make_train_fn(), eval_fn,
                             row_fn=row_fn, deployment=dep,
                             state_dir=str(tmp_path / "state"))

        # uninterrupted REFERENCE iteration: same spec/seed/log snapshot,
        # separate registry + state, no fleet — the parity baseline
        ref_reg = ModelRegistry(str(tmp_path / "ref_reg"))
        ref_reg.publish("m", v1, version="v1")
        ref_reg.pin("m", "prod", "v1")
        ref_loop = ContinualLoop(
            dataclasses.replace(spec), ref_reg, logdir, make_train_fn(),
            eval_fn, row_fn=row_fn, state_dir=str(tmp_path / "ref_state"))
        ref_rec = ref_loop.run_once()
        assert ref_rec["outcome"] == "promoted", ref_rec
        ref_params = ref_reg.resolve("m", "prod").stage.get("params")

        # ---- iteration 1: crash mid-train, restart, canary, promote ----
        with inject_faults([FaultSpec("crash", match="step:11", times=1,
                                      planes=("training",))]):
            rec1 = loop.run_once()
        assert rec1["outcome"] == "promoted", rec1
        assert rec1["supervisor"]["restarts"] == 1
        v2 = rec1["version"]
        assert reg.alias_target("m", "prod") == v2
        # killed-and-resumed candidate == uninterrupted reference, bytes
        prod_params = reg.resolve("m", "prod").stage.get("params")
        assert _params_equal(ref_params, prod_params)
        # the whole fleet now serves v2
        for w in handle.registry.workers():
            assert w.get("version") == v2

        probe = {"x": [0.1, -0.2, 0.3, 0.4]}
        r0 = _post(handle.address, probe)

        # ---- iteration 2: poisoned data fails the gate ----
        names = [f"part-{i:05d}.jsonl" for i in range(900, 912)]
        holdout = [n for n in names if loop._holdout_part(n)]
        train = [n for n in names if not loop._holdout_part(n)]
        assert holdout and train
        Xp, yp = make_rows(360, 11, poison=True)
        Xc, yc = make_rows(120, 12)
        for j, n in enumerate(train):
            write_part(logdir, int(n[5:10]), Xp[j * 30:(j + 1) * 30],
                       yp[j * 30:(j + 1) * 30], garbage=2, drop_y=1)
        for j, n in enumerate(holdout):
            write_part(logdir, int(n[5:10]), Xc[j * 16:(j + 1) * 16],
                       yc[j * 16:(j + 1) * 16])
        rec2 = loop.run_once()
        assert rec2["outcome"] == "gate_failed", rec2
        assert rec2["quarantined"] > 0
        assert reg.alias_target("m", "prod") == v2  # prod untouched...
        assert _post(handle.address, probe) == r0   # ...and so is serving
        assert _params_equal(
            prod_params, reg.resolve("m", "prod").stage.get("params"))

        # ---- iteration 3: gate passes, canary p95 regresses, rollback ---
        _send_labeled_traffic(handle.address, 120, seed=21)
        lg.flush()
        spec3 = dataclasses.replace(
            spec, gate_min_margin=-1e9, canary_min_requests=3,
            canary={"p95_regression_factor": 1e-6,
                    "min_latency_samples": 1,
                    "error_rate_threshold": 1.0, "window": 1000,
                    "min_samples": 1000})
        loop3 = ContinualLoop(spec3, reg, logdir, make_train_fn(), eval_fn,
                              row_fn=row_fn, deployment=dep,
                              state_dir=str(tmp_path / "state"))
        rec3 = loop3.run_once()
        assert rec3["outcome"] == "canary_rolled_back", rec3
        assert reg.alias_target("m", "prod") == v2
        assert _post(handle.address, probe) == r0
        # loop health series moved
        from synapseml_tpu.core import observability as obs

        snap = obs.get_registry().snapshot()
        assert any(k.startswith("synapseml_continual_iterations_total")
                   for k in snap)
    finally:
        if lg is not None:
            lg.close()
        handle.stop()
