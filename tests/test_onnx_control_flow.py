"""ONNX Loop/Scan subgraph ops lowered to lax control flow, plus the
dynamically-shaped eager-only tail (NonZero/Compress/Unique) and remaining
unary/normalization ops. The reference runs these through ONNX Runtime behind
ONNXModel (`ONNXRuntime.scala:25`); here Scan becomes one compiled lax.scan
step and Loop picks between exact eager semantics and lax.while_loop/scan."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from synapseml_tpu.onnx import (
    AttributeProto,
    GraphProto,
    ModelProto,
    NodeProto,
    ValueInfoProto,
    numpy_to_tensor,
)
from synapseml_tpu.onnx import proto as P
from synapseml_tpu.onnx.convert import OP_REGISTRY, ConvertedModel


def node(op, inputs, outputs, **attrs):
    return NodeProto(input=list(inputs), output=list(outputs), op_type=op,
                     attribute=[AttributeProto.make(k, v)
                                for k, v in attrs.items()])


def run_op(op, ins, **attrs):
    out = OP_REGISTRY[op]([None if x is None else np.asarray(x) for x in ins],
                          attrs)
    return out


def vi(name, dims=()):
    return ValueInfoProto(name=name, elem_type=P.FLOAT, dims=list(dims))


# ---------------------------------------------------------------------------
# unary / normalization tail
# ---------------------------------------------------------------------------

rs = np.random.default_rng(0)
X = rs.uniform(-0.9, 0.9, size=(3, 5)).astype(np.float32)


@pytest.mark.parametrize("op,ref", [
    ("Tan", np.tan), ("Asin", np.arcsin), ("Acos", np.arccos),
    ("Atan", np.arctan), ("Sinh", np.sinh), ("Cosh", np.cosh),
    ("Asinh", np.arcsinh), ("Atanh", np.arctanh),
])
def test_trig_unary(op, ref):
    np.testing.assert_allclose(np.asarray(run_op(op, [X])), ref(X),
                               rtol=1e-5, atol=1e-6)


def test_acosh():
    x = (1.0 + np.abs(X) * 3).astype(np.float32)
    np.testing.assert_allclose(np.asarray(run_op("Acosh", [x])), np.arccosh(x),
                               rtol=1e-5)


def test_hardmax():
    out = np.asarray(run_op("Hardmax", [X], axis=-1))
    expect = np.zeros_like(X)
    expect[np.arange(3), X.argmax(-1)] = 1.0
    np.testing.assert_array_equal(out, expect)
    out0 = np.asarray(run_op("Hardmax", [X], axis=0))
    assert out0.sum(axis=0).tolist() == [1.0] * 5


def test_lrn_matches_window_spec():
    x = rs.normal(size=(2, 7, 3, 3)).astype(np.float32)
    size, alpha, beta, bias = 3, 2e-4, 0.6, 1.5
    out = np.asarray(run_op("LRN", [x], size=size, alpha=alpha, beta=beta,
                            bias=bias))
    C = x.shape[1]
    lo = (size - 1) // 2
    expect = np.empty_like(x)
    for c in range(C):
        w = slice(max(0, c - lo), min(C, c + (size - 1 - lo) + 1))
        s = (x[:, w] ** 2).sum(axis=1)
        expect[:, c] = x[:, c] / (bias + alpha / size * s) ** beta
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)


def test_lp_normalization():
    np.testing.assert_allclose(
        np.asarray(run_op("LpNormalization", [X], axis=1, p=2)),
        X / np.linalg.norm(X, axis=1, keepdims=True), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(run_op("LpNormalization", [X], axis=0, p=1)),
        X / np.abs(X).sum(0, keepdims=True), rtol=1e-5)


def test_global_lp_pool():
    x = rs.normal(size=(2, 3, 4, 5)).astype(np.float32)
    out = np.asarray(run_op("GlobalLpPool", [x], p=2))
    expect = np.sqrt((x ** 2).sum(axis=(2, 3), keepdims=True))
    assert out.shape == (2, 3, 1, 1)
    np.testing.assert_allclose(out, expect, rtol=1e-5)


# ---------------------------------------------------------------------------
# dynamically-shaped eager-only ops
# ---------------------------------------------------------------------------

def test_nonzero():
    x = np.array([[1, 0, 2], [0, 3, 0]], np.float32)
    out = run_op("NonZero", [x])
    np.testing.assert_array_equal(out, np.stack(np.nonzero(x)))
    assert out.dtype == np.int64


def test_nonzero_rejected_under_jit():
    with pytest.raises(NotImplementedError, match="eager"):
        jax.jit(lambda x: OP_REGISTRY["NonZero"]([x], {}))(jnp.ones((3,)))


def test_compress():
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    cond = np.array([0, 1, 1, 0], bool)
    np.testing.assert_array_equal(np.asarray(run_op("Compress", [x, cond],
                                                    axis=1)),
                                  np.compress(cond, x, axis=1))
    flat_cond = np.array([1, 0, 1, 0, 1], bool)
    np.testing.assert_array_equal(
        np.asarray(run_op("Compress", [x, flat_cond])),
        np.compress(flat_cond, x.ravel()))


def test_compress_traced_data_concrete_condition():
    # condition concrete => static output shape => data may stay traced
    x = np.arange(8, dtype=np.float32).reshape(2, 4)
    cond = np.array([1, 0, 1, 0], bool)
    out = jax.jit(lambda d: OP_REGISTRY["Compress"]([d, cond], {"axis": 1}))(x)
    np.testing.assert_array_equal(np.asarray(out), x[:, [0, 2]])


@pytest.mark.parametrize("sorted_", [1, 0])
def test_unique(sorted_):
    x = np.array([2, 1, 1, 3, 4, 3], np.int64)
    y, idx, inv, counts = run_op("Unique", [x], sorted=sorted_)
    if sorted_:
        expect = np.array([1, 2, 3, 4])
    else:
        expect = np.array([2, 1, 3, 4])  # first-occurrence order
    np.testing.assert_array_equal(y, expect)
    np.testing.assert_array_equal(np.asarray(y)[inv], x)  # inverse rebuilds x
    np.testing.assert_array_equal(counts, [np.sum(x == v) for v in expect])
    np.testing.assert_array_equal(x[idx], expect)  # first occurrences


def test_unique_axis():
    x = np.array([[1, 1], [2, 3], [1, 1]], np.float32)
    y, idx, inv, counts = run_op("Unique", [x], axis=0)
    np.testing.assert_array_equal(y, [[1, 1], [2, 3]])
    np.testing.assert_array_equal(counts, [2, 1])


# ---------------------------------------------------------------------------
# Scan
# ---------------------------------------------------------------------------

def scan_cumsum_model(reverse=False, out_axis=0):
    """state s; per-step: s' = s + x_t, scan-output s' — a running cumsum."""
    body = GraphProto(
        name="body",
        node=[node("Add", ["s_in", "x_t"], ["s_out"]),
              node("Identity", ["s_out"], ["y_t"])],
        input=[vi("s_in", [2]), vi("x_t", [2])],
        output=[vi("s_out", [2]), vi("y_t", [2])],
    )
    attrs = dict(body=body, num_scan_inputs=1)
    if reverse:
        attrs["scan_input_directions"] = [1]
        attrs["scan_output_directions"] = [1]
    if out_axis:
        attrs["scan_output_axes"] = [out_axis]
    g = GraphProto(
        name="scan_cumsum",
        node=[node("Scan", ["s0", "xs"], ["s_final", "ys"], **attrs)],
        input=[vi("s0", [2]), vi("xs", [5, 2])],
        output=[vi("s_final", [2]),
                vi("ys", [2, 5] if out_axis else [5, 2])],
    )
    return ConvertedModel(ModelProto(graph=g))


def test_scan_cumsum_eager_and_jit():
    m = scan_cumsum_model()
    xs = rs.normal(size=(5, 2)).astype(np.float32)
    s0 = np.zeros(2, np.float32)
    out = m(s0=s0, xs=xs)
    np.testing.assert_allclose(np.asarray(out["s_final"]), xs.sum(0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out["ys"]), np.cumsum(xs, 0),
                               rtol=1e-5)
    jout = m.jit_fn()(s0, xs)
    np.testing.assert_allclose(np.asarray(jout["ys"]), np.cumsum(xs, 0),
                               rtol=1e-5)


def test_scan_reverse_direction():
    m = scan_cumsum_model(reverse=True)
    xs = rs.normal(size=(5, 2)).astype(np.float32)
    out = m(s0=np.zeros(2, np.float32), xs=xs)
    # reverse scan + reverse output = suffix sums aligned with input order
    np.testing.assert_allclose(np.asarray(out["ys"]),
                               np.cumsum(xs[::-1], 0)[::-1], rtol=1e-5)


def test_scan_output_axis():
    m = scan_cumsum_model(out_axis=1)
    xs = rs.normal(size=(5, 2)).astype(np.float32)
    out = m(s0=np.zeros(2, np.float32), xs=xs)
    np.testing.assert_allclose(np.asarray(out["ys"]), np.cumsum(xs, 0).T,
                               rtol=1e-5)


# ---------------------------------------------------------------------------
# Loop
# ---------------------------------------------------------------------------

def loop_model(with_scan_output=True, early_exit_at=None):
    """Loop body: s' = s + (i+1)^2; optional scan output s'; optional
    cond_out = i < early_exit_at - 1 (else constant true)."""
    body_nodes = [
        node("Cast", ["i"], ["i_f"], to=P.FLOAT),
        node("Add", ["i_f", "one"], ["i1"]),
        node("Mul", ["i1", "i1"], ["sq"]),
        node("Add", ["s_in", "sq"], ["s_out"]),
    ]
    if early_exit_at is None:
        body_nodes.append(node("Identity", ["cond_in"], ["cond_out"]))
    else:
        body_nodes += [
            node("Cast", ["i"], ["i64"], to=P.INT64),
            node("Less", ["i64", "limit"], ["cond_out"]),
        ]
    outputs = [vi("cond_out"), vi("s_out")]
    if with_scan_output:
        body_nodes.append(node("Identity", ["s_out"], ["y_t"]))
        outputs.append(vi("y_t"))
    inits = [numpy_to_tensor(np.float32(1.0), "one")]
    if early_exit_at is not None:
        inits.append(numpy_to_tensor(np.int64(early_exit_at - 1), "limit"))
    body = GraphProto(name="body", node=body_nodes,
                      input=[vi("i"), vi("cond_in"), vi("s_in")],
                      output=outputs, initializer=inits)
    g_outputs = [vi("s_final")]
    loop_outs = ["s_final"]
    if with_scan_output:
        loop_outs.append("ys")
        g_outputs.append(vi("ys", [None]))
    g = GraphProto(
        name="loop_model",
        node=[node("Loop", ["M", "cond0", "s0"], loop_outs, body=body)],
        input=[ValueInfoProto(name="M", elem_type=P.INT64, dims=[]),
               ValueInfoProto(name="cond0", elem_type=P.BOOL, dims=[]),
               vi("s0")],
        output=g_outputs,
    )
    return ConvertedModel(ModelProto(graph=g))


def test_loop_for_eager():
    m = loop_model()
    out = m(M=np.int64(4), cond0=np.array(True), s0=np.float32(0.0))
    # sum of squares 1+4+9+16
    assert float(out["s_final"]) == 30.0
    np.testing.assert_allclose(np.asarray(out["ys"]), [1, 5, 14, 30])


def test_loop_for_jit_static_m():
    m = loop_model()
    # M concrete (closure), data traced -> lax.scan path
    fn = jax.jit(lambda s0: m(M=np.int64(4), cond0=np.array(True), s0=s0))
    out = fn(jnp.float32(0.0))
    assert float(out["s_final"]) == 30.0
    np.testing.assert_allclose(np.asarray(out["ys"]), [1, 5, 14, 30])


def test_loop_early_exit_eager_dynamic_length():
    m = loop_model(early_exit_at=3)
    out = m(M=np.int64(100), cond0=np.array(True), s0=np.float32(0.0))
    # exits after iteration 3: scan output has EXACTLY 3 rows (dynamic length)
    np.testing.assert_allclose(np.asarray(out["ys"]), [1, 5, 14])
    assert float(out["s_final"]) == 14.0


def test_loop_while_traced_state_only():
    m = loop_model(with_scan_output=False, early_exit_at=5)
    fn = jax.jit(lambda s0: m(M=np.int64(100), cond0=np.array(True), s0=s0))
    out = fn(jnp.float32(0.0))  # lax.while_loop path
    assert float(out["s_final"]) == sum((k + 1) ** 2 for k in range(5))


def test_loop_traced_early_exit_with_scan_output_rejected():
    m = loop_model(early_exit_at=3)
    with pytest.raises(NotImplementedError, match="static"):
        # M itself traced + scan outputs => dynamic output shape
        jax.jit(lambda M, s0: m(M=M, cond0=np.array(True), s0=s0))(
            jnp.int64(4) if jax.config.jax_enable_x64 else jnp.int32(4),
            jnp.float32(0.0))


def test_loop_jit_data_dependent_cond_with_scan_output_rejected():
    # concrete M but the body's cond_out depends on traced data: must raise,
    # not silently run all M iterations (eager answer would be [1, 5, 14])
    m = loop_model(early_exit_at=3)
    with pytest.raises(NotImplementedError, match="data-dependent"):
        jax.jit(lambda s0: m(M=np.int64(100), cond0=np.array(True), s0=s0))(
            jnp.float32(0.0))


def test_loop_while_int64_max_trip_count():
    # torch exports while-loops with M = INT64_MAX; must clamp, not wrap
    m = loop_model(with_scan_output=False, early_exit_at=5)
    fn = jax.jit(lambda s0: m(M=np.int64(np.iinfo(np.int64).max),
                              cond0=np.array(True), s0=s0))
    out = fn(jnp.float32(0.0))
    assert float(out["s_final"]) == sum((k + 1) ** 2 for k in range(5))


def test_loop_zero_trip_scan_output_shape():
    # cond0 false: scan output must keep the per-step row shape/dtype, (0,)+row
    body = GraphProto(
        name="body",
        node=[node("Identity", ["cond_in"], ["cond_out"]),
              node("Identity", ["s_in"], ["s_out"]),
              node("Identity", ["s_in"], ["y_t"])],
        input=[vi("i"), vi("cond_in"), vi("s_in", [2])],
        output=[vi("cond_out"), vi("s_out", [2]), vi("y_t", [2])],
    )
    g = GraphProto(
        name="zero_trip",
        node=[node("Loop", ["M", "cond0", "s0"], ["s_final", "ys"], body=body)],
        input=[ValueInfoProto(name="M", elem_type=P.INT64, dims=[]),
               ValueInfoProto(name="cond0", elem_type=P.BOOL, dims=[]),
               vi("s0", [2])],
        output=[vi("s_final", [2]), vi("ys", [None, 2])],
    )
    m = ConvertedModel(ModelProto(graph=g))
    out = m(M=np.int64(5), cond0=np.array(False), s0=np.ones(2, np.float32))
    assert np.asarray(out["ys"]).shape == (0, 2)
    np.testing.assert_array_equal(np.asarray(out["s_final"]), [1, 1])


def test_loop_jit_concrete_false_cond_zero_trips():
    # concrete cond0=False under jit: zero iterations, correctly-shaped
    # empty scan output — not M silently-executed trips
    m = loop_model()
    out = jax.jit(lambda s0: m(M=np.int64(5), cond0=np.array(False), s0=s0))(
        jnp.float32(7.0))
    assert float(out["s_final"]) == 7.0
    assert np.asarray(out["ys"]).shape == (0,)


def test_loop_jit_traced_cond0_with_scan_output_rejected():
    m = loop_model()
    with pytest.raises(NotImplementedError, match="concrete"):
        jax.jit(lambda c, s0: m(M=np.int64(5), cond0=c, s0=s0))(
            jnp.asarray(True), jnp.float32(0.0))


def if_model():
    """out = x*2 if mean(x) > 0 else x-1 — a DATA-dependent If."""
    then_b = GraphProto(name="then",
                        node=[node("Mul", ["x", "two"], ["y_then"])],
                        initializer=[numpy_to_tensor(np.float32(2.0), "two")],
                        output=[vi("y_then", [3])])
    else_b = GraphProto(name="else",
                        node=[node("Sub", ["x", "one"], ["y_else"])],
                        initializer=[numpy_to_tensor(np.float32(1.0), "one")],
                        output=[vi("y_else", [3])])
    g = GraphProto(
        name="data_if",
        node=[node("ReduceMean", ["x"], ["m"], keepdims=0),
              node("Greater", ["m", "zero"], ["cond"]),
              node("If", ["cond"], ["y"], then_branch=then_b,
                   else_branch=else_b)],
        initializer=[numpy_to_tensor(np.float32(0.0), "zero")],
        input=[vi("x", [3])],
        output=[vi("y", [3])],
    )
    return ConvertedModel(ModelProto(graph=g))


def test_if_data_dependent_condition():
    m = if_model()
    pos = np.asarray([1.0, 2.0, 3.0], np.float32)
    neg = np.asarray([-1.0, -2.0, -3.0], np.float32)
    # eager: concrete cond, single branch
    np.testing.assert_allclose(np.asarray(m(x=pos)["y"]), pos * 2)
    np.testing.assert_allclose(np.asarray(m(x=neg)["y"]), neg - 1)
    # jit: traced cond -> lax.cond, both branches compiled once
    fn = jax.jit(lambda x: m(x=x)["y"])
    np.testing.assert_allclose(np.asarray(fn(pos)), pos * 2)
    np.testing.assert_allclose(np.asarray(fn(neg)), neg - 1)


def test_if_shape_divergent_branches_rejected_under_jit():
    then_b = GraphProto(name="then",
                        node=[node("Identity", ["x"], ["a"])],
                        output=[vi("a", [3])])
    else_b = GraphProto(
        name="else",
        node=[node("Concat", ["x", "x"], ["b"], axis=0)],
        output=[vi("b", [6])])
    g = GraphProto(
        name="divergent",
        node=[node("ReduceMean", ["x"], ["m"], keepdims=0),
              node("Greater", ["m", "zero"], ["cond"]),
              node("If", ["cond"], ["y"], then_branch=then_b,
                   else_branch=else_b)],
        initializer=[numpy_to_tensor(np.float32(0.0), "zero")],
        input=[vi("x", [3])], output=[vi("y", [None])],
    )
    m = ConvertedModel(ModelProto(graph=g))
    with pytest.raises(NotImplementedError, match="matching shapes"):
        jax.jit(lambda x: m(x=x)["y"])(jnp.ones(3, jnp.float32))


def test_reduce_noop_with_empty_axes_omitted_input():
    # opset-18: axes omitted entirely + noop_with_empty_axes=1 => identity
    x = rs.normal(size=(2, 3)).astype(np.float32)
    out = OP_REGISTRY["ReduceSum"]([x], {"noop_with_empty_axes": 1})
    np.testing.assert_array_equal(np.asarray(out), x)
    # without the flag, reduce-all still holds
    out2 = OP_REGISTRY["ReduceSum"]([x], {})
    np.testing.assert_allclose(np.asarray(out2), x.sum(), rtol=1e-6)
