import numpy as np
import pytest

import jax
import jax.numpy as jnp

from synapseml_tpu.core import DataFrame, load_stage
from synapseml_tpu.models import (
    DeepTextClassifier,
    DeepTextModel,
    DeepVisionClassifier,
    HashingTokenizer,
)
from synapseml_tpu.models.flax_nets import (
    BertClassifier,
    LlamaLM,
    ViTClassifier,
    bert_tiny,
    greedy_generate,
    llama_tiny,
    resnet_tiny,
    vit_tiny,
)
from synapseml_tpu.parallel import MeshConfig


def make_text_df(n=64, seed=0):
    rng = np.random.default_rng(seed)
    pos_words = ["good", "great", "excellent", "love", "wonderful"]
    neg_words = ["bad", "awful", "terrible", "hate", "horrible"]
    texts, labels = [], []
    for _ in range(n):
        label = int(rng.integers(0, 2))
        words = rng.choice(pos_words if label else neg_words, size=5)
        texts.append(" ".join(words))
        labels.append(label)
    return DataFrame.from_dict({"text": texts, "label": np.array(labels, np.int32)},
                               num_partitions=2)


def test_hashing_tokenizer():
    tok = HashingTokenizer(vocab_size=1024)
    out = tok(["hello world", "hello"], max_len=16)
    assert out["input_ids"].shape == (2, 8)
    assert out["input_ids"][0, 0] == HashingTokenizer.CLS
    # deterministic
    out2 = tok(["hello world", "hello"], max_len=16)
    np.testing.assert_array_equal(out["input_ids"], out2["input_ids"])
    # same token -> same id across positions
    assert out["input_ids"][0, 1] == out["input_ids"][1, 1]


def test_bert_forward():
    cfg = bert_tiny()
    m = BertClassifier(cfg, num_classes=3)
    ids = jnp.ones((2, 16), jnp.int32)
    mask = jnp.ones((2, 16), jnp.int32)
    variables = m.init(jax.random.PRNGKey(0), ids, mask)
    logits = m.apply(variables, ids, mask)
    assert logits.shape == (2, 3)
    assert logits.dtype == jnp.float32


def test_vit_forward():
    m = ViTClassifier(vit_tiny(), num_classes=4, patch=8)
    x = jnp.zeros((2, 32, 32, 3))
    variables = m.init(jax.random.PRNGKey(0), x)
    assert m.apply(variables, x).shape == (2, 4)


def test_resnet_forward_and_features():
    m = resnet_tiny(num_classes=5)
    x = jnp.zeros((2, 32, 32, 3))
    variables = m.init(jax.random.PRNGKey(0), x)
    logits = m.apply(variables, x)
    assert logits.shape == (2, 5)
    feats = m.apply(variables, x, features_only=True)
    assert feats.shape[0] == 2 and feats.ndim == 2


def test_llama_forward_and_generate():
    cfg = llama_tiny()
    m = LlamaLM(cfg)
    ids = jnp.ones((2, 8), jnp.int32)
    variables = m.init(jax.random.PRNGKey(0), ids)
    logits = m.apply(variables, ids)
    assert logits.shape == (2, 8, cfg.vocab_size)

    dm = LlamaLM(cfg, decode=True)
    out = greedy_generate(dm, variables["params"], np.ones((1, 4), np.int32),
                          max_new_tokens=6)
    assert out.shape == (1, 10)
    np.testing.assert_array_equal(np.asarray(out)[:, :4], np.ones((1, 4)))


def test_decode_cache_matches_full_forward():
    """KV-cache decode must reproduce the dense causal forward pass."""
    cfg = llama_tiny()
    m = LlamaLM(cfg)
    rng = jax.random.PRNGKey(1)
    ids = jax.random.randint(rng, (1, 6), 0, cfg.vocab_size)
    variables = m.init(jax.random.PRNGKey(0), ids)
    full_logits = m.apply(variables, ids)

    dm = LlamaLM(cfg, decode=True)
    cache = dm.init(jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32))["cache"]
    logits_steps = []
    for t in range(6):
        pos = jnp.full((1, 1), t, jnp.int32)
        lg, st = dm.apply({"params": variables["params"], "cache": cache},
                          ids[:, t : t + 1], positions=pos, mutable=["cache"])
        cache = st["cache"]
        logits_steps.append(np.asarray(lg[:, 0]))
    step_logits = np.stack(logits_steps, axis=1)
    np.testing.assert_allclose(np.asarray(full_logits), step_logits, atol=2e-2, rtol=2e-2)


def test_deep_text_classifier_learns(tmp_path):
    df = make_text_df(n=64)
    est = DeepTextClassifier(checkpoint="bert-tiny", num_classes=2, batch_size=16,
                             max_token_len=16, learning_rate=3e-3, max_steps=30,
                             mesh_config=MeshConfig(data=-1))
    model = est.fit(df)
    out = model.transform(df)
    acc = float(np.mean(out.collect_column("prediction") ==
                        out.collect_column("label")))
    assert acc > 0.9, f"train accuracy {acc} too low"
    # save/load round trip reproduces predictions (SerializationFuzzing analog)
    path = str(tmp_path / "dtm")
    model.save(path)
    m2 = load_stage(path)
    out2 = m2.transform(df)
    np.testing.assert_array_equal(out.collect_column("prediction"),
                                  out2.collect_column("prediction"))


def test_deep_text_layer_freezing():
    df_a = make_text_df(n=32, seed=1)
    df_b = make_text_df(n=32, seed=2)

    def fit(df, unfreeze):
        return DeepTextClassifier(checkpoint="bert-tiny", num_classes=2, batch_size=16,
                                  max_token_len=16, max_steps=4, seed=0,
                                  unfreeze_layers=unfreeze).fit(df)

    def layer0(m):
        return np.asarray(m.get("model_params")["encoder"]["layer_0"]["attn"]["q"]["kernel"])

    def head(m):
        return np.asarray(m.get("model_params")["classifier"]["kernel"])

    m_f1, m_f2 = fit(df_a, 1), fit(df_b, 1)
    # frozen layer_0 stays at (seed-deterministic) init: identical across runs
    # on DIFFERENT data, while the trainable head moved differently
    np.testing.assert_array_equal(layer0(m_f1), layer0(m_f2))
    assert not np.allclose(head(m_f1), head(m_f2))
    # unfrozen run must move layer_0 away from the frozen runs' init values
    m_all = fit(df_a, -1)
    assert not np.allclose(layer0(m_all), layer0(m_f1))


def test_deep_vision_classifier_runs():
    rng = np.random.default_rng(0)
    n = 32
    labels = rng.integers(0, 2, n).astype(np.int32)
    # class-dependent mean makes the task learnable
    imgs = rng.normal(size=(n, 16, 16, 3)).astype(np.float32) + labels[:, None, None, None]
    df = DataFrame.from_dict({"image": imgs, "label": labels}, num_partitions=2)
    est = DeepVisionClassifier(backbone="resnet_tiny", num_classes=2, batch_size=16,
                               max_steps=20, learning_rate=5e-3)
    model = est.fit(df)
    out = model.transform(df)
    acc = float(np.mean(out.collect_column("prediction") == labels))
    assert acc > 0.8, f"train accuracy {acc} too low"


def test_deep_text_attn_impl_ring_on_seq_mesh():
    """attn_impl='ring' wired through DeepTextClassifier: fit + transform on a
    mesh with a seq axis (the long-context path the reference lacks)."""
    import synapseml_tpu as st
    from synapseml_tpu.models import DeepTextClassifier
    from synapseml_tpu.parallel import MeshConfig

    rows = [{"text": "good great fine", "label": 1},
            {"text": "bad awful poor", "label": 0}] * 8
    df = st.DataFrame.from_rows(rows)
    model = DeepTextClassifier(
        checkpoint="bert-tiny", num_classes=2, batch_size=8, max_token_len=16,
        max_steps=6, learning_rate=3e-3, attn_impl="ring",
        mesh_config=MeshConfig(data=-1, seq=2)).fit(df)
    assert model.get("arch_config").attn_impl == "ring"
    out = model.transform(df)
    probs = np.asarray(list(out.collect_column("scores")))
    assert probs.shape == (16, 2) and np.all(np.isfinite(probs))


def test_deep_vision_classifier_vit_backbone():
    """ViT through the ESTIMATOR surface (regression: the x-vs-images kwarg
    mismatch meant vit backbones only worked via direct module calls)."""
    import synapseml_tpu as st
    from synapseml_tpu.models import DeepVisionClassifier

    rs = np.random.default_rng(0)
    rows = []
    for i in range(16):
        label = i % 2
        img = np.full((16, 16, 3), label, np.float32) + \
            rs.normal(0, 0.1, (16, 16, 3)).astype(np.float32)
        rows.append({"image": img, "label": label})
    df = st.DataFrame.from_rows(rows)
    model = DeepVisionClassifier(backbone="vit_tiny", num_classes=2,
                                 batch_size=8, max_steps=8,
                                 learning_rate=3e-3).fit(df)
    out = model.transform(df)
    probs = np.asarray(list(out.collect_column("scores")))
    assert probs.shape == (16, 2) and np.all(np.isfinite(probs))


def test_deep_text_classifier_checkpoint_dir(tmp_path):
    """checkpoint_dir on the estimator writes async training checkpoints
    (reference ModelCheckpoint role) with the final state always saved."""
    from synapseml_tpu.core import DataFrame
    from synapseml_tpu.models import DeepTextClassifier
    from synapseml_tpu.parallel import latest_step, restore_checkpoint

    rows = [{"text": "good fine", "label": 1},
            {"text": "bad poor", "label": 0}] * 12
    df = DataFrame.from_rows(rows)
    DeepTextClassifier(checkpoint="bert-tiny", num_classes=2, batch_size=8,
                       max_token_len=8, max_steps=6, learning_rate=3e-3,
                       checkpoint_dir=str(tmp_path / "ck"),
                       checkpoint_every=2).fit(df)
    assert latest_step(str(tmp_path / "ck")) == 6
    restored = restore_checkpoint(str(tmp_path / "ck"))
    assert int(np.asarray(restored["step"])) == 6 and "opt_state" in restored
